#!/usr/bin/env python3
"""End-to-end platform gate: the whole SURVEY.md §3.1 spawn call stack, live.

Boots every platform service in one process against the in-memory API server
(controllers with real watch/queue threads, the admission webhook over real
HTTPS with strict client verification and a mid-run cert rotation — the
reference serves admission TLS-only with certwatcher reload,
admission-webhook/main.go:753-770 — and the web apps over WSGI), then
drives the user journey the reference's KinD workflows gate (reference
.github/workflows/nb_controller_intergration_test.yaml:27-58 — "pods
Ready <= 300 s"):

  1. register a workspace (dashboard -> profile controller -> namespace/RBAC)
  2. spawn a TPU notebook through the spawner API (dry-run, PVCs, create)
  3. admission-webhook merge of the TPU PodDefault on the worker pods
  4. kubelet-sim brings workers Running -> Notebook status converges
  5. stop via culling annotation -> replicas 0; start again -> Ready
  6. delete -> garbage collection

Prints the spawn-to-ready latency (the BASELINE.md platform metric) and
exits non-zero on any step failure.  Used by ci/run.sh and bench_spawn.py.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from werkzeug.test import Client


class E2E:
    def __init__(self, *, hosts_sim: bool = True, transport: str = "memory"):
        from kubeflow_tpu.platform.apis.poddefault import tpu_pod_default
        from kubeflow_tpu.platform.apps.jupyter.app import create_app as jwa
        from kubeflow_tpu.platform.controllers import culling, profile, tensorboard
        from kubeflow_tpu.platform.controllers.notebook import make_controller
        from kubeflow_tpu.platform.dashboard.app import create_app as dashboard
        from kubeflow_tpu.platform.runtime import Manager
        from kubeflow_tpu.platform.testing import FakeKube
        from kubeflow_tpu.platform.webhook.server import WebhookServer

        import logging

        logging.getLogger("werkzeug").setLevel(logging.ERROR)

        # self.kube is always the in-memory store (the kubelet-sim pokes it
        # directly, standing in for the cluster); api_client is what the
        # platform components speak.  --transport http interposes the real
        # REST client against the FakeKube served over HTTP (the envtest
        # analogue: watches, RV conflicts, patch content types, SARs all
        # cross a real wire — reference suite_test.go:52-113).
        from kubeflow_tpu.platform.testing.httpkube import make_transport

        self.kube = FakeKube()
        self.api_client, self.http_server = make_transport(
            self.kube, transport)
        self.kube.add_namespace("kubeflow")
        self.kube.add_tpu_node("tpu-node-1", topology="2x4")
        self.kube.create(tpu_pod_default("kubeflow", "v5e", "2x4"))

        from kubeflow_tpu.platform.k8s.types import NOTEBOOK as NB_GVK

        self.mgr = Manager(self.api_client)
        nb_ctrl = self.mgr.add(
            make_controller(self.api_client, use_istio=True))
        self.mgr.add(profile.make_controller(self.api_client))
        self.mgr.add(tensorboard.make_controller(self.api_client))
        self.mgr.add(culling.make_controller(
            self.api_client, prober=lambda url: None,
            notebook_informer=nb_ctrl.informers.get(NB_GVK)))
        self.mgr.start()

        import tempfile

        from kubeflow_tpu.platform.webhook.certs import (
            generate_self_signed,
            write_pair,
        )

        # Admission is served HTTPS-only, like the reference: the kubelet
        # sim verifies every handshake strictly against the current
        # serving cert (pinned as its CA), so rotate_certs() below can
        # PROVE a live reload happened.
        self._cert_dir = tempfile.mkdtemp(prefix="e2e-webhook-certs")
        self.cert_path, self.key_path = write_pair(
            self._cert_dir, *generate_self_signed()
        )
        self._tls_ctx = None
        self._webhook_conn = None
        self.webhook = WebhookServer(
            self.api_client, host="127.0.0.1", port=0,
            cert_file=self.cert_path, key_file=self.key_path,
        )
        self.webhook.start()

        self.jupyter = Client(jwa(self.api_client, secure_cookies=False))
        self.dashboard = Client(dashboard(self.api_client, secure_cookies=False))
        self.user = {"kubeflow-userid": "e2e-user@kubeflow.org"}
        self.hosts_sim = hosts_sim

    def close(self):
        import shutil

        self.mgr.stop()
        self.webhook.stop()
        if self.http_server is not None:
            self.http_server.stop()
        shutil.rmtree(self._cert_dir, ignore_errors=True)

    # -- steps ---------------------------------------------------------------

    def register(self) -> str:
        resp = self.dashboard.post("/api/workgroup/create", json={}, headers=self.user)
        assert resp.status_code == 200, resp.get_data(as_text=True)
        ns = resp.get_json()["namespace"]
        self._wait(lambda: self._ns_ready(ns), "namespace provisioning")
        return ns

    def _ns_ready(self, ns: str) -> bool:
        from kubeflow_tpu.platform.k8s import errors
        from kubeflow_tpu.platform.k8s.types import NAMESPACE, SERVICEACCOUNT

        try:
            self.kube.get(NAMESPACE, ns)
            self.kube.get(SERVICEACCOUNT, "default-editor", ns)
            return True
        except errors.ApiError:
            return False

    def spawn(self, ns: str, name: str = "e2e-nb") -> float:
        """POST the spawner form; returns spawn-to-ready seconds."""
        from kubeflow_tpu.platform.k8s.types import STATEFULSET, deep_get

        t0 = time.perf_counter()
        resp = self.jupyter.post(
            f"/api/namespaces/{ns}/notebooks",
            json={"name": name, "tpus": {"accelerator": "v5e", "topology": "2x4"}},
            headers=self.user,
        )
        assert resp.status_code == 200, resp.get_data(as_text=True)

        sts = self._wait(
            lambda: self._get(STATEFULSET, name, ns), "StatefulSet creation",
            poll=0.002,
        )
        replicas = deep_get(sts, "spec", "replicas")
        assert replicas == 1, f"2x4 is single-host (8 chips): replicas={replicas}"
        limits = deep_get(
            sts, "spec", "template", "spec", "containers", default=[{}]
        )[0].get("resources", {}).get("limits", {})
        assert limits.get("google.com/tpu") == "8", limits

        if self.hosts_sim:
            self._kubelet_sim(ns, name, replicas)
        self._wait(lambda: self._phase(ns, name) == "running",
                   "notebook Ready", poll=0.002)
        return time.perf_counter() - t0

    def _client_tls_ctx(self):
        """Strict-verification client context pinning the serving cert as
        CA — the handshake succeeds only against the exact pair the server
        currently presents (the cert carries an IP SAN for 127.0.0.1, so
        hostname checking stays on).  Cached single-slot (reset by
        rotate_certs): building a context re-reads and re-parses the CA
        store (~25 ms), which would dominate the spawn-to-ready metric if
        paid per admission; a real kubelet holds its client config for
        the webhook's lifetime too."""
        import ssl

        if self._tls_ctx is None:
            self._tls_ctx = ssl.create_default_context(cafile=self.cert_path)
        return self._tls_ctx

    def _webhook_post(self, payload: dict) -> dict:
        """POST to /apply-poddefault over a persistent verified-TLS
        connection — the real apiserver keeps webhook connections alive,
        so the ~10-20 ms per-connection handshake is paid once, not per
        admission (it would otherwise dominate the spawn metric)."""
        import http.client
        import socket

        body = json.dumps(payload)
        for attempt in (0, 1):
            if self._webhook_conn is None:
                self._webhook_conn = http.client.HTTPSConnection(
                    "127.0.0.1", self.webhook.port,
                    context=self._client_tls_ctx(), timeout=5,
                )
                self._webhook_conn.connect()
                # Headers and body leave as separate TLS records; without
                # NODELAY the second waits on the server's delayed ACK.
                self._webhook_conn.sock.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                )
            try:
                self._webhook_conn.request(
                    "POST", "/apply-poddefault", body=body,
                    headers={"Content-Type": "application/json"},
                )
                return json.load(self._webhook_conn.getresponse())
            except (ConnectionError, OSError):
                # Server closed the keep-alive (idle timeout): ONE fresh-
                # connection retry; a failure on the fresh connection is a
                # real error and propagates.
                self._webhook_conn.close()
                self._webhook_conn = None
                if attempt:
                    raise

    def rotate_certs(self):
        """Mid-run serving-cert rotation (VERDICT r3 item 5): write a new
        self-signed pair over the mounted files, reload the live listener
        (what the 60 s watch loop does, invoked directly so the gate is
        deterministic), and prove the old chain is really gone — a
        handshake still pinning the OLD cert must now fail.  Subsequent
        admissions (the stop/start leg's kubelet sim) verify against the
        new pair, proving the positive half."""
        import ssl
        import urllib.error
        import urllib.request

        from kubeflow_tpu.platform.webhook.certs import (
            generate_self_signed,
            write_pair,
        )

        old_ctx = self._client_tls_ctx()  # snapshots the pre-rotation cert
        write_pair(self._cert_dir, *generate_self_signed())
        # The kubelet sim re-pins the new cert with a fresh handshake, so
        # the stop/start leg's admissions prove the positive half.
        self._tls_ctx = None
        if self._webhook_conn is not None:
            self._webhook_conn.close()
            self._webhook_conn = None
        assert self.webhook.reload_certs(), "cert reload did not happen"
        try:
            urllib.request.urlopen(
                f"https://127.0.0.1:{self.webhook.port}/apply-poddefault",
                data=b"{}", timeout=5, context=old_ctx,
            )
        except urllib.error.URLError as e:
            assert isinstance(e.reason, ssl.SSLError), e
        else:
            raise AssertionError(
                "server still presents the pre-rotation certificate"
            )

    def _kubelet_sim(self, ns: str, name: str, replicas: int):
        """Admit each worker pod through the real webhook over verified
        HTTPS, then mark Running."""
        from kubeflow_tpu.platform.k8s.types import STATEFULSET, deep_get

        sts = self.kube.get(STATEFULSET, name, ns)
        pod_template = deep_get(sts, "spec", "template")
        for i in range(replicas):
            pod = {
                "apiVersion": "v1",
                "kind": "Pod",
                "metadata": {
                    "name": f"{name}-{i}",
                    "namespace": ns,
                    "labels": dict(deep_get(pod_template, "metadata", "labels",
                                            default={}) or {},
                                   **{"notebook-name": name}),
                },
                "spec": deep_get(pod_template, "spec"),
            }
            review = {
                "apiVersion": "admission.k8s.io/v1",
                "kind": "AdmissionReview",
                "request": {
                    "uid": f"e2e-{name}-{i}",
                    "namespace": ns,
                    "object": pod,
                },
            }
            out = self._webhook_post(review)
            assert out["response"]["allowed"], out
            self.kube.create(pod)
            self.kube.set_pod_phase(ns, f"{name}-{i}", "Running", ready=True)

    def quota_denial(self, ns: str):
        """TPU quota enforcement, end-to-end in the active transport.

        Assumes spawn(ns) already ran: caps the namespace at the 8 chips
        that notebook's worker holds,
        then proves (a) the spawner pre-flight 403s a further spawn with the
        user-facing "TPU quota exceeded" message, and (b) the apiserver's
        own pod admission 403s a direct over-quota pod create — the two
        layers the reference gets from kube-apiserver quota admission
        (reference nb_controller_intergration_test.yaml:27-58).
        """
        from kubeflow_tpu.platform.k8s import errors
        from kubeflow_tpu.platform.k8s.types import RESOURCEQUOTA

        quota = {
            "apiVersion": "v1", "kind": "ResourceQuota",
            "metadata": {"name": "e2e-tpu-quota", "namespace": ns},
            "spec": {"hard": {"google.com/tpu": "8"}},
        }
        self.api_client.create(quota)
        try:
            # The running 2x4 notebook holds all 8 chips: remaining is 0.
            resp = self.jupyter.post(
                f"/api/namespaces/{ns}/notebooks",
                json={"name": "denied-nb",
                      "tpus": {"accelerator": "v5e", "topology": "2x4"}},
                headers=self.user,
            )
            body = resp.get_data(as_text=True)
            assert resp.status_code == 403, (resp.status_code, body)
            assert "TPU quota exceeded" in body, body
            # Raw pod admission (what denies a scaling StatefulSet).
            try:
                self.api_client.create({
                    "apiVersion": "v1", "kind": "Pod",
                    "metadata": {"name": "greedy", "namespace": ns},
                    "spec": {"containers": [{"name": "c", "resources": {
                        "limits": {"google.com/tpu": "64"}}}]},
                })
            except errors.Forbidden as e:
                assert "exceeded quota" in str(e), e
            else:
                raise AssertionError("over-quota pod was admitted")
        finally:
            self.api_client.delete(RESOURCEQUOTA, "e2e-tpu-quota", ns)

    def stop_start(self, ns: str, name: str = "e2e-nb"):
        resp = self.jupyter.patch(
            f"/api/namespaces/{ns}/notebooks/{name}",
            json={"stopped": True}, headers=self.user,
        )
        assert resp.status_code == 200
        self._wait(lambda: self._replicas(ns, name) == 0, "scale to zero")
        # Stopping deletes the pods in a real cluster; mirror that.
        self._delete_pods(ns, name)
        self._wait(lambda: self._phase(ns, name) == "stopped", "stopped status")

        resp = self.jupyter.patch(
            f"/api/namespaces/{ns}/notebooks/{name}",
            json={"stopped": False}, headers=self.user,
        )
        assert resp.status_code == 200
        self._wait(lambda: (self._replicas(ns, name) or 0) >= 1, "scale back up")
        if self.hosts_sim:
            self._kubelet_sim(ns, name, self._replicas(ns, name))
        self._wait(lambda: self._phase(ns, name) == "running", "running again")

    def delete(self, ns: str, name: str = "e2e-nb"):
        resp = self.jupyter.delete(
            f"/api/namespaces/{ns}/notebooks/{name}", headers=self.user
        )
        assert resp.status_code == 200
        from kubeflow_tpu.platform.k8s.types import NOTEBOOK

        self._wait(lambda: self._get(NOTEBOOK, name, ns) is None, "deletion")

    # -- helpers -------------------------------------------------------------

    def _get(self, gvk, name, ns):
        from kubeflow_tpu.platform.k8s import errors

        try:
            return self.kube.get(gvk, name, ns)
        except errors.ApiError:
            return None

    def _replicas(self, ns, name):
        from kubeflow_tpu.platform.k8s.types import STATEFULSET, deep_get

        sts = self._get(STATEFULSET, name, ns)
        return None if sts is None else deep_get(sts, "spec", "replicas")

    def _phase(self, ns, name):
        resp = self.jupyter.get(
            f"/api/namespaces/{ns}/notebooks", headers=self.user
        )
        for row in resp.get_json().get("notebooks", []):
            if row["name"] == name:
                return row["status"]["phase"]
        return None

    def _wait(self, fn, what: str, timeout: float = 20.0,
              poll: float = 0.02):
        """``poll`` is the probe interval; bench_spawn's timed waits pass
        2 ms so the measurement isn't quantized by its own poller, while
        untimed waits keep the cheap 20 ms default."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            out = fn()
            if out:
                return out
            time.sleep(poll)
        raise TimeoutError(f"e2e: timed out waiting for {what}")

    def _delete_pods(self, ns, name):
        from kubeflow_tpu.platform.k8s.types import POD, name_of

        for pod in self.kube.list(POD, ns):
            if name_of(pod).startswith(name + "-"):
                self.kube.delete(POD, name_of(pod), ns)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", action="store_true", help="print metrics JSON only")
    ap.add_argument("--transport", choices=["memory", "http"], default="memory",
                    help="how the platform talks to the API store: in-memory "
                         "FakeKube, or RestKubeClient over an HTTP shim "
                         "serving the same store (the envtest analogue)")
    args = ap.parse_args(argv)

    e2e = E2E(transport=args.transport)
    try:
        ns = e2e.register()
        spawn_s = e2e.spawn(ns)
        e2e.quota_denial(ns)
        # Rotate the webhook serving cert mid-run: the stop/start leg's
        # re-admissions then verify against the NEW pair.
        e2e.rotate_certs()
        e2e.stop_start(ns)
        e2e.delete(ns)
    finally:
        e2e.close()

    out = {"spawn_to_ready_s": round(spawn_s, 3), "namespace": ns, "ok": True,
           "transport": args.transport, "tls_rotated": True}
    if args.json:
        print(json.dumps(out))
    else:
        print(f"E2E OK ({args.transport}): spawn-to-ready "
              f"{out['spawn_to_ready_s']}s (control plane only; image pull "
              f"excluded) in namespace {ns}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
