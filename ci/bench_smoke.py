#!/usr/bin/env python3
"""bench-smoke presubmit lane: run bench_scale.py at a tiny N and assert
the band self-report still parses — every stdout line is JSON, every line
carries a metric name, banded lines carry band/band_floor, and the
parallel-dispatch keys this lane exists to guard
(``ctrlplane_wave_converge_workers`` / ``ctrlplane_wire_converge_s``) are
present.  A refactor that renames a metric, breaks a band field, or
silently drops a phase fails CI here instead of being discovered the next
time someone reads a BENCH json.

The tiny N keeps this inside a presubmit budget; VALUES are not asserted
(a 6-notebook wave on a shared CI box says nothing about regressions —
that's what the banded full runs are for), only shape and coverage.
"""
from __future__ import annotations

import json
import subprocess
import sys

REQUIRED_METRICS = {
    "ctrlplane_fleet_converge_ms_per_notebook",
    "ctrlplane_fleet_scale_ratio",
    "ctrlplane_fleet_resync_cpu_s",
    "ctrlplane_cached_reads_per_s",
    "ctrlplane_resync_alloc_peak_kb_per_obj",
    "ctrlplane_chaos_converge_s",
    "ctrlplane_wave_converge_workers",
    "ctrlplane_wire_converge_s",
    "ctrlplane_fleet_churn",
}
# Metrics whose full-run lines are banded; at smoke N they must still
# carry the self-report fields so trending tooling never hits a gap.
BANDED_METRICS = {
    "ctrlplane_fleet_converge_ms_per_notebook",
    "ctrlplane_fleet_scale_ratio",
    "ctrlplane_wave_converge_workers",
    "ctrlplane_wire_converge_s",
    "ctrlplane_chaos_converge_s",
}


def main() -> int:
    cmd = [
        sys.executable, "bench_scale.py",
        "--small", "6", "--large", "10", "--chaos-fleet", "6",
        "--sweep-fleet", "8", "--churn-seconds", "0.5",
    ]
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=560)
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    if not lines:
        print("bench_scale produced no output", file=sys.stderr)
        print(proc.stderr[-2000:], file=sys.stderr)
        return 1
    seen = {}
    for ln in lines:
        try:
            rec = json.loads(ln)
        except ValueError:
            print(f"non-JSON bench line: {ln!r}", file=sys.stderr)
            return 1
        if "metric" not in rec:
            print(f"bench line without metric name: {ln!r}", file=sys.stderr)
            return 1
        seen[rec["metric"]] = rec
    missing = REQUIRED_METRICS - set(seen)
    if missing:
        print(f"missing bench metrics: {sorted(missing)}", file=sys.stderr)
        return 1
    for name in BANDED_METRICS:
        rec = seen[name]
        if rec.get("band") not in ("pass", "REGRESSION"):
            print(f"{name}: band field missing/invalid: {rec.get('band')!r}",
                  file=sys.stderr)
            return 1
        if not isinstance(rec.get("band_floor"), (int, float)):
            print(f"{name}: band_floor missing", file=sys.stderr)
            return 1
    sweep = seen["ctrlplane_wave_converge_workers"]
    for key in ("workers_1_converge_s", "workers_4_converge_s"):
        if not isinstance(sweep.get(key), (int, float)):
            print(f"sweep line missing {key}", file=sys.stderr)
            return 1
    print(f"bench-smoke OK: {len(seen)} metrics "
          f"({', '.join(sorted(seen))})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
