#!/usr/bin/env python3
"""bench-smoke presubmit lane: run both bench harnesses at smoke size and
assert their self-reports still parse — every stdout line is JSON, every
line carries a metric name, banded lines carry band/band_floor, and the
load-bearing keys are present:

* ``bench_scale.py`` (control plane, tiny N): the parallel-dispatch keys
  (``ctrlplane_wave_converge_workers`` / ``ctrlplane_wire_converge_s``);
* ``bench.py --sections llama8k,serve,serve_paged`` (compute plane,
  KFT_BENCH_SMOKE=1): the telemetry-derived keys (``step_p50_s``/
  ``step_p99_s`` from the shared step histogram, the ``hbm_peak_bytes``
  key — null on CPU — and the ``attention_mask_bytes_estimate`` line
  the XLA arm's pre-flight estimator publishes), plus the
  continuous-batching ``serve`` A/B line (scheduler vs lock-serialized
  tokens/s, speedup band, p99 TTFT/latency keys) and the paged-KV
  ``serve_paged`` A/B line (paged vs fixed-slot pool at equal KV
  memory, speedup band, prefix-hit ratio — which must be positive, and
  the paged arm must beat the fixed arm outright).

A refactor that renames a metric, breaks a band field, or silently
unhooks the telemetry wiring fails CI here instead of being discovered
the next time someone reads a BENCH json.

The tiny N keeps this inside a presubmit budget; VALUES are not asserted
(a 6-notebook wave on a shared CI box says nothing about regressions —
that's what the banded full runs are for), only shape and coverage.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys

# Run as `python ci/bench_smoke.py` from the repo root: put the root on
# the path so the segment-name source of truth imports.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REQUIRED_METRICS = {
    "ctrlplane_fleet_converge_ms_per_notebook",
    "ctrlplane_fleet_scale_ratio",
    "ctrlplane_fleet_resync_cpu_s",
    "ctrlplane_cached_reads_per_s",
    "ctrlplane_resync_alloc_peak_kb_per_obj",
    "ctrlplane_chaos_converge_s",
    "ctrlplane_wave_converge_workers",
    "ctrlplane_wire_converge_s",
    "ctrlplane_sharded_converge_s",
    "ctrlplane_sharded_replica_load",
    "ctrlplane_fleet_churn",
    "ctrlplane_events_decoded_per_s",
    "ctrlplane_replica_decode_fraction",
    "tpujob_queue_decisions_per_s",
    "inferenceservice_scale_converge_s",
    "fleetscrape_samples_per_s",
    "ctrlplane_profile_overhead_pct",
}
# Metrics whose full-run lines are banded; at smoke N they must still
# carry the self-report fields so trending tooling never hits a gap.
BANDED_METRICS = {
    "ctrlplane_fleet_converge_ms_per_notebook",
    "ctrlplane_fleet_scale_ratio",
    "ctrlplane_wave_converge_workers",
    "ctrlplane_wire_converge_s",
    "ctrlplane_chaos_converge_s",
    "ctrlplane_sharded_converge_s",
    "ctrlplane_sharded_replica_load",
    "ctrlplane_events_decoded_per_s",
    "ctrlplane_replica_decode_fraction",
    "tpujob_queue_decisions_per_s",
    "inferenceservice_scale_converge_s",
    "fleetscrape_samples_per_s",
    "ctrlplane_profile_overhead_pct",
}


def _parse_json_lines(stdout: str, what: str):
    lines = [ln for ln in stdout.splitlines() if ln.strip()]
    seen = {}
    for ln in lines:
        try:
            rec = json.loads(ln)
        except ValueError:
            print(f"non-JSON {what} line: {ln!r}", file=sys.stderr)
            return None
        if "metric" not in rec:
            print(f"{what} line without metric name: {ln!r}",
                  file=sys.stderr)
            return None
        seen[rec["metric"]] = rec
    return seen


def check_compute_bench() -> int:
    """bench.py smoke (CPU, llama8k + serve): the telemetry wiring keys
    and the continuous-batching A/B line."""
    # 8 forced host devices so the serve_paged sharded arm (tp=2,fsdp=4
    # page-pool split) actually runs instead of reporting mesh_skipped.
    env = dict(os.environ, KFT_BENCH_SMOKE="1", JAX_PLATFORMS="cpu",
               XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                          + " --xla_force_host_platform_device_count=8"
                          ).strip())
    proc = subprocess.run(
        [sys.executable, "bench.py", "--sections",
         "llama8k,serve,serve_paged"],
        capture_output=True, text=True, timeout=560, env=env,
    )
    seen = _parse_json_lines(proc.stdout, "bench")
    if seen is None:
        print(proc.stderr[-2000:], file=sys.stderr)
        return 1
    line = seen.get("llama8k_train_tokens_per_sec")
    if line is None:
        print(f"bench smoke missing the llama8k line: {sorted(seen)}",
              file=sys.stderr)
        print(proc.stderr[-2000:], file=sys.stderr)
        return 1
    for key in ("step_p50_s", "step_p99_s"):
        if not isinstance(line.get(key), (int, float)):
            print(f"llama8k line missing telemetry key {key}: {line}",
                  file=sys.stderr)
            return 1
    if "hbm_peak_bytes" not in line:  # null on CPU, but the KEY must ride
        print(f"llama8k line missing hbm_peak_bytes: {line}",
              file=sys.stderr)
        return 1
    # Kernel-selection proof (ISSUE 7): the flash arm must have traced
    # the Pallas kernel at least once, and the XLA arm never — a routing
    # regression that silently falls back to XLA makes the A/B ratio
    # meaningless long before anyone reads a BENCH json.
    if not line.get("flash_arm_pallas_calls", 0) > 0:
        print("flash arm never selected the Pallas kernel "
              f"(silent XLA fallback?): {line}", file=sys.stderr)
        return 1
    if line.get("xla_arm_pallas_calls") != 0:
        print(f"XLA arm unexpectedly traced the Pallas kernel: {line}",
              file=sys.stderr)
        return 1
    est = seen.get("attention_mask_bytes_estimate")
    if est is None or not est.get("value", 0) > 0:
        # The XLA arm ran a masked causal attention, so the pre-flight
        # estimator MUST have published a positive footprint.
        print(f"mask-estimate line missing/zero after the XLA arm: {est}",
              file=sys.stderr)
        return 1
    # The XLA arm is mask-free (ISSUE 7): the footprint is the f32
    # logits+probs pair ONLY — 2 * 4 * b * h * sq * sk with the smoke
    # config's h=2 (bench._smoke_cfg).  Exact equality: any extra term
    # means a materialized mask buffer crept back into the estimator (or
    # the path it describes).
    want = 2 * 4 * est["batch"] * 2 * est["seq_len"] * est["seq_len"]
    if est["value"] != want:
        print(f"mask estimate {est['value']} != logits+probs-only {want}: "
              f"a mask buffer term is back in the footprint: {est}",
              file=sys.stderr)
        return 1
    # Continuous-batching serve section (ISSUE 8): the A/B line must
    # parse with both arms' throughput, the speedup band self-report,
    # and the p99 keys — shape and coverage, not values (the 2x floor
    # is asserted by the banded full run, not a shared CI box).
    serve = seen.get("serve_continuous_batching_tokens_per_sec")
    if serve is None:
        print(f"bench smoke missing the serve line: {sorted(seen)}",
              file=sys.stderr)
        return 1
    for key in ("value", "locked_tokens_per_sec", "speedup_vs_locked",
                "band_floor", "latency_p99_s", "locked_latency_p99_s"):
        if not isinstance(serve.get(key), (int, float)):
            print(f"serve line missing key {key}: {serve}",
                  file=sys.stderr)
            return 1
    if serve.get("band") not in ("pass", "REGRESSION"):
        print(f"serve line band invalid: {serve.get('band')!r}",
              file=sys.stderr)
        return 1
    for key in ("ttft_p99_s", "locked_ttft_p99_s"):
        if key not in serve:  # null only on an empty histogram
            print(f"serve line missing key {key}: {serve}",
                  file=sys.stderr)
            return 1
    # Paged-KV serve section (ISSUE 17): the A/B line must parse with
    # both arms' throughput, the speedup band self-report (floor 1.5,
    # asserted by the banded full run), and the prefix-cache proof.
    # Two VALUE assertions ride even at smoke size because they test
    # mechanism, not hardware: the prefix cache must actually hit on a
    # shared-system-prompt workload, and the paged arm must BEAT the
    # fixed-slot arm outright — a paged pool that loses to the pool it
    # replaced is a routing/implementation regression at any N.
    paged = seen.get("serve_paged_tokens_per_sec")
    if paged is None:
        print(f"bench smoke missing the serve_paged line: {sorted(seen)}",
              file=sys.stderr)
        return 1
    for key in ("value", "fixed_tokens_per_sec", "speedup_vs_fixed",
                "band_floor", "prefix_hit_ratio", "latency_p99_s",
                "fixed_latency_p99_s"):
        if not isinstance(paged.get(key), (int, float)):
            print(f"serve_paged line missing key {key}: {paged}",
                  file=sys.stderr)
            return 1
    if paged.get("band") not in ("pass", "REGRESSION"):
        print(f"serve_paged line band invalid: {paged.get('band')!r}",
              file=sys.stderr)
        return 1
    for key in ("ttft_p99_s", "fixed_ttft_p99_s"):
        if key not in paged:  # null only on an empty histogram
            print(f"serve_paged line missing key {key}: {paged}",
                  file=sys.stderr)
            return 1
    if not paged["prefix_hit_ratio"] > 0:
        print(f"prefix cache never hit on a shared-prefix workload: "
              f"{paged}", file=sys.stderr)
        return 1
    if not paged["speedup_vs_fixed"] > 1.0:
        print(f"paged arm lost to the fixed-slot arm: {paged}",
              file=sys.stderr)
        return 1
    # The ISSUE 20 line: the GSPMD-sharded arm must have actually run
    # (nonzero throughput, pool split >1 way — the 8-device forcing
    # above makes that unconditional), and the pipelined-vs-synchronous
    # dispatch A/B must parse and sit inside its band (1.15x overlap
    # floor with >=2 host cores, 0.85x no-regression tripwire on a
    # single-core box — bench.py picks and reports the floor).
    sharded = seen.get("serve_paged_sharded")
    if sharded is None:
        print(f"bench smoke missing the serve_paged_sharded line: "
              f"{sorted(seen)}", file=sys.stderr)
        return 1
    for key in ("value", "mesh_pool_shards",
                "dispatch_pipelined_tokens_per_sec",
                "dispatch_sync_tokens_per_sec", "dispatch_speedup",
                "dispatch_overlap_ratio", "band_floor", "host_cores"):
        if not isinstance(sharded.get(key), (int, float)):
            print(f"serve_paged_sharded line missing key {key}: "
                  f"{sharded}", file=sys.stderr)
            return 1
    if not (sharded["value"] > 0 and sharded["mesh_pool_shards"] > 1):
        print(f"sharded serve arm did not run sharded: {sharded}",
              file=sys.stderr)
        return 1
    if sharded.get("band") != "pass":
        print(f"dispatch pipelining outside its band: {sharded}",
              file=sys.stderr)
        return 1
    print(f"bench-smoke compute OK: {len(seen)} metrics "
          f"({', '.join(sorted(seen))})")
    return 0


def main() -> int:
    cmd = [
        sys.executable, "bench_scale.py",
        "--small", "6", "--large", "10", "--chaos-fleet", "6",
        "--sweep-fleet", "8", "--churn-seconds", "0.5",
        "--sharded-fleet", "24", "--inference-services", "6",
        "--fleetscrape-targets", "24", "--profile-fleet", "6",
    ]
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=560)
    seen = _parse_json_lines(proc.stdout, "bench_scale")
    if not seen:
        if seen is not None:
            print("bench_scale produced no output", file=sys.stderr)
        print(proc.stderr[-2000:], file=sys.stderr)
        return 1
    missing = REQUIRED_METRICS - set(seen)
    if missing:
        print(f"missing bench metrics: {sorted(missing)}", file=sys.stderr)
        return 1
    for name in BANDED_METRICS:
        rec = seen[name]
        if rec.get("band") not in ("pass", "REGRESSION"):
            print(f"{name}: band field missing/invalid: {rec.get('band')!r}",
                  file=sys.stderr)
            return 1
        if not isinstance(rec.get("band_floor"), (int, float)):
            print(f"{name}: band_floor missing", file=sys.stderr)
            return 1
    sweep = seen["ctrlplane_wave_converge_workers"]
    for key in ("workers_1_converge_s", "workers_4_converge_s"):
        if not isinstance(sweep.get(key), (int, float)):
            print(f"sweep line missing {key}", file=sys.stderr)
            return 1
    # Causal-tracing segment breakdowns (ISSUE 14): the wave-converge and
    # inferenceservice lines must carry a non-empty *_segments dict of
    # named-segment seconds — an empty dict means the journey wiring
    # silently unhooked (a raw create severed the trace, the store was
    # disabled, the analyzer broke) long before anyone reads a journey.
    from kubeflow_tpu.telemetry.critical_path import SEGMENTS

    known = set(SEGMENTS) | {"unattributed"}
    for line_name in ("ctrlplane_fleet_converge_ms_per_notebook",
                      "inferenceservice_scale_converge_s"):
        segs = seen[line_name].get("converge_segments")
        if not isinstance(segs, dict) or not segs:
            print(f"{line_name}: converge_segments missing/empty: {segs}",
                  file=sys.stderr)
            return 1
        bad = {k: v for k, v in segs.items()
               if k not in known or not isinstance(v, (int, float))
               or v < 0}
        if bad:
            print(f"{line_name}: malformed segment entries: {bad}",
                  file=sys.stderr)
            return 1
        if sum(segs.values()) <= 0:
            print(f"{line_name}: zero-length segment breakdown: {segs}",
                  file=sys.stderr)
            return 1
    # Sharded-HA lines (ISSUE 9): the per-replica load vectors and the
    # fencing-invariant write count must keep riding — a zero count means
    # the bench silently stopped exercising the fence.
    sharded = seen["ctrlplane_sharded_converge_s"]
    if not (isinstance(sharded.get("fenced_writes_checked"), int)
            and sharded["fenced_writes_checked"] > 0):
        print("sharded line: fenced_writes_checked missing/zero",
              file=sys.stderr)
        return 1
    load = seen["ctrlplane_sharded_replica_load"]
    for key in ("replica_cache_objs", "replica_events_admitted"):
        if not isinstance(load.get(key), list) or not load[key]:
            print(f"sharded load line missing {key}", file=sys.stderr)
            return 1
    # Wire fast path (ISSUE 18): the decode A/B line must carry both
    # legs, and when the native library built, the native leg must beat
    # the python leg OUTRIGHT — a scanner that loses to json.loads is a
    # routing/implementation regression at any N (the 3x floor itself is
    # left to the banded full run).  The decode-fraction line proves
    # server-side shard filtering actually thinned the replica streams:
    # a mean of 1.0 means every replica still decodes the full firehose.
    decode = seen["ctrlplane_events_decoded_per_s"]
    for key in ("value", "python_eps", "speedup_x", "avg_line_bytes"):
        if not isinstance(decode.get(key), (int, float)):
            print(f"decode A/B line missing key {key}: {decode}",
                  file=sys.stderr)
            return 1
    if decode.get("native_available") and not decode["speedup_x"] > 1.0:
        print(f"native decode lost to python json.loads: {decode}",
              file=sys.stderr)
        return 1
    frac = seen["ctrlplane_replica_decode_fraction"]
    if not (isinstance(frac.get("replica_decode_fraction"), list)
            and frac["replica_decode_fraction"]):
        print(f"decode-fraction line missing per-replica vector: {frac}",
              file=sys.stderr)
        return 1
    if not (isinstance(frac.get("events_emitted_delta"), int)
            and frac["events_emitted_delta"] > 0):
        print(f"decode-fraction line: no events emitted: {frac}",
              file=sys.stderr)
        return 1
    if not frac.get("value", 1.0) < 1.0:
        print(f"replicas still decode the full stream (filtering "
              f"unhooked?): {frac}", file=sys.stderr)
        return 1
    # TPUJob queue band (ISSUE 11): the decision loop must actually have
    # decided — a zero count means the drain silently stopped exercising
    # the ledger.
    jobq = seen["tpujob_queue_decisions_per_s"]
    if not (isinstance(jobq.get("decisions"), int)
            and jobq["decisions"] > 0 and jobq.get("value", 0) > 0):
        print(f"jobqueue line missing/zero decisions: {jobq}",
              file=sys.stderr)
        return 1
    # Fleet metrics pipeline band (ISSUE 15): the scrape->store->rule
    # loop must really have stored samples and evaluated rules — zeros
    # mean the pipeline silently unhooked.
    scrape = seen["fleetscrape_samples_per_s"]
    if not (isinstance(scrape.get("samples"), int) and scrape["samples"] > 0
            and scrape.get("value", 0) > 0
            and isinstance(scrape.get("rule_evals"), int)
            and scrape["rule_evals"] > 0):
        print(f"fleetscrape line missing/zero samples: {scrape}",
              file=sys.stderr)
        return 1
    # Profiler overhead band (ISSUE 16): the line must carry both wall
    # A/B legs and the CPU denominator, and the sampler must really have
    # sampled — zero profile_samples means the sampler thread never ran
    # (or attribution broke), which would make the overhead claim
    # vacuous.  sampler_cpu_s must be present (it IS the band's
    # numerator) but may legitimately round toward zero on a fast box.
    prof = seen["ctrlplane_profile_overhead_pct"]
    for key in ("converge_off_s", "converge_on_s", "converge_cpu_s"):
        if not (isinstance(prof.get(key), (int, float))
                and prof[key] > 0):
            print(f"profile overhead line missing/zero {key}: {prof}",
                  file=sys.stderr)
            return 1
    if not (isinstance(prof.get("sampler_cpu_s"), (int, float))
            and prof["sampler_cpu_s"] >= 0):
        print(f"profile overhead line missing sampler_cpu_s: {prof}",
              file=sys.stderr)
        return 1
    if not (isinstance(prof.get("profile_samples"), int)
            and prof["profile_samples"] > 0):
        print(f"profile overhead line missing/zero profile_samples: {prof}",
              file=sys.stderr)
        return 1
    # InferenceService autoscale band (ISSUE 12): both wave legs must
    # have run (a zero leg means the fleet never actually scaled) and the
    # zero-dead-letter invariant must ride the line.
    inference = seen["inferenceservice_scale_converge_s"]
    for key in ("wave_converge_s", "drain_converge_s"):
        if not (isinstance(inference.get(key), (int, float))
                and inference[key] > 0):
            print(f"inference scale line missing/zero {key}: {inference}",
                  file=sys.stderr)
            return 1
    if inference.get("dead_letters") != 0:
        print(f"inference scale line dead_letters != 0: {inference}",
              file=sys.stderr)
        return 1
    print(f"bench-smoke ctrlplane OK: {len(seen)} metrics "
          f"({', '.join(sorted(seen))})")
    return check_compute_bench()


if __name__ == "__main__":
    sys.exit(main())
