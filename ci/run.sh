#!/usr/bin/env bash
# The CI gate, runnable anywhere (no cluster, no TPU): unit + integration
# tests on the virtual 8-device CPU mesh, native-library build + parity,
# the end-to-end platform flow, and the driver contract dry-runs.
# Mirrors the reference's per-component GitHub workflows
# (reference .github/workflows/*_intergration_test.yaml) collapsed into one
# hermetic script.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "=== native build ==="
make -C native

echo "=== unit + integration tests ==="
# QUICK=1 skips the @pytest.mark.slow tier (the ~15 tests over 20s each);
# every test runs under the conftest watchdog (KFT_TEST_TIMEOUT_S, default
# 600 s/test) so a hung mesh test fails CI in bounded time instead of
# wedging it.
if [ -n "${QUICK:-}" ]; then
  python -m pytest tests/ -q -m "not slow"
else
  python -m pytest tests/ -q
fi

echo "=== end-to-end platform gate ==="
python ci/e2e.py

echo "=== end-to-end platform gate (HTTP transport / envtest analogue) ==="
python ci/e2e.py --transport http

echo "=== driver contract: single-chip compile ==="
JAX_PLATFORMS=cpu python -c "
import __graft_entry__ as g, jax
fn, a = g.entry()
jax.jit(fn).lower(*a).compile()
print('entry() compiles')"

echo "=== driver contract: multi-chip dryrun ==="
XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

echo "=== conformance suite ==="
python conformance/run.py

echo "=== spawn benchmark ==="
python bench_spawn.py

echo "CI PASS"
