#!/usr/bin/env bash
# The CI gate, runnable anywhere (no cluster, no TPU): unit + integration
# tests on the virtual 8-device CPU mesh, native-library build + parity,
# the end-to-end platform flow, and the driver contract dry-runs.
# Mirrors the reference's per-component GitHub workflows
# (reference .github/workflows/*_intergration_test.yaml) collapsed into one
# hermetic script.
#
# Every tier runs under a WALL-TIME BUDGET (VERDICT r2 weak item 7): the
# per-test watchdog (conftest KFT_TEST_TIMEOUT_S) bounds a single hang,
# these bound aggregate drift — a tier that slowly accretes runtime fails
# loudly here instead of quietly pushing the gate past its budget.
set -euo pipefail
cd "$(dirname "$0")/.."

budget() { # budget <seconds> <label> <cmd...>
  local limit=$1 label=$2
  shift 2
  echo "=== ${label} (budget ${limit}s) ==="
  local t0=$SECONDS
  "$@"
  local dt=$((SECONDS - t0))
  echo "--- ${label}: ${dt}s of ${limit}s"
  if [ "$dt" -gt "$limit" ]; then
    echo "TIER BUDGET EXCEEDED: ${label} took ${dt}s > ${limit}s" >&2
    exit 1
  fi
}

budget 180 "native build" make -C native

# kftlint (ISSUE 13, docs/analysis.md): zero unsuppressed, un-baselined
# findings over the tree — the same gate the `lint` workflow lane runs.
budget 60 "kftlint invariants" \
  python -m kubeflow_tpu.analysis --baseline ci/kftlint_baseline.json

# QUICK=1 skips the @pytest.mark.slow tier (the ~15 tests over 20s each);
# every test runs under the conftest watchdog (KFT_TEST_TIMEOUT_S, default
# 600 s/test) so a hung mesh test fails CI in bounded time instead of
# wedging it.
if [ -n "${QUICK:-}" ]; then
  budget 900 "unit + integration tests (quick tier)" \
    python -m pytest tests/ -q -m "not slow"
else
  budget 2400 "unit + integration tests (full)" \
    python -m pytest tests/ -q
fi

budget 120 "end-to-end platform gate" python ci/e2e.py

budget 180 "end-to-end platform gate (HTTP transport / envtest analogue)" \
  python ci/e2e.py --transport http

budget 300 "driver contract: single-chip compile" \
  env JAX_PLATFORMS=cpu python -c "
import __graft_entry__ as g, jax
fn, a = g.entry()
jax.jit(fn).lower(*a).compile()
print('entry() compiles')"

budget 600 "driver contract: multi-chip dryrun" \
  env XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

budget 300 "conformance suite" python conformance/run.py

budget 120 "spawn benchmark" python bench_spawn.py

echo "CI PASS"
