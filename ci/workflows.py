#!/usr/bin/env python3
"""Per-component CI workflow builder + dispatch table.

The reference drives CI from a dispatch table mapping changed paths to
Argo-workflow builder functions (reference prow_config.yaml:8-40 →
py/kubeflow/kubeflow/ci/workflow_utils.py:30-70 ArgoTestBuilder, with
Kaniko image build-test tasks in ci/notebook_servers/*).  This module is
the same idea for this repo: a table of component workflows selected by
changed-path globs, each emitting an Argo-shaped Workflow manifest for
in-cluster execution — and, because this repo's tests are hermetic, each
step also carries the equivalent local command so the whole pipeline can
run anywhere via ``run --local`` (that is what ci/run.sh collapses into
one script).

CLI:
  python ci/workflows.py list
  python ci/workflows.py select <changed-path>...   # names to trigger
  python ci/workflows.py emit <name>                # Argo Workflow YAML
  python ci/workflows.py run <name>                 # execute locally
"""
from __future__ import annotations

import dataclasses
import fnmatch
import json
import subprocess
import sys
from typing import Dict, List, Optional

# Runner image for in-cluster steps: images/ci/Dockerfile — the full repo
# at /src with the native library pre-built and test deps installed, so the
# emitted manifests are directly runnable (build it with the kaniko step
# the notebook-images workflow emits, or docker build -f images/ci/Dockerfile .).
REPO_IMAGE = "ghcr.io/kubeflow-tpu/ci-runner:latest"


@dataclasses.dataclass
class Step:
    name: str
    command: List[str]           # local + in-cluster command (repo root cwd)
    depends: Optional[str] = None


@dataclasses.dataclass
class ComponentWorkflow:
    name: str
    include_dirs: List[str]      # changed-path globs that trigger it
    steps: List[Step]
    job_types: List[str] = dataclasses.field(
        default_factory=lambda: ["presubmit"]
    )

    def matches(self, path: str) -> bool:
        return any(fnmatch.fnmatch(path, glob) for glob in self.include_dirs)

    def to_argo(self) -> dict:
        """Argo-Workflow-shaped manifest (DAG over the steps)."""
        tasks = []
        for s in self.steps:
            # Local steps embed this interpreter's path; the runner image
            # provides plain `python` on PATH.
            cmd = ["python" if c == sys.executable else c for c in s.command]
            task = {
                "name": s.name,
                "template": "run",
                "arguments": {"parameters": [
                    {"name": "cmd", "value": json.dumps(cmd)},
                ]},
            }
            if s.depends:
                task["depends"] = s.depends
            tasks.append(task)
        return {
            "apiVersion": "argoproj.io/v1alpha1",
            "kind": "Workflow",
            "metadata": {"generateName": f"{self.name}-"},
            "spec": {
                "entrypoint": "dag",
                "templates": [
                    {"name": "dag", "dag": {"tasks": tasks}},
                    {
                        "name": "run",
                        "inputs": {"parameters": [{"name": "cmd"}]},
                        "container": {
                            "image": REPO_IMAGE,
                            "workingDir": "/src",
                            "command": ["python", "-c"],
                            "args": [
                                "import json,subprocess,sys;"
                                "sys.exit(subprocess.call("
                                "json.loads(sys.argv[1])))",
                                "{{inputs.parameters.cmd}}",
                            ],
                        },
                    },
                ],
            },
        }

    def run_local(self, *, cwd: str = ".", echo=print) -> bool:
        for s in self.steps:
            echo(f"--- [{self.name}] {s.name}: {' '.join(s.command)}")
            if subprocess.call(s.command, cwd=cwd) != 0:
                echo(f"FAILED: {self.name}/{s.name}")
                return False
        return True


def _pytest(*paths: str) -> List[str]:
    return [sys.executable, "-m", "pytest", "-q", *paths]


# The dispatch table (reference prow_config.yaml:8-40).  include_dirs use
# repo-relative globs; "releasing/*" triggers everything, like the
# reference's releasing/version/* entries.
WORKFLOWS: Dict[str, ComponentWorkflow] = {}


def _register(wf: ComponentWorkflow) -> ComponentWorkflow:
    WORKFLOWS[wf.name] = wf
    return wf


_register(ComponentWorkflow(
    name="notebook-controller",
    include_dirs=[
        "kubeflow_tpu/platform/controllers/*", "kubeflow_tpu/platform/apis/*",
        "kubeflow_tpu/platform/runtime/*", "kubeflow_tpu/platform/k8s/*",
        "releasing/*",
    ],
    steps=[
        Step("unit", _pytest(
            "tests/ctrlplane/test_notebook_controller.py",
            "tests/ctrlplane/test_culling.py",
            "tests/ctrlplane/test_notebook_conversion.py",
            "tests/ctrlplane/test_tensorboard_controller.py",
            "tests/ctrlplane/test_profile_controller.py",
        )),
        Step("e2e", [sys.executable, "ci/e2e.py"], depends="unit"),
    ],
))

_register(ComponentWorkflow(
    # Chaos/resilience lane (ISSUE 4): the retry/circuit/dead-letter unit
    # matrix plus the tier-1 seeded smoke storm on every control-plane
    # change.  The 60 s http-transport soak is slow-marked and runs in the
    # postsubmit lane below.
    name="resilience",
    include_dirs=[
        "kubeflow_tpu/platform/k8s/*", "kubeflow_tpu/platform/runtime/*",
        "kubeflow_tpu/platform/testing/*",
        "kubeflow_tpu/platform/controllers/*", "releasing/*",
    ],
    steps=[
        Step("unit", _pytest("tests/ctrlplane/test_resilience.py")),
        Step("chaos-smoke", _pytest("tests/ctrlplane/test_chaos.py")
             + ["-m", "not slow"], depends="unit"),
    ],
))

_register(ComponentWorkflow(
    # Sharded-HA presubmit slice (ISSUE 9): hash-assignment properties,
    # coordinator/fencing units, and the FAST single-kill chaos variant
    # on every control-plane change.  The full matrix (1k-object
    # 4-replica kill, split-brain lease expiry, membership churn, the
    # storm soak) runs in the ha-chaos postsubmit lane below.
    name="ha-shard",
    include_dirs=[
        "kubeflow_tpu/platform/runtime/*", "kubeflow_tpu/platform/k8s/*",
        "kubeflow_tpu/platform/testing/*",
        "kubeflow_tpu/platform/controllers/*", "releasing/*",
    ],
    steps=[
        Step("fast", _pytest("tests/ctrlplane/test_sharding.py")
             + ["-m", "not slow", "-k", "not 1k_wave"]),
    ],
))

_register(ComponentWorkflow(
    # ha-chaos postsubmit lane (ISSUE 9): the replica-kill and
    # lease-expiry chaos matrix in full — the 1k-object 4-replica kill
    # (the acceptance-criteria test), split brain under a paused
    # replica, membership churn mid-wave, and the storm soak that mixes
    # all of it with seeded fault injection — plus the 4-replica
    # bench_scale smoke asserting the sharded band lines still parse
    # and the per-replica load band holds at smoke size.
    name="ha-chaos",
    include_dirs=[
        "kubeflow_tpu/platform/runtime/*", "kubeflow_tpu/platform/k8s/*",
        "kubeflow_tpu/platform/testing/*",
        "kubeflow_tpu/platform/controllers/*", "bench_scale.py",
        "releasing/*",
    ],
    job_types=["postsubmit"],
    steps=[
        Step("chaos-matrix", _pytest("tests/ctrlplane/test_sharding.py")),
        Step("bench-4replica", [
            sys.executable, "bench_scale.py", "--sharded-only",
            "--sharded-fleet", "200",
        ], depends="chaos-matrix"),
    ],
))

_register(ComponentWorkflow(
    name="resilience-soak",
    include_dirs=[
        "kubeflow_tpu/platform/k8s/*", "kubeflow_tpu/platform/runtime/*",
        "kubeflow_tpu/platform/testing/*", "releasing/*",
    ],
    job_types=["postsubmit"],
    steps=[Step("soak", _pytest("tests/ctrlplane/test_chaos.py")
                + ["-m", "slow"])],
))

_register(ComponentWorkflow(
    # bench-smoke presubmit lane (ISSUE 5 satellite, extended by ISSUE 6):
    # bench_scale.py at a tiny N PLUS bench.py's llama8k section under
    # KFT_BENCH_SMOKE, asserting the band self-reports parse, the
    # parallel-dispatch keys (ctrlplane_wave_converge_workers /
    # wire-converge) are present, and the compute lines carry the
    # telemetry-derived keys (step p50/p99, hbm_peak_bytes, the
    # attention mask-estimate line) — shape and coverage, not values
    # (ci/bench_smoke.py).
    name="bench-smoke",
    include_dirs=[
        "bench.py", "bench_scale.py", "ci/bench_smoke.py",
        "kubeflow_tpu/telemetry/*",
        "kubeflow_tpu/platform/runtime/*", "kubeflow_tpu/platform/k8s/*",
        "kubeflow_tpu/platform/testing/*",
        "kubeflow_tpu/platform/controllers/*",
        "kubeflow_tpu/ops/*", "kubeflow_tpu/train/*",
        "kubeflow_tpu/models/*", "releasing/*",
    ],
    steps=[Step("smoke", [sys.executable, "ci/bench_smoke.py"])],
))

_register(ComponentWorkflow(
    # Serve presubmit lane (ISSUE 17, extended by ISSUE 20): the
    # paged-KV/spec-decode fast matrix on every serve-path change —
    # paged == contiguous == sequential token equality (greedy + seeded
    # sampling), shared-prefix copy-on-write divergence, chunked-prefill
    # interleave with mid-flight eviction, the speculative accept/reject
    # boundaries, the KFT_SERVE_PAGED=0 fallback pin, and the strict
    # knob validation — plus the serve-registry page/prefix/spec counter
    # balance pins.  The paged-sharded step runs the ISSUE 20 matrix on
    # 8 forced host devices: GSPMD-sharded pool == unsharded ==
    # fixed-slot token streams, pipelined == synchronous dispatch, and
    # the structured-fallback surfaces (the XLA_FLAGS prefix is explicit
    # so the manifest is runnable outside tests/conftest.py's forcing).
    name="serve",
    include_dirs=[
        "kubeflow_tpu/models/*", "kubeflow_tpu/telemetry/*",
        "kubeflow_tpu/ops/*", "kubeflow_tpu/platform/config.py",
        "kubeflow_tpu/parallel/*", "releasing/*",
    ],
    steps=[
        Step("engine-matrix", _pytest("tests/test_scheduler.py")
             + ["-m", "not slow"]),
        Step("paged-sharded",
             ["env", "XLA_FLAGS=--xla_force_host_platform_device_count=8"]
             + _pytest("tests/test_paged.py")),
        Step("serve-metrics", _pytest("tests/test_telemetry.py")
             + ["-k", "serve_kv or serve_spec"], depends="engine-matrix"),
    ],
))

_register(ComponentWorkflow(
    # Serve-soak lane (ISSUE 8, postsubmit; extended by ISSUE 17):
    # concurrent clients hammer the werkzeug generation app over a real
    # socket for a bounded wall-clock, asserting the continuous-batching
    # invariants — no dropped requests, no cross-request row mixing
    # (greedy determinism), telemetry counters balance (admitted ==
    # evicted + in-flight) — plus the paged-pool soak: a shared-prefix
    # hammer against the paged engine pinning zero cross-request page
    # aliasing outside the declared shared prefix, prefix hits accruing,
    # and the page ledger draining balanced (free + shared ==
    # pages_total - 1, active == 0).  The fast token-equality matrix
    # gates both soaks.
    name="serve-soak",
    include_dirs=[
        "kubeflow_tpu/models/*", "kubeflow_tpu/telemetry/*",
        "kubeflow_tpu/ops/*", "releasing/*",
    ],
    job_types=["postsubmit"],
    steps=[
        Step("equality", _pytest("tests/test_scheduler.py")
             + ["-m", "not slow"]),
        Step("soak", _pytest("tests/test_scheduler.py")
             + ["-m", "slow"], depends="equality"),
    ],
))

_register(ComponentWorkflow(
    # TPUJob presubmit lane (ISSUE 10, extended by ISSUE 11): the gang
    # reconciler + API matrix (gang creation, MEGASCALE round-trip vs
    # parallel/dist.py, restart/backoff semantics, CRD yaml-vs-api pin,
    # the queue validation matrix) PLUS the fast queue/preemption matrix
    # (ledger units, park-with-reason, priority-then-FIFO drain, the
    # two-phase checkpoint-then-evict, elastic admit + grow-back,
    # crashloop-cannot-starve) on every change to the controller, the
    # queue, the env contract, or the trainer pieces the gang resumes
    # through.  The tpujob-train-converge and queue-preempt-elastic
    # conformance checks ride the existing `conformance` postsubmit
    # lane, whose kubeflow_tpu/* + conformance/* globs already cover
    # this subsystem.
    name="tpujob",
    include_dirs=[
        "kubeflow_tpu/platform/controllers/*", "kubeflow_tpu/platform/apis/*",
        "kubeflow_tpu/platform/runtime/*",
        "kubeflow_tpu/parallel/envspec.py", "kubeflow_tpu/parallel/dist.py",
        "kubeflow_tpu/train/*", "kubeflow_tpu/platform/testing/*",
        "manifests/*", "releasing/*",
    ],
    steps=[
        Step("unit", _pytest(
            "tests/ctrlplane/test_tpujob_controller.py",
            "tests/ctrlplane/test_manifests.py",
        )),
        Step("queue", _pytest("tests/ctrlplane/test_jobqueue.py")
             + ["-m", "not slow"], depends="unit"),
        Step("storm", _pytest("tests/ctrlplane/test_chaos.py")
             + ["-m", "not slow", "-k", "tpujob"], depends="queue"),
    ],
))

_register(ComponentWorkflow(
    # queue-chaos postsubmit lane (ISSUE 11): the queue's heavy invariant
    # pins — a 9-job priority storm under seeded faults into a 2-slot
    # budget (drain order, zero dead-letters, zero half-gangs) and the
    # ShardedFleet replica kill mid-drain (survivor preserves
    # priority-then-FIFO order with every write fenced).
    name="queue-chaos",
    include_dirs=[
        "kubeflow_tpu/platform/controllers/*", "kubeflow_tpu/platform/apis/*",
        "kubeflow_tpu/platform/runtime/*",
        "kubeflow_tpu/platform/testing/*", "releasing/*",
    ],
    job_types=["postsubmit"],
    steps=[
        Step("fast-matrix", _pytest("tests/ctrlplane/test_jobqueue.py")
             + ["-m", "not slow"]),
        Step("storm-and-kill", _pytest("tests/ctrlplane/test_jobqueue.py")
             + ["-m", "slow"], depends="fast-matrix"),
    ],
))

_register(ComponentWorkflow(
    # InferenceService presubmit lane (ISSUE 12): the serving controller
    # + API unit matrix (revisioned Deployments, rolling readiness-gated
    # flips, scale-to-zero/wake, quota clamp, CRD drift pins) with the
    # pure-unit autoscaler decision matrix and the shared-ledger pin,
    # then the seeded inferenceservice storm.  The
    # inferenceservice-autoscale-rollout conformance scenario rides the
    # existing `conformance` postsubmit lane, whose kubeflow_tpu/* +
    # conformance/* globs already cover this subsystem.
    name="inferenceservice",
    include_dirs=[
        "kubeflow_tpu/platform/controllers/*", "kubeflow_tpu/platform/apis/*",
        "kubeflow_tpu/platform/runtime/*", "kubeflow_tpu/platform/testing/*",
        "kubeflow_tpu/models/serve.py", "kubeflow_tpu/telemetry/*",
        "manifests/*", "releasing/*",
    ],
    steps=[
        Step("unit", _pytest(
            "tests/ctrlplane/test_inferenceservice_controller.py",
            "tests/ctrlplane/test_autoscale.py",
            "tests/ctrlplane/test_manifests.py",
        )),
        Step("ledger", _pytest("tests/ctrlplane/test_jobqueue.py")
             + ["-m", "not slow", "-k", "inference"], depends="unit"),
        Step("storm", _pytest("tests/ctrlplane/test_chaos.py")
             + ["-m", "not slow", "-k", "inferenceservice"],
             depends="ledger"),
    ],
))

_register(ComponentWorkflow(
    # activator presubmit lane (ISSUE 19): the serving front door's unit
    # matrix — hold/replay lifecycle, wake-stamp cadence vs the
    # autoscaler's staleness race, per-tenant buckets + the SLO-knee
    # surcharge, WRR fair-share drain, structured shed outcomes — plus
    # the replica-side QoS gates (warm-503, deadline-504, priority
    # admission) and the noisy-neighbor conformance smoke: two tenants
    # through a live activator, the hammering one shed with wire 429s
    # while the quiet one's TTFT holds.
    name="activator",
    include_dirs=[
        "kubeflow_tpu/platform/activator.py",
        "kubeflow_tpu/platform/main.py",
        "kubeflow_tpu/platform/controllers/*",
        "kubeflow_tpu/models/client.py", "kubeflow_tpu/models/serve.py",
        "kubeflow_tpu/models/scheduler.py", "kubeflow_tpu/models/paged.py",
        "conformance/*", "releasing/*",
    ],
    steps=[
        Step("unit", _pytest(
            "tests/ctrlplane/test_activator.py",
            "tests/ctrlplane/test_autoscale.py",
        )),
        Step("qos-gates", _pytest("tests/test_serve.py",
                                  "tests/test_scheduler.py")
             + ["-m", "not slow", "-k",
                "priority or deadline or warm_probe or qos"],
             depends="unit"),
        Step("noisy-neighbor-smoke", [
            sys.executable, "conformance/run.py",
            "--only", "inferenceservice-noisy-neighbor",
        ], depends="unit"),
    ],
))

_register(ComponentWorkflow(
    # lint presubmit lane (ISSUE 13): kftlint over the whole tree — exit
    # nonzero on any unsuppressed, un-baselined finding (the shipped
    # baseline is EMPTY: every repo-native invariant in docs/analysis.md
    # is enforced from day one) — then the locktrace tier-1 suite: the
    # sharding/jobqueue/chaos harnesses under the lock-order tracer with
    # the coordinator's shared state guarded, plus the planted-violation
    # fixtures proving the detector bites.
    name="lint",
    include_dirs=[
        "kubeflow_tpu/*", "ci/kftlint_baseline.json",
        "tests/ctrlplane/lintcorpus/*", "releasing/*",
    ],
    steps=[
        Step("kftlint", [
            sys.executable, "-m", "kubeflow_tpu.analysis",
            "--baseline", "ci/kftlint_baseline.json",
        ]),
        Step("lint-unit", _pytest("tests/ctrlplane/test_analysis.py"),
             depends="kftlint"),
        Step("locktrace", _pytest("tests/ctrlplane/test_locktrace.py")
             + ["-m", "not slow"], depends="kftlint"),
    ],
))

_register(ComponentWorkflow(
    # journey presubmit lane (ISSUE 14): the causal-propagation unit
    # matrix (mint/stamp/extract/link round trips, client+wire header
    # carry, FlightPool context carry, the critical-path analyzer) plus
    # the merged-journey acceptance assertion — the TPUJob conformance
    # scenario's submit→Running critical-path decomposition — as a
    # presubmit smoke.  A severed journey (a raw create dropping the
    # traceparent, a broken extract at watch delivery) fails HERE, not
    # the first time an operator opens /debug/journey.
    name="journey",
    include_dirs=[
        "kubeflow_tpu/telemetry/*", "kubeflow_tpu/platform/runtime/*",
        "kubeflow_tpu/platform/k8s/*", "kubeflow_tpu/platform/testing/*",
        "kubeflow_tpu/platform/controllers/*", "conformance/*",
        "releasing/*",
    ],
    steps=[
        Step("unit", _pytest("tests/ctrlplane/test_causal.py")),
        Step("journey-smoke", [
            sys.executable, "conformance/run.py",
            "--only", "tpujob-train-converge",
        ], depends="unit"),
    ],
))

_register(ComponentWorkflow(
    # slo presubmit lane (ISSUE 15): the fleet metrics pipeline's unit
    # matrices — TSDB edge semantics (counter resets, ring/series
    # eviction, sparse buckets), fleetscrape (fan-out, reason-classified
    # failures, the autoscaler's stored-series sample), burn-rate rule
    # evaluation incl. the 2-replica exactly-one-Event pin, and goodput
    # tiling — then the autoscaler A/B migration pin.  The slow
    # ShardedFleet/storm acceptance variants ride the -m slow exclusion
    # into the conformance/postsubmit cadence.
    name="slo",
    include_dirs=[
        "kubeflow_tpu/telemetry/*", "kubeflow_tpu/platform/runtime/*",
        "kubeflow_tpu/platform/controllers/*",
        "kubeflow_tpu/platform/testing/*", "releasing/*",
    ],
    steps=[
        Step("unit", _pytest(
            "tests/ctrlplane/test_tsdb.py",
            "tests/ctrlplane/test_fleetscrape.py",
            "tests/ctrlplane/test_slo.py",
            "tests/ctrlplane/test_goodput.py",
        ) + ["-m", "not slow"]),
        Step("autoscale-ab", _pytest("tests/ctrlplane/test_autoscale.py"),
             depends="unit"),
    ],
))

_register(ComponentWorkflow(
    # profile presubmit lane (ISSUE 16): the sampling profiler's unit
    # matrix — window rotation/folded format under a fake clock, the
    # Tracer-seam attribution pins (a FlightPool slot sample lands under
    # the submitting controller's role, the fleetscrape pool carries a
    # stable name), the /debug/profile surface — plus the incident
    # flight recorder's determinism matrix (2-replica exactly-one-Event,
    # debounce, ring bound, bundle manifest shape), then the debug-index
    # coverage pin so neither surface can ship unlisted.
    name="profile",
    include_dirs=[
        "kubeflow_tpu/telemetry/*", "kubeflow_tpu/platform/runtime/*",
        "kubeflow_tpu/platform/testing/*", "releasing/*",
    ],
    steps=[
        Step("unit", _pytest(
            "tests/ctrlplane/test_profiler.py",
            "tests/ctrlplane/test_incidents.py",
        ) + ["-m", "not slow"]),
        Step("observability", _pytest(
            "tests/ctrlplane/test_observability.py",
        ) + ["-m", "not slow"], depends="unit"),
    ],
))

_register(ComponentWorkflow(
    name="admission-webhook",
    include_dirs=["kubeflow_tpu/platform/webhook/*", "releasing/*"],
    steps=[Step("unit", _pytest("tests/ctrlplane/test_webhook.py"))],
))

_register(ComponentWorkflow(
    name="web-apps",
    include_dirs=[
        "kubeflow_tpu/platform/apps/*", "kubeflow_tpu/platform/web/*",
        "kubeflow_tpu/platform/kfam/*", "kubeflow_tpu/platform/dashboard/*",
        "kubeflow_tpu/platform/frontend/*", "releasing/*",
    ],
    steps=[
        Step("unit", _pytest(
            "tests/ctrlplane/test_web_apps.py",
            "tests/ctrlplane/test_frontend.py",
        )),
        Step("e2e", [sys.executable, "ci/e2e.py"], depends="unit"),
    ],
))

_register(ComponentWorkflow(
    name="compute",
    include_dirs=[
        "kubeflow_tpu/models/*", "kubeflow_tpu/ops/*",
        "kubeflow_tpu/parallel/*", "kubeflow_tpu/train/*",
        "kubeflow_tpu/data/*", "__graft_entry__.py", "releasing/*",
    ],
    steps=[
        Step("unit", _pytest(
            "tests/test_models.py", "tests/test_attention.py",
            "tests/test_moe.py", "tests/test_parallel.py",
            "tests/test_parallel_extra.py", "tests/test_scheduler.py",
        )),
        Step("dryrun", [
            sys.executable, "-c",
            "import __graft_entry__ as g; g.dryrun_multichip(8)",
        ], depends="unit"),
    ],
))

_register(ComponentWorkflow(
    name="native",
    include_dirs=["native/*", "kubeflow_tpu/platform/native.py", "releasing/*"],
    steps=[
        Step("build", ["make", "-C", "native"]),
        Step("parity", _pytest(
            "tests/ctrlplane/test_native.py",
            "tests/ctrlplane/test_workqueue.py",
        ), depends="build"),
    ],
))

_register(ComponentWorkflow(
    # native-wire presubmit lane (ISSUE 18): build the native library,
    # then the wire fast-path matrix — the 3-way decode/merge/encode
    # semantics matrix (python/native/mixed engines), the native parity
    # suite, and the sharding suite with server-side shard filtering on
    # (its default) — plus a KF_NATIVE=0 leg proving the pure-Python
    # fallback passes the same codec matrix: the fallback is a pinned
    # contract, not a hope, because a box where the toolchain is absent
    # runs it for every event.
    name="native-wire",
    include_dirs=[
        "native/*", "kubeflow_tpu/platform/native.py",
        "kubeflow_tpu/platform/k8s/*", "kubeflow_tpu/platform/runtime/*",
        "releasing/*",
    ],
    steps=[
        Step("build", ["make", "-C", "native"]),
        Step("matrix", _pytest(
            "tests/ctrlplane/test_wirecodec.py",
            "tests/ctrlplane/test_native.py",
        ) + ["-m", "not slow"], depends="build"),
        Step("filtered-sharding", _pytest("tests/ctrlplane/test_sharding.py")
             + ["-m", "not slow", "-k", "not 1k_wave"], depends="build"),
        Step("python-fallback", ["env", "KF_NATIVE=0"] + _pytest(
            "tests/ctrlplane/test_wirecodec.py") + ["-m", "not slow"],
            depends="build"),
    ],
))

_register(ComponentWorkflow(
    name="notebook-images",
    include_dirs=["images/*", "examples/*", "releasing/*"],
    steps=[
        # Image definitions are validated structurally (Dockerfile lint +
        # example notebooks execute); actual builds run in-cluster with
        # Kaniko, mirroring the reference's build-test tasks.
        Step("examples", _pytest("tests/test_examples.py")),
    ],
))

_register(ComponentWorkflow(
    # The BASELINE.md throughput lane for configs 2-4.  Config 4 (ViT-B/16,
    # JAX) measures TPU-attached on this very image (same stack bench.py
    # drives); configs 2-3 (TF / torch-XLA) need a capable TPU VM.  One
    # command either way:
    #   python ci/workflows.py run hardware-baselines
    # Measured rows REPLACE same-config rows in BASELINE.md; configs whose
    # runtime is absent exit 3 with a loud per-config skip report instead
    # of pretending to measure.
    name="hardware-baselines",
    include_dirs=["images/*", "examples/*", "ci/hardware_baselines.py",
                  "releasing/*"],
    job_types=["hardware"],
    steps=[Step("measure", [sys.executable, "ci/hardware_baselines.py"])],
))

_register(ComponentWorkflow(
    name="conformance",
    include_dirs=["kubeflow_tpu/*", "conformance/*", "releasing/*"],
    job_types=["postsubmit"],
    steps=[Step("conformance", [sys.executable, "conformance/run.py"])],
))


def select(changed_paths: List[str], *, job_type: str = "presubmit") -> List[str]:
    """Workflow names triggered by the changed paths (the run_e2e_workflow
    include_dirs matching the reference dispatches on)."""
    out = []
    for wf in WORKFLOWS.values():
        if job_type not in wf.job_types:
            continue
        if any(wf.matches(p) for p in changed_paths):
            out.append(wf.name)
    return out


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] not in {"list", "select", "emit", "run"}:
        print(__doc__)
        return 2
    cmd, *rest = argv
    if cmd == "list":
        for wf in WORKFLOWS.values():
            print(f"{wf.name}: triggers={wf.include_dirs}")
        return 0
    if cmd == "select":
        for name in select(rest):
            print(name)
        return 0
    if cmd == "emit":
        import yaml

        wf = WORKFLOWS.get(rest[0] if rest else "")
        if wf is None:
            print(f"unknown workflow {rest}", file=sys.stderr)
            return 2
        print(yaml.safe_dump(wf.to_argo(), sort_keys=False))
        return 0
    # run
    names = rest or list(WORKFLOWS)
    ok = True
    for name in names:
        wf = WORKFLOWS.get(name)
        if wf is None:
            print(f"unknown workflow {name}", file=sys.stderr)
            return 2
        ok = wf.run_local() and ok
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
