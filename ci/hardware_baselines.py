#!/usr/bin/env python3
"""Hardware measurement lane for BASELINE.md configs 2-4 (VERDICT r2 #10,
r3 #3).

The dev/CI image ships no TensorFlow-TPU runtime and no torch_xla (and has
no network egress to install them), so the throughput numbers for

  config 2: jupyter-tensorflow-full single-device notebook (ResNet50 CIFAR)
  config 3: jupyter-pytorch-full -> PyTorch/XLA notebook (BERT fine-tune)
  config 4: codeserver-python image with JAX + Flax (ViT-B/16 training)

were unmeasured.  This script IS the measurement: run it on any capable
TPU VM (one command, emitted as the ``hardware-baselines`` workflow by
ci/workflows.py) and it

  * measures whichever runtimes are importable at the scales the example
    notebooks (examples/08, examples/03, examples/04) define,
  * prints one JSON line per config (measured or skipped+reason), and
  * records measured numbers in BASELINE.md with the date — REPLACING any
    prior row for the same config (re-running the lane must not grow the
    file; VERDICT r3 item 6).

Config 4 runs on the same JAX stack bench.py already drives through the
tunnel, so on THIS dev image it measures TPU-attached.

Exit codes: 0 = every config measured; 3 = at least one config skipped
because its runtime is absent (the expected result on the dev image —
loud, not silent).
"""
from __future__ import annotations

import datetime
import json
import os
import re
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # config 4 imports the kubeflow_tpu model zoo
    sys.path.insert(0, REPO)
BLOCK_HEADER = "Hardware lane measurements"

# The example notebooks' training scales (examples/08_resnet_cifar_tensorflow
# and examples/03_bert_finetune_pytorch_xla "real" branches).
TF_BATCH = 256
TF_STEPS = 50
TF_WARMUP = 5
BERT_BATCH = 32
BERT_SEQ = 128
BERT_STEPS = 30
BERT_WARMUP = 3
# ViT batch/model construction is owned by bench._vit_setup (shared arm).
VIT_STEPS = 20
VIT_WARMUP = 3


def measure_tf_resnet() -> dict:
    """Config 2: ResNet50 on CIFAR-shaped synthetic data under TPUStrategy
    when a TPU is attached, mirroring examples/08."""
    try:
        import tensorflow as tf
    except ImportError:
        return {"config": 2, "metric": "tf_resnet50_cifar_images_per_sec",
                "skipped": "tensorflow not installed"}

    try:
        resolver = tf.distribute.cluster_resolver.TPUClusterResolver(tpu="")
        tf.config.experimental_connect_to_cluster(resolver)
        tf.tpu.experimental.initialize_tpu_system(resolver)
        strategy = tf.distribute.TPUStrategy(resolver)
        device = "tpu"
    except Exception:
        strategy = tf.distribute.get_strategy()
        device = "cpu/gpu"

    with strategy.scope():
        model = tf.keras.applications.ResNet50(
            weights=None, input_shape=(32, 32, 3), classes=10
        )
        model.compile(
            optimizer=tf.keras.optimizers.SGD(0.1, momentum=0.9),
            loss=tf.keras.losses.SparseCategoricalCrossentropy(
                from_logits=False
            ),
        )
    images = tf.random.uniform((TF_BATCH, 32, 32, 3))
    labels = tf.random.uniform((TF_BATCH,), maxval=10, dtype=tf.int32)
    ds = tf.data.Dataset.from_tensors((images, labels)).repeat()
    it = iter(strategy.experimental_distribute_dataset(ds))

    @tf.function
    def step(batch):
        # Keras train_step(data) under strategy.run: the canonical custom
        # TPUStrategy loop (same shape as examples/08).
        return strategy.run(model.train_step, args=(batch,))

    def force(out):
        # Under TPUStrategy the per-replica values need a cross-replica
        # reduce before they are host-readable tensors.
        loss = out["loss"] if isinstance(out, dict) else out
        loss = strategy.reduce(tf.distribute.ReduceOp.MEAN, loss, axis=None)
        return float(loss)

    for _ in range(TF_WARMUP):
        out = step(next(it))
    force(out)
    t0 = time.perf_counter()
    for _ in range(TF_STEPS):
        out = step(next(it))
    force(out)  # device sync closes the timed window
    dt = time.perf_counter() - t0
    return {"config": 2, "metric": "tf_resnet50_cifar_images_per_sec",
            "value": round(TF_BATCH * TF_STEPS / dt, 1), "device": device,
            "batch": TF_BATCH, "steps": TF_STEPS}


def measure_torch_xla_bert() -> dict:
    """Config 3: tiny-BERT-config fine-tune step loop on the XLA device,
    mirroring examples/03's real branch (transformers BERT-base when the
    weights are reachable, random-init config otherwise)."""
    try:
        import torch
        import torch_xla.core.xla_model as xm
        from transformers import BertConfig, BertForSequenceClassification
    except ImportError as e:
        return {"config": 3, "metric": "torch_xla_bert_examples_per_sec",
                "skipped": f"runtime not installed ({e})"}

    device = xm.xla_device()
    cfg = BertConfig(num_labels=2)
    model = BertForSequenceClassification(cfg).to(device).train()
    optim = torch.optim.AdamW(model.parameters(), lr=2e-5)
    ids = torch.randint(0, cfg.vocab_size, (BERT_BATCH, BERT_SEQ),
                        device=device)
    labels = torch.randint(0, 2, (BERT_BATCH,), device=device)

    def step():
        optim.zero_grad()
        out = model(input_ids=ids, labels=labels)
        out.loss.backward()
        xm.optimizer_step(optim)
        return out.loss

    for _ in range(BERT_WARMUP):
        step()
    xm.mark_step()
    t0 = time.perf_counter()
    for _ in range(BERT_STEPS):
        loss = step()
    xm.mark_step()
    loss.item()  # device sync
    dt = time.perf_counter() - t0
    return {"config": 3, "metric": "torch_xla_bert_examples_per_sec",
            "value": round(BERT_BATCH * BERT_STEPS / dt, 1),
            "batch": BERT_BATCH, "seq": BERT_SEQ, "steps": BERT_STEPS}


def measure_jax_vit() -> dict:
    """Config 4: ViT-B/16 training step (JAX + Flax, bf16, adamw) at
    examples/04's batch, single device — the arm construction and the
    analytic-matmul flops accounting are SHARED with bench.py
    (bench._vit_setup / bench.vit_train_flops_per_image), so this lane's
    baseline and the bench's banded line can never measure different
    arms (round-5 dedup; the band compares the two directly)."""
    try:
        import jax

        import bench as bench_mod
    except ImportError as e:
        return {"config": 4, "metric": "jax_vit_b16_images_per_sec",
                "skipped": f"runtime not installed ({e})"}

    device = jax.devices()[0].platform
    smoke = bool(int(os.environ.get("KFT_HWLANE_SMOKE", "0")))
    model, state, step, data, batch, _ = bench_mod._vit_setup(smoke=smoke)
    steps, warmup = (2, 1) if smoke else (VIT_STEPS, VIT_WARMUP)
    for _ in range(warmup):
        state, m = step(state, data)
    float(m["loss"])  # scalar fetch: full device sync through the tunnel
    t0 = time.perf_counter()
    for _ in range(steps):
        state, m = step(state, data)
    float(m["loss"])
    dt = time.perf_counter() - t0
    ips = batch * steps / dt

    train_flops = bench_mod.vit_train_flops_per_image(model.cfg)
    tfs = ips * train_flops / 1e12
    return {"config": 4, "metric": "jax_vit_b16_images_per_sec",
            "value": round(ips, 1), "device": device, "batch": batch,
            "steps": steps,
            "model_gflops_per_image": round(train_flops / 1e9, 1),
            "model_tflops_per_sec": round(tfs, 1),
            "mfu_vs_197tf": round(tfs / 197.0, 4)}


def record_in_baseline(results, path=None) -> None:
    """Replace-not-append (VERDICT r3 item 6): BASELINE.md keeps exactly
    ONE "Hardware lane measurements" block with one row per config.  Rows
    for configs not re-measured this run are carried over unchanged (their
    own measurement date stays in the row); same-config rows are replaced.
    Running the lane twice yields one identical block."""
    measured = [r for r in results if "value" in r]
    if not measured:
        return
    path = path or os.path.join(REPO, "BASELINE.md")
    text = open(path).read()

    # Collect rows from every existing block (the r3 file carried two
    # near-duplicate blocks; later blocks win), then remove all blocks.
    block_re = re.compile(
        rf"\n*^{BLOCK_HEADER}[^\n]*\n\n(?:- config [^\n]*\n)*",
        re.M,
    )
    rows: dict[int, str] = {}
    for m in block_re.finditer(text):
        for line in m.group(0).splitlines():
            lm = re.match(r"- config (\d+):", line)
            if lm:
                rows[int(lm.group(1))] = line
    text = block_re.sub("\n", text)

    stamp = datetime.date.today().isoformat()
    for r in measured:
        extras = {k: v for k, v in r.items()
                  if k not in ("config", "metric", "value")}
        rows[r["config"]] = (
            f"- config {r['config']}: {r['metric']} = {r['value']} "
            f"({json.dumps(extras)}) [measured {stamp}]"
        )
    block = [f"{BLOCK_HEADER} (ci/hardware_baselines.py; same-config "
             "rows are replaced on re-run, date per row):", ""]
    block += [rows[k] for k in sorted(rows)]
    with open(path, "w") as f:
        f.write(text.rstrip("\n") + "\n\n" + "\n".join(block) + "\n")


def main() -> int:
    results = [measure_tf_resnet(), measure_torch_xla_bert(),
               measure_jax_vit()]
    for r in results:
        print(json.dumps(r), flush=True)
    record_in_baseline(results, path=os.environ.get("KFT_BASELINE_MD"))
    return 3 if any("skipped" in r for r in results) else 0


if __name__ == "__main__":
    sys.exit(main())
