#!/usr/bin/env python3
"""Hardware measurement lane for BASELINE.md configs 2-3 (VERDICT r2 #10).

The dev/CI image ships no TensorFlow and no torch_xla (and has no network
egress to install them), so the throughput numbers for

  config 2: jupyter-tensorflow-full single-device notebook (ResNet50 CIFAR)
  config 3: jupyter-pytorch-full -> PyTorch/XLA notebook (BERT fine-tune)

have never been measured.  This script IS the measurement: run it on any
TF- or torch-XLA-capable TPU VM (one command, emitted as the
``hardware-baselines`` workflow by ci/workflows.py) and it

  * measures whichever runtimes are importable at the scales the example
    notebooks (examples/08, examples/03) define,
  * prints one JSON line per config (measured or skipped+reason), and
  * appends measured numbers to BASELINE.md with the date, closing the
    standing gap the moment such an environment exists.

Exit codes: 0 = every config measured; 3 = at least one config skipped
because its runtime is absent (the expected result on the dev image —
loud, not silent).
"""
from __future__ import annotations

import datetime
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The example notebooks' training scales (examples/08_resnet_cifar_tensorflow
# and examples/03_bert_finetune_pytorch_xla "real" branches).
TF_BATCH = 256
TF_STEPS = 50
TF_WARMUP = 5
BERT_BATCH = 32
BERT_SEQ = 128
BERT_STEPS = 30
BERT_WARMUP = 3


def measure_tf_resnet() -> dict:
    """Config 2: ResNet50 on CIFAR-shaped synthetic data under TPUStrategy
    when a TPU is attached, mirroring examples/08."""
    try:
        import tensorflow as tf
    except ImportError:
        return {"config": 2, "metric": "tf_resnet50_cifar_images_per_sec",
                "skipped": "tensorflow not installed"}

    try:
        resolver = tf.distribute.cluster_resolver.TPUClusterResolver(tpu="")
        tf.config.experimental_connect_to_cluster(resolver)
        tf.tpu.experimental.initialize_tpu_system(resolver)
        strategy = tf.distribute.TPUStrategy(resolver)
        device = "tpu"
    except Exception:
        strategy = tf.distribute.get_strategy()
        device = "cpu/gpu"

    with strategy.scope():
        model = tf.keras.applications.ResNet50(
            weights=None, input_shape=(32, 32, 3), classes=10
        )
        model.compile(
            optimizer=tf.keras.optimizers.SGD(0.1, momentum=0.9),
            loss=tf.keras.losses.SparseCategoricalCrossentropy(
                from_logits=False
            ),
        )
    images = tf.random.uniform((TF_BATCH, 32, 32, 3))
    labels = tf.random.uniform((TF_BATCH,), maxval=10, dtype=tf.int32)
    ds = tf.data.Dataset.from_tensors((images, labels)).repeat()
    it = iter(strategy.experimental_distribute_dataset(ds))

    @tf.function
    def step(batch):
        # Keras train_step(data) under strategy.run: the canonical custom
        # TPUStrategy loop (same shape as examples/08).
        return strategy.run(model.train_step, args=(batch,))

    def force(out):
        # Under TPUStrategy the per-replica values need a cross-replica
        # reduce before they are host-readable tensors.
        loss = out["loss"] if isinstance(out, dict) else out
        loss = strategy.reduce(tf.distribute.ReduceOp.MEAN, loss, axis=None)
        return float(loss)

    for _ in range(TF_WARMUP):
        out = step(next(it))
    force(out)
    t0 = time.perf_counter()
    for _ in range(TF_STEPS):
        out = step(next(it))
    force(out)  # device sync closes the timed window
    dt = time.perf_counter() - t0
    return {"config": 2, "metric": "tf_resnet50_cifar_images_per_sec",
            "value": round(TF_BATCH * TF_STEPS / dt, 1), "device": device,
            "batch": TF_BATCH, "steps": TF_STEPS}


def measure_torch_xla_bert() -> dict:
    """Config 3: tiny-BERT-config fine-tune step loop on the XLA device,
    mirroring examples/03's real branch (transformers BERT-base when the
    weights are reachable, random-init config otherwise)."""
    try:
        import torch
        import torch_xla.core.xla_model as xm
        from transformers import BertConfig, BertForSequenceClassification
    except ImportError as e:
        return {"config": 3, "metric": "torch_xla_bert_examples_per_sec",
                "skipped": f"runtime not installed ({e})"}

    device = xm.xla_device()
    cfg = BertConfig(num_labels=2)
    model = BertForSequenceClassification(cfg).to(device).train()
    optim = torch.optim.AdamW(model.parameters(), lr=2e-5)
    ids = torch.randint(0, cfg.vocab_size, (BERT_BATCH, BERT_SEQ),
                        device=device)
    labels = torch.randint(0, 2, (BERT_BATCH,), device=device)

    def step():
        optim.zero_grad()
        out = model(input_ids=ids, labels=labels)
        out.loss.backward()
        xm.optimizer_step(optim)
        return out.loss

    for _ in range(BERT_WARMUP):
        step()
    xm.mark_step()
    t0 = time.perf_counter()
    for _ in range(BERT_STEPS):
        loss = step()
    xm.mark_step()
    loss.item()  # device sync
    dt = time.perf_counter() - t0
    return {"config": 3, "metric": "torch_xla_bert_examples_per_sec",
            "value": round(BERT_BATCH * BERT_STEPS / dt, 1),
            "batch": BERT_BATCH, "seq": BERT_SEQ, "steps": BERT_STEPS}


def append_to_baseline(results) -> None:
    measured = [r for r in results if "value" in r]
    if not measured:
        return
    stamp = datetime.date.today().isoformat()
    lines = ["", f"Hardware lane measurements ({stamp}, "
                 "ci/hardware_baselines.py):", ""]
    for r in measured:
        lines.append(f"- config {r['config']}: {r['metric']} = "
                     f"{r['value']} ({json.dumps({k: v for k, v in r.items() if k not in ('config', 'metric', 'value')})})")
    with open(os.path.join(REPO, "BASELINE.md"), "a") as f:
        f.write("\n".join(lines) + "\n")


def main() -> int:
    results = [measure_tf_resnet(), measure_torch_xla_bert()]
    for r in results:
        print(json.dumps(r), flush=True)
    append_to_baseline(results)
    return 3 if any("skipped" in r for r in results) else 0


if __name__ == "__main__":
    sys.exit(main())
