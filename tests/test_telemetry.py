"""Compute-plane telemetry (ISSUE 6): HBM gauges degrade gracefully on
CPU, the train loop's MFU gauge matches bench.py's accounting, slow steps
dump span trees, the attention pre-flight estimator fires BEFORE any
allocation (the BENCH_r05 crash mode, observable CPU-only), and the
shared trace core serves both halves of the repo."""
import json
import logging
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu import telemetry
from kubeflow_tpu.telemetry import compute as ctel


def _sample(name, labels=None):
    return ctel.registry.get_sample_value(name, labels or {})


class _FakeDevice:
    platform = "faketpu"

    def __init__(self, id, stats):
        self.id = id
        self._stats = stats

    def memory_stats(self):
        return self._stats


# -- HBM watermarks -----------------------------------------------------------


def test_memory_stats_none_degrades_to_absent_gauges(monkeypatch):
    """A backend without memory introspection (CPU) exports NO
    device_memory_bytes samples — and nothing crashes."""
    monkeypatch.setattr(jax, "devices", lambda: [_FakeDevice(0, None)])
    text = ctel.render().decode()
    assert "device_memory_bytes{" not in text
    assert ctel.hbm_peak_bytes() is None
    assert ctel.free_hbm_bytes() is None


def test_memory_stats_errors_degrade_too(monkeypatch):
    class Exploding:
        platform, id = "faketpu", 0

        def memory_stats(self):
            raise RuntimeError("backend wedged")

    monkeypatch.setattr(jax, "devices", lambda: [Exploding()])
    assert ctel.device_memory_snapshot() == {}
    assert "device_memory_bytes{" not in ctel.render().decode()


def test_device_memory_collector_exports_kinds(monkeypatch):
    stats = {"bytes_in_use": 3 * 2**30, "peak_bytes_in_use": 5 * 2**30,
             "bytes_limit": 16 * 2**30}
    monkeypatch.setattr(
        jax, "devices",
        lambda: [_FakeDevice(0, stats), _FakeDevice(1, None)])
    text = ctel.render().decode()
    for kind, val in (("in_use", 3), ("peak", 5), ("limit", 16)):
        assert _sample("device_memory_bytes",
                       {"device": "faketpu:0", "kind": kind}) == val * 2**30
    # The stats-less sibling is absent, not zero.
    assert 'device="faketpu:1"' not in text
    assert ctel.hbm_peak_bytes() == 5 * 2**30
    assert ctel.free_hbm_bytes() == 13 * 2**30


# -- attention pre-flight estimator ------------------------------------------


def test_attention_estimator_fires_before_any_allocation(monkeypatch, caplog):
    """A deliberately oversized causal xla_attention (8 GB+ of O(S²)
    state on a mocked 1 GB-free device) publishes the estimate gauge and
    the structured warning from TRACE time — jax.eval_shape allocates
    nothing, which is exactly the point: the BENCH_r05
    RESOURCE_EXHAUSTED becomes a signal before the OOM, not after."""
    from kubeflow_tpu.ops.attention import (
        attention_footprint_bytes,
        xla_attention,
    )

    monkeypatch.setattr(ctel, "free_hbm_bytes", lambda: 2**30)
    before = _sample("attention_mask_budget_warnings_total") or 0.0
    b, s, h, d = 2, 8192, 8, 64
    q = jax.ShapeDtypeStruct((b, s, h, d), jnp.bfloat16)
    with caplog.at_level(logging.WARNING,
                         logger="kubeflow_tpu.telemetry.compute"):
        out = jax.eval_shape(
            lambda q, k, v: xla_attention(q, k, v, causal=True), q, q, q)
    assert out.shape == (b, s, h, d)

    want = attention_footprint_bytes(
        batch=b, heads=h, q_len=s, k_len=s, causal=True, segments=False)
    # Mask-free footprint (ISSUE 7): f32 logits + probs ONLY — the causal
    # condition is iota-fused into the select, no tril buffer term.
    assert want == 2 * 4 * b * h * s * s
    assert _sample("attention_mask_bytes_estimate") == want
    assert _sample("attention_mask_budget_warnings_total") == before + 1

    dumps = [r for r in caplog.records
             if "attention footprint over budget" in r.getMessage()]
    assert dumps, caplog.records
    msg = dumps[-1].getMessage()
    payload = json.loads(msg[msg.index("{"):])
    assert payload["event"] == "attention_mask_budget_exceeded"
    assert payload["estimate_bytes"] == want
    assert payload["free_hbm_bytes"] == 2**30
    assert payload["q_len"] == s and payload["causal"] is True


def test_attention_estimator_quiet_within_budget(monkeypatch, caplog):
    from kubeflow_tpu.ops.attention import xla_attention

    monkeypatch.setattr(ctel, "free_hbm_bytes", lambda: 2**34)
    before = _sample("attention_mask_budget_warnings_total") or 0.0
    q = jnp.ones((1, 16, 2, 8), jnp.float32)
    with caplog.at_level(logging.WARNING,
                         logger="kubeflow_tpu.telemetry.compute"):
        xla_attention(q, q, q, causal=True)
    assert _sample("attention_mask_budget_warnings_total") == before
    # The gauge still tracks the (tiny) footprint — logits + probs only.
    assert _sample("attention_mask_bytes_estimate") == 2 * 4 * 2 * 256


def test_attention_estimator_skips_unmasked_path():
    from kubeflow_tpu.ops.attention import xla_attention

    marker = 123456789.0
    ctel.attention_mask_bytes_estimate.set(marker)
    q = jnp.ones((1, 8, 2, 8), jnp.float32)
    xla_attention(q, q, q, causal=False)  # no mask -> no estimate update
    assert _sample("attention_mask_bytes_estimate") == marker


# -- train-loop step telemetry ------------------------------------------------


def _fake_lm_loop(n_steps, step_seconds=0.0, log_every=0, **cfg_kwargs):
    """train_loop over a pure-Python step (no jit) with [4, 32] int
    batches — fast, and everything telemetry sees is identical in shape
    to a real LM loop."""
    from kubeflow_tpu.train.loop import LoopConfig, train_loop

    class State:
        step = 0

    def step_fn(state, batch):
        if step_seconds:
            time.sleep(step_seconds)
        state.step += 1
        return state, {"loss": 1.0 / state.step}

    batches = (np.ones((4, 32), np.int32) for _ in range(n_steps))
    return train_loop(
        State(), step_fn, batches,
        LoopConfig(total_steps=n_steps, log_every=log_every, **cfg_kwargs),
    )


def test_step_histogram_and_quantile_gauges_populate():
    snap = ctel.step_snapshot()
    _fake_lm_loop(4)
    q = ctel.step_quantiles((0.5, 0.99), since=snap)
    assert q[0.5] is not None and q[0.99] is not None
    text = ctel.render().decode()
    assert "train_step_seconds_p50" in text
    assert "train_step_seconds_p99" in text
    # First step of the run lands in the compile phase, the rest in run.
    assert ctel.registry.get_sample_value(
        "train_step_seconds_count", {"phase": "run"}) >= 3


def test_mfu_gauge_matches_bench_accounting():
    """Acceptance: the loop's exported tokens/s + MFU agree with
    bench.py's own accounting (tokens/s x FLOPs/token / peak) within 1%
    on a toy fixed-shape run — same formula, same telemetry layer."""
    import bench
    from kubeflow_tpu.models.llama import CONFIGS

    cfg = CONFIGS["llama_debug"]
    fpt = ctel.lm_train_flops_per_token(cfg, 32)
    # One accounting: bench.py's name IS the telemetry function.
    assert bench.lm_train_flops_per_token(cfg, 32) == fpt

    _, history = _fake_lm_loop(6, log_every=3, flops_per_token=fpt)
    last = history[-1]
    assert last["tokens_per_sec"] > 0
    # tokens inferred from the [4, 32] int batch.
    assert last["tokens_per_sec"] == pytest.approx(
        last["steps_per_sec"] * 4 * 32, rel=1e-6)
    expected_mfu = bench.ctel.mfu(last["tokens_per_sec"], fpt)
    assert _sample("train_mfu") == pytest.approx(expected_mfu, rel=0.01)
    assert _sample("train_tokens_per_sec") == pytest.approx(
        last["tokens_per_sec"], rel=0.01)
    assert last["mfu"] == pytest.approx(expected_mfu, rel=0.01)


def test_slow_step_dump_fires_on_injected_sleep(monkeypatch, caplog):
    """Acceptance: an injected slow step yields ONE JSON dump with >= 3
    spans (data -> dispatch -> bookkeeping) on the train trace logger."""
    monkeypatch.setattr(ctel, "TRAIN_SLOW_STEP_SECONDS", 0.01)
    before = _sample("train_slow_steps_total") or 0.0
    with caplog.at_level(logging.WARNING, logger="kubeflow_tpu.train.trace"):
        _fake_lm_loop(2, step_seconds=0.03)
    dumps = [r for r in caplog.records
             if "slow train step trace" in r.getMessage()]
    assert dumps
    msg = dumps[0].getMessage()
    payload = json.loads(msg[msg.index("{"):])
    assert payload["component"] == "train"
    assert payload["duration_ms"] >= 10.0
    names = [s["name"] for s in payload["spans"]]
    assert len(names) >= 3, names
    assert {"data", "dispatch", "bookkeeping"} <= set(names)
    dispatch = next(s for s in payload["spans"] if s["name"] == "dispatch")
    assert dispatch["phase"] in ("compile", "run")
    assert _sample("train_slow_steps_total") >= before + 2
    # Same trace queryable from the ring buffer (the /debug/traces source).
    assert any(t["trace_id"] == payload["trace_id"]
               for t in ctel.train_tracer.recent())


def test_log_window_barrier_not_counted_as_slow_step(monkeypatch, caplog):
    """The log-step metric fetch is a whole-window barrier on async
    backends — it must not trip the slow-step dump or land in the step
    histogram (only data+dispatch count)."""
    from kubeflow_tpu.train.loop import LoopConfig, train_loop

    monkeypatch.setattr(ctel, "TRAIN_SLOW_STEP_SECONDS", 0.02)
    before = _sample("train_slow_steps_total") or 0.0

    class StallingMetric:
        """float() stalls, like fetching a device value drains the
        pipeline."""

        def __float__(self):
            time.sleep(0.05)
            return 1.0

    class State:
        step = 0

    def step_fn(state, batch):
        return state, {"loss": StallingMetric()}

    batches = (np.ones((4, 32), np.int32) for _ in range(3))
    with caplog.at_level(logging.WARNING, logger="kubeflow_tpu.train.trace"):
        train_loop(State(), step_fn, batches,
                   LoopConfig(total_steps=3, log_every=1))
    assert not [r for r in caplog.records
                if "slow train step trace" in r.getMessage()]
    assert _sample("train_slow_steps_total") == before


def test_barrier_survives_non_scalar_first_metric():
    from kubeflow_tpu.train.loop import _barrier

    fetched = []

    class Scalar:
        def __float__(self):
            fetched.append(True)
            return 1.0

    # A multi-element leading metric must not stop the sweep before a
    # convertible value provides the completion barrier.
    _barrier({"per_token": np.ones(3), "loss": Scalar()})
    assert fetched == [True]
    _barrier({})  # empty metrics: no crash
    _barrier({"arr": np.ones(3)})  # nothing scalar: block_until_ready path


def test_slow_step_auto_captures_profile(monkeypatch, tmp_path):
    """A slow step arms a JAX profiler capture of the NEXT step (once per
    run), wired through train/profiling.py machinery."""
    import os

    monkeypatch.setattr(ctel, "TRAIN_SLOW_STEP_SECONDS", 0.01)
    logdir = str(tmp_path / "slowprof")
    _fake_lm_loop(3, step_seconds=0.03, slow_step_profile_dir=logdir)
    found = []
    for _root, _dirs, files in os.walk(logdir):
        found.extend(files)
    assert found, f"no profiler trace files under {logdir}"
    # The captured step carries a profile span.
    prof = [t for t in ctel.train_tracer.recent()
            for s in t["spans"] if s["name"] == "profile"]
    assert prof


def test_default_log_emits_structured_kv_line(capsys):
    from kubeflow_tpu.train.loop import _default_log

    _default_log(4, {"loss": 1.5, "steps_per_sec": 2.25, "step": 4})
    out = capsys.readouterr().out.strip()
    assert out.startswith("train_step ")
    fields = dict(kv.split("=", 1) for kv in out.split()[1:])
    assert fields == {"step": "4", "loss": "1.5", "steps_per_sec": "2.25"}


def test_logfmt_shared_formatter():
    line = telemetry.logfmt("ev", a=1, b=0.123456789, c="x")
    assert line == "ev a=1 b=0.123457 c=x"


# -- profiling robustness (satellite) ----------------------------------------


def test_profile_trace_never_masks_region_exception(monkeypatch, caplog):
    """A crashed region propagates ITS exception even when stop_trace
    blows up in the unwind (the pre-fix behavior raised the profiler's
    error instead, masking the training failure)."""
    from kubeflow_tpu.train import profiling

    monkeypatch.setattr(jax.profiler, "start_trace", lambda logdir: None)

    def bad_stop():
        raise RuntimeError("profiler wedged")

    monkeypatch.setattr(jax.profiler, "stop_trace", bad_stop)
    with caplog.at_level(logging.WARNING,
                         logger="kubeflow_tpu.train.profiling"):
        with pytest.raises(ValueError, match="training blew up"):
            with profiling.profile_trace(str("/tmp/kft-prof-test")):
                raise ValueError("training blew up")
    assert any("stop_trace failed" in r.getMessage() for r in caplog.records)


def test_profile_trace_clean_path_still_strict(monkeypatch, tmp_path):
    """On the SUCCESS path a stop_trace failure propagates — a 'profile'
    that wrote no trace must not report success."""
    from kubeflow_tpu.train import profiling

    monkeypatch.setattr(jax.profiler, "start_trace", lambda logdir: None)

    def bad_stop():
        raise RuntimeError("no trace written")

    monkeypatch.setattr(jax.profiler, "stop_trace", bad_stop)
    with pytest.raises(RuntimeError, match="no trace written"):
        with profiling.profile_trace(str(tmp_path)):
            pass


def test_profile_trace_start_failure_propagates(monkeypatch, tmp_path):
    from kubeflow_tpu.train import profiling

    def bad_start(logdir):
        raise RuntimeError("no backend")

    monkeypatch.setattr(jax.profiler, "start_trace", bad_start)
    with pytest.raises(RuntimeError, match="no backend"):
        with profiling.profile_trace(str(tmp_path)):
            pytest.fail("region must not run when start_trace failed")


def test_generate_decode_rejects_mismatched_budget():
    """The decode budget must equal what the prefill sized its cache for
    — a longer scan would clamp writes into the last cache slot and
    return garbage with no error (review finding); defaulted = safe,
    mismatched = loud."""
    import dataclasses

    from kubeflow_tpu.models.generate import (
        generate,
        generate_decode,
        generate_prefill,
    )
    from kubeflow_tpu.models.llama import CONFIGS, Llama

    cfg = dataclasses.replace(CONFIGS["llama_debug"], max_seq_len=64)
    model = Llama(cfg)
    params = model.init(jax.random.key(0),
                        jnp.ones((1, 8), jnp.int32))["params"]
    prompt = jnp.array([[5, 9, 2]], jnp.int32)
    one = generate(model, params, prompt, max_new_tokens=4)
    _, st = generate_prefill(model, params, prompt, max_new_tokens=4)
    # Omitting the kwarg inherits the prefill budget.
    assert (generate_decode(model, params, st) == one).all()
    _, st = generate_prefill(model, params, prompt, max_new_tokens=4)
    with pytest.raises(ValueError, match="does not match the budget"):
        generate_decode(model, params, st, max_new_tokens=32)


# -- shared trace core --------------------------------------------------------


def test_tracer_isolated_per_plane():
    """The train tracer and a fresh serve-style tracer share ONE
    implementation but never interleave buffers or active slots."""
    from kubeflow_tpu.telemetry.trace import Tracer

    a = Tracer("a", keys=("component", "request"))
    b = Tracer("b", keys=("component", "request"))
    a.begin("a", "r1")
    assert a.active() and not b.active()
    with a.span("x"):
        pass
    with b.span("ghost"):  # no active b trace: no-op
        pass
    d = a.finish("ok")
    assert [s["name"] for s in d["spans"]] == ["x"]
    assert a.recent() and not b.recent()


def test_tracer_key_naming_matches_plane():
    from kubeflow_tpu.telemetry.trace import Tracer

    t = Tracer("serve", keys=("component", "request"))
    t.begin("model-serve", "req-1")
    d = t.finish("ok")
    assert d["component"] == "model-serve" and d["request"] == "req-1"
    assert "controller" not in d


# -- paged-KV serve metrics (ISSUE 17) ---------------------------------------


def _metric_value(text, name, labels=""):
    import re

    pat = rf"^{re.escape(name)}{re.escape(labels)} ([0-9.e+-]+)$"
    m = re.search(pat, text, re.M)
    return float(m.group(1)) if m else None


def test_serve_kv_page_balance_invariants():
    """The paged pool's metric balance, pinned: at drain
    free + active + shared == pages_total - 1 (the null page belongs to
    no state), active returns to 0, the prefix counters accrue on a
    shared-prompt workload, and fragmentation reads 0 with no live
    rows."""
    import dataclasses

    from werkzeug.test import Client

    from kubeflow_tpu.models.llama import CONFIGS, Llama
    from kubeflow_tpu.models.paged import PagedDecodeScheduler
    from kubeflow_tpu.models.serve import GenerationService, create_app

    cfg = dataclasses.replace(CONFIGS["llama_debug"], max_seq_len=64)
    model = Llama(cfg)
    params = model.init(jax.random.key(0), jnp.ones((1, 8), jnp.int32))[
        "params"]
    service = GenerationService(model, params)
    client = Client(create_app(service, model_name="m"))
    sched = PagedDecodeScheduler(
        model, params, slots=4, slot_len=64, quantum=4, page_len=8,
        prefill_chunk=16, telemetry=lambda: service.telemetry)
    service._scheduler = sched
    sys_prompt = [9, 8, 7, 6, 5, 4, 3, 2, 1] * 2  # 18 tokens = 2+ pages
    service.generate([sys_prompt + [40], sys_prompt + [41]],
                     max_new_tokens=5)
    service.generate([sys_prompt + [42]], max_new_tokens=5)  # cache hit
    text = client.get("/metrics").get_data(as_text=True)
    free = _metric_value(text, "serve_kv_pages", '{state="free"}')
    active = _metric_value(text, "serve_kv_pages", '{state="active"}')
    shared = _metric_value(text, "serve_kv_pages", '{state="shared"}')
    assert active == 0.0  # pool drained
    assert shared > 0  # the system prompt stayed resident
    assert free + active + shared == sched.num_pages - 1
    assert _metric_value(
        text, "serve_kv_page_fragmentation_ratio") == 0.0
    hits = _metric_value(text, "serve_prefix_cache_hits_total")
    misses = _metric_value(text, "serve_prefix_cache_misses_total")
    assert hits > 0 and misses > 0  # second request hit, first missed
    # Counters mirror the scheduler's own ledger exactly.
    st = sched.stats()
    assert (hits, misses) == (st["prefix_hits"], st["prefix_misses"])


def test_serve_spec_decode_counters_balance():
    """accepted <= proposed always; with draft == target (greedy
    determinism) every proposal is accepted and both counters ride the
    serve registry."""
    import dataclasses

    from werkzeug.test import Client

    from kubeflow_tpu.models.llama import CONFIGS, Llama
    from kubeflow_tpu.models.paged import PagedDecodeScheduler
    from kubeflow_tpu.models.serve import GenerationService, create_app

    cfg = dataclasses.replace(CONFIGS["llama_debug"], max_seq_len=64)
    model = Llama(cfg)
    params = model.init(jax.random.key(0), jnp.ones((1, 8), jnp.int32))[
        "params"]
    service = GenerationService(model, params)
    client = Client(create_app(service, model_name="m"))
    service._scheduler = PagedDecodeScheduler(
        model, params, slots=4, slot_len=64, quantum=4, page_len=8,
        spec_tokens=3, draft_model=model, draft_params=params,
        telemetry=lambda: service.telemetry)
    service.generate([[5, 9, 2, 7]], max_new_tokens=10)
    text = client.get("/metrics").get_data(as_text=True)
    proposed = _metric_value(
        text, "serve_spec_decode_proposed_tokens_total")
    accepted = _metric_value(
        text, "serve_spec_decode_accepted_tokens_total")
    assert proposed > 0
    assert accepted == proposed  # draft == target: the all-accept bound
