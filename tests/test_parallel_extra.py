"""Tests: Ulysses SP, pipeline parallelism, checkpoint/resume, data loader.

All on the 8-virtual-CPU-device mesh from conftest — the same surface the
driver's dryrun_multichip uses.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

# Compute-side modules need the accelerator-era jax API (jax.shard_map et
# al.); importorskip keeps COLLECTION clean on platform-only environments
# instead of erroring the whole tier-1 run (BENCH/ISSUE 5 satellite).
pytest.importorskip(
    "kubeflow_tpu.parallel.pipeline",
    reason="compute-side accelerator env required (jax.shard_map)",
    exc_type=ImportError)
pytest.importorskip(
    "kubeflow_tpu.parallel.ulysses",
    reason="compute-side accelerator env required (jax.shard_map)",
    exc_type=ImportError)

from kubeflow_tpu.data import ShardedLoader, synthetic_image_batches, synthetic_lm_batches
from kubeflow_tpu.ops.attention import xla_attention
from kubeflow_tpu.parallel.mesh import MeshConfig, make_mesh
from kubeflow_tpu.parallel.pipeline import pipeline_apply, stack_stage_params
from kubeflow_tpu.parallel.ulysses import ulysses_attention


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_reference(devices8, causal):
    mesh = make_mesh(dp=2, sp=4, devices=devices8)
    k0 = jax.random.key(0)
    q = jax.random.normal(jax.random.fold_in(k0, 1), (4, 64, 8, 16))
    k = jax.random.normal(jax.random.fold_in(k0, 2), (4, 64, 4, 16))
    v = jax.random.normal(jax.random.fold_in(k0, 3), (4, 64, 4, 16))
    out = jax.jit(
        lambda q, k, v: ulysses_attention(q, k, v, mesh=mesh, causal=causal)
    )(q, k, v)
    ref = xla_attention(q, k, v, causal=causal)
    assert jnp.max(jnp.abs(out - ref)) < 2e-5


def test_ulysses_rejects_indivisible_heads(devices8):
    mesh = make_mesh(sp=8, devices=devices8)
    q = jnp.zeros((2, 16, 6, 8))
    with pytest.raises(ValueError, match="must divide"):
        ulysses_attention(q, q, q, mesh=mesh)


def test_ulysses_composes_with_tp_axis(devices8):
    # sp=2, tp=2, dp=2: attention must ignore the other axes correctly.
    mesh = make_mesh(dp=2, tp=2, sp=2, devices=devices8)
    q = jax.random.normal(jax.random.key(1), (4, 32, 4, 8))
    out = jax.jit(lambda q: ulysses_attention(q, q, q, mesh=mesh, causal=True))(q)
    ref = xla_attention(q, q, q, causal=True)
    assert jnp.max(jnp.abs(out - ref)) < 2e-5


def test_llama_ulysses_impl_matches_xla(devices8):
    import dataclasses

    from kubeflow_tpu.models.llama import CONFIGS, Llama
    from kubeflow_tpu.parallel.context import global_mesh

    mesh = make_mesh(dp=2, sp=4, devices=devices8)
    cfg = dataclasses.replace(CONFIGS["llama_debug"], attn_impl="xla")
    tokens = jax.random.randint(jax.random.key(0), (2, 64), 0, 256)
    model = Llama(cfg)
    params = model.init(jax.random.key(1), tokens)["params"]
    ref = model.apply({"params": params}, tokens)

    cfg_u = dataclasses.replace(cfg, attn_impl="ulysses")
    with global_mesh(mesh):
        out = jax.jit(
            lambda p, t: Llama(cfg_u).apply({"params": p}, t)
        )(params, tokens)
    assert jnp.max(jnp.abs(out - ref)) < 2e-4


# -- pipeline -----------------------------------------------------------------


def _mlp_stage(params, x):
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    return h @ params["w2"] + params["b2"]


def _stage_params(rng, n_stages, d, hidden):
    per_stage = []
    for i in range(n_stages):
        k1, k2, rng = jax.random.split(rng, 3)
        per_stage.append(
            {
                "w1": jax.random.normal(k1, (d, hidden)) / np.sqrt(d),
                "b1": jnp.zeros((hidden,)),
                "w2": jax.random.normal(k2, (hidden, d)) / np.sqrt(hidden),
                "b2": jnp.zeros((d,)),
            }
        )
    return per_stage


@pytest.mark.parametrize("pp,n_micro", [(2, 4), (4, 8)])
def test_pipeline_matches_sequential(devices8, pp, n_micro):
    mesh = make_mesh(pp=pp, dp=8 // pp, devices=devices8)
    d, hidden, batch = 8, 16, 16
    per_stage = _stage_params(jax.random.key(0), pp, d, hidden)
    stacked = stack_stage_params(per_stage)
    x = jax.random.normal(jax.random.key(1), (batch, d))

    out = jax.jit(
        lambda p, x: pipeline_apply(_mlp_stage, p, x, mesh=mesh, n_micro=n_micro)
    )(stacked, x)

    ref = x
    for p in per_stage:
        ref = _mlp_stage(p, ref)
    assert jnp.max(jnp.abs(out - ref)) < 1e-4


def test_pipeline_differentiable(devices8):
    pp, d, hidden, batch = 2, 4, 8, 8
    mesh = make_mesh(pp=pp, dp=4, devices=devices8)
    stacked = stack_stage_params(_stage_params(jax.random.key(0), pp, d, hidden))
    x = jax.random.normal(jax.random.key(1), (batch, d))

    def loss(p, x):
        y = pipeline_apply(_mlp_stage, p, x, mesh=mesh, n_micro=2)
        return jnp.mean(y**2)

    g = jax.jit(jax.grad(loss))(stacked, x)

    def loss_ref(p_list, x):
        for p in p_list:
            x = _mlp_stage(p, x)
        return jnp.mean(x**2)

    per_stage = [jax.tree.map(lambda l: l[i], stacked) for i in range(pp)]
    g_ref_list = jax.grad(loss_ref)(per_stage, x)
    g_ref = jax.tree.map(lambda *xs: jnp.stack(xs), *g_ref_list)
    for a, b in zip(jax.tree.leaves(g), jax.tree.leaves(g_ref)):
        assert jnp.max(jnp.abs(a - b)) < 1e-4


@pytest.mark.parametrize("interleave", [2, 4])
def test_pipeline_interleaved_matches_sequential(devices8, interleave):
    pp, n_micro = 2, 2
    mesh = make_mesh(pp=pp, dp=8 // pp, devices=devices8)
    d, hidden, batch = 8, 16, 16
    total = pp * interleave
    per_stage = _stage_params(jax.random.key(0), total, d, hidden)
    stacked = stack_stage_params(per_stage)
    x = jax.random.normal(jax.random.key(1), (batch, d))

    out = jax.jit(
        lambda p, x: pipeline_apply(
            _mlp_stage, p, x, mesh=mesh, n_micro=n_micro, interleave=interleave
        )
    )(stacked, x)

    ref = x
    for p in per_stage:
        ref = _mlp_stage(p, ref)
    assert jnp.max(jnp.abs(out - ref)) < 1e-4


def test_pipeline_interleaved_differentiable(devices8):
    pp, v, d, hidden, batch = 2, 2, 4, 8, 8
    mesh = make_mesh(pp=pp, dp=4, devices=devices8)
    total = pp * v
    stacked = stack_stage_params(_stage_params(jax.random.key(0), total, d, hidden))
    x = jax.random.normal(jax.random.key(1), (batch, d))

    def loss(p, x):
        y = pipeline_apply(
            _mlp_stage, p, x, mesh=mesh, n_micro=2, interleave=v
        )
        return jnp.mean(y**2)

    g = jax.jit(jax.grad(loss))(stacked, x)

    def loss_ref(p_list, x):
        for p in p_list:
            x = _mlp_stage(p, x)
        return jnp.mean(x**2)

    per_stage = [jax.tree.map(lambda l: l[i], stacked) for i in range(total)]
    g_ref_list = jax.grad(loss_ref)(per_stage, x)
    g_ref = jax.tree.map(lambda *xs: jnp.stack(xs), *g_ref_list)
    for a, b in zip(jax.tree.leaves(g), jax.tree.leaves(g_ref)):
        assert jnp.max(jnp.abs(a - b)) < 1e-4


def test_pipeline_interleaved_with_param_specs(devices8):
    """User param_specs must shift past the inserted local rounds axis."""
    from jax.sharding import PartitionSpec as PS

    pp, v, d, hidden = 2, 2, 8, 16
    mesh = make_mesh(pp=pp, tp=2, dp=2, devices=devices8)
    per = _stage_params(jax.random.key(0), pp * v, d, hidden)
    stacked = stack_stage_params(per)
    x = jax.random.normal(jax.random.key(1), (8, d))
    # Megatron layout: w1 column-split, w2 row-split; the stage fn running
    # inside shard_map must psum the partial second matmul over tp itself.
    specs = {"w1": PS(None, "tp"), "b1": PS("tp"),
             "w2": PS("tp", None), "b2": PS(None)}

    def tp_stage(params, x):
        h = jnp.tanh(x @ params["w1"] + params["b1"])
        return jax.lax.psum(h @ params["w2"], "tp") + params["b2"]

    out = jax.jit(lambda p, x: pipeline_apply(
        tp_stage, p, x, mesh=mesh, n_micro=2, interleave=v,
        param_specs=specs,
    ))(stacked, x)
    ref = x
    for p in per:
        ref = _mlp_stage(p, ref)
    assert jnp.max(jnp.abs(out - ref)) < 1e-4


def test_pipeline_interleaved_rejects_excess_microbatches(devices8):
    mesh = make_mesh(pp=2, dp=4, devices=devices8)
    stacked = stack_stage_params(_stage_params(jax.random.key(0), 4, 4, 8))
    with pytest.raises(ValueError, match="n_micro <= pp"):
        pipeline_apply(
            _mlp_stage, stacked, jnp.zeros((8, 4)), mesh=mesh,
            n_micro=4, interleave=2,
        )


def test_pipeline_rejects_bad_stage_axis(devices8):
    mesh = make_mesh(pp=2, dp=4, devices=devices8)
    bad = {"w": jnp.zeros((3, 4, 4))}  # leading axis 3 != pp 2
    with pytest.raises(ValueError, match="leading axis"):
        pipeline_apply(lambda p, x: x, bad, jnp.zeros((8, 4)), mesh=mesh, n_micro=2)


# -- checkpoint/resume --------------------------------------------------------


def test_checkpoint_save_restore_roundtrip(tmp_path, devices8):
    from kubeflow_tpu.models import create_model
    from kubeflow_tpu.parallel.sharding import llama_rules
    from kubeflow_tpu.parallel.train import shard_train_state
    from kubeflow_tpu.train import CheckpointManager, create_train_state

    mesh = make_mesh(fsdp=4, tp=2, devices=devices8)
    model = create_model("llama_debug")
    tokens = jnp.ones((2, 16), jnp.int32)
    state = create_train_state(jax.random.key(0), model, tokens, optax.adamw(1e-3))
    state = shard_train_state(state, mesh, llama_rules())

    with CheckpointManager(str(tmp_path / "ckpt"), async_save=False) as mgr:
        assert mgr.restore(state) is None  # fresh dir → start from scratch
        mgr.save(0, state)
        bumped = state.replace(
            step=state.step + 5,
            params=jax.tree.map(lambda x: x + 1.0, state.params),
        )
        mgr.save(5, bumped)
        mgr.wait()
        assert mgr.latest_step() == 5

        restored = mgr.restore(state)
        assert int(restored.step) == int(bumped.step)
        for a, b in zip(jax.tree.leaves(restored.params), jax.tree.leaves(bumped.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b))
        # Restored arrays carry the template's mesh sharding.
        leaf = jax.tree.leaves(restored.params)[0]
        assert leaf.sharding.mesh.shape == mesh.shape


def test_checkpoint_retention(tmp_path, devices8):
    from kubeflow_tpu.models import create_model
    from kubeflow_tpu.train import CheckpointManager, create_train_state

    model = create_model("llama_debug")
    tokens = jnp.ones((1, 8), jnp.int32)
    state = create_train_state(jax.random.key(0), model, tokens, optax.sgd(0.1))
    with CheckpointManager(str(tmp_path / "c"), max_to_keep=2, async_save=False) as mgr:
        for s in (1, 2, 3):
            mgr.save(s, state.replace(step=jnp.asarray(s)))
        mgr.wait()
        assert mgr.latest_step() == 3
        assert len(mgr.all_steps()) <= 2


# -- data loader --------------------------------------------------------------


def test_sharded_loader_lm(devices8):
    mesh = make_mesh(dp=4, sp=2, devices=devices8)
    sharding = NamedSharding(mesh, P(("dp", "fsdp")))
    it = synthetic_lm_batches(global_batch=8, seq_len=16, vocab_size=100, steps=3)
    batches = list(ShardedLoader(it, sharding, prefetch=2))
    assert len(batches) == 3
    for b in batches:
        assert b.shape == (8, 16)
        assert b.sharding.spec == P(("dp", "fsdp"))


def test_sharded_loader_images_tuple(devices8):
    mesh = make_mesh(dp=8, devices=devices8)
    sharding = NamedSharding(mesh, P(("dp", "fsdp")))
    it = synthetic_image_batches(global_batch=8, image_size=8, num_classes=10, steps=2)
    batches = list(ShardedLoader(it, sharding, prefetch=0))
    assert len(batches) == 2
    images, labels = batches[0]
    assert images.shape == (8, 8, 8, 3) and labels.shape == (8,)


def test_sharded_loader_propagates_iterator_errors(devices8):
    mesh = make_mesh(dp=8, devices=devices8)
    sharding = NamedSharding(mesh, P(("dp", "fsdp")))

    def bad_iter():
        yield np.zeros((8, 4), np.int32)
        raise RuntimeError("corrupt shard")

    loader = ShardedLoader(bad_iter(), sharding, prefetch=2)
    with pytest.raises(RuntimeError, match="corrupt shard"):
        list(loader)


def test_sharded_loader_early_break_releases_feeder(devices8):
    import time

    mesh = make_mesh(dp=8, devices=devices8)
    sharding = NamedSharding(mesh, P(("dp", "fsdp")))
    it = synthetic_lm_batches(global_batch=8, seq_len=4, vocab_size=10, steps=None)
    loader = ShardedLoader(it, sharding, prefetch=1)
    for batch in loader:
        break  # infinite stream abandoned early
    deadline = time.monotonic() + 5.0
    while loader._thread.is_alive() and time.monotonic() < deadline:
        time.sleep(0.05)
    assert not loader._thread.is_alive()  # feeder released, not blocked on put


def test_sharded_loader_reiteration_after_break(devices8):
    mesh = make_mesh(dp=8, devices=devices8)
    sharding = NamedSharding(mesh, P(("dp", "fsdp")))

    def counted():
        i = 0
        while True:
            yield np.full((8, 2), i, np.int32)
            i += 1

    loader = ShardedLoader(counted(), sharding, prefetch=2)
    first = None
    for batch in loader:
        first = int(np.asarray(batch)[0, 0])
        break
    # Second iteration must resume cleanly: fresh feeder, no stale batches
    # from the abandoned round, monotonically later data.
    second = None
    for batch in loader:
        second = int(np.asarray(batch)[0, 0])
        break
    assert first == 0 and second > first


def test_host_batch_size_requires_divisibility(monkeypatch):
    from kubeflow_tpu.data import loader

    monkeypatch.setattr(jax, "process_count", lambda: 4)
    with pytest.raises(ValueError, match="divisible"):
        loader._host_batch_size(6)
    assert loader._host_batch_size(8) == 2


def test_ulysses_flash_local_kernel_matches(devices8):
    # Force the flash local kernel via attn_fn and compare against the
    # default XLA path (and the unsharded reference).
    from kubeflow_tpu.ops.attention import xla_attention
    from kubeflow_tpu.ops.pallas import flash_attention as fa
    from kubeflow_tpu.parallel import make_mesh
    from kubeflow_tpu.parallel.ulysses import ulysses_attention

    mesh = make_mesh(dp=2, sp=4, devices=jax.devices()[:8])
    k0 = jax.random.key(0)
    q = jax.random.normal(jax.random.fold_in(k0, 1), (2, 512, 4, 64))
    k = jax.random.normal(jax.random.fold_in(k0, 2), (2, 512, 4, 64))
    v = jax.random.normal(jax.random.fold_in(k0, 3), (2, 512, 4, 64))

    def flash_fn(q, k, v, *, causal, scale):
        return fa.flash_attention(q, k, v, causal=causal, softmax_scale=scale)

    out_flash = jax.jit(lambda q, k, v: ulysses_attention(
        q, k, v, mesh=mesh, causal=True, attn_fn=flash_fn))(q, k, v)
    out_default = jax.jit(lambda q, k, v: ulysses_attention(
        q, k, v, mesh=mesh, causal=True))(q, k, v)
    ref = xla_attention(q, k, v, causal=True)
    assert jnp.max(jnp.abs(out_flash - out_default)) < 2e-5
    assert jnp.max(jnp.abs(out_flash - ref)) < 2e-5


@pytest.mark.slow
def test_four_slice_hybrid_dryrun_16_devices():
    """The driver-contract 4-slice arm, builder-side: a fresh process with
    16 virtual CPU devices must execute the full dryrun including the
    slices=4 hybrid DCNxICI train step (VERDICT r4 item 7 — every executed
    hybrid mesh had been 2-slice)."""
    import os
    import subprocess
    import sys

    env = dict(os.environ,
               XLA_FLAGS="--xla_force_host_platform_device_count=16")
    out = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(16)"],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "slices=4 hybrid=dcn(dp=4)xici(" in out.stdout, out.stdout
