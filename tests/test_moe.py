"""MoE layer + expert parallelism tests (virtual 8-device CPU mesh)."""
import jax
import jax.numpy as jnp
import optax
import pytest
from jax.sharding import PartitionSpec as P

from kubeflow_tpu.models import create_model
from kubeflow_tpu.models.moe import MoeMlp
from kubeflow_tpu.parallel import llama_rules, make_mesh, make_sharded_train_step
from kubeflow_tpu.parallel.context import global_mesh
from kubeflow_tpu.parallel.sharding import tree_specs
from kubeflow_tpu.parallel.train import shard_train_state
from kubeflow_tpu.train import create_train_state, make_lm_train_step


def test_moe_mlp_forward_shape_and_finite():
    layer = MoeMlp(n_experts=4, hidden_dim=32, top_k=2, dtype=jnp.float32)
    x = jax.random.normal(jax.random.key(0), (2, 16, 8))
    params = layer.init(jax.random.key(1), x)["params"]
    y = layer.apply({"params": params}, x)
    assert y.shape == x.shape
    assert jnp.all(jnp.isfinite(y))


@pytest.mark.slow
def test_moe_dispatch_respects_capacity():
    # With capacity_factor tiny, most tokens overflow and the layer output
    # must shrink toward zero (dropped tokens contribute nothing).
    x = jax.random.normal(jax.random.key(0), (2, 32, 8))
    big = MoeMlp(n_experts=2, hidden_dim=16, top_k=1, capacity_factor=8.0,
                 dtype=jnp.float32)
    params = big.init(jax.random.key(1), x)["params"]
    y_full = big.apply({"params": params}, x)
    tiny = MoeMlp(n_experts=2, hidden_dim=16, top_k=1, capacity_factor=1 / 32,
                  dtype=jnp.float32)
    y_dropped = tiny.apply({"params": params}, x)
    # capacity = 1 token/expert/row → at most 2 of 32 tokens live per row.
    live = jnp.sum(jnp.any(y_dropped != 0, axis=-1))
    assert live <= 2 * 2
    assert jnp.sum(jnp.any(y_full != 0, axis=-1)) > live


def test_moe_aux_loss_sowed():
    layer = MoeMlp(n_experts=4, hidden_dim=16, dtype=jnp.float32)
    x = jax.random.normal(jax.random.key(0), (1, 16, 8))
    params = layer.init(jax.random.key(1), x)["params"]
    _, cols = layer.apply({"params": params}, x, mutable=["losses"])
    (aux,) = jax.tree.leaves(cols["losses"])
    # Perfectly balanced routing gives aux == 1.0; anything routed is >= 1.
    assert float(aux) >= 1.0 - 1e-5


def test_mixtral_param_specs():
    model = create_model("mixtral_debug")
    tokens = jnp.ones((2, 16), jnp.int32)
    params = model.init(jax.random.key(0), tokens)["params"]
    specs = tree_specs(params, llama_rules())
    mlp = specs["layer_0"]["mlp"]
    assert mlp["w_gate"] == P("ep", "fsdp", "tp")
    assert mlp["w_down"] == P("ep", "tp", "fsdp")
    assert mlp["router"]["kernel"] == P("fsdp", None)


@pytest.mark.slow
def test_expert_parallel_step_matches_single_device(devices8):
    """Loss after one ep=4 sharded step equals the single-device step."""
    model = create_model("mixtral_debug")
    tokens = jax.random.randint(jax.random.key(0), (8, 64), 0, 256)
    tx = optax.sgd(1e-2)
    step_fn = make_lm_train_step(aux_loss_weight=0.01)

    state = create_train_state(jax.random.key(1), model, tokens, tx)
    _, ref_metrics = jax.jit(step_fn)(state, tokens)

    mesh = make_mesh(fsdp=2, ep=4, devices=devices8)
    with global_mesh(mesh):
        state2 = create_train_state(jax.random.key(1), model, tokens, tx)
        state2 = shard_train_state(state2, mesh, llama_rules())
        step, data_sh = make_sharded_train_step(
            step_fn, state2, mesh, llama_rules()
        )
        batch = jax.device_put(tokens, data_sh)
        _, metrics = step(state2, batch)
        assert abs(float(metrics["loss"]) - float(ref_metrics["loss"])) < 1e-4
        assert float(metrics["moe_aux_loss"]) >= 1.0 - 1e-5


@pytest.mark.slow
def test_moe_aux_loss_with_scan_layers(devices8):
    # Under scan_layers the sowed per-layer aux losses arrive as ONE
    # stacked (n_layers,) leaf; the lm step must still produce a scalar
    # loss (regression: value_and_grad raised on a vector loss).
    import dataclasses

    import optax

    from kubeflow_tpu.models.llama import CONFIGS, Llama
    from kubeflow_tpu.train import create_train_state, make_lm_train_step

    cfg = dataclasses.replace(
        CONFIGS["mixtral_debug"], max_seq_len=32, scan_layers=True
    )
    model = Llama(cfg)
    tokens = jnp.ones((2, 16), jnp.int32)
    state = create_train_state(
        jax.random.key(0), model, tokens, optax.adamw(1e-3)
    )
    step = jax.jit(make_lm_train_step(aux_loss_weight=0.01))
    state, metrics = step(state, tokens)
    assert metrics["loss"].shape == ()
    assert metrics["moe_aux_loss"].shape == ()
    assert jnp.isfinite(metrics["moe_aux_loss"])
