"""Weight-only int8 quantization for serving (models/quantize.py)."""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models.quantize import (
    QTensor,
    dequantize_params,
    quantize_array,
    quantize_params,
    quantized_bytes,
)


def tiny_llama():
    from kubeflow_tpu.models.llama import CONFIGS, Llama

    cfg = dataclasses.replace(CONFIGS["llama_debug"], max_seq_len=64)
    model = Llama(cfg)
    tokens = jnp.ones((2, 16), jnp.int32)
    params = model.init(jax.random.key(0), tokens)["params"]
    return model, params, tokens


def test_quantize_array_roundtrip_error_bounded():
    w = jax.random.normal(jax.random.key(1), (64, 32)) * 0.2
    qt = quantize_array(w)
    assert qt.q.dtype == jnp.int8
    assert qt.scale.shape == (1, 32)  # per-output-channel
    err = jnp.abs(qt.dequantize(jnp.float32) - w)
    # Symmetric int8: error bounded by scale/2 per channel.
    assert float(jnp.max(err / qt.scale)) <= 0.51


def test_quantize_params_selects_matmul_weights_only():
    _, params, _ = tiny_llama()
    qparams = quantize_params(params)
    leaves = jax.tree.leaves(qparams, is_leaf=lambda x: isinstance(x, QTensor))
    kinds = {type(l).__name__ for l in leaves}
    assert "QTensor" in kinds
    # Norm scales stay full-precision.
    flat = jax.tree_util.tree_flatten_with_path(
        qparams, is_leaf=lambda x: isinstance(x, QTensor)
    )[0]
    for path, leaf in flat:
        name = ".".join(str(getattr(p, "key", "")) for p in path)
        if "norm" in name:
            assert not isinstance(leaf, QTensor), name
        if name.endswith("kernel") or name.endswith("embedding"):
            assert isinstance(leaf, QTensor), name


def test_t5_rel_embedding_not_quantized():
    """`rel_embedding` (T5's attention-bias table) must stay full precision
    — only exact `kernel`/`embedding` path segments quantize."""
    from kubeflow_tpu.models import create_model

    model = create_model("t5_debug")
    enc = jnp.ones((1, 8), jnp.int32)
    dec = jnp.ones((1, 4), jnp.int32)
    params = model.init(jax.random.key(0), enc, dec)["params"]
    qparams = quantize_params(params)
    flat = jax.tree_util.tree_flatten_with_path(
        qparams, is_leaf=lambda x: isinstance(x, QTensor)
    )[0]
    rel = [(".".join(str(getattr(p, "key", "")) for p in path), leaf)
           for path, leaf in flat if "rel_embedding" in
           ".".join(str(getattr(p, "key", "")) for p in path)]
    assert rel, "t5_debug should have a rel_embedding leaf"
    for name, leaf in rel:
        assert not isinstance(leaf, QTensor), name


def test_quantized_forward_close_to_full_precision():
    model, params, tokens = tiny_llama()
    full = model.apply({"params": params}, tokens)
    deq = dequantize_params(quantize_params(params), jnp.float32)
    quant = model.apply({"params": deq}, tokens)
    # Per-channel int8 on a tiny random model: logits track closely.
    denom = float(jnp.std(full))
    assert float(jnp.max(jnp.abs(full - quant))) / denom < 0.15


def test_generate_accepts_quantized_params():
    from kubeflow_tpu.models.generate import generate

    model, params, _ = tiny_llama()
    prompt = jnp.array([[3, 5, 7, 9]], jnp.int32)
    out_full = generate(model, params, prompt, max_new_tokens=8)
    out_q = generate(model, quantize_params(params), prompt, max_new_tokens=8)
    assert out_q.shape == out_full.shape == (1, 8)
    assert out_q.dtype == out_full.dtype


def test_quantized_bytes_halved():
    _, params, _ = tiny_llama()
    orig = sum(l.size * l.dtype.itemsize for l in jax.tree.leaves(params))
    qbytes = quantized_bytes(quantize_params(params))
    # f32 kernels -> int8 (+small scales): well under half.
    assert qbytes < orig * 0.5


def test_serve_with_int8_quantization():
    from werkzeug.test import Client

    from kubeflow_tpu.models.serve import create_app, load_service

    svc = load_service("llama_debug", max_seq_len=64, quantize="int8")
    client = Client(create_app(svc, model_name="llama_debug"))
    resp = client.post("/v1/generate", json={
        "tokens": [[3, 5, 7]], "max_new_tokens": 4,
    })
    assert resp.status_code == 200, resp.get_data(as_text=True)
    out = resp.get_json()
    assert len(out["tokens"]) == 1 and len(out["tokens"][0]) == 4


def test_serve_rejects_unknown_quantization():
    from kubeflow_tpu.models.serve import load_service

    with pytest.raises(ValueError, match="unsupported quantization"):
        load_service("llama_debug", max_seq_len=64, quantize="int4")


def test_llama_1b4_config_is_ab_scale():
    """The int8 A/B config (BASELINE.md round 3): ~1.36B params, so the
    bf16 arm (2.7 GB) and int8 arm (1.4 GB) both fit one 16 GB chip."""
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models.llama import CONFIGS, Llama

    cfg = CONFIGS["llama_1b4"]
    model = Llama(cfg)
    shapes = jax.eval_shape(
        lambda: model.init(jax.random.key(0), jnp.ones((1, 8), jnp.int32))
    )["params"]
    n = sum(int(jnp.prod(jnp.array(x.shape))) for x in jax.tree.leaves(shapes))
    assert 1.2e9 < n < 1.6e9, n
    assert cfg.head_dim == 128  # flash-kernel-ready
