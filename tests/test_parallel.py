import jax
import jax.numpy as jnp
import optax
import pytest
from jax.sharding import PartitionSpec as P

# Compute-side modules need the accelerator-era jax API (jax.shard_map et
# al.); importorskip keeps COLLECTION clean on platform-only environments
# instead of erroring the whole tier-1 run (BENCH/ISSUE 5 satellite).
pytest.importorskip(
    "kubeflow_tpu.parallel.ring",
    reason="compute-side accelerator env required (jax.shard_map)",
    exc_type=ImportError)

from kubeflow_tpu.models import create_model
from kubeflow_tpu.ops.attention import xla_attention
from kubeflow_tpu.parallel import (
    batch_sharding,
    llama_rules,
    make_mesh,
    make_sharded_train_step,
)
from kubeflow_tpu.parallel.context import global_mesh
from kubeflow_tpu.parallel.mesh import MeshConfig
from kubeflow_tpu.parallel.ring import ring_attention
from kubeflow_tpu.parallel.sharding import tree_specs
from kubeflow_tpu.parallel.train import shard_train_state
from kubeflow_tpu.train import create_train_state, make_lm_train_step


def test_make_mesh_shapes(devices8):
    mesh = make_mesh(dp=2, fsdp=2, tp=2, devices=devices8)
    assert mesh.devices.shape == (1, 2, 2, 1, 2, 1)
    mesh = make_mesh(fsdp=-1, tp=2, devices=devices8)
    assert mesh.shape["fsdp"] == 4


def test_make_mesh_rejects_bad_sizes(devices8):
    with pytest.raises(ValueError):
        make_mesh(dp=3, devices=devices8)


def test_hybrid_mesh_slice_major_layout(devices8):
    from kubeflow_tpu.parallel.mesh import make_hybrid_mesh

    mesh = make_hybrid_mesh(
        ici=MeshConfig(fsdp=2, tp=2), dcn=MeshConfig(dp=2), devices=devices8
    )
    assert mesh.devices.shape == (1, 2, 2, 1, 2, 1)
    # dp (the DCN axis) must split the devices into contiguous slice-major
    # blocks: dp=0 gets devices 0-3, dp=1 gets 4-7 — so a dp all-reduce is
    # the only traffic crossing the slice boundary.
    dp0 = mesh.devices[0, 0].flatten()
    dp1 = mesh.devices[0, 1].flatten()
    assert {d.id for d in dp0} == {d.id for d in devices8[:4]}
    assert {d.id for d in dp1} == {d.id for d in devices8[4:]}


def test_hybrid_mesh_executes_collectives(devices8):
    from jax.sharding import NamedSharding
    from kubeflow_tpu.parallel.mesh import make_hybrid_mesh

    mesh = make_hybrid_mesh(
        ici=MeshConfig(fsdp=4), dcn=MeshConfig(dp=2), devices=devices8
    )
    x = jnp.arange(16.0).reshape(8, 2)
    sharding = NamedSharding(mesh, P(("dp", "fsdp")))

    @jax.jit
    def total(x):
        return jnp.sum(x)

    out = total(jax.device_put(x, sharding))
    assert float(out) == float(jnp.sum(x))


def test_hybrid_mesh_rejects_wrong_count(devices8):
    from kubeflow_tpu.parallel.mesh import make_hybrid_mesh

    with pytest.raises(ValueError):
        make_hybrid_mesh(
            ici=MeshConfig(fsdp=3), dcn=MeshConfig(dp=2), devices=devices8
        )


def test_dist_slice_identity(monkeypatch):
    from kubeflow_tpu.parallel import dist

    monkeypatch.setenv("TPU_WORKER_ID", "1")
    monkeypatch.setenv("TPU_HOSTS_PER_SLICE", "2")
    monkeypatch.setenv("MEGASCALE_NUM_SLICES", "4")
    monkeypatch.setenv("MEGASCALE_SLICE_ID", "2")
    assert dist.num_slices() == 4
    assert dist.slice_id() == 2
    monkeypatch.delenv("MEGASCALE_SLICE_ID")
    assert dist.slice_id() == 0


def test_dist_initialize_multislice_process_grid(monkeypatch):
    """initialize_from_env folds slice id into the global process id."""
    from kubeflow_tpu.parallel import dist

    calls = {}

    def fake_init(**kw):
        calls.update(kw)

    monkeypatch.setattr(jax.distributed, "initialize", fake_init)
    monkeypatch.setenv("TPU_WORKER_ID", "1")
    monkeypatch.setenv(
        "TPU_WORKER_HOSTNAMES", "nb-s1-0.nb-workers,nb-s1-1.nb-workers"
    )
    monkeypatch.setenv("TPU_HOSTS_PER_SLICE", "2")
    monkeypatch.setenv("MEGASCALE_NUM_SLICES", "2")
    monkeypatch.setenv("MEGASCALE_SLICE_ID", "1")
    monkeypatch.setenv("MEGASCALE_COORDINATOR_ADDRESS", "nb-0.nb-workers")
    assert dist.initialize_from_env() is True
    assert calls["num_processes"] == 4
    assert calls["process_id"] == 3  # slice 1, worker 1
    assert calls["coordinator_address"].startswith("nb-0.nb-workers:")
    # Multislice without the global coordinator address must fail fast, not
    # hang every slice at its own local barrier.
    monkeypatch.delenv("MEGASCALE_COORDINATOR_ADDRESS")
    with pytest.raises(RuntimeError, match="MEGASCALE_COORDINATOR_ADDRESS"):
        dist.initialize_from_env()


def test_dist_process_grid_pure():
    """process_grid computes the join parameters without touching
    jax.distributed — the multislice dryrun certifies against it."""
    from kubeflow_tpu.parallel import dist

    env = {
        "worker_id": "1",
        "hostnames": "a,b",
        "num_slices": "3",
        "slice_id": "2",
        "coordinator": "coord",
    }
    addr, n, pid = dist.process_grid(env)
    assert (n, pid) == (6, 5)  # slice-major: 2*2 + 1
    assert addr.startswith("coord:")
    assert dist.process_grid({"hostnames": "", "num_slices": "",
                              "worker_id": "", "slice_id": "",
                              "coordinator": ""}) is None
    with pytest.raises(RuntimeError, match="MEGASCALE_COORDINATOR_ADDRESS"):
        dist.process_grid({"worker_id": "0", "hostnames": "a,b",
                           "num_slices": "2", "slice_id": "0",
                           "coordinator": ""})


def test_t5_and_bert_rules_cover_every_matmul_weight():
    """Every kernel/embedding leaf must get a non-replicated spec — a rule
    gap would silently serve 'tensor parallel' with replicated weights."""
    from jax.tree_util import tree_flatten_with_path

    from kubeflow_tpu.parallel import bert_rules, t5_rules
    from kubeflow_tpu.parallel.sharding import tree_specs

    cases = []
    t5 = create_model("t5_debug")
    t5p = t5.init(jax.random.key(0), jnp.ones((1, 8), jnp.int32),
                  jnp.ones((1, 4), jnp.int32))["params"]
    cases.append((t5p, t5_rules()))
    bert = create_model("bert_debug")
    bp = bert.init(jax.random.key(0), jnp.ones((1, 8), jnp.int32))["params"]
    cases.append((bp, bert_rules()))
    for params, rules in cases:
        specs = tree_specs(params, rules)
        flat_p = tree_flatten_with_path(params)[0]
        flat_s = jax.tree.leaves(
            specs, is_leaf=lambda x: isinstance(x, P)
        )
        for (path, leaf), spec in zip(flat_p, flat_s):
            name = ".".join(str(getattr(k, "key", "")) for k in path)
            if (name.endswith("kernel") or name.endswith("embedding")) \
                    and leaf.ndim >= 2 and "type_embed" not in name:
                assert any(a is not None for a in spec), (
                    f"{name} replicated: {spec}"
                )


def test_rules_place_on_mesh(devices8):
    """Specs must actually PLACE (divisibility): a rule putting a tiny
    fixed axis (e.g. BERT's 2-row type table) on tp would pass spec checks
    but fail device_put."""
    from kubeflow_tpu.parallel import bert_rules, t5_rules
    from kubeflow_tpu.parallel.sharding import shard_params

    mesh = make_mesh(tp=2, fsdp=2, dp=2, devices=devices8)
    t5 = create_model("t5_debug")
    t5p = t5.init(jax.random.key(0), jnp.ones((1, 8), jnp.int32),
                  jnp.ones((1, 4), jnp.int32))["params"]
    shard_params(t5p, mesh, t5_rules())
    bert = create_model("bert_debug")
    bp = bert.init(jax.random.key(0), jnp.ones((1, 8), jnp.int32))["params"]
    placed = shard_params(bp, mesh, bert_rules())
    leaf = jax.tree.leaves(placed)[0]
    assert len(leaf.sharding.device_set) > 1


def test_llama_param_specs():
    model = create_model("llama_debug")
    tokens = jnp.ones((2, 16), jnp.int32)
    params = model.init(jax.random.key(0), tokens)["params"]
    specs = tree_specs(params, llama_rules())
    assert specs["embed"]["embedding"] == P("tp", "fsdp")
    assert specs["layer_0"]["attn"]["q_proj"]["kernel"] == P("fsdp", "tp", None)
    assert specs["layer_0"]["attn"]["o_proj"]["kernel"] == P("tp", None, "fsdp")
    assert specs["layer_0"]["mlp"]["down_proj"]["kernel"] == P("tp", "fsdp")
    # Norm scales replicate; spec clamps to rank 1.
    assert specs["final_norm"]["scale"] == P(None)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(devices8, causal):
    mesh = make_mesh(dp=2, sp=4, devices=devices8)
    k0 = jax.random.key(0)
    q = jax.random.normal(jax.random.fold_in(k0, 1), (4, 128, 4, 32))
    k = jax.random.normal(jax.random.fold_in(k0, 2), (4, 128, 2, 32))
    v = jax.random.normal(jax.random.fold_in(k0, 3), (4, 128, 2, 32))
    out = jax.jit(
        lambda q, k, v: ring_attention(q, k, v, mesh=mesh, causal=causal)
    )(q, k, v)
    ref = xla_attention(q, k, v, causal=causal)
    assert jnp.max(jnp.abs(out - ref)) < 2e-5


def _parity_setup():
    """Model/tokens/reference-loss shared by the sharded-step parity
    tests (one construction so the debug shape can't drift apart)."""
    model = create_model(
        "llama_debug", n_heads=4, n_kv_heads=4, dim=64, vocab_size=128
    )
    tokens = jax.random.randint(jax.random.key(1), (4, 32), 0, 128)
    tx = optax.adamw(1e-3)
    state = create_train_state(jax.random.key(0), model, tokens, tx)
    _, ref_metrics = jax.jit(make_lm_train_step())(state, tokens)
    return state, tokens, tx, ref_metrics


@pytest.mark.slow
def test_sharded_train_step_matches_single_device(devices8):
    """The same step on a dp/fsdp/tp mesh must produce the same loss as on
    one device — sharding is an implementation detail, not math."""
    state, tokens, tx, ref_metrics = _parity_setup()
    model = state.apply_fn.__self__
    step = make_lm_train_step()

    mesh = make_mesh(MeshConfig(dp=2, fsdp=2, tp=2), devices=devices8)
    rules = llama_rules()
    sharded = shard_train_state(
        create_train_state(jax.random.key(0), model, tokens, tx), mesh, rules
    )
    sstep, data_sh = make_sharded_train_step(step, sharded, mesh, rules)
    sharded, metrics = sstep(sharded, jax.device_put(tokens, data_sh))
    assert abs(float(metrics["loss"]) - float(ref_metrics["loss"])) < 1e-4


@pytest.mark.slow
def test_graft_entry_dryrun(devices8):
    import __graft_entry__ as g

    g.dryrun_multichip(8)


def test_spmd_full_remat_gate_trips():
    """The dryrun's stderr gate must fail when the partitioner reports an
    involuntary full rematerialization (and pass when it doesn't)."""
    import os

    import __graft_entry__ as g

    with g._fail_on_spmd_full_remat():
        os.write(2, b"benign compiler chatter\n")
    with pytest.raises(AssertionError, match="full rematerialization"):
        with g._fail_on_spmd_full_remat():
            os.write(
                2,
                b"W0000 spmd_partitioner.cc:652 [SPMD] Involuntary full "
                b"rematerialization. ...\n",
            )


@pytest.mark.slow
def test_graft_entry_forward():
    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.eval_shape(fn, *args)
    assert out.shape[-1] == 32000


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.slow
def test_ring_attention_flash_blocks_match(devices8, causal):
    # block_impl="flash" folds visiting blocks through the Pallas kernel
    # (with-lse variant); outputs and grads must match the einsum path.
    mesh = make_mesh(dp=2, sp=4, devices=devices8)
    k0 = jax.random.key(0)
    q = jax.random.normal(jax.random.fold_in(k0, 1), (2, 512, 4, 64))
    k = jax.random.normal(jax.random.fold_in(k0, 2), (2, 512, 2, 64))
    v = jax.random.normal(jax.random.fold_in(k0, 3), (2, 512, 2, 64))

    def run(block_impl):
        return jax.jit(
            lambda q, k, v: ring_attention(
                q, k, v, mesh=mesh, causal=causal, block_impl=block_impl
            )
        )(q, k, v)

    out_flash = run("flash")
    out_einsum = run("einsum")
    ref = xla_attention(q, k, v, causal=causal)
    assert jnp.max(jnp.abs(out_flash - out_einsum)) < 2e-5
    assert jnp.max(jnp.abs(out_flash - ref)) < 2e-5

    def loss(block_impl):
        def fn(q, k, v):
            out = ring_attention(
                q, k, v, mesh=mesh, causal=causal, block_impl=block_impl
            )
            return (out.astype(jnp.float32) ** 2).sum()
        return fn

    gf = jax.jit(jax.grad(loss("flash"), argnums=(0, 1, 2)))(q, k, v)
    ge = jax.jit(jax.grad(loss("einsum"), argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(gf, ge):
        scale = jnp.max(jnp.abs(b)) + 1e-9
        assert jnp.max(jnp.abs(a - b)) / scale < 1e-4


def test_ring_attention_flash_rejects_unsupported(devices8):
    mesh = make_mesh(dp=2, sp=4, devices=devices8)
    q = jnp.ones((2, 128, 4, 32))  # head_dim 32: below the kernel's gate
    with pytest.raises(ValueError, match="unsupported"):
        ring_attention(q, q, q, mesh=mesh, block_impl="flash")


def test_hybrid_mesh_real_multislice_branch_keeps_ici_inside_slices():
    """On real multislice hardware (devices carry slice_index) the hybrid
    mesh goes through mesh_utils.create_hybrid_device_mesh; every ICI axis
    must stay inside one slice and the DCN axis must stride across slices.
    Exercised hermetically with mock sliced devices (the virtual-CPU path
    can never reach this branch)."""
    from dataclasses import dataclass

    from kubeflow_tpu.parallel.mesh import MeshConfig, make_hybrid_mesh

    @dataclass(frozen=True, eq=True)
    class FakeDev:
        id: int
        slice_index: int
        process_index: int = 0
        platform: str = "tpu"
        device_kind: str = "fake-tpu"

        @property
        def coords(self):
            local = self.id % 4
            return (local % 2, local // 2, 0)

        @property
        def core_on_chip(self):
            return 0

    devs = [FakeDev(id=i, slice_index=i // 4) for i in range(8)]
    mesh = make_hybrid_mesh(
        MeshConfig(fsdp=2, tp=2), MeshConfig(dp=2), devices=devs
    )
    assert dict(zip(mesh.axis_names, mesh.devices.shape)) == {
        "pp": 1, "dp": 2, "fsdp": 2, "ep": 1, "tp": 2, "sp": 1,
    }
    for dp in (0, 1):
        slices = {d.slice_index
                  for d in mesh.devices[:, dp].flatten().tolist()}
        assert len(slices) == 1, (dp, slices)  # ICI axes inside ONE slice
    assert (
        {d.slice_index for d in mesh.devices[:, 0].flatten().tolist()}
        != {d.slice_index for d in mesh.devices[:, 1].flatten().tolist()}
    )  # the DCN axis is what crosses slices


@pytest.mark.slow
def test_sharded_step_with_ce_chunk_matches_single_device(devices8):
    """Chunked CE (ce_chunk, the long-context memory lever) under SPMD —
    including a sequence-parallel mesh with the sequence axis ACTUALLY
    sharded (shard_sequence=True), which the CE scan's [B, S, D] →
    [n, B, C, D] reshape crosses — must stay math-identical to the
    unsharded unchunked step."""
    state, tokens, tx, ref_metrics = _parity_setup()
    step = make_lm_train_step(ce_chunk=8)
    rules = llama_rules()
    for cfg_mesh, shard_seq in ((MeshConfig(dp=2, fsdp=2, tp=2), False),
                                (MeshConfig(dp=2, sp=4), True)):
        mesh = make_mesh(cfg_mesh, devices=devices8)
        model = state.apply_fn.__self__
        sharded = shard_train_state(
            create_train_state(jax.random.key(0), model, tokens, tx),
            mesh, rules,
        )
        sstep, data_sh = make_sharded_train_step(
            step, sharded, mesh, rules, shard_sequence=shard_seq)
        _, metrics = sstep(sharded, jax.device_put(tokens, data_sh))
        assert abs(float(metrics["loss"]) - float(ref_metrics["loss"])) \
            < 1e-4, cfg_mesh
