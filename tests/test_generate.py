"""KV-cache generation: decode-with-cache must equal full re-forward."""
import dataclasses

import jax
import jax.numpy as jnp
import pytest

from kubeflow_tpu.models.generate import generate, sample_logits
from kubeflow_tpu.models.llama import CONFIGS, Llama


@pytest.fixture(scope="module")
def model_and_params():
    cfg = dataclasses.replace(CONFIGS["llama_debug"], max_seq_len=64)
    model = Llama(cfg)
    tokens = jnp.ones((1, 8), jnp.int32)
    params = model.init(jax.random.key(0), tokens)["params"]
    return model, params


def naive_greedy(model, params, prompt, n):
    """Re-forward the whole sequence each step, no cache — the oracle."""
    seq = prompt
    out = []
    for _ in range(n):
        logits = model.apply({"params": params}, seq)
        nxt = jnp.argmax(logits[:, -1], axis=-1)
        out.append(nxt)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    return jnp.stack(out, axis=1)


@pytest.mark.slow
def test_greedy_cache_matches_full_forward(model_and_params):
    model, params = model_and_params
    prompt = jnp.array([[5, 9, 2, 7, 11, 3]], jnp.int32)
    want = naive_greedy(model, params, prompt, 8)
    got = generate(model, params, prompt, max_new_tokens=8, temperature=0.0)
    assert got.shape == (1, 8)
    assert (got == want).all(), (got, want)


def test_right_padded_prompt_matches_unpadded(model_and_params):
    model, params = model_and_params
    short = jnp.array([[5, 9, 2]], jnp.int32)
    want = generate(model, params, short, max_new_tokens=6, temperature=0.0)
    padded = jnp.array([[5, 9, 2, 0, 0, 0, 0, 0]], jnp.int32)
    mask = jnp.array([[1, 1, 1, 0, 0, 0, 0, 0]], bool)
    got = generate(model, params, padded, prompt_mask=mask,
                   max_new_tokens=6, temperature=0.0)
    assert (got == want).all(), (got, want)


def test_batch_with_mixed_lengths(model_and_params):
    model, params = model_and_params
    # Batched mixed-length rows must reproduce their per-row outputs.
    rows = [jnp.array([[5, 9, 2]], jnp.int32),
            jnp.array([[7, 1, 4, 8, 2]], jnp.int32)]
    singles = [generate(model, params, r, max_new_tokens=4, temperature=0.0)
               for r in rows]
    prompt = jnp.array([[5, 9, 2, 0, 0], [7, 1, 4, 8, 2]], jnp.int32)
    mask = jnp.array([[1, 1, 1, 0, 0], [1, 1, 1, 1, 1]], bool)
    got = generate(model, params, prompt, prompt_mask=mask,
                   max_new_tokens=4, temperature=0.0)
    assert (got[0] == singles[0][0]).all(), (got[0], singles[0])
    assert (got[1] == singles[1][0]).all(), (got[1], singles[1])


def test_eos_pads_after_stop(model_and_params):
    model, params = model_and_params
    prompt = jnp.array([[5, 9, 2, 7]], jnp.int32)
    ref = generate(model, params, prompt, max_new_tokens=6, temperature=0.0)
    eos = int(ref[0, 2])  # a token known to occur in the greedy stream
    stop = int(jnp.argmax(ref[0] == eos))  # its first occurrence
    got = generate(model, params, prompt, max_new_tokens=6, temperature=0.0,
                   eos_token=eos)
    assert (got[0, : stop + 1] == ref[0, : stop + 1]).all(), (got, ref)
    assert (got[0, stop + 1:] == eos).all(), (got, ref)


def test_top_k_sampling_stays_in_top_k():
    logits = jnp.array([[0.0, 5.0, 4.0, 3.0, -1.0]])
    for seed in range(20):
        tok = sample_logits(logits, jax.random.key(seed),
                            temperature=1.0, top_k=2)
        assert int(tok[0]) in (1, 2)


def test_sampled_generation_is_reproducible(model_and_params):
    model, params = model_and_params
    prompt = jnp.array([[5, 9, 2]], jnp.int32)
    a = generate(model, params, prompt, rng=jax.random.key(7),
                 max_new_tokens=5, temperature=0.8, top_k=8)
    b = generate(model, params, prompt, rng=jax.random.key(7),
                 max_new_tokens=5, temperature=0.8, top_k=8)
    assert (a == b).all()


@pytest.mark.slow
def test_decode_with_remat_and_moe():
    # remat and MoE variants must also trace through the decode path.
    for name in ("mixtral_debug",):
        cfg = dataclasses.replace(CONFIGS[name], max_seq_len=32)
        model = Llama(cfg)
        tokens = jnp.ones((2, 4), jnp.int32)
        params = model.init(jax.random.key(0), tokens)["params"]
        out = generate(model, params, tokens, max_new_tokens=3,
                       temperature=0.0)
        assert out.shape == (2, 3)
    cfg = dataclasses.replace(CONFIGS["llama_debug"], max_seq_len=32,
                              remat=True)
    model = Llama(cfg)
    tokens = jnp.ones((1, 4), jnp.int32)
    params = model.init(jax.random.key(0), tokens)["params"]
    out = generate(model, params, tokens, max_new_tokens=3, temperature=0.0)
    assert out.shape == (1, 3)


def test_generate_under_dp_mesh(model_and_params):
    # SPMD decode: batch sharded over dp, params replicated — GSPMD must
    # partition the whole prefill+scan and agree with the unsharded run.
    from jax.sharding import NamedSharding, PartitionSpec as P

    from kubeflow_tpu.parallel import make_mesh

    model, params = model_and_params
    prompt = jnp.tile(jnp.array([[5, 9, 2, 7]], jnp.int32), (4, 1))
    want = generate(model, params, prompt, max_new_tokens=4, temperature=0.0)

    mesh = make_mesh(dp=2, devices=jax.devices()[:2])
    sharded_prompt = jax.device_put(prompt, NamedSharding(mesh, P("dp", None)))
    sharded_params = jax.device_put(
        params, NamedSharding(mesh, P())
    )
    got = generate(model, sharded_params, sharded_prompt,
                   max_new_tokens=4, temperature=0.0)
    assert (jax.device_get(got) == jax.device_get(want)).all()


def test_overflow_raises(model_and_params):
    model, params = model_and_params  # max_seq_len = 64
    prompt = jnp.ones((1, 40), jnp.int32)
    with pytest.raises(ValueError, match="exceeds max_seq_len"):
        generate(model, params, prompt, max_new_tokens=32, temperature=0.0)


def test_moe_padding_invariance_generous_capacity():
    # With pads excluded from routing and capacity generous enough that no
    # real token drops, padded and unpadded MoE generation must agree.
    cfg = dataclasses.replace(
        CONFIGS["mixtral_debug"], max_seq_len=32, capacity_factor=8.0
    )
    model = Llama(cfg)
    short = jnp.array([[5, 9, 2]], jnp.int32)
    params = model.init(jax.random.key(0), short)["params"]
    want = generate(model, params, short, max_new_tokens=4, temperature=0.0)
    padded = jnp.array([[5, 9, 2, 0, 0, 0]], jnp.int32)
    mask = jnp.array([[1, 1, 1, 0, 0, 0]], bool)
    got = generate(model, params, padded, prompt_mask=mask,
                   max_new_tokens=4, temperature=0.0)
    assert (got == want).all(), (got, want)


def test_decode_rejects_segment_ids(model_and_params):
    model, params = model_and_params
    tokens = jnp.ones((1, 4), jnp.int32)
    with pytest.raises(ValueError, match="packed sequences"):
        model.apply({"params": params}, tokens, decode=True,
                    segment_ids=jnp.zeros((1, 4), jnp.int32),
                    mutable=["cache"])


def test_beam_search_beam1_equals_greedy(model_and_params):
    """Decoder-only beams=1 == greedy generate — pins the prefill seeding,
    cache tiling, per-step positions, and backtracking."""
    from kubeflow_tpu.models.generate import beam_search

    model, params = model_and_params
    prompt = jnp.array([[3, 7, 11, 2], [9, 4, 1, 8]], jnp.int32)
    greedy = generate(model, params, prompt, max_new_tokens=8)
    beam1 = beam_search(model, params, prompt, max_new_tokens=8, beams=1)
    assert (greedy == beam1).all(), (greedy, beam1)


def test_beam_search_respects_prompt_padding(model_and_params):
    from kubeflow_tpu.models.generate import beam_search

    model, params = model_and_params
    short = jnp.array([[3, 7, 11]], jnp.int32)
    padded = jnp.array([[3, 7, 11, 0, 0]], jnp.int32)
    mask = jnp.array([[1, 1, 1, 0, 0]], bool)
    a = beam_search(model, params, short, max_new_tokens=6, beams=3)
    b = beam_search(model, params, padded, prompt_mask=mask,
                    max_new_tokens=6, beams=3)
    assert (a == b).all()


def test_beam_search_improves_sequence_score(model_and_params):
    from kubeflow_tpu.models.generate import beam_search

    model, params = model_and_params
    prompt = jnp.array([[5, 2, 9, 13]], jnp.int32)

    def score(seq):
        full = jnp.concatenate([prompt, seq], axis=1)
        logits = model.apply({"params": params}, full)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        plen = prompt.shape[1]
        tok_lp = jnp.take_along_axis(
            logp[:, plen - 1:-1], seq[..., None], axis=-1
        )[..., 0]
        return float(tok_lp.sum())

    b1 = beam_search(model, params, prompt, max_new_tokens=6, beams=1,
                     length_penalty=0.0)
    b4 = beam_search(model, params, prompt, max_new_tokens=6, beams=4,
                     length_penalty=0.0)
    assert score(b4) >= score(b1) - 1e-4
