"""Test harness: force JAX onto a virtual 8-device CPU mesh.

The image registers an ``axon`` TPU backend via sitecustomize
(JAX_PLATFORMS=axon); tests must not depend on the tunnelled chip, and the
multi-chip sharding tests need 8 devices.  This file runs before any test
module imports jax, so the platform/device-count knobs still take effect.
"""
import os

# Must be set before the XLA CPU client initializes.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

import faulthandler  # noqa: E402
import sys  # noqa: E402
import threading  # noqa: E402

import pytest  # noqa: E402

# Per-test wall-clock cap (pytest-timeout is not in the image).  A watchdog
# thread — not SIGALRM — because a hung test is usually stuck inside an XLA
# compile/execute C call, where Python signal handlers don't run.  On
# expiry: dump all thread stacks, then hard-exit so CI fails in bounded
# time instead of hanging (the pytest-timeout "thread" method semantics).
DEFAULT_TEST_TIMEOUT_S = float(os.environ.get("KFT_TEST_TIMEOUT_S", "600"))


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long mesh/pipeline/train tests "
        "(quick tier: -m 'not slow')",
    )
    config.addinivalue_line(
        "markers", "timeout(seconds): override the per-test wall-clock cap",
    )


@pytest.hookimpl(wrapper=True)
def pytest_runtest_protocol(item, nextitem):
    marker = item.get_closest_marker("timeout")
    seconds = float(marker.args[0]) if marker else DEFAULT_TEST_TIMEOUT_S
    done = threading.Event()

    def watchdog():
        if not done.wait(seconds):
            # Un-redirect fd 2 so the dump survives os._exit (pytest's
            # fd-level capture would otherwise swallow it).
            capman = item.config.pluginmanager.getplugin("capturemanager")
            try:
                if capman is not None:
                    capman.suspend_global_capture(in_=True)
            except Exception:
                pass
            sys.stderr.write(
                f"\n\n=== TIMEOUT: {item.nodeid} exceeded {seconds:.0f}s; "
                "thread stacks follow ===\n"
            )
            faulthandler.dump_traceback()
            sys.stderr.flush()
            os._exit(70)

    t = threading.Thread(target=watchdog, daemon=True, name="test-watchdog")
    t.start()
    try:
        return (yield)
    finally:
        done.set()


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual CPU devices, got {devs}"
    return devs[:8]
