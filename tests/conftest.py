"""Test harness: force JAX onto a virtual 8-device CPU mesh.

The image registers an ``axon`` TPU backend via sitecustomize
(JAX_PLATFORMS=axon); tests must not depend on the tunnelled chip, and the
multi-chip sharding tests need 8 devices.  This file runs before any test
module imports jax, so the platform/device-count knobs still take effect.
"""
import os

# Must be set before the XLA CPU client initializes.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual CPU devices, got {devs}"
    return devs[:8]
