import jax
import jax.numpy as jnp
import optax
import pytest

from kubeflow_tpu.models import create_model, list_models
from kubeflow_tpu.train import (
    create_train_state,
    make_classification_train_step,
    make_lm_train_step,
)


def test_registry_lists_all_families():
    names = list_models()
    for required in ("resnet50", "llama2_7b", "vit_b16", "bert_base"):
        assert required in names


def test_resnet_tiny_forward_and_learns():
    model = create_model("resnet_tiny")
    rng = jax.random.key(0)
    images = jax.random.normal(rng, (8, 32, 32, 3))
    labels = jnp.arange(8) % 10
    state = create_train_state(
        rng, model, images, optax.sgd(0.05, momentum=0.9),
        init_kwargs={"train": False},
    )
    step = jax.jit(make_classification_train_step(has_batch_stats=True))
    state, first = step(state, (images, labels))
    last = first
    for _ in range(10):
        state, last = step(state, (images, labels))
    assert float(last["loss"]) < float(first["loss"])


def test_llama_debug_forward_and_learns():
    model = create_model("llama_debug")
    rng = jax.random.key(0)
    tokens = jax.random.randint(rng, (4, 32), 0, 256)
    state = create_train_state(rng, model, tokens, optax.adamw(1e-2))
    logits = model.apply({"params": state.params}, tokens)
    assert logits.shape == (4, 32, 256)
    step = jax.jit(make_lm_train_step())
    state, first = step(state, tokens)
    last = first
    for _ in range(15):
        state, last = step(state, tokens)
    assert float(last["loss"]) < float(first["loss"])


def test_vit_debug_forward():
    model = create_model("vit_debug")
    rng = jax.random.key(0)
    images = jax.random.normal(rng, (2, 32, 32, 3))
    variables = model.init(rng, images, train=False)
    logits = model.apply(variables, images, train=False)
    assert logits.shape == (2, 10)


def test_bert_debug_forward_with_mask():
    model = create_model("bert_debug")
    rng = jax.random.key(0)
    tokens = jax.random.randint(rng, (2, 16), 0, 128)
    mask = jnp.ones((2, 16)).at[:, 8:].set(0)
    variables = model.init(rng, tokens, train=False)
    logits = model.apply(variables, tokens, attention_mask=mask, train=False)
    assert logits.shape == (2, 2)
    # Masked positions must not affect the output.
    tokens2 = tokens.at[:, 12].set(0)
    logits2 = model.apply(variables, tokens2, attention_mask=mask, train=False)
    assert jnp.allclose(logits, logits2, atol=1e-5)


def test_unknown_model_raises():
    with pytest.raises(KeyError):
        create_model("resnet9000")


def test_space_to_depth_transform():
    import numpy as np

    from kubeflow_tpu.models.resnet import space_to_depth

    x = jnp.arange(2 * 4 * 4 * 3).reshape(2, 4, 4, 3).astype(jnp.float32)
    y = space_to_depth(x, 2)
    assert y.shape == (2, 2, 2, 12)
    # Block (0,0) packs pixels (0,0),(0,1),(1,0),(1,1) in row-major order.
    np.testing.assert_array_equal(
        np.asarray(y[0, 0, 0]),
        np.concatenate([np.asarray(x[0, 0, 0]), np.asarray(x[0, 0, 1]),
                        np.asarray(x[0, 1, 0]), np.asarray(x[0, 1, 1])]),
    )


def test_space_to_depth_stem_trains():
    import optax

    from kubeflow_tpu.models import create_model
    from kubeflow_tpu.train import (
        create_train_state,
        make_classification_train_step,
    )

    model = create_model(
        "resnet_tiny", stem="space_to_depth", num_classes=10
    )
    rng = jax.random.key(0)
    images = jax.random.normal(rng, (4, 16, 16, 3), jnp.float32)
    labels = jnp.array([0, 1, 2, 3])
    state = create_train_state(
        rng, model, images, optax.sgd(0.05), init_kwargs={"train": False}
    )
    step = jax.jit(make_classification_train_step(has_batch_stats=True))
    losses = []
    for _ in range(5):
        state, metrics = step(state, (images, labels))
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]


# -- scan-over-layers (LlamaConfig.scan_layers) -------------------------------


def test_llama_scan_layers_matches_loop():
    import dataclasses

    import jax.tree_util as jtu

    from kubeflow_tpu.models.llama import CONFIGS, Llama

    cfg_loop = dataclasses.replace(CONFIGS["llama_debug"], max_seq_len=32)
    cfg_scan = dataclasses.replace(cfg_loop, scan_layers=True)
    loop_m, scan_m = Llama(cfg_loop), Llama(cfg_scan)
    tokens = jnp.arange(8).reshape(1, 8).astype(jnp.int32) + 1
    p_loop = loop_m.init(jax.random.key(0), tokens)["params"]
    p_scan = dict(scan_m.init(jax.random.key(0), tokens)["params"])
    # Transplant the loop params (stacked) so outputs must match exactly.
    p_scan["layers_scan"] = {"block": jtu.tree_map(
        lambda *xs: jnp.stack(xs),
        *[p_loop[f"layer_{i}"] for i in range(cfg_loop.n_layers)],
    )}
    for k in ("embed", "final_norm", "lm_head"):
        p_scan[k] = p_loop[k]
    out_loop = loop_m.apply({"params": p_loop}, tokens)
    out_scan = scan_m.apply({"params": p_scan}, tokens)
    assert jnp.max(jnp.abs(out_loop - out_scan)) < 1e-5

    # Decode (KV cache under nn.scan) agrees too.
    from kubeflow_tpu.models.generate import generate

    g1 = generate(loop_m, p_loop, tokens, max_new_tokens=4, temperature=0.0)
    g2 = generate(scan_m, p_scan, tokens, max_new_tokens=4, temperature=0.0)
    assert (g1 == g2).all()


@pytest.mark.slow
def test_llama_scan_layers_sharded_step(devices8):
    import dataclasses

    import optax

    from kubeflow_tpu.models.llama import CONFIGS, Llama
    from kubeflow_tpu.parallel import llama_rules, make_mesh
    from kubeflow_tpu.parallel.train import (
        make_sharded_train_step,
        shard_train_state,
    )
    from kubeflow_tpu.train import create_train_state, make_lm_train_step

    cfg = dataclasses.replace(
        CONFIGS["llama_debug"], max_seq_len=32, scan_layers=True
    )
    model = Llama(cfg)
    tokens = jnp.ones((8, 32), jnp.int32)
    state = create_train_state(
        jax.random.key(0), model, tokens, optax.adamw(1e-3)
    )
    mesh = make_mesh(dp=2, fsdp=2, tp=2)
    state = shard_train_state(state, mesh, llama_rules())
    # Stacked params: layer axis replicated, feature axes sharded as usual.
    qk = state.params["layers_scan"]["block"]["attn"]["q_proj"]["kernel"]
    assert tuple(qk.sharding.spec) == (None, "fsdp", "tp", None)
    step, data_sh = make_sharded_train_step(
        make_lm_train_step(), state, mesh, llama_rules()
    )
    batch = jax.device_put(tokens, data_sh)
    state, metrics = step(state, batch)
    assert jnp.isfinite(metrics["loss"])


def test_llama_remat_mlp_matches_block_mode():
    """remat_mode='mlp' (FFN-only recompute, BASELINE.md round 3) must be a
    pure scheduling change: same params tree (the wrapped class keeps the
    'mlp' path), same loss, same gradients as full-block remat."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import optax

    from kubeflow_tpu.models.llama import CONFIGS, Llama
    from kubeflow_tpu.train import create_train_state
    from kubeflow_tpu.train.steps import make_lm_grad_fn

    tokens = jax.random.randint(jax.random.key(0), (2, 32), 0, 256)
    losses, grads = [], []
    for mode in ("block", "mlp"):
        cfg = dataclasses.replace(CONFIGS["llama_debug"], remat=True,
                                  remat_mode=mode)
        state = create_train_state(
            jax.random.key(1), Llama(cfg), tokens, optax.sgd(1e-2)
        )
        g, _, m = make_lm_grad_fn()(state, tokens)
        losses.append(float(m["loss"]))
        grads.append(g)
    assert abs(losses[0] - losses[1]) < 1e-5
    flat0 = jax.tree_util.tree_leaves_with_path(grads[0])
    flat1 = jax.tree_util.tree_leaves_with_path(grads[1])
    assert [p for p, _ in flat0] == [p for p, _ in flat1]  # same tree/paths
    for (_, a), (_, b) in zip(flat0, flat1):
        assert float(jnp.max(jnp.abs(a - b))) < 1e-5
