import jax
import jax.numpy as jnp
import optax
import pytest

from kubeflow_tpu.models import create_model, list_models
from kubeflow_tpu.train import (
    create_train_state,
    make_classification_train_step,
    make_lm_train_step,
)


def test_registry_lists_all_families():
    names = list_models()
    for required in ("resnet50", "llama2_7b", "vit_b16", "bert_base"):
        assert required in names


def test_resnet_tiny_forward_and_learns():
    model = create_model("resnet_tiny")
    rng = jax.random.key(0)
    images = jax.random.normal(rng, (8, 32, 32, 3))
    labels = jnp.arange(8) % 10
    state = create_train_state(
        rng, model, images, optax.sgd(0.05, momentum=0.9),
        init_kwargs={"train": False},
    )
    step = jax.jit(make_classification_train_step(has_batch_stats=True))
    state, first = step(state, (images, labels))
    last = first
    for _ in range(10):
        state, last = step(state, (images, labels))
    assert float(last["loss"]) < float(first["loss"])


def test_llama_debug_forward_and_learns():
    model = create_model("llama_debug")
    rng = jax.random.key(0)
    tokens = jax.random.randint(rng, (4, 32), 0, 256)
    state = create_train_state(rng, model, tokens, optax.adamw(1e-2))
    logits = model.apply({"params": state.params}, tokens)
    assert logits.shape == (4, 32, 256)
    step = jax.jit(make_lm_train_step())
    state, first = step(state, tokens)
    last = first
    for _ in range(15):
        state, last = step(state, tokens)
    assert float(last["loss"]) < float(first["loss"])


def test_vit_debug_forward():
    model = create_model("vit_debug")
    rng = jax.random.key(0)
    images = jax.random.normal(rng, (2, 32, 32, 3))
    variables = model.init(rng, images, train=False)
    logits = model.apply(variables, images, train=False)
    assert logits.shape == (2, 10)


def test_bert_debug_forward_with_mask():
    model = create_model("bert_debug")
    rng = jax.random.key(0)
    tokens = jax.random.randint(rng, (2, 16), 0, 128)
    mask = jnp.ones((2, 16)).at[:, 8:].set(0)
    variables = model.init(rng, tokens, train=False)
    logits = model.apply(variables, tokens, attention_mask=mask, train=False)
    assert logits.shape == (2, 2)
    # Masked positions must not affect the output.
    tokens2 = tokens.at[:, 12].set(0)
    logits2 = model.apply(variables, tokens2, attention_mask=mask, train=False)
    assert jnp.allclose(logits, logits2, atol=1e-5)


def test_unknown_model_raises():
    with pytest.raises(KeyError):
        create_model("resnet9000")


def test_space_to_depth_transform():
    import numpy as np

    from kubeflow_tpu.models.resnet import space_to_depth

    x = jnp.arange(2 * 4 * 4 * 3).reshape(2, 4, 4, 3).astype(jnp.float32)
    y = space_to_depth(x, 2)
    assert y.shape == (2, 2, 2, 12)
    # Block (0,0) packs pixels (0,0),(0,1),(1,0),(1,1) in row-major order.
    np.testing.assert_array_equal(
        np.asarray(y[0, 0, 0]),
        np.concatenate([np.asarray(x[0, 0, 0]), np.asarray(x[0, 0, 1]),
                        np.asarray(x[0, 1, 0]), np.asarray(x[0, 1, 1])]),
    )


def test_space_to_depth_stem_trains():
    import optax

    from kubeflow_tpu.models import create_model
    from kubeflow_tpu.train import (
        create_train_state,
        make_classification_train_step,
    )

    model = create_model(
        "resnet_tiny", stem="space_to_depth", num_classes=10
    )
    rng = jax.random.key(0)
    images = jax.random.normal(rng, (4, 16, 16, 3), jnp.float32)
    labels = jnp.array([0, 1, 2, 3])
    state = create_train_state(
        rng, model, images, optax.sgd(0.05), init_kwargs={"train": False}
    )
    step = jax.jit(make_classification_train_step(has_batch_stats=True))
    losses = []
    for _ in range(5):
        state, metrics = step(state, (images, labels))
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]
