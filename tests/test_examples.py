"""The bundled example notebooks (BASELINE configs 1-5) must stay executable.

JAX notebooks are exec'd at tiny scale on the CPU mesh; the PyTorch/XLA and
sklearn ones are validated structurally (no network / heavyweight downloads
in unit tests).
"""
from __future__ import annotations

import json
import os

import pytest

EXAMPLES = os.path.join(os.path.dirname(__file__), "..", "examples")


def _code(name: str) -> str:
    with open(os.path.join(EXAMPLES, name)) as f:
        nb = json.load(f)
    return "\n".join(
        "".join(c["source"]) for c in nb["cells"] if c["cell_type"] == "code"
    )


@pytest.mark.parametrize("name", sorted(os.listdir(EXAMPLES)))
def test_notebook_is_valid_ipynb(name):
    with open(os.path.join(EXAMPLES, name)) as f:
        nb = json.load(f)
    assert nb["nbformat"] == 4
    assert any(c["cell_type"] == "code" for c in nb["cells"])


def test_mnist_scipy_runs():
    exec(compile(_code("01_mnist_scipy.ipynb"), "nb01", "exec"), {})


@pytest.mark.slow
def test_resnet50_notebook_runs_tiny(devices8):
    src = _code("02_resnet50_cifar.ipynb")
    src = src.replace("BATCH = 256", "BATCH = 8")
    src = src.replace('create_model("resnet50"', 'create_model("resnet_tiny"')
    src = src.replace("STEPS = 50", "STEPS = 2")
    exec(compile(src, "nb02", "exec"), {})


def test_vit_notebook_runs_tiny(devices8):
    src = _code("04_vit_train_jax.ipynb")
    src = src.replace("(64, 224, 224, 3)", "(8, 32, 32, 3)").replace("(64,)", "(8,)")
    src = src.replace(
        'create_model("vit_b16", num_classes=1000', 'create_model("vit_debug", num_classes=10'
    )
    src = src.replace("0, 1000)", "0, 10)").replace("range(5)", "range(2)")
    # vit_debug has 2 heads; default_mesh_config(8) would pick tp=4.
    src = src.replace(
        "make_mesh(default_mesh_config(len(jax.devices())))",
        "make_mesh(dp=2, fsdp=2, tp=2)",
    )
    exec(compile(src, "nb04", "exec"), {})


def test_llama_multihost_notebook_runs_tiny(devices8, tmp_path):
    src = _code("05_llama_pjit_multihost.ipynb")
    src = src.replace("GLOBAL_BATCH, SEQ = 32, 1024", "GLOBAL_BATCH, SEQ = 8, 128")
    src = src.replace('CONFIGS["llama_125m"]', 'CONFIGS["llama_debug"]')
    src = src.replace("steps=20", "steps=2")
    src = src.replace("/home/jovyan/checkpoints/llama", str(tmp_path / "ckpt"))
    exec(compile(src, "nb05", "exec"), {})


@pytest.mark.slow
def test_packing_int8_beam_notebook_runs_tiny(devices8):
    src = _code("07_packing_int8_beam.ipynb")
    src = src.replace('CFG = "llama_125m"', 'CFG = "llama_debug"')
    src = src.replace("SEQ = 512", "SEQ = 64")
    src = src.replace("STEPS = 3", "STEPS = 1")
    src = src.replace('T5_CONFIGS["t5_small"]', 'T5_CONFIGS["t5_debug"]')
    src = src.replace("max_new_tokens=12", "max_new_tokens=4")
    src = src.replace("(2, 24)", "(2, 8)")
    exec(compile(src, "nb07", "exec"), {})


def test_pytorch_xla_notebook_structure():
    src = _code("03_bert_finetune_pytorch_xla.ipynb")
    for needle in ("torch_xla", "xla_device", "AdamW", "mark_step"):
        assert needle in src


def test_generation_t5_notebook_runs_tiny(devices8):
    src = _code("06_generation_t5.ipynb")
    src = src.replace('CFG = "llama_125m"', 'CFG = "llama_debug"')
    src = src.replace("MAX_SEQ = 512", "MAX_SEQ = 64")
    src = src.replace("NEW_TOKENS = 32", "NEW_TOKENS = 4")
    src = src.replace('T5_CONFIGS["t5_small"]', 'T5_CONFIGS["t5_debug"]')
    src = src.replace("SRC_LEN, TGT_LEN, BATCH = 64, 32, 8",
                      "SRC_LEN, TGT_LEN, BATCH = 16, 8, 2")
    src = src.replace("0, 32000)", "0, 128)")
    src = src.replace("STEPS = 3", "STEPS = 1")
    exec(compile(src, "nb06", "exec"), {})


@pytest.mark.slow
def test_pytorch_xla_notebook_one_step_cpu():
    """BASELINE config 3 actually executes: torch_xla is absent in this
    image, so the notebook's documented CPU fallback runs one real
    fine-tune step on a tiny random BERT (KFT_SMOKE=1 hermetic branch) —
    no longer JSON-validation-only (VERDICT r1 item 9)."""
    import math

    os.environ["KFT_SMOKE"] = "1"
    try:
        scope = {}
        exec(compile(_code("03_bert_finetune_pytorch_xla.ipynb"),
                     "nb03", "exec"), scope)
    finally:
        os.environ.pop("KFT_SMOKE", None)
    losses = scope["losses"]
    assert len(losses) == 2 and all(math.isfinite(v) for v in losses)
    # The device line reports what ran; be loud about the torch_xla gap.
    import importlib.util

    if importlib.util.find_spec("torch_xla") is None:
        print("NOTE: torch_xla not installed in this image; "
              "the notebook executed its CPU fallback path.")


def test_tensorflow_notebook_structure():
    """BASELINE config 2 (jupyter-tensorflow-tpu-full): the notebook must
    carry the TPUStrategy + CPU-fallback structure (the image chain exists
    — images/jupyter-tensorflow-tpu*); where TF is importable the slow
    tier below also EXECUTES a tiny run of it."""
    src = _code("08_resnet_cifar_tensorflow.ipynb")
    for needle in ("TPUClusterResolver", "TPUStrategy",
                   "get_strategy()", "ResNet50"):
        assert needle in src, needle


@pytest.mark.slow
def test_tensorflow_notebook_runs_tiny_when_tf_present():
    import importlib.util

    if importlib.util.find_spec("tensorflow") is None:
        pytest.skip("tensorflow not installed in this image; structural "
                    "validation only (see BASELINE.md config 2 note)")
    src = (_code("08_resnet_cifar_tensorflow.ipynb")
           .replace("STEPS = 50", "STEPS = 1")
           .replace("BATCH = 256", "BATCH = 8")
           .replace("steps_per_epoch=10", "steps_per_epoch=1")
           .replace("steps_per_execution=10", "steps_per_execution=1"))
    exec(compile(src, "nb08", "exec"), {})


def test_long_context_notebook_runs_tiny(devices8):
    src = _code("09_long_context.ipynb")
    src = src.replace("SEQ = 32768", "SEQ = 256")
    src = src.replace("CE_CHUNK = 2048", "CE_CHUNK = 64")
    src = src.replace('CONFIGS["llama_1b4"]', 'CONFIGS["llama_debug"]')
    exec(compile(src, "nb09", "exec"), {})
