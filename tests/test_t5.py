"""T5 encoder-decoder: shapes, causality, padding invariance, training."""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models.t5 import CONFIGS, T5, relative_position_bucket


@pytest.fixture(scope="module")
def model_and_params():
    model = T5(CONFIGS["t5_debug"])
    src = jnp.ones((2, 10), jnp.int32)
    tgt = jnp.ones((2, 6), jnp.int32)
    params = model.init(jax.random.key(0), src, tgt)["params"]
    return model, params


def test_logits_shape(model_and_params):
    model, params = model_and_params
    src = jnp.ones((3, 12), jnp.int32)
    tgt = jnp.ones((3, 5), jnp.int32)
    out = model.apply({"params": params}, src, tgt)
    assert out.shape == (3, 5, CONFIGS["t5_debug"].vocab_size)
    assert out.dtype == jnp.float32


def test_decoder_is_causal(model_and_params):
    # Changing target token t must not affect logits at positions < t.
    model, params = model_and_params
    src = jnp.array([[4, 8, 15, 16, 23, 42]], jnp.int32)
    tgt_a = jnp.array([[1, 2, 3, 4]], jnp.int32)
    tgt_b = jnp.array([[1, 2, 3, 99]], jnp.int32)
    out_a = model.apply({"params": params}, src, tgt_a)
    out_b = model.apply({"params": params}, src, tgt_b)
    np.testing.assert_allclose(out_a[:, :3], out_b[:, :3], atol=1e-5)
    assert not np.allclose(out_a[:, 3], out_b[:, 3], atol=1e-5)


def test_encoder_padding_invariance(model_and_params):
    # Extra padded source tokens (masked out) must not change the logits.
    model, params = model_and_params
    src = jnp.array([[4, 8, 15]], jnp.int32)
    tgt = jnp.array([[1, 2]], jnp.int32)
    want = model.apply({"params": params}, src, tgt)
    padded = jnp.array([[4, 8, 15, 0, 0, 0]], jnp.int32)
    mask = jnp.array([[1, 1, 1, 0, 0, 0]], bool)
    got = model.apply({"params": params}, padded, tgt, source_mask=mask)
    np.testing.assert_allclose(want, got, atol=1e-5)


def test_source_actually_conditions_decoder(model_and_params):
    model, params = model_and_params
    tgt = jnp.array([[1, 2, 3]], jnp.int32)
    a = model.apply({"params": params}, jnp.array([[5, 6]], jnp.int32), tgt)
    b = model.apply({"params": params}, jnp.array([[7, 9]], jnp.int32), tgt)
    assert not np.allclose(a, b, atol=1e-5)


def test_relative_position_buckets():
    rel = np.arange(-6, 7)[None, :]  # query at 0 vs keys -6..6
    bi = relative_position_bucket(
        rel, bidirectional=True, num_buckets=8, max_distance=16
    )
    assert bi.min() >= 0 and bi.max() < 8
    # Sign split: negative and positive relative positions use distinct halves.
    assert len(set(bi[0][:6]) & set(bi[0][7:])) == 0
    uni = relative_position_bucket(
        rel, bidirectional=False, num_buckets=8, max_distance=16
    )
    assert uni.min() >= 0 and uni.max() < 8
    # In the unidirectional scheme every "future" key collapses to bucket 0.
    assert (uni[0][7:] == uni[0][7]).all()


@pytest.mark.slow
def test_train_step_decreases_loss():
    import optax

    cfg = dataclasses.replace(CONFIGS["t5_debug"])
    model = T5(cfg)
    rng = jax.random.key(0)
    src = jax.random.randint(rng, (4, 8), 0, cfg.vocab_size)
    tgt = jax.random.randint(jax.random.key(1), (4, 6), 0, cfg.vocab_size)
    params = model.init(rng, src, tgt)["params"]
    tx = optax.adam(1e-2)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state):
        def loss_fn(p):
            logits = model.apply({"params": p}, src, tgt)
            labels = jnp.roll(tgt, -1, axis=1)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1], labels[:, :-1]
            ).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    for _ in range(8):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, losses


def test_registry_has_t5():
    from kubeflow_tpu.models import create_model, list_models

    assert "t5_small" in list_models()
    m = create_model("t5_debug")
    assert isinstance(m, T5)


@pytest.mark.slow
def test_seq2seq_cached_decode_matches_full_forward(model_and_params):
    """Greedy cached generation == the uncached argmax loop that re-runs
    the full decoder each step (pins cache writes AND the dynamic-position
    relative bias against the static path)."""
    from kubeflow_tpu.models.generate import generate_seq2seq

    model, params = model_and_params
    src = jax.random.randint(jax.random.key(1), (2, 10), 2, 128)
    # > rel_buckets//2 = 16 tokens so the log-spaced bucket branch (where a
    # float32 re-derivation once diverged from numpy's float64) is hit too.
    n = 24
    got = generate_seq2seq(
        model, params, src, max_new_tokens=n, eos_token=None
    )

    # Reference: full (uncached) forward over the growing target prefix.
    tgt = jnp.zeros((2, 1), jnp.int32)  # BOS = pad id 0
    want = []
    for _ in range(n):
        logits = model.apply({"params": params}, src, tgt)
        nxt = jnp.argmax(logits[:, -1], axis=-1)
        want.append(nxt)
        tgt = jnp.concatenate([tgt, nxt[:, None].astype(jnp.int32)], axis=1)
    want = jnp.stack(want, axis=1)
    assert (got == want).all(), (got, want)


def test_seq2seq_source_mask_respected(model_and_params):
    """Padding the source (with mask) must not change the generation."""
    from kubeflow_tpu.models.generate import generate_seq2seq

    model, params = model_and_params
    src = jax.random.randint(jax.random.key(2), (1, 8), 2, 128)
    padded = jnp.concatenate(
        [src, jnp.full((1, 4), 99, jnp.int32)], axis=1
    )
    mask = jnp.concatenate(
        [jnp.ones((1, 8), bool), jnp.zeros((1, 4), bool)], axis=1
    )
    a = generate_seq2seq(model, params, src, max_new_tokens=5, eos_token=None)
    b = generate_seq2seq(
        model, params, padded, source_mask=mask, max_new_tokens=5,
        eos_token=None,
    )
    assert (a == b).all()


def test_seq2seq_eos_padding(model_and_params):
    from kubeflow_tpu.models.generate import generate_seq2seq

    model, params = model_and_params
    src = jnp.ones((2, 6), jnp.int32)
    out = generate_seq2seq(model, params, src, max_new_tokens=8)
    assert out.shape == (2, 8)
    # After an EOS (id 1), the row pads with EOS.
    arr = np.asarray(out)
    for row in arr:
        hits = np.where(row == 1)[0]
        if hits.size:
            assert (row[hits[0]:] == 1).all()


def test_beam_search_beam1_equals_greedy(model_and_params):
    """beams=1 must reproduce greedy decoding exactly — pins the cache
    re-gather, parent backtracking, and EOS freezing machinery."""
    from kubeflow_tpu.models.generate import (
        beam_search_seq2seq,
        generate_seq2seq,
    )

    model, params = model_and_params
    src = jax.random.randint(jax.random.key(3), (2, 9), 2, 128)
    greedy = generate_seq2seq(model, params, src, max_new_tokens=10)
    beam1 = beam_search_seq2seq(
        model, params, src, max_new_tokens=10, beams=1
    )
    assert (greedy == beam1).all(), (greedy, beam1)


def _sequence_logprob(model, params, src, seqs, eos=1):
    """Sum log p(token_t | prefix) over each sequence up to+incl first EOS."""
    b, t = seqs.shape
    bos = jnp.zeros((b, 1), jnp.int32)
    tgt_in = jnp.concatenate([bos, seqs[:, :-1]], axis=1)
    logits = model.apply({"params": params}, src, tgt_in)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    tok_lp = jnp.take_along_axis(logp, seqs[..., None], axis=-1)[..., 0]
    # Count tokens up to and including the first EOS.
    before = jnp.cumprod(seqs != eos, axis=1)
    keep = jnp.concatenate(
        [jnp.ones((b, 1)), before[:, :-1].astype(jnp.float32)], axis=1
    )
    return jnp.sum(tok_lp * keep, axis=1)


def test_beam_search_finds_higher_scoring_sequences(model_and_params):
    from kubeflow_tpu.models.generate import beam_search_seq2seq

    model, params = model_and_params
    src = jax.random.randint(jax.random.key(4), (3, 9), 2, 128)
    b1 = beam_search_seq2seq(model, params, src, max_new_tokens=8,
                             beams=1, length_penalty=0.0)
    b4 = beam_search_seq2seq(model, params, src, max_new_tokens=8,
                             beams=4, length_penalty=0.0)
    s1 = _sequence_logprob(model, params, src, b1)
    s4 = _sequence_logprob(model, params, src, b4)
    # Wider search never scores worse on this model (and the scores come
    # from an independent full-forward rescoring, pinning the beam
    # bookkeeping against the actual model distribution).
    assert (s4 >= s1 - 1e-4).all(), (s1, s4)


def test_beam_search_eos_padding(model_and_params):
    from kubeflow_tpu.models.generate import beam_search_seq2seq

    model, params = model_and_params
    src = jnp.ones((2, 6), jnp.int32)
    out = beam_search_seq2seq(model, params, src, max_new_tokens=8, beams=3)
    arr = np.asarray(out)
    assert arr.shape == (2, 8)
    for row in arr:
        hits = np.where(row == 1)[0]
        if hits.size:
            assert (row[hits[0]:] == 1).all()
