"""Sequence packing (data/packing.py + native/packer.cc)."""
import numpy as np
import pytest

from kubeflow_tpu.data.packing import (
    _pack_python,
    pack_documents,
    pack_tokens,
    packed_lm_batches,
)


def assert_valid_packing(lengths, row_len, assignment, offset, n_rows):
    slots = {}
    for i, ln in enumerate(lengths):
        r, o = int(assignment[i]), int(offset[i])
        assert 0 <= r < n_rows
        assert o + ln <= row_len, f"doc {i} overflows row {r}"
        for s in range(o, o + ln):
            assert (r, s) not in slots, f"overlap at row {r} slot {s}"
            slots[(r, s)] = i


def test_pack_documents_valid_and_tight():
    rng = np.random.default_rng(0)
    lengths = rng.integers(1, 100, 200).tolist()
    assignment, offset, n_rows = pack_documents(lengths, 128)
    assert_valid_packing(lengths, 128, assignment, offset, n_rows)
    # BFD is within 11/9 OPT + 4; lower-bound OPT by total/row_len.
    opt_lb = -(-sum(lengths) // 128)
    assert n_rows <= (11 * opt_lb) // 9 + 4
    # And clearly better than one-doc-per-row.
    assert n_rows < len(lengths) // 2


def test_native_and_python_packers_agree():
    from kubeflow_tpu.platform import native

    rng = np.random.default_rng(1)
    lengths = rng.integers(1, 64, 500)
    py = _pack_python(np.asarray(lengths, np.int64), 64)
    nat = native.native_pack(np.asarray(lengths, np.int64), 64)
    if nat is None:
        pytest.skip("native library unavailable")
    assert (py[0] == nat[0]).all() and (py[1] == nat[1]).all()
    assert py[2] == nat[2]


def test_pack_documents_rejects_bad_lengths():
    with pytest.raises(ValueError):
        pack_documents([5, 300], 128)   # too long
    with pytest.raises(ValueError):
        _pack_python(np.array([5, 0]), 128)  # empty doc
    from kubeflow_tpu.platform import native

    if native.available():
        with pytest.raises(ValueError):
            native.native_pack(np.array([5, 0], np.int64), 128)


def test_pack_tokens_segments():
    docs = [np.arange(1, 5), np.arange(10, 13), np.arange(20, 26)]
    tokens, segments = pack_tokens(docs, 8, pad_id=0)
    # 4+3 fit one row; 6 takes its own.
    assert tokens.shape == segments.shape == (2, 8)
    for i, doc in enumerate(docs):
        # Every document appears contiguously under a single segment id.
        found = False
        for r in range(tokens.shape[0]):
            for o in range(8 - len(doc) + 1):
                if (tokens[r, o:o + len(doc)] == doc).all():
                    seg = segments[r, o:o + len(doc)]
                    assert (seg == seg[0]).all() and seg[0] > 0
                    found = True
        assert found, f"doc {i} not packed"
    # Padding slots carry segment 0.
    assert ((tokens == 0) == (segments == 0)).all()


def test_packed_lm_batches_shapes():
    rng = np.random.default_rng(2)
    docs = [rng.integers(1, 50, rng.integers(4, 30)) for _ in range(100)]
    batches = list(packed_lm_batches(iter(docs), batch_rows=4, seq_len=32))
    assert batches
    for tokens, segments in batches:
        assert tokens.shape == segments.shape == (4, 32)
        assert segments.max() >= 1


def test_packed_lm_batches_drops_nothing():
    """Overflow rows carry into the next window: every input token reaches
    exactly one output slot (the reviewer's repro: 4 docs of 5 tokens,
    2 rows of 8 — the old code dropped half the corpus)."""
    docs = [np.full(5, i + 1) for i in range(4)]
    out = list(packed_lm_batches(
        iter(docs), batch_rows=2, seq_len=8, drop_remainder=False))
    got = np.concatenate([t[t != 0] for t, _ in out])
    assert sorted(got.tolist()) == sorted(
        np.concatenate(docs).tolist()
    )
    # Larger randomized check.
    rng = np.random.default_rng(3)
    docs = [rng.integers(1, 99, rng.integers(3, 20)) for _ in range(60)]
    out = list(packed_lm_batches(
        iter(docs), batch_rows=3, seq_len=24, drop_remainder=False))
    got = np.concatenate([t[t != 0] for t, _ in out])
    assert sorted(got.tolist()) == sorted(np.concatenate(docs).tolist())


def test_lm_step_masks_packed_loss():
    import dataclasses

    import jax
    import jax.numpy as jnp
    import optax

    from kubeflow_tpu.models.llama import CONFIGS, Llama
    from kubeflow_tpu.train import create_train_state, make_lm_train_step

    cfg = dataclasses.replace(CONFIGS["llama_debug"], max_seq_len=32)
    model = Llama(cfg)
    docs = [np.arange(1, 12), np.arange(30, 44), np.arange(50, 55)]
    tokens, segments = pack_tokens(docs, 32)
    tokens_j = jnp.asarray(tokens)
    segments_j = jnp.asarray(segments)
    state = create_train_state(
        jax.random.key(0), model, tokens_j, optax.sgd(0.0)
    )
    step = jax.jit(make_lm_train_step())
    _, packed = step(state, (tokens_j, segments_j))
    _, unpacked = step(state, tokens_j)
    # Masking changes the loss (pad/cross-doc targets excluded) and both
    # are finite.
    assert jnp.isfinite(packed["loss"]) and jnp.isfinite(unpacked["loss"])
    assert abs(float(packed["loss"]) - float(unpacked["loss"])) > 1e-6


def test_lm_loss_weights_zero_targets():
    import jax.numpy as jnp

    from kubeflow_tpu.train.steps import cross_entropy

    logits = jnp.zeros((2, 3, 7))
    labels = jnp.zeros((2, 3), jnp.int32)
    w = jnp.zeros((2, 3))
    # All-masked: defined (0), not NaN.
    assert float(cross_entropy(logits, labels, w)) == 0.0
