"""Train loop: logging, eval hook, checkpoint/resume, trainer CLI."""
import dataclasses

import jax
import jax.numpy as jnp
import optax
import pytest

from kubeflow_tpu.train import create_train_state, make_lm_train_step
from kubeflow_tpu.train.loop import LoopConfig, train_loop


def tiny_state(seed=0):
    from kubeflow_tpu.models.llama import CONFIGS, Llama

    cfg = dataclasses.replace(CONFIGS["llama_debug"], max_seq_len=32)
    model = Llama(cfg)
    tokens = jnp.ones((4, 32), jnp.int32)
    return create_train_state(
        jax.random.key(seed), model, tokens, optax.adamw(1e-3)
    ), cfg


def batches(n=10_000, seed=0):
    rng = jax.random.key(seed)
    i = 0
    while i < n:
        yield jax.random.randint(jax.random.fold_in(rng, i), (4, 32), 0, 256)
        i += 1


def test_loop_runs_and_logs():
    state, _ = tiny_state()
    step = jax.jit(make_lm_train_step())
    logged = []
    state, history = train_loop(
        state, step, batches(), LoopConfig(total_steps=6, log_every=2),
        on_log=lambda s, vals: logged.append(s),
    )
    assert int(state.step) == 6
    assert logged == [2, 4, 6]
    assert all("loss" in h and "steps_per_sec" in h for h in history)
    # Loss must actually move (the step is real, not a no-op).
    assert history[-1]["loss"] != history[0]["loss"]


def test_loop_checkpoint_resume(tmp_path):
    ckpt = str(tmp_path / "run1")
    step = jax.jit(make_lm_train_step())

    state, _ = tiny_state()
    cfg = LoopConfig(total_steps=5, log_every=0, checkpoint_dir=ckpt,
                     checkpoint_every=2)
    state, _ = train_loop(state, step, batches(), cfg)
    assert int(state.step) == 5
    trained_params = state.params

    # "Restart the pod": fresh state, same checkpoint dir, higher target.
    state2, _ = tiny_state(seed=123)  # different init — must be overwritten
    cfg2 = dataclasses.replace(cfg, total_steps=8)
    state2, history = train_loop(state2, step, batches(), cfg2)
    assert int(state2.step) == 8
    # The resumed run continued from the trained params, not seed 123's.
    restored_leaf = jax.tree_util.tree_leaves(trained_params)[0]
    fresh_leaf = jax.tree_util.tree_leaves(tiny_state(seed=123)[0].params)[0]
    assert not jnp.allclose(restored_leaf, fresh_leaf, atol=1e-6)


def test_sigterm_mid_loop_saves_restorable_checkpoint(tmp_path):
    """Graceful preemption (ISSUE 10 satellite): run.py's SIGTERM handler
    sets the loop's stop event; the loop exits between steps and its
    finally block force-saves + waits — a real signal mid-loop must leave
    a checkpoint a fresh process can resume from, even when the save
    interval alone would never have written one."""
    import signal
    import threading

    from kubeflow_tpu.train.run import install_preemption_handler

    ckpt = str(tmp_path / "preempted")
    stop = threading.Event()
    prev_handler = signal.getsignal(signal.SIGTERM)
    assert install_preemption_handler(stop) is True

    def on_log(step, vals):
        # Deliver a REAL signal mid-run (handler runs in this main
        # thread at the next bytecode boundary — exactly a preemption).
        signal.raise_signal(signal.SIGTERM)

    state, _ = tiny_state()
    step_fn = jax.jit(make_lm_train_step())
    # checkpoint_every is huge: the ONLY checkpoint can come from the
    # preemption save in the loop's finally block.
    try:
        state, _ = train_loop(
            state, step_fn, batches(),
            LoopConfig(total_steps=500, log_every=3, checkpoint_dir=ckpt,
                       checkpoint_every=10_000),
            on_log=on_log, stop=stop,
        )
    finally:
        # Don't leak the handler into the rest of the pytest process: a
        # runner's graceful-shutdown SIGTERM would be silently swallowed.
        signal.signal(signal.SIGTERM, prev_handler)
    assert stop.is_set()
    stopped_at = int(state.step)
    assert 0 < stopped_at < 500  # preempted mid-loop, not ran out
    trained_leaf = jax.tree_util.tree_leaves(state.params)[0]

    # "The gang's next generation": fresh init, same dir — must resume
    # from the preemption checkpoint, not from scratch.
    state2, _ = tiny_state(seed=99)
    state2, _ = train_loop(
        state2, step_fn, batches(),
        LoopConfig(total_steps=stopped_at + 2, log_every=0,
                   checkpoint_dir=ckpt, checkpoint_every=10_000),
    )
    assert int(state2.step) == stopped_at + 2
    fresh_leaf = jax.tree_util.tree_leaves(tiny_state(seed=99)[0].params)[0]
    assert not jnp.allclose(trained_leaf, fresh_leaf, atol=1e-6)


def test_grad_accum_matches_full_batch_step():
    """Mean-of-microbatch-grads == full-batch grad (equal microbatches), so
    the accumulated step must match the plain step bit-for-bit-ish."""
    from kubeflow_tpu.train import make_grad_accum_step, make_lm_grad_fn

    state_a, _ = tiny_state()
    state_b = jax.tree.map(lambda x: x, state_a)
    batch = jax.random.randint(jax.random.key(7), (4, 32), 0, 256)
    plain = jax.jit(make_lm_train_step())
    accum = jax.jit(make_grad_accum_step(make_lm_grad_fn(), n_accum=4))
    state_a, m_a = plain(state_a, batch)
    state_b, m_b = accum(state_b, batch)
    for pa, pb in zip(jax.tree.leaves(state_a.params),
                      jax.tree.leaves(state_b.params)):
        # float32-appropriate tolerance: the accumulated path sums grads in
        # a different order (scan over microbatches vs one fused reduce),
        # and the optimizer's rsqrt amplifies those last-ulp differences —
        # measured up to ~4e-5 on identical math.  2e-5 banded the
        # reduction order, not a real divergence.
        assert jnp.allclose(pa, pb, atol=1e-4), float(jnp.abs(pa - pb).max())
    # Metrics are averaged over microbatches; the mean of per-microbatch
    # losses equals the full-batch loss for equal-size microbatches.
    assert abs(float(m_a["loss"]) - float(m_b["loss"])) < 2e-4


def test_grad_accum_batch_stats_path():
    from kubeflow_tpu.models import create_model
    from kubeflow_tpu.train import (
        make_classification_grad_fn,
        make_grad_accum_step,
    )

    model = create_model("resnet_tiny", num_classes=10)
    images = jnp.ones((8, 32, 32, 3), jnp.float32)
    state = create_train_state(
        jax.random.key(0), model, images, optax.sgd(0.1),
        init_kwargs={"train": False},
    )
    step = jax.jit(make_grad_accum_step(
        make_classification_grad_fn(has_batch_stats=True),
        n_accum=2, has_batch_stats=True,
    ))
    labels = jnp.zeros((8,), jnp.int32)
    before = jax.tree.leaves(state.batch_stats)[0].copy()
    state, metrics = step(state, (images, labels))
    assert jnp.isfinite(metrics["loss"])
    # batch_stats actually advanced through the scan.
    after = jax.tree.leaves(state.batch_stats)[0]
    assert not jnp.allclose(before, after)


def test_grad_accum_rejects_indivisible_batch():
    from kubeflow_tpu.train import make_grad_accum_step, make_lm_grad_fn

    step = make_grad_accum_step(make_lm_grad_fn(), n_accum=3)
    state, _ = tiny_state()
    with pytest.raises(ValueError, match="not divisible"):
        step(state, jnp.ones((4, 32), jnp.int32))


def test_step_indexed_batches_resume_exactly():
    from kubeflow_tpu.data.loader import synthetic_lm_batches

    full = list(synthetic_lm_batches(
        global_batch=4, seq_len=8, vocab_size=50, seed=3, steps=6))
    resumed = list(synthetic_lm_batches(
        global_batch=4, seq_len=8, vocab_size=50, seed=3, steps=6, start=4))
    assert len(resumed) == 2
    assert (resumed[0] == full[4]).all() and (resumed[1] == full[5]).all()


def test_loop_callable_batches_gets_resume_point(tmp_path):
    state, _ = tiny_state()
    step = jax.jit(make_lm_train_step())
    cfg = LoopConfig(total_steps=4, log_every=0,
                     checkpoint_dir=str(tmp_path), checkpoint_every=2)
    train_loop(state, step, lambda start: batches(seed=start), cfg)
    seen = []

    def make_batches(start):
        seen.append(start)
        return batches(seed=start)

    fresh, _ = tiny_state()
    cfg2 = dataclasses.replace(cfg, total_steps=6)
    state2, _ = train_loop(fresh, step, make_batches, cfg2)
    assert seen == [4]  # resumed at the checkpointed step
    assert int(state2.step) == 6


def test_loop_eval_hook():
    state, _ = tiny_state()
    step = jax.jit(make_lm_train_step())

    def eval_fn(state, batch):
        return {"loss": 1.25}

    state, history = train_loop(
        state, step, batches(),
        LoopConfig(total_steps=4, log_every=0, eval_every=2, eval_steps=3),
        eval_fn=eval_fn, eval_batches=lambda: batches(seed=9),
    )
    evals = [h for h in history if "eval_loss" in h]
    assert [h["step"] for h in evals] == [2, 4]
    assert all(h["eval_loss"] == pytest.approx(1.25) for h in evals)


def test_loop_stops_on_data_exhaustion():
    state, _ = tiny_state()
    step = jax.jit(make_lm_train_step())
    state, _ = train_loop(
        state, step, batches(n=3), LoopConfig(total_steps=100, log_every=0)
    )
    assert int(state.step) == 3


@pytest.mark.slow
def test_trainer_cli_smoke(devices8, tmp_path):
    from kubeflow_tpu.train import run as trainer

    rc = trainer.main([
        "--model", "llama_debug", "--task", "lm", "--steps", "4",
        "--batch", "8", "--seq", "32", "--mesh", "dp=2,fsdp=2,tp=2",
        "--log-every", "2", "--checkpoint-dir", str(tmp_path / "cli"),
        "--checkpoint-every", "2",
    ])
    assert rc == 0
    # Resume path: second invocation continues past step 4.
    rc = trainer.main([
        "--model", "llama_debug", "--task", "lm", "--steps", "6",
        "--batch", "8", "--seq", "32", "--mesh", "dp=2,fsdp=2,tp=2",
        "--log-every", "2", "--checkpoint-dir", str(tmp_path / "cli"),
        "--checkpoint-every", "2",
    ])
    assert rc == 0


def test_trainer_cli_packed_smoke(devices8, tmp_path):
    from kubeflow_tpu.train.run import main

    rc = main([
        "--model", "llama_debug", "--steps", "3", "--batch", "8",
        "--seq", "64", "--packed", "--mesh", "dp=2,fsdp=4",
        "--log-every", "1",
    ])
    assert rc == 0


def test_trainer_cli_rejects_bad_mesh():
    from kubeflow_tpu.train import run as trainer

    with pytest.raises(ValueError, match="unknown mesh axis"):
        trainer.parse_mesh("bogus=2", 8)
    with pytest.raises(ValueError, match="integer"):
        trainer.parse_mesh("tp=two", 8)


def test_profile_steps_produces_trace(tmp_path):
    from kubeflow_tpu.train.profiling import profile_steps

    state, _ = tiny_state()
    step = jax.jit(make_lm_train_step())
    data = next(batches())
    logdir = str(tmp_path / "profile")
    (new_state, metrics), where = profile_steps(
        logdir, step, state, data, warmup=1, steps=2
    )
    assert int(new_state.step) == 3  # state threaded through warmup + trace
    # A plugins/profile/<run>/ directory with trace artifacts must exist.
    found = []
    for root, _dirs, files in __import__("os").walk(logdir):
        found.extend(files)
    assert found, f"no trace files under {logdir}"


def test_restore_params_casts_to_template_dtype(tmp_path):
    """Unsharded params-only restore lands in the TEMPLATE dtype: a float32
    checkpoint served by a bfloat16 model must not silently restore float32
    (ADVICE r1 — train/checkpoint.py _restore_args unsharded branch)."""
    from kubeflow_tpu.train.checkpoint import CheckpointManager

    state, _ = tiny_state()
    with CheckpointManager(str(tmp_path)) as mgr:
        mgr.save(0, state, force=True)
    template = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, jnp.bfloat16), state.params
    )
    with CheckpointManager(str(tmp_path)) as mgr:
        params = mgr.restore_params(template=template)
    dtypes = {x.dtype for x in jax.tree_util.tree_leaves(params)}
    assert dtypes == {jnp.dtype(jnp.bfloat16)}


def test_cross_entropy_custom_vjp_matches_log_softmax_reference():
    """The fused token-NLL (custom VJP, no vocab-sized residual) must match
    the straightforward log_softmax formulation in values AND gradients,
    weighted and unweighted (BASELINE.md round-3 optimization)."""
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.train.steps import cross_entropy

    def ref(logits, labels, weights=None):
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
        if weights is None:
            return -jnp.mean(ll)
        w = weights.astype(jnp.float32)
        return -jnp.sum(ll * w) / jnp.maximum(jnp.sum(w), 1.0)

    key = jax.random.key(0)
    logits = jax.random.normal(key, (2, 9, 33), jnp.float32) * 6
    labels = jax.random.randint(jax.random.fold_in(key, 1), (2, 9), 0, 33)
    w = (jax.random.uniform(jax.random.fold_in(key, 2), (2, 9)) > 0.4
         ).astype(jnp.float32)
    for weights in (None, w):
        v, g = jax.value_and_grad(
            lambda l: cross_entropy(l, labels, weights))(logits)
        vr, gr = jax.value_and_grad(
            lambda l: ref(l, labels, weights))(logits)
        assert abs(float(v - vr)) < 1e-5
        assert float(jnp.max(jnp.abs(g - gr))) < 1e-6


def test_bf16_grad_dtype_matches_f32_within_tolerance():
    """grad_dtype=bf16 (mixed precision: bf16 grad storage + f32 master
    weights) must produce the same training signal as f32 grads within
    bf16 mantissa tolerance, and the stored grads must actually BE bf16
    (the memory saving is the point — 2.73 GB at 1.36B params)."""
    from kubeflow_tpu.train import make_lm_grad_fn

    state, _ = tiny_state()
    batch = next(batches(1))
    g32, _, m32 = make_lm_grad_fn()(state, batch)
    g16, _, m16 = make_lm_grad_fn(grad_dtype=jnp.bfloat16)(state, batch)

    leaves16 = jax.tree.leaves(g16)
    assert all(x.dtype == jnp.bfloat16 for x in leaves16)
    assert abs(float(m32["loss"]) - float(m16["loss"])) < 1e-2
    for a, b in zip(jax.tree.leaves(g32), leaves16):
        a = a.astype(jnp.float32)
        b = b.astype(jnp.float32)
        denom = float(jnp.max(jnp.abs(a))) or 1.0
        assert float(jnp.max(jnp.abs(a - b))) / denom < 0.05


def test_bf16_grad_step_trains():
    """An end-to-end bf16-grad step updates f32 master params (dtype
    preserved) and the loss goes down over a few steps."""
    state, _ = tiny_state()
    step = jax.jit(make_lm_train_step(grad_dtype=jnp.bfloat16),
                   donate_argnums=(0,))
    batch = next(batches(1))
    first = None
    for _ in range(8):
        state, metrics = step(state, batch)
        if first is None:
            first = float(metrics["loss"])
    assert all(
        x.dtype == jnp.float32 for x in jax.tree.leaves(state.params)
    )
    assert float(metrics["loss"]) < first


def test_chunked_ce_matches_unchunked_loss_and_grads():
    """ce_chunk (chunked lm_head + CE, the long-context memory lever)
    must reproduce the unchunked path's loss and gradients — including
    the head's own gradient, which accumulates across scan chunks."""
    from kubeflow_tpu.train import make_lm_grad_fn as mk

    state, _ = tiny_state()
    batch = next(batches(1))
    g_ref, _, m_ref = mk()(state, batch)
    g_chk, _, m_chk = mk(ce_chunk=8)(state, batch)  # 32 = 4 chunks of 8

    assert abs(float(m_ref["loss"]) - float(m_chk["loss"])) < 1e-5
    flat_ref = jax.tree.leaves(g_ref)
    flat_chk = jax.tree.leaves(g_chk)
    for a, b in zip(flat_ref, flat_chk):
        denom = float(jnp.max(jnp.abs(a))) or 1.0
        assert float(jnp.max(jnp.abs(a - b))) / denom < 2e-4


def test_chunked_ce_packed_weights_match():
    """Packed-sequence weights (segment_ids) through the chunked path."""
    from kubeflow_tpu.train import make_lm_grad_fn as mk

    state, _ = tiny_state()
    tokens = next(batches(1))
    seg = jnp.concatenate(
        [jnp.full((4, 16), 1), jnp.full((4, 8), 2), jnp.zeros((4, 8),
                                                              jnp.int32)],
        axis=1)
    g_ref, _, m_ref = mk()(state, (tokens, seg))
    g_chk, _, m_chk = mk(ce_chunk=8)(state, (tokens, seg))
    assert abs(float(m_ref["loss"]) - float(m_chk["loss"])) < 1e-5
    for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_chk)):
        denom = float(jnp.max(jnp.abs(a))) or 1.0
        assert float(jnp.max(jnp.abs(a - b))) / denom < 2e-4


def test_chunked_ce_rejects_indivisible_chunk():
    import pytest as _pytest

    from kubeflow_tpu.train import make_lm_grad_fn as mk

    state, _ = tiny_state()
    with _pytest.raises(ValueError, match="not divisible"):
        mk(ce_chunk=7)(state, next(batches(1)))


def test_chunked_ce_with_moe_aux_loss():
    """ce_chunk composes with the MoE aux-loss collection path (mixtral
    configs are LlamaConfigs, so return_hidden covers them too)."""
    from kubeflow_tpu.models import create_model
    from kubeflow_tpu.train import make_lm_grad_fn as mk

    model = create_model("mixtral_debug")
    tokens = jnp.ones((2, 32), jnp.int32)
    state = create_train_state(jax.random.key(0), model, tokens,
                               optax.adamw(1e-3))
    g_ref, _, m_ref = mk(aux_loss_weight=0.01)(state, tokens)
    g_chk, _, m_chk = mk(aux_loss_weight=0.01, ce_chunk=8)(state, tokens)
    assert abs(float(m_ref["loss"]) - float(m_chk["loss"])) < 1e-5
    assert abs(float(m_ref["moe_aux_loss"]) - float(m_chk["moe_aux_loss"])) \
        < 1e-6
    for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_chk)):
        denom = float(jnp.max(jnp.abs(a))) or 1.0
        assert float(jnp.max(jnp.abs(a - b))) / denom < 2e-4


def test_trainer_cli_long_context_levers(devices8):
    """--grad-dtype bf16 and --ce-chunk through the sharded trainer CLI."""
    from kubeflow_tpu.train import run as trainer

    rc = trainer.main([
        "--model", "llama_debug", "--task", "lm", "--steps", "3",
        "--batch", "8", "--seq", "32", "--mesh", "dp=2,fsdp=2,tp=2",
        "--grad-dtype", "bf16", "--ce-chunk", "8", "--log-every", "2",
    ])
    assert rc == 0
