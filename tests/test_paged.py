"""Sharded paged serving + pipelined dispatch (models/paged.py, ISSUE 20).

The load-bearing contracts, in order of importance:

* The GSPMD-sharded page pool is an OPTIMIZATION, never a behavior
  change: sharded-paged == unsharded-paged == fixed-slot-pool token
  streams, byte for byte, across greedy, seeded top-k, mixed lengths,
  shared prefixes (copy-on-write divergence) and mid-flight eviction —
  on 8 forced host devices (tests/conftest.py).
* Pipelined dispatch (quantum N+1 launched before quantum N's tokens are
  harvested) is byte-identical to the synchronous loop; only latency
  moves, never tokens.
* Falling back to the fixed-slot pool is never silent: the reason is
  recorded on the service, counted by serve_paged_fallback_total, and
  surfaced by /debug/serve.
"""
import dataclasses

import jax
import jax.numpy as jnp
import pytest
from werkzeug.test import Client

from kubeflow_tpu.models.llama import CONFIGS, Llama
from kubeflow_tpu.models.paged import PagedDecodeScheduler
from kubeflow_tpu.models.scheduler import DecodeScheduler
from kubeflow_tpu.models.serve import GenerationService, create_app
from kubeflow_tpu.parallel.sharding import rules_for_model, shard_params
from kubeflow_tpu.train.run import parse_mesh


@pytest.fixture(scope="module")
def model_and_params():
    cfg = dataclasses.replace(CONFIGS["llama_debug"], max_seq_len=64)
    model = Llama(cfg)
    params = model.init(jax.random.key(0), jnp.ones((1, 8), jnp.int32))[
        "params"]
    return model, params


@pytest.fixture(scope="module")
def mesh_and_sharded(devices8, model_and_params):
    model, params = model_and_params
    mesh = parse_mesh("tp=2,fsdp=4", 8)
    return mesh, shard_params(params, mesh, rules_for_model(model))


def _paged(model, params, **kw):
    kw.setdefault("slots", 3)
    kw.setdefault("slot_len", 64)
    kw.setdefault("quantum", 4)
    kw.setdefault("page_len", 16)
    kw.setdefault("prefill_chunk", 16)
    return PagedDecodeScheduler(model, params, **kw)


# The equality-matrix workload: mixed lengths, two rows sharing a
# page-aligned 16-token prefix (prefix-cache hit + COW divergence), a
# greedy/seeded-top-k mix, and budgets spread so rows finish while the
# queue still holds work (slots=3 < 6 requests → mid-flight eviction
# and refill on every arm).
_PREFIX = list(range(1, 17))
_WORKLOAD = [
    # (rows, max_new_tokens, temperature, top_k, seed)
    ([[5, 9, 2, 7]], 10, 0.0, None, 0),
    ([_PREFIX + [21, 22]], 8, 0.9, 5, 11),
    ([[3, 3]], 12, 0.7, 8, 7),
    ([_PREFIX + [31]], 8, 0.0, None, 0),
    ([[4] * 24, [8, 6, 4]], 6, 0.8, 4, 3),
    ([[2, 3, 4, 5, 6]], 9, 0.0, None, 0),
]


def _run_workload(sched):
    pending = [
        sched.submit(rows, max_new_tokens=n, temperature=t, top_k=k,
                     seed=s, eos_token=None)
        for rows, n, t, k, s in _WORKLOAD
    ]
    out = [p.result() for p in pending]
    sched.stop()
    return out


@pytest.mark.slow
def test_sharded_paged_token_equality_matrix(devices8, model_and_params,
                                             mesh_and_sharded):
    """The ISSUE 20 acceptance matrix: five engines, one workload, one
    token stream.  The sharded arm must also actually shard — pool split
    4 ways over fsdp, rank-3 pool leaves spanning devices.

    slow: five fresh-compiled engines on 8 virtual devices cost ~1 min
    of single-core CPU; the `paged-sharded` presubmit step runs this
    file WITHOUT the tier-1 `not slow` filter, and the tier-1 smoke
    below keeps a 3-arm tripwire in every run."""
    model, params = model_and_params
    mesh, sharded = mesh_and_sharded

    baseline = _run_workload(_paged(model, params))

    spmd = _paged(model, sharded, mesh=mesh)
    assert spmd.pool_shards == 4
    got = _run_workload(spmd)
    assert got == baseline
    assert spmd.stats()["pool_shards"] == 4
    pool_leaf = next(x for x in jax.tree.leaves(spmd._cache)
                     if getattr(x, "ndim", 0) >= 3)
    assert len(pool_leaf.sharding.device_set) >= 4

    fixed = DecodeScheduler(model, params, slots=3, slot_len=64,
                            quantum=4)
    assert _run_workload(fixed) == baseline

    sync = _paged(model, params, pipeline=False)
    assert sync.pipeline is False
    assert _run_workload(sync) == baseline

    spec = _paged(model, params, draft_model=model, draft_params=params,
                  spec_tokens=3)
    assert _run_workload(spec) == baseline


# The tier-1 smoke workload: the dimensions test_scheduler.py's
# sharded-serve check (greedy, mixed lengths) does NOT already cover —
# seeded top-k under the mesh, a prefix-cache hit, and COW divergence
# off the shared prefix.
_SMOKE = [
    ([[5, 9, 2, 7]], 6, 0.0, None, 0),
    ([_PREFIX + [21, 22]], 5, 0.9, 5, 11),
    ([_PREFIX + [31]], 5, 0.0, None, 0),
]


def test_sharded_paged_token_equality_smoke(devices8, model_and_params,
                                            mesh_and_sharded):
    """Tier-1 tripwire for the slow matrix above: sharded-paged ==
    unsharded-paged == sharded-synchronous on seeded top-k + shared
    prefixes, and the sharded arm really shards (pool split 4 ways,
    pool leaves spanning devices).  Pipelining and sharding compose:
    the third arm is the sharded engine with the pipeline off."""
    model, params = model_and_params
    mesh, sharded = mesh_and_sharded

    def run(sched):
        pending = [
            sched.submit(rows, max_new_tokens=n, temperature=t, top_k=k,
                         seed=s, eos_token=None)
            for rows, n, t, k, s in _SMOKE
        ]
        out = [p.result() for p in pending]
        sched.stop()
        return out

    baseline = run(_paged(model, params))
    spmd = _paged(model, sharded, mesh=mesh)
    assert spmd.pool_shards == 4
    got = run(spmd)
    assert got == baseline
    pool_leaf = next(x for x in jax.tree.leaves(spmd._cache)
                     if getattr(x, "ndim", 0) >= 3)
    assert len(pool_leaf.sharding.device_set) >= 4
    sync = _paged(model, sharded, mesh=mesh, pipeline=False)
    assert sync.pipeline is False
    assert run(sync) == baseline


def test_sharded_pool_pages_round_up_to_shards(devices8, model_and_params,
                                               mesh_and_sharded):
    """num_pages rounds UP to a multiple of the pool shard count so
    shard boundaries land on page boundaries — never down (capacity is a
    promise submit() already validated against)."""
    model, _ = model_and_params
    mesh, sharded = mesh_and_sharded
    sched = _paged(model, sharded, mesh=mesh, num_pages=33)
    assert sched.pool_shards == 4
    assert sched.num_pages == 36
    assert sched.stats()["pages_total"] == 36
    assert sched.pool_positions == 36 * sched.page_len
    sched.stop()


def test_tp_only_mesh_is_replicated_not_fallback(devices8,
                                                 model_and_params):
    """A mesh with no data axes (tp/sp only) has nothing to split the
    pool over: the pool replicates (pool_shards == 1) and the paged
    engine still serves — this is NOT a fixed-pool fallback."""
    model, params = model_and_params
    mesh = parse_mesh("tp=2,sp=4", 8)
    sharded = shard_params(params, mesh, rules_for_model(model))
    sched = _paged(model, sharded, mesh=mesh)
    assert sched.pool_shards == 1
    # Construction-level on purpose: compiling prefill+decode for a
    # second mesh costs ~10 s of tier-1 budget, and shards=1 runs the
    # EXACT decode path the smoke above already pins.  With no data
    # axis there is no page NamedSharding at all — GSPMD replicates the
    # pool at dispatch — and the engine is still the paged one.
    assert sched._page_ns is None
    sched._ensure_pool()
    assert sched._cache is not None
    assert sched.stats()["pool_shards"] == 1
    assert sched.stats()["pages_total"] == sched.num_pages
    sched.stop()


def test_pipeline_env_knob_and_stats(model_and_params, monkeypatch):
    """KFT_SERVE_PIPELINE=0 pins the synchronous loop; stats() reports
    the pipeline flag and the dispatch-overlap accounting either way."""
    model, params = model_and_params
    monkeypatch.setenv("KFT_SERVE_PIPELINE", "0")
    sched = _paged(model, params)
    assert sched.pipeline is False
    monkeypatch.delenv("KFT_SERVE_PIPELINE")
    on = _paged(model, params)
    assert on.pipeline is True
    got = on.submit([[1, 2, 3]], max_new_tokens=6,
                    eos_token=None).result()
    st = on.stats()
    on.stop()
    sched.stop()
    assert st["pipeline"] is True
    assert st["dispatch_cycle_s"] >= st["dispatch_blocked_s"] >= 0.0
    assert 0.0 <= st["dispatch_overlap_ratio"] <= 1.0
    assert len(got[0]) == 6


def test_fallback_env_disabled_is_recorded(model_and_params, monkeypatch):
    """KFT_SERVE_PAGED=0 still pins the fixed pool, but no longer
    silently: the reason lands on the service, in the counter, and on
    /debug/serve."""
    model, params = model_and_params
    monkeypatch.setenv("KFT_SERVE_PAGED", "0")
    svc = GenerationService(model, params)
    client = Client(create_app(svc, model_name="m"))
    sched = svc._scheduler_or_none()
    assert isinstance(sched, DecodeScheduler)
    assert not isinstance(sched, PagedDecodeScheduler)
    assert svc.scheduler_fallback["reason"] == "env-disabled"
    text = client.get("/metrics").get_data(as_text=True)
    assert ('serve_paged_fallback_total{reason="env-disabled"} 1.0'
            in text)
    body = client.get("/debug/serve").get_json()
    assert body["engine"] == "DecodeScheduler"
    assert body["paged_fallback"]["reason"] == "env-disabled"
    assert body["mesh"] is None
    assert "KFT_SERVE_PAGED" in body["knobs"]


def test_fallback_spec_decode_mesh_is_recorded(devices8, model_and_params,
                                               mesh_and_sharded):
    """Draft model + mesh is the one remaining structural fallback: the
    fixed pool serves the mesh, the draft is inert, and the reason is
    recorded instead of a crash or a silent drop."""
    model, params = model_and_params
    mesh, sharded = mesh_and_sharded
    svc = GenerationService(model, sharded, mesh=mesh, draft_model=model,
                            draft_params=params)
    client = Client(create_app(svc, model_name="m"))
    sched = svc._scheduler_or_none()
    assert isinstance(sched, DecodeScheduler)
    assert not isinstance(sched, PagedDecodeScheduler)
    assert svc.scheduler_fallback["reason"] == "spec-decode-mesh"
    text = client.get("/metrics").get_data(as_text=True)
    assert ('serve_paged_fallback_total{reason="spec-decode-mesh"} 1.0'
            in text)


def test_debug_serve_reports_paged_engine(devices8, model_and_params,
                                          mesh_and_sharded):
    """The happy path on /debug/serve: paged engine, no fallback, pool
    shard count and mesh shape visible."""
    model, _ = model_and_params
    mesh, sharded = mesh_and_sharded
    svc = GenerationService(model, sharded, mesh=mesh)
    client = Client(create_app(svc, model_name="m"))
    assert svc.generate([[5, 9, 2, 7]], max_new_tokens=4)
    body = client.get("/debug/serve").get_json()
    assert body["engine"] == "PagedDecodeScheduler"
    assert body["paged_fallback"] is None
    assert body["mesh"]["fsdp"] == 4 and body["mesh"]["tp"] == 2
    assert body["scheduler"]["pool_shards"] == 4
    assert body["scheduler"]["pipeline"] is True
    text = client.get("/metrics").get_data(as_text=True)
    assert "serve_page_pool_shards 4.0" in text
    assert "serve_dispatch_overlap_ratio" in text
