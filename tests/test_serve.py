"""Generation server: HTTP surface over models/generate.py."""
import dataclasses

import jax
import jax.numpy as jnp
import pytest
from werkzeug.test import Client

from kubeflow_tpu.models.generate import generate
from kubeflow_tpu.models.llama import CONFIGS, Llama
from kubeflow_tpu.models.serve import GenerationService, create_app


@pytest.fixture(scope="module")
def service():
    cfg = dataclasses.replace(CONFIGS["llama_debug"], max_seq_len=64)
    model = Llama(cfg)
    tokens = jnp.ones((1, 8), jnp.int32)
    params = model.init(jax.random.key(0), tokens)["params"]
    return GenerationService(model, params)


@pytest.fixture
def client(service):
    return Client(create_app(service, model_name="llama_debug"))


def test_healthz_and_model_info(client):
    assert client.get("/healthz").status_code == 200
    info = client.get("/v1/model").get_json()
    assert info["model"] == "llama_debug"
    assert info["config"]["vocab_size"] == 256


def test_generate_matches_library_call(client, service):
    rows = [[5, 9, 2, 7]]
    resp = client.post("/v1/generate", json={
        "tokens": rows, "max_new_tokens": 6, "temperature": 0.0,
    })
    assert resp.status_code == 200
    got = resp.get_json()["tokens"]
    want = generate(
        service.model, service.params, jnp.array(rows, jnp.int32),
        max_new_tokens=6, temperature=0.0,
    )
    assert got == jax.device_get(want).tolist()


def test_generate_mixed_length_batch(client):
    resp = client.post("/v1/generate", json={
        "tokens": [[5, 9], [7, 1, 4, 8]], "max_new_tokens": 4,
    })
    assert resp.status_code == 200
    out = resp.get_json()["tokens"]
    assert len(out) == 2 and all(len(r) == 4 for r in out)


def test_generate_validation_errors(client):
    for body in (
        {},                                     # missing tokens
        {"tokens": []},                         # empty batch
        {"tokens": [[]]},                       # empty row
        {"tokens": [[999999]]},                 # out-of-vocab token
        {"tokens": [["x"]]},                    # non-int token
        {"tokens": [[1]], "max_new_tokens": 0},     # zero budget
        {"tokens": [[1]], "max_new_tokens": "abc"},  # non-int budget
        {"tokens": [[1]], "temperature": None},      # null coercion
        {"tokens": [[1]], "seed": [1]},              # bad seed type
        {"tokens": [[1]], "top_k": 0},               # zero top_k
        {"tokens": [[1]], "eos_token": True},        # bool eos sentinel
    ):
        resp = client.post("/v1/generate", json=body)
        assert resp.status_code == 400, body


def test_serve_from_checkpoint(tmp_path):
    import optax

    from kubeflow_tpu.models.serve import load_service
    from kubeflow_tpu.train import create_train_state, make_lm_train_step
    from kubeflow_tpu.train.loop import LoopConfig, train_loop

    # Train a couple of steps, checkpoint, then serve from the checkpoint.
    cfg = dataclasses.replace(CONFIGS["llama_debug"], max_seq_len=64)
    model = Llama(cfg)
    tokens = jnp.ones((2, 16), jnp.int32)
    state = create_train_state(
        jax.random.key(0), model, tokens, optax.adamw(1e-3)
    )
    step = jax.jit(make_lm_train_step())
    batch = jax.random.randint(jax.random.key(1), (2, 16), 0, 256)
    state, _ = train_loop(
        state, step, iter([batch, batch, batch]),
        LoopConfig(total_steps=3, log_every=0,
                   checkpoint_dir=str(tmp_path), checkpoint_every=1),
    )
    svc = load_service("llama_debug", checkpoint_dir=str(tmp_path),
                       max_seq_len=64)
    leaf_trained = jax.tree_util.tree_leaves(state.params)[0]
    leaf_served = jax.tree_util.tree_leaves(svc.params)[0]
    assert jnp.allclose(leaf_trained, leaf_served, atol=1e-6)
    out = svc.generate([[5, 9, 2]], max_new_tokens=3)
    assert len(out[0]) == 3


def test_serve_draft_model_plumbing_and_validation():
    """--draft-model (ISSUE 17): load_service builds a same-vocab draft
    pair that the paged scheduler consumes for speculative decoding, and
    rejects incompatible drafts at startup, not on the first step."""
    from kubeflow_tpu.models.paged import PagedDecodeScheduler
    from kubeflow_tpu.models.serve import create_app, load_service

    svc = load_service("llama_debug", max_seq_len=64,
                       draft_model_name="llama_debug")
    assert svc.draft_model is not None and svc.draft_params is not None
    create_app(svc, model_name="llama_debug")  # attaches telemetry
    sched = svc._scheduler_or_none()
    try:
        assert isinstance(sched, PagedDecodeScheduler)
        assert sched.draft_model is svc.draft_model
        assert sched.draft_params is svc.draft_params
    finally:
        if sched is not None:
            sched.stop()

    # A seq2seq draft can't propose into a decoder-only token stream.
    with pytest.raises(ValueError, match="needs a decoder-only draft"):
        load_service("llama_debug", max_seq_len=64,
                     draft_model_name="t5_debug")
    # And seq2seq targets never route through the paged scheduler.
    with pytest.raises(ValueError, match="decoder-only serving"):
        load_service("t5_debug", draft_model_name="llama_debug")


def test_serve_seq2seq_model():
    """T5 serving: `tokens` rows are sources, response is the generated
    target — same HTTP contract, routed to the seq2seq service."""
    from werkzeug.test import Client

    from kubeflow_tpu.models.generate import generate_seq2seq
    from kubeflow_tpu.models.serve import (
        Seq2SeqGenerationService,
        create_app,
        load_service,
    )

    svc = load_service("t5_debug")
    assert isinstance(svc, Seq2SeqGenerationService)
    client = Client(create_app(svc, model_name="t5_debug"))
    rows = [[5, 9, 2, 7], [3, 4]]
    resp = client.post("/v1/generate", json={
        "tokens": rows, "max_new_tokens": 6,
    })
    assert resp.status_code == 200, resp.get_data(as_text=True)
    got = resp.get_json()["tokens"]
    src = jnp.array([[5, 9, 2, 7], [3, 4, 0, 0]], jnp.int32)
    mask = jnp.array([[1, 1, 1, 1], [1, 1, 0, 0]], bool)
    want = generate_seq2seq(
        svc.model, svc.params, src, source_mask=mask, max_new_tokens=6,
    )
    assert got == jax.device_get(want).tolist()


def test_serve_seq2seq_request_limits():
    from werkzeug.test import Client

    from kubeflow_tpu.models.serve import create_app, load_service

    svc = load_service("t5_debug")
    client = Client(create_app(svc, model_name="t5_debug"))
    r = client.post("/v1/generate", json={
        "tokens": [[5]], "max_new_tokens": 100_000_000,
    })
    assert r.status_code == 400
    assert "limit" in r.get_json()["log"]
    r = client.post("/v1/generate", json={
        "tokens": [list(range(1, 60)) * 100], "max_new_tokens": 2,
    })
    assert r.status_code == 400
    # Batch-axis limit: memory scales with rows too.
    r = client.post("/v1/generate", json={
        "tokens": [[5]] * 1000, "max_new_tokens": 2,
    })
    assert r.status_code == 400
    assert "rows" in r.get_json()["log"]


def test_serve_max_seq_len_rejected_for_seq2seq():
    import pytest as _pytest

    from kubeflow_tpu.models.serve import load_service

    with _pytest.raises(ValueError, match="max_seq_len"):
        load_service("t5_debug", max_seq_len=512)


def test_serve_seq2seq_int8():
    from werkzeug.test import Client

    from kubeflow_tpu.models.serve import create_app, load_service

    svc = load_service("t5_debug", quantize="int8")
    client = Client(create_app(svc, model_name="t5_debug"))
    resp = client.post("/v1/generate", json={
        "tokens": [[5, 9, 2]], "max_new_tokens": 4,
    })
    assert resp.status_code == 200, resp.get_data(as_text=True)
    assert len(resp.get_json()["tokens"][0]) == 4


def test_serve_metrics_endpoint(client):
    client.post("/v1/generate", json={"tokens": [[5, 9]], "max_new_tokens": 2})
    client.post("/v1/generate", json={"tokens": []})  # invalid
    text = client.get("/metrics").get_data(as_text=True)
    assert 'generate_requests_total{outcome="ok"} 1.0' in text
    assert 'generate_requests_total{outcome="invalid"} 1.0' in text
    assert "generate_tokens_total 2.0" in text
    assert "generate_request_seconds_bucket" in text


def test_readyz_runs_and_caches_warm_generate(service):
    """/readyz (ISSUE 12): the first probe runs a REAL one-token warm
    generate() and reports its wall time; later probes serve the cached
    result — the controller's rolling update gets an honest signal
    without paying a generate per probe."""
    calls = []
    orig = service.generate

    def counting(*args, **kwargs):
        calls.append((args, kwargs))
        return orig(*args, **kwargs)

    service.generate = counting
    try:
        c = Client(create_app(service, model_name="llama_debug"))
        resp = c.get("/readyz")
        assert resp.status_code == 200, resp.get_data(as_text=True)
        body = resp.get_json()
        assert body["ready"] is True
        assert body["warm_generate_seconds"] >= 0
        assert len(calls) == 1
        assert calls[0][1]["max_new_tokens"] == 1
        # Cached: a second probe generates nothing.
        assert c.get("/readyz").status_code == 200
        assert len(calls) == 1
    finally:
        service.generate = orig


def test_readyz_reports_failure_and_retries(service):
    """A failing warm generate is a 503 with the error (not a 500 stack
    dump), and is retried — a transient fault must not wedge readiness
    at permanently-unready."""
    orig = service.generate
    state = {"fail": True}

    def flaky(*args, **kwargs):
        if state["fail"]:
            raise RuntimeError("device lost")
        return orig(*args, **kwargs)

    service.generate = flaky
    try:
        c = Client(create_app(service, model_name="llama_debug"))
        resp = c.get("/readyz")
        assert resp.status_code == 503
        assert "device lost" in resp.get_data(as_text=True)
        state["fail"] = False
        assert c.get("/readyz").status_code == 200
    finally:
        service.generate = orig


def test_serve_replica_revision_metric(service, monkeypatch):
    """/metrics exposes serve_replica_revision (the rollout tests' probe):
    explicit argument wins, else KFT_SERVE_REVISION, else 0."""
    c = Client(create_app(service, model_name="llama_debug", revision=7))
    assert "serve_replica_revision 7.0" in \
        c.get("/metrics").get_data(as_text=True)
    monkeypatch.setenv("KFT_SERVE_REVISION", "3")
    c2 = Client(create_app(service, model_name="llama_debug"))
    assert "serve_replica_revision 3.0" in \
        c2.get("/metrics").get_data(as_text=True)
    assert c2.get("/readyz").get_json()["revision"] == 3


def test_serve_spmd_mesh_matches_single_device(devices8):
    """--mesh serving: params sharded tensor-parallel over the mesh produce
    the same tokens as the unsharded service."""
    from kubeflow_tpu.models.serve import load_service

    plain = load_service("llama_debug", max_seq_len=64)
    spmd = load_service("llama_debug", max_seq_len=64, mesh_spec="tp=2,fsdp=4")
    rows = [[5, 9, 2, 7]]
    a = plain.generate(rows, max_new_tokens=6)
    b = spmd.generate(rows, max_new_tokens=6)
    assert a == b
    # Params really are distributed.
    import jax

    leaf = jax.tree.leaves(spmd.params)[0]
    assert len(leaf.sharding.device_set) > 1


def test_serve_spmd_restores_checkpoint_sharded(devices8, tmp_path):
    """--mesh + --checkpoint-dir restores DIRECTLY into the sharded layout
    (no replicated staging) and serves the same tokens as unsharded."""
    import dataclasses

    import optax

    from kubeflow_tpu.models.llama import CONFIGS, Llama
    from kubeflow_tpu.models.serve import load_service
    from kubeflow_tpu.train import create_train_state, make_lm_train_step
    from kubeflow_tpu.train.loop import LoopConfig, train_loop

    cfg = dataclasses.replace(CONFIGS["llama_debug"], max_seq_len=64)
    model = Llama(cfg)
    state = create_train_state(
        jax.random.key(0), model, jnp.ones((2, 64), jnp.int32),
        optax.adamw(1e-3),
    )
    step = jax.jit(make_lm_train_step())

    def batches():
        while True:
            yield jax.random.randint(jax.random.key(1), (2, 64), 0, 256)

    train_loop(state, step, batches(), LoopConfig(
        total_steps=2, log_every=0, checkpoint_dir=str(tmp_path),
        checkpoint_every=1,
    ))
    plain = load_service("llama_debug", max_seq_len=64,
                         checkpoint_dir=str(tmp_path))
    spmd = load_service("llama_debug", max_seq_len=64,
                        checkpoint_dir=str(tmp_path), mesh_spec="tp=2,fsdp=4")
    leaf = jax.tree.leaves(spmd.params)[0]
    assert len(leaf.sharding.device_set) > 1
    rows = [[5, 9, 2]]
    assert plain.generate(rows, max_new_tokens=4) == spmd.generate(
        rows, max_new_tokens=4
    )


def test_serve_mesh_rejects_unsupported_combos():
    from kubeflow_tpu.models.serve import load_service

    with pytest.raises(ValueError, match="quantize"):
        load_service("llama_debug", max_seq_len=64, quantize="int8",
                     mesh_spec="tp=2")


def test_serve_spmd_seq2seq_matches_single_device(devices8):
    """T5 under --mesh: params sharded by t5_rules, same generations."""
    from kubeflow_tpu.models.serve import load_service

    plain = load_service("t5_debug")
    spmd = load_service("t5_debug", mesh_spec="tp=2,fsdp=4")
    rows = [[5, 9, 2, 7]]
    assert plain.generate(rows, max_new_tokens=5) == spmd.generate(
        rows, max_new_tokens=5
    )
    leaf = jax.tree.leaves(spmd.params)[0]
    assert len(leaf.sharding.device_set) > 1


def test_serve_missing_checkpoint_raises(tmp_path):
    from kubeflow_tpu.models.serve import load_service

    with pytest.raises(FileNotFoundError):
        load_service("llama_debug", checkpoint_dir=str(tmp_path / "none"),
                     max_seq_len=64)


def test_token_rows_reject_bools(client):
    """JSON true is an int subclass in Python — it must 400, not silently
    become token 1 (ADVICE r1)."""
    resp = client.post("/v1/generate", json={"tokens": [[True, 2]]})
    assert resp.status_code == 400


def test_serve_telemetry_series_and_traces(client):
    """ISSUE 6 acceptance: /metrics exposes queue-depth, batch-occupancy,
    TTFT, and per-token-latency series after a generate, and
    /debug/traces returns a per-request span tree
    (admit → queue → prefill → decode)."""
    resp = client.post("/v1/generate", json={
        "tokens": [[5, 9], [7, 1, 4]], "max_new_tokens": 4,
    })
    assert resp.status_code == 200
    text = client.get("/metrics").get_data(as_text=True)
    assert "serve_queue_depth 0.0" in text  # gauge exists, drained
    assert "serve_batch_rows_bucket" in text
    assert "serve_batch_fill_ratio_bucket" in text
    assert "serve_time_to_first_token_seconds_count 1.0" in text
    assert "serve_per_token_seconds_count 1.0" in text
    assert "serve_input_tokens_total 5.0" in text  # 2 + 3 prompt tokens
    assert "serve_output_tokens_total 8.0" in text  # 2 rows x 4, no EOS

    body = client.get("/debug/traces").get_json()
    traces = body["traces"]
    assert traces, body
    tr = traces[-1]
    assert tr["component"] == "llama_debug"
    assert tr["request"].startswith("req-")
    assert tr["result"] == "ok"
    names = [s["name"] for s in tr["spans"]]
    assert names == ["admit", "queue", "prefill", "decode"]
    # ?n= bounds the response (same contract as the controllers').
    client.post("/v1/generate", json={"tokens": [[5]], "max_new_tokens": 2})
    assert len(client.get("/debug/traces?n=1").get_json()["traces"]) == 1


def test_serve_debug_traces_honors_opt_out(monkeypatch, service):
    """DEBUG_TRACES=false 404s the serve traces endpoint — same opt-out
    contract as the controllers' health port (both are unauthenticated)."""
    from kubeflow_tpu.models.serve import create_app as mk_app

    monkeypatch.setenv("DEBUG_TRACES", "false")
    c = Client(mk_app(service, model_name="llama_debug"))
    assert c.get("/debug/traces").status_code == 404
    # Metrics stay up; only the trace bodies are gated.
    assert c.get("/metrics").status_code == 200


def test_serve_trace_records_invalid_requests(client):
    client.post("/v1/generate", json={"tokens": [[999999]]})
    traces = client.get("/debug/traces").get_json()["traces"]
    assert traces and traces[-1]["result"] == "error"


def test_serve_two_phase_matches_one_shot_generate(service):
    """The instrumented service path (generate_prefill + generate_decode)
    must produce EXACTLY the one-shot generate()'s tokens — the split is
    a jit boundary, not a semantic change (shared _prefill_parts/
    _decode_scan in models/generate.py)."""
    from kubeflow_tpu.models.serve import create_app as mk_app

    mk_app(service, model_name="llama_debug")  # attaches telemetry
    rows = [[5, 9, 2], [4, 4, 4, 4]]
    got = service.generate(rows, max_new_tokens=5, temperature=0.7,
                           top_k=7, seed=3)
    want = generate(
        service.model, service.params,
        jnp.array([[5, 9, 2, 0], [4, 4, 4, 4]], jnp.int32),
        prompt_mask=jnp.array([[1, 1, 1, 0], [1, 1, 1, 1]], bool),
        max_new_tokens=5, temperature=0.7, top_k=7,
        rng=jax.random.key(3),
    )
    assert got == jax.device_get(want).tolist()


def test_serve_metrics_over_http_transport(service):
    """E2E over a real socket (the acceptance wording): generate via
    HTTP, then scrape /metrics and /debug/traces from the same server."""
    import json as _json
    import urllib.request

    from kubeflow_tpu.models.serve import create_app as mk_app

    app = mk_app(service, model_name="llama_debug")
    server, base = app.test_server()
    try:
        req = urllib.request.Request(
            base + "/v1/generate",
            data=_json.dumps({"tokens": [[5, 9, 2]],
                              "max_new_tokens": 3}).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            out = _json.loads(resp.read())
        assert len(out["tokens"][0]) == 3
        with urllib.request.urlopen(base + "/metrics", timeout=10) as resp:
            text = resp.read().decode()
        assert "serve_time_to_first_token_seconds_count 1.0" in text
        assert "serve_queue_depth 0.0" in text
        with urllib.request.urlopen(base + "/debug/traces",
                                    timeout=10) as resp:
            traces = _json.loads(resp.read())["traces"]
        assert len(traces) >= 1
        assert [s["name"] for s in traces[-1]["spans"]] == [
            "admit", "queue", "prefill", "decode"]
    finally:
        server.shutdown()


def test_tokens_total_excludes_post_eos_padding():
    """generate() right-pads finished rows with EOS; the throughput counter
    counts through the first EOS only (ADVICE r1)."""

    class StubService:
        default_eos_token = None

        class model:
            class cfg:
                vocab_size = 256

        def generate(self, rows, **kw):
            return [[5, 7, 9, 9], [1, 2, 3, 4]]

    c = Client(create_app(StubService(), model_name="stub"))
    resp = c.post("/v1/generate",
                  json={"tokens": [[1], [1]], "eos_token": 9})
    assert resp.status_code == 200
    text = c.get("/metrics").get_data(as_text=True)
    # Row 0 counts through its first EOS (3 tokens); row 1 never hit EOS
    # (all 4 count): 7 total, not the 8 raw slots.
    assert "generate_tokens_total 7.0" in text


# -- the front-door contract on the replica side (ISSUE 19) -------------------


def test_generate_503_while_warm_probe_inflight(service, monkeypatch):
    """A not-yet-warm replica answers /v1/generate with a structured
    503 + Retry-After WHILE the /readyz warm generate is in flight —
    instead of silently queueing the request behind a multi-second
    compile.  Once readiness flips, the same request serves 200."""
    import threading

    from kubeflow_tpu.models.serve import create_app as mk_app

    started, gate = threading.Event(), threading.Event()
    real = service.generate

    def slow_generate(*a, **kw):
        started.set()
        assert gate.wait(30)
        return real(*a, **kw)

    monkeypatch.setattr(service, "generate", slow_generate)
    c = Client(mk_app(service, model_name="llama_debug"))
    probe = threading.Thread(target=lambda: c.get("/readyz"))
    probe.start()
    try:
        assert started.wait(30)  # the warm generate is now in flight
        resp = c.post("/v1/generate",
                      json={"tokens": [[5, 9]], "max_new_tokens": 2})
        assert resp.status_code == 503
        assert resp.headers["Retry-After"] == "2"
        body = resp.get_json()
        assert body["success"] is False and "not warm" in body["log"]
    finally:
        gate.set()
        probe.join(30)
    # Warm now (and cached): the replay lands.
    resp = c.post("/v1/generate",
                  json={"tokens": [[5, 9]], "max_new_tokens": 2})
    assert resp.status_code == 200


def test_generate_deadline_expired_is_504_never_run(client, service):
    """X-KFT-Deadline-Seconds already spent on arrival: a structured 504
    without touching the device — the activator never replays a 504."""
    resp = client.post(
        "/v1/generate",
        json={"tokens": [[5, 9]], "max_new_tokens": 2},
        headers={"X-KFT-Deadline-Seconds": "-0.5"})
    assert resp.status_code == 504
    assert "expired" in resp.get_json()["log"]
    # A generous budget serves normally.
    resp = client.post(
        "/v1/generate",
        json={"tokens": [[5, 9]], "max_new_tokens": 2},
        headers={"X-KFT-Deadline-Seconds": "60"})
    assert resp.status_code == 200


def test_generate_qos_header_validation(client):
    ok = client.post("/v1/generate",
                     json={"tokens": [[5, 9]], "max_new_tokens": 2},
                     headers={"X-KFT-Priority": "interactive"})
    assert ok.status_code == 200
    bad_prio = client.post("/v1/generate",
                           json={"tokens": [[5, 9]], "max_new_tokens": 2},
                           headers={"X-KFT-Priority": "urgent"})
    assert bad_prio.status_code == 400
    assert "priority class" in bad_prio.get_json()["log"]
    bad_deadline = client.post(
        "/v1/generate", json={"tokens": [[5, 9]], "max_new_tokens": 2},
        headers={"X-KFT-Deadline-Seconds": "soon"})
    assert bad_deadline.status_code == 400


def test_generate_rejections_counted_by_reason(client):
    client.post("/v1/generate",
                json={"tokens": [[5, 9]], "max_new_tokens": 2},
                headers={"X-KFT-Deadline-Seconds": "-1"})
    text = client.get("/metrics").get_data(as_text=True)
    assert 'generate_rejected_total{reason="deadline"}' in text
