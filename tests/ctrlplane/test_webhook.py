import base64
import json

import pytest

from kubeflow_tpu.platform.apis.poddefault import tpu_pod_default
from kubeflow_tpu.platform.webhook.jsonpatch import apply_patch, create_patch
from kubeflow_tpu.platform.webhook.mutate import (
    EXCLUDE_ANNOTATION,
    MergeConflict,
    apply_pod_defaults,
    filter_pod_defaults,
    mutate_admission_review,
    safe_to_apply,
)


def make_pod(labels=None, annotations=None):
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": "nb-0", "namespace": "user1",
            "labels": labels or {}, "annotations": annotations or {},
        },
        "spec": {
            "containers": [
                {"name": "notebook", "image": "jupyter", "env": []},
                {"name": "istio-proxy", "image": "proxy"},
            ],
        },
    }


def make_pd(name="pd", selector=None, **spec):
    return {
        "apiVersion": "kubeflow.org/v1alpha1",
        "kind": "PodDefault",
        "metadata": {"name": name, "namespace": "user1", "resourceVersion": "7"},
        "spec": {"selector": selector or {"matchLabels": {"use-pd": "true"}}, **spec},
    }


def test_selector_filtering():
    pod = make_pod(labels={"use-pd": "true"})
    pds = [make_pd("a"), make_pd("b", selector={"matchLabels": {"other": "x"}})]
    assert [p["metadata"]["name"] for p in filter_pod_defaults(pds, pod)] == ["a"]


def test_match_expressions():
    pod = make_pod(labels={"tier": "gold"})
    pd = make_pd(selector={"matchExpressions": [
        {"key": "tier", "operator": "In", "values": ["gold", "silver"]},
        {"key": "legacy", "operator": "DoesNotExist"},
    ]})
    assert filter_pod_defaults([pd], pod)
    pod2 = make_pod(labels={"tier": "bronze"})
    assert not filter_pod_defaults([pd], pod2)


def test_exclusion_annotation():
    pod = make_pod(labels={"use-pd": "true"},
                   annotations={EXCLUDE_ANNOTATION: "true"})
    assert filter_pod_defaults([make_pd()], pod) == []


def test_env_and_volume_merge_skips_istio_proxy():
    pod = make_pod(labels={"use-pd": "true"})
    pd = make_pd(
        env=[{"name": "FOO", "value": "1"}],
        volumes=[{"name": "v", "emptyDir": {}}],
        volumeMounts=[{"name": "v", "mountPath": "/v"}],
    )
    out = apply_pod_defaults(pod, [pd])
    notebook = out["spec"]["containers"][0]
    istio = out["spec"]["containers"][1]
    assert {"name": "FOO", "value": "1"} in notebook["env"]
    assert notebook["volumeMounts"] == [{"name": "v", "mountPath": "/v"}]
    assert "volumeMounts" not in istio and "env" not in istio
    assert out["spec"]["volumes"] == [{"name": "v", "emptyDir": {}}]
    assert out["metadata"]["annotations"][
        "poddefault.admission.kubeflow.org/poddefault-pd"
    ] == "7"


def test_identical_collision_ok_different_conflicts():
    pod = make_pod(labels={"use-pd": "true"})
    pod["spec"]["containers"][0]["env"] = [{"name": "FOO", "value": "1"}]
    ok_pd = make_pd(env=[{"name": "FOO", "value": "1"}])
    assert safe_to_apply(pod, [ok_pd]) is None
    bad_pd = make_pd(env=[{"name": "FOO", "value": "2"}])
    msg = safe_to_apply(pod, [bad_pd])
    assert msg and "FOO" in msg
    with pytest.raises(MergeConflict):
        apply_pod_defaults(pod, [bad_pd])


def test_command_only_if_unset():
    pod = make_pod(labels={"use-pd": "true"})
    pd = make_pd(command=["run.sh"], args=["--fast"])
    out = apply_pod_defaults(pod, [pd])
    assert out["spec"]["containers"][0]["command"] == ["run.sh"]
    pod2 = make_pod(labels={"use-pd": "true"})
    pod2["spec"]["containers"][0]["command"] = ["mine.sh"]
    out2 = apply_pod_defaults(pod2, [pd])
    assert out2["spec"]["containers"][0]["command"] == ["mine.sh"]


def test_sidecar_and_tolerations():
    pod = make_pod(labels={"use-pd": "true"})
    pd = make_pd(
        sidecars=[{"name": "logger", "image": "fluentd"}],
        tolerations=[{"key": "google.com/tpu", "operator": "Exists",
                      "effect": "NoSchedule"}],
    )
    out = apply_pod_defaults(pod, [pd])
    assert any(c["name"] == "logger" for c in out["spec"]["containers"])
    assert out["spec"]["tolerations"][0]["key"] == "google.com/tpu"


def test_tpu_pod_default_injects_runtime_env():
    pod = make_pod(labels={"tpu-v5e": "true"})
    pd = tpu_pod_default("user1", "v5e", "2x4")
    out = apply_pod_defaults(pod, [pd])
    env = {e["name"]: e.get("value") for e in out["spec"]["containers"][0]["env"]}
    assert env["TPU_TOPOLOGY"] == "2x4"
    assert env["TPU_ACCELERATOR_TYPE"] == "v5e-8"
    mounts = out["spec"]["containers"][0]["volumeMounts"]
    assert {"name": "tpu-shm", "mountPath": "/dev/shm"} in mounts


def _tpujob_worker_pod():
    """A TPUJob-shaped worker pod exactly as the controller generates it:
    template labels (incl. the tpujob-worker selector label) + the
    injected TPU_*/MEGASCALE_* env."""
    from kubeflow_tpu.platform.controllers.tpujob import TPUJobReconciler
    from kubeflow_tpu.platform.k8s.types import deep_get
    from kubeflow_tpu.platform.testing import FakeKube

    job = {
        "apiVersion": "kubeflow.org/v1alpha1", "kind": "TPUJob",
        "metadata": {"name": "train", "namespace": "user1"},
        "spec": {
            "tpu": {"accelerator": "v5e", "topology": "4x4", "slices": 2},
            "template": {"spec": {"containers": [
                {"name": "worker", "image": "trainer"}]}},
        },
    }
    sts = TPUJobReconciler(FakeKube()).generate_statefulset(
        job, slice_idx=1, generation=0)
    tmpl = deep_get(sts, "spec", "template")
    return {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "train-s1-0", "namespace": "user1",
                     "labels": dict(deep_get(tmpl, "metadata", "labels"))},
        "spec": deep_get(tmpl, "spec"),
    }


def _tpujob_manifest_pod_default():
    import pathlib

    import yaml

    path = (pathlib.Path(__file__).resolve().parents[2]
            / "manifests" / "tpujob-poddefault.yaml")
    with open(path) as f:
        return yaml.safe_load(f)


def test_tpujob_pod_default_layers_env_without_clobbering_megascale():
    """The shipped manifests/tpujob-poddefault.yaml applies to a
    controller-generated TPUJob worker pod: libtpu/JAX env layered on,
    the controller-injected MEGASCALE/TPU env byte-identical after the
    merge (ISSUE 10 satellite)."""
    pod = _tpujob_worker_pod()
    pd = _tpujob_manifest_pod_default()
    before = {e["name"]: e for e in pod["spec"]["containers"][0]["env"]}
    assert safe_to_apply(pod, [pd]) is None
    out = apply_pod_defaults(pod, [pd])
    env = {e["name"]: e for e in out["spec"]["containers"][0]["env"]}
    # Layered by the PodDefault...
    assert env["JAX_PLATFORMS"]["value"] == "tpu,cpu"
    assert env["TPU_PREMAPPED_BUFFER_SIZE"]["value"] == "17179869184"
    mounts = out["spec"]["containers"][0]["volumeMounts"]
    assert {"name": "tpu-shm", "mountPath": "/dev/shm"} in mounts
    # ...with every controller-injected variable untouched.
    for name in ("MEGASCALE_SLICE_ID", "MEGASCALE_NUM_SLICES",
                 "MEGASCALE_COORDINATOR_ADDRESS", "TPU_TOPOLOGY",
                 "TPU_WORKER_HOSTNAMES", "TPU_WORKER_ID"):
        assert env[name] == before[name], name
    anns = out["metadata"]["annotations"]
    assert any("tpujob-worker-runtime" in k for k in anns), anns


def test_tpujob_pod_default_megascale_conflict_is_rejected():
    """A PodDefault that names a controller-owned MEGASCALE variable with
    a DIFFERENT value must hit the MergeConflict path: the webhook skips
    the whole merge (pod admitted unmutated) instead of silently
    rewriting the gang's cross-slice identity."""
    pod = _tpujob_worker_pod()
    evil = {
        "apiVersion": "kubeflow.org/v1alpha1", "kind": "PodDefault",
        "metadata": {"name": "evil", "namespace": "user1",
                     "resourceVersion": "9"},
        "spec": {"selector": {"matchLabels": {"tpujob-worker": "true"}},
                 "env": [{"name": "MEGASCALE_NUM_SLICES", "value": "99"}]},
    }
    msg = safe_to_apply(pod, [evil])
    assert msg and "MEGASCALE_NUM_SLICES" in msg
    with pytest.raises(MergeConflict):
        apply_pod_defaults(pod, [evil])
    review = {"request": {
        "uid": "u-tpujob", "namespace": "user1",
        "resource": {"resource": "pods"}, "object": pod,
    }}
    out = mutate_admission_review(review, [evil])
    assert out["response"]["allowed"]
    assert "patch" not in out["response"]


def test_admission_review_roundtrip():
    pod = make_pod(labels={"use-pd": "true"})
    review = {
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "request": {
            "uid": "u1",
            "resource": {"group": "", "version": "v1", "resource": "pods"},
            "namespace": "user1",
            "object": pod,
        },
    }
    pd = make_pd(env=[{"name": "FOO", "value": "1"}])
    out = mutate_admission_review(review, [pd])
    resp = out["response"]
    assert resp["uid"] == "u1" and resp["allowed"]
    patch = json.loads(base64.b64decode(resp["patch"]))
    mutated = apply_patch(pod, patch)
    assert {"name": "FOO", "value": "1"} in mutated["spec"]["containers"][0]["env"]


def test_admission_review_conflict_allows_without_patch():
    pod = make_pod(labels={"use-pd": "true"})
    pod["spec"]["containers"][0]["env"] = [{"name": "FOO", "value": "mine"}]
    review = {"request": {
        "uid": "u2", "namespace": "user1",
        "resource": {"resource": "pods"}, "object": pod,
    }}
    out = mutate_admission_review(review, [make_pd(env=[{"name": "FOO", "value": "other"}])])
    assert out["response"]["allowed"]
    assert "patch" not in out["response"]


def test_jsonpatch_diff_apply_roundtrip():
    before = {"a": {"b": 1, "drop": 2}, "list": [1, 2], "keep": "x"}
    after = {"a": {"b": 9, "new": {"deep": True}}, "list": [1, 2, 3], "keep": "x"}
    patch = create_patch(before, after)
    assert apply_patch(before, patch) == after


def test_jsonpatch_escaping():
    before = {"metadata": {"annotations": {}}}
    after = {"metadata": {"annotations": {"a/b~c": "v"}}}
    patch = create_patch(before, after)
    assert apply_patch(before, patch) == after


def test_malformed_poddefault_fails_open_over_http():
    """A PodDefault with garbage fields must not 500 (which would block all
    pod admission under failurePolicy=Fail) — the server fails open."""
    import requests

    from kubeflow_tpu.platform.testing import FakeKube
    from kubeflow_tpu.platform.webhook.server import WebhookServer

    kube = FakeKube()
    kube.add_namespace("user1")
    bad = make_pd("bad")
    bad["spec"]["env"] = "not-a-list"
    kube.create(bad)
    srv = WebhookServer(kube, host="127.0.0.1", port=0)
    srv.start()
    try:
        review = {"request": {
            "uid": "u3", "namespace": "user1",
            "resource": {"resource": "pods"},
            "object": make_pod(labels={"use-pd": "true"}),
        }}
        r = requests.post(
            f"http://127.0.0.1:{srv.port}/apply-poddefault", json=review, timeout=10
        )
        assert r.status_code == 200
        resp = r.json()["response"]
        assert resp["allowed"] is True
        assert "patch" not in resp
        assert "skipped" in resp["status"]["message"]
    finally:
        srv.stop()


def test_webhook_tls_serving_and_live_cert_rotation(tmp_path):
    """The webhook serves admission over HTTPS and hot-reloads a rotated
    cert/key pair into the live listener — new handshakes present the new
    chain, old chains stop validating, no restart (reference certwatcher,
    admission-webhook/main.go:753-770)."""
    # Self-signed keygen is the one webhook path that needs the optional
    # ``cryptography`` package (certs.generate_self_signed imports it
    # lazily for the same reason); on images without it the TLS rotation
    # test skips instead of failing — cert-manager supplies pairs there.
    pytest.importorskip("cryptography")
    import json as _json
    import ssl
    import urllib.error
    import urllib.request

    from kubeflow_tpu.platform.testing import FakeKube
    from kubeflow_tpu.platform.webhook.certs import (
        generate_self_signed,
        write_pair,
    )
    from kubeflow_tpu.platform.webhook.server import WebhookServer

    kube = FakeKube()
    kube.add_namespace("user1")
    kube.create(make_pd("pd", env=[{"name": "A", "value": "1"}]))
    cert, key = write_pair(str(tmp_path), *generate_self_signed())
    srv = WebhookServer(kube, host="127.0.0.1", port=0,
                        cert_file=cert, key_file=key)
    srv.start()
    url = f"https://127.0.0.1:{srv.port}/apply-poddefault"
    review = _json.dumps({"request": {
        "uid": "u-tls", "namespace": "user1",
        "resource": {"resource": "pods"},
        "object": make_pod(labels={"use-pd": "true"}),
    }}).encode()

    def admit(ctx):
        req = urllib.request.Request(
            url, data=review, headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10, context=ctx) as r:
            return _json.load(r)["response"]

    try:
        old_ctx = ssl.create_default_context(cafile=cert)
        assert admit(old_ctx)["allowed"] is True
        # Plaintext clients are refused: admission is HTTPS-only.
        with pytest.raises(Exception):
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/apply-poddefault",
                data=review, timeout=5)

        # Rotate: new pair on disk, reload the live context.
        write_pair(str(tmp_path), *generate_self_signed())
        assert srv.reload_certs() is True
        assert srv.reload_certs() is False  # idempotent until next change

        new_ctx = ssl.create_default_context(cafile=cert)
        assert admit(new_ctx)["allowed"] is True
        with pytest.raises(urllib.error.URLError) as ei:
            admit(old_ctx)
        assert isinstance(ei.value.reason, ssl.SSLError)
    finally:
        srv.stop()


def test_fake_patch_strips_last_finalizer_deletes():
    from kubeflow_tpu.platform.k8s import errors as kerrors
    from kubeflow_tpu.platform.k8s.types import PROFILE
    from kubeflow_tpu.platform.testing import FakeKube

    kube = FakeKube()
    kube.create({"apiVersion": "kubeflow.org/v1", "kind": "Profile",
                 "metadata": {"name": "p", "finalizers": ["profile-finalizer"]}})
    kube.delete(PROFILE, "p")
    assert kube.get(PROFILE, "p")["metadata"]["deletionTimestamp"]
    kube.patch(PROFILE, "p", {"metadata": {"finalizers": None}})
    with pytest.raises(kerrors.NotFound):
        kube.get(PROFILE, "p")
