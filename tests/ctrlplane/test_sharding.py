"""Sharded HA control plane: hash assignment, lease coordination,
fencing, and the chaos matrix (replica kill / lease expiry / membership
churn) over the ShardedFleet harness.

The invariant under test everywhere: a key is reconciled — in particular
WRITTEN — by at most one replica at any instant, across processes,
enforced by shard-lease fencing (runtime/sharding.py) and asserted from
per-replica ChaosKube call logs joined against coordinator ownership
windows (ShardedFleet.assert_fencing_invariant).
"""
import threading
import time
from collections import Counter

import pytest

from kubeflow_tpu.platform.k8s.types import LEASE, NOTEBOOK
from kubeflow_tpu.platform.runtime.sharding import (
    FencedClient,
    FencingError,
    ShardCoordinator,
    set_current_request,
    shard_of,
    stable_key_hash,
)
from kubeflow_tpu.platform.testing import FakeKube
from kubeflow_tpu.platform.testing.shardfleet import ShardedFleet

# Fast lease timings for every test: failover bound = 0.5 s.
TTL = 0.5
RENEW = 0.05


def coordinator(kube, ident, **kw):
    kw.setdefault("num_shards", 8)
    kw.setdefault("lease_seconds", TTL)
    kw.setdefault("renew_seconds", RENEW)
    return ShardCoordinator(kube, identity=ident, **kw)


# -- hash-shard assignment (satellite: property tests) ------------------------


def test_hash_is_stable_across_process_restarts():
    """Pinned values: the shard map must survive interpreter restarts and
    version bumps — a drifting hash would turn every rollout into a
    full-keyspace reshuffle.  (Python's builtin hash() is salted per
    process; this is why sharding uses FNV-1a.)"""
    assert stable_key_hash("user1", "nb-0001") == 1726504714
    assert stable_key_hash("team-a", "my-notebook") == 266736693
    assert stable_key_hash("", "x") == 1473376466
    assert stable_key_hash("kubeflow", "tensorboard-1") == 2885168702
    assert shard_of("user1", "nb-0001", 8) == 2
    assert shard_of("team-a", "my-notebook", 8) == 5


@pytest.mark.parametrize("num_shards", [4, 8])
def test_hash_uniform_within_10pct_at_10k_keys(num_shards):
    counts = Counter(
        shard_of(f"ns{i % 7}", f"nb-{i:05d}", num_shards)
        for i in range(10_000)
    )
    expected = 10_000 / num_shards
    assert set(counts) == set(range(num_shards))
    for shard, n in counts.items():
        assert abs(n - expected) / expected < 0.10, (
            f"shard {shard}: {n} keys vs expected {expected:.0f}")


def test_every_key_owned_by_exactly_one_range():
    """Partition property: over a fleet-shaped synthetic keyspace, each
    key lands in exactly one shard, the per-shard key sets are disjoint,
    and their union is the whole keyspace."""
    keys = [(f"ns{i % 13}", f"nb-{i:05d}") for i in range(5_000)]
    buckets = {s: set() for s in range(8)}
    for ns, name in keys:
        s = shard_of(ns, name, 8)
        assert 0 <= s < 8
        buckets[s].add((ns, name))
    union = set()
    for s, bucket in buckets.items():
        assert not (union & bucket), "a key appeared in two shard ranges"
        union |= bucket
    assert union == set(keys)


# -- coordinator protocol ------------------------------------------------------


def kube_with_ns():
    kube = FakeKube()
    kube.add_namespace("kubeflow")
    return kube


def test_single_replica_acquires_everything():
    kube = kube_with_ns()
    a = coordinator(kube, "a")
    a._tick()
    assert sorted(a.owned()) == list(range(8))


def test_join_rebalance_sheds_to_fair_share():
    """A joining replica announces itself via its membership lease; the
    incumbent sheds its highest-numbered excess down to ceil(S/M) and the
    joiner acquires exactly the freed ranges."""
    kube = kube_with_ns()
    a, b = coordinator(kube, "a"), coordinator(kube, "b")
    a._tick()
    assert len(a.owned()) == 8
    b._tick()            # b registers membership; everything still held
    assert b.owned() == frozenset()
    a._tick()            # a sees M=2 -> fair=4 -> sheds 4..7
    assert sorted(a.owned()) == [0, 1, 2, 3]
    b._tick()
    assert sorted(b.owned()) == [4, 5, 6, 7]
    # Stable thereafter: no thrash.
    a._tick(), b._tick()
    assert sorted(a.owned()) == [0, 1, 2, 3]
    assert sorted(b.owned()) == [4, 5, 6, 7]


def test_graceful_stop_hands_over_immediately():
    kube = kube_with_ns()
    a, b = coordinator(kube, "a"), coordinator(kube, "b")
    a._tick(), b._tick(), a._tick(), b._tick()
    assert len(a.owned()) == 4 and len(b.owned()) == 4
    a.stop()             # releases leases: no TTL wait for the survivor
    b._tick()
    assert sorted(b.owned()) == list(range(8))


def test_crash_absorbed_after_ttl():
    kube = kube_with_ns()
    a, b = coordinator(kube, "a"), coordinator(kube, "b")
    a._tick(), b._tick(), a._tick(), b._tick()
    a.crash()            # no release: leases (and membership) age out
    b._tick()
    assert len(b.owned()) == 4, "no early takeover before the TTL"
    time.sleep(TTL + 0.1)
    b._tick()
    assert sorted(b.owned()) == list(range(8))


def test_fencing_token_bumps_on_ownership_change():
    kube = kube_with_ns()
    a = coordinator(kube, "a", num_shards=1)
    a._tick()
    assert a.fence_token(0) == 0  # fresh create: transition epoch 0
    a.crash()
    time.sleep(TTL + 0.05)
    b = coordinator(kube, "b", num_shards=1)
    b._tick()
    assert b.owns_shard(0)
    assert b.fence_token(0) == a.fence_token(0) + 1


def test_paused_replica_fences_itself_before_writing():
    """THE split-brain case: a paused-but-alive replica still believes it
    owns its shards; once the lease expired under it and a survivor took
    over, its next write must fence itself — confirm-renew fails against
    the moved lease, the shard drops, FencingError surfaces, and the
    write NEVER reaches the inner client."""
    kube = kube_with_ns()
    a, b = coordinator(kube, "a"), coordinator(kube, "b")
    a._tick()
    assert len(a.owned()) == 8
    calls = []

    class Recording:
        def __init__(self, inner):
            self._inner = inner

        def __getattr__(self, name):
            fn = getattr(self._inner, name)
            if name in ("create", "update", "update_status", "patch",
                        "patch_status", "delete"):
                def wrapped(*args, **kw):
                    calls.append(name)
                    return fn(*args, **kw)
                return wrapped
            return fn

    fenced = FencedClient(Recording(kube), a)
    # a pauses (GC stall / partition); past the TTL b absorbs everything.
    time.sleep(TTL + 0.1)
    b._tick()
    assert sorted(b.owned()) == list(range(8))
    assert a.owns_key("ns", "x"), "a still BELIEVES it owns the key"
    set_current_request(("ns", "x"))
    try:
        with pytest.raises(FencingError):
            fenced.patch_status(NOTEBOOK, "x", {"status": {}}, "ns")
    finally:
        set_current_request(None)
    assert calls == [], "the fenced write reached the wire"
    assert fenced.fenced_total == 1
    assert not a.owns_shard(shard_of("ns", "x", 8)), "shard not dropped"
    assert any(action == "fenced" for _, action, _, _ in a.ownership_log)


def test_stale_but_unclaimed_lease_confirms_and_writes():
    """The non-split-brain staleness: renewals stalled but NOBODY took
    the lease — one synchronous confirm-renew re-establishes ownership
    and the write proceeds (a lone replica must not fence itself into
    uselessness on every GC pause)."""
    kube = kube_with_ns()
    a = coordinator(kube, "a")
    a._tick()
    time.sleep(TTL + 0.1)  # stale, but no contender
    fenced = FencedClient(kube, a, log_writes=True)
    kube.add_namespace("ns")
    set_current_request(("ns", "x"))
    try:
        fenced.create({
            "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
            "metadata": {"name": "x", "namespace": "ns"},
            "spec": {},
        })
    finally:
        set_current_request(None)
    assert fenced.fenced_total == 0
    assert len(fenced.write_log) == 1
    assert fenced.write_log[0]["shard"] == shard_of("ns", "x", 8)


def test_writes_outside_reconcile_pass_unfenced():
    kube = kube_with_ns()
    a = coordinator(kube, "a")  # owns nothing: never ticked
    fenced = FencedClient(kube, a, log_writes=True)
    kube.add_namespace("ns")
    fenced.create({
        "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
        "metadata": {"name": "free", "namespace": "ns"}, "spec": {},
    })
    assert fenced.write_log[0].get("shard") is None


def test_flight_pool_carries_fence_context():
    """A reconcile's fanned-out secondary writes must fence on the SAME
    key as its inline writes: FlightPool.run captures the submitting
    thread's fence context onto its workers."""
    from kubeflow_tpu.platform.runtime.flight import FlightPool
    from kubeflow_tpu.platform.runtime import sharding

    pool = FlightPool(4, name="fence-test")
    seen = []
    set_current_request(("ns", "key-1"))
    try:
        pool.run([
            (lambda: seen.append(sharding.current_request()))
            for _ in range(3)
        ])
    finally:
        set_current_request(None)
    assert seen == [("ns", "key-1")] * 3


# -- informer shard filtering --------------------------------------------------


def test_informer_admit_filter_and_refilter():
    from kubeflow_tpu.platform.runtime.informer import Informer

    kube = FakeKube()
    kube.add_namespace("ns")
    for i in range(20):
        kube.create({
            "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
            "metadata": {"name": f"nb-{i:02d}", "namespace": "ns"},
            "spec": {},
        })
    owned = {0, 1}  # of 4

    def admit(obj):
        return shard_of("ns", obj["metadata"]["name"], 4) in owned

    deltas = []
    informer = Informer(kube, NOTEBOOK, admit=admit)
    informer.add_handler(lambda et, obj: deltas.append(
        (et, obj["metadata"]["name"])))
    informer.start()
    assert informer.wait_for_sync(5.0)
    in_range = {f"nb-{i:02d}" for i in range(20)
                if shard_of("ns", f"nb-{i:02d}", 4) in owned}
    assert {name for _, name in informer.keys("ns")} == in_range
    assert informer.events_seen >= 20
    assert informer.events_admitted < informer.events_seen
    # Rebalance: lose shard 1, gain shard 2.  The refilter drops the
    # moved-out range SILENTLY (no phantom DELETED — a shard move is not
    # an object deletion) and the relist ADDs the moved-in range only.
    deltas.clear()
    owned.clear()
    owned.update({0, 2})
    informer.refilter()
    now_range = {f"nb-{i:02d}" for i in range(20)
                 if shard_of("ns", f"nb-{i:02d}", 4) in owned}
    assert {name for _, name in informer.keys("ns")} == now_range
    assert all(et == "ADDED" for et, _ in deltas), deltas
    assert {name for _, name in deltas} == now_range - in_range
    informer.stop()


def test_refilter_token_dedupes_shared_informer():
    """Two controllers sharing one informer both refilter it on the same
    rebalance event; the event-epoch token must collapse that to ONE
    relist (listeners run sequentially on the dispatch thread, so a
    plain concurrency gate can't)."""
    from kubeflow_tpu.platform.runtime.informer import Informer

    kube = FakeKube()
    kube.add_namespace("ns")
    kube.create({
        "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
        "metadata": {"name": "nb", "namespace": "ns"}, "spec": {},
    })
    lists = []

    class Counting:
        def __init__(self, inner):
            self._inner = inner

        def list_with_rv(self, *a, **kw):
            # _relist prefers list_with_rv — count the relist LISTs here.
            lists.append(1)
            return self._inner.list_with_rv(*a, **kw)

        def __getattr__(self, name):
            return getattr(self._inner, name)

    informer = Informer(Counting(kube), NOTEBOOK, admit=lambda o: True)
    informer._relist()
    base = len(lists)
    assert informer.refilter(token=7) == 0 and len(lists) == base + 1
    # Same event, second sharer: no second LIST.
    informer.refilter(token=7)
    assert len(lists) == base + 1
    # A NEW event relists again.
    informer.refilter(token=8)
    assert len(lists) == base + 2


# -- chaos matrix over the ShardedFleet ---------------------------------------


def _first_absorb_times(survivors, crashed, kill_t):
    """Per crashed shard: seconds from the kill to the FIRST survivor
    acquisition (absorption latency; later acquires are rebalance
    churn)."""
    first = {}
    for r in survivors:
        for s, a, t, _ in r.coordinator.ownership_log:
            if a == "acquire" and s in crashed and t > kill_t:
                dt = t - kill_t
                if s not in first or dt < first[s]:
                    first[s] = dt
    return first


def test_fleet_kill_replica_mid_wave_fast():
    """Presubmit single-kill variant: a 200-notebook wave over 3
    replicas, one killed mid-wave.  Zero keys lost (every notebook
    converges), the dead replica's ranges are absorbed within ~one lease
    TTL, and the fencing invariant holds across every write."""
    fleet = ShardedFleet(replicas=3, num_shards=8,
                         lease_seconds=TTL, renew_seconds=RENEW)
    try:
        # Let the initial rebalance settle first — killing a replica that
        # never acquired a shard would leave `crashed` empty and the test
        # vacuous (the wave still starts before the kill: mid-wave).
        fleet.wait_stable_shard_map()
        fleet.create_wave(200)
        time.sleep(0.05)
        kill_t = time.monotonic()
        fleet.kill(1)
        fleet.wait_converged(timeout=120)
        fleet.wait_stable_shard_map()
        survivors = [r for r in fleet.replicas if r.alive]
        crashed = {s for s, a, _, _ in
                   fleet.replicas[1].coordinator.ownership_log
                   if a == "crash"}
        absorb = _first_absorb_times(survivors, crashed, kill_t)
        assert crashed and set(absorb) == crashed
        # FIRST acquisition per crashed shard within one TTL of the kill,
        # plus renew-tick scheduling slack (later re-acquires are
        # rebalance churn, not absorption latency).
        assert max(absorb.values()) <= TTL + 4 * RENEW + 0.5, absorb
        checked = fleet.assert_fencing_invariant()
        assert checked > 0
    finally:
        fleet.close()


def test_trace_ids_unique_across_replica_fleet():
    """ISSUE 14 satellite: reconcile trace ids are the causal 128-bit
    counter-in-random-block mints — the PR-1 16-hex prefix+counter
    scheme was seeded once per process, so two replicas could emit
    COLLIDING ids into a merged journey.  A 4-replica fleet wave must
    produce all-unique 32-hex ids across every replica's reconciles."""
    from kubeflow_tpu.platform.runtime import trace as rtrace

    rtrace.clear()
    fleet = ShardedFleet(replicas=4, num_shards=8,
                         lease_seconds=TTL, renew_seconds=RENEW)
    try:
        fleet.wait_stable_shard_map()
        fleet.wave(40, timeout=120)
    finally:
        fleet.close()
    ids = [t["trace_id"] for t in rtrace.recent()]
    assert ids, "no reconcile traces recorded"
    assert all(len(i) == 32 and int(i, 16) >= 0 for i in ids), ids[:3]
    assert len(ids) == len(set(ids)), "colliding reconcile trace ids"


def test_journey_survives_replica_kill():
    """ISSUE 14 acceptance: one notebook's trace_id stays continuous
    across a replica kill — the surviving replica's reconcile spans join
    the SAME journey the dead replica started, and the per-replica span
    attribution proves both wrote to it."""
    from kubeflow_tpu.telemetry import causal

    fleet = ShardedFleet(replicas=2, num_shards=4,
                         lease_seconds=TTL, renew_seconds=RENEW)
    try:
        fleet.wait_stable_shard_map()
        fleet.wave(12, timeout=120)
        # Pick a notebook owned by replica 0 (the one we'll kill).
        owned0 = fleet.replicas[0].coordinator.owned()
        victim = next(
            f"nb-{i:05d}" for i in range(12)
            if shard_of(fleet.namespace, f"nb-{i:05d}", 4) in owned0)
        nb = fleet.kube.get(NOTEBOOK, victim, fleet.namespace)
        ctx = causal.from_object(nb)
        assert ctx is not None
        before = [s for s in causal.journey(ctx.trace_id)
                  if s.get("segment") == "reconcile"]
        assert before and all(s.get("replica") == "r0" for s in before)
        fleet.kill(0)
        fleet.wait_stable_shard_map()
        # Touch the spec so the survivor reconciles (and re-writes) it —
        # the update keeps the create-time stamp, so the journey is the
        # same trace.
        nb = fleet.kube.get(NOTEBOOK, victim, fleet.namespace)
        nb["spec"]["tpu"]["topology"] = "2x2"
        fleet.kube.update(nb)
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            after = [s for s in causal.journey(ctx.trace_id)
                     if s.get("segment") == "reconcile"
                     and s.get("replica") == "r1"]
            if after:
                break
            time.sleep(0.05)
        assert after, "survivor's reconciles never joined the journey"
        merged = causal.merge_journeys(causal.journey(ctx.trace_id))
        replicas = {s.get("replica") for s in merged
                    if s.get("segment") == "reconcile"}
        assert replicas == {"r0", "r1"}, replicas
        assert {s["trace_id"] for s in merged} == {ctx.trace_id}
    finally:
        fleet.close()


def test_tpujob_gang_writes_fenced_across_replica_kill():
    """The fifth controller under sharded HA (ISSUE 10): a TPUJob fleet
    over 2 replicas survives a replica kill mid-lifecycle.  After the
    kill, EVERY gang loses a worker — the survivor must run the heaviest
    write burst a controller makes (whole-gang teardown + recreate +
    status) for gangs it owned AND gangs it absorbed, and the per-replica
    wire logs joined with the ownership windows must show every
    StatefulSet/Service/TPUJob write fenced, with no overlapping-window
    double writes and no dead-letters."""
    from kubeflow_tpu.platform.apis import tpujob as jobapi
    from kubeflow_tpu.platform.controllers import tpujob as jobctrl
    from kubeflow_tpu.platform.k8s.types import TPUJOB

    fleet = ShardedFleet(replicas=2, num_shards=4, workers=2,
                         lease_seconds=TTL, renew_seconds=RENEW,
                         controller_factory=jobctrl.make_controller,
                         # 12 gangs x 2 slices of 2x4 (1 host each): the
                         # queue admits all 24 slices only with 24 slots.
                         tpu_nodes=24)
    n = 12

    def all_jobs_at(phase, restarts):
        js = fleet.kube.list(TPUJOB, fleet.namespace)
        return len(js) == n and all(
            jobapi.phase_of(j) == phase
            and jobapi.restarts_of(j) == restarts for j in js)

    def wait(pred, what, timeout=120.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if pred():
                return
            time.sleep(0.05)
        js = fleet.kube.list(TPUJOB, fleet.namespace)
        raise TimeoutError(f"{what}: {[(j['metadata']['name'], j.get('status')) for j in js[:4]]}")

    try:
        fleet.wait_stable_shard_map()
        for i in range(n):
            fleet.kube.create({
                "apiVersion": "kubeflow.org/v1alpha1", "kind": "TPUJob",
                "metadata": {"name": f"tj-{i:03d}",
                             "namespace": fleet.namespace},
                "spec": {
                    "tpu": {"accelerator": "v5e", "topology": "2x4",
                            "slices": 2},
                    "template": {"spec": {"containers": [
                        {"name": "worker", "image": "trainer"}]}},
                },
            })
        wait(lambda: all_jobs_at("Running", 0), "initial gang converge")
        fleet.kill(0)
        # Preempt slice 1's worker of EVERY job — including jobs whose
        # shard the dead replica owned (the survivor sees those pods only
        # after absorb + refilter-relist).
        for i in range(n):
            fleet.kube.set_pod_phase(fleet.namespace,
                                     f"tj-{i:03d}-s1-0", "Failed")
        wait(lambda: all_jobs_at("Running", 1),
             "every gang restarted by the survivor", timeout=180.0)
        checked = fleet.assert_fencing_invariant(
            kinds={"StatefulSet", "Service", "TPUJob"})
        assert checked > 0
        for r in fleet.replicas:
            if r.alive:
                assert not r.controller.dead_letters
    finally:
        fleet.close()


def test_fleet_kill_replica_1k_wave_4_replicas():
    """The acceptance-criteria chaos test: a converge wave over 1000
    notebooks across 4 replicas; one replica is killed mid-wave.  All
    1000 reach Ready (zero keys lost), survivors absorb the dead
    replica's shard ranges within one lease TTL, and the per-replica
    ChaosKube call logs joined with the coordinators' ownership windows
    show no key written by two replicas in overlapping windows."""
    # A longer TTL than the unit tests: at 1k objects the GIL is
    # saturated and renew ticks lag — a 0.5 s lease would churn spuriously
    # (safely, thanks to fencing, but churn is not what this test pins).
    ttl, renew = 2.0, 0.2
    fleet = ShardedFleet(replicas=4, num_shards=8,
                         lease_seconds=ttl, renew_seconds=renew)
    try:
        fleet.wait_stable_shard_map(timeout=4 * ttl + 15)
        fleet.create_wave(1000)
        time.sleep(0.2)  # mid-wave: reconciles in flight on every replica
        kill_t = time.monotonic()
        fleet.kill(2)
        fleet.wait_converged(timeout=240)
        fleet.wait_stable_shard_map(timeout=4 * ttl + 15)
        survivors = [r for r in fleet.replicas if r.alive]
        crashed = {s for s, a, _, _ in
                   fleet.replicas[2].coordinator.ownership_log
                   if a == "crash"}
        absorb = _first_absorb_times(survivors, crashed, kill_t)
        assert crashed and set(absorb) == crashed
        assert max(absorb.values()) <= ttl + 4 * renew + 1.0, absorb
        checked = fleet.assert_fencing_invariant()
        assert checked >= 1000, (
            f"only {checked} writes checked — the wave did not exercise "
            "the fence")
        # Per-replica caches hold a fraction of the keyspace, not all of
        # it (the scale-out property the informer admit filter buys).
        from kubeflow_tpu.platform.k8s.types import NOTEBOOK as NB

        for r in survivors:
            cached = len(r.controller.informers[NB])
            assert cached < 1000, (
                f"replica {r.index} caches the full keyspace ({cached})")
    finally:
        fleet.close()


def test_fleet_lease_expiry_under_paused_replica():
    """Split brain at fleet scale: pause a replica's renewals mid-fleet,
    let survivors absorb its leases, then trigger reconciles of its old
    keys.  The stale owner must fence itself — its wire log shows ZERO
    writes after the survivors' takeover, while the new owners write the
    keys; the fencing invariant holds throughout."""
    fleet = ShardedFleet(replicas=2, num_shards=4,
                         lease_seconds=TTL, renew_seconds=RENEW)
    try:
        fleet.wave(60, timeout=60)
        victim = fleet.replicas[0]
        owned_before = set(victim.coordinator.owned())
        assert owned_before
        pause_t = time.monotonic()
        fleet.pause(0)
        survivor = fleet.replicas[1]
        deadline = time.monotonic() + TTL * 8 + 10
        while (time.monotonic() < deadline
               and len(survivor.coordinator.owned()) < 4):
            time.sleep(0.02)
        assert sorted(survivor.coordinator.owned()) == [0, 1, 2, 3]
        takeover_t = max(
            t for s, a, t, _ in survivor.coordinator.ownership_log
            if a == "acquire" and s in owned_before and t > pause_t)
        # Regress every notebook's status: the reconcile MUST rewrite it
        # (an annotation touch would diff to a no-op under the
        # write-coalesced path).  The paused replica still believes it
        # owns its old ranges, enqueues, reconciles — and every status
        # write it attempts must fence (stale lease, foreign holder),
        # while the new owners repair the same keys unopposed.
        from kubeflow_tpu.platform.k8s import errors

        for nb in fleet.kube.list(NOTEBOOK, "fleet"):
            nb = dict(nb)
            nb["status"] = dict(nb.get("status") or {})
            nb["status"]["readyReplicas"] = 0
            try:
                fleet.kube.update_status(nb)
            except errors.ApiError:
                pass
        # Each fence drops ONE shard (the one whose write was refused);
        # wait until every stale shard's reconcile has been caught, not
        # just the first.
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if (victim.client.fenced_total > 0
                    and victim.coordinator.owned() == frozenset()):
                break
            time.sleep(0.02)
        assert victim.client.fenced_total > 0, (
            "the stale owner never tried (and fenced) a write")
        assert victim.coordinator.owned() == frozenset(), (
            "fencing must drop the stale shards")
        # THE split-brain assertion, from the ChaosKube call log: nothing
        # from the stale owner reached the wire after the takeover.
        fleet.assert_no_writes_after(0, takeover_t)
        fleet.assert_fencing_invariant()
    finally:
        fleet.close()


def test_fleet_membership_churn_during_converge():
    """Membership churn mid-wave: a replica joins (incumbents shed toward
    the new fair share; the joiner resyncs only the moved ranges) and
    another leaves gracefully — the wave still converges with zero lost
    keys and no overlapping-ownership writes."""
    fleet = ShardedFleet(replicas=2, num_shards=8,
                         lease_seconds=TTL, renew_seconds=RENEW)
    try:
        fleet.create_wave(300)
        time.sleep(0.05)
        joiner = fleet.add_replica()
        time.sleep(0.15)
        fleet.stop_replica(0)   # graceful: drains, releases, instant handover
        fleet.wait_converged(timeout=120)
        per = fleet.wait_stable_shard_map()
        # The joiner ended up owning real ranges (the rebalance landed).
        assert per.get(joiner.index), "joiner never acquired a shard"
        checked = fleet.assert_fencing_invariant()
        assert checked > 0
    finally:
        fleet.close()


@pytest.mark.slow
def test_fleet_storm_kill_and_churn_soak():
    """Postsubmit (ha-chaos lane): the full matrix in one soak — a
    seeded fault storm on every replica's reconcile path, a kill, a
    join, a graceful leave, all during one 400-notebook wave.  Converge
    with zero dead-letters on live replicas and the fencing invariant
    across everything."""
    from kubeflow_tpu.platform.testing.chaos import storm

    fleet = ShardedFleet(replicas=4, num_shards=8,
                         lease_seconds=TTL, renew_seconds=RENEW,
                         chaos_faults=storm(rate=0.02), chaos_seed=20260804)
    try:
        fleet.wait_stable_shard_map()
        fleet.create_wave(400)
        time.sleep(0.1)
        fleet.kill(3)
        time.sleep(0.2)
        fleet.add_replica()
        time.sleep(0.2)
        fleet.stop_replica(1)
        fleet.wait_converged(timeout=300)
        for r in fleet.replicas:
            if r.alive:
                assert not r.controller.dead_letters, (
                    f"replica {r.index} dead-lettered "
                    f"{r.controller.dead_letters}")
        fleet.assert_fencing_invariant()
    finally:
        fleet.close()


# -- observability (satellite) -------------------------------------------------


def test_shard_metrics_and_debug_endpoint():
    """controller_shard_owned rides /metrics at scrape time, the lease
    transition counter carries acquire/renew/release reasons, and
    /debug/shards serves the live map (docs/observability.md)."""
    import json
    import urllib.request

    from kubeflow_tpu.platform.main import _serve_health
    from kubeflow_tpu.platform.runtime import metrics as rtmetrics

    kube = kube_with_ns()
    a = coordinator(kube, "a", num_shards=4)
    a.start()
    try:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and len(a.owned()) < 4:
            time.sleep(0.02)
        assert len(a.owned()) == 4
        text = rtmetrics.render().decode()
        assert 'controller_shard_owned{controller="kubeflow-tpu-ctrlplane"'\
            ',shard="0"} 1.0' in text
        assert "controller_lease_transitions_total" in text

        class _Mgr:
            def healthy(self):
                return True

        server = _serve_health(_Mgr(), 0, host="127.0.0.1", shards=a)
        try:
            port = server.server_address[1]
            body = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/shards", timeout=5).read())
            assert body["identity"] == "a"
            assert body["num_shards"] == 4
            assert body["owned"] == [0, 1, 2, 3]
            assert body["shards"]["0"]["owned_by_me"] is True
        finally:
            server.shutdown()
    finally:
        a.stop()
    # Deregistered on stop: the gauge must not keep reporting a dead
    # coordinator's map.
    text = rtmetrics.render().decode()
    assert 'controller_shard_owned{controller="kubeflow-tpu-ctrlplane"'\
        ',shard="0"} 1.0' not in text
