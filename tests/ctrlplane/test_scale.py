"""Fleet-scale regression tier (VERDICT r4 item 3): a budgeted slice of
bench_scale.py's protocol — wave convergence, near-linear scaling, resync
drain — small enough for CI (~15 s) but big enough that the O(N^2)
failure modes it exists to catch (per-reconcile namespace LISTs, store
scans over every kind, unindexed event mirroring) show up as a blown
budget.  The full-size numbers (600/1000 notebooks) live in BASELINE.md
and are re-measured by ``python bench_scale.py``.

The allocation tripwire at the bottom is TIER-1 (not slow): a miniature
N=40 fleet under tracemalloc that fails fast on copy-amplification
regressions (a return of copy-per-read on the informer path) without the
full bench.
"""
from __future__ import annotations

import time

import pytest

from kubeflow_tpu.platform.runtime import Request

slow = pytest.mark.slow


def _harness(**kwargs):
    from bench_scale import FleetHarness

    return FleetHarness(**kwargs)


def test_resync_allocation_stays_in_band():
    """Tier-1 copy-amplification tripwire: one steady-state resync cycle
    of a 40-notebook fleet, under tracemalloc.  The peak allocation per
    no-op reconcile is pinned: zero-copy frozen-view reads measured
    ~3.5 KiB/object on the dev container pre-causal-tracing and
    ~4.8-5.0 with it (the causal machinery adds a mostly-FIXED retained
    footprint — context ids, trace links, the journey ring — that a tiny
    N amortizes poorly: the same machine measures ~1.9 KiB/object at
    N=200 and the full bench band holds at 600), while the
    pre-frozen-view copy-per-read path added ~+2.4 KiB/object of
    PER-OBJECT copy churn on top of any base — so the 6.5 band still
    fails fast if deep copies creep back onto the informer read path,
    long before the full bench would notice."""
    h = _harness()
    try:
        h.wave(40, timeout=60.0)
        h.resync_cycle(timeout=30.0)  # warmup: lazy imports, first drain
        alloc = h.resync_alloc(timeout=30.0)
    finally:
        h.close()
    assert alloc["n"] >= 40
    assert alloc["peak_kb_per_obj"] < 6.5, (
        f"resync allocated {alloc['peak_kb_per_obj']:.2f} KiB/object at "
        f"peak (band 6.5) — copy amplification is back on the read path")


@slow
@pytest.mark.parametrize("n", [150])
def test_wave_converges_within_budget(n):
    h = _harness()
    try:
        res = h.wave(n, timeout=60.0)
    finally:
        h.close()
    # Budget: bench_scale measured ~1.4-3.5 ms/notebook on this harness;
    # 20 ms/notebook is ~10x headroom for CI noise while still failing
    # instantly on anything quadratic (pre-fix: 15 ms/nb at 150 and
    # growing with N).
    per_nb = res["converge_s"] / n * 1e3
    assert per_nb < 20.0, f"{per_nb:.1f} ms/notebook"
    assert res["errors"] == 0


@slow
def test_near_linear_scaling_small_vs_large():
    """Per-notebook converge time must not grow superlinearly with fleet
    size (the assertion functional tests cannot make)."""
    times = {}
    for n in (50, 200):
        h = _harness()
        try:
            times[n] = h.wave(n, timeout=60.0)["converge_s"] / n
        finally:
            h.close()
    ratio = times[200] / times[50]
    # bench_scale.py measures 1.1-1.2x at 4x fleet after the round-5
    # fixes; 3x is the CI tripwire (pre-fix this read ~2-4x and grew).
    assert ratio < 3.0, f"superlinear: {ratio:.2f}x per-notebook at 4x fleet"


@slow
def test_resync_cycle_drains_and_is_cheap():
    h = _harness()
    try:
        h.wave(150, timeout=60.0)
        res = h.resync_cycle(timeout=30.0)
        assert res["n"] >= 150
        # 0.04 s measured for 150 objects; 1 s is the tripwire.
        assert res["cpu_s"] < 1.0, f"resync CPU {res['cpu_s']:.2f}s"
    finally:
        h.close()


@slow
def test_steady_churn_queue_stays_drained():
    h = _harness()
    try:
        h.wave(100, timeout=60.0)
        res = h.churn(seconds=1.5, rate_hz=150.0)
        assert res["drained"]
        assert res["new_errors"] == 0
        # p95 backlog bounded well below the fleet size: the queue keeps
        # up with sustained updates instead of accreting.
        assert res["p95_queue_depth"] <= 50, res
    finally:
        h.close()


@slow
def test_noop_reconcile_cost_flat_in_fleet_size():
    """The per-reconcile cost must be O(1) in fleet size — cache-indexed
    reads, no namespace-wide LISTs (the round-5 informer architecture)."""
    costs = {}
    for n in (100, 400):
        h = _harness()
        try:
            h.wave(n, timeout=60.0)
            h.ctrl.stop()
            time.sleep(0.2)
            reqs = [Request("fleet", f"nb-{i:04d}")
                    for i in range(0, n, max(1, n // 50))][:50]
            t0 = time.process_time()
            for r in reqs:
                h.ctrl.reconciler.reconcile(r)
            costs[n] = (time.process_time() - t0) / len(reqs)
        finally:
            h.close()
    ratio = costs[400] / costs[100]
    assert ratio < 3.0, (
        f"per-reconcile cost grew {ratio:.2f}x for 4x fleet "
        f"({costs[100]*1e3:.2f} -> {costs[400]*1e3:.2f} ms)")


@slow
def test_http_transport_fleet_with_short_watch_windows():
    """The same fleet machinery over the REAL wire (RestKubeClient against
    httpkube — the envtest analogue), with the client's bounded watch
    windows shrunk to 2 s so the informers' resourceVersion resume /
    history-replay path (round 5) fires many times mid-wave.  Two waves
    with rollovers between them: no lost deltas, no reconcile errors, no
    wedged informers."""
    h = _harness(transport="http", watch_window=2.0)
    try:
        res1 = h.wave(60, timeout=90.0)
        assert res1["errors"] == 0
        time.sleep(4.5)  # at least two full window rollovers while idle
        res2 = h.wave(60, timeout=90.0, prefix="wave2")
        assert res2["errors"] == 0
    finally:
        h.close()
