"""The fleet scrape manager (ISSUE 15): target fan-out into the TSDB,
reason-classified scrape failures, the autoscaler's stored-series
ServeSample, self-scrape targets, and the pipeline cadence loop."""
from __future__ import annotations

import pytest

from kubeflow_tpu.platform.runtime import metrics
from kubeflow_tpu.telemetry import fleetscrape as fs
from kubeflow_tpu.telemetry.tsdb import TSDB


def page(*, queue=0.0, requests=0.0, slots=None, active=None,
         ttft=None):
    lines = [f"serve_queue_depth {queue}",
             f'generate_requests_total{{outcome="ok"}} {requests}']
    if slots is not None:
        lines += [f"serve_decode_slots {slots}",
                  f"serve_decode_slots_active {active or 0}"]
    for le, v in (ttft or {}).items():
        lines.append(
            f'serve_time_to_first_token_seconds_bucket{{le="{le}"}} {v}')
    return "\n".join(lines) + "\n"


def _errors(reason):
    return metrics.registry.get_sample_value(
        "fleetscrape_scrape_errors_total", {"reason": reason}) or 0.0


def test_scrape_stores_samples_with_target_labels():
    db = TSDB()
    sc = fs.FleetScraper(db, scraper=lambda url: page(queue=4.0),
                         now=lambda: 50.0)
    stats = sc.scrape([
        fs.Target(url="http://a/metrics", labels={"service": "n/s",
                                                  "replica": "a"}),
        fs.Target(url="http://b/metrics", labels={"service": "n/s",
                                                  "replica": "b"}),
    ])
    assert stats.ok == 2 and stats.samples == 4
    rows = db.values_at("serve_queue_depth", {"service": "n/s"}, 50.0)
    assert sorted(r["replica"] for r, _v in rows) == ["a", "b"]


def test_scrape_error_reasons_are_bounded_and_counted():
    db = TSDB()
    before = {r: _errors(r) for r in ("timeout", "connect", "parse")}

    def scraper(url):
        if "dead" in url:
            return None                 # down replica
        if "slow" in url:
            raise TimeoutError()        # stalled socket
        return "{ not metrics"          # parse regression

    own = []
    sc = fs.FleetScraper(db, scraper=scraper, on_error=own.append)
    stats = sc.scrape([fs.Target(url="http://dead/metrics"),
                       fs.Target(url="http://slow/metrics"),
                       fs.Target(url="http://garbled/metrics")])
    assert stats.ok == 0
    assert _errors("connect") == before["connect"] + 1
    assert _errors("timeout") == before["timeout"] + 1
    assert _errors("parse") == before["parse"] + 1
    # The owner hook (the serving controller's labeled counter) sees the
    # same bounded reasons.
    assert sorted(own) == ["connect", "parse", "timeout"]


def test_self_scrape_target_reads_local_registry():
    db = TSDB()
    sc = fs.FleetScraper(db)
    target = fs.self_target(metrics.render, labels={"replica": "self"})
    stats = sc.scrape([target], ts=9.0)
    assert stats.ok == 1 and stats.samples > 10
    # A control-plane series landed with the self label.
    assert db.instant("notebook_create_total", {"replica": "self"})


def test_serve_sample_first_pass_has_no_ttft_signal():
    db = TSDB()
    sc = fs.FleetScraper(
        db, scraper=lambda url: page(queue=6.0, requests=10.0,
                                     ttft={"1.0": 5, "+Inf": 5}))
    sc.scrape_service("n/s", [fs.Target(url="u", labels={
        "service": "n/s", "replica": "r0"})], ts=10.0)
    s = fs.serve_sample(db, "n/s")
    assert s.replicas_scraped == 1 and s.queue_depth == 6.0
    assert s.requests_total == 10.0
    assert s.ttft_p99_s is None  # cumulative history is not pressure


def test_serve_sample_delta_resets_and_outage_rebaseline():
    db = TSDB()
    pages = {}
    sc = fs.FleetScraper(db, scraper=lambda url: pages.get(url))
    t = [fs.Target(url="u", labels={"service": "n/s", "replica": "r0"})]

    pages["u"] = page(ttft={"1.0": 10, "+Inf": 10})
    sc.scrape_service("n/s", t, ts=10.0)
    pages["u"] = page(ttft={"1.0": 10, "+Inf": 14})
    sc.scrape_service("n/s", t, ts=20.0)
    s = fs.serve_sample(db, "n/s")
    # Delta: 4 new events, all over the 1.0 bucket -> p99 clamps to the
    # top finite bound.
    assert s.ttft_p99_s == 1.0
    # Replica restart: counters reset BELOW the previous pass — the
    # per-le clamp keeps the delta at zero instead of negative.
    pages["u"] = page(ttft={"1.0": 1, "+Inf": 1})
    sc.scrape_service("n/s", t, ts=30.0)
    assert fs.serve_sample(db, "n/s").ttft_p99_s is None
    # Outage pass (no replica answered) re-baselines: the next
    # successful pass has no TTFT signal.
    pages["u"] = None
    sc.scrape_service("n/s", t, ts=40.0)
    assert fs.serve_sample(db, "n/s").replicas_scraped == 0
    pages["u"] = page(ttft={"1.0": 50, "+Inf": 50})
    sc.scrape_service("n/s", t, ts=50.0)
    assert fs.serve_sample(db, "n/s").ttft_p99_s is None


def test_serve_sample_frozen_clock_passes_stay_distinct():
    """Reconcilers run with test-frozen clocks; pass timestamps must
    stay strictly monotonic per service or the pass join collapses."""
    db = TSDB()
    pages = {"u": page(queue=2.0)}
    sc = fs.FleetScraper(db, scraper=lambda url: pages["u"],
                         now=lambda: 1000.0)
    t = [fs.Target(url="u", labels={"service": "n/s", "replica": "r0"})]
    sc.scrape_service("n/s", t)
    pages["u"] = page(queue=8.0)
    sc.scrape_service("n/s", t)
    s = fs.serve_sample(db, "n/s")
    assert s.replicas_scraped == 1 and s.queue_depth == 8.0


def test_discovery_sources_feed_targets_and_survive_failure():
    db = TSDB()
    sc = fs.FleetScraper(db, scraper=lambda url: page())
    sc.add_source(lambda: [fs.Target(url="http://x/metrics")])

    def broken():
        raise RuntimeError("boom")

    sc.add_source(broken)
    assert len(sc.targets()) == 1
    stats = sc.scrape()
    assert stats.targets == 1 and stats.ok == 1


def test_inferenceservice_targets_follow_endpoint_contract():
    pods = [
        {"metadata": {"name": "p0", "annotations": {
            "inferenceservices.kubeflow.org/endpoint": "http://ep0/"}},
         "status": {}},
        {"metadata": {"name": "p1"}, "status": {"podIP": "10.0.0.9"}},
        {"metadata": {"name": "p2"}, "status": {}},  # no route: skipped
    ]
    targets = fs.inferenceservice_targets(pods, port=9000,
                                          service_key="n/s")
    assert [(t.url, t.labels["replica"]) for t in targets] == [
        ("http://ep0/metrics", "p0"),
        ("http://10.0.0.9:9000/metrics", "p1"),
    ]
    assert all(t.labels["service"] == "n/s" for t in targets)


def test_pipeline_step_scrapes_evaluates_and_ticks_goodput():
    from kubeflow_tpu.telemetry import goodput as gp
    from kubeflow_tpu.telemetry import slo

    db = TSDB()
    clock = [100.0]
    engine = slo.RuleEngine(db, [], now=lambda: clock[0])
    acct = gp.GoodputAccountant(now=lambda: clock[0])
    pipe = fs.MetricsPipeline(tsdb=db, engine=engine, goodput=acct,
                              now=lambda: clock[0], interval=999.0)
    pipe.scraper.add_source(lambda: [fs.self_target(metrics.render)])
    stats = pipe.step()
    assert stats.ok == 1 and engine.last_eval_at == 100.0
    clock[0] = 110.0
    pipe.step()
    assert engine.last_eval_at == 110.0
    # start/stop is clean (daemon thread, no firing at this interval).
    pipe.start()
    pipe.stop()


@pytest.mark.parametrize("reason,exc", [
    ("timeout", TimeoutError), ("connect", RuntimeError)])
def test_fetch_hook_exception_classification(reason, exc):
    db = TSDB()

    def scraper(url):
        raise exc()

    sc = fs.FleetScraper(db, scraper=scraper)
    before = _errors(reason)
    sc.scrape([fs.Target(url="u")])
    assert _errors(reason) == before + 1


def test_reconciler_scrape_memory_is_private_but_wiring_is_shared():
    """The migration keeps the replaced ``_ttft_prev`` dict's isolation:
    a bare reconciler owns a PRIVATE store (a second instance's first
    pass re-baselines instead of computing a delta against the first's
    passes), while make_controller wires the process-shared store so the
    manager's rule engine reads the same serve series."""
    from kubeflow_tpu.platform.controllers import inferenceservice as svc
    from kubeflow_tpu.platform.testing import FakeKube

    kube = FakeKube()
    kube.add_namespace("serve")
    r1 = svc.InferenceServiceReconciler(kube, scraper=lambda url: None)
    r2 = svc.InferenceServiceReconciler(kube, scraper=lambda url: None)
    assert r1.tsdb is not r2.tsdb
    ctrl = svc.make_controller(kube, scraper=lambda url: None)
    try:
        assert ctrl.reconciler.tsdb is fs.default_tsdb()
    finally:
        del ctrl


def test_target_names_filter_and_eviction_visibility():
    """Replica targets filter to the decision series (the fleet-scale
    guard against series-bound churn), and eviction at the bound is
    surfaced as a counter — never a buried attribute."""
    db = TSDB(max_series=3)
    full = page(queue=1.0, requests=2.0, slots=8, active=4,
                ttft={"1.0": 1, "+Inf": 2})
    sc = fs.FleetScraper(db, scraper=lambda url: full)
    before = metrics.registry.get_sample_value(
        "kft_tsdb_series_evicted_total") or 0.0
    t = fs.Target(url="u", labels={"service": "n/s", "replica": "r0"},
                  names=frozenset({"serve_queue_depth"}))
    stats = sc.scrape([t], ts=1.0)
    assert stats.samples == 1
    assert db.names() == ["serve_queue_depth"]
    # Unfiltered scrape overflows the tiny bound: evictions become a
    # scrapeable counter delta.
    sc.scrape([fs.Target(url="u", labels={"replica": "r1"})], ts=2.0)
    assert db.evictions > 0
    after = metrics.registry.get_sample_value(
        "kft_tsdb_series_evicted_total") or 0.0
    assert after - before == db.evictions
    # The serving controller's default target set carries the filter.
    pods = [{"metadata": {"name": "p0", "annotations": {
        "inferenceservices.kubeflow.org/endpoint": "http://e"}},
        "status": {}}]
    (target,) = fs.inferenceservice_targets(pods, port=1, service_key="x")
    assert target.names == fs.SERVE_SAMPLE_NAMES


def test_watch_lag_overflow_counted_not_observed():
    """A lag past the replay bound records neither span nor histogram
    sample (by design) but MUST count — a watch path degraded beyond
    the bound would otherwise be invisible to the watch-lag SLO."""
    import time as _time

    from kubeflow_tpu.platform.k8s.types import NOTEBOOK
    from kubeflow_tpu.platform.runtime import Reconciler, Request
    from kubeflow_tpu.platform.runtime.controller import Controller
    from kubeflow_tpu.telemetry import causal

    class _R(Reconciler):
        def reconcile(self, req):
            return None

    ctrl = Controller("lag-probe", _R(), primary=NOTEBOOK)
    ctx = causal.mint()
    obj = {"apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
           "metadata": {"name": "nb", "namespace": "x", "annotations": {
               causal.TRACEPARENT_ANNOTATION: ctx.to_traceparent(),
               causal.TRACESTATE_ANNOTATION:
                   f"kft=ts:{_time.time() - 10_000.0}",
           }}}

    def overflow():
        return metrics.registry.get_sample_value(
            "informer_watch_lag_overflow_total",
            {"kind": "Notebook"}) or 0.0

    before = overflow()
    ctrl._note_event(obj, [Request("x", "nb")])
    assert overflow() == before + 1
    # Dedup: the same stamp never counts twice.
    ctrl._note_event(obj, [Request("x", "nb")])
    assert overflow() == before + 1
