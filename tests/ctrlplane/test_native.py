"""Parity tests: native C++ engine (libkfnative) vs pure-Python implementations.

The native library mirrors two hot paths:
  * kfp_* JSON patch engine  vs  platform/webhook/jsonpatch.py
  * kfq_* workqueue          vs  platform/runtime/controller.py::_WorkQueue

Every case runs against BOTH backends and asserts identical behavior, so the
implementations cannot drift.  If g++/make is unavailable the native half
skips (the platform then runs pure-Python everywhere).
"""
from __future__ import annotations

import time

import pytest

from kubeflow_tpu.platform import native
from kubeflow_tpu.platform.runtime.controller import Request, _WorkQueue, make_workqueue
from kubeflow_tpu.platform.webhook import jsonpatch

NATIVE = native.available()
needs_native = pytest.mark.skipif(not NATIVE, reason="libkfnative not built")

# Representative documents: pod-spec-shaped, escapes, unicode, nesting,
# arrays, numbers, null/bool transitions.
DIFF_CASES = [
    ({}, {}),
    ({"a": 1}, {"a": 1}),
    ({"a": 1}, {"a": 2}),
    ({"a": 1}, {}),
    ({}, {"a": [1, 2, {"b": None}]}),
    ({"a": {"b": {"c": 1}}}, {"a": {"b": {"c": 2, "d": "x"}}}),
    ({"arr": [1, 2, 3]}, {"arr": [1, 2]}),
    ({"s": "héllo\n\"quoted\""}, {"s": "wörld/~tilde"}),
    ({"a/b": 1, "m~n": 2}, {"a/b": 3, "m~n": 2}),
    ({"x": True}, {"x": False}),
    ({"x": None}, {"x": 0}),
    ({"x": True}, {"x": 1}),  # Python ==: no patch op
    ({"x": 1}, {"x": 1.0}),  # Python ==: no patch op
    ({"x": 1}, {"x": True}),
    ({"big": 2**63}, {"big": 2**63 + 1}),  # beyond int64: must still diff
    ({"big": 2**63 + 1}, {"big": 2**63 + 1}),
    ({"big": -(2**70)}, {"big": 2**70}),
    (
        {
            "metadata": {"name": "nb", "labels": {"app": "notebook"}},
            "spec": {
                "containers": [
                    {"name": "main", "image": "jax:tpu", "env": [{"name": "A", "value": "1"}]}
                ]
            },
        },
        {
            "metadata": {
                "name": "nb",
                "labels": {"app": "notebook", "tpu": "v5e"},
                "annotations": {"poddefault.kubeflow.org/tpu-env": "42"},
            },
            "spec": {
                "containers": [
                    {
                        "name": "main",
                        "image": "jax:tpu",
                        "env": [{"name": "A", "value": "1"}, {"name": "TPU_TOPOLOGY", "value": "2x4"}],
                        "resources": {"limits": {"google.com/tpu": 8}},
                    }
                ],
                "nodeSelector": {"cloud.google.com/gke-tpu-topology": "2x4"},
                "tolerations": [{"key": "google.com/tpu", "operator": "Exists"}],
            },
        },
    ),
]


@needs_native
@pytest.mark.parametrize("before,after", DIFF_CASES)
def test_create_patch_parity(before, after):
    py = jsonpatch.create_patch(before, after)
    nat = native.create_patch(before, after)
    assert nat == py


@needs_native
@pytest.mark.parametrize("before,after", DIFF_CASES)
def test_patch_round_trip_both_backends(before, after):
    ops = jsonpatch.create_patch(before, after)
    assert jsonpatch.apply_patch(before, ops) == after
    assert native.apply_patch(before, ops) == after
    ops_nat = native.create_patch(before, after)
    assert jsonpatch.apply_patch(before, ops_nat) == after
    assert native.apply_patch(before, ops_nat) == after


@needs_native
def test_apply_patch_ops_parity():
    doc = {"a": {"b": [1, 2, 3]}, "keep": True}
    ops = [
        {"op": "add", "path": "/a/b/1", "value": 99},
        {"op": "add", "path": "/a/b/-", "value": 4},
        {"op": "replace", "path": "/keep", "value": False},
        {"op": "add", "path": "/new/nested", "value": "x"},
        {"op": "test", "path": "/a/b/0", "value": 1},
        {"op": "copy", "from": "/a/b/0", "path": "/copied"},
        {"op": "move", "from": "/a/b/2", "path": "/moved"},
        {"op": "remove", "path": "/a/b/0"},
    ]
    assert native.apply_patch(doc, ops) == jsonpatch.apply_patch(doc, ops)


@needs_native
def test_apply_patch_errors_parity():
    bad = [
        [{"op": "remove", "path": "/missing"}],
        [{"op": "replace", "path": "/missing", "value": 1}],
        [{"op": "test", "path": "/a", "value": 2}],
        [{"op": "bogus", "path": "/a"}],
    ]
    for ops in bad:
        with pytest.raises(jsonpatch.PatchError):
            jsonpatch.apply_patch({"a": 1}, ops)
        with pytest.raises(native.NativeError):
            native.apply_patch({"a": 1}, ops)


@needs_native
def test_fast_path_used_in_webhook():
    before = {"spec": {"containers": [{"name": "m"}]}}
    after = {"spec": {"containers": [{"name": "m"}], "nodeSelector": {"t": "v5e"}}}
    assert jsonpatch.create_patch_fast(before, after) == jsonpatch.create_patch(before, after)


# -- workqueue parity ---------------------------------------------------------


def _queues():
    qs = [_WorkQueue(base_delay=0.01, max_delay=0.1)]
    if NATIVE:
        qs.append(native.NativeWorkQueue(base_delay=0.01, max_delay=0.1))
    return qs


@pytest.mark.parametrize("q", _queues(), ids=lambda q: type(q).__name__)
def test_queue_dedup_and_order(q):
    r1, r2 = Request("ns", "a"), Request("ns", "b")
    q.add(r1)
    q.add(r1)  # dedup
    q.add(r2, delay=0.05)
    assert q.get(0.5) == r1
    assert q.get(1.0) == r2  # delivered after its delay, exactly once
    assert q.get(0.02) is None
    q.shut_down()


@pytest.mark.parametrize("q", _queues(), ids=lambda q: type(q).__name__)
def test_queue_immediate_add_preempts_delayed(q):
    r = Request("ns", "x")
    q.add(r, delay=5.0)
    q.add(r)  # immediate entry supersedes the delayed one
    t0 = time.monotonic()
    assert q.get(1.0) == r
    assert time.monotonic() - t0 < 0.5
    assert q.get(0.02) is None  # no duplicate delivery from stale entry
    q.shut_down()


@pytest.mark.parametrize("q", _queues(), ids=lambda q: type(q).__name__)
def test_queue_rate_limit_backoff_and_forget(q):
    r = Request("ns", "err")
    q.add_rate_limited(r)  # 0.01
    assert q.get(1.0) == r
    q.done(r)
    q.add_rate_limited(r)  # 0.02
    t0 = time.monotonic()
    assert q.get(1.0) == r
    assert time.monotonic() - t0 >= 0.01
    q.done(r)
    q.forget(r)
    q.add_rate_limited(r)  # back to base delay
    assert q.get(1.0) == r
    q.done(r)
    q.shut_down()


@pytest.mark.parametrize("q", _queues(), ids=lambda q: type(q).__name__)
def test_queue_per_key_exclusion_parity(q):
    r = Request("ns", "x")
    q.add(r)
    assert q.get(0.5) == r
    q.add(r)                       # parked while processing
    assert q.get(0.05) is None     # never delivered concurrently
    q.done(r)
    assert q.get(0.5) == r         # fires after done
    q.done(r)
    q.shut_down()


@pytest.mark.parametrize("q", _queues(), ids=lambda q: type(q).__name__)
def test_queue_shutdown_unblocks(q):
    import threading

    results = []
    t = threading.Thread(target=lambda: results.append(q.get(5.0)))
    t.start()
    time.sleep(0.05)
    q.shut_down()
    t.join(timeout=2.0)
    assert not t.is_alive()
    assert results == [None]


@needs_native
def test_native_queue_id_maps_stay_bounded():
    q = native.NativeWorkQueue(base_delay=0.01, max_delay=0.1)
    for i in range(200):
        r = Request("ns", f"nb-{i}")
        q.add(r)
        assert q.get(1.0) == r
        q.forget(r)
        q.done(r)
    assert len(q._to_id) == 0  # pruned at done (no pending, no failures)
    q.shut_down()


def test_make_workqueue_returns_native_when_available():
    q = make_workqueue()
    if NATIVE:
        assert isinstance(q, native.NativeWorkQueue)
    else:
        assert isinstance(q, _WorkQueue)
    q.shut_down()


# -- RFC 7386 merge patch (kfp_merge_*) ---------------------------------------


def test_native_merge_patch_matches_python():
    import copy

    from kubeflow_tpu.platform import native
    from kubeflow_tpu.platform.testing.fake import _merge_patch

    if not native.available():
        pytest.skip("native library unavailable")
    cases = [
        ({"a": 1}, {"a": 2}),
        ({"a": {"b": 1, "c": 2}}, {"a": {"b": None}}),
        ({"a": [1, 2]}, {"a": [3]}),                      # arrays replace
        ({"a": 1}, {"b": {"c": {"d": None, "e": 1}}}),    # nested null strip
        ({"m": {"x": 1}}, {"m": "scalar"}),               # obj -> scalar
        ({}, {"a": None}),                                # null on missing
        ({"u": "ü", "n": 2**63 + 1}, {"u": "v", "big": 2**70}),
    ]
    for target, patch in cases:
        py = copy.deepcopy(target)
        _merge_patch(py, patch)
        assert native.merge_patch_apply(target, patch) == py, (target, patch)


def test_native_merge_create_roundtrip():
    from kubeflow_tpu.platform import native

    if not native.available():
        pytest.skip("native library unavailable")
    import random

    # No literal nulls and no bools: RFC 7386 cannot express *storing* a
    # null (it always means "remove"), and the diff's equality follows
    # Python == where True == 1 — both are outside the k8s-object domain
    # this engine serves (API objects store neither).
    def rand_doc(depth=0):
        r = random.random()
        if depth > 2 or r < 0.3:
            return random.choice([1, "s", 3.5, [1, 2], "t"])
        return {
            f"k{i}": rand_doc(depth + 1) for i in range(random.randint(0, 4))
        }

    random.seed(7)
    for _ in range(50):
        before = {"root": rand_doc(), "x": rand_doc()}
        after = {"root": rand_doc(), "y": rand_doc()}
        patch = native.merge_patch_create(before, after)
        assert native.merge_patch_apply(before, patch) == after


def test_fake_kube_patch_uses_native_merge():
    from kubeflow_tpu.platform import native
    from kubeflow_tpu.platform.k8s.types import NOTEBOOK
    from kubeflow_tpu.platform.testing import FakeKube

    if not native.available():
        pytest.skip("native library unavailable")
    kube = FakeKube()
    kube.add_namespace("ns")
    kube.create({
        "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
        "metadata": {"name": "nb", "namespace": "ns",
                     "annotations": {"a": "1", "b": "2"}},
        "spec": {"template": {"spec": {"containers": [{"name": "nb"}]}}},
    })
    kube.patch(NOTEBOOK, "nb",
               {"metadata": {"annotations": {"a": None, "c": "3"}}}, "ns")
    nb = kube.get(NOTEBOOK, "nb", "ns")
    ann = {k: v for k, v in nb["metadata"]["annotations"].items()
           if not k.startswith("kubeflow.org/trace")}  # causal stamp rides every CR
    assert ann == {"b": "2", "c": "3"}


def test_loaded_never_builds(monkeypatch):
    # loaded() must be a pure check: no build side effects even when the
    # library has not been loaded in this process.
    from kubeflow_tpu.platform import native

    calls = []
    monkeypatch.setattr(native, "_try_build", lambda: calls.append(1) or False)
    native.loaded()
    assert calls == []
