import datetime

import pytest

from kubeflow_tpu.platform.apis import notebook as nbapi
from kubeflow_tpu.platform.controllers.culling import CullingReconciler
from kubeflow_tpu.platform.k8s.types import NOTEBOOK
from kubeflow_tpu.platform.runtime import Request
from kubeflow_tpu.platform.testing import FakeKube

from .test_notebook_controller import make_notebook

T0 = datetime.datetime(2026, 7, 29, 12, 0, 0, tzinfo=datetime.timezone.utc)


class Clock:
    def __init__(self):
        self.now = T0

    def __call__(self):
        return self.now

    def advance(self, minutes):
        self.now += datetime.timedelta(minutes=minutes)


@pytest.fixture
def kube():
    k = FakeKube()
    k.add_namespace("user1")
    k.create(make_notebook())
    return k


def kernels(state, last):
    return [{"execution_state": state, "last_activity": last}]


def test_busy_kernels_record_activity_not_cull(kube):
    clock = Clock()
    r = CullingReconciler(
        kube, prober=lambda url: kernels("busy", "2026-07-29T11:59:00Z"),
        idle_minutes=30, now=clock,
    )
    result = r.reconcile(Request("user1", "nb"))
    assert result and result.requeue_after == 60.0
    nb = kube.get(NOTEBOOK, "nb", "user1")
    assert not nbapi.is_stopped(nb)
    assert nb["metadata"]["annotations"][nbapi.LAST_ACTIVITY_ANNOTATION]


def test_idle_past_window_culls(kube):
    clock = Clock()
    r = CullingReconciler(
        kube, prober=lambda url: kernels("idle", "2026-07-29T11:00:00Z"),
        idle_minutes=30, now=clock,
    )
    clock.advance(45)  # now 12:45; last activity 11:00 → 105 min idle
    r.reconcile(Request("user1", "nb"))
    nb = kube.get(NOTEBOOK, "nb", "user1")
    assert nbapi.is_stopped(nb)


def test_idle_within_window_spares(kube):
    clock = Clock()
    r = CullingReconciler(
        kube, prober=lambda url: kernels("idle", "2026-07-29T11:50:00Z"),
        idle_minutes=30, now=clock,
    )
    r.reconcile(Request("user1", "nb"))
    assert not nbapi.is_stopped(kube.get(NOTEBOOK, "nb", "user1"))


def test_unreachable_notebook_not_culled(kube):
    r = CullingReconciler(kube, prober=lambda url: None, idle_minutes=0)
    result = r.reconcile(Request("user1", "nb"))
    assert result is not None  # requeues
    assert not nbapi.is_stopped(kube.get(NOTEBOOK, "nb", "user1"))


def test_already_stopped_is_noop(kube):
    nb = kube.get(NOTEBOOK, "nb", "user1")
    nb["metadata"].setdefault("annotations", {})[nbapi.STOP_ANNOTATION] = "x"
    kube.update(nb)
    calls = []
    r = CullingReconciler(kube, prober=lambda url: calls.append(url))
    assert r.reconcile(Request("user1", "nb")) is None
    assert calls == []  # no probe of a stopped notebook


def test_probe_url_targets_worker0_service():
    r = CullingReconciler(FakeKube(), prober=lambda url: None)
    assert r.kernels_url("user1", "nb") == (
        "http://nb.user1.svc.cluster.local/notebook/user1/nb/api/kernels"
    )


def test_probe_url_dev_mode_uses_kubectl_proxy(monkeypatch):
    # DEV != "false" probes through a local kubectl proxy instead of cluster
    # DNS (reference culling_controller.go:211-216).
    monkeypatch.setenv("DEV", "true")
    r = CullingReconciler(FakeKube(), prober=lambda url: [])
    url = r.kernels_url("user1", "nb")
    assert url == (
        "http://localhost:8001/api/v1/namespaces/user1/services/nb:http-nb"
        "/proxy/notebook/user1/nb/api/kernels"
    )
    monkeypatch.delenv("DEV")
    r = CullingReconciler(FakeKube(), prober=lambda url: [])
    assert url != r.kernels_url("user1", "nb")
