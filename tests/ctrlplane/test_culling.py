import datetime

import pytest

from kubeflow_tpu.platform.apis import notebook as nbapi
from kubeflow_tpu.platform.controllers.culling import CullingReconciler
from kubeflow_tpu.platform.k8s.types import NOTEBOOK
from kubeflow_tpu.platform.runtime import Request
from kubeflow_tpu.platform.testing import FakeKube

from .test_notebook_controller import make_notebook

T0 = datetime.datetime(2026, 7, 29, 12, 0, 0, tzinfo=datetime.timezone.utc)


class Clock:
    def __init__(self):
        self.now = T0

    def __call__(self):
        return self.now

    def advance(self, minutes):
        self.now += datetime.timedelta(minutes=minutes)


@pytest.fixture
def kube():
    k = FakeKube()
    k.add_namespace("user1")
    k.create(make_notebook())
    return k


def kernels(state, last):
    return [{"execution_state": state, "last_activity": last}]


def test_busy_kernels_record_activity_not_cull(kube):
    clock = Clock()
    r = CullingReconciler(
        kube, prober=lambda url: kernels("busy", "2026-07-29T11:59:00Z"),
        idle_minutes=30, now=clock,
    )
    result = r.reconcile(Request("user1", "nb"))
    assert result and result.requeue_after == 60.0
    nb = kube.get(NOTEBOOK, "nb", "user1")
    assert not nbapi.is_stopped(nb)
    assert nb["metadata"]["annotations"][nbapi.LAST_ACTIVITY_ANNOTATION]


def test_idle_past_window_culls(kube):
    clock = Clock()
    r = CullingReconciler(
        kube, prober=lambda url: kernels("idle", "2026-07-29T11:00:00Z"),
        idle_minutes=30, now=clock,
    )
    clock.advance(45)  # now 12:45; last activity 11:00 → 105 min idle
    r.reconcile(Request("user1", "nb"))
    nb = kube.get(NOTEBOOK, "nb", "user1")
    assert nbapi.is_stopped(nb)


def test_idle_within_window_spares(kube):
    clock = Clock()
    r = CullingReconciler(
        kube, prober=lambda url: kernels("idle", "2026-07-29T11:50:00Z"),
        idle_minutes=30, now=clock,
    )
    r.reconcile(Request("user1", "nb"))
    assert not nbapi.is_stopped(kube.get(NOTEBOOK, "nb", "user1"))


def test_unreachable_notebook_not_culled(kube):
    r = CullingReconciler(kube, prober=lambda url: None, idle_minutes=0)
    result = r.reconcile(Request("user1", "nb"))
    assert result is not None  # requeues
    assert not nbapi.is_stopped(kube.get(NOTEBOOK, "nb", "user1"))


def test_already_stopped_is_noop(kube):
    nb = kube.get(NOTEBOOK, "nb", "user1")
    nb["metadata"].setdefault("annotations", {})[nbapi.STOP_ANNOTATION] = "x"
    kube.update(nb)
    calls = []
    r = CullingReconciler(kube, prober=lambda url: calls.append(url))
    assert r.reconcile(Request("user1", "nb")) is None
    assert calls == []  # no probe of a stopped notebook


def test_probe_url_targets_worker0_service():
    r = CullingReconciler(FakeKube(), prober=lambda url: None)
    assert r.kernels_url("user1", "nb") == (
        "http://nb.user1.svc.cluster.local/notebook/user1/nb/api/kernels"
    )


def test_probe_url_dev_mode_uses_kubectl_proxy(monkeypatch):
    # DEV != "false" probes through a local kubectl proxy instead of cluster
    # DNS (reference culling_controller.go:211-216).
    monkeypatch.setenv("DEV", "true")
    r = CullingReconciler(FakeKube(), prober=lambda url: [])
    url = r.kernels_url("user1", "nb")
    assert url == (
        "http://localhost:8001/api/v1/namespaces/user1/services/nb:http-nb"
        "/proxy/notebook/user1/nb/api/kernels"
    )
    monkeypatch.delenv("DEV")
    r = CullingReconciler(FakeKube(), prober=lambda url: [])
    assert url != r.kernels_url("user1", "nb")


def test_fleet_of_slow_probes_culls_within_budget(kube):
    """Fleet-scale culling (round 5): with 8 probe workers, a fleet where
    every probe takes 50 ms — and a few notebooks are unreachable-slow —
    still completes a full idleness sweep in a bounded time; one worker
    would serialize ~N x probe latency."""
    import threading
    import time as _time

    from kubeflow_tpu.platform.controllers.culling import make_controller

    N = 40
    for i in range(N):
        kube.create(make_notebook(f"fleet-{i}"))

    probed = set()
    lock = threading.Lock()

    def slow_prober(url):
        _time.sleep(0.25 if url.count("fleet-3") else 0.05)
        with lock:
            probed.add(url)
        return [{"execution_state": "idle",
                 "last_activity": "2000-01-01T00:00:00Z"}]

    ctrl = make_controller(
        kube, prober=slow_prober, idle_minutes=1.0,
        check_period_minutes=0.01,
    )
    assert ctrl.workers == 8
    ctrl.start(kube)
    try:
        deadline = _time.monotonic() + 20.0
        while _time.monotonic() < deadline:
            stopped = sum(
                1 for nb in kube.list(NOTEBOOK, "user1")
                if nb["metadata"]["name"].startswith("fleet-")
                and nbapi.is_stopped(nb))
            if stopped == N:
                break
            _time.sleep(0.05)
        else:
            raise AssertionError(
                f"only {stopped}/{N} culled within budget "
                f"({len(probed)} probed)")
    finally:
        ctrl.stop()


def test_probe_throttled_to_check_period(kube):
    """Watch-event storms (each _record_activity PATCH re-enqueues the
    key) must not probe faster than the check period — the probe rate is
    the operator's knob, not the delta rate (review r5)."""
    clock = Clock()
    probes = []
    r = CullingReconciler(
        kube,
        prober=lambda url: probes.append(url) or kernels(
            "busy", "2026-07-29T11:59:00Z"),
        idle_minutes=30, check_period_minutes=1.0, now=clock,
    )
    for _ in range(5):  # event storm within one period
        res = r.reconcile(Request("user1", "nb"))
    assert len(probes) == 1
    assert res.requeue_after <= 60.0
    clock.advance(1.01)  # next period: probe again
    r.reconcile(Request("user1", "nb"))
    assert len(probes) == 2
    # A clock step BACKWARDS must not extend the suppression window.
    clock.advance(-5)
    r.reconcile(Request("user1", "nb"))
    assert len(probes) == 3
    # A stopped notebook drops its throttle entry once the period passes
    # (the throttle runs before the GET, so cleanup lags one period).
    nb = kube.get(NOTEBOOK, "nb", "user1")
    nb["metadata"].setdefault("annotations", {})[nbapi.STOP_ANNOTATION] = "x"
    kube.update(nb)
    clock.advance(7)
    r.reconcile(Request("user1", "nb"))
    assert ("user1", "nb") not in r._last_probe
    assert len(probes) == 3  # a stopped notebook is never probed


def test_culler_shares_notebook_informer(kube):
    """The manager wires ONE Notebook informer into both the notebook and
    culling controllers (controller-runtime shared cache): one list+watch
    stream, both controllers' mappers fed, idempotent start."""
    import time as _time

    from kubeflow_tpu.platform.controllers import culling as culling_mod
    from kubeflow_tpu.platform.controllers.notebook import (
        make_controller as make_nb,
    )

    nb_ctrl = make_nb(kube, use_istio=False)
    shared = nb_ctrl.informers[NOTEBOOK]
    cull_ctrl = culling_mod.make_controller(
        kube, prober=lambda url: kernels("idle", "2000-01-01T00:00:00Z"),
        idle_minutes=0.0, check_period_minutes=0.001,
        notebook_informer=shared,
    )
    assert cull_ctrl.informers[NOTEBOOK] is shared
    nb_ctrl.start(kube)
    cull_ctrl.start(kube)  # second start of the shared informer: no-op
    try:
        kube.create(make_notebook("shared-nb"))
        deadline = _time.monotonic() + 10.0
        while _time.monotonic() < deadline:
            if nbapi.is_stopped(kube.get(NOTEBOOK, "shared-nb", "user1")):
                break
            _time.sleep(0.02)
        else:
            raise AssertionError("culler never acted on the shared stream")
    finally:
        nb_ctrl.stop()
        cull_ctrl.stop()


def test_sharer_stopping_does_not_kill_shared_informer(kube):
    """Lifecycle ownership (review r5): a controller handed a SHARED
    informer must never stop it — the sharer that dies first must not
    freeze the owner's cache."""
    from kubeflow_tpu.platform.controllers import culling as culling_mod
    from kubeflow_tpu.platform.controllers.notebook import (
        make_controller as make_nb,
    )

    nb_ctrl = make_nb(kube, use_istio=False)
    shared = nb_ctrl.informers[NOTEBOOK]
    cull_ctrl = culling_mod.make_controller(
        kube, prober=lambda url: None, notebook_informer=shared)
    nb_ctrl.start(kube)
    cull_ctrl.start(kube)
    try:
        cull_ctrl.stop()
        assert not shared._stop.is_set(), \
            "culler stopped the notebook controller's informer"
    finally:
        nb_ctrl.stop()
    assert shared._stop.is_set()  # the OWNER's stop does stop it
    # And a stopped informer refuses a zombie restart.
    with pytest.raises(RuntimeError, match="not restartable"):
        shared.start()
