"""EventRecorder correlation tests (runtime/events.py): same-reason
recurrence patches count on the existing Event, novel reasons create,
the spam-filter token bucket drops floods, and a vanished Event falls
back to a fresh create.
"""
from __future__ import annotations

from kubeflow_tpu.platform.k8s.types import EVENT
from kubeflow_tpu.platform.runtime.events import EventCorrelator, EventRecorder
from kubeflow_tpu.platform.testing import FakeKube


def _notebook(name="nb", ns="ns"):
    return {
        "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
        "metadata": {"name": name, "namespace": ns, "uid": "u1"},
    }


def _recorder(kube, **correlator_kwargs):
    return EventRecorder(
        kube, "test-component",
        correlator=EventCorrelator(**correlator_kwargs))


def test_same_reason_increments_count_via_patch_not_create(monkeypatch):
    kube = FakeKube()
    kube.add_namespace("ns")
    rec = _recorder(kube)
    nb = _notebook()

    first = rec.event(nb, "Warning", "ReconcileFailed", "boom 1")
    assert first["count"] == 1
    patches = []
    real_patch = kube.patch
    monkeypatch.setattr(
        kube, "patch",
        lambda *a, **k: patches.append(a) or real_patch(*a, **k))
    second = rec.event(nb, "Warning", "ReconcileFailed", "boom 2")
    third = rec.event(nb, "Warning", "ReconcileFailed", "boom 3")
    # One Event object total, count-incremented in place by PATCH.
    events = [e for e in kube.list(EVENT, "ns")
              if e.get("reason") == "ReconcileFailed"]
    assert len(events) == 1
    assert events[0]["count"] == 3
    assert events[0]["message"] == "boom 3"
    assert len(patches) == 2
    assert second["count"] == 2 and third["count"] == 3


def test_novel_reason_creates_a_fresh_event():
    kube = FakeKube()
    kube.add_namespace("ns")
    rec = _recorder(kube)
    nb = _notebook()
    rec.event(nb, "Warning", "ReconcileFailed", "boom")
    rec.event(nb, "Normal", "CreatedStatefulSet", "ok")
    reasons = {e.get("reason") for e in kube.list(EVENT, "ns")}
    assert reasons == {"ReconcileFailed", "CreatedStatefulSet"}


def test_distinct_objects_do_not_correlate():
    kube = FakeKube()
    kube.add_namespace("ns")
    rec = _recorder(kube)
    rec.event(_notebook("nb-a"), "Warning", "Fail", "x")
    rec.event(_notebook("nb-b"), "Warning", "Fail", "x")
    assert len(kube.list(EVENT, "ns")) == 2


def test_token_bucket_drops_floods():
    kube = FakeKube()
    kube.add_namespace("ns")
    rec = _recorder(kube, spam_burst=3, spam_refill_qps=0.0)
    nb = _notebook()
    results = [rec.event(nb, "Warning", "Flood", f"m{i}") for i in range(10)]
    # First `burst` calls land (1 create + 2 patches); the rest drop with
    # ZERO extra API traffic.
    assert [r is not None for r in results] == [True] * 3 + [False] * 7
    events = [e for e in kube.list(EVENT, "ns") if e.get("reason") == "Flood"]
    assert len(events) == 1
    assert events[0]["count"] == 3


def test_token_bucket_refills_over_time():
    now = [0.0]
    kube = FakeKube()
    kube.add_namespace("ns")
    rec = EventRecorder(
        kube, "c",
        correlator=EventCorrelator(spam_burst=1, spam_refill_qps=1.0,
                                   now=lambda: now[0]))
    nb = _notebook()
    assert rec.event(nb, "Warning", "R", "a") is not None
    assert rec.event(nb, "Warning", "R", "b") is None  # bucket empty
    now[0] = 1.5  # refill one token
    assert rec.event(nb, "Warning", "R", "c") is not None


def test_vanished_event_falls_back_to_fresh_create():
    kube = FakeKube()
    kube.add_namespace("ns")
    rec = _recorder(kube)
    nb = _notebook()
    first = rec.event(nb, "Warning", "Gone", "x")
    kube.delete(EVENT, first["metadata"]["name"], "ns")
    second = rec.event(nb, "Warning", "Gone", "y")
    assert second is not None
    assert second["count"] == 1  # fresh series, not a resurrected count
    assert second["metadata"]["name"] != first["metadata"]["name"]
    # And the NEW event is patchable again.
    third = rec.event(nb, "Warning", "Gone", "z")
    assert third["count"] == 2


def test_recorder_metrics_actions(monkeypatch):
    from kubeflow_tpu.platform.runtime import metrics

    def counter_value(action):
        return metrics.event_recorder_events_total.labels(
            action=action)._value.get()

    base = {a: counter_value(a) for a in ("create", "patch", "drop")}
    kube = FakeKube()
    kube.add_namespace("ns")
    rec = _recorder(kube, spam_burst=2, spam_refill_qps=0.0)
    nb = _notebook()
    rec.event(nb, "Warning", "M", "1")   # create
    rec.event(nb, "Warning", "M", "2")   # patch
    rec.event(nb, "Warning", "M", "3")   # drop
    assert counter_value("create") - base["create"] == 1
    assert counter_value("patch") - base["patch"] == 1
    assert counter_value("drop") - base["drop"] == 1


def test_recreated_object_starts_a_fresh_event_series():
    """A deleted-and-recreated same-name object (new uid) must not patch
    counts onto the predecessor's uid-bound Event (client-go aggregator
    key parity)."""
    kube = FakeKube()
    kube.add_namespace("ns")
    rec = _recorder(kube)
    old = dict(_notebook("nb"))
    rec.event(old, "Warning", "Fail", "x")
    new = {
        "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
        "metadata": {"name": "nb", "namespace": "ns", "uid": "u2"},
    }
    ev = rec.event(new, "Warning", "Fail", "x")
    assert ev["count"] == 1
    events = [e for e in kube.list(EVENT, "ns") if e.get("reason") == "Fail"]
    assert len(events) == 2
    assert {e["involvedObject"]["uid"] for e in events} == {"u1", "u2"}
