"""End-to-end: the real controller machinery (watch → queue → reconcile
threads) against the fake API server — SURVEY.md §4 tier-2 analogue."""
import time

import pytest

from kubeflow_tpu.platform.controllers.notebook import make_controller
from kubeflow_tpu.platform.k8s import errors
from kubeflow_tpu.platform.k8s.types import NOTEBOOK, STATEFULSET, deep_get
from kubeflow_tpu.platform.runtime import Manager
from kubeflow_tpu.platform.testing import FakeKube

from .test_notebook_controller import make_notebook


def wait_for(fn, timeout=10.0, interval=0.05):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            out = fn()
            if out:
                return out
        except errors.ApiError as e:
            last = e
        time.sleep(interval)
    raise AssertionError(f"condition not met within {timeout}s (last: {last})")


def test_notebook_lifecycle_through_manager():
    kube = FakeKube()
    kube.add_namespace("user1")
    mgr = Manager(kube)
    mgr.add(make_controller(kube, use_istio=False))
    mgr.start()
    try:
        kube.create(make_notebook(tpu={"accelerator": "v5e", "topology": "4x4"}))
        sts = wait_for(lambda: kube.get(STATEFULSET, "nb", "user1"))
        assert deep_get(sts, "spec", "replicas") == 2

        # kubelet-sim: bring worker 0 up; controller should mirror status.
        kube.create({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "nb-0", "namespace": "user1",
                         "labels": {"statefulset": "nb", "notebook-name": "nb"}},
        })
        kube.set_pod_phase("user1", "nb-0", "Running", ready=True)
        nb = wait_for(
            lambda: (
                lambda o: o if deep_get(o, "status", "readyReplicas") == 1 else None
            )(kube.get(NOTEBOOK, "nb", "user1"))
        )
        assert nb["status"]["conditions"][0]["status"] == "True"
    finally:
        mgr.stop()


def test_resync_reads_informer_cache_not_apiserver():
    """A controller whose primary is informer-sourced resyncs from the
    cache: the periodic re-list must not hit the apiserver with the full
    kind every period (round 5 — at fleet scale the raw LIST per period
    was the point of removing it)."""
    import threading
    import time

    from kubeflow_tpu.platform.k8s.types import NOTEBOOK
    from kubeflow_tpu.platform.runtime import Reconciler, Request
    from kubeflow_tpu.platform.runtime.controller import Controller
    from kubeflow_tpu.platform.runtime.informer import Informer
    from kubeflow_tpu.platform.testing import FakeKube

    kube = FakeKube()
    kube.add_namespace("ns")
    kube.create({
        "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
        "metadata": {"name": "nb", "namespace": "ns"},
        "spec": {"template": {"spec": {"containers": [{"name": "c"}]}}},
    })

    lists = []
    orig_list = kube.list

    def counting_list(gvk, namespace=None, **kw):
        lists.append(gvk.kind)
        return orig_list(gvk, namespace, **kw)

    seen = []
    done = threading.Event()

    class Probe(Reconciler):
        def reconcile(self, req):
            seen.append(req)
            if len(seen) >= 3:  # initial + >=2 resync passes
                done.set()

    informer = Informer(kube, NOTEBOOK)
    ctrl = Controller("resync-probe", Probe(), primary=NOTEBOOK,
                      informers={NOTEBOOK: informer}, resync_period=0.1)
    ctrl.start(kube)
    kube.list = counting_list  # count only POST-start lists
    try:
        assert done.wait(10.0), seen
        time.sleep(0.25)  # a couple more resync ticks under the counter
    finally:
        ctrl.stop()
        kube.list = orig_list
    # The resync ticks fed from the informer cache; the apiserver saw no
    # Notebook LISTs after startup (the informer's own resync is hourly).
    assert "Notebook" not in lists, lists
