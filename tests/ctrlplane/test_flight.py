"""FlightPool contract tests (runtime/flight.py): submission-order
results, per-slot error propagation, inline nesting, bounded concurrency.
"""
from __future__ import annotations

import threading
import time

import pytest

from kubeflow_tpu.platform.runtime.flight import FlightPool, shared_pool


def test_results_come_back_in_submission_order():
    pool = FlightPool(4)
    # Slower earlier slots: completion order is the REVERSE of submission
    # order, so any completion-ordered implementation fails this.
    delays = [0.05, 0.03, 0.01, 0.0]

    def make(i):
        def fn():
            time.sleep(delays[i])
            return i
        return fn

    assert pool.run([make(i) for i in range(4)]) == [0, 1, 2, 3]


def test_errors_propagate_per_slot():
    pool = FlightPool(4)

    def boom_a():
        raise ValueError("a")

    def boom_b():
        raise KeyError("b")

    settled = pool.run([lambda: "ok0", boom_a, lambda: "ok2", boom_b],
                       return_exceptions=True)
    assert settled[0] == "ok0" and settled[2] == "ok2"
    assert isinstance(settled[1], ValueError)
    assert isinstance(settled[3], KeyError)


def test_default_raises_first_error_after_all_slots_settle():
    pool = FlightPool(4)
    ran = []

    def boom():
        raise RuntimeError("first")

    def late_ok():
        time.sleep(0.02)
        ran.append("late")
        return "late"

    # The error slot finishes long before the slow sibling; run() must
    # still wait for the sibling (no partial fan-out) and then raise the
    # first-by-submission-order error.
    with pytest.raises(RuntimeError, match="first"):
        pool.run([boom, late_ok])
    assert ran == ["late"]


def test_nested_fanout_runs_inline_no_deadlock():
    # size=2 with 2 outer calls that each fan out 2 inner calls: if the
    # inner run() queued behind its own parents the pool would deadlock.
    pool = FlightPool(2)

    def outer(tag):
        def fn():
            return pool.run([lambda: f"{tag}-0", lambda: f"{tag}-1"])
        return fn

    out = pool.run([outer("a"), outer("b")])
    assert out == [["a-0", "a-1"], ["b-0", "b-1"]]


def test_concurrency_is_bounded_by_size():
    pool = FlightPool(2)
    lock = threading.Lock()
    state = {"now": 0, "peak": 0}

    def fn():
        with lock:
            state["now"] += 1
            state["peak"] = max(state["peak"], state["now"])
        time.sleep(0.01)
        with lock:
            state["now"] -= 1

    pool.run([fn] * 8)
    assert state["peak"] <= 2


def test_size_one_runs_inline():
    pool = FlightPool(1)
    tids = []

    def fn():
        tids.append(threading.get_ident())

    pool.run([fn, fn, fn])
    assert set(tids) == {threading.get_ident()}


def test_empty_and_single_call():
    pool = FlightPool(4)
    assert pool.run([]) == []
    assert pool.run([lambda: 7]) == [7]


def test_inline_path_settles_all_slots_before_raising():
    """size=1 (the determinism knob) must keep the pooled error contract:
    every call still runs, then the first error re-raises — a failed
    sibling never hides the others' writes at any pool size."""
    pool = FlightPool(1)
    ran = []

    def boom():
        raise RuntimeError("first")

    with pytest.raises(RuntimeError, match="first"):
        pool.run([boom, lambda: ran.append("late")])
    assert ran == ["late"]


def test_flight_carries_trace_and_causal_context():
    """The PR-8 fence carry extended (ISSUE 14): the submitting
    reconcile's CAUSAL context and its ACTIVE trace ride the same carry —
    a span opened inside a flight slot lands in the submitting
    reconcile's trace (not the worker thread's), and causal.current()
    inside a slot is the reconcile's context."""
    from kubeflow_tpu.platform.runtime import trace
    from kubeflow_tpu.telemetry import causal

    pool = FlightPool(4)
    ctx = causal.mint()
    tr = trace.begin("flight-probe", "ns/nb")
    assert tr is not None
    causal.set_current(ctx)
    try:
        seen = {}

        def slot(i):
            def fn():
                seen[i] = (causal.current(), trace.current(),
                           threading.get_ident())
                with trace.span(f"slot-{i}"):
                    time.sleep(0.005)
                return i
            return fn

        assert pool.run([slot(i) for i in range(3)]) == [0, 1, 2]
        # Ran on pool threads, not inline.
        assert any(t[2] != threading.get_ident() for t in seen.values())
        for got_ctx, got_tr, _tid in seen.values():
            assert got_ctx is ctx
            assert got_tr is tr
    finally:
        causal.set_current(None)
        d = trace.finish()
    names = [s["name"] for s in d["spans"]]
    assert sorted(n for n in names if n.startswith("slot-")) == [
        "slot-0", "slot-1", "slot-2"]


def test_shared_pool_is_a_singleton():
    assert shared_pool() is shared_pool()
    assert shared_pool().size >= 1


def test_shared_pool_follows_env_changes(monkeypatch):
    """The monkeypatch-then-construct recipe must actually take effect:
    a changed CONTROLLER_FLIGHT_POOL_SIZE yields a fresh singleton."""
    before = shared_pool()
    monkeypatch.setenv("CONTROLLER_FLIGHT_POOL_SIZE", "1")
    one = shared_pool()
    assert one.size == 1 and one is not before
    assert shared_pool() is one  # stable while the env holds
    monkeypatch.delenv("CONTROLLER_FLIGHT_POOL_SIZE")
    assert shared_pool().size == before.size
