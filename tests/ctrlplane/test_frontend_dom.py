"""Executable frontend tests: the SHIPPED app.js runs under the JS
interpreter + DOM shim against the REAL Flask/WSGI backends (VERDICT r1
item 4 — replaces string-grep contract tests; the reference gates this tier
with Cypress, reference jupyter/frontend/cypress/e2e/form-page.cy.ts).

Every test here drives the same artifacts a browser would: parse
index.html, execute app.js, click/type/submit, and assert on what reached
the backend (FakeKube) and what rendered back into the DOM.  Renaming a DOM
id, form field, or API path breaks these tests."""
from __future__ import annotations

import os

import pytest
from werkzeug.test import Client

from kubeflow_tpu.platform.k8s import errors
from kubeflow_tpu.platform.k8s.types import NOTEBOOK, PVC, deep_get
from kubeflow_tpu.platform.testing import FakeKube
from kubeflow_tpu.platform.testing.jsdom import BrowserHarness

FRONTEND = os.path.join(
    os.path.dirname(__file__), "..", "..",
    "kubeflow_tpu", "platform", "frontend",
)


def harness(app_name: str, create_app, kube, **kw):
    client = Client(create_app(kube, secure_cookies=False))
    return BrowserHarness(
        os.path.join(FRONTEND, app_name), client,
        url="http://spa.test/?ns=user1", **kw,
    )


@pytest.fixture
def kube():
    k = FakeKube()
    k.add_namespace("user1")
    k.add_tpu_node("tpu-node-1", topology="2x4")
    return k


@pytest.fixture
def jupyter(kube):
    from kubeflow_tpu.platform.apps.jupyter.app import create_app

    return harness("jupyter", create_app, kube)


# -- jupyter spawner ----------------------------------------------------------


def test_spawn_flow_end_to_end(kube, jupyter):
    """Open the dialog, pick a TPU, submit — the POST body is built by the
    shipped JS and the backend creates the Notebook CR."""
    jupyter.click("#new-notebook")
    assert jupyter.get("spawner").open
    # The accelerator list came from the real /api/namespaces/<ns>/tpus.
    accs = [o.textContent for o in jupyter.query_all("#tpu-acc option")]
    assert "v5e" in accs
    jupyter.set_value("[name=name]", "my-nb", event="input")
    jupyter.set_value("[name=cpu]", "3", event="input")
    jupyter.set_value("[name=memory]", "9Gi", event="input")
    jupyter.set_value("#tpu-acc", "v5e")  # change event populates topologies
    topos = [o.textContent for o in jupyter.query_all("#tpu-topo option")]
    assert topos == ["2x4"]
    jupyter.set_value("#tpu-topo", "2x4")
    jupyter.submit("#spawn-form")

    nb = kube.get(NOTEBOOK, "my-nb", "user1")
    assert nb["spec"]["tpu"] == {"accelerator": "v5e", "topology": "2x4"}
    # cpu/memory typed into the form reached the container resources.
    container = deep_get(nb, "spec", "template", "spec", "containers")[0]
    requests = container["resources"]["requests"]
    assert requests["cpu"] == "3" and requests["memory"] == "9Gi"
    # Default workspace toggle: a workspace PVC was provisioned + mounted.
    volumes = deep_get(nb, "spec", "template", "spec", "volumes", default=[])
    assert any("workspace" in (v.get("name") or "") for v in volumes)
    assert "Launching my-nb" in jupyter.text("#toast")
    assert not jupyter.get("spawner").open


def test_spawn_workspace_none_sends_null_volume(kube, jupyter):
    """workspace=none in the form must reach the backend as an explicit
    workspaceVolume: null (no PVC provisioned)."""
    jupyter.click("#new-notebook")
    jupyter.set_value("[name=name]", "no-ws", event="input")
    jupyter.set_value("#workspace-select", "none")
    jupyter.submit("#spawn-form")
    nb = kube.get(NOTEBOOK, "no-ws", "user1")
    volumes = deep_get(nb, "spec", "template", "spec", "volumes", default=[])
    assert not any("workspace" in (v.get("name") or "") for v in volumes)
    assert kube.list(PVC, "user1") == []


def test_spawn_custom_image_toggle(kube, jupyter):
    jupyter.click("#new-notebook")
    assert jupyter.get("custom-image-row").hidden
    jupyter.set_value("#image-select", "__custom__")
    assert not jupyter.get("custom-image-row").hidden
    jupyter.set_value("[name=name]", "cust", event="input")
    jupyter.set_value("[name=customImage]", "registry.io/me/img:1", event="input")
    jupyter.submit("#spawn-form")
    nb = kube.get(NOTEBOOK, "cust", "user1")
    image = deep_get(nb, "spec", "template", "spec", "containers")[0]["image"]
    assert image == "registry.io/me/img:1"


def test_spawn_error_surfaces_as_toast(kube, jupyter):
    jupyter.click("#new-notebook")
    # Empty name: backend 400s; the JS must toast the error, not crash.
    jupyter.submit("#spawn-form")
    assert jupyter.query("#toast").classList.contains("error")
    assert kube.list(NOTEBOOK, "user1") == []


def test_table_stop_and_delete_actions(kube, jupyter):
    from kubeflow_tpu.platform.apis import notebook as nbapi

    kube.create({
        "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
        "metadata": {"name": "running-nb", "namespace": "user1"},
        "spec": {"template": {"spec": {"containers": [
            {"name": "running-nb", "image": "img"}]}}},
    })
    jupyter.fire_timers()  # poll() refresh
    rows = jupyter.query_all("#nb-table tbody tr")
    assert len(rows) == 1 and "running-nb" in rows[0].textContent

    jupyter.query("#nb-table tbody button.ghost").click()  # Stop
    nb = kube.get(NOTEBOOK, "running-nb", "user1")
    assert deep_get(nb, "metadata", "annotations",
                    nbapi.STOP_ANNOTATION) is not None

    jupyter.confirm_response = False
    jupyter.query("#nb-table tbody button.danger").click()  # Delete, refused
    assert kube.get(NOTEBOOK, "running-nb", "user1") is not None
    jupyter.confirm_response = True
    jupyter.query("#nb-table tbody button.danger").click()
    assert jupyter.confirm_prompts[-1].startswith("Delete notebook running-nb")
    with pytest.raises(errors.NotFound):
        kube.get(NOTEBOOK, "running-nb", "user1")


def test_poddefault_chips_reach_post_body(kube, jupyter):
    from kubeflow_tpu.platform.apis.poddefault import tpu_pod_default

    kube.create(tpu_pod_default("user1", "v5e", "2x4"))
    jupyter.click("#new-notebook")
    chips = jupyter.query_all("#poddefault-chips .chip")
    assert len(chips) == 1
    chips[0].click()  # toggle on
    jupyter.set_value("[name=name]", "with-pd", event="input")
    jupyter.submit("#spawn-form")
    nb = kube.get(NOTEBOOK, "with-pd", "user1")
    # PodDefault opt-ins become labels the webhook selector matches
    # (form.py _set_configurations).
    labels = deep_get(nb, "metadata", "labels", default={})
    assert "true" in labels.values()


def test_csrf_token_round_trips_through_cookie(jupyter):
    """api() reads XSRF-TOKEN from document.cookie and echoes it as the
    X-XSRF-TOKEN header — the double-submit contract, executed."""
    jupyter.click("#new-notebook")
    jupyter.set_value("[name=name]", "csrf-nb", event="input")
    jupyter.submit("#spawn-form")
    post = next(r for r in jupyter.requests if r["method"] == "POST")
    assert post["path"].endswith("/notebooks")


# -- volumes -----------------------------------------------------------------


def test_volumes_create_and_guarded_delete(kube):
    from kubeflow_tpu.platform.apps.volumes.app import create_app

    h = harness("volumes", create_app, kube)
    h.click("#new-pvc")
    h.set_value("[name=name]", "data-1", event="input")
    h.set_value("[name=size]", "20Gi", event="input")
    h.submit("#create-form")
    pvc = kube.get(PVC, "data-1", "user1")
    assert pvc["spec"]["resources"]["requests"]["storage"] == "20Gi"

    h.fire_timers()
    rows = h.query_all("#pvc-table tbody tr")
    assert len(rows) == 1 and "data-1" in rows[0].textContent

    h.query("#pvc-table tbody button.danger").click()
    assert "Data is lost permanently" in h.confirm_prompts[-1]
    with pytest.raises(errors.NotFound):
        kube.get(PVC, "data-1", "user1")


def test_volumes_mounted_pvc_delete_disabled(kube):
    from kubeflow_tpu.platform.apps.volumes.app import create_app

    kube.create({
        "apiVersion": "v1", "kind": "PersistentVolumeClaim",
        "metadata": {"name": "busy", "namespace": "user1"},
        "spec": {"resources": {"requests": {"storage": "1Gi"}},
                 "accessModes": ["ReadWriteOnce"]},
    })
    kube.create({
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "user-pod", "namespace": "user1"},
        "spec": {"volumes": [
            {"name": "v", "persistentVolumeClaim": {"claimName": "busy"}}],
            "containers": [{"name": "c", "image": "i"}]},
    })
    h = harness("volumes", create_app, kube)
    h.fire_timers()
    btn = h.query("#pvc-table tbody button.danger")
    assert btn.hasAttribute("disabled")
    assert "user-pod" in h.query("#pvc-table tbody").textContent


# -- tensorboards ------------------------------------------------------------


def test_tensorboards_create_from_form(kube):
    from kubeflow_tpu.platform.apps.tensorboards.app import create_app
    from kubeflow_tpu.platform.k8s.types import TENSORBOARD

    h = harness("tensorboards", create_app, kube)
    h.click("#new-tb")
    h.set_value("[name=name]", "tb-1", event="input")
    h.set_value("[name=logspath]", "pvc://data-1/logs", event="input")
    h.submit("#create-form")
    tb = kube.get(TENSORBOARD, "tb-1", "user1")
    assert tb["spec"]["logspath"] == "pvc://data-1/logs"
    h.fire_timers()
    assert "tb-1" in h.query("#tb-table tbody").textContent


# -- dashboard ---------------------------------------------------------------


@pytest.fixture
def dashboard_env():
    """Dashboard + a synchronous profile control plane: Profile creates
    reconcile inline (namespace/RBAC exist by the time the register POST
    returns), so the JS's follow-up env-info fetch sees the workgroup —
    deterministic where the threaded e2e harness polls."""
    from kubeflow_tpu.platform.controllers.profile import ProfileReconciler
    from kubeflow_tpu.platform.dashboard.app import create_app
    from kubeflow_tpu.platform.k8s.types import name_of
    from kubeflow_tpu.platform.runtime import Request

    class SyncProfileKube(FakeKube):
        def create(self, obj, **kw):
            out = super().create(obj, **kw)
            if obj.get("kind") == "Profile":
                ProfileReconciler(self).reconcile(Request("", name_of(out)))
            return out

    kube = SyncProfileKube()
    kube.add_namespace("kubeflow")
    h = harness("dashboard", create_app, kube, user="owner@x.io")
    return h, kube


def test_dashboard_register_then_contributors(dashboard_env):
    h, kube = dashboard_env
    # Fresh user: the register card shows.
    assert not h.get("register-card").hidden
    h.click("#register-btn")
    assert "Created namespace" in h.text("#toast")
    assert h.get("register-card").hidden
    ns_options = [o.value for o in h.get("ns-select").options]
    assert len(ns_options) == 1
    ns = ns_options[0]

    # Add a contributor through the real form + backend.
    h.query("[data-view=contributors]").click()
    assert not h.get("view-contributors").hidden
    h.set_value("[name=contributor]", "bob@x.io", event="input")
    h.submit("#contrib-form")
    body = h.query("#contrib-table tbody").textContent
    assert "bob@x.io" in body and "contributor" in body

    # Remove again via the rendered button.
    h.query("#contrib-table tbody button.danger").click()
    assert "bob@x.io" not in h.query("#contrib-table tbody").textContent
    assert ns  # silences linters; ns asserted above


def test_dashboard_home_shows_namespace_tpu_quota_card(dashboard_env):
    """The home quota card shows the namespace's chips-remaining under the
    SAME accounting as the spawner picker (a running notebook's declared
    chips count), and hides when no quota constrains the namespace."""
    h, kube = dashboard_env
    h.click("#register-btn")
    ns = h.get("ns-select").options[0].value
    # No quota yet: the card stays hidden after a refresh.
    h.set_value("#ns-select", ns)
    assert h.get("quota-card").hidden
    kube.create({
        "apiVersion": "v1", "kind": "ResourceQuota",
        "metadata": {"name": "dash-quota", "namespace": ns},
        "spec": {"hard": {"google.com/tpu": "16"}},
    })
    kube.create({
        "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
        "metadata": {"name": "holder", "namespace": ns},
        "spec": {"tpu": {"accelerator": "v5e", "topology": "2x4"}},
    })
    # Re-select the namespace to trigger refreshHome through the UI.
    h.set_value("#ns-select", ns)
    card = h.get("quota-card")
    assert not card.hidden
    assert h.text("#stat-quota") == "8 of 16 chips free"
    assert ns in h.text("#quota-card-title")


def test_dashboard_activity_feed_renders_events(dashboard_env):
    h, kube = dashboard_env
    h.click("#register-btn")
    ns = h.get("ns-select").options[0].value
    kube.create({
        "apiVersion": "v1", "kind": "Event",
        "metadata": {"name": "ev-1", "namespace": ns},
        "involvedObject": {"kind": "Notebook", "name": "nb-1",
                           "namespace": ns},
        "reason": "FailedScheduling",
        "message": "0/3 nodes available: insufficient google.com/tpu",
        "type": "Warning",
        "lastTimestamp": "2099-01-01T00:00:00Z",
    })
    # No poll timer on the dashboard: re-selecting the namespace fires the
    # change handler, which re-fetches the activity feed.
    h.set_value("#ns-select", ns)
    feed = h.query("#activity-table tbody").textContent
    assert "Notebook/nb-1" in feed
    assert "insufficient google.com/tpu" in feed


# -- jupyter spawner depth (VERDICT r1 item 1) -------------------------------


def test_server_type_switches_image_group(kube, jupyter):
    jupyter.click("#new-notebook")
    first = [o.value for o in jupyter.get("image-select").options]
    assert any("jupyter-jax-tpu" in v for v in first)
    jupyter.query("[name=serverType][value=group-two]").click()
    second = [o.value for o in jupyter.get("image-select").options]
    assert any("codeserver" in v for v in second)
    jupyter.set_value("[name=name]", "vs", event="input")
    jupyter.submit("#spawn-form")
    nb = kube.get(NOTEBOOK, "vs", "user1")
    image = deep_get(nb, "spec", "template", "spec", "containers")[0]["image"]
    assert "codeserver" in image


def test_custom_workspace_volume_name_and_size(kube, jupyter):
    jupyter.click("#new-notebook")
    jupyter.set_value("[name=name]", "ws-nb", event="input")
    jupyter.set_value("#workspace-select", "custom")
    assert not jupyter.get("workspace-custom-row").hidden
    jupyter.set_value("[name=workspaceName]", "scratch", event="input")
    jupyter.set_value("[name=workspaceSize]", "42Gi", event="input")
    jupyter.submit("#spawn-form")
    pvc = kube.get(PVC, "scratch", "user1")
    assert pvc["spec"]["resources"]["requests"]["storage"] == "42Gi"
    nb = kube.get(NOTEBOOK, "ws-nb", "user1")
    volumes = deep_get(nb, "spec", "template", "spec", "volumes", default=[])
    assert any(v.get("name") == "scratch" for v in volumes)


def test_data_volume_new_pvc_row(kube, jupyter):
    jupyter.click("#new-notebook")
    jupyter.set_value("[name=name]", "dv-nb", event="input")
    jupyter.click("#add-volume")
    jupyter.set_value("#data-volumes .vol-row .vol-name", "dv-data", event="input")
    jupyter.set_value("#data-volumes .vol-row .vol-size", "5Gi", event="input")
    jupyter.set_value("#data-volumes .vol-row .vol-mount", "/data/x", event="input")
    jupyter.submit("#spawn-form")
    pvc = kube.get(PVC, "dv-data", "user1")
    assert pvc["spec"]["resources"]["requests"]["storage"] == "5Gi"
    nb = kube.get(NOTEBOOK, "dv-nb", "user1")
    mounts = deep_get(nb, "spec", "template", "spec", "containers")[0]["volumeMounts"]
    assert {"name": "dv-data", "mountPath": "/data/x"} in mounts


def test_data_volume_attach_existing_pvc(kube, jupyter):
    kube.create({
        "apiVersion": "v1", "kind": "PersistentVolumeClaim",
        "metadata": {"name": "datasets", "namespace": "user1"},
        "spec": {"resources": {"requests": {"storage": "100Gi"}},
                 "accessModes": ["ReadWriteMany"]},
    })
    jupyter.click("#new-notebook")
    jupyter.set_value("[name=name]", "att-nb", event="input")
    jupyter.click("#add-volume")
    jupyter.set_value("#data-volumes .vol-row .vol-type", "existing")
    # The existing-PVC dropdown was filled from the real /pvcs route.
    opts = [o.value for o in jupyter.query("#data-volumes .vol-row .vol-existing").options]
    assert "datasets" in opts
    jupyter.set_value("#data-volumes .vol-row .vol-existing", "datasets")
    jupyter.set_value("#data-volumes .vol-row .vol-mount", "/data/sets", event="input")
    jupyter.submit("#spawn-form")
    nb = kube.get(NOTEBOOK, "att-nb", "user1")
    volumes = deep_get(nb, "spec", "template", "spec", "volumes", default=[])
    assert {"name": "datasets",
            "persistentVolumeClaim": {"claimName": "datasets"}} in volumes
    # No second PVC was created for the attach.
    assert len(kube.list(PVC, "user1")) == 2  # datasets + workspace


def test_volume_row_remove_button(kube, jupyter):
    jupyter.click("#new-notebook")
    jupyter.click("#add-volume")
    jupyter.click("#add-volume")
    assert len(jupyter.query_all("#data-volumes .vol-row")) == 2
    jupyter.query("#data-volumes .vol-row .vol-remove").click()
    assert len(jupyter.query_all("#data-volumes .vol-row")) == 1


def test_shm_checkbox_controls_dshm_volume(kube, jupyter):
    jupyter.click("#new-notebook")
    shm = jupyter.get("shm-check")
    assert shm.checked  # config default shm: true
    jupyter.set_value("[name=name]", "with-shm", event="input")
    jupyter.submit("#spawn-form")
    nb = kube.get(NOTEBOOK, "with-shm", "user1")
    volumes = deep_get(nb, "spec", "template", "spec", "volumes", default=[])
    assert any(v.get("name") == "dshm" for v in volumes)

    jupyter.click("#new-notebook")
    jupyter.set_value("[name=name]", "no-shm", event="input")
    jupyter.get("shm-check").click()  # uncheck
    jupyter.submit("#spawn-form")
    nb = kube.get(NOTEBOOK, "no-shm", "user1")
    volumes = deep_get(nb, "spec", "template", "spec", "volumes", default=[])
    assert not any(v.get("name") == "dshm" for v in volumes)


def test_affinity_and_toleration_groups(kube, jupyter):
    jupyter.click("#new-notebook")
    jupyter.set_value("[name=name]", "adv-nb", event="input")
    jupyter.set_value("#affinity-select", "tpu-node-pool")
    jupyter.set_value("#toleration-select", "tpu-reserved")
    jupyter.submit("#spawn-form")
    nb = kube.get(NOTEBOOK, "adv-nb", "user1")
    spec = deep_get(nb, "spec", "template", "spec")
    assert "nodeAffinity" in spec["affinity"]
    assert spec["tolerations"][0]["key"] == "google.com/tpu"


def test_read_only_field_is_disabled_and_admin_value_wins(kube, tmp_path):
    """readOnly cpu: the control disables (so the browser omits it) and the
    backend enforces the admin value regardless."""
    import yaml

    from kubeflow_tpu.platform.apps.jupyter.app import create_app
    from kubeflow_tpu.platform.apps.jupyter.form import load_spawner_config

    cfg = load_spawner_config()
    cfg = {**cfg, "cpu": {"value": "2", "readOnly": True}}
    path = tmp_path / "spawner.yaml"
    path.write_text(yaml.safe_dump({"spawnerFormDefaults": cfg}))

    client = Client(create_app(kube, secure_cookies=False,
                               spawner_config_path=str(path)))
    h = BrowserHarness(os.path.join(FRONTEND, "jupyter"), client,
                       url="http://spa.test/?ns=user1")
    h.click("#new-notebook")
    assert h.query("[name=cpu]").disabled
    h.set_value("[name=name]", "ro-nb", event="input")
    h.submit("#spawn-form")
    nb = kube.get(NOTEBOOK, "ro-nb", "user1")
    requests = deep_get(nb, "spec", "template", "spec",
                        "containers")[0]["resources"]["requests"]
    assert requests["cpu"] == "2"


def _harness_with_config(kube, tmp_path, overrides, *, drop=()):
    """SPA harness against a backend whose spawner config is patched."""
    import yaml

    from kubeflow_tpu.platform.apps.jupyter.app import create_app
    from kubeflow_tpu.platform.apps.jupyter.form import load_spawner_config

    cfg = {**load_spawner_config(), **overrides}
    for key in drop:
        cfg.pop(key, None)
    path = tmp_path / "spawner.yaml"
    path.write_text(yaml.safe_dump({"spawnerFormDefaults": cfg}))
    client = Client(create_app(kube, secure_cookies=False,
                               spawner_config_path=str(path)))
    return BrowserHarness(os.path.join(FRONTEND, "jupyter"), client,
                          url="http://spa.test/?ns=user1"), client


def test_image_pull_policy_round_trips_to_container(kube, jupyter):
    """Default config carries imagePullPolicy Always (the option images
    are :latest tags); the SPA shows the select and a user override lands
    on the container (VERDICT r2 item 7; reference
    spawner_ui_config.yaml:14-29)."""
    jupyter.click("#new-notebook")
    assert not jupyter.get("image-pull-policy-row").hidden
    assert jupyter.query("#image-pull-policy").value == "Always"  # admin default
    jupyter.set_value("[name=name]", "pp-nb", event="input")
    jupyter.set_value("#image-pull-policy", "IfNotPresent")  # user override
    jupyter.submit("#spawn-form")
    nb = kube.get(NOTEBOOK, "pp-nb", "user1")
    container = deep_get(nb, "spec", "template", "spec", "containers")[0]
    assert container["imagePullPolicy"] == "IfNotPresent"


def test_image_pull_policy_default_survives_dialog_reopen(kube, jupyter):
    """form.reset() after a spawn reverts selects to HTML attributes; the
    reopen handler must re-apply the admin default (Always), not the first
    <option> (IfNotPresent)."""
    jupyter.click("#new-notebook")
    assert jupyter.query("#image-pull-policy").value == "Always"
    jupyter.set_value("[name=name]", "first", event="input")
    jupyter.submit("#spawn-form")
    jupyter.click("#new-notebook")
    assert jupyter.query("#image-pull-policy").value == "Always"
    jupyter.set_value("[name=name]", "second", event="input")
    jupyter.submit("#spawn-form")
    nb = kube.get(NOTEBOOK, "second", "user1")
    container = deep_get(nb, "spec", "template", "spec", "containers")[0]
    assert container["imagePullPolicy"] == "Always"


def test_image_pull_policy_ignored_when_knob_absent(kube, tmp_path):
    """Without the knob in the admin config, a hand-crafted body value is
    ignored (SPA hiding the control is not a gate) and the container keeps
    kubelet's default."""
    h, client = _harness_with_config(kube, tmp_path, {},
                                     drop=("imagePullPolicy",))
    h.click("#new-notebook")
    assert h.get("image-pull-policy-row").hidden
    resp = client.post(
        "/api/namespaces/user1/notebooks",
        json={"name": "no-knob", "imagePullPolicy": "Never"},
        headers={"kubeflow-userid": "test-user@kubeflow.org"},
    )
    assert resp.status_code == 200, resp.get_data(as_text=True)
    nb = kube.get(NOTEBOOK, "no-knob", "user1")
    container = deep_get(nb, "spec", "template", "spec", "containers")[0]
    assert "imagePullPolicy" not in container


def test_image_pull_policy_readonly_pins_admin_value(kube, tmp_path):
    h, _ = _harness_with_config(kube, tmp_path, {
        "imagePullPolicy": {"value": "Never", "readOnly": True},
    })
    h.click("#new-notebook")
    assert h.query("#image-pull-policy").disabled
    h.set_value("[name=name]", "pp-ro", event="input")
    h.submit("#spawn-form")
    nb = kube.get(NOTEBOOK, "pp-ro", "user1")
    container = deep_get(nb, "spec", "template", "spec", "containers")[0]
    assert container["imagePullPolicy"] == "Never"


def test_allow_custom_image_false_hides_option_and_backend_rejects(
        kube, tmp_path):
    """allowCustomImage=false removes the custom option from the executed
    SPA AND the backend 400s a hand-crafted customImage body."""
    h, client = _harness_with_config(kube, tmp_path,
                                     {"allowCustomImage": False})
    h.click("#new-notebook")
    values = [o.value for o in h.query_all("#image-select option")]
    assert "__custom__" not in values
    # Defense in depth: bypass the SPA entirely (same trusted-header
    # identity the harness uses).
    resp = client.post(
        "/api/namespaces/user1/notebooks",
        json={"name": "sneak", "customImage": "evil/img:1",
              "customImageCheck": True},
        headers={"kubeflow-userid": "test-user@kubeflow.org"},
    )
    assert resp.status_code == 400
    assert "custom images are disabled" in resp.get_data(as_text=True)
    assert kube.list(NOTEBOOK, "user1") == []


def test_hide_registry_and_tag_rewrite_displayed_names_only(kube, tmp_path):
    """hideRegistry/hideTag change option LABELS; the submitted CR keeps
    the full image reference."""
    h, _ = _harness_with_config(kube, tmp_path, {
        "hideRegistry": True, "hideTag": True,
    })
    h.click("#new-notebook")
    labels = [o.textContent for o in h.query_all("#image-select option")]
    shown = [l for l in labels if "custom" not in l]
    assert all("ghcr.io/" not in l for l in shown), labels
    assert all(":" not in l for l in shown), labels
    assert "kubeflow-tpu/jupyter-jax-tpu" in shown[0]
    h.set_value("[name=name]", "disp-nb", event="input")
    h.submit("#spawn-form")
    nb = kube.get(NOTEBOOK, "disp-nb", "user1")
    image = deep_get(nb, "spec", "template", "spec", "containers")[0]["image"]
    assert image == "ghcr.io/kubeflow-tpu/jupyter-jax-tpu:latest"


def test_hide_registry_false_shows_full_reference(kube, tmp_path):
    h, _ = _harness_with_config(kube, tmp_path, {
        "hideRegistry": False, "hideTag": False,
    })
    h.click("#new-notebook")
    labels = [o.textContent for o in h.query_all("#image-select option")]
    assert "ghcr.io/kubeflow-tpu/jupyter-jax-tpu:latest" in labels


# -- table ergonomics: sort / filter / pagination (VERDICT r2 item 6) --------


def _mk_nb(kube, name, image="img"):
    kube.create({
        "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
        "metadata": {"name": name, "namespace": "user1"},
        "spec": {"template": {"spec": {"containers": [
            {"name": name, "image": image}]}}},
    })


def test_notebook_table_pagination(kube, jupyter):
    """12 notebooks: page 1 shows 10 with a pager, next shows the rest."""
    for i in range(12):
        _mk_nb(kube, f"nb-{i:02d}")
    jupyter.fire_timers()  # poll() refresh
    assert len(jupyter.query_all("#nb-table tbody tr")) == 10
    assert "1–10 of 12" in jupyter.query("#nb-pager .pager-label").textContent
    assert jupyter.query("#nb-pager .pager-prev").disabled
    jupyter.query("#nb-pager .pager-next").click()
    assert len(jupyter.query_all("#nb-table tbody tr")) == 2
    assert "11–12 of 12" in jupyter.query("#nb-pager .pager-label").textContent
    assert jupyter.query("#nb-pager .pager-next").disabled
    jupyter.query("#nb-pager .pager-prev").click()
    assert len(jupyter.query_all("#nb-table tbody tr")) == 10


def test_notebook_table_sort_toggles(kube, jupyter):
    for name in ("charlie", "alpha", "bravo"):
        _mk_nb(kube, name)
    jupyter.fire_timers()

    def names():
        return [a.textContent
                for a in jupyter.query_all("#nb-table tbody a.nb-name")]

    th = jupyter.query('th[data-sort="name"]')
    th.click()
    assert names() == ["alpha", "bravo", "charlie"]
    assert "sort-asc" in th.className
    th.click()
    assert names() == ["charlie", "bravo", "alpha"]
    assert "sort-desc" in th.className


def test_notebook_table_memory_sorts_as_quantity(kube, jupyter):
    """512Mi must rank below 1Gi — quantities sort numerically, not
    lexicographically."""
    for name, mem in (("small", "512Mi"), ("big", "2Gi"), ("mid", "1Gi")):
        kube.create({
            "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
            "metadata": {"name": name, "namespace": "user1"},
            "spec": {"template": {"spec": {"containers": [{
                "name": name, "image": "img",
                "resources": {"requests": {"memory": mem}},
            }]}}},
        })
    jupyter.fire_timers()
    jupyter.query('th[data-sort="memory"]').click()
    names = [a.textContent
             for a in jupyter.query_all("#nb-table tbody a.nb-name")]
    assert names == ["small", "mid", "big"]


def test_notebook_table_filter(kube, jupyter):
    _mk_nb(kube, "train-1")
    _mk_nb(kube, "train-2")
    _mk_nb(kube, "serve-1")
    jupyter.fire_timers()
    jupyter.set_value("#nb-filter", "train", event="input")
    rows = jupyter.query_all("#nb-table tbody tr")
    assert len(rows) == 2
    assert all("train" in r.textContent for r in rows)
    jupyter.set_value("#nb-filter", "", event="input")
    assert len(jupyter.query_all("#nb-table tbody tr")) == 3


def test_volumes_table_sort_and_filter(kube):
    from kubeflow_tpu.platform.apps.volumes.app import create_app

    for name, size in (("zeta-vol", "5Gi"), ("alpha-vol", "10Gi")):
        kube.create({
            "apiVersion": "v1", "kind": "PersistentVolumeClaim",
            "metadata": {"name": name, "namespace": "user1"},
            "spec": {"resources": {"requests": {"storage": size}},
                     "accessModes": ["ReadWriteOnce"]},
            "status": {"phase": "Bound"},
        })
    h = harness("volumes", create_app, kube)
    h.query('th[data-sort="name"]').click()
    names = [a.textContent for a in h.query_all("#pvc-table tbody a.pvc-name")]
    assert names == ["alpha-vol", "zeta-vol"]
    h.set_value("#pvc-filter", "zeta", event="input")
    assert len(h.query_all("#pvc-table tbody tr")) == 1


# -- dashboard resource chart (VERDICT r2 item 6) ----------------------------


def test_dashboard_chart_renders_series_from_metrics(kube):
    """A wired metrics service renders one SVG polyline per label plus a
    legend, driven by the executed JS against /api/metrics/<type>."""
    from kubeflow_tpu.platform.dashboard.app import create_app
    from kubeflow_tpu.platform.dashboard.metrics_service import (
        MetricsService,
        TimeSeriesPoint,
    )

    class FakeMetrics(MetricsService):
        def node_cpu_utilization(self, interval):
            return [TimeSeriesPoint(1000 + 60 * i, label, 0.1 * i + bias)
                    for label, bias in (("node-a", 0.0), ("node-b", 0.3))
                    for i in range(5)]

        def tpu_duty_cycle(self, interval):
            return []

    kube.add_namespace("kubeflow")

    def make(k, **kw):
        return create_app(k, metrics_service=FakeMetrics(), **kw)

    h = harness("dashboard", make, kube, user="owner@x.io")
    assert not h.get("metrics-card").hidden
    lines = h.query_all("#metric-chart polyline")
    assert len(lines) == 2
    assert {l.getAttribute("data-series") for l in lines} == {"node-a", "node-b"}
    # Points are "x,y x,y ..." pairs inside the padded viewport.
    pts = lines[0].getAttribute("points").split(" ")
    assert len(pts) == 5
    legend = [s.textContent for s in h.query_all("#metric-legend .legend-item")]
    assert legend == ["node-a", "node-b"]
    # Switching to an empty metric type shows the empty note.
    h.set_value("#metric-type", "tpu")
    assert h.get("metrics-empty").hidden is False
    assert len(h.query_all("#metric-chart polyline")) == 0
    # A per-type 405 (podcpu not implemented by this service) must NOT
    # latch the card hidden — node CPU stays reachable.
    h.set_value("#metric-type", "podcpu")
    assert not h.get("metrics-card").hidden
    h.set_value("#metric-type", "node")
    assert len(h.query_all("#metric-chart polyline")) == 2


def test_dashboard_chart_hidden_without_metrics_service(dashboard_env):
    h, _ = dashboard_env
    assert h.get("metrics-card").hidden


def test_dashboard_chart_transient_initial_error_does_not_latch(kube):
    """Advisor r3: only a 404/405 on the initial probe (= no metrics
    service wired) may hide the card for the session.  A transient 500 on
    first load must leave the card retryable — the next selector change
    succeeds and renders."""
    from kubeflow_tpu.platform.dashboard.app import create_app
    from kubeflow_tpu.platform.dashboard.metrics_service import (
        MetricsService,
        TimeSeriesPoint,
    )

    class FlakyMetrics(MetricsService):
        calls = 0

        def node_cpu_utilization(self, interval):
            FlakyMetrics.calls += 1
            if FlakyMetrics.calls == 1:
                raise RuntimeError("transient blip")
            return [TimeSeriesPoint(1000 + 60 * i, "node-a", 0.1 * i)
                    for i in range(3)]

        def tpu_duty_cycle(self, interval):
            return []

    kube.add_namespace("kubeflow")

    def make(k, **kw):
        return create_app(k, metrics_service=FlakyMetrics(), **kw)

    h = harness("dashboard", make, kube, user="owner@x.io")
    # Initial probe hit the 500: card NOT latched hidden.
    assert not h.get("metrics-card").hidden
    # A per-type 501 after the failed initial probe is "type unsupported",
    # not "no service" — it must not latch the card either.
    h.set_value("#metric-type", "podcpu")
    assert not h.get("metrics-card").hidden
    # Retry via selector change renders the series.
    h.set_value("#metric-type", "node")
    assert len(h.query_all("#metric-chart polyline")) == 1


def test_dashboard_chart_unsupported_default_type_does_not_latch(kube):
    """A WIRED metrics service whose DEFAULT selector type is unsupported
    (501) must not hide the card: the other types stay reachable.  Only
    the unambiguous nothing-configured 405 may latch."""
    from kubeflow_tpu.platform.dashboard.app import create_app
    from kubeflow_tpu.platform.dashboard.metrics_service import (
        MetricsService,
        TimeSeriesPoint,
    )

    class TpuOnlyMetrics(MetricsService):
        def node_cpu_utilization(self, interval):
            raise NotImplementedError

        def tpu_duty_cycle(self, interval):
            return [TimeSeriesPoint(1000 + 60 * i, "chip-0", 0.2 * i)
                    for i in range(3)]

    kube.add_namespace("kubeflow")

    def make(k, **kw):
        return create_app(k, metrics_service=TpuOnlyMetrics(), **kw)

    h = harness("dashboard", make, kube, user="owner@x.io")
    assert not h.get("metrics-card").hidden  # 501 on first load: no latch
    h.set_value("#metric-type", "tpu")
    assert len(h.query_all("#metric-chart polyline")) == 1


# -- notebook detail page (VERDICT r1 item 1) --------------------------------


@pytest.fixture
def detail_env(kube, jupyter):
    """A notebook with a running pod, logs, and an event."""
    kube.create({
        "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
        "metadata": {"name": "det-nb", "namespace": "user1",
                     "creationTimestamp": "2026-01-01T00:00:00Z"},
        "spec": {
            "tpu": {"accelerator": "v5e", "topology": "2x4"},
            "template": {"spec": {"containers": [{
                "name": "det-nb", "image": "ghcr.io/x/jax:1",
                "resources": {"requests": {"cpu": "4", "memory": "8Gi"}},
            }], "volumes": [{"name": "ws", "emptyDir": {}}]}},
        },
        "status": {"conditions": [{"type": "Ready", "status": "True",
                                   "reason": "Running", "message": "ok"}]},
    })
    kube.create({
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "det-nb-0", "namespace": "user1",
                     "labels": {"notebook-name": "det-nb"}},
        "spec": {"containers": [{"name": "det-nb", "image": "ghcr.io/x/jax:1"}]},
    })
    kube.set_pod_logs("user1", "det-nb-0", "line-1\njupyter up on :8888",
                      container="det-nb")
    kube.create({
        "apiVersion": "v1", "kind": "Event",
        "metadata": {"name": "det-ev", "namespace": "user1"},
        "involvedObject": {"kind": "Pod", "name": "det-nb-0",
                           "namespace": "user1"},
        "reason": "Pulled", "message": "image pulled", "type": "Normal",
        "lastTimestamp": "2099-01-01T00:00:00Z",
    })
    jupyter.fire_timers()
    return jupyter


def test_detail_overview_tab(detail_env):
    h = detail_env
    h.query("#nb-table tbody a.nb-name").click()
    assert h.get("view-table").hidden and not h.get("view-detail").hidden
    assert h.text("#detail-title") == "det-nb"
    overview = h.text("#overview-list")
    assert "ghcr.io/x/jax:1" in overview
    assert "v5e 2x4" in overview
    assert "8Gi" in overview and "ws" in overview
    conds = h.query("#cond-table tbody").textContent
    assert "Ready" in conds and "Running" in conds
    assert h.get("detail-connect").href == "/notebook/user1/det-nb/"


def test_detail_logs_tab(detail_env):
    h = detail_env
    h.query("#nb-table tbody a.nb-name").click()
    h.query("#detail-tabs [data-tab=logs]").click()
    pods = [o.value for o in h.get("log-pod-select").options]
    assert pods == ["det-nb-0"]
    assert "jupyter up on :8888" in h.text("#log-output")


def test_detail_events_tab(detail_env):
    h = detail_env
    h.query("#nb-table tbody a.nb-name").click()
    h.query("#detail-tabs [data-tab=events]").click()
    body = h.query("#ev-table tbody").textContent
    assert "Pulled" in body and "image pulled" in body


def test_detail_yaml_tab_renders_cr(detail_env):
    h = detail_env
    h.query("#nb-table tbody a.nb-name").click()
    h.query("#detail-tabs [data-tab=yaml]").click()
    yaml_text = h.text("#yaml-output")
    assert "apiVersion: kubeflow.org/v1beta1" in yaml_text
    assert "accelerator: v5e" in yaml_text
    # Valid YAML round-trip: what the tab shows parses back to the CR spec.
    import yaml as pyyaml

    parsed = pyyaml.safe_load(yaml_text)
    assert parsed["spec"]["tpu"] == {"accelerator": "v5e", "topology": "2x4"}


def test_detail_back_returns_to_table(detail_env):
    h = detail_env
    h.query("#nb-table tbody a.nb-name").click()
    h.click("#detail-back")
    assert not h.get("view-table").hidden and h.get("view-detail").hidden


def test_detail_deep_link_via_query_param(kube, detail_env):
    """?nb=<name> opens the detail view straight from page load."""
    from kubeflow_tpu.platform.apps.jupyter.app import create_app

    client = Client(create_app(kube, secure_cookies=False))
    h = BrowserHarness(os.path.join(FRONTEND, "jupyter"), client,
                       url="http://spa.test/?ns=user1&nb=det-nb")
    assert not h.get("view-detail").hidden
    assert h.text("#detail-title") == "det-nb"


# -- volume details page (VERDICT r1 item 7) ---------------------------------


def test_volume_details_page(kube):
    from kubeflow_tpu.platform.apps.volumes.app import create_app

    kube.create({
        "apiVersion": "v1", "kind": "PersistentVolumeClaim",
        "metadata": {"name": "det-pvc", "namespace": "user1",
                     "creationTimestamp": "2026-01-01T00:00:00Z"},
        "spec": {"resources": {"requests": {"storage": "25Gi"}},
                 "accessModes": ["ReadWriteOnce"],
                 "storageClassName": "ssd"},
        "status": {"phase": "Bound"},
    })
    kube.create({
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "mounter", "namespace": "user1"},
        "spec": {
            "volumes": [{"name": "data",
                         "persistentVolumeClaim": {"claimName": "det-pvc"}}],
            "containers": [{"name": "c", "image": "i", "volumeMounts": [
                {"name": "data", "mountPath": "/data/det"}]}],
        },
        "status": {"phase": "Running"},
    })
    kube.create({
        "apiVersion": "v1", "kind": "Event",
        "metadata": {"name": "pvc-ev", "namespace": "user1"},
        "involvedObject": {"kind": "PersistentVolumeClaim", "name": "det-pvc",
                           "namespace": "user1"},
        "reason": "ProvisioningSucceeded", "message": "provisioned volume",
        "type": "Normal", "lastTimestamp": "2099-01-01T00:00:00Z",
    })
    h = harness("volumes", create_app, kube)
    h.fire_timers()
    h.query("#pvc-table tbody a.pvc-name").click()
    assert not h.get("view-detail").hidden
    details = h.text("#detail-list")
    assert "25Gi" in details and "ssd" in details and "Bound" in details
    pods = h.query("#detail-pods-table tbody").textContent
    assert "mounter" in pods and "Running" in pods and "/data/det" in pods
    events = h.query("#detail-ev-table tbody").textContent
    assert "ProvisioningSucceeded" in events
    h.click("#detail-back")
    assert not h.get("view-table").hidden


def test_volume_details_deep_link(kube):
    from kubeflow_tpu.platform.apps.volumes.app import create_app

    kube.create({
        "apiVersion": "v1", "kind": "PersistentVolumeClaim",
        "metadata": {"name": "linked", "namespace": "user1"},
        "spec": {"resources": {"requests": {"storage": "1Gi"}},
                 "accessModes": ["ReadWriteOnce"]},
    })
    client = Client(create_app(kube, secure_cookies=False))
    h = BrowserHarness(os.path.join(FRONTEND, "volumes"), client,
                       url="http://spa.test/?ns=user1&pvc=linked")
    assert not h.get("view-detail").hidden
    assert h.text("#detail-title") == "linked"


# -- async-ordering mode: races under deferred scheduling (VERDICT r2 #4) ----


def test_spawner_quota_disables_over_budget_topologies(kube):
    """Quota-aware spawner UX (VERDICT r3 item 7): the picker shows the
    namespace chip budget and disables topologies it can't admit."""
    from kubeflow_tpu.platform.apps.jupyter.app import create_app

    kube.add_tpu_node("tpu-node-2", topology="4x4")
    kube.create({
        "apiVersion": "v1", "kind": "ResourceQuota",
        "metadata": {"name": "kf-resource-quota", "namespace": "user1"},
        "spec": {"hard": {"google.com/tpu": "8"}},
    })
    jupyter = harness("jupyter", create_app, kube)
    jupyter.click("#new-notebook")
    label = jupyter.get("tpu-quota-label")
    assert not label.hidden
    assert label.textContent == "8 of 8 TPU chips remaining"
    jupyter.set_value("#tpu-acc", "v5e")
    opts = {o.attributes.get("value"): o
            for o in jupyter.query_all("#tpu-topo option")}
    assert set(opts) == {"2x4", "4x4"}
    assert not opts["2x4"].disabled          # 8 chips: exactly fits
    assert opts["4x4"].disabled              # 16 chips: over the 8 budget
    assert "(over quota)" in opts["4x4"].textContent


def test_spawner_quota_counts_used_chips(kube):
    """used=8 of hard=8 leaves nothing: every topology is disabled and the
    remaining-chips label says 0."""
    from kubeflow_tpu.platform.apps.jupyter.app import create_app

    kube.create({
        "apiVersion": "v1", "kind": "ResourceQuota",
        "metadata": {"name": "kf-resource-quota", "namespace": "user1"},
        "spec": {"hard": {"google.com/tpu": "8"}},
    })
    kube.create({
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "holder-0", "namespace": "user1"},
        "spec": {"containers": [{"name": "c", "resources": {
            "limits": {"google.com/tpu": "8"}}}]},
    })
    jupyter = harness("jupyter", create_app, kube)
    jupyter.click("#new-notebook")
    assert jupyter.text("#tpu-quota-label") == \
        "0 of 8 TPU chips remaining"
    jupyter.set_value("#tpu-acc", "v5e")
    opts = jupyter.query_all("#tpu-topo option")
    assert opts and all(o.disabled for o in opts)


def test_spawner_slice_change_preserves_topology_pick(kube):
    """Changing the slice count rebuilds the topology list; the user's pick
    must survive while it stays admissible, and fall to the first enabled
    option only when it doesn't."""
    from kubeflow_tpu.platform.apps.jupyter.app import create_app

    kube.add_tpu_node("tpu-node-2", topology="4x4")
    kube.create({
        "apiVersion": "v1", "kind": "ResourceQuota",
        "metadata": {"name": "kf-resource-quota", "namespace": "user1"},
        "spec": {"hard": {"google.com/tpu": "32"}},
    })
    jupyter = harness("jupyter", create_app, kube)
    jupyter.click("#new-notebook")
    jupyter.set_value("#tpu-acc", "v5e")
    jupyter.set_value("#tpu-topo", "4x4")
    # 2 slices of 4x4 = 32 chips: still fits, pick must survive.
    jupyter.set_value("#tpu-slices", "2")
    assert jupyter.get("tpu-topo").value == "4x4"
    # 3 slices of 4x4 = 48 chips: over budget -> falls to 2x4 (24 fits).
    jupyter.set_value("#tpu-slices", "3")
    topo = jupyter.get("tpu-topo")
    assert topo.value == "2x4"
    opts = {o.attributes.get("value"): o
            for o in jupyter.query_all("#tpu-topo option")}
    assert opts["4x4"].disabled and not opts["2x4"].disabled


def test_deferred_timeout_rejects_promise_and_chain_unwinds(tmp_path):
    """Advisor r3: a suspension that never settles must fail ONCE, fast.
    The stuck promise is rejected at the (configurable) timeout, so a JS
    try/catch sees a real rejection and every transitive awaiter resumes
    immediately — under the old hard-coded behavior the first timeout was
    a Python error invisible to JS, the async result stayed pending, and
    each downstream awaiter serially ate its own 30 s."""
    import time as _time

    from kubeflow_tpu.platform.testing.jsdom import BrowserHarness
    from kubeflow_tpu.platform.testing.jsengine import (
        Env,
        JSPromise,
        Parser,
        tokenize,
    )

    (tmp_path / "index.html").write_text("<html><body></body></html>")
    h = BrowserHarness(str(tmp_path), client=None, url="http://t.test/")
    seen, grabbed = [], []
    h.interp.globals.declare("report", lambda *a: seen.append(tuple(a)))
    h.interp.globals.declare("grab", lambda f: grabbed.append(f))
    h.interp.globals.declare("stuck", JSPromise("pending", None))
    src = """
    async function waiter(tag) {
      try { await stuck; report(tag, "settled"); }
      catch (e) { report(tag, "caught"); }
    }
    async function chain() { await waiter("a"); report("chain", "done"); }
    grab(chain); grab(waiter);
    """
    ast = Parser(tokenize(src, "<test>"), "<test>").parse_program()
    env = Env(h.interp.globals)
    h.interp.hoist(ast, env)
    for stmt in ast:
        h.interp.exec(stmt, env)
    chain_fn, waiter_fn = grabbed

    rt = h.enable_deferred(timeout=0.8)
    try:
        t0 = _time.monotonic()
        chain_p = chain_fn()  # awaits waiter("a") which awaits stuck
        waiter_fn("b")        # sibling awaiter of the SAME stuck promise
        while _time.monotonic() - t0 < 6 and (
            chain_p.state == "pending" or len(seen) < 2
        ):
            _time.sleep(0.02)
        elapsed = _time.monotonic() - t0
    finally:
        h.disable_deferred()
    # Whichever awaiter's deadline fires first rejects a stuck promise; the
    # sibling unwinds from that one rejection, and the transitive chain
    # settles (fulfilled if `a` unwound before chain's own deadline,
    # rejected if chain's fired first — all three deadlines race, so the
    # guaranteed contract is SETTLED-fast, not which message each saw).
    assert ("a", "caught") in seen and ("b", "caught") in seen
    assert chain_p.state != "pending"
    # One timeout total, not one per awaiter.
    assert elapsed < 2.4, f"unwind took {elapsed:.1f}s — serial timeouts?"


def test_deferred_out_of_order_fetch_basics(kube, jupyter):
    """Mechanics: with deferred mode on, fetches pend; awaits suspend; the
    test delivers responses in ANY order and continuations run then."""
    _mk_nb(kube, "seed-nb")
    with jupyter.deferred_mode():
        jupyter.fire_timers()  # poll -> refreshTable suspends on its fetch
        assert len(jupyter.pending_fetches) == 1
        # Nothing rendered yet: the async flow is suspended mid-await.
        assert len(jupyter.query_all("#nb-table tbody tr")) == 0
        jupyter.resolve_fetch(0)
        rows = jupyter.query_all("#nb-table tbody tr")
        assert len(rows) == 1 and "seed-nb" in rows[0].textContent
    assert jupyter.pending_fetches == []


def test_stale_refresh_cannot_clobber_newer_data(kube, jupyter):
    """The race the synchronous tier could never exercise: refresh A is
    dispatched, a notebook appears, refresh B is dispatched and its
    response arrives FIRST; stale A arrives last and must NOT overwrite
    B's newer table (refreshSeq guard in app.js)."""
    with jupyter.deferred_mode():
        jupyter.fire_timers()          # refresh A: captures EMPTY list
        _mk_nb(kube, "fresh-nb")
        jupyter.fire_timers()          # refresh B: captures fresh-nb
        assert len(jupyter.pending_fetches) == 2
        jupyter.resolve_fetch(1)       # B's response lands first
        rows = jupyter.query_all("#nb-table tbody tr")
        assert len(rows) == 1 and "fresh-nb" in rows[0].textContent
        jupyter.resolve_fetch(0)       # stale A lands last
        rows = jupyter.query_all("#nb-table tbody tr")
        assert len(rows) == 1, "stale refresh clobbered newer data"
        assert "fresh-nb" in rows[0].textContent


def test_submit_races_inflight_refresh(kube, jupyter):
    """A spawn submitted while a refresh is in flight: the POST completes,
    the old refresh's stale (pre-spawn) response cannot blank the row the
    follow-up refresh rendered."""
    with jupyter.deferred_mode():
        jupyter.fire_timers()                      # refresh A (empty)
        jupyter.click("#new-notebook")
        jupyter.set_value("[name=name]", "race-nb", event="input")
        jupyter.submit("#spawn-form")              # POST pends
        # POST is pending_fetches[1]; deliver it -> spawn handler resumes
        # and fires refresh C.
        posts = [i for i, f in enumerate(jupyter.pending_fetches)
                 if f["method"] == "POST"]
        jupyter.resolve_fetch(posts[0])
        assert kube.get(NOTEBOOK, "race-nb", "user1") is not None
        refreshes = [i for i, f in enumerate(jupyter.pending_fetches)
                     if f["method"] == "GET" and "notebooks" in f["path"]]
        # Deliver the NEWEST refresh first, then the stale pre-spawn one.
        jupyter.resolve_fetch(refreshes[-1])
        assert len(jupyter.query_all("#nb-table tbody tr")) == 1
        jupyter.resolve_fetch(0)                   # stale refresh A
        rows = jupyter.query_all("#nb-table tbody tr")
        assert len(rows) == 1 and "race-nb" in rows[0].textContent


def test_double_submit_guard_under_deferred_fetch(kube, jupyter):
    """Two rapid Launch clicks while the first POST is still in flight must
    produce exactly ONE notebook (submit button disables for the duration)."""
    with jupyter.deferred_mode():
        jupyter.click("#new-notebook")
        jupyter.set_value("[name=name]", "once-nb", event="input")
        jupyter.submit("#spawn-form")          # POST #1 pends
        assert jupyter.query("#spawn-submit").disabled
        jupyter.submit("#spawn-form")          # rapid second click: guarded
        posts = [f for f in jupyter.pending_fetches if f["method"] == "POST"]
        assert len(posts) == 1, "second submit fired a duplicate POST"
        idx = jupyter.pending_fetches.index(posts[0])
        jupyter.resolve_fetch(idx)
        assert not jupyter.query("#spawn-submit").disabled
    assert len(kube.list(NOTEBOOK, "user1")) == 1
