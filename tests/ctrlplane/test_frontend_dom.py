"""Executable frontend tests: the SHIPPED app.js runs under the JS
interpreter + DOM shim against the REAL Flask/WSGI backends (VERDICT r1
item 4 — replaces string-grep contract tests; the reference gates this tier
with Cypress, reference jupyter/frontend/cypress/e2e/form-page.cy.ts).

Every test here drives the same artifacts a browser would: parse
index.html, execute app.js, click/type/submit, and assert on what reached
the backend (FakeKube) and what rendered back into the DOM.  Renaming a DOM
id, form field, or API path breaks these tests."""
from __future__ import annotations

import os

import pytest
from werkzeug.test import Client

from kubeflow_tpu.platform.k8s import errors
from kubeflow_tpu.platform.k8s.types import NOTEBOOK, PVC, deep_get
from kubeflow_tpu.platform.testing import FakeKube
from kubeflow_tpu.platform.testing.jsdom import BrowserHarness

FRONTEND = os.path.join(
    os.path.dirname(__file__), "..", "..",
    "kubeflow_tpu", "platform", "frontend",
)


def harness(app_name: str, create_app, kube, **kw):
    client = Client(create_app(kube, secure_cookies=False))
    return BrowserHarness(
        os.path.join(FRONTEND, app_name), client,
        url="http://spa.test/?ns=user1", **kw,
    )


@pytest.fixture
def kube():
    k = FakeKube()
    k.add_namespace("user1")
    k.add_tpu_node("tpu-node-1", topology="2x4")
    return k


@pytest.fixture
def jupyter(kube):
    from kubeflow_tpu.platform.apps.jupyter.app import create_app

    return harness("jupyter", create_app, kube)


# -- jupyter spawner ----------------------------------------------------------


def test_spawn_flow_end_to_end(kube, jupyter):
    """Open the dialog, pick a TPU, submit — the POST body is built by the
    shipped JS and the backend creates the Notebook CR."""
    jupyter.click("#new-notebook")
    assert jupyter.get("spawner").open
    # The accelerator list came from the real /api/namespaces/<ns>/tpus.
    accs = [o.textContent for o in jupyter.query_all("#tpu-acc option")]
    assert "v5e" in accs
    jupyter.set_value("[name=name]", "my-nb", event="input")
    jupyter.set_value("[name=cpu]", "3", event="input")
    jupyter.set_value("[name=memory]", "9Gi", event="input")
    jupyter.set_value("#tpu-acc", "v5e")  # change event populates topologies
    topos = [o.textContent for o in jupyter.query_all("#tpu-topo option")]
    assert topos == ["2x4"]
    jupyter.set_value("#tpu-topo", "2x4")
    jupyter.submit("#spawn-form")

    nb = kube.get(NOTEBOOK, "my-nb", "user1")
    assert nb["spec"]["tpu"] == {"accelerator": "v5e", "topology": "2x4"}
    # cpu/memory typed into the form reached the container resources.
    container = deep_get(nb, "spec", "template", "spec", "containers")[0]
    requests = container["resources"]["requests"]
    assert requests["cpu"] == "3" and requests["memory"] == "9Gi"
    # Default workspace toggle: a workspace PVC was provisioned + mounted.
    volumes = deep_get(nb, "spec", "template", "spec", "volumes", default=[])
    assert any("workspace" in (v.get("name") or "") for v in volumes)
    assert "Launching my-nb" in jupyter.text("#toast")
    assert not jupyter.get("spawner").open


def test_spawn_workspace_none_sends_null_volume(kube, jupyter):
    """workspace=none in the form must reach the backend as an explicit
    workspaceVolume: null (no PVC provisioned)."""
    jupyter.click("#new-notebook")
    jupyter.set_value("[name=name]", "no-ws", event="input")
    jupyter.set_value("#workspace-select", "none")
    jupyter.submit("#spawn-form")
    nb = kube.get(NOTEBOOK, "no-ws", "user1")
    volumes = deep_get(nb, "spec", "template", "spec", "volumes", default=[])
    assert not any("workspace" in (v.get("name") or "") for v in volumes)
    assert kube.list(PVC, "user1") == []


def test_spawn_custom_image_toggle(kube, jupyter):
    jupyter.click("#new-notebook")
    assert jupyter.get("custom-image-row").hidden
    jupyter.set_value("#image-select", "__custom__")
    assert not jupyter.get("custom-image-row").hidden
    jupyter.set_value("[name=name]", "cust", event="input")
    jupyter.set_value("[name=customImage]", "registry.io/me/img:1", event="input")
    jupyter.submit("#spawn-form")
    nb = kube.get(NOTEBOOK, "cust", "user1")
    image = deep_get(nb, "spec", "template", "spec", "containers")[0]["image"]
    assert image == "registry.io/me/img:1"


def test_spawn_error_surfaces_as_toast(kube, jupyter):
    jupyter.click("#new-notebook")
    # Empty name: backend 400s; the JS must toast the error, not crash.
    jupyter.submit("#spawn-form")
    assert jupyter.query("#toast").classList.contains("error")
    assert kube.list(NOTEBOOK, "user1") == []


def test_table_stop_and_delete_actions(kube, jupyter):
    from kubeflow_tpu.platform.apis import notebook as nbapi

    kube.create({
        "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
        "metadata": {"name": "running-nb", "namespace": "user1"},
        "spec": {"template": {"spec": {"containers": [
            {"name": "running-nb", "image": "img"}]}}},
    })
    jupyter.fire_timers()  # poll() refresh
    rows = jupyter.query_all("#nb-table tbody tr")
    assert len(rows) == 1 and "running-nb" in rows[0].textContent

    jupyter.query("#nb-table tbody button.ghost").click()  # Stop
    nb = kube.get(NOTEBOOK, "running-nb", "user1")
    assert deep_get(nb, "metadata", "annotations",
                    nbapi.STOP_ANNOTATION) is not None

    jupyter.confirm_response = False
    jupyter.query("#nb-table tbody button.danger").click()  # Delete, refused
    assert kube.get(NOTEBOOK, "running-nb", "user1") is not None
    jupyter.confirm_response = True
    jupyter.query("#nb-table tbody button.danger").click()
    assert jupyter.confirm_prompts[-1].startswith("Delete notebook running-nb")
    with pytest.raises(errors.NotFound):
        kube.get(NOTEBOOK, "running-nb", "user1")


def test_poddefault_chips_reach_post_body(kube, jupyter):
    from kubeflow_tpu.platform.apis.poddefault import tpu_pod_default

    kube.create(tpu_pod_default("user1", "v5e", "2x4"))
    jupyter.click("#new-notebook")
    chips = jupyter.query_all("#poddefault-chips .chip")
    assert len(chips) == 1
    chips[0].click()  # toggle on
    jupyter.set_value("[name=name]", "with-pd", event="input")
    jupyter.submit("#spawn-form")
    nb = kube.get(NOTEBOOK, "with-pd", "user1")
    # PodDefault opt-ins become labels the webhook selector matches
    # (form.py _set_configurations).
    labels = deep_get(nb, "metadata", "labels", default={})
    assert "true" in labels.values()


def test_csrf_token_round_trips_through_cookie(jupyter):
    """api() reads XSRF-TOKEN from document.cookie and echoes it as the
    X-XSRF-TOKEN header — the double-submit contract, executed."""
    jupyter.click("#new-notebook")
    jupyter.set_value("[name=name]", "csrf-nb", event="input")
    jupyter.submit("#spawn-form")
    post = next(r for r in jupyter.requests if r["method"] == "POST")
    assert post["path"].endswith("/notebooks")


# -- volumes -----------------------------------------------------------------


def test_volumes_create_and_guarded_delete(kube):
    from kubeflow_tpu.platform.apps.volumes.app import create_app

    h = harness("volumes", create_app, kube)
    h.click("#new-pvc")
    h.set_value("[name=name]", "data-1", event="input")
    h.set_value("[name=size]", "20Gi", event="input")
    h.submit("#create-form")
    pvc = kube.get(PVC, "data-1", "user1")
    assert pvc["spec"]["resources"]["requests"]["storage"] == "20Gi"

    h.fire_timers()
    rows = h.query_all("#pvc-table tbody tr")
    assert len(rows) == 1 and "data-1" in rows[0].textContent

    h.query("#pvc-table tbody button.danger").click()
    assert "Data is lost permanently" in h.confirm_prompts[-1]
    with pytest.raises(errors.NotFound):
        kube.get(PVC, "data-1", "user1")


def test_volumes_mounted_pvc_delete_disabled(kube):
    from kubeflow_tpu.platform.apps.volumes.app import create_app

    kube.create({
        "apiVersion": "v1", "kind": "PersistentVolumeClaim",
        "metadata": {"name": "busy", "namespace": "user1"},
        "spec": {"resources": {"requests": {"storage": "1Gi"}},
                 "accessModes": ["ReadWriteOnce"]},
    })
    kube.create({
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "user-pod", "namespace": "user1"},
        "spec": {"volumes": [
            {"name": "v", "persistentVolumeClaim": {"claimName": "busy"}}],
            "containers": [{"name": "c", "image": "i"}]},
    })
    h = harness("volumes", create_app, kube)
    h.fire_timers()
    btn = h.query("#pvc-table tbody button.danger")
    assert btn.hasAttribute("disabled")
    assert "user-pod" in h.query("#pvc-table tbody").textContent


# -- tensorboards ------------------------------------------------------------


def test_tensorboards_create_from_form(kube):
    from kubeflow_tpu.platform.apps.tensorboards.app import create_app
    from kubeflow_tpu.platform.k8s.types import TENSORBOARD

    h = harness("tensorboards", create_app, kube)
    h.click("#new-tb")
    h.set_value("[name=name]", "tb-1", event="input")
    h.set_value("[name=logspath]", "pvc://data-1/logs", event="input")
    h.submit("#create-form")
    tb = kube.get(TENSORBOARD, "tb-1", "user1")
    assert tb["spec"]["logspath"] == "pvc://data-1/logs"
    h.fire_timers()
    assert "tb-1" in h.query("#tb-table tbody").textContent


# -- dashboard ---------------------------------------------------------------


@pytest.fixture
def dashboard_env():
    """Dashboard + a synchronous profile control plane: Profile creates
    reconcile inline (namespace/RBAC exist by the time the register POST
    returns), so the JS's follow-up env-info fetch sees the workgroup —
    deterministic where the threaded e2e harness polls."""
    from kubeflow_tpu.platform.controllers.profile import ProfileReconciler
    from kubeflow_tpu.platform.dashboard.app import create_app
    from kubeflow_tpu.platform.k8s.types import name_of
    from kubeflow_tpu.platform.runtime import Request

    class SyncProfileKube(FakeKube):
        def create(self, obj, **kw):
            out = super().create(obj, **kw)
            if obj.get("kind") == "Profile":
                ProfileReconciler(self).reconcile(Request("", name_of(out)))
            return out

    kube = SyncProfileKube()
    kube.add_namespace("kubeflow")
    h = harness("dashboard", create_app, kube, user="owner@x.io")
    return h, kube


def test_dashboard_register_then_contributors(dashboard_env):
    h, kube = dashboard_env
    # Fresh user: the register card shows.
    assert not h.get("register-card").hidden
    h.click("#register-btn")
    assert "Created namespace" in h.text("#toast")
    assert h.get("register-card").hidden
    ns_options = [o.value for o in h.get("ns-select").options]
    assert len(ns_options) == 1
    ns = ns_options[0]

    # Add a contributor through the real form + backend.
    h.query("[data-view=contributors]").click()
    assert not h.get("view-contributors").hidden
    h.set_value("[name=contributor]", "bob@x.io", event="input")
    h.submit("#contrib-form")
    body = h.query("#contrib-table tbody").textContent
    assert "bob@x.io" in body and "contributor" in body

    # Remove again via the rendered button.
    h.query("#contrib-table tbody button.danger").click()
    assert "bob@x.io" not in h.query("#contrib-table tbody").textContent
    assert ns  # silences linters; ns asserted above


def test_dashboard_activity_feed_renders_events(dashboard_env):
    h, kube = dashboard_env
    h.click("#register-btn")
    ns = h.get("ns-select").options[0].value
    kube.create({
        "apiVersion": "v1", "kind": "Event",
        "metadata": {"name": "ev-1", "namespace": ns},
        "involvedObject": {"kind": "Notebook", "name": "nb-1",
                           "namespace": ns},
        "reason": "FailedScheduling",
        "message": "0/3 nodes available: insufficient google.com/tpu",
        "type": "Warning",
        "lastTimestamp": "2099-01-01T00:00:00Z",
    })
    # No poll timer on the dashboard: re-selecting the namespace fires the
    # change handler, which re-fetches the activity feed.
    h.set_value("#ns-select", ns)
    feed = h.query("#activity-table tbody").textContent
    assert "Notebook/nb-1" in feed
    assert "insufficient google.com/tpu" in feed
