"""Frontend serving tests: every app serves its SPA shell + shared assets,
static routes skip authn, traversal is refused, API routes still guarded."""
from __future__ import annotations

import pytest
from werkzeug.test import Client

from kubeflow_tpu.platform.testing import FakeKube


def _client(create_app):
    kube = FakeKube()
    kube.add_namespace("alice")
    app = create_app(kube, secure_cookies=False)
    return Client(app)


APPS = []
from kubeflow_tpu.platform.apps.jupyter.app import create_app as jupyter_app
from kubeflow_tpu.platform.apps.tensorboards.app import create_app as tb_app
from kubeflow_tpu.platform.apps.volumes.app import create_app as volumes_app
from kubeflow_tpu.platform.dashboard.app import create_app as dashboard_app

APPS = [
    ("jupyter", jupyter_app),
    ("volumes", volumes_app),
    ("tensorboards", tb_app),
    ("dashboard", dashboard_app),
]


@pytest.mark.parametrize("name,factory", APPS, ids=[a[0] for a in APPS])
def test_spa_shell_served_without_auth(name, factory):
    client = _client(factory)
    # No identity header at all: static shell must still load.
    resp = client.get("/")
    assert resp.status_code == 200
    assert resp.content_type.startswith("text/html")
    body = resp.get_data(as_text=True)
    assert "app.js" in body and "shared/kubeflow.css" in body

    js = client.get("/app.js")
    assert js.status_code == 200
    assert js.content_type.startswith("application/javascript")

    css = client.get("/shared/kubeflow.css")
    assert css.status_code == 200
    assert css.content_type.startswith("text/css")

    common = client.get("/shared/common.js")
    assert common.status_code == 200


@pytest.mark.parametrize("name,factory", APPS, ids=[a[0] for a in APPS])
def test_api_still_requires_identity(name, factory):
    client = _client(factory)
    path = {
        "jupyter": "/api/namespaces/alice/notebooks",
        "volumes": "/api/namespaces/alice/pvcs",
        "tensorboards": "/api/namespaces/alice/tensorboards",
        "dashboard": "/api/namespaces",
    }[name]
    assert client.get(path).status_code == 401
    assert client.get(path, headers={"kubeflow-userid": "alice@x.io"}).status_code == 200


def test_traversal_refused():
    client = _client(jupyter_app)
    for path in ("/shared/../jupyter/app.js", "/shared/..%2f..%2fnative%2fMakefile",
                 "/shared/does-not-exist.css"):
        resp = client.get(path, headers={"kubeflow-userid": "a@x.io"})
        assert resp.status_code == 404, path


def test_dashboard_lists_namespace_contributors():
    """The manage-contributors view needs the NAMESPACE's bindings (owner +
    contributors), not the caller's own — regression for the env-info-only
    first cut."""
    kube = FakeKube()
    kube.add_namespace("kubeflow")
    # Nobody is cluster admin (the cluster-admin probe is delete-on-Profile);
    # access must come from ownership/bindings alone.
    kube.authz_policy = (
        lambda user, verb, gvk, **kw: not (verb == "delete" and gvk.kind == "Profile")
    )
    app = dashboard_app(kube, secure_cookies=False)
    client = Client(app)
    owner = {"kubeflow-userid": "alice@x.io"}

    resp = client.post("/api/workgroup/create", json={}, headers=owner)
    assert resp.status_code == 200
    ns = resp.get_json()["namespace"]
    # The profile controller (not running here) would create the namespace.
    kube.add_namespace(ns)

    resp = client.post(
        "/api/workgroup/add-contributor",
        json={"contributor": "bob@x.io", "namespace": ns},
        headers=owner,
    )
    assert resp.status_code == 200

    resp = client.get(f"/api/workgroup/contributors/{ns}", headers=owner)
    assert resp.status_code == 200
    contributors = resp.get_json()["contributors"]
    roles = {c["user"]: c["role"] for c in contributors}
    assert roles.get("bob@x.io") == "contributor"
    assert "owner" in roles.values()

    # Outsiders are refused.
    resp = client.get(
        f"/api/workgroup/contributors/{ns}",
        headers={"kubeflow-userid": "mallory@x.io"},
    )
    assert resp.status_code == 403
