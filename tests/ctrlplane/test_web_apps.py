"""Web-app tests driven over real HTTP against ephemeral servers."""
import pytest
import requests as http

from kubeflow_tpu.platform.k8s.types import NOTEBOOK, PROFILE, PVC, deep_get
from kubeflow_tpu.platform.testing import FakeKube
from kubeflow_tpu.platform.web.crud_backend import AuthContext

USER_HEADER = {"kubeflow-userid": "alice@example.com"}


@pytest.fixture
def kube():
    k = FakeKube()
    k.add_namespace("user1")
    k.add_tpu_node("tpu-1", topology="2x4")
    k.add_tpu_node("tpu-2", topology="4x4")
    return k


def auth():
    # secure_cookies off in tests: CSRF covered separately.
    return AuthContext()


def serve(app):
    server, base = app.test_server()
    return base


@pytest.fixture
def jwa(kube):
    from kubeflow_tpu.platform.apps.jupyter.app import create_app

    app = create_app(kube, auth=auth())
    return serve(app)


def test_jwa_requires_identity_header(jwa):
    r = http.get(f"{jwa}/api/config")
    assert r.status_code == 401
    r = http.get(f"{jwa}/api/config", headers=USER_HEADER)
    assert r.status_code == 200
    assert "tpus" in r.json()["config"]


def test_jwa_healthz_no_auth(jwa):
    assert http.get(f"{jwa}/healthz").status_code == 200


def test_jwa_tpu_listing_intersects_nodes(jwa):
    r = http.get(f"{jwa}/api/namespaces/user1/tpus", headers=USER_HEADER)
    tpus = r.json()["tpus"]
    assert len(tpus) == 1 and tpus[0]["accelerator"] == "v5e"
    assert set(tpus[0]["topologies"]) == {"2x4", "4x4"}


def test_jwa_spawn_flow(jwa, kube):
    body = {
        "name": "mynb",
        "serverType": "jupyter",
        "tpus": {"accelerator": "v5e", "topology": "4x4"},
        "configurations": ["tpu-v5e"],
    }
    r = http.post(
        f"{jwa}/api/namespaces/user1/notebooks", json=body, headers=USER_HEADER
    )
    assert r.status_code == 200, r.text
    nb = kube.get(NOTEBOOK, "mynb", "user1")
    assert nb["spec"]["tpu"] == {"accelerator": "v5e", "topology": "4x4"}
    assert nb["metadata"]["labels"]["tpu-v5e"] == "true"
    container = deep_get(nb, "spec", "template", "spec", "containers")[0]
    assert container["image"].startswith("ghcr.io/kubeflow-tpu/jupyter-jax-tpu")
    assert container["resources"]["limits"]["cpu"] == "4.8"  # 4 * 1.2
    # Workspace PVC created from the template.
    pvc = kube.get(PVC, "mynb-workspace", "user1")
    assert deep_get(pvc, "spec", "resources", "requests", "storage") == "10Gi"
    # List shows a row with status.
    rows = http.get(
        f"{jwa}/api/namespaces/user1/notebooks", headers=USER_HEADER
    ).json()["notebooks"]
    assert rows[0]["name"] == "mynb"
    assert rows[0]["tpu"]["topology"] == "4x4"
    assert rows[0]["status"]["phase"] in ("waiting", "running")


def test_jwa_spawn_multislice(jwa, kube):
    body = {
        "name": "ms",
        "tpus": {"accelerator": "v5e", "topology": "4x4", "slices": 2},
    }
    r = http.post(
        f"{jwa}/api/namespaces/user1/notebooks", json=body, headers=USER_HEADER
    )
    assert r.status_code == 200, r.text
    nb = kube.get(NOTEBOOK, "ms", "user1")
    assert nb["spec"]["tpu"] == {
        "accelerator": "v5e", "topology": "4x4", "slices": 2,
    }
    # The config ceiling (maxSlices: 4) rejects over-asking.
    r = http.post(
        f"{jwa}/api/namespaces/user1/notebooks",
        json={"name": "big",
              "tpus": {"accelerator": "v5e", "topology": "4x4", "slices": 9}},
        headers=USER_HEADER,
    )
    assert r.status_code == 400
    assert "exceeds" in r.json()["log"]


def test_jwa_rejects_unoffered_topology(jwa):
    body = {"name": "bad", "tpus": {"accelerator": "v5e", "topology": "16x16"}}
    r = http.post(
        f"{jwa}/api/namespaces/user1/notebooks", json=body, headers=USER_HEADER
    )
    assert r.status_code == 400
    assert "not offered" in r.json()["log"]


def test_jwa_stop_start_delete(jwa, kube):
    http.post(
        f"{jwa}/api/namespaces/user1/notebooks",
        json={"name": "nb1"}, headers=USER_HEADER,
    )
    r = http.patch(
        f"{jwa}/api/namespaces/user1/notebooks/nb1",
        json={"stopped": True}, headers=USER_HEADER,
    )
    assert r.status_code == 200
    nb = kube.get(NOTEBOOK, "nb1", "user1")
    assert "kubeflow-resource-stopped" in nb["metadata"]["annotations"]
    http.patch(
        f"{jwa}/api/namespaces/user1/notebooks/nb1",
        json={"stopped": False}, headers=USER_HEADER,
    )
    nb = kube.get(NOTEBOOK, "nb1", "user1")
    assert "kubeflow-resource-stopped" not in nb["metadata"].get("annotations", {})
    assert http.delete(
        f"{jwa}/api/namespaces/user1/notebooks/nb1", headers=USER_HEADER
    ).status_code == 200


def test_jwa_authz_denied(kube):
    from kubeflow_tpu.platform.apps.jupyter.app import create_app

    kube.authz_policy = lambda **kw: kw["verb"] == "list" and kw["gvk"].kind == "Node"
    base = serve(create_app(kube, auth=auth()))
    r = http.get(f"{base}/api/namespaces/user1/notebooks", headers=USER_HEADER)
    assert r.status_code == 403


def test_vwa_pvc_lifecycle(kube):
    from kubeflow_tpu.platform.apps.volumes.app import create_app

    base = serve(create_app(kube, auth=auth()))
    r = http.post(
        f"{base}/api/namespaces/user1/pvcs",
        json={"name": "data", "size": "5Gi", "mode": "ReadWriteOnce"},
        headers=USER_HEADER,
    )
    assert r.status_code == 200
    rows = http.get(
        f"{base}/api/namespaces/user1/pvcs", headers=USER_HEADER
    ).json()["pvcs"]
    assert rows[0]["capacity"] == "5Gi"
    # A pod mounting the claim blocks deletion.
    kube.create({
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "user-pod", "namespace": "user1"},
        "spec": {"volumes": [{"name": "v",
                              "persistentVolumeClaim": {"claimName": "data"}}]},
    })
    r = http.delete(f"{base}/api/namespaces/user1/pvcs/data", headers=USER_HEADER)
    assert r.status_code == 409
    kube.delete(
        __import__("kubeflow_tpu.platform.k8s.types", fromlist=["POD"]).POD,
        "user-pod", "user1",
    )
    assert http.delete(
        f"{base}/api/namespaces/user1/pvcs/data", headers=USER_HEADER
    ).status_code == 200


def test_vwa_single_pvc_and_events(kube):
    from kubeflow_tpu.platform.apps.volumes.app import create_app

    base = serve(create_app(kube, auth=auth()))
    http.post(
        f"{base}/api/namespaces/user1/pvcs",
        json={"name": "data", "size": "5Gi"}, headers=USER_HEADER,
    )
    r = http.get(f"{base}/api/namespaces/user1/pvcs/data", headers=USER_HEADER)
    assert r.status_code == 200
    assert deep_get(r.json()["pvc"], "spec", "resources", "requests", "storage") == "5Gi"
    # Events route returns only events involving this claim.
    kube.create({
        "apiVersion": "v1", "kind": "Event",
        "metadata": {"name": "ev1", "namespace": "user1"},
        "involvedObject": {"kind": "PersistentVolumeClaim", "name": "data"},
        "reason": "ProvisioningSucceeded", "message": "ok",
    })
    kube.create({
        "apiVersion": "v1", "kind": "Event",
        "metadata": {"name": "ev2", "namespace": "user1"},
        "involvedObject": {"kind": "PersistentVolumeClaim", "name": "other"},
        "reason": "x", "message": "not ours",
    })
    evs = http.get(
        f"{base}/api/namespaces/user1/pvcs/data/events", headers=USER_HEADER
    ).json()["events"]
    assert [e["reason"] for e in evs] == ["ProvisioningSucceeded"]


def test_twa_pvcs_and_poddefaults(kube):
    from kubeflow_tpu.platform.apps.tensorboards.app import create_app

    base = serve(create_app(kube, auth=auth()))
    kube.create({
        "apiVersion": "v1", "kind": "PersistentVolumeClaim",
        "metadata": {"name": "logs", "namespace": "user1"},
        "spec": {"resources": {"requests": {"storage": "1Gi"}}},
    })
    kube.create({
        "apiVersion": "kubeflow.org/v1alpha1", "kind": "PodDefault",
        "metadata": {"name": "tpu-v5e", "namespace": "user1"},
        "spec": {"selector": {"matchLabels": {"tpu-v5e": "true"}},
                 "desc": "Attach v5e TPU env"},
    })
    pvcs = http.get(
        f"{base}/api/namespaces/user1/pvcs", headers=USER_HEADER
    ).json()["pvcs"]
    assert pvcs == ["logs"]
    pds = http.get(
        f"{base}/api/namespaces/user1/poddefaults", headers=USER_HEADER
    ).json()["poddefaults"]
    assert pds == [{"name": "tpu-v5e", "label": "tpu-v5e",
                    "desc": "Attach v5e TPU env"}]


def test_twa_tensorboard_lifecycle(kube):
    from kubeflow_tpu.platform.apps.tensorboards.app import create_app

    base = serve(create_app(kube, auth=auth()))
    r = http.post(
        f"{base}/api/namespaces/user1/tensorboards",
        json={"name": "tb1", "logspath": "pvc://data/logs"}, headers=USER_HEADER,
    )
    assert r.status_code == 200
    rows = http.get(
        f"{base}/api/namespaces/user1/tensorboards", headers=USER_HEADER
    ).json()["tensorboards"]
    assert rows[0]["logspath"] == "pvc://data/logs"
    assert http.delete(
        f"{base}/api/namespaces/user1/tensorboards/tb1", headers=USER_HEADER
    ).status_code == 200


def test_kfam_binding_flow(kube):
    from kubeflow_tpu.platform.kfam.app import create_app

    kube.create({
        "apiVersion": "kubeflow.org/v1", "kind": "Profile",
        "metadata": {"name": "user1"},
        "spec": {"owner": {"kind": "User", "name": "alice@example.com"}},
    })
    base = serve(create_app(kube, auth=auth()))
    binding = {
        "user": {"kind": "User", "name": "bob@example.com"},
        "referredNamespace": "user1",
        "roleRef": {"kind": "ClusterRole", "name": "kubeflow-edit"},
    }
    r = http.post(f"{base}/kfam/v1/bindings", json=binding, headers=USER_HEADER)
    assert r.status_code == 200, r.text
    out = http.get(
        f"{base}/kfam/v1/bindings?namespace=user1", headers=USER_HEADER
    ).json()["bindings"]
    assert any(b["user"]["name"] == "bob@example.com" for b in out)
    # Non-owner cannot mutate.
    kube.authz_policy = lambda **kw: False  # no cluster admin
    r = http.post(
        f"{base}/kfam/v1/bindings", json=binding,
        headers={"kubeflow-userid": "mallory@example.com"},
    )
    assert r.status_code == 403
    kube.authz_policy = None
    r = http.request(
        "DELETE", f"{base}/kfam/v1/bindings", json=binding, headers=USER_HEADER
    )
    assert r.status_code == 200
    out = http.get(
        f"{base}/kfam/v1/bindings?namespace=user1", headers=USER_HEADER
    ).json()["bindings"]
    assert not any(b["user"]["name"] == "bob@example.com" for b in out)


def test_dashboard_env_info_and_registration(kube):
    from kubeflow_tpu.platform.dashboard.app import create_app

    base = serve(create_app(kube, auth=auth()))
    info = http.get(f"{base}/api/workgroup/env-info", headers=USER_HEADER).json()
    assert info["hasWorkgroup"] is False
    r = http.post(f"{base}/api/workgroup/create", json={}, headers=USER_HEADER)
    assert r.status_code == 200
    assert r.json()["namespace"] == "kubeflow-alice"
    kube.get(PROFILE, "kubeflow-alice")
    info = http.get(f"{base}/api/workgroup/env-info", headers=USER_HEADER).json()
    assert info["hasWorkgroup"] is True
    assert {"namespace": "kubeflow-alice", "role": "owner",
            "user": "alice@example.com"} in info["namespaces"]


def test_dashboard_tpu_overview(kube):
    from kubeflow_tpu.platform.dashboard.app import create_app

    kube.create({
        "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
        "metadata": {"name": "nb", "namespace": "user1"},
        "spec": {"template": {"spec": {"containers": [{"image": "x"}]}},
                 "tpu": {"accelerator": "v5e", "topology": "4x4"}},
    })
    # A multislice notebook counts every slice's chips; an invalid stored
    # spec must not 500 the endpoint.
    kube.create({
        "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
        "metadata": {"name": "ms", "namespace": "user1"},
        "spec": {"template": {"spec": {"containers": [{"image": "x"}]}},
                 "tpu": {"accelerator": "v5e", "topology": "2x4", "slices": 2}},
    })
    kube.create({
        "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
        "metadata": {"name": "bad", "namespace": "user1"},
        "spec": {"template": {"spec": {"containers": [{"image": "x"}]}},
                 "tpu": {"accelerator": "v5e", "topology": "3x3"}},
    })
    base = serve(create_app(kube, auth=auth()))
    overview = http.get(f"{base}/api/tpu-overview", headers=USER_HEADER).json()
    assert overview["clusterCapacityChips"] == 16  # two 8-chip fake nodes
    # nb 4x4 = 16 + ms 2x4 x 2 slices = 16; 'bad' skipped.
    assert overview["requestedChipsByNamespace"] == {"user1": 32}
    assert "quota" not in overview  # only present when ?ns= is asked

    # ?ns= adds the namespace chip budget under the shared picker
    # accounting (declared running CRs count; 'bad' parses to 0).
    kube.create({
        "apiVersion": "v1", "kind": "ResourceQuota",
        "metadata": {"name": "q", "namespace": "user1"},
        "spec": {"hard": {"google.com/tpu": "48"}},
    })
    overview = http.get(f"{base}/api/tpu-overview?ns=user1",
                        headers=USER_HEADER).json()
    assert overview["quota"] == {"hard": 48, "used": 32, "remaining": 16}
    # A quota-less namespace reports null.
    kube.add_namespace("noquota")
    overview = http.get(f"{base}/api/tpu-overview?ns=noquota",
                        headers=USER_HEADER).json()
    assert overview["quota"] is None


def test_csrf_double_submit(kube):
    from kubeflow_tpu.platform.apps.jupyter.app import create_app
    from kubeflow_tpu.platform.web.crud_backend import install_standard_middleware

    app = create_app(kube, auth=auth())
    # Re-wire with secure cookies on (create_app read env default=off in test).
    base = serve(app)
    # The test app was built with default middleware; emulate secure mode by
    # building a fresh app with secure_cookies=True.
    from kubeflow_tpu.platform.web.framework import App

    secure_app = App("secure")
    from kubeflow_tpu.platform.web.crud_backend import CrudBackend

    backend = CrudBackend(kube, auth())
    install_standard_middleware(secure_app, backend, secure_cookies=True)

    @secure_app.route("/mutate", methods=["POST"])
    def mutate(request):
        return {"ok": True}

    base = serve(secure_app)
    # First GET sets the cookie (Secure attr, so send it back manually —
    # the test rides plain HTTP).
    r0 = http.get(f"{base}/healthz")
    token = r0.cookies.get("XSRF-TOKEN")
    assert token
    cookie_header = {"Cookie": f"XSRF-TOKEN={token}"}
    # POST without the matching header fails; with it succeeds.
    r = http.post(f"{base}/mutate", headers={**USER_HEADER, **cookie_header})
    assert r.status_code == 403
    r = http.post(
        f"{base}/mutate",
        headers={**USER_HEADER, **cookie_header, "X-XSRF-TOKEN": token},
    )
    assert r.status_code == 200


def test_duplicate_spawn_is_409(jwa):
    body = {"name": "dup"}
    assert http.post(
        f"{jwa}/api/namespaces/user1/notebooks", json=body, headers=USER_HEADER
    ).status_code == 200
    r = http.post(
        f"{jwa}/api/namespaces/user1/notebooks", json=body, headers=USER_HEADER
    )
    assert r.status_code == 409
    assert "already exists" in r.json()["log"]


def test_jwa_pod_logs(jwa, kube):
    # Reference get.py:99-105 — logs of one worker pod, split into lines,
    # container named after the notebook; authz on the pods/log subresource.
    kube.create({
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "mynb-0", "namespace": "user1",
                     "labels": {"notebook-name": "mynb"}},
        "spec": {"containers": [{"name": "mynb"}]},
    })
    kube.set_pod_logs("user1", "mynb-0", "line1\nline2", container="mynb")
    r = http.get(
        f"{jwa}/api/namespaces/user1/notebooks/mynb/pod/mynb-0/logs",
        headers=USER_HEADER,
    )
    assert r.status_code == 200
    assert r.json()["logs"] == ["line1", "line2"]
    # Missing pod -> k8s NotFound surfaces as 404.
    r = http.get(
        f"{jwa}/api/namespaces/user1/notebooks/mynb/pod/ghost-0/logs",
        headers=USER_HEADER,
    )
    assert r.status_code == 404


def test_dashboard_metrics_service(kube):
    # Reference api.ts:29-58 — /api/metrics/<node|podcpu|podmem> backed by a
    # MetricsService; 405 when none is configured.
    from kubeflow_tpu.platform.dashboard.app import create_app
    from kubeflow_tpu.platform.dashboard.metrics_service import (
        Interval,
        PrometheusMetricsService,
    )

    # No service wired -> 405 (matches the reference's operation_not_supported).
    bare = serve(create_app(kube, auth=auth()))
    r = http.get(f"{bare}/api/metrics/node", headers=USER_HEADER)
    assert r.status_code == 405

    calls = []

    def fake_fetch(url, params):
        calls.append((url, params))
        return {
            "status": "success",
            "data": {"result": [
                {"metric": {"instance": "node-a"},
                 "values": [[1000, "0.5"], [1060, "0.75"]]},
            ]},
        }

    svc = PrometheusMetricsService(
        "http://prom:9090", fetch=fake_fetch, now=lambda: 2000.0
    )
    base = serve(create_app(kube, auth=auth(), metrics_service=svc))
    r = http.get(
        f"{base}/api/metrics/node?interval=Last5m", headers=USER_HEADER
    )
    assert r.status_code == 200
    pts = r.json()["points"]
    assert [p["value"] for p in pts] == [0.5, 0.75]
    assert pts[0]["label"] == "node-a"
    url, params = calls[0]
    assert url == "http://prom:9090/api/v1/query_range"
    assert params["end"] - params["start"] == Interval.Last5m.minutes * 60

    # incl. the control-plane series (reconcile p99 / workqueue depth).
    for mtype in ("podcpu", "podmem", "tpu", "reconcile", "workqueue"):
        assert http.get(
            f"{base}/api/metrics/{mtype}", headers=USER_HEADER
        ).status_code == 200
    assert http.get(
        f"{base}/api/metrics/bogus", headers=USER_HEADER
    ).status_code == 404

    # A failing backend degrades to an empty series, not an error.
    def broken(url, params):
        raise RuntimeError("prometheus down")

    svc_broken = PrometheusMetricsService("http://prom:9090", fetch=broken)
    base2 = serve(create_app(kube, auth=auth(), metrics_service=svc_broken))
    r = http.get(f"{base2}/api/metrics/podcpu", headers=USER_HEADER)
    assert r.status_code == 200 and r.json()["points"] == []


def test_web_framework_counts_requests_per_kind(jwa):
    """The shared middleware exports request_kf{component,kind} for every
    /api route — the jupyter/volumes/tensorboards apps report per-kind
    request counts like KFAM does, with no per-app wiring."""
    from kubeflow_tpu.platform.runtime import metrics

    def val(kind):
        return metrics.registry.get_sample_value(
            "request_kf_total", {"component": "jupyter-web-app", "kind": kind}
        ) or 0.0

    before = val("notebooks")
    r = http.get(f"{jwa}/api/namespaces/user1/notebooks", headers=USER_HEADER)
    assert r.status_code == 200
    assert val("notebooks") == before + 1
    # Probes and /metrics aren't resource requests; no series for them.
    http.get(f"{jwa}/healthz")
    assert metrics.registry.get_sample_value(
        "request_kf_total", {"component": "jupyter-web-app", "kind": "healthz"}
    ) is None


def test_web_framework_counts_5xx_as_failures(kube):
    from kubeflow_tpu.platform.runtime import metrics
    from kubeflow_tpu.platform.web.crud_backend import (
        CrudBackend,
        install_standard_middleware,
    )
    from kubeflow_tpu.platform.web.framework import App

    app = App("fail-app")
    backend = CrudBackend(kube, AuthContext(disable_auth=True))
    install_standard_middleware(app, backend, secure_cookies=False)

    @app.route("/api/namespaces/<ns>/widgets")
    def widgets(request, ns):
        raise RuntimeError("boom")

    server, base = app.test_server()
    try:
        assert http.get(f"{base}/api/namespaces/x/widgets").status_code == 500
        assert metrics.registry.get_sample_value(
            "request_kf_failure_total",
            {"component": "fail-app", "kind": "widgets", "severity": "major"},
        ) == 1.0
        # 4xx is the client's problem: counted as a request, not a failure.
        assert http.get(f"{base}/api/nope").status_code == 404
    finally:
        server.shutdown()


def test_kind_of_rule_route_shapes():
    from kubeflow_tpu.platform.web.crud_backend import _kind_of_rule

    assert _kind_of_rule("/api/namespaces/<ns>/notebooks") == "notebooks"
    assert _kind_of_rule(
        "/api/namespaces/<ns>/notebooks/<name>/pod/<pod>/logs") == "notebooks"
    # A bare /api/namespaces addresses the Namespace kind itself (the
    # dashboard's picker route), not a scope prefix.
    assert _kind_of_rule("/api/namespaces") == "namespaces"
    assert _kind_of_rule("/api/storageclasses") == "storageclasses"
    assert _kind_of_rule("/api/activities/<ns>") == "activities"
    for rule in ("/healthz", "/metrics", "/kfam/v1/bindings", None):
        assert _kind_of_rule(rule) is None


# -- informer-cache-backed reads (zero-copy frozen views, APP_USE_INFORMERS) --


def test_web_apps_serve_cached_frozen_reads_over_http(kube):
    """Every production web app runs with informer caches by default
    (main.py APP_USE_INFORMERS): spin each app up WITH caches wired and
    drive its hot routes over real HTTP — any handler that mutates a
    cached result (TypeError on frozen views) or serializes one outside
    json_response would 500 here.  Also pins read-your-writes: a GET
    right after a POST must not 404 out of a lagging cache
    (CrudBackend's read-through)."""
    from kubeflow_tpu.platform.apps.jupyter.app import create_app as jwa_app
    from kubeflow_tpu.platform.apps.tensorboards.app import (
        create_app as twa_app,
    )
    from kubeflow_tpu.platform.apps.volumes.app import create_app as vwa_app
    from kubeflow_tpu.platform.k8s.types import (
        NODE,
        PODDEFAULT,
        RESOURCEQUOTA,
        STORAGECLASS,
        TENSORBOARD,
    )
    from kubeflow_tpu.platform.runtime.informer import Informer

    kube.create({"apiVersion": "v1", "kind": "ResourceQuota",
                 "metadata": {"name": "q", "namespace": "user1"},
                 "spec": {"hard": {"google.com/tpu": "16"}}})
    kube.create({"apiVersion": "v1", "kind": "PersistentVolumeClaim",
                 "metadata": {"name": "vol1", "namespace": "user1"},
                 "spec": {"accessModes": ["ReadWriteOnce"],
                          "resources": {"requests": {"storage": "1Gi"}}}})
    caches = {g: Informer(kube, g).start()
              for g in (NOTEBOOK, PVC, PODDEFAULT, RESOURCEQUOTA, NODE,
                        STORAGECLASS, TENSORBOARD)}
    try:
        for inf in caches.values():
            assert inf.wait_for_sync(10)

        jwa = serve(jwa_app(kube, auth=auth(), caches=caches))
        r = http.post(f"{jwa}/api/namespaces/user1/notebooks",
                      headers=USER_HEADER,
                      json={"name": "cached-nb", "image": "img",
                            "tpus": {"accelerator": "v5e",
                                     "topology": "2x4"}})
        assert r.status_code == 200, r.text
        # read-your-writes: immediate GET must not 404 from a lagging
        # cache (read-through), and the quota picker must serve from the
        # RESOURCEQUOTA/NODE caches without error.
        r = http.get(f"{jwa}/api/namespaces/user1/notebooks/cached-nb",
                     headers=USER_HEADER)
        assert r.status_code == 200, r.text
        assert r.json()["notebook"]["metadata"]["name"] == "cached-nb"
        r = http.get(f"{jwa}/api/namespaces/user1/tpus", headers=USER_HEADER)
        assert r.status_code == 200 and r.json()["quota"]["hard"] == 16
        r = http.get(f"{jwa}/api/namespaces/user1/pvcs", headers=USER_HEADER)
        assert r.status_code == 200

        vwa = serve(vwa_app(kube, auth=auth(), caches=caches))
        r = http.get(f"{vwa}/api/namespaces/user1/pvcs", headers=USER_HEADER)
        assert r.status_code == 200
        assert "vol1" in [p["name"] for p in r.json()["pvcs"]]
        r = http.get(f"{vwa}/api/storageclasses", headers=USER_HEADER)
        assert r.status_code == 200

        twa = serve(twa_app(kube, auth=auth(), caches=caches))
        r = http.post(f"{twa}/api/namespaces/user1/tensorboards",
                      headers=USER_HEADER,
                      json={"name": "tb1", "logspath": "pvc://vol1/logs"})
        assert r.status_code == 200, r.text
        r = http.get(f"{twa}/api/namespaces/user1/tensorboards",
                     headers=USER_HEADER)
        assert r.status_code == 200
        r = http.get(f"{twa}/api/namespaces/user1/pvcs", headers=USER_HEADER)
        assert r.status_code == 200
    finally:
        for inf in caches.values():
            inf.stop()
