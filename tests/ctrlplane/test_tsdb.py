"""The in-process TSDB's edge semantics (ISSUE 15 satellite matrix):
counter resets mid-window (replica restart), stale-series eviction at
the series bound, ring capacity, histogram_quantile over sparse
buckets, exact-timestamp pass joins."""
from __future__ import annotations

import math

from kubeflow_tpu.telemetry.tsdb import TSDB


def test_append_instant_and_label_matching():
    db = TSDB()
    db.append("m", {"a": "1"}, 10.0, ts=1.0)
    db.append("m", {"a": "1"}, 11.0, ts=2.0)
    db.append("m", {"a": "2"}, 99.0, ts=2.0)
    rows = db.instant("m", {"a": "1"})
    assert rows == [({"a": "1"}, 2.0, 11.0)]
    # at= picks the latest sample at or before the instant.
    assert db.instant("m", {"a": "1"}, at=1.5) == [({"a": "1"}, 1.0, 10.0)]
    # No matcher = every series of the name.
    assert len(db.instant("m")) == 2
    assert db.instant("missing") == []
    assert sorted(db.names()) == ["m"]


def test_instant_staleness_drops_dead_series():
    """A target that stopped reporting keeps its frozen last value in
    the ring; a staleness-bounded read must not count it (the goodput
    no-double-count contract)."""
    db = TSDB()
    db.append("g", {"replica": "dead"}, 8.0, ts=10.0)
    db.append("g", {"replica": "live"}, 8.0, ts=100.0)
    rows = db.instant("g", at=100.0, staleness=30.0)
    assert [r[0]["replica"] for r in rows] == ["live"]
    # Unbounded read still sees both.
    assert len(db.instant("g", at=100.0)) == 2


def test_ring_capacity_drops_oldest_samples():
    db = TSDB(capacity=4)
    for i in range(10):
        db.append("m", None, float(i), ts=float(i))
    (_labels, samples), = db.window("m")
    assert [v for _ts, v in samples] == [6.0, 7.0, 8.0, 9.0]


def test_stale_series_evicted_at_capacity():
    """At max_series, the series with the OLDEST last sample is evicted
    — a dead target's leftovers, never the hot series still appending."""
    db = TSDB(max_series=3)
    db.append("m", {"i": "stale"}, 1.0, ts=1.0)
    db.append("m", {"i": "warm"}, 1.0, ts=50.0)
    db.append("m", {"i": "hot"}, 1.0, ts=100.0)
    db.append("m", {"i": "new"}, 1.0, ts=101.0)  # evicts "stale"
    assert db.evictions == 1
    have = {ls["i"] for ls in db.labelsets("m")}
    assert have == {"warm", "hot", "new"}
    # Appending to an existing series never evicts.
    db.append("m", {"i": "warm"}, 2.0, ts=102.0)
    assert db.evictions == 1 and len(db) == 3


def test_increase_is_counter_reset_aware():
    """A replica restart drops its counter to ~0 mid-window: the
    post-reset value is the increase since the reset — never a negative
    rate, never a lost pre-reset head."""
    db = TSDB()
    for ts, v in [(1, 100.0), (2, 110.0), (3, 5.0), (4, 8.0)]:
        db.append("c", {"r": "0"}, v, ts=float(ts))
    # Window [1.5, 4]: the sample just before it anchors the first
    # delta — +10, reset->5, +3 = 18.
    assert db.increase("c", {"r": "0"}, window=2.5, at=4.0) == 18.0
    # A window containing the series' FIRST sample counts deltas only —
    # Prometheus semantics: one cumulative observation is history, not
    # an increase (a first scrape after a restart must not read a
    # long-lived remote counter's lifetime as in-window events).
    assert db.increase("c", {"r": "0"}, window=100.0, at=4.0) == 18.0
    assert db.rate("c", {"r": "0"}, window=2.5, at=4.0) == 18.0 / 2.5
    # A single-sample series contributes nothing yet.
    db.append("c", {"r": "1"}, 7.0, ts=3.9)
    assert db.increase("c", None, window=2.5, at=4.0) == 18.0
    db.append("c", {"r": "1"}, 9.0, ts=3.95)
    assert db.increase("c", None, window=2.5, at=4.0) == 20.0
    # Empty window: zero, never an error.
    assert db.increase("c", None, window=1.0, at=50.0) == 0.0


def test_histogram_quantile_over_sparse_buckets():
    """Series may carry different bucket subsets (old replicas predate a
    bucket change; a page was truncated): the merge treats a missing le
    as absent, not zero-crash, and interpolates over what exists."""
    db = TSDB()
    # replica 0: full bucket set (zero baseline + one observation set,
    # so the windowed form has a real increase to interpolate over).
    for le, v in [("0.1", 2.0), ("1.0", 8.0), ("+Inf", 10.0)]:
        db.append("h_bucket", {"le": le, "r": "0"}, 0.0, ts=1.0)
        db.append("h_bucket", {"le": le, "r": "0"}, v, ts=5.0)
    # replica 1: sparse — no 0.1 bucket.
    for le, v in [("1.0", 4.0), ("+Inf", 4.0)]:
        db.append("h_bucket", {"le": le, "r": "1"}, 0.0, ts=1.0)
        db.append("h_bucket", {"le": le, "r": "1"}, v, ts=5.0)
    q50 = db.histogram_quantile(0.5, "h_bucket", at=5.0)
    assert q50 is not None and 0.1 <= q50 <= 1.0
    # Windowed form over increases (same values: zero baseline).
    q50w = db.histogram_quantile(0.5, "h_bucket", window=10.0, at=5.0)
    assert q50w == q50
    # Empty matcher -> None, never a crash.
    assert db.histogram_quantile(0.99, "h_bucket", {"r": "9"}) is None
    assert db.histogram_quantile(0.99, "nope") is None


def test_values_at_is_an_exact_pass_join():
    db = TSDB()
    db.append("g", {"r": "0"}, 1.0, ts=10.0)
    db.append("g", {"r": "1"}, 2.0, ts=10.0)
    db.append("g", {"r": "0"}, 5.0, ts=20.0)  # r1 missed the pass
    assert sorted(v for _l, v in db.values_at("g", ts=10.0)) == [1.0, 2.0]
    assert [v for _l, v in db.values_at("g", ts=20.0)] == [5.0]
    assert db.values_at("g", ts=15.0) == []


def test_merged_at_exact_and_latest():
    db = TSDB()
    db.append("h_bucket", {"le": "1.0", "r": "0"}, 3.0, ts=10.0)
    db.append("h_bucket", {"le": "+Inf", "r": "0"}, 4.0, ts=10.0)
    db.append("h_bucket", {"le": "1.0", "r": "1"}, 1.0, ts=9.0)
    exact = db.merged_at("h_bucket", ts=10.0)
    assert exact == {1.0: 3.0, math.inf: 4.0}
    latest = db.merged_at("h_bucket", ts=10.0, exact=False)
    assert latest == {1.0: 4.0, math.inf: 4.0}


def test_drop_and_len():
    db = TSDB()
    db.append("a", {"service": "ns/x"}, 1.0, ts=1.0)
    db.append("b", {"service": "ns/x"}, 1.0, ts=1.0)
    db.append("a", {"service": "ns/y"}, 1.0, ts=1.0)
    assert len(db) == 3
    assert db.drop(matcher={"service": "ns/x"}) == 2
    assert len(db) == 1 and db.labelsets("a") == [{"service": "ns/y"}]


def test_ingest_page_parses_buckets_and_rejects_garbage():
    db = TSDB()
    page = (
        "# HELP h stuff\n# TYPE h histogram\n"
        'h_bucket{le="0.5"} 1\nh_bucket{le="+Inf"} 2\n'
        "h_count 2\nh_sum 0.9\n"
        'requests_total{outcome="ok"} 7\n'
    )
    n = db.ingest_page(page, labels={"replica": "r0"}, ts=3.0)
    assert n == 5
    assert db.merged_at("h_bucket", {"replica": "r0"}, ts=3.0) == {
        0.5: 1.0, math.inf: 2.0}
    assert db.values_at("requests_total", {"outcome": "ok"}, 3.0) == [
        ({"outcome": "ok", "replica": "r0"}, 7.0)]
    try:
        db.ingest_page("this is { not metrics", ts=4.0)
    except ValueError:
        pass
    else:  # pragma: no cover
        raise AssertionError("garbage page must raise ValueError")


def test_latest_n_newest_first():
    db = TSDB()
    for i in range(5):
        db.append("p", {"service": "s"}, float(i), ts=float(i))
    assert db.latest_n("p", {"service": "s"}, n=2) == [(4.0, 4.0),
                                                       (3.0, 3.0)]
