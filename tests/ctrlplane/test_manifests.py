"""Deployment artifacts stay consistent: manifests parse, reference only
services main.py provides, CRDs match the API layer's GVKs, and the release
pinning script works."""
from __future__ import annotations

import os
import pathlib
import subprocess
import sys

import yaml

ROOT = pathlib.Path(__file__).resolve().parents[2]
MANIFESTS = ROOT / "manifests"


def _docs():
    for path in sorted(MANIFESTS.rglob("*.yaml")):
        with open(path) as f:
            for doc in yaml.safe_load_all(f):
                if doc:
                    yield path.name, doc


def test_manifests_parse_and_have_kinds():
    docs = list(_docs())
    assert len(docs) > 15
    for name, doc in docs:
        assert "kind" in doc and "apiVersion" in doc, name


def test_deployment_commands_are_real_services():
    import importlib

    main = importlib.import_module("kubeflow_tpu.platform.main")
    valid = {"controllers", "webhook", "jupyter", "volumes", "tensorboards",
             "kfam", "dashboard"}
    seen = set()
    for name, doc in _docs():
        if doc["kind"] != "Deployment":
            continue
        for container in doc["spec"]["template"]["spec"]["containers"]:
            cmd = container.get("command", [])
            if "kubeflow_tpu.platform.main" in cmd:
                service = cmd[-1]
                assert service in valid, f"{name}: unknown service {service}"
                seen.add(service)
    assert seen == valid  # every service has a Deployment


def test_crds_match_api_layer():
    from kubeflow_tpu.platform.k8s.types import (
        INFERENCESERVICE, NOTEBOOK, PODDEFAULT, PROFILE, TENSORBOARD, TPUJOB,
    )

    by_plural = {}
    for _, doc in _docs():
        if doc["kind"] == "CustomResourceDefinition":
            spec = doc["spec"]
            by_plural[spec["names"]["plural"]] = (
                spec["group"],
                {v["name"] for v in spec["versions"] if v.get("served")},
            )
    for gvk in (NOTEBOOK, PROFILE, PODDEFAULT, TENSORBOARD, TPUJOB,
                INFERENCESERVICE):
        assert gvk.plural in by_plural, f"no CRD for {gvk.kind}"
        group, versions = by_plural[gvk.plural]
        assert group == gvk.group
        assert gvk.version in versions


def test_every_crd_is_in_kustomization_and_rbac():
    """A new CRD must be WIRED, not just present: listed in the
    kustomization (or `kubectl apply -k` skips it) and granted to the
    controller ClusterRole (or its reconciler gets 403s)."""
    kustomization = yaml.safe_load(
        (MANIFESTS / "kustomization.yaml").read_text())
    listed = set(kustomization["resources"])
    for path in sorted((MANIFESTS / "crds").glob("*.yaml")):
        assert f"crds/{path.name}" in listed, (
            f"{path.name} missing from manifests/kustomization.yaml")
    role = next(doc for _n, doc in _docs()
                if doc["kind"] == "ClusterRole"
                and doc["metadata"]["name"] == "kubeflow-tpu-controller")
    granted = set()
    for rule in role["rules"]:
        granted.update(rule.get("resources", []))
    for _n, doc in _docs():
        if doc["kind"] != "CustomResourceDefinition":
            continue
        plural = doc["spec"]["names"]["plural"]
        assert plural in granted, f"ClusterRole lacks {plural}"
        if any("status" in (v.get("subresources") or {})
               for v in doc["spec"]["versions"]):
            assert f"{plural}/status" in granted, (
                f"ClusterRole lacks {plural}/status")


def test_tpujob_crd_yaml_matches_api_manifest():
    """manifests/crds/tpujob.yaml and apis/tpujob.crd_manifest() describe
    ONE schema: same group/names/served versions, same required spec
    fields, same restartPolicy enum — the yaml cannot drift from what the
    controller validates."""
    from kubeflow_tpu.platform.apis import tpujob as jobapi

    with open(MANIFESTS / "crds" / "tpujob.yaml") as f:
        from_yaml = yaml.safe_load(f)
    from_api = jobapi.crd_manifest()
    assert from_yaml["spec"]["group"] == from_api["spec"]["group"]
    assert (from_yaml["spec"]["names"]["kind"]
            == from_api["spec"]["names"]["kind"] == "TPUJob")
    for doc in (from_yaml, from_api):
        (version,) = doc["spec"]["versions"]
        assert version["name"] == jobapi.VERSION
        assert version["storage"] is True
        assert version["subresources"] == {"status": {}}
        spec_schema = version["schema"]["openAPIV3Schema"][
            "properties"]["spec"]
        assert sorted(spec_schema["required"]) == ["template", "tpu"]
        assert spec_schema["properties"]["tpu"]["required"] == [
            "accelerator"]
        assert (spec_schema["properties"]["restartPolicy"]["enum"]
                == list(jobapi.RESTART_POLICIES))
        assert set(spec_schema["properties"]) == {
            "tpu", "template", "restartPolicy", "backoffLimit",
            "priority", "checkpointDir"}
        # Queue-era spec surface: priority >= 1 and the elastic floor.
        assert spec_schema["properties"]["priority"]["minimum"] == 1
        tpu_props = spec_schema["properties"]["tpu"]["properties"]
        assert set(tpu_props) == {"accelerator", "topology", "slices",
                                  "minSlices"}
        assert tpu_props["minSlices"]["minimum"] == 1
        # Printer columns: `kubectl get tpujobs` must show the queue
        # state (PHASE/PRIORITY/SLICES/REASON/AGE) — names, types and
        # jsonPaths pinned on BOTH sides of the drift fence.
        cols = [(c["name"], c["type"], c["jsonPath"])
                for c in version["additionalPrinterColumns"]]
        assert cols == [
            ("Phase", "string", ".status.phase"),
            ("Priority", "integer", ".spec.priority"),
            ("Slices", "integer", ".status.allocatedSlices"),
            ("Reason", "string", ".status.reason"),
            ("Age", "date", ".metadata.creationTimestamp"),
        ]


def test_inferenceservice_crd_yaml_matches_api_manifest():
    """manifests/crds/inferenceservice.yaml and
    apis/inferenceservice.crd_manifest() describe ONE schema: same
    group/names/served versions, same required spec fields, same scale
    subresource paths, same printer columns — the yaml cannot drift from
    what the controller validates."""
    from kubeflow_tpu.platform.apis import inferenceservice as svcapi

    with open(MANIFESTS / "crds" / "inferenceservice.yaml") as f:
        from_yaml = yaml.safe_load(f)
    from_api = svcapi.crd_manifest()
    assert from_yaml["spec"]["group"] == from_api["spec"]["group"]
    assert (from_yaml["spec"]["names"]["kind"]
            == from_api["spec"]["names"]["kind"] == "InferenceService")
    for doc in (from_yaml, from_api):
        (version,) = doc["spec"]["versions"]
        assert version["name"] == svcapi.VERSION
        assert version["storage"] is True
        subresources = version["subresources"]
        assert subresources["status"] == {}
        # The scale subresource drives kubectl scale / HPA tooling over
        # the SAME fields the telemetry autoscaler writes.
        assert subresources["scale"] == {
            "specReplicasPath": ".spec.replicas.initial",
            "statusReplicasPath": ".status.replicas",
            "labelSelectorPath": ".status.selector",
        }
        spec_schema = version["schema"]["openAPIV3Schema"][
            "properties"]["spec"]
        assert sorted(spec_schema["required"]) == ["model", "tpu"]
        assert set(spec_schema["properties"]) == {
            "model", "checkpointDir", "quantize", "mesh", "image",
            "maxSeqLen", "port", "tpu", "replicas", "scale"}
        assert spec_schema["properties"]["tpu"]["required"] == [
            "accelerator"]
        assert set(spec_schema["properties"]["tpu"]["properties"]) == {
            "accelerator", "topology"}  # replicas scale, not DCN slices
        assert spec_schema["properties"]["quantize"]["enum"] == ["int8"]
        reps = spec_schema["properties"]["replicas"]["properties"]
        assert reps["min"]["minimum"] == 0  # scale-to-zero is spec'able
        assert set(spec_schema["properties"]["scale"]["properties"]) == {
            "queueDepthTarget", "ttftP99TargetSeconds",
            "slotOccupancyTarget", "idleSeconds", "cooldownSeconds"}
        cols = [(c["name"], c["type"], c["jsonPath"])
                for c in version["additionalPrinterColumns"]]
        assert cols == [
            ("Phase", "string", ".status.phase"),
            ("Model", "string", ".spec.model"),
            ("Replicas", "integer", ".status.replicas"),
            ("Ready", "integer", ".status.readyReplicas"),
            ("Revision", "integer", ".status.revision"),
            ("Reason", "string", ".status.reason"),
            ("Age", "date", ".metadata.creationTimestamp"),
        ]


def test_release_pinning_roundtrip(tmp_path):
    # Copy manifests, pin to a tag, verify no :latest remains.
    import shutil

    work = tmp_path / "repo"
    (work / "releasing").mkdir(parents=True)
    shutil.copytree(MANIFESTS, work / "manifests")
    shutil.copy(ROOT / "releasing" / "update-manifest-images.py",
                work / "releasing" / "update-manifest-images.py")
    (work / "releasing" / "VERSION").write_text("v9.9.9\n")
    out = subprocess.run(
        [sys.executable, str(work / "releasing" / "update-manifest-images.py"),
         "--check"],
        capture_output=True, text=True,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    pinned = (work / "manifests" / "controllers.yaml").read_text()
    assert "ghcr.io/kubeflow-tpu/platform:v9.9.9" in pinned


def test_main_entrypoint_parses():
    out = subprocess.run(
        [sys.executable, "-m", "kubeflow_tpu.platform.main", "--help"],
        capture_output=True, text=True, cwd=ROOT,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0
    for service in ("controllers", "webhook", "dashboard"):
        assert service in out.stdout


def test_deploy_runbook_matches_manifests():
    """docs/deploy.md (VERDICT r4 item 9) cannot rot: every object name,
    env knob and metric the runbook teaches must exist in the manifests /
    code, and every env var the manifests set must be documented."""
    runbook = (ROOT / "docs" / "deploy.md").read_text()

    # Every metadata.name in the manifests that the runbook's tables walk
    # through must appear verbatim.
    for obj_name in [
        "kubeflow-tpu-controller", "kubeflow-tpu-webhook",
        "kubeflow-tpu-webhook-certs", "kubeflow-tpu-poddefaults",
        "jupyter-web-app", "kubeflow-self-signing-issuer",
        "kf-resource-quota",
    ]:
        assert obj_name in runbook, f"runbook lost object {obj_name}"
    manifest_names = {
        doc.get("metadata", {}).get("name", "") for _, doc in _docs()
    }
    for needed in ["kubeflow-tpu-controller", "kubeflow-tpu-webhook",
                   "kubeflow-tpu-poddefaults", "jupyter-web-app"]:
        assert needed in manifest_names, f"manifests lost {needed}"

    # Every env var any manifest container sets is documented.
    for name, doc in _docs():
        for c in (doc.get("spec", {}).get("template", {}).get("spec", {})
                  .get("containers", []) or []):
            for env in c.get("env", []) or []:
                assert env["name"] in runbook, (
                    f"{name} sets {env['name']} but docs/deploy.md does "
                    "not document it")

    # Every knob the runbook documents exists in the code (reading it via
    # config.env/env_bool/env_float or os.environ).
    import re

    documented = set(re.findall(r"^\| `([A-Z][A-Z0-9_/ `]*?)`", runbook,
                                re.MULTILINE))
    documented = {k.split("`")[0].strip() for k in documented}
    code = "".join(
        p.read_text() for p in (ROOT / "kubeflow_tpu" / "platform").rglob("*.py")
    )
    for knob in documented:
        for part in knob.split("/"):
            part = part.strip(" `")
            if part:
                assert f'"{part}"' in code, (
                    f"docs/deploy.md documents {part} but no platform "
                    "code reads it")

    # The metrics section names real series.
    metrics_src = (ROOT / "kubeflow_tpu" / "platform" / "runtime"
                   / "metrics.py").read_text()
    for series in ["notebook_spawn_to_ready_seconds", "notebook_running",
                   "tpu_chips_requested", "reconcile_errors_total",
                   "notebook_culling_total", "service_heartbeat"]:
        assert series in runbook and series in metrics_src, series

    # The apply command targets the kustomization that exists.
    assert "kubectl apply -k manifests/" in runbook
    assert (MANIFESTS / "kustomization.yaml").exists()
