"""Deployment artifacts stay consistent: manifests parse, reference only
services main.py provides, CRDs match the API layer's GVKs, and the release
pinning script works."""
from __future__ import annotations

import os
import pathlib
import subprocess
import sys

import yaml

ROOT = pathlib.Path(__file__).resolve().parents[2]
MANIFESTS = ROOT / "manifests"


def _docs():
    for path in sorted(MANIFESTS.rglob("*.yaml")):
        with open(path) as f:
            for doc in yaml.safe_load_all(f):
                if doc:
                    yield path.name, doc


def test_manifests_parse_and_have_kinds():
    docs = list(_docs())
    assert len(docs) > 15
    for name, doc in docs:
        assert "kind" in doc and "apiVersion" in doc, name


def test_deployment_commands_are_real_services():
    import importlib

    main = importlib.import_module("kubeflow_tpu.platform.main")
    valid = {"controllers", "webhook", "jupyter", "volumes", "tensorboards",
             "kfam", "dashboard"}
    seen = set()
    for name, doc in _docs():
        if doc["kind"] != "Deployment":
            continue
        for container in doc["spec"]["template"]["spec"]["containers"]:
            cmd = container.get("command", [])
            if "kubeflow_tpu.platform.main" in cmd:
                service = cmd[-1]
                assert service in valid, f"{name}: unknown service {service}"
                seen.add(service)
    assert seen == valid  # every service has a Deployment


def test_crds_match_api_layer():
    from kubeflow_tpu.platform.k8s.types import (
        NOTEBOOK, PODDEFAULT, PROFILE, TENSORBOARD,
    )

    by_plural = {}
    for _, doc in _docs():
        if doc["kind"] == "CustomResourceDefinition":
            spec = doc["spec"]
            by_plural[spec["names"]["plural"]] = (
                spec["group"],
                {v["name"] for v in spec["versions"] if v.get("served")},
            )
    for gvk in (NOTEBOOK, PROFILE, PODDEFAULT, TENSORBOARD):
        assert gvk.plural in by_plural, f"no CRD for {gvk.kind}"
        group, versions = by_plural[gvk.plural]
        assert group == gvk.group
        assert gvk.version in versions


def test_release_pinning_roundtrip(tmp_path):
    # Copy manifests, pin to a tag, verify no :latest remains.
    import shutil

    work = tmp_path / "repo"
    (work / "releasing").mkdir(parents=True)
    shutil.copytree(MANIFESTS, work / "manifests")
    shutil.copy(ROOT / "releasing" / "update-manifest-images.py",
                work / "releasing" / "update-manifest-images.py")
    (work / "releasing" / "VERSION").write_text("v9.9.9\n")
    out = subprocess.run(
        [sys.executable, str(work / "releasing" / "update-manifest-images.py"),
         "--check"],
        capture_output=True, text=True,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    pinned = (work / "manifests" / "controllers.yaml").read_text()
    assert "ghcr.io/kubeflow-tpu/platform:v9.9.9" in pinned


def test_main_entrypoint_parses():
    out = subprocess.run(
        [sys.executable, "-m", "kubeflow_tpu.platform.main", "--help"],
        capture_output=True, text=True, cwd=ROOT,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0
    for service in ("controllers", "webhook", "dashboard"):
        assert service in out.stdout
