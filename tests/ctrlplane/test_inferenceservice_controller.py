"""InferenceService controller (ISSUE 12): revisioned TPU serving
Deployments behind a Service/VirtualService, telemetry-driven autoscaling
over the real scrape path, rolling readiness-gated revision flips,
scale-to-zero + cold-start wake, and the shared chip ledger."""
from __future__ import annotations

import pytest

from kubeflow_tpu.platform.apis import inferenceservice as api
from kubeflow_tpu.platform.controllers.inferenceservice import (
    InferenceServiceReconciler,
    parse_serve_sample,
)
from kubeflow_tpu.platform.k8s import errors
from kubeflow_tpu.platform.k8s.types import (
    DEPLOYMENT,
    INFERENCESERVICE,
    POD,
    SERVICE,
    TPUJOB,
    VIRTUALSERVICE,
    deep_get,
)
from kubeflow_tpu.platform.runtime import Request
from kubeflow_tpu.platform.testing import FakeKube


def make_service(name="llm", ns="serve", *, replicas=None, scale=None,
                 checkpoint=None, model="llama_125m", port=None):
    spec = {"model": model, "tpu": {"accelerator": "v5e",
                                    "topology": "2x4"}}
    if replicas is not None:
        spec["replicas"] = replicas
    if scale is not None:
        spec["scale"] = scale
    if checkpoint is not None:
        spec["checkpointDir"] = checkpoint
    if port is not None:
        spec["port"] = port
    return {
        "apiVersion": "kubeflow.org/v1alpha1", "kind": "InferenceService",
        "metadata": {"name": name, "namespace": ns}, "spec": spec,
    }


def metrics_text(*, queue_depth=0.0, requests=0.0, slots_active=None,
                 slots=None, revision=None):
    lines = [f"serve_queue_depth {queue_depth}",
             f'generate_requests_total{{outcome="ok"}} {requests}']
    if slots is not None:
        lines += [f"serve_decode_slots {slots}",
                  f"serve_decode_slots_active {slots_active or 0}"]
    if revision is not None:
        lines.append(f"serve_replica_revision {revision}")
    return "\n".join(lines) + "\n"


def add_replica_pod(kube, ns, name, revision, ordinal, *, ready=True,
                    endpoint=None):
    pod_name = f"{name}-v{revision}-{ordinal}"
    annotations = {}
    if endpoint is not None:
        annotations[api.ANNOTATION_ENDPOINT] = endpoint
    kube.create({
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": pod_name, "namespace": ns,
                     "labels": {api.LABEL_SERVICE_NAME: name,
                                api.LABEL_REVISION: str(revision)},
                     "annotations": annotations},
        "spec": {"containers": [{"name": "server"}]},
    })
    kube.set_pod_phase(ns, pod_name, "Running", ready=ready)
    return pod_name


@pytest.fixture
def kube():
    k = FakeKube()
    k.add_namespace("serve")
    return k


def make_reconciler(kube, *, scraper=None, now=None):
    return InferenceServiceReconciler(
        kube,
        scraper=scraper or (lambda url: None),
        sync_period=0.01,
        now=now or (lambda: 1000.0),
    )


def test_invalid_spec_parks_degraded(kube):
    svc = make_service()
    svc["spec"]["tpu"]["topology"] = "4x4"  # 2 hosts: not a serving shape
    kube.create(svc)
    make_reconciler(kube).reconcile(Request("serve", "llm"))
    stored = kube.get(INFERENCESERVICE, "llm", "serve")
    cond = deep_get(stored, "status", "conditions")[0]
    assert cond["type"] == "Degraded" and cond["reason"] == "InvalidSpec"
    assert "single-host" in cond["message"]
    with pytest.raises(errors.NotFound):
        kube.get(DEPLOYMENT, "llm-v1", "serve")


def test_first_reconcile_creates_revisioned_serving_stack(kube):
    kube.create(make_service(replicas={"min": 1, "max": 4, "initial": 2},
                             checkpoint="gs://ckpts/llm", port=9000))
    make_reconciler(kube).reconcile(Request("serve", "llm"))

    dep = kube.get(DEPLOYMENT, "llm-v1", "serve")
    assert deep_get(dep, "spec", "replicas") == 2
    tmpl = deep_get(dep, "spec", "template", "spec")
    container = tmpl["containers"][0]
    # One single-host v5e 2x4 slice per replica: 8 chips + selectors.
    assert container["resources"]["limits"]["google.com/tpu"] == "8"
    assert tmpl["nodeSelector"] == {
        "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
        "cloud.google.com/gke-tpu-topology": "2x4"}
    cmd = container["command"]
    assert cmd[:3] == ["python", "-m", "kubeflow_tpu.models.serve"]
    assert ["--model", "llama_125m"] == cmd[3:5]
    assert "--checkpoint-dir" in cmd and "gs://ckpts/llm" in cmd
    assert "--port" in cmd and "9000" in cmd
    # The readiness generate() probe + the revision env the /metrics
    # gauge exports.
    assert container["readinessProbe"]["httpGet"]["path"] == "/readyz"
    assert {"name": "KFT_SERVE_REVISION", "value": "1"} \
        in container["env"]

    svc = kube.get(SERVICE, "llm", "serve")
    assert deep_get(svc, "spec", "selector") == {
        api.LABEL_SERVICE_NAME: "llm", api.LABEL_REVISION: "1"}
    assert deep_get(svc, "spec", "ports")[0]["targetPort"] == 9000
    vs = kube.get(VIRTUALSERVICE, "inferenceservice-serve-llm", "serve")
    assert deep_get(vs, "spec", "http")[0]["match"][0]["uri"][
        "prefix"] == "/serve/serve/llm/"

    stored = kube.get(INFERENCESERVICE, "llm", "serve")
    status = stored["status"]
    assert status["phase"] == "Pending"
    assert status["replicas"] == 2 and status["readyReplicas"] == 0
    assert status["revision"] == status["targetRevision"] == 1
    assert status["selector"] == "inferenceservice-name=llm"


def test_ready_replicas_flip_phase_ready(kube):
    kube.create(make_service(replicas={"min": 2, "max": 4}))
    r = make_reconciler(kube)
    r.reconcile(Request("serve", "llm"))
    for i in range(2):
        add_replica_pod(kube, "serve", "llm", 1, i)
    r.reconcile(Request("serve", "llm"))
    status = kube.get(INFERENCESERVICE, "llm", "serve")["status"]
    assert status["phase"] == "Ready"
    assert status["readyReplicas"] == 2


def test_autoscale_scales_up_through_real_scrape_path(kube):
    """Deep per-replica queues on the scraped /metrics pages widen the
    Deployment — the serve series drive the decision, not pod counts."""
    kube.create(make_service(replicas={"min": 2, "max": 8},
                             scale={"queueDepthTarget": 4.0}))
    pages = {}
    r = make_reconciler(kube, scraper=lambda url: pages.get(url))
    r.reconcile(Request("serve", "llm"))
    for i in range(2):
        add_replica_pod(kube, "serve", "llm", 1, i,
                        endpoint=f"http://replica-{i}")
        pages[f"http://replica-{i}/metrics"] = metrics_text(
            queue_depth=12.0, requests=10.0)
    r.reconcile(Request("serve", "llm"))
    status = kube.get(INFERENCESERVICE, "llm", "serve")["status"]
    # ceil(2 * 12/4) = 6
    assert status["replicas"] == 6
    dep = kube.get(DEPLOYMENT, "llm-v1", "serve")
    assert deep_get(dep, "spec", "replicas") == 6
    # Slot occupancy alone can also drive it (the second signal).
    for i in range(2):
        pages[f"http://replica-{i}/metrics"] = metrics_text(
            queue_depth=0.0, requests=20.0, slots=8, slots_active=8)
    r.reconcile(Request("serve", "llm"))
    status = kube.get(INFERENCESERVICE, "llm", "serve")["status"]
    assert status["replicas"] == 8  # ceil(6 * 1.0/0.8) = 8 (max clamp)


def test_scale_up_clamped_to_profile_quota(kube):
    """The serving side of the one-quota-truth weld: a scale-up may only
    target replicas the namespace's free google.com/tpu chips can pay
    for; the clamp is visible as status.reason."""
    kube.create({
        "apiVersion": "v1", "kind": "ResourceQuota",
        "metadata": {"name": "kf-resource-quota", "namespace": "serve"},
        "spec": {"hard": {"google.com/tpu": "24"}},
    })
    kube.create(make_service(replicas={"min": 2, "max": 8}))
    pages = {}
    r = make_reconciler(kube, scraper=lambda url: pages.get(url))
    r.reconcile(Request("serve", "llm"))
    for i in range(2):
        add_replica_pod(kube, "serve", "llm", 1, i,
                        endpoint=f"http://replica-{i}")
        pages[f"http://replica-{i}/metrics"] = metrics_text(
            queue_depth=20.0, requests=5.0)
    r.reconcile(Request("serve", "llm"))
    status = kube.get(INFERENCESERVICE, "llm", "serve")["status"]
    # Wanted ceil(2*5)=8, but 24 chips = 3 replicas total.
    assert status["replicas"] == 3
    assert status["reason"] == api.REASON_QUOTA_CLAMPED


def test_rolling_update_flips_only_after_readiness_generate(kube):
    """A checkpoint change warms revision 2 NEXT TO revision 1; the
    Service keeps selecting revision 1 until a revision-2 pod is Ready
    AND answers the controller's /readyz probe; then traffic flips and
    revision 1 drains.  (docs/resilience.md: 'revision fails readiness'
    row — the old revision keeps serving indefinitely.)"""
    kube.create(make_service(replicas={"min": 1, "max": 2}))
    pages = {}
    r = make_reconciler(kube, scraper=lambda url: pages.get(url))
    r.reconcile(Request("serve", "llm"))
    add_replica_pod(kube, "serve", "llm", 1, 0, endpoint="http://r1")

    svc = kube.get(INFERENCESERVICE, "llm", "serve")
    svc = dict(svc)
    svc["spec"] = dict(svc["spec"], checkpointDir="gs://ckpts/new")
    kube.update(svc)
    r.reconcile(Request("serve", "llm"))

    # Both revisions stand; traffic stays on 1 — and revision 1's POD
    # TEMPLATE is untouched: the new checkpoint must never leak into the
    # serving Deployment (that would roll the old pods onto unproven
    # weights before the readiness gate).
    v1 = kube.get(DEPLOYMENT, "llm-v1", "serve")
    v1_cmd = deep_get(v1, "spec", "template", "spec",
                      "containers")[0]["command"]
    assert "--checkpoint-dir" not in v1_cmd, v1_cmd
    v2 = kube.get(DEPLOYMENT, "llm-v2", "serve")
    v2_cmd = deep_get(v2, "spec", "template", "spec",
                      "containers")[0]["command"]
    assert "gs://ckpts/new" in v2_cmd
    assert deep_get(kube.get(SERVICE, "llm", "serve"),
                    "spec", "selector")[api.LABEL_REVISION] == "1"
    status = kube.get(INFERENCESERVICE, "llm", "serve")["status"]
    assert status["phase"] == "Rolling"
    assert (status["revision"], status["targetRevision"]) == (1, 2)

    # Revision 2's pod comes up but FAILS the readiness probe (scraper
    # has no /readyz page for it): still no flip.
    add_replica_pod(kube, "serve", "llm", 2, 0, endpoint="http://r2")
    r.reconcile(Request("serve", "llm"))
    assert deep_get(kube.get(SERVICE, "llm", "serve"),
                    "spec", "selector")[api.LABEL_REVISION] == "1"

    # The probe passes: flip, and revision 1 drains.
    pages["http://r2/readyz"] = '{"ready": true}'
    r.reconcile(Request("serve", "llm"))
    assert deep_get(kube.get(SERVICE, "llm", "serve"),
                    "spec", "selector")[api.LABEL_REVISION] == "2"
    with pytest.raises(errors.NotFound):
        kube.get(DEPLOYMENT, "llm-v1", "serve")
    status = kube.get(INFERENCESERVICE, "llm", "serve")["status"]
    assert status["revision"] == status["targetRevision"] == 2
    assert status["phase"] == "Ready"
    # The new revision's pods carry the bumped KFT_SERVE_REVISION.
    dep = kube.get(DEPLOYMENT, "llm-v2", "serve")
    env = deep_get(dep, "spec", "template", "spec",
                   "containers")[0]["env"]
    assert {"name": "KFT_SERVE_REVISION", "value": "2"} in env


def test_spec_revert_mid_rollout_abandons_target_revision(kube):
    """A revert while revision 2 warms (e.g. the new checkpoint turned
    out bad) abandons the in-flight revision: its Deployment is swept,
    the serving revision never stopped serving, no flip happens."""
    kube.create(make_service(replicas={"min": 1, "max": 2}))
    r = make_reconciler(kube)
    r.reconcile(Request("serve", "llm"))
    svc = dict(kube.get(INFERENCESERVICE, "llm", "serve"))
    original_spec = dict(svc["spec"])
    svc["spec"] = dict(svc["spec"], checkpointDir="gs://ckpts/bad")
    kube.update(svc)
    r.reconcile(Request("serve", "llm"))
    assert kube.get(DEPLOYMENT, "llm-v2", "serve")
    svc = dict(kube.get(INFERENCESERVICE, "llm", "serve"))
    svc["spec"] = original_spec
    kube.update(svc)
    r.reconcile(Request("serve", "llm"))
    status = kube.get(INFERENCESERVICE, "llm", "serve")["status"]
    assert status["revision"] == status["targetRevision"] == 1
    assert status["phase"] != "Rolling"
    with pytest.raises(errors.NotFound):
        kube.get(DEPLOYMENT, "llm-v2", "serve")
    assert deep_get(kube.get(SERVICE, "llm", "serve"),
                    "spec", "selector")[api.LABEL_REVISION] == "1"


def test_replica_bound_change_is_not_a_rollout(kube):
    """Only pod-spec-affecting fields roll a revision: widening
    spec.replicas.max must scale in place, never warm a second
    Deployment."""
    kube.create(make_service(replicas={"min": 1, "max": 2}))
    r = make_reconciler(kube)
    r.reconcile(Request("serve", "llm"))
    svc = dict(kube.get(INFERENCESERVICE, "llm", "serve"))
    svc["spec"] = dict(svc["spec"], replicas={"min": 1, "max": 8})
    kube.update(svc)
    r.reconcile(Request("serve", "llm"))
    status = kube.get(INFERENCESERVICE, "llm", "serve")["status"]
    assert status["revision"] == status["targetRevision"] == 1
    with pytest.raises(errors.NotFound):
        kube.get(DEPLOYMENT, "llm-v2", "serve")


def test_scale_to_zero_and_cold_start_wake(kube):
    """min=0 + idle window elapsed → zero replicas (phase Idle); the
    activator's wake annotation brings it back to one (phase Waking)
    with no cooldown in the way."""
    now_box = [1000.0]
    kube.create(make_service(
        replicas={"min": 0, "max": 4, "initial": 1},
        scale={"idleSeconds": 60.0}))
    pages = {}
    r = make_reconciler(kube, scraper=lambda url: pages.get(url),
                        now=lambda: now_box[0])
    r.reconcile(Request("serve", "llm"))
    add_replica_pod(kube, "serve", "llm", 1, 0, endpoint="http://r0")
    pages["http://r0/metrics"] = metrics_text(queue_depth=0.0,
                                              requests=5.0)
    r.reconcile(Request("serve", "llm"))  # traffic observed at t=1000

    now_box[0] = 1061.0  # idle window elapsed, counter unchanged
    r.reconcile(Request("serve", "llm"))
    status = kube.get(INFERENCESERVICE, "llm", "serve")["status"]
    assert status["replicas"] == 0 and status["phase"] == "Idle"
    assert deep_get(kube.get(DEPLOYMENT, "llm-v1", "serve"),
                    "spec", "replicas") == 0
    kube.delete(POD, "llm-v1-0", "serve")

    # First request hits the scaled-to-zero service: the activator
    # stamps the wake annotation (docs/serving.md "Scale-to-zero").
    now_box[0] = 1100.0
    svc = dict(kube.get(INFERENCESERVICE, "llm", "serve"))
    svc["metadata"] = dict(svc["metadata"], annotations={
        api.ANNOTATION_WAKE: "1099.0"})
    kube.update(svc)
    r.reconcile(Request("serve", "llm"))
    status = kube.get(INFERENCESERVICE, "llm", "serve")["status"]
    assert status["replicas"] == 1 and status["phase"] == "Waking"
    assert deep_get(kube.get(DEPLOYMENT, "llm-v1", "serve"),
                    "spec", "replicas") == 1


def test_inference_scale_up_parks_tpujob_insufficient_quota(kube):
    """The satellite pin, end to end across BOTH controllers: after a
    serving scale-up commits 24 of 32 chips, a 2-slice (16-chip) TPUJob
    reconciles to Queued/InsufficientQuota — the gang is never promised
    chips the model servers hold.  Scaling the service down lifts it."""
    from kubeflow_tpu.platform.apis import tpujob as jobapi
    from kubeflow_tpu.platform.controllers.tpujob import TPUJobReconciler

    kube.create({
        "apiVersion": "v1", "kind": "ResourceQuota",
        "metadata": {"name": "kf-resource-quota", "namespace": "serve"},
        "spec": {"hard": {"google.com/tpu": "32"}},
    })
    kube.create(make_service(replicas={"min": 3, "max": 3}))
    make_reconciler(kube).reconcile(Request("serve", "llm"))
    assert kube.get(INFERENCESERVICE, "llm", "serve")[
        "status"]["replicas"] == 3  # 24 chips committed

    kube.create({
        "apiVersion": "kubeflow.org/v1alpha1", "kind": "TPUJob",
        "metadata": {"name": "train", "namespace": "serve"},
        "spec": {
            "tpu": {"accelerator": "v5e", "topology": "2x4", "slices": 2},
            "template": {"spec": {"containers": [{"name": "w"}]}},
        },
    })
    jr = TPUJobReconciler(kube)
    jr.reconcile(Request("serve", "train"))
    job = kube.get(TPUJOB, "train", "serve")
    assert jobapi.phase_of(job) == "Queued"
    assert deep_get(job, "status", "reason") == "InsufficientQuota"

    # Scale the service to one replica: 16 chips free — the gang admits.
    svc = dict(kube.get(INFERENCESERVICE, "llm", "serve"))
    svc["spec"] = dict(svc["spec"],
                       replicas={"min": 1, "max": 1, "initial": 1})
    kube.update(svc)
    make_reconciler(kube).reconcile(Request("serve", "llm"))
    jr.reconcile(Request("serve", "train"))
    job = kube.get(TPUJOB, "train", "serve")
    assert jobapi.allocated_slices(job) == 2


def test_invalid_spec_edit_preserves_status_record(kube):
    """A transiently invalid spec edit marks Degraded by MERGING into the
    stored status — the revision/replica record survives, so a revert
    resumes the real revision instead of cold-restarting at revision 1."""
    kube.create(make_service(replicas={"min": 2, "max": 4}))
    r = make_reconciler(kube)
    r.reconcile(Request("serve", "llm"))
    before = dict(kube.get(INFERENCESERVICE, "llm", "serve")["status"])
    svc = dict(kube.get(INFERENCESERVICE, "llm", "serve"))
    svc["spec"] = dict(svc["spec"], quantize="int4")  # invalid
    kube.update(svc)
    r.reconcile(Request("serve", "llm"))
    status = kube.get(INFERENCESERVICE, "llm", "serve")["status"]
    assert status["conditions"][0]["type"] == "Degraded"
    assert status["revision"] == before["revision"] == 1
    assert status["replicas"] == before["replicas"] == 2
    # Revert: the service resumes at its real revision — no v2, no cold
    # restart.
    svc = dict(kube.get(INFERENCESERVICE, "llm", "serve"))
    spec = dict(svc["spec"])
    spec.pop("quantize")
    svc["spec"] = spec
    kube.update(svc)
    r.reconcile(Request("serve", "llm"))
    status = kube.get(INFERENCESERVICE, "llm", "serve")["status"]
    assert status["revision"] == status["targetRevision"] == 1
    assert kube.get(DEPLOYMENT, "llm-v1", "serve")


def test_parse_serve_sample_merges_pages():
    pages = [
        metrics_text(queue_depth=6.0, requests=10.0, slots=8,
                     slots_active=4),
        metrics_text(queue_depth=2.0, requests=5.0, slots=8,
                     slots_active=8),
    ]
    s = parse_serve_sample(pages)
    assert s.replicas_scraped == 2
    assert s.queue_depth == 4.0          # mean per replica
    assert s.requests_total == 15.0      # summed counter
    assert s.slot_occupancy == 0.75      # 12 active / 16 slots
    empty = parse_serve_sample([])
    assert empty.replicas_scraped == 0


def test_crd_manifest_shape():
    crd = api.crd_manifest()
    (version,) = crd["spec"]["versions"]
    assert version["subresources"]["scale"][
        "statusReplicasPath"] == ".status.replicas"
    schema = version["schema"]["openAPIV3Schema"]["properties"]["spec"]
    assert sorted(schema["required"]) == ["model", "tpu"]
    api.validate(make_service(replicas={"min": 0, "max": 2}))
    with pytest.raises(api.ValidationError):
        api.validate(make_service(replicas={"min": 3, "max": 2}))
    with pytest.raises(api.ValidationError):
        bad = make_service()
        bad["spec"]["tpu"]["slices"] = 2
        api.validate(bad)
