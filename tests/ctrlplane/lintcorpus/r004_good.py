"""R004 good twin: status goes through the diff'd merge patch."""
from kubeflow_tpu.platform.runtime.apply import patch_status_diff


class Reconciler:
    def reconcile(self, req):
        obj = {"metadata": {"name": req.name}, "status": {}}
        patch_status_diff(self.client, self.gvk, obj, {"phase": "Ready"})
        return None
