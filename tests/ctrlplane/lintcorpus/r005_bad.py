"""R005 bad twin: stray env reads the knob registry cannot see."""
import os
from os import environ

TIMEOUT = float(os.environ.get("CORPUS_TIMEOUT", "30"))


def flag():
    return os.getenv("CORPUS_FLAG") == "1"


def aliased():
    return environ.get("CORPUS_ALIASED")
