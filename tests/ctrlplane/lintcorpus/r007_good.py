"""R007 good twin (routed as a metrics module): declared once, bounded
label keys; collections.Counter is not a metric."""
import collections

from prometheus_client import CollectorRegistry, Counter

registry = CollectorRegistry()

reconciles_total = Counter(
    "corpus_reconciles_total",
    "reconciles by controller and result",
    ["controller", "result"],
    registry=registry,
)

word_counts = collections.Counter("not a metric declaration")
