"""R001 good twin: writes via the injected client / apply helpers; dict
``.update`` is not a client verb."""


class Reconciler:
    def reconcile(self, req):
        desired = {"metadata": {"name": req.name}}
        apply.create(self.client, desired)  # the stamping helper (R009)
        limits = {}
        limits.update({"google.com/tpu": 8})  # a dict, not a client
        return None


def helper(client, obj):
    # Bare `client` is the injected (possibly fenced) client passed down.
    client.update(obj)
