"""R001 bad twin: reconcile-path writes that escape the fence."""


class Reconciler:
    def reconcile(self, req):
        obj = {"metadata": {"name": req.name}}
        # Fence bypass: writing through .inner skips check_fence.
        self.client.inner.update(obj)
        # Inline transport client: never wired through FencedClient.
        FakeKube().create(obj)
        # Client-shaped receiver that is not the injected self.client.
        self.informer_client.delete(None, req.name)
        return None
