"""R009 bad twin: a raw create on the INJECTED client in a reconcile
path drops the traceparent annotation and severs the object journey
silently (creates on any other client are R001 findings already)."""


class Reconciler:
    def reconcile(self, req):
        desired = {"metadata": {"name": req.name}}
        # Child created without the context stamp: the STS never joins
        # its notebook's journey.
        self.client.create(desired)
        return None


def helper(client, desired):
    client.create(desired)
