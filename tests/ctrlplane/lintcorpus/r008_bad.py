"""R008 bad twin: unbounded blocking inside a reconcile body."""
import time


class Reconciler:
    def reconcile(self, req):
        time.sleep(5)             # the workqueue owns time
        self.lock.acquire()       # no timeout: can pin the worker forever
        self.ready.wait()         # ditto
        return None
