"""R002 bad twin: mutating frozen informer views without thaw()."""


class Reconciler:
    def reconcile(self, req):
        nb = self.informer.get(req.name)
        nb["status"] = {"phase": "Ready"}  # item store on a frozen view
        items = self.informer.list(req.namespace)
        for it in items:
            it.setdefault("metadata", {})  # mutator on an iterated view
        return None
