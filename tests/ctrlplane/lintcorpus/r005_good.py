"""R005 good twin: knobs resolve through the registry."""
from kubeflow_tpu.platform import config

TIMEOUT = config.knob("CORPUS_TIMEOUT", 30.0, float,
                      doc="corpus example timeout")


def flag():
    return config.env_bool("CORPUS_FLAG", False)
