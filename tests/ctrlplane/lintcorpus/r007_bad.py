"""R007 bad twin: a metric declared in a controller module, with a label
key outside the bounded allowlist."""
from prometheus_client import Counter

requests_total = Counter(
    "corpus_requests_total",
    "requests by user",
    ["user_email"],  # unbounded-cardinality label key
)
