"""R008 good twin: every block is bounded; delays ride the requeue."""


class Reconciler:
    def reconcile(self, req):
        if not self.lock.acquire(timeout=1.0):
            return self.requeue_after(0.5)
        try:
            if not self.ready.wait(0.25):
                return self.requeue_after(0.5)
        finally:
            self.lock.release()
        if self.backoff_needed():
            return self.requeue_after(5.0)  # instead of time.sleep(5)
        return None
