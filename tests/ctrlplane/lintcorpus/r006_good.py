"""R006 good twin: broad handlers leave a trail; narrow handlers may
pass."""
import logging

log = logging.getLogger("corpus")


def release_lease(client, lease):
    try:
        client.update(lease)
    except Exception:
        log.debug("lease release failed; it will expire", exc_info=True)


def optional_field(obj):
    try:
        return obj["status"]["phase"]
    except KeyError:  # narrow and expected: not a silent swallow
        pass
    return None
