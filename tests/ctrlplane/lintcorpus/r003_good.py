"""R003 good twin: the accelerator stack stays behind function-local
imports; control-plane-safe imports are free."""
import threading

from kubeflow_tpu.platform.k8s import errors  # noqa: F401


def maybe_touch_model():
    import jax  # lazily, only on the path that needs it

    return jax.numpy.zeros(1), threading.Lock()
