"""R006 bad twin: the error vanishes without a trace."""


def release_lease(client, lease):
    try:
        client.update(lease)
    except Exception:
        pass
