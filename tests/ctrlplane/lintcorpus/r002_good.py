"""R002 good twin: read frozen views freely; thaw() before any write;
plural receivers are containers of informers, not caches."""


class Reconciler:
    def reconcile(self, req):
        nb = self.informer.get(req.name)
        phase = nb.get("status", {}).get("phase")  # reads are free
        nb = thaw(nb)                              # intent-to-write copy
        nb["status"] = {"phase": phase or "Ready"}
        informer = self.informers.get(req.gvk)     # container .get
        informer.resync_period = 30.0              # Informer config, not a view
        return None
