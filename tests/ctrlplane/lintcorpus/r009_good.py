"""R009 good twin: child creates ride the context-stamping apply
helpers; non-client ``.create`` receivers are not client writes."""


class Reconciler:
    def reconcile(self, req):
        desired = {"metadata": {"name": req.name}}
        apply.create(self.client, desired)
        create_or_update(self.client, GVK, desired)
        factory.create(desired)  # not a client-shaped receiver
        return None
