"""R003 bad twin: module-level accelerator-stack imports in control-plane
code."""
import jax

from kubeflow_tpu import models
from kubeflow_tpu.models import llama


def reconcile_with_model(req):
    return jax.numpy.zeros(1), llama, models
