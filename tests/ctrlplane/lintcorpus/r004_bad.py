"""R004 bad twin: full-object status write."""


class Reconciler:
    def reconcile(self, req):
        obj = {"metadata": {"name": req.name}, "status": {}}
        obj["status"]["phase"] = "Ready"
        self.client.update_status(obj)  # wipes sibling status owners
        return None
