"""R010 bad twin: hot-path decodes that bypass the codec seam."""
import json
from json import loads


def on_event(line):
    evt = json.loads(line)
    return evt["type"], evt["object"]


def read_body(fh):
    return json.load(fh)


def aliased(line):
    return loads(line)
