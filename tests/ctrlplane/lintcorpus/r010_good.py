"""R010 good twin: decode through the codec seam."""
from kubeflow_tpu.platform.k8s import codec


def on_event(line):
    etype, obj = codec.decode_event(line)
    return etype, obj


def admit(obj):
    return codec.materialize(obj)
