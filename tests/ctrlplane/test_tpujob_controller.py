"""TPUJob gang reconciler: gang creation, env contract, all-or-nothing
restarts, backoff/Never semantics, status aggregation — plus the
MEGASCALE round-trip pin: ``parallel/dist.py`` must discover exactly what
the controller injects (both read parallel/envspec.py; this test fails if
either side drifts off the shared constants)."""
import threading
import time

import pytest

from kubeflow_tpu.platform.apis import tpujob as jobapi
from kubeflow_tpu.platform.controllers.tpujob import (
    TPUJobReconciler,
    make_controller,
    pods_to_tpujob_requests,
)
from kubeflow_tpu.platform.k8s import errors
from kubeflow_tpu.platform.k8s.types import (
    POD,
    SERVICE,
    STATEFULSET,
    TPUJOB,
    deep_get,
    name_of,
)
from kubeflow_tpu.platform.runtime import Request
from kubeflow_tpu.platform.runtime import metrics
from kubeflow_tpu.platform.testing import FakeKube


def make_job(name="tjob", ns="jobs", *, topology="4x4", slices=2,
             restart_policy=None, backoff_limit=None, checkpoint_dir=None,
             priority=None, min_slices=None):
    spec = {
        "tpu": {"accelerator": "v5e", "topology": topology,
                "slices": slices},
        "template": {"spec": {"containers": [{
            "name": "worker", "image": "trainer",
            "command": ["python", "-m", "kubeflow_tpu.train.run"],
        }]}},
    }
    if restart_policy is not None:
        spec["restartPolicy"] = restart_policy
    if backoff_limit is not None:
        spec["backoffLimit"] = backoff_limit
    if checkpoint_dir is not None:
        spec["checkpointDir"] = checkpoint_dir
    if priority is not None:
        spec["priority"] = priority
    if min_slices is not None:
        spec["tpu"]["minSlices"] = min_slices
    return {
        "apiVersion": "kubeflow.org/v1alpha1", "kind": "TPUJob",
        "metadata": {"name": name, "namespace": ns},
        "spec": spec,
    }


@pytest.fixture
def kube():
    k = FakeKube()
    k.add_namespace("jobs")
    # 4 hosts of v5e 4x4 (2 hosts per slice) = 2 free slice slots: the
    # default 2-slice job fits WHOLE — the jobqueue ledger gates gang
    # admission on node-derived topology capacity now.
    for i in range(4):
        k.add_tpu_node(f"tpu-{i + 1}", topology="4x4")
    return k


def reconcile(kube, name="tjob", ns="jobs"):
    TPUJobReconciler(kube).reconcile(Request(ns, name))


def set_gang_running(kube, job, *, ns="jobs"):
    """Kubelet-sim: every expected worker pod of the CURRENT generation
    exists and is Running/ready."""
    name = name_of(job)
    gen = jobapi.generation_of(kube.get(TPUJOB, name, ns))
    spec = jobapi.tpu_slice(job)
    for s in range(spec.num_slices):
        sts_name = TPUJobReconciler.slice_sts_name(name, s)
        sts = kube.get(STATEFULSET, sts_name, ns)
        tmpl = deep_get(sts, "spec", "template")
        for i in range(spec.num_hosts):
            pod_name = f"{sts_name}-{i}"
            try:
                kube.create({
                    "apiVersion": "v1", "kind": "Pod",
                    "metadata": {"name": pod_name, "namespace": ns,
                                 "labels": dict(deep_get(
                                     tmpl, "metadata", "labels",
                                     default={}) or {})},
                    "spec": deep_get(tmpl, "spec"),
                })
            except errors.AlreadyExists:
                pass
            kube.set_pod_phase(ns, pod_name, "Running", ready=True)
    return gen


# -- gang creation ------------------------------------------------------------


def test_gang_creates_one_sts_per_slice_and_coordinator_service(kube):
    kube.create(make_job())
    reconcile(kube)
    # v5e 4x4 = 16 chips / 8 per host = 2 hosts per slice, 2 slices.
    for sts_name, slice_idx in (("tjob", 0), ("tjob-s1", 1)):
        sts = kube.get(STATEFULSET, sts_name, "jobs")
        assert deep_get(sts, "spec", "replicas") == 2, sts_name
        assert deep_get(sts, "spec", "podManagementPolicy") == "Parallel"
        refs = sts["metadata"]["ownerReferences"]
        assert refs and refs[0]["kind"] == "TPUJob"
        labels = deep_get(sts, "spec", "template", "metadata", "labels")
        assert labels[jobapi.LABEL_TPUJOB_NAME] == "tjob"
        assert labels[jobapi.LABEL_TPUJOB_WORKER] == "true"
        assert labels[jobapi.LABEL_GENERATION] == "0"
        main = deep_get(sts, "spec", "template", "spec", "containers")[0]
        limits = main["resources"]["limits"]
        assert limits["google.com/tpu"] == "8"
        selectors = deep_get(sts, "spec", "template", "spec",
                             "nodeSelector")
        assert selectors["cloud.google.com/gke-tpu-topology"] == "4x4"
        env = {e["name"]: e.get("value") for e in main["env"]}
        assert env["MEGASCALE_SLICE_ID"] == str(slice_idx)
        assert env["MEGASCALE_NUM_SLICES"] == "2"
    svc = kube.get(SERVICE, "tjob-workers", "jobs")
    assert deep_get(svc, "spec", "clusterIP") == "None"
    assert deep_get(svc, "spec", "publishNotReadyAddresses") is True
    assert deep_get(svc, "spec", "selector") == {
        jobapi.LABEL_TPUJOB_NAME: "tjob"}
    # Fresh gang, no pods yet: Pending with zeroed per-slice counts.
    job = kube.get(TPUJOB, "tjob", "jobs")
    assert jobapi.phase_of(job) == "Pending"
    assert deep_get(job, "status", "slices") == [
        {"slice": 0, "ready": 0, "total": 2},
        {"slice": 1, "ready": 0, "total": 2},
    ]


def test_megascale_env_roundtrips_through_dist_discovery(kube, monkeypatch):
    """THE drift pin: inject with the controller, discover with dist.py —
    the (coordinator, num_processes, process_id) grid must come out
    slice-major, exactly as make_hybrid_mesh assumes."""
    from kubeflow_tpu.parallel import dist

    kube.create(make_job())
    r = TPUJobReconciler(kube)
    job = kube.get(TPUJOB, "tjob", "jobs")
    sts = r.generate_statefulset(job, slice_idx=1, generation=0)
    env_list = deep_get(sts, "spec", "template", "spec", "containers")[0]["env"]
    for e in env_list:
        if "value" in e:
            monkeypatch.setenv(e["name"], e["value"])
    # The downward-API ordinal (valueFrom in the manifest): worker 1.
    monkeypatch.setenv("TPU_WORKER_ID", "1")

    env = dist.worker_env()
    assert env["topology"] == "4x4"
    assert env["num_slices"] == "2" and env["slice_id"] == "1"
    grid = dist.process_grid()
    assert grid is not None
    coordinator, num_processes, process_id = grid
    assert coordinator == (
        "tjob-0.tjob-workers.jobs.svc.cluster.local:8476")
    assert num_processes == 4          # 2 hosts x 2 slices
    assert process_id == 1 * 2 + 1     # slice-major: slice 1, worker 1
    # And the per-slice hostnames list only THIS slice's workers.
    hosts = env["hostnames"].split(",")
    assert hosts == [
        "tjob-s1-0.tjob-workers.jobs.svc.cluster.local",
        "tjob-s1-1.tjob-workers.jobs.svc.cluster.local",
    ]


def test_checkpoint_dir_rides_as_kft_env(kube):
    kube.create(make_job(checkpoint_dir="/ckpt/run1"))
    reconcile(kube)
    sts = kube.get(STATEFULSET, "tjob", "jobs")
    env = {e["name"]: e.get("value") for e in deep_get(
        sts, "spec", "template", "spec", "containers")[0]["env"]}
    assert env["KFT_CHECKPOINT_DIR"] == "/ckpt/run1"


def test_invalid_spec_parks_degraded(kube):
    bad = make_job(name="bad")
    bad["spec"]["tpu"]["topology"] = "3x3"  # does not pack into v5e hosts
    kube.create(bad)
    reconcile(kube, "bad")
    job = kube.get(TPUJOB, "bad", "jobs")
    conds = {c["type"]: c for c in deep_get(
        job, "status", "conditions", default=[])}
    assert conds["Degraded"]["reason"] == "InvalidSpec"
    with pytest.raises(errors.NotFound):
        kube.get(STATEFULSET, "bad", "jobs")


def test_slice_name_conflict_parks_instead_of_fighting(kube):
    # A sibling workload legally owns the name this job's slice 1 needs.
    kube.create({
        "apiVersion": "apps/v1", "kind": "StatefulSet",
        "metadata": {"name": "tjob-s1", "namespace": "jobs",
                     "labels": {"notebook-name": "tjob-s1"}},
        "spec": {"replicas": 1},
    })
    kube.create(make_job())
    reconcile(kube)
    job = kube.get(TPUJOB, "tjob", "jobs")
    conds = {c["type"]: c for c in deep_get(
        job, "status", "conditions", default=[])}
    assert conds["Degraded"]["reason"] == "SliceNameConflict"
    # Nothing partial: slice 0 was NOT created either (a partial gang
    # would hold TPU hosts forever at the barrier).
    with pytest.raises(errors.NotFound):
        kube.get(STATEFULSET, "tjob", "jobs")


# -- status aggregation -------------------------------------------------------


def test_status_running_when_every_worker_ready(kube):
    kube.create(make_job())
    reconcile(kube)
    job = kube.get(TPUJOB, "tjob", "jobs")
    set_gang_running(kube, job)
    reconcile(kube)
    job = kube.get(TPUJOB, "tjob", "jobs")
    assert jobapi.phase_of(job) == "Running"
    assert deep_get(job, "status", "slices") == [
        {"slice": 0, "ready": 2, "total": 2},
        {"slice": 1, "ready": 2, "total": 2},
    ]
    assert jobapi.restarts_of(job) == 0


def test_succeeded_when_all_workers_succeed_and_chips_free(kube):
    kube.create(make_job())
    reconcile(kube)
    job = kube.get(TPUJOB, "tjob", "jobs")
    set_gang_running(kube, job)
    for pod in kube.list(POD, "jobs"):
        kube.set_pod_phase("jobs", name_of(pod), "Succeeded", ready=False)
    reconcile(kube)
    job = kube.get(TPUJOB, "tjob", "jobs")
    assert jobapi.phase_of(job) == "Succeeded"
    # Chips freed: the gang's StatefulSets are gone; pods stay for logs.
    for sts_name in ("tjob", "tjob-s1"):
        with pytest.raises(errors.NotFound):
            kube.get(STATEFULSET, sts_name, "jobs")
    assert len(kube.list(POD, "jobs")) == 4
    # Terminal is sticky: another reconcile does not resurrect the gang.
    reconcile(kube)
    with pytest.raises(errors.NotFound):
        kube.get(STATEFULSET, "tjob", "jobs")


# -- gang restart semantics ---------------------------------------------------


def test_worker_failure_restarts_the_whole_gang(kube):
    kube.create(make_job(checkpoint_dir="/ckpt"))
    reconcile(kube)
    job = kube.get(TPUJOB, "tjob", "jobs")
    set_gang_running(kube, job)
    reconcile(kube)
    before = metrics.tpujob_restarts_total._value.get()
    # ONE worker of slice 1 fails: all-or-nothing semantics must condemn
    # every slice's StatefulSet and every worker pod.
    kube.set_pod_phase("jobs", "tjob-s1-0", "Failed")
    reconcile(kube)
    job = kube.get(TPUJOB, "tjob", "jobs")
    assert jobapi.phase_of(job) == "Restarting"
    assert jobapi.restarts_of(job) == 1
    assert metrics.tpujob_restarts_total._value.get() == before + 1
    assert kube.list(POD, "jobs") == []  # old generation fully gone
    # Next reconcile recreates the gang under generation 1.
    reconcile(kube)
    for sts_name in ("tjob", "tjob-s1"):
        sts = kube.get(STATEFULSET, sts_name, "jobs")
        assert deep_get(sts, "metadata", "annotations",
                        "tpujobs.kubeflow.org/generation") == "1"
    # The recreated gang converges back to Running at generation 1.
    job = kube.get(TPUJOB, "tjob", "jobs")
    set_gang_running(kube, job)
    reconcile(kube)
    job = kube.get(TPUJOB, "tjob", "jobs")
    assert jobapi.phase_of(job) == "Running"
    assert jobapi.restarts_of(job) == 1


def test_backoff_limit_exhausted_goes_terminally_failed(kube):
    kube.create(make_job(backoff_limit=0))
    reconcile(kube)
    job = kube.get(TPUJOB, "tjob", "jobs")
    set_gang_running(kube, job)
    kube.set_pod_phase("jobs", "tjob-0", "Failed")
    reconcile(kube)
    job = kube.get(TPUJOB, "tjob", "jobs")
    assert jobapi.phase_of(job) == "Failed"
    conds = {c["type"]: c for c in deep_get(
        job, "status", "conditions", default=[])}
    assert conds["Failed"]["reason"] == "BackoffLimitExceeded"
    # Chips freed, pods kept for post-mortem logs.
    with pytest.raises(errors.NotFound):
        kube.get(STATEFULSET, "tjob", "jobs")
    assert len(kube.list(POD, "jobs")) == 4
    # Sticky: nothing recreates the gang.
    reconcile(kube)
    with pytest.raises(errors.NotFound):
        kube.get(STATEFULSET, "tjob", "jobs")


def test_restart_policy_never_fails_on_first_worker_failure(kube):
    kube.create(make_job(restart_policy="Never", backoff_limit=5))
    reconcile(kube)
    job = kube.get(TPUJOB, "tjob", "jobs")
    set_gang_running(kube, job)
    kube.set_pod_phase("jobs", "tjob-s1-1", "Failed")
    reconcile(kube)
    job = kube.get(TPUJOB, "tjob", "jobs")
    assert jobapi.phase_of(job) == "Failed"
    assert jobapi.restarts_of(job) == 0


def test_stale_generation_pods_are_garbage_collected(kube):
    """A straggler pod from a torn-down generation (its delete lost a
    race) must be GC'd and never counted into the new gang's status."""
    kube.create(make_job())
    reconcile(kube)
    kube.create({
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "tjob-ghost", "namespace": "jobs",
                     "labels": {jobapi.LABEL_TPUJOB_NAME: "tjob",
                                jobapi.LABEL_GENERATION: "7"}},
        "spec": {"containers": [{"name": "worker"}]},
    })
    reconcile(kube)
    names = {name_of(p) for p in kube.list(POD, "jobs")}
    assert "tjob-ghost" not in names


# -- api validation -----------------------------------------------------------


def test_validate_rejects_bad_specs():
    for mutate, msg in [
        (lambda j: j["spec"].pop("tpu"), "accelerator"),
        (lambda j: j["spec"]["tpu"].pop("accelerator"), "accelerator"),
        (lambda j: j["spec"]["tpu"].update(accelerator="v9z"),
         "unknown TPU accelerator"),
        (lambda j: j["spec"].update(restartPolicy="Always"),
         "restartPolicy"),
        (lambda j: j["spec"].update(backoffLimit=-1), "backoffLimit"),
        (lambda j: j["spec"]["template"]["spec"].update(containers=[]),
         "containers"),
        (lambda j: j["metadata"].update(name="x" * 53), "52"),
        # Queue-era matrix (ISSUE 11 satellite): non-positive priority,
        # non-integer priority, an elastic floor above the declared
        # width, and junk minSlices all park Degraded at admission
        # instead of crash-looping the reconciler.
        (lambda j: j["spec"].update(priority=0), "priority"),
        (lambda j: j["spec"].update(priority=-3), "priority"),
        (lambda j: j["spec"].update(priority="high"), "priority"),
        (lambda j: j["spec"].update(priority=True), "priority"),
        (lambda j: j["spec"]["tpu"].update(minSlices=3), "minSlices"),
        (lambda j: j["spec"]["tpu"].update(minSlices=0), "minSlices"),
        (lambda j: j["spec"]["tpu"].update(minSlices="one"), "minSlices"),
    ]:
        job = make_job()
        mutate(job)
        with pytest.raises(jobapi.ValidationError, match=msg):
            jobapi.validate(job)
    jobapi.validate(make_job())  # the base shape is valid
    jobapi.validate(make_job(priority=500, min_slices=1))  # queue knobs OK


def test_invalid_priority_parks_degraded_with_warning_event(kube):
    """The reconcile-side contract for the validation matrix: a stored
    non-positive priority (possible via kubectl — the CRD minimum only
    guards the happy path) parks Degraded with a Warning event and never
    reaches gang creation."""
    from kubeflow_tpu.platform.k8s.types import EVENT

    bad = make_job(name="badprio")
    bad["spec"]["priority"] = 0
    kube.create(bad)
    reconcile(kube, "badprio")
    job = kube.get(TPUJOB, "badprio", "jobs")
    conds = {c["type"]: c for c in deep_get(
        job, "status", "conditions", default=[])}
    assert conds["Degraded"]["reason"] == "InvalidSpec"
    assert "priority" in conds["Degraded"]["message"]
    with pytest.raises(errors.NotFound):
        kube.get(STATEFULSET, "badprio", "jobs")
    events = [e for e in kube.list(EVENT, "jobs")
              if deep_get(e, "involvedObject", "name") == "badprio"]
    assert any(e.get("type") == "Warning"
               and e.get("reason") == "InvalidTPUJob" for e in events)


def test_elastic_spec_helpers_default_to_rigid():
    job = make_job()
    assert jobapi.priority_of(job) == jobapi.DEFAULT_PRIORITY
    assert jobapi.min_slices_of(job) == 2  # = slices: rigid by default
    assert jobapi.min_slices_of(make_job(min_slices=1)) == 1


def test_pod_mapper_routes_by_job_label():
    pod = {"metadata": {"namespace": "jobs",
                        "labels": {jobapi.LABEL_TPUJOB_NAME: "tjob"}}}
    assert pods_to_tpujob_requests(pod) == [Request("jobs", "tjob")]
    assert pods_to_tpujob_requests({"metadata": {"labels": {}}}) == []


# -- end to end with real controller threads ----------------------------------


def test_controller_converges_with_gang_sim(kube):
    """Full loop: controller threads + the gang sim playing kubelet — a
    submitted job reaches Running with every slice ready, through watch
    events alone (no reconcile_now)."""
    from kubeflow_tpu.platform.testing.jobsim import TpuJobGangSim

    sim = TpuJobGangSim(kube, "jobs")
    ctrl = make_controller(kube)
    ctrl.start(kube)
    try:
        kube.create(make_job(name="e2e-job"))
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            job = kube.get(TPUJOB, "e2e-job", "jobs")
            if jobapi.phase_of(job) == "Running":
                break
            time.sleep(0.05)
        job = kube.get(TPUJOB, "e2e-job", "jobs")
        assert jobapi.phase_of(job) == "Running", job.get("status")
        assert all(s["ready"] == s["total"] == 2
                   for s in deep_get(job, "status", "slices", default=[]))
    finally:
        ctrl.stop()
        sim.close()
