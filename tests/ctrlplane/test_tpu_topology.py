import pytest

from kubeflow_tpu.platform.tpu import slice_spec, topologies_on_nodes
from kubeflow_tpu.platform.testing import FakeKube
from kubeflow_tpu.platform.k8s.types import NODE


@pytest.mark.parametrize(
    "acc,topo,chips,hosts,per_pod",
    [
        ("v5e", "1x1", 1, 1, 1),
        ("v5e", "2x2", 4, 1, 4),
        ("v5e", "2x4", 8, 1, 8),
        ("v5e", "4x4", 16, 2, 8),
        ("v5e", "4x8", 32, 4, 8),
        ("v5e", "8x16", 128, 16, 8),
        ("v4", "2x2x2", 8, 2, 4),
        ("v5p", "2x2x1", 4, 1, 4),
        ("v6e", "2x4", 8, 1, 8),
    ],
)
def test_slice_math(acc, topo, chips, hosts, per_pod):
    s = slice_spec(acc, topo)
    assert s.chips == chips
    assert s.num_hosts == hosts
    assert s.chips_per_pod == per_pod
    assert s.multi_host == (hosts > 1)


def test_defaults_and_errors():
    assert slice_spec("v5e").topology == "2x4"
    with pytest.raises(ValueError):
        slice_spec("h100")
    with pytest.raises(ValueError):
        slice_spec("v5e", "2x2x2")  # wrong rank
    with pytest.raises(ValueError):
        slice_spec("v5e", "2xbanana")


def test_node_scan():
    kube = FakeKube()
    kube.add_tpu_node("tpu-node-1", accelerator="tpu-v5-lite-podslice", topology="2x4")
    kube.add_tpu_node("tpu-node-2", accelerator="tpu-v5-lite-podslice", topology="4x4")
    kube.add_tpu_node("tpu-node-3", accelerator="tpu-v4-podslice", topology="2x2x2", chips=4)
    kube.create({"apiVersion": "v1", "kind": "Node", "metadata": {"name": "cpu-node"}})
    found = topologies_on_nodes(kube.list(NODE))
    assert found == {"v4": ["2x2x2"], "v5e": ["2x4", "4x4"]}
