"""ResourceQuota enforcement: the TPU-chip-quota north star, made real.

The reference gets quota admission from the real apiserver its KinD CI runs
(reference profile_controller.go:253-280 creates the object; kube-apiserver
denies).  Here ``testing/fake.py`` plays the apiserver, so these tests pin
the admission plugin's contract: 403 on exceed (dry-run included),
status.used bookkeeping, release on delete and on terminal phase, and the
wire transport (httpkube) carrying the denial as a typed Forbidden.
"""
import pytest

from kubeflow_tpu.platform.k8s import errors
from kubeflow_tpu.platform.k8s import quota as quota_mod
from kubeflow_tpu.platform.k8s.types import POD, RESOURCEQUOTA
from kubeflow_tpu.platform.testing import FakeKube


def make_quota(ns, hard, name="kf-resource-quota"):
    return {
        "apiVersion": "v1", "kind": "ResourceQuota",
        "metadata": {"name": name, "namespace": ns},
        "spec": {"hard": hard},
    }


def make_pod(ns, name, *, tpu=None, cpu=None, memory=None, limits_only=False,
             labels=None):
    res = {}
    if tpu is not None:
        res["google.com/tpu"] = str(tpu)
    if cpu is not None:
        res["cpu"] = cpu
    if memory is not None:
        res["memory"] = memory
    resources = {"limits": res} if limits_only else {"requests": res,
                                                    "limits": dict(res)}
    meta = {"name": name, "namespace": ns}
    if labels:
        meta["labels"] = labels
    return {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": meta,
        "spec": {"containers": [{"name": "main", "resources": resources}]},
    }


@pytest.fixture
def kube():
    k = FakeKube()
    k.add_namespace("u")
    return k


# -- quantity math -----------------------------------------------------------

@pytest.mark.parametrize("q,want", [
    ("8", 8.0), (8, 8.0), ("500m", 0.5), ("1500m", 1.5),
    ("2Gi", 2 * 2**30), ("128Mi", 128 * 2**20), ("1k", 1000.0),
    ("0.5", 0.5), ("1e3", 1000.0),
])
def test_parse_quantity(q, want):
    assert quota_mod.parse_quantity(q) == want


@pytest.mark.parametrize("v,key,want", [
    (8.0, "google.com/tpu", "8"), (0.5, "cpu", "500m"),
    (1.5, "cpu", "1500m"),
    (2 * 2**30, "memory", "2Gi"), (3 * 2**20, "requests.memory", "3Mi"),
    (1000.0, "cpu", "1000"),
    # Counted resources stay decimal even at exact binary multiples —
    # the apiserver never writes "1Ki" chips or pods.
    (1024.0, "google.com/tpu", "1024"), (2048.0, "pods", "2048"),
])
def test_format_quantity(v, key, want):
    assert quota_mod.format_quantity(v, key) == want


def test_pod_usage_requests_default_from_limits():
    pod = make_pod("u", "p", tpu=8, limits_only=True)
    usage = quota_mod.pod_quota_usage(pod)
    assert usage["requests.google.com/tpu"] == 8.0
    assert usage["limits.google.com/tpu"] == 8.0
    assert usage["pods"] == 1.0


def test_init_containers_contribute_max_not_sum():
    """Init containers run sequentially: two 2Gi inits + a 2Gi main is a
    2Gi pod (the real plugin's rule), not 4Gi — a sum would falsely deny
    pods the real cluster runs."""
    pod = {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "p", "namespace": "u"},
        "spec": {
            "initContainers": [
                {"name": "i1", "resources": {"requests": {"memory": "2Gi"}}},
                {"name": "i2", "resources": {"requests": {"memory": "2Gi"}}},
            ],
            "containers": [
                {"name": "m", "resources": {"requests": {"memory": "2Gi"}}},
            ],
        },
    }
    usage = quota_mod.pod_quota_usage(pod)
    assert usage["requests.memory"] == 2 * 2**30
    # A big init and a small main: the init phase dominates.
    pod["spec"]["initContainers"][0]["resources"]["requests"]["memory"] = "8Gi"
    assert quota_mod.pod_quota_usage(pod)["requests.memory"] == 8 * 2**30


@pytest.mark.parametrize("bad", ["nan", "inf", "-inf", "NaN", "", "abc"])
def test_parse_quantity_rejects_junk(bad):
    """Non-finite values would defeat every comparison gate (NaN compares
    False against any limit) — they must fail parse, not slip through."""
    with pytest.raises(ValueError):
        quota_mod.parse_quantity(bad)


def test_pod_update_quota_admission_and_rollback(kube):
    """In-place resize charges the delta; junk quantities on update/patch
    roll back without poisoning the namespace."""
    kube.create(make_quota("u", {"cpu": "4"}))
    kube.create(make_pod("u", "p", cpu="2"))
    p = kube.get(POD, "p", "u")
    # Resize 2 -> 5 exceeds hard=4 (delta 3 vs remaining 2): Forbidden.
    p["spec"]["containers"][0]["resources"]["requests"]["cpu"] = "5"
    p["spec"]["containers"][0]["resources"]["limits"]["cpu"] = "5"
    with pytest.raises(errors.Forbidden):
        kube.update(p)
    # Junk via patch: typed Invalid, store rolled back, namespace healthy.
    with pytest.raises(errors.Invalid):
        kube.patch(POD, "p", {"spec": {"containers": [
            {"name": "main", "resources": {"requests": {"cpu": "abc"}}}]}},
            "u", patch_type="merge")
    kube.create(make_pod("u", "p2", cpu="2"))  # still admits fine
    # A within-quota resize works and is re-accounted.
    p = kube.get(POD, "p", "u")
    p["spec"]["containers"][0]["resources"]["requests"]["cpu"] = "1"
    p["spec"]["containers"][0]["resources"]["limits"]["cpu"] = "1"
    kube.update(p)
    rq = kube.get(RESOURCEQUOTA, "kf-resource-quota", "u")
    assert rq["status"]["used"]["cpu"] == "3"


def test_nan_quota_rejected_at_write(kube):
    with pytest.raises(errors.Invalid):
        kube.create(make_quota("u", {"google.com/tpu": "nan"}))
    with pytest.raises(errors.Invalid):
        kube.create(make_pod("u", "p", cpu="inf"))


def test_usage_key_spellings():
    # Bare and requests.-prefixed spellings hit the same usage bucket —
    # both appear in the wild for extended resources.
    assert quota_mod.usage_key("google.com/tpu") == "requests.google.com/tpu"
    assert quota_mod.usage_key("requests.google.com/tpu") == \
        "requests.google.com/tpu"
    assert quota_mod.usage_key("cpu") == "requests.cpu"
    assert quota_mod.usage_key("limits.memory") == "limits.memory"
    assert quota_mod.usage_key("pods") == "pods"


# -- admission ---------------------------------------------------------------

def test_over_quota_pod_denied_with_apiserver_phrasing(kube):
    """The VERDICT's exact complaint scenario: 64 chips into an 8-chip
    quota must be forbidden, not admitted."""
    kube.create(make_quota("u", {"google.com/tpu": "8"}))
    with pytest.raises(errors.Forbidden) as exc:
        kube.create(make_pod("u", "greedy", tpu=64))
    msg = str(exc.value)
    assert 'pods "greedy" is forbidden' in msg
    assert "exceeded quota: kf-resource-quota" in msg
    assert "requested: google.com/tpu=64" in msg
    assert "limited: google.com/tpu=8" in msg


def test_dry_run_create_also_denied(kube):
    kube.create(make_quota("u", {"google.com/tpu": "8"}))
    with pytest.raises(errors.Forbidden):
        kube.create(make_pod("u", "greedy", tpu=16), dry_run=True)
    # And a dry-run denial persists nothing.
    assert kube.list(POD, "u") == []


def test_within_quota_admitted_and_used_tracked(kube):
    kube.create(make_quota("u", {"google.com/tpu": "16", "pods": "10"}))
    kube.create(make_pod("u", "w0", tpu=8))
    rq = kube.get(RESOURCEQUOTA, "kf-resource-quota", "u")
    assert rq["status"]["used"]["google.com/tpu"] == "8"
    assert rq["status"]["used"]["pods"] == "1"
    assert rq["status"]["hard"]["google.com/tpu"] == "16"
    kube.create(make_pod("u", "w1", tpu=8))
    assert kube.get(RESOURCEQUOTA, "kf-resource-quota", "u")[
        "status"]["used"]["google.com/tpu"] == "16"
    # Quota full: one more chip is over.
    with pytest.raises(errors.Forbidden):
        kube.create(make_pod("u", "w2", tpu=1))


def test_delete_releases_quota(kube):
    kube.create(make_quota("u", {"google.com/tpu": "8"}))
    kube.create(make_pod("u", "w0", tpu=8))
    with pytest.raises(errors.Forbidden):
        kube.create(make_pod("u", "w1", tpu=8))
    kube.delete(POD, "w0", "u")
    assert kube.get(RESOURCEQUOTA, "kf-resource-quota", "u")[
        "status"]["used"]["google.com/tpu"] == "0"
    kube.create(make_pod("u", "w1", tpu=8))  # fits again


def test_terminal_phase_releases_quota(kube):
    kube.create(make_quota("u", {"google.com/tpu": "8"}))
    kube.create(make_pod("u", "w0", tpu=8))
    kube.set_pod_phase("u", "w0", "Succeeded")
    assert kube.get(RESOURCEQUOTA, "kf-resource-quota", "u")[
        "status"]["used"]["google.com/tpu"] == "0"
    kube.create(make_pod("u", "w1", tpu=8))


def test_quota_created_after_pods_sees_existing_usage(kube):
    kube.create(make_pod("u", "w0", tpu=8))
    kube.create(make_quota("u", {"google.com/tpu": "8"}))
    rq = kube.get(RESOURCEQUOTA, "kf-resource-quota", "u")
    assert rq["status"]["used"]["google.com/tpu"] == "8"
    with pytest.raises(errors.Forbidden):
        kube.create(make_pod("u", "w1", tpu=1))


def test_cpu_memory_quantities_enforced(kube):
    kube.create(make_quota("u", {"cpu": "2", "memory": "4Gi"}))
    kube.create(make_pod("u", "a", cpu="1500m", memory="2Gi"))
    with pytest.raises(errors.Forbidden) as exc:
        kube.create(make_pod("u", "b", cpu="600m", memory="1Gi"))
    assert "requested: cpu=600m, used: cpu=1500m, limited: cpu=2" in \
        str(exc.value)
    kube.create(make_pod("u", "c", cpu="500m", memory="2Gi"))


def test_pod_without_constrained_resource_counts_zero(kube):
    # Documented deviation from the strict plugin: CPU-only sidecars stay
    # deployable in a TPU-quota'd namespace.
    kube.create(make_quota("u", {"google.com/tpu": "8"}))
    kube.create(make_pod("u", "sidecar", cpu="1"))
    assert kube.get(RESOURCEQUOTA, "kf-resource-quota", "u")[
        "status"]["used"]["google.com/tpu"] == "0"


def test_unquota_namespace_unaffected(kube):
    kube.add_namespace("free")
    kube.create(make_quota("u", {"google.com/tpu": "8"}))
    kube.create(make_pod("free", "big", tpu=256))  # no quota there


def test_cascade_delete_releases_quota(kube):
    kube.create(make_quota("u", {"google.com/tpu": "16"}))
    owner = kube.create({
        "apiVersion": "apps/v1", "kind": "StatefulSet",
        "metadata": {"name": "nb", "namespace": "u"}, "spec": {},
    })
    pod = make_pod("u", "nb-0", tpu=8)
    pod["metadata"]["ownerReferences"] = [{
        "apiVersion": "apps/v1", "kind": "StatefulSet", "name": "nb",
        "uid": owner["metadata"]["uid"],
    }]
    kube.create(pod)
    assert kube.get(RESOURCEQUOTA, "kf-resource-quota", "u")[
        "status"]["used"]["google.com/tpu"] == "8"
    from kubeflow_tpu.platform.k8s.types import STATEFULSET

    kube.delete(STATEFULSET, "nb", "u")
    assert kube.get(RESOURCEQUOTA, "kf-resource-quota", "u")[
        "status"]["used"]["google.com/tpu"] == "0"


def test_tpu_remaining_helper(kube):
    kube.create(make_quota("u", {"google.com/tpu": "32"}))
    kube.create(make_pod("u", "w0", tpu=8))
    quotas = kube.list(RESOURCEQUOTA, "u")
    assert quota_mod.tpu_remaining(quotas) == {
        "hard": 32, "used": 8, "remaining": 24}
    assert quota_mod.tpu_remaining([]) is None


def test_malformed_hard_rejected_at_write_time(kube):
    """A typo'd quantity must fail the quota WRITE (as the real apiserver
    does), not crash every later pod admission."""
    with pytest.raises(errors.Invalid):
        kube.create(make_quota("u", {"google.com/tpu": "abc"}))
    kube.create(make_quota("u", {"google.com/tpu": "8"}))
    rq = kube.get(RESOURCEQUOTA, "kf-resource-quota", "u")
    rq["spec"]["hard"]["google.com/tpu"] = "lots"
    with pytest.raises(errors.Invalid):
        kube.update(rq)
    with pytest.raises(errors.Invalid):
        kube.patch(RESOURCEQUOTA, "kf-resource-quota",
                   {"spec": {"hard": {"cpu": "many"}}}, "u")
    # The failed patch rolled back: the stored object is still valid and
    # admission still works.
    assert kube.get(RESOURCEQUOTA, "kf-resource-quota", "u")[
        "spec"]["hard"] == {"google.com/tpu": "8"}
    kube.create(make_pod("u", "ok", tpu=8))


# -- the spawner surface -----------------------------------------------------

@pytest.fixture
def jwa_kube():
    k = FakeKube()
    k.add_namespace("user1")
    k.add_tpu_node("tpu-1", topology="2x4")
    k.add_tpu_node("tpu-2", topology="4x4")
    return k


@pytest.fixture
def jwa(jwa_kube):
    from werkzeug.test import Client

    from kubeflow_tpu.platform.apps.jupyter.app import create_app

    return Client(create_app(jwa_kube, secure_cookies=False))


USER = {"kubeflow-userid": "alice@example.com"}


def test_spawn_preflight_denies_over_quota_with_remaining(jwa, jwa_kube):
    jwa_kube.create(make_quota("user1", {"google.com/tpu": "8"}))
    r = jwa.post("/api/namespaces/user1/notebooks",
                 json={"name": "big",
                       "tpus": {"accelerator": "v5e", "topology": "4x4"}},
                 headers=USER)
    assert r.status_code == 403, r.get_data(as_text=True)
    msg = r.get_json()["log"] if "log" in (r.get_json() or {}) else \
        r.get_data(as_text=True)
    assert "TPU quota exceeded" in msg
    assert "requested 16" in msg and "remaining 8" in msg


def test_spawn_preflight_counts_existing_usage(jwa, jwa_kube):
    jwa_kube.create(make_quota("user1", {"google.com/tpu": "16"}))
    r = jwa.post("/api/namespaces/user1/notebooks",
                 json={"name": "first",
                       "tpus": {"accelerator": "v5e", "topology": "2x4"}},
                 headers=USER)
    assert r.status_code == 200, r.get_data(as_text=True)
    # The notebook exists but its pods don't yet — simulate the worker pod
    # (carrying the notebook-name label a real worker has, so it isn't
    # double-counted on top of the CR's declared chips) so used=8, then a
    # 16-chip spawn must be denied with remaining 8.
    jwa_kube.create(make_pod("user1", "first-0", tpu=8,
                             labels={"notebook-name": "first"}))
    r = jwa.post("/api/namespaces/user1/notebooks",
                 json={"name": "second",
                       "tpus": {"accelerator": "v5e", "topology": "4x4"}},
                 headers=USER)
    assert r.status_code == 403
    assert "remaining 8" in r.get_data(as_text=True)
    # An 8-chip spawn still fits.
    r = jwa.post("/api/namespaces/user1/notebooks",
                 json={"name": "third",
                       "tpus": {"accelerator": "v5e", "topology": "2x4"}},
                 headers=USER)
    assert r.status_code == 200, r.get_data(as_text=True)


def test_back_to_back_spawns_cannot_both_slip_under_quota(jwa, jwa_kube):
    """The second of two quick spawns must be denied even though the first
    notebook's pods don't exist yet — the preflight counts declared
    notebook footprints, not just materialized pods (review finding: the
    stranding the preflight exists to prevent)."""
    jwa_kube.create(make_quota("user1", {"google.com/tpu": "8"}))
    r = jwa.post("/api/namespaces/user1/notebooks",
                 json={"name": "first",
                       "tpus": {"accelerator": "v5e", "topology": "2x4"}},
                 headers=USER)
    assert r.status_code == 200, r.get_data(as_text=True)
    # No pods created; status.used is still 0.  The declared 8 chips of
    # "first" must still block another 8-chip ask.
    r = jwa.post("/api/namespaces/user1/notebooks",
                 json={"name": "second",
                       "tpus": {"accelerator": "v5e", "topology": "2x4"}},
                 headers=USER)
    assert r.status_code == 403
    assert "TPU quota exceeded" in r.get_data(as_text=True)


def test_stopped_notebooks_release_their_declared_claim(jwa, jwa_kube):
    """A stopped notebook holds no pods; its declared chips must not block
    a new spawn (mirrors the real cluster, where quota frees on scale-0)."""
    from kubeflow_tpu.platform.apis import notebook as nbapi
    from kubeflow_tpu.platform.k8s.types import NOTEBOOK

    jwa_kube.create(make_quota("user1", {"google.com/tpu": "8"}))
    r = jwa.post("/api/namespaces/user1/notebooks",
                 json={"name": "first",
                       "tpus": {"accelerator": "v5e", "topology": "2x4"}},
                 headers=USER)
    assert r.status_code == 200
    r = jwa.patch("/api/namespaces/user1/notebooks/first",
                  json={"stopped": True}, headers=USER)
    assert r.status_code == 200
    assert nbapi.is_stopped(jwa_kube.get(NOTEBOOK, "first", "user1"))
    r = jwa.post("/api/namespaces/user1/notebooks",
                 json={"name": "second",
                       "tpus": {"accelerator": "v5e", "topology": "2x4"}},
                 headers=USER)
    assert r.status_code == 200, r.get_data(as_text=True)


def test_spawn_preflight_multislice_counts_all_slices(jwa, jwa_kube):
    jwa_kube.create(make_quota("user1", {"google.com/tpu": "24"}))
    r = jwa.post("/api/namespaces/user1/notebooks",
                 json={"name": "ms",
                       "tpus": {"accelerator": "v5e", "topology": "4x4",
                                "slices": 2}},
                 headers=USER)
    assert r.status_code == 403
    assert "requested 32" in r.get_data(as_text=True)


def test_spawn_preflight_cpu_quota(jwa, jwa_kube):
    jwa_kube.create(make_quota("user1", {"cpu": "1"}))
    r = jwa.post("/api/namespaces/user1/notebooks",
                 json={"name": "fat", "cpu": "4",
                       "tpus": {"accelerator": "v5e", "topology": "2x4"}},
                 headers=USER)
    assert r.status_code == 403
    assert "namespace quota exceeded" in r.get_data(as_text=True)


def test_restart_runs_quota_preflight(jwa, jwa_kube):
    """PATCH stopped=false re-claims chips: with the budget now held by
    another notebook, the restart must 403 with the user-facing message
    instead of stranding at pod admission (review finding)."""
    jwa_kube.create(make_quota("user1", {"google.com/tpu": "8"}))
    assert jwa.post("/api/namespaces/user1/notebooks",
                    json={"name": "first",
                          "tpus": {"accelerator": "v5e", "topology": "2x4"}},
                    headers=USER).status_code == 200
    assert jwa.patch("/api/namespaces/user1/notebooks/first",
                     json={"stopped": True}, headers=USER).status_code == 200
    assert jwa.post("/api/namespaces/user1/notebooks",
                    json={"name": "second",
                          "tpus": {"accelerator": "v5e", "topology": "2x4"}},
                    headers=USER).status_code == 200
    r = jwa.patch("/api/namespaces/user1/notebooks/first",
                  json={"stopped": False}, headers=USER)
    assert r.status_code == 403
    assert "TPU quota exceeded" in r.get_data(as_text=True)
    # Free the budget: stopping "second" lets "first" restart.
    assert jwa.patch("/api/namespaces/user1/notebooks/second",
                     json={"stopped": True}, headers=USER).status_code == 200
    assert jwa.patch("/api/namespaces/user1/notebooks/first",
                     json={"stopped": False}, headers=USER).status_code == 200


def test_malformed_user_quantity_is_400_not_500(jwa, jwa_kube):
    jwa_kube.create(make_quota("user1", {"cpu": "8"}))
    r = jwa.post("/api/namespaces/user1/notebooks",
                 json={"name": "bad", "cpu": "abc",
                       "tpus": {"accelerator": "v5e", "topology": "2x4"}},
                 headers=USER)
    assert r.status_code == 400, r.get_data(as_text=True)
    assert "invalid cpu quantity" in r.get_data(as_text=True)


def test_malformed_pod_quantities_rejected_typed(kube):
    """Raw pod create with junk quantities gets a typed Invalid (422), and
    never enters the store to poison later admissions."""
    kube.create(make_quota("u", {"cpu": "8"}))
    with pytest.raises(errors.Invalid) as exc:
        kube.create({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "junk", "namespace": "u"},
            "spec": {"containers": [{"name": "c", "resources": {
                "requests": {"cpu": "abc"}}}]},
        })
    assert "invalid quantity 'abc'" in str(exc.value)
    kube.create(make_pod("u", "fine", cpu="1"))  # namespace not poisoned


def test_tpus_budget_counts_declared_notebooks(jwa, jwa_kube):
    """The picker budget must use the same declared-notebook accounting as
    the preflight, so the UI never enables a pick the submit 403s."""
    jwa_kube.create(make_quota("user1", {"google.com/tpu": "16"}))
    assert jwa.post("/api/namespaces/user1/notebooks",
                    json={"name": "first",
                          "tpus": {"accelerator": "v5e", "topology": "2x4"}},
                    headers=USER).status_code == 200
    # No pods yet (status.used == 0) — but 8 chips are declared.
    r = jwa.get("/api/namespaces/user1/tpus", headers=USER)
    assert r.get_json()["quota"] == {"hard": 16, "used": 8, "remaining": 8}


def test_declared_chips_not_double_counted(jwa, jwa_kube):
    """A CR carrying BOTH spec.tpu and a redundant template chip limit
    declares its chips once: spec.tpu is authoritative."""
    jwa_kube.create(make_quota("user1", {"google.com/tpu": "16"}))
    jwa_kube.create({
        "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
        "metadata": {"name": "redundant", "namespace": "user1"},
        "spec": {"tpu": {"accelerator": "v5e", "topology": "2x4"},
                 "template": {"spec": {"containers": [{
                     "name": "redundant", "resources": {
                         "limits": {"google.com/tpu": "8"}}}]}}},
    })
    r = jwa.get("/api/namespaces/user1/tpus", headers=USER)
    assert r.get_json()["quota"] == {"hard": 16, "used": 8, "remaining": 8}
    # And a second legitimate 8-chip spawn is NOT falsely denied.
    assert jwa.post("/api/namespaces/user1/notebooks",
                    json={"name": "ok",
                          "tpus": {"accelerator": "v5e", "topology": "2x4"}},
                    headers=USER).status_code == 200


def test_preflight_sums_foreign_pods_and_declared_notebooks(jwa, jwa_kube):
    """Chips held by NON-notebook pods (a training Job, a bare pod) and by
    a not-yet-materialized notebook CR must ADD, not max: hard=16 with a
    foreign 8-chip pod plus a declared-8 notebook is fully committed, so
    another 8-chip spawn must 403 (it would otherwise strand at pod
    admission — the exact failure the preflight exists to prevent)."""
    jwa_kube.create(make_quota("user1", {"google.com/tpu": "16"}))
    jwa_kube.create(make_pod("user1", "training-job-0", tpu=8))  # no label
    r = jwa.post("/api/namespaces/user1/notebooks",
                 json={"name": "a",
                       "tpus": {"accelerator": "v5e", "topology": "2x4"}},
                 headers=USER)
    assert r.status_code == 200, r.get_data(as_text=True)
    # Notebook "a" declares 8 but has no pods yet; foreign pod holds 8.
    r = jwa.post("/api/namespaces/user1/notebooks",
                 json={"name": "b",
                       "tpus": {"accelerator": "v5e", "topology": "2x4"}},
                 headers=USER)
    assert r.status_code == 403, r.get_data(as_text=True)
    assert "remaining 0" in r.get_data(as_text=True)
    # The picker agrees with the preflight.
    r = jwa.get("/api/namespaces/user1/tpus", headers=USER)
    assert r.get_json()["quota"] == {"hard": 16, "used": 16, "remaining": 0}
    # Once "a" materializes its labeled worker pod, nothing double-counts:
    # still exactly 16 used.
    jwa_kube.create(make_pod("user1", "a-0", tpu=8,
                             labels={"notebook-name": "a"}))
    r = jwa.get("/api/namespaces/user1/tpus", headers=USER)
    assert r.get_json()["quota"] == {"hard": 16, "used": 16, "remaining": 0}


def test_stopped_notebook_terminating_pods_still_hold_quota(jwa, jwa_kube):
    """A just-stopped notebook's pods may still be terminating: its CR no
    longer declares chips, but the live pods DO still count (pod admission
    counts them) — a respawn that ignored them would pass pre-flight and
    strand at pod admission."""
    jwa_kube.create(make_quota("user1", {"google.com/tpu": "8"}))
    r = jwa.post("/api/namespaces/user1/notebooks",
                 json={"name": "a",
                       "tpus": {"accelerator": "v5e", "topology": "2x4"}},
                 headers=USER)
    assert r.status_code == 200, r.get_data(as_text=True)
    jwa_kube.create(make_pod("user1", "a-0", tpu=8,
                             labels={"notebook-name": "a"}))
    # Stop the notebook; its worker pod is still Running (terminating).
    r = jwa.patch("/api/namespaces/user1/notebooks/a",
                  json={"stopped": True}, headers=USER)
    assert r.status_code == 200, r.get_data(as_text=True)
    r = jwa.post("/api/namespaces/user1/notebooks",
                 json={"name": "b",
                       "tpus": {"accelerator": "v5e", "topology": "2x4"}},
                 headers=USER)
    assert r.status_code == 403, r.get_data(as_text=True)
    # Once the pod is actually gone, the chips free up and b spawns.
    from kubeflow_tpu.platform.k8s.types import POD as _POD
    jwa_kube.delete(_POD, "a-0", "user1")
    r = jwa.post("/api/namespaces/user1/notebooks",
                 json={"name": "b",
                       "tpus": {"accelerator": "v5e", "topology": "2x4"}},
                 headers=USER)
    assert r.status_code == 200, r.get_data(as_text=True)


def test_tpus_endpoint_reports_chip_budget(jwa, jwa_kube):
    r = jwa.get("/api/namespaces/user1/tpus", headers=USER)
    assert r.get_json()["quota"] is None
    jwa_kube.create(make_quota("user1", {"google.com/tpu": "32"}))
    jwa_kube.create(make_pod("user1", "w0", tpu=8))
    r = jwa.get("/api/namespaces/user1/tpus", headers=USER)
    assert r.get_json()["quota"] == {"hard": 32, "used": 8, "remaining": 24}


# -- the wire transport ------------------------------------------------------

def test_denial_crosses_httpkube_as_typed_forbidden():
    from kubeflow_tpu.platform.k8s.client import RestKubeClient
    from kubeflow_tpu.platform.testing.httpkube import HttpKubeServer

    kube = FakeKube()
    kube.add_namespace("u")
    kube.create(make_quota("u", {"google.com/tpu": "8"}))
    server = HttpKubeServer(kube).start()
    try:
        client = RestKubeClient(server.base_url)
        with pytest.raises(errors.Forbidden) as exc:
            client.create(make_pod("u", "greedy", tpu=64))
        assert "exceeded quota" in str(exc.value)
        # Within-quota create works and status.used crosses back.
        client.create(make_pod("u", "ok", tpu=8))
        rq = client.get(RESOURCEQUOTA, "kf-resource-quota", "u")
        assert rq["status"]["used"]["google.com/tpu"] == "8"
    finally:
        server.stop()
