"""The serving front door (ISSUE 19, platform/activator.py): zero-drop
cold-start holds (wake-stamp + replay), per-tenant token-bucket
admission with the burn-driven SLO surcharge, weighted fair-share hold
drain, structured shed outcomes on the wire, and the controller-push
EndpointBook.  Hermetic — fake kube client, fake forward transport."""
from __future__ import annotations

import json
import threading
import time
import types

import pytest
from werkzeug.test import Client

from kubeflow_tpu.platform import activator as act_mod
from kubeflow_tpu.platform.activator import (
    Activator,
    EndpointBook,
    TokenBucket,
    _ServiceFront,
    create_activator_app,
)
from kubeflow_tpu.platform.apis import inferenceservice as api
from kubeflow_tpu.platform.runtime import metrics


class FakeClient:
    """Captures the wake-annotation patches the activator writes."""

    def __init__(self):
        self.patches = []

    def patch(self, gvk, name, patch, namespace=None, *,
              patch_type="merge"):
        self.patches.append((gvk, name, namespace, patch, patch_type))


def ok_forward(calls):
    def forward(url, method, body, headers, timeout):
        calls.append({"url": url, "method": method, "body": body,
                      "headers": dict(headers)})
        return 200, {"Content-Type": "application/json"}, json.dumps(
            {"success": True, "tokens": [[1, 2]]}).encode()
    return forward


def make_front(book=None, forward=None, client=None, **kw):
    book = book if book is not None else EndpointBook()
    client = client if client is not None else FakeClient()
    act = Activator(client, book=book, forward=forward or ok_forward([]),
                    **kw)
    return Client(create_activator_app(act)), book, client, act


def post(client, path="/serve/ns/svc/v1/generate", *, headers=None,
         body=None):
    return client.post(
        path, data=json.dumps(body or {"tokens": [[1]]}),
        headers={"Content-Type": "application/json", **(headers or {})})


def shed_count(tenant, reason):
    return metrics.registry.get_sample_value(
        "serve_requests_shed_total",
        {"tenant": tenant, "reason": reason}) or 0.0


# -- QoS primitives -----------------------------------------------------------


def test_token_bucket_refill_and_retry_hint():
    clock = [0.0]
    b = TokenBucket(rate=2.0, burst=4.0, now=lambda: clock[0])
    for _ in range(4):
        assert b.take()[0]
    granted, wait = b.take()
    assert not granted and wait == pytest.approx(0.5)
    clock[0] += 0.5  # one token refilled
    assert b.take()[0]
    # The bucket caps at burst: a long idle stretch is not a mega-burst.
    clock[0] += 1e6
    grants = sum(1 for _ in range(10) if b.take()[0])
    assert grants == 4


def test_wrr_drain_is_weighted_fair():
    """Smooth WRR with a=2, b=1: every window of three drains serves a
    twice and b once, and within a tenant the order stays FIFO."""
    front = _ServiceFront({"a": 2.0, "b": 1.0})
    tags = {}  # _Waiter has __slots__, so tag by identity
    with front.lock:
        for tenant, n in (("a", 6), ("b", 3)):
            for i in range(n):
                w = act_mod._Waiter(tenant)
                tags[id(w)] = f"{tenant}{i}"
                front.enqueue(w)
    order = []
    for _ in range(9):
        with front.lock:
            w = front.next_waiter()
            front.advance(w)
        order.append(tags[id(w)])
    for lo in range(0, 9, 3):
        window = order[lo:lo + 3]
        assert sum(1 for t in window if t.startswith("a")) == 2, order
    assert [t for t in order if t.startswith("a")] == [
        f"a{i}" for i in range(6)]
    assert [t for t in order if t.startswith("b")] == [
        f"b{i}" for i in range(3)]
    with front.lock:
        assert front.next_waiter() is None
        assert not front._wrr_current  # state clears when drained


def test_tenant_weights_knob_parses(monkeypatch):
    monkeypatch.setenv("KFT_ACTIVATOR_TENANT_WEIGHTS", "alice=2, bob=1")
    assert act_mod.tenant_weights() == {"alice": 2.0, "bob": 1.0}


# -- endpoint book ------------------------------------------------------------


def test_endpoint_book_publish_forget_subscribe():
    book = EndpointBook()
    seen = []
    book.subscribe(seen.append)
    book.publish("ns/a", endpoints=["http://x:1", None],
                 ttft_target_s=0.5, phase="Ready")
    rec = book.get("ns/a")
    assert rec.endpoints == ("http://x:1",)  # falsy entries dropped
    assert rec.ttft_target_s == 0.5 and rec.phase == "Ready"
    book.forget("ns/a")
    assert book.get("ns/a") is None
    assert seen == ["ns/a", "ns/a"]
    assert book.snapshot() == {}


# -- admission: tenant buckets + the SLO surcharge ----------------------------


def test_tenant_bucket_429_isolates_tenants(monkeypatch):
    monkeypatch.setenv("KFT_ACTIVATOR_TENANT_BURST", "2")
    monkeypatch.setenv("KFT_ACTIVATOR_TENANT_RATE", "0.001")
    client, book, _, _ = make_front()
    book.publish("ns/svc", endpoints=["http://b:1"])
    heavy = {"X-KFT-Tenant": "heavy"}
    assert post(client, headers=heavy).status_code == 200
    assert post(client, headers=heavy).status_code == 200
    before = shed_count("heavy", "tenant-bucket")
    resp = post(client, headers=heavy)
    assert resp.status_code == 429
    assert float(resp.headers["Retry-After"]) >= 1
    assert "admission rate" in resp.get_json()["log"]
    assert shed_count("heavy", "tenant-bucket") == before + 1
    # The quiet tenant's bucket is untouched.
    assert post(client, headers={"X-KFT-Tenant": "quiet"}).status_code \
        == 200


def test_slo_knee_surcharge_sheds_heavy_tenant(monkeypatch):
    """Past the knee (stored-series TTFT p99 over the target multiple)
    every request costs KFT_ACTIVATOR_SHED_COST tokens: with burst 3 and
    cost 4 the very first request runs the bucket dry → 429 slo-shed.
    Below the knee the same request costs 1 and flows."""
    from kubeflow_tpu.telemetry import fleetscrape

    monkeypatch.setenv("KFT_ACTIVATOR_TENANT_BURST", "3")
    monkeypatch.setenv("KFT_ACTIVATOR_SHED_COST", "4")
    ttft = {"p99": 10.0}
    monkeypatch.setattr(
        fleetscrape, "serve_sample",
        lambda tsdb, key: types.SimpleNamespace(ttft_p99_s=ttft["p99"]))
    client, book, _, act = make_front(tsdb=object())
    book.publish("ns/svc", endpoints=["http://b:1"], ttft_target_s=0.5)
    # 10.0 > 0.5 * 4 (the default multiple): over the knee.
    before = shed_count("hammer", "slo-shed")
    resp = post(client, headers={"X-KFT-Tenant": "hammer"})
    assert resp.status_code == 429 and resp.headers.get("Retry-After")
    assert shed_count("hammer", "slo-shed") == before + 1
    # Below the knee the surcharge is off (fresh cache + fresh tenant).
    ttft["p99"] = 0.1
    act._knee_cache.clear()
    assert post(client, headers={"X-KFT-Tenant": "calm"}).status_code \
        == 200


def test_malformed_deadline_400_and_unknown_service_404():
    client, book, _, _ = make_front()
    book.publish("ns/svc", endpoints=["http://b:1"])
    assert post(client, headers={
        "X-KFT-Deadline-Seconds": "soon"}).status_code == 400
    resp = post(client, path="/serve/ns/nope/v1/generate")
    assert resp.status_code == 404
    assert "no such service" in resp.get_json()["log"]


# -- the warm proxy path ------------------------------------------------------


def test_proxy_forwards_qos_headers_and_remaining_deadline():
    calls = []
    client, book, _, _ = make_front(forward=ok_forward(calls))
    book.publish("ns/svc", endpoints=["http://b:1"])
    resp = post(client, headers={
        "X-KFT-Tenant": "alice", "X-KFT-Priority": "interactive",
        "X-KFT-Deadline-Seconds": "30",
        "traceparent": "00-" + "a" * 32 + "-" + "b" * 16 + "-01"})
    assert resp.status_code == 200
    assert resp.get_json()["tokens"] == [[1, 2]]
    [call] = calls
    assert call["url"] == "http://b:1/v1/generate"
    h = call["headers"]
    assert h["X-KFT-Tenant"] == "alice"
    assert h["X-KFT-Priority"] == "interactive"
    assert h["traceparent"].startswith("00-" + "a" * 32)
    # Forwarded as the REMAINING budget, not the original header.
    assert 0.0 < float(h["X-KFT-Deadline-Seconds"]) <= 30.0


def test_proxy_retries_backend_503_then_succeeds(monkeypatch):
    monkeypatch.setenv("KFT_ACTIVATOR_REPLAY_BASE_SECONDS", "0.001")
    monkeypatch.setenv("KFT_ACTIVATOR_REPLAY_CAP_SECONDS", "0.002")
    statuses = [503, 503, 200]
    calls = []

    def flaky(url, method, body, headers, timeout):
        calls.append(url)
        status = statuses[min(len(calls) - 1, len(statuses) - 1)]
        return status, {"Content-Type": "application/json"}, json.dumps(
            {"success": status == 200}).encode()

    client, book, _, _ = make_front(forward=flaky)
    book.publish("ns/svc", endpoints=["http://b:1"])
    assert post(client).status_code == 200
    assert len(calls) == 3


def test_proxy_exhausted_replays_return_503_with_retry_after(monkeypatch):
    monkeypatch.setenv("KFT_ACTIVATOR_REPLAY_RETRIES", "2")
    monkeypatch.setenv("KFT_ACTIVATOR_REPLAY_BASE_SECONDS", "0.001")
    monkeypatch.setenv("KFT_ACTIVATOR_REPLAY_CAP_SECONDS", "0.002")

    def down(url, method, body, headers, timeout):
        raise OSError("connection refused")

    client, book, _, _ = make_front(forward=down)
    book.publish("ns/svc", endpoints=["http://b:1"])
    resp = post(client)
    assert resp.status_code == 503
    assert resp.headers.get("Retry-After")
    assert "replay budget exhausted" in resp.get_json()["log"]


def test_proxy_passes_backend_failures_through_verbatim():
    """A backend 504 (its own deadline gate) or 400 is the caller's
    answer — the activator must not retry or rewrap it."""
    calls = []

    def gone(url, method, body, headers, timeout):
        calls.append(url)
        return 504, {"Content-Type": "application/json"}, json.dumps(
            {"success": False, "log": "request deadline expired"}).encode()

    client, book, _, _ = make_front(forward=gone)
    book.publish("ns/svc", endpoints=["http://b:1"])
    resp = post(client)
    assert resp.status_code == 504
    assert len(calls) == 1  # never replayed


# -- the cold hold path -------------------------------------------------------


def test_cold_hold_stamps_wake_and_replays(monkeypatch):
    """The tentpole lifecycle: a request for a zero-replica service is
    held (never refused), the wake annotation is merge-patched with the
    current time, and once the controller publishes ready endpoints the
    request replays — one 200, zero drops."""
    monkeypatch.setenv("KFT_ACTIVATOR_RESTAMP_SECONDS", "0.05")
    calls = []
    client, book, kube, act = make_front(forward=ok_forward(calls),
                                         now=lambda: 1234.5)
    book.publish("ns/svc", endpoints=[], phase="Idle")  # cold, known
    out = {}

    def go():
        out["resp"] = post(client, headers={"X-KFT-Tenant": "alice"})

    t = threading.Thread(target=go)
    t.start()
    deadline = time.monotonic() + 5.0
    while not kube.patches and time.monotonic() < deadline:
        time.sleep(0.01)
    gvk, name, ns, patch, patch_type = kube.patches[0]
    assert (name, ns, patch_type) == ("svc", "ns", "merge")
    assert patch["metadata"]["annotations"][api.ANNOTATION_WAKE] \
        == "1234.500"
    with act._fronts_lock:
        front = act._fronts["ns/svc"]
    with front.lock:
        assert front.held_count() == 1  # parked, not dropped
    # The controller converged: ready endpoints published → replay.
    book.publish("ns/svc", endpoints=["http://b:1"], phase="Ready")
    t.join(10)
    assert out["resp"].status_code == 200
    assert calls and calls[0]["url"] == "http://b:1/v1/generate"
    with front.lock:
        assert front.held_count() == 0


def test_hold_restamps_while_held(monkeypatch):
    """The staleness-race defeat: while requests stay held the stamp is
    refreshed on cadence, so a controller that read a stale stamp sees a
    fresh one next pass (tests/ctrlplane/test_autoscale.py pins the
    decide_scale side)."""
    monkeypatch.setenv("KFT_ACTIVATOR_RESTAMP_SECONDS", "0.05")
    clock = {"t": 100.0}
    client, book, kube, _ = make_front(now=lambda: clock["t"])
    book.publish("ns/svc", endpoints=[])
    out = {}

    def go():
        out["resp"] = post(client)

    t = threading.Thread(target=go)
    t.start()
    deadline = time.monotonic() + 5.0
    while len(kube.patches) < 3 and time.monotonic() < deadline:
        clock["t"] += 1.0
        time.sleep(0.02)
    book.publish("ns/svc", endpoints=["http://b:1"])
    t.join(10)
    assert out["resp"].status_code == 200
    stamps = [float(p[3]["metadata"]["annotations"][api.ANNOTATION_WAKE])
              for p in kube.patches]
    assert len(stamps) >= 3
    assert stamps == sorted(stamps) and stamps[-1] > stamps[0]


def test_hold_overflow_sheds_503(monkeypatch):
    monkeypatch.setenv("KFT_ACTIVATOR_HOLD_QUEUE", "1")
    monkeypatch.setenv("KFT_ACTIVATOR_RESTAMP_SECONDS", "0.05")
    client, book, _, act = make_front()
    book.publish("ns/svc", endpoints=[])
    out = {}
    t = threading.Thread(target=lambda: out.setdefault("r", post(client)))
    t.start()
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        with act._fronts_lock:
            front = act._fronts.get("ns/svc")
        if front is not None:
            with front.lock:
                if front.held_count() == 1:
                    break
        time.sleep(0.01)
    before = shed_count("late", "hold-overflow")
    resp = post(client, headers={"X-KFT-Tenant": "late"})
    assert resp.status_code == 503
    assert resp.headers.get("Retry-After")
    assert "hold queue full" in resp.get_json()["log"]
    assert shed_count("late", "hold-overflow") == before + 1
    book.publish("ns/svc", endpoints=["http://b:1"])
    t.join(10)
    assert out["r"].status_code == 200  # the held one still lands


def test_wake_timeout_sheds_503(monkeypatch):
    monkeypatch.setenv("KFT_ACTIVATOR_WAKE_DEADLINE_SECONDS", "0.2")
    monkeypatch.setenv("KFT_ACTIVATOR_RESTAMP_SECONDS", "0.05")
    client, book, _, _ = make_front()
    book.publish("ns/svc", endpoints=[])
    before = shed_count("default", "wake-timeout")
    resp = post(client)
    assert resp.status_code == 503
    assert resp.headers.get("Retry-After")
    assert "wake deadline" in resp.get_json()["log"]
    assert shed_count("default", "wake-timeout") == before + 1


def test_held_request_deadline_504_and_never_replayed(monkeypatch):
    """X-KFT-Deadline-Seconds expires while held → 504, evicted from the
    hold queue, and NEVER forwarded once the service wakes — a dead
    request must not consume a decode slot."""
    monkeypatch.setenv("KFT_ACTIVATOR_RESTAMP_SECONDS", "0.05")
    calls = []
    client, book, _, act = make_front(forward=ok_forward(calls))
    book.publish("ns/svc", endpoints=[])
    before = shed_count("giveup", "deadline")
    resp = post(client, headers={"X-KFT-Tenant": "giveup",
                                 "X-KFT-Deadline-Seconds": "0.15"})
    assert resp.status_code == 504
    assert "deadline expired" in resp.get_json()["log"]
    assert shed_count("giveup", "deadline") == before + 1
    with act._fronts_lock:
        front = act._fronts["ns/svc"]
    with front.lock:
        assert front.held_count() == 0  # evicted, not leaked
    book.publish("ns/svc", endpoints=["http://b:1"])
    time.sleep(0.05)
    assert calls == []  # the corpse never replayed


def test_debug_snapshot_lists_services_and_holds():
    client, book, _, act = make_front()
    book.publish("ns/svc", endpoints=["http://b:1"], ttft_target_s=0.5,
                 phase="Ready")
    snap = client.get("/debug/activator").get_json()
    assert snap["services"]["ns/svc"]["endpoints"] == ["http://b:1"]
    assert snap["services"]["ns/svc"]["phase"] == "Ready"
    assert snap["held"] == {}
    assert client.get("/healthz").status_code == 200
    # The single-slot registry feeds the controller health port's
    # /debug/activator (main._serve_health).
    act_mod.register_debug(act)
    try:
        assert act_mod.debug_snapshot() == act.debug_snapshot()
    finally:
        act_mod.register_debug(None)
    assert act_mod.debug_snapshot() is None


# -- controller publish (the discovery seam) ----------------------------------


def test_reconciler_publishes_endpoints_and_forgets_on_delete():
    from kubeflow_tpu.platform.k8s.types import INFERENCESERVICE
    from kubeflow_tpu.platform.runtime import Request
    from kubeflow_tpu.platform.testing import FakeKube

    from .test_inferenceservice_controller import (
        add_replica_pod,
        make_reconciler,
        make_service,
    )

    kube = FakeKube()
    kube.add_namespace("serve")
    book = EndpointBook()
    kube.create(make_service(replicas={"min": 2, "max": 4}))
    r = make_reconciler(kube)
    r.book = book
    r.reconcile(Request("serve", "llm"))
    rec = book.get("serve/llm")
    assert rec is not None and rec.endpoints == ()  # nothing ready yet
    assert rec.phase == "Pending"

    add_replica_pod(kube, "serve", "llm", 1, 0,
                    endpoint="http://replica-0:9000")
    add_replica_pod(kube, "serve", "llm", 1, 1, ready=False,
                    endpoint="http://replica-1:9000")
    r.reconcile(Request("serve", "llm"))
    rec = book.get("serve/llm")
    assert rec.endpoints == ("http://replica-0:9000",)  # ready pods only
    assert rec.ttft_target_s is None  # no TTFT target in this spec

    kube.delete(INFERENCESERVICE, "llm", "serve")
    r.reconcile(Request("serve", "llm"))
    assert book.get("serve/llm") is None
