"""Wire fast path tests (ISSUE 18): native scanner vs Python semantics.

The codec seam (k8s/codec.py) carries every watch/list byte, so its two
engines must be indistinguishable except in speed.  This suite pins:

* the raw C scanner (native/wirecodec.cc): envelope slicing, identity
  field extraction (only escape-free strings), duplicate-key last-wins,
  scalar-metadata demotion, malformed-line rejection — all against
  ``json.loads`` as the semantic reference, including a seeded fuzz;
* the 3-way decode/encode matrix (python / native / mixed) over a corpus
  of realistic and adversarial watch lines;
* LazyResource/LazyMeta laziness, proven by ``codec.stats()`` counters
  rather than guessed: identity reads parse nothing, admit materializes
  exactly once;
* merge-patch parity (native kfp_merge_create vs apply.py's ``_diff``)
  and canonical-serialization byte equality, both fuzzed;
* ShardFilter: spec round-trip, fail-open admits for every source, the
  ``involved`` candidate derivation;
* server-side filtering end to end: FakeKube watch/list and the real
  RestKubeClient -> HttpKube wire path only deliver subscribed shards;
* the KF_WIRE_CODEC / KF_SHARD_SERVER_FILTER knob contracts.

When the library is unbuilt the native halves skip and the Python
fallback legs still run — which is itself the contract the KF_NATIVE=0
CI leg enforces.
"""
from __future__ import annotations

import json
import random
import threading

import pytest

from kubeflow_tpu.platform import native
from kubeflow_tpu.platform.k8s import codec
from kubeflow_tpu.platform.k8s.types import NOTEBOOK, freeze
from kubeflow_tpu.platform.runtime import apply
from kubeflow_tpu.platform.runtime.sharding import ShardFilter, shard_of
from kubeflow_tpu.platform.testing import FakeKube

NATIVE = native.available()
needs_native = pytest.mark.skipif(not NATIVE, reason="libkfnative not built")


def _nb(name, ns="user1", labels=None):
    md = {"name": name, "namespace": ns}
    if labels:
        md["labels"] = labels
    return {
        "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
        "metadata": md,
        "spec": {"template": {"spec": {"containers": [
            {"name": name, "image": "img"}]}}},
    }


def _line(etype="MODIFIED", obj=None) -> bytes:
    if obj is None:
        obj = {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "nb-0", "namespace": "user1",
                         "resourceVersion": "42",
                         "labels": {"notebook-name": "nb"}},
            "spec": {"containers": [{"name": "nb", "image": "img"}]},
            "status": {"phase": "Running"},
        }
    return json.dumps({"type": etype, "object": obj},
                      separators=(",", ":")).encode()


# -- the raw scanner ----------------------------------------------------------


@needs_native
def test_scan_event_slices_match_json_loads():
    line = _line()
    etype, obj_bytes, meta_bytes = native.wire_scan_event(line)
    evt = json.loads(line)
    assert etype == evt["type"]
    assert json.loads(obj_bytes) == evt["object"]
    assert json.loads(meta_bytes) == evt["object"]["metadata"]


@needs_native
def test_scanner_extracts_identity_fields():
    scan = native.wire_scanner()
    etype, obj, meta, name, ns, rv = scan(_line())
    assert (etype, name, ns, rv) == ("MODIFIED", "nb-0", "user1", "42")


@needs_native
def test_escaped_name_is_not_extracted_but_still_readable():
    # Field extraction is an optimization for escape-free strings; an
    # escaped value must come back None (-> slow path), never mangled.
    obj = {"metadata": {"name": 'a"b', "namespace": "user1"}}
    scan = native.wire_scanner()
    _, _, meta, name, ns, _ = scan(_line(obj=obj))
    assert name is None          # escaped: not extracted
    assert ns == "user1"         # escape-free sibling still extracted
    assert json.loads(meta)["name"] == 'a"b'


@needs_native
def test_duplicate_keys_last_wins_like_json_loads():
    # json.loads keeps the LAST occurrence at every level; the scanner
    # must agree or the fast path would answer differently than the
    # fallback for the same bytes.
    line = (b'{"type":"ADDED","object":{"metadata":{"name":"first"},'
            b'"x":1},"object":{"metadata":{"name":"old","name":"new",'
            b'"namespace":"ns2"}}}')
    evt = json.loads(line)
    scan = native.wire_scanner()
    etype, obj, meta, name, ns, rv = scan(line)
    assert json.loads(obj) == evt["object"]
    assert json.loads(meta) == evt["object"]["metadata"]
    assert name == evt["object"]["metadata"]["name"] == "new"
    assert ns == "ns2"


@needs_native
def test_scalar_metadata_is_demoted_to_slow_path():
    # A non-object metadata (never produced by a real apiserver) must not
    # be sliced: the Python side materializes the body and sees exactly
    # what json.loads would.
    etype, obj, meta = native.wire_scan_event(
        b'{"type":"ADDED","object":{"metadata":5,"spec":{}}}')
    assert meta is None
    assert json.loads(obj) == {"metadata": 5, "spec": {}}


@needs_native
def test_error_event_status_object():
    status = {"kind": "Status", "apiVersion": "v1", "status": "Failure",
              "reason": "Expired", "code": 410,
              "message": "too old resource version: 5"}
    etype, obj, meta = native.wire_scan_event(_line("ERROR", status))
    assert etype == "ERROR"
    assert meta is None          # a Status has no metadata
    assert json.loads(obj) == status


@needs_native
@pytest.mark.parametrize("bad", [
    b"",
    b"not json",
    b'{"object":{}}',                       # missing type
    b'{"type":"ADDED"}',                    # missing object
    b'{"type":5,"object":{}}',              # non-string type
    b'{"type":"ADDED","object":{}}trail',   # trailing data
    b'{"type":"ADDED","object":{"a":"unterminated}',
])
def test_scanner_rejects_malformed(bad):
    with pytest.raises(native.NativeError):
        native.wire_scan_event(bad)


@needs_native
def test_scanner_fuzz_against_json_loads():
    # Random envelopes: every slice must json.loads to exactly what a
    # full-document parse sees, and every extracted field must equal it.
    rng = random.Random(18)

    def rand_value(depth=0):
        r = rng.random()
        if depth > 2 or r < 0.35:
            return rng.choice([
                1, -7, 3.5, "plain", "with spaces", 'q"uote', "back\\slash",
                "unié", "nl\nline", True, False, None, [], {},
                [1, "two", {"three": 3}],
            ])
        return {f"k{i}": rand_value(depth + 1)
                for i in range(rng.randint(0, 4))}

    def rand_meta():
        md = {}
        if rng.random() < 0.9:
            md["name"] = rng.choice(
                ["nb-0", "nb", 'we"ird', "unié", "a-b-c"])
        if rng.random() < 0.7:
            md["namespace"] = rng.choice(["user1", "user2"])
        if rng.random() < 0.7:
            md["resourceVersion"] = str(rng.randint(1, 10**6))
        if rng.random() < 0.5:
            md["labels"] = {"app": "notebook"}
        return md

    scan = native.wire_scanner()
    for i in range(300):
        obj = {"metadata": rand_meta(), "spec": rand_value(),
               "status": rand_value()}
        for sep in ((",", ":"), (", ", ": ")):
            line = json.dumps(
                {"type": rng.choice(["ADDED", "MODIFIED", "DELETED"]),
                 "object": obj}, separators=sep).encode()
            evt = json.loads(line)
            etype, ob, mb, name, ns, rv = scan(line)
            assert etype == evt["type"]
            assert json.loads(ob) == evt["object"], line
            assert json.loads(mb) == evt["object"]["metadata"]
            md = evt["object"]["metadata"]
            for got, key in ((name, "name"), (ns, "namespace"),
                             (rv, "resourceVersion")):
                if got is not None:
                    assert got == md[key], (line, key)


# -- the 3-way decode/encode matrix ------------------------------------------


CORPUS = [
    _line(),
    _line("ADDED", _nb("nb", labels={"notebook-name": "nb"})),
    _line("DELETED", {"metadata": {"name": "gone"}}),
    _line("ERROR", {"kind": "Status", "apiVersion": "v1", "code": 410,
                    "status": "Failure", "reason": "Expired",
                    "message": "too old"}),
    _line(obj={"metadata": {"name": 'esc"aped', "namespace": "user1"},
               "spec": {"uni": "héllo", "n": 2**40}}),
    _line(obj={"metadata": {"name": "cluster-scoped"}}),    # no namespace
    json.dumps({"type": "MODIFIED",
                "object": {"metadata": {"name": "spaced"}}},
               separators=(", ", ": ")).encode(),           # padded JSON
]


@needs_native
@pytest.mark.parametrize("line", CORPUS, ids=range(len(CORPUS)))
def test_three_way_decode_matrix(line):
    t_py, o_py = codec.decode_event(line, engine="python")
    t_nat, o_nat = codec.decode_event(line, engine="native")
    assert t_nat == t_py
    # Mapping equality before materialization, dict equality after.
    assert o_nat == o_py
    assert codec.materialize(o_nat) == o_py
    # Mixed legs: a natively decoded object must serialize identically
    # through both encode engines.
    _, o_mixed = codec.decode_event(line, engine="native")
    assert json.loads(codec.encode(o_mixed, engine="native")) == o_py
    _, o_mixed2 = codec.decode_event(line, engine="native")
    assert json.loads(codec.encode(o_mixed2, engine="python")) == o_py


@needs_native
def test_identity_reads_parse_nothing():
    before = codec.stats()
    _, obj = codec.decode_event(_line(), engine="native")
    m = obj["metadata"]
    assert (m.get("name"), m.get("namespace"), m.get("resourceVersion")) \
        == ("nb-0", "user1", "42")
    assert "name" in m and bool(m)
    assert not m.parsed                   # no metadata JSON parse
    assert not obj.materialized           # no body parse
    after = codec.stats()
    assert after["materialize"] == before["materialize"]
    assert after["decode_native"] == before["decode_native"] + 1
    assert after["decode_python"] == before["decode_python"]


@needs_native
def test_non_identity_meta_read_parses_slice_not_body():
    _, obj = codec.decode_event(_line(), engine="native")
    m = obj["metadata"]
    assert m["labels"] == {"notebook-name": "nb"}
    assert m.parsed                       # metadata slice decoded...
    assert not obj.materialized           # ...body still deferred


@needs_native
def test_admit_materializes_exactly_once():
    before = codec.stats()["materialize"]
    _, obj = codec.decode_event(_line(), engine="native")
    doc = codec.materialize(obj)
    assert type(doc) is dict
    assert doc["status"]["phase"] == "Running"
    assert codec.materialize(obj) is doc  # cached, not re-parsed
    assert codec.stats()["materialize"] == before + 1


@needs_native
def test_lazy_meta_is_read_only_and_deep_gettable():
    from kubeflow_tpu.platform.k8s.types import deep_get

    _, obj = codec.decode_event(_line(), engine="native")
    assert deep_get(obj, "metadata", "labels", "notebook-name") == "nb"
    with pytest.raises(TypeError):
        obj["metadata"]["name"] = "other"     # no __setitem__


@needs_native
def test_scan_failure_falls_back_to_python():
    # A line the scanner rejects but json.loads accepts (no "type" key)
    # must cost a fallback, not a failure.
    line = b'{"object": {"metadata": {"name": "x"}}}'
    before = codec.stats()["decode_python"]
    etype, obj = codec.decode_event(line, engine="native")
    assert etype == "" and obj == {"metadata": {"name": "x"}}
    assert codec.stats()["decode_python"] == before + 1


def test_forced_native_without_library_uses_python(monkeypatch):
    # engine="native" on a box with no library: the decoder factory
    # returns None and the Python path answers.
    monkeypatch.setattr(codec, "_tls", threading.local())
    monkeypatch.setattr(native, "wire_scanner", lambda: None)
    etype, obj = codec.decode_event(_line(), engine="native")
    assert etype == "MODIFIED" and type(obj) is dict


def test_python_engine_decodes_plain_dicts():
    before = codec.stats()["decode_python"]
    etype, obj = codec.decode_event(_line(), engine="python")
    assert etype == "MODIFIED" and type(obj) is dict
    assert codec.stats()["decode_python"] == before + 1


@needs_native
def test_encode_raw_passthrough_until_materialized():
    line = _line()
    raw = json.loads(line)["object"]
    _, obj = codec.decode_event(line, engine="native")
    before = codec.stats()
    out = codec.encode(obj)
    # Byte-identical passthrough: the wire bytes were never re-serialized.
    assert json.dumps(raw, separators=(",", ":")) == out
    assert codec.stats()["encode_raw"] == before["encode_raw"] + 1
    codec.materialize(obj)
    out2 = codec.encode(obj)              # materialized: python path
    assert json.loads(out2) == raw
    assert codec.stats()["encode_raw"] == before["encode_raw"] + 1


def test_encode_frozen_view_without_thaw():
    doc = _nb("frozen")
    assert json.loads(codec.encode(freeze(doc))) == doc


# -- merge patch + canonical serialization parity -----------------------------


def _py_merge(current, desired):
    patch = apply._diff(current or {}, desired or {})
    return None if patch is apply._UNCHANGED else patch


MERGE_CASES = [
    ({}, {}),
    ({"a": 1}, {"a": 1}),
    ({"a": 1}, {"a": 2}),
    ({"a": 1, "b": 2}, {"a": 1}),
    ({"a": {"b": 1, "c": 2}}, {"a": {"b": 1}}),
    ({"a": [1, 2]}, {"a": [3]}),
    ({"m": {"x": 1}}, {"m": "scalar"}),
    ({"s": 'hé"llo'}, {"s": "wörld"}),
    ({"spec": {"containers": [{"name": "a"}]}},
     {"spec": {"containers": [{"name": "a"}, {"name": "b"}]},
      "status": {"phase": "Running"}}),
]


@needs_native
@pytest.mark.parametrize("cur,des", MERGE_CASES)
def test_merge_patch_native_matches_python_diff(cur, des):
    assert codec.merge_patch_native(cur, des) == _py_merge(cur, des)


@needs_native
def test_merge_patch_parity_fuzz():
    # No nulls/bools: RFC 7386 cannot store a null and the diff follows
    # Python == (True == 1) — both outside the k8s-object domain.
    rng = random.Random(1806)

    def rand_doc(depth=0):
        r = rng.random()
        if depth > 2 or r < 0.3:
            return rng.choice([1, "s", 3.5, [1, 2], "t", 7, "x"])
        return {f"k{i}": rand_doc(depth + 1)
                for i in range(rng.randint(0, 4))}

    for _ in range(120):
        cur = {"root": rand_doc(), "x": rand_doc()}
        des = {"root": rand_doc(), "y": rand_doc()}
        assert codec.merge_patch_native(cur, des) == _py_merge(cur, des), \
            (cur, des)


@needs_native
def test_merge_patch_for_routes_native_and_counts():
    if not codec.engine_native():
        pytest.skip("codec engine forced to python")
    before = codec.stats()["merge_native"]
    patch = apply.merge_patch_for({"a": 1, "b": 2}, {"a": 2})
    assert patch == {"a": 2, "b": None}
    assert apply.merge_patch_for({"a": 1}, {"a": 1}) is None
    assert codec.stats()["merge_native"] == before + 2


@needs_native
def test_canonical_json_byte_equal_fuzz():
    rng = random.Random(99)

    def rand_doc(depth=0):
        r = rng.random()
        if depth > 2 or r < 0.35:
            return rng.choice([
                0, 1, -42, 2**40, 3.5, 0.25, True, False, None,
                "plain", 'q"uote', "back\\slash", "nl\nline",
                "unié中", "", [1, 2, "three"], {},
            ])
        return {f"k{i}": rand_doc(depth + 1)
                for i in range(rng.randint(0, 4))}

    for _ in range(100):
        doc = {"root": rand_doc(), "arr": [rand_doc() for _ in range(3)]}
        # Canonical form is UTF-8 passthrough, not \uXXXX escapes.
        want = json.dumps(doc, separators=(",", ":"), ensure_ascii=False)
        assert native.canonical_json(json.dumps(doc)) == want


# -- ShardFilter --------------------------------------------------------------


def test_shard_filter_spec_round_trip():
    for src in ("self", "label=notebook-name", "owner=StatefulSet",
                "involved"):
        f = ShardFilter(8, frozenset({1, 5}), src)
        g = ShardFilter.parse(f.spec())
        assert (g.num_shards, g.shards, g.source) == (8, {1, 5}, src)


@pytest.mark.parametrize("bad", [
    None, "", "v2:8:1:self", "v1:8:1", "v1:zero:1:self", "v1:8:x,y:self",
    "v1:0:1:self", "v1:-4:1:self", "v1:8:1:bogus", "self:8:1:v1",
])
def test_shard_filter_malformed_specs_parse_to_unfiltered(bad):
    assert ShardFilter.parse(bad) is None


def test_shard_filter_admits_self_source():
    f = ShardFilter(8, frozenset({shard_of("user1", "mine", 8)}), "self")
    assert f.admits(_nb("mine"))
    other = next(n for n in (f"nb-{i}" for i in range(64))
                 if shard_of("user1", n, 8) not in f.shards)
    assert not f.admits(_nb(other))
    assert f.admits({"metadata": {"namespace": "user1"}})  # no name: open


def test_shard_filter_admits_label_source():
    f = ShardFilter(8, frozenset({shard_of("user1", "nb", 8)}),
                    "label=notebook-name")
    assert f.admits(_nb("nb-0", labels={"notebook-name": "nb"}))
    assert f.admits(_nb("nb-0"))          # label missing: fail-open
    other = next(n for n in (f"x{i}" for i in range(64))
                 if shard_of("user1", n, 8) not in f.shards)
    assert not f.admits(_nb("pod", labels={"notebook-name": other}))


def test_shard_filter_admits_owner_source():
    f = ShardFilter(8, frozenset({shard_of("user1", "nb", 8)}),
                    "owner=StatefulSet")
    pod = _nb("nb-0")
    pod["metadata"]["ownerReferences"] = [
        {"kind": "StatefulSet", "name": "nb", "controller": True}]
    assert f.admits(pod)
    pod["metadata"]["ownerReferences"] = [
        {"kind": "ReplicaSet", "name": "nb", "controller": True}]
    assert f.admits(pod)                  # no controlling STS: fail-open


def test_shard_filter_involved_candidates():
    # nb-s2-0 -> itself, ordinal-stripped, slice-suffix-stripped: any
    # resolvable owner name keeps the event on the stream.
    cands = ShardFilter._involved_candidates(
        {"involvedObject": {"name": "nb-s2-0"}})
    assert cands == ["nb-s2-0", "nb-s2", "nb"]
    assert ShardFilter._involved_candidates({}) == []


def test_shard_filter_involved_admits_any_candidate_shard():
    f = ShardFilter(8, frozenset({shard_of("user1", "nb", 8)}), "involved")
    evt = {"metadata": {"namespace": "user1", "name": "evt-1"},
           "involvedObject": {"name": "nb-0"}}
    assert f.admits(evt)                  # stripped "nb" is subscribed
    assert f.admits({"metadata": {"namespace": "user1"}})  # no involved


# -- server-side filtering end to end ----------------------------------------


def _admissible(names, shards, num_shards=8, ns="user1"):
    return {n for n in names if shard_of(ns, n, num_shards) in shards}


def test_fakekube_list_and_watch_are_shard_filtered():
    kube = FakeKube()
    kube.add_namespace("user1")
    names = [f"nb-{i}" for i in range(24)]
    for n in names:
        kube.create(_nb(n))
    shards = frozenset({0, 3, 5})
    spec = ShardFilter(8, shards, "self").spec()
    want = _admissible(names, shards)
    assert want and want != set(names)    # the filter really splits
    got = {n["metadata"]["name"]
           for n in kube.list(NOTEBOOK, "user1", shard_filter=spec)}
    assert got == want
    # Watch backlog + live events, both filtered server-side.
    stop = threading.Event()
    w = kube.watch(NOTEBOOK, "user1", shard_filter=spec, stop=stop)
    seen = {obj["metadata"]["name"] for _, obj in
            (next(w) for _ in range(len(want)))}
    assert seen == want
    # events_emitted counts PRE-filter: the decode-fraction denominator.
    emitted = kube.events_emitted["Notebook"]
    assert emitted >= len(names)
    stop.set()


def test_fakekube_relist_rv_stays_global_under_filter():
    kube = FakeKube()
    kube.add_namespace("user1")
    for i in range(8):
        kube.create(_nb(f"nb-{i}"))
    spec = ShardFilter(8, frozenset({1}), "self").spec()
    _, rv_filtered = kube.list_with_rv(NOTEBOOK, "user1",
                                       shard_filter=spec)
    _, rv_full = kube.list_with_rv(NOTEBOOK, "user1")
    assert rv_filtered == rv_full         # resume point misses no shard


def test_httpkube_wire_carries_shard_filter():
    from kubeflow_tpu.platform.k8s.client import RestKubeClient
    from kubeflow_tpu.platform.testing.httpkube import HttpKubeServer

    kube = FakeKube()
    kube.add_namespace("user1")
    names = [f"nb-{i}" for i in range(16)]
    for n in names:
        kube.create(_nb(n))
    shards = frozenset({2, 6})
    spec = ShardFilter(8, shards, "self").spec()
    want = _admissible(names, shards)
    assert want and want != set(names)
    server = HttpKubeServer(kube).start()
    try:
        client = RestKubeClient(server.base_url, qps=0)
        assert client.supports_shard_filter
        got = {n["metadata"]["name"]
               for n in client.list(NOTEBOOK, "user1", shard_filter=spec)}
        assert got == want
        items, rv = client.list_with_rv(NOTEBOOK, "user1",
                                        shard_filter=spec)
        assert {n["metadata"]["name"] for n in items} == want
        stop = threading.Event()
        seen = set()
        for etype, obj in client.watch(NOTEBOOK, "user1",
                                       shard_filter=spec, stop=stop):
            assert etype == "ADDED"
            seen.add(obj["metadata"]["name"])
            if len(seen) == len(want):
                stop.set()
                break
        assert seen == want
    finally:
        server.stop()


def test_informer_subscription_thins_stream_and_refilters():
    from kubeflow_tpu.platform.runtime.informer import Informer

    kube = FakeKube()
    kube.add_namespace("user1")
    names = [f"nb-{i}" for i in range(24)]
    for n in names:
        kube.create(_nb(n))
    shards = {"cur": frozenset({0, 1, 2, 3})}

    def subscription():
        return ShardFilter(8, shards["cur"], "self").spec()

    def admit(obj):
        return shard_of(obj["metadata"].get("namespace") or "",
                        obj["metadata"]["name"], 8) in shards["cur"]

    inf = Informer(kube, NOTEBOOK, admit=admit)
    inf.shard_subscription = subscription
    inf.start()
    try:
        assert inf.wait_for_sync(5.0)
        want = _admissible(names, shards["cur"])
        assert {k[1] for k in inf.keys()} == want
        # The SERVER thinned the stream: this informer saw only its own
        # ranges, not the full keyspace (fail-open allows a superset of
        # admit, but self-source derives every key here).
        assert inf.events_seen == len(want) < len(names)
        # Shard move: new subscription + refilter -> ranged relist under
        # the NEW filter; dropped ranges leave, acquired ranges land.
        shards["cur"] = frozenset({4, 5, 6, 7})
        inf.refilter()
        want2 = _admissible(names, shards["cur"])
        assert {k[1] for k in inf.keys()} == want2
        assert not (want2 & want)
    finally:
        inf.stop()


def test_informer_ignores_subscription_without_server_support():
    from kubeflow_tpu.platform.runtime.informer import Informer

    class NoFilterKube(FakeKube):
        supports_shard_filter = False

        def list(self, gvk, namespace=None, **kw):
            assert kw.pop("shard_filter", None) is None
            return super().list(gvk, namespace, **kw)

    kube = NoFilterKube()
    kube.add_namespace("user1")
    for i in range(6):
        kube.create(_nb(f"nb-{i}"))
    inf = Informer(kube, NOTEBOOK)
    inf.shard_subscription = lambda: ShardFilter(8, frozenset({0}),
                                                 "self").spec()
    inf.start()
    try:
        assert inf.wait_for_sync(5.0)
        # Unfiltered: a server that cannot filter must deliver everything.
        assert len(inf.keys()) == 6
    finally:
        inf.stop()


# -- knobs --------------------------------------------------------------------


def test_wire_codec_knob_validates_and_defaults(monkeypatch):
    monkeypatch.setenv("KF_WIRE_CODEC", "bogus")
    assert codec._knob_codec() == "auto"      # env-invalid -> default
    monkeypatch.setenv("KF_WIRE_CODEC", "python")
    codec.reset_engine_cache()
    try:
        assert codec._knob_codec() == "python"
        assert codec.engine_native() is False
    finally:
        monkeypatch.delenv("KF_WIRE_CODEC")
        codec.reset_engine_cache()


def test_server_filter_knob_validates_and_defaults(monkeypatch):
    from kubeflow_tpu.platform.runtime.controller import (
        _server_filter_enabled,
    )

    assert _server_filter_enabled() is True
    monkeypatch.setenv("KF_SHARD_SERVER_FILTER", "0")
    assert _server_filter_enabled() is False
    monkeypatch.setenv("KF_SHARD_SERVER_FILTER", "bogus")
    assert _server_filter_enabled() is True   # env-invalid -> default


def test_knobs_are_registered_for_debug_dump():
    from kubeflow_tpu.platform import config
    from kubeflow_tpu.platform.runtime.controller import (
        _server_filter_enabled,
    )

    codec._knob_codec()
    _server_filter_enabled()
    dump = config.effective()
    assert "KF_WIRE_CODEC" in dump
    assert "KF_SHARD_SERVER_FILTER" in dump
