"""Multi-version Notebook CRD conversion (hub/spoke through v1beta1).

Role of reference notebook-controller/api/v1/notebook_conversion.go:25-60 —
v1alpha1/v1 spokes convert through the v1beta1 hub; here the spokes carry
the TPU request as chip limits + annotations, the hub as spec.tpu.
"""
from __future__ import annotations

import copy
import json

import pytest

from kubeflow_tpu.platform.apis import notebook as nbapi


def hub_notebook(tpu=True):
    nb = {
        "apiVersion": "kubeflow.org/v1beta1",
        "kind": "Notebook",
        "metadata": {"name": "nb", "namespace": "user1"},
        "spec": {
            "template": {
                "spec": {"containers": [{"name": "nb", "image": "jupyter"}]}
            },
        },
        "status": {
            "readyReplicas": 1,
            "containerState": {"running": {}},
            "conditions": [{"type": "Ready", "status": "True"}],
        },
    }
    if tpu:
        nb["spec"]["tpu"] = {"accelerator": "v5e", "topology": "2x4"}
    return nb


def test_hub_to_v1_lowers_tpu_to_limits_and_annotations():
    v1 = nbapi.convert(hub_notebook(), "v1")
    assert v1["apiVersion"] == "kubeflow.org/v1"
    assert "tpu" not in v1["spec"]
    annotations = v1["metadata"]["annotations"]
    assert annotations[nbapi.ANNOTATION_TPU_ACCELERATOR] == "v5e"
    assert annotations[nbapi.ANNOTATION_TPU_TOPOLOGY] == "2x4"
    limits = v1["spec"]["template"]["spec"]["containers"][0]["resources"]["limits"]
    # v5e 2x4 is single-host: all 8 chips on the one pod.
    assert limits[nbapi.TPU_RESOURCE] == "8"


def test_v1_roundtrip_is_lossless():
    hub = hub_notebook()
    back = nbapi.convert(nbapi.convert(hub, "v1"), "v1beta1")
    assert back == hub


def test_v1alpha1_drops_container_state_but_keeps_tpu():
    a1 = nbapi.convert(hub_notebook(), "v1alpha1")
    assert "containerState" not in a1["status"]
    back = nbapi.convert(a1, "v1beta1")
    assert back["spec"]["tpu"] == {"accelerator": "v5e", "topology": "2x4"}


def test_multislice_roundtrip_is_lossless():
    hub = hub_notebook()
    hub["spec"]["tpu"]["slices"] = 2
    v1 = nbapi.convert(hub, "v1")
    assert v1["metadata"]["annotations"][nbapi.ANNOTATION_TPU_SLICES] == "2"
    back = nbapi.convert(v1, "v1beta1")
    assert back == hub


def test_no_tpu_roundtrip():
    hub = hub_notebook(tpu=False)
    v1 = nbapi.convert(hub, "v1")
    assert "annotations" not in v1["metadata"]
    assert nbapi.convert(v1, "v1beta1") == hub


def test_convert_identity():
    hub = hub_notebook()
    assert nbapi.convert(hub, "v1beta1") == hub


def test_convert_rejects_foreign_api_version():
    nb = hub_notebook()
    nb["apiVersion"] = "example.com/v1"
    with pytest.raises(nbapi.ConversionError):
        nbapi.convert(nb, "v1beta1")


def test_conversion_review_success():
    review = {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "ConversionReview",
        "request": {
            "uid": "u-1",
            "desiredAPIVersion": "kubeflow.org/v1",
            "objects": [hub_notebook(), hub_notebook(tpu=False)],
        },
    }
    out = nbapi.convert_review(review)
    assert out["kind"] == "ConversionReview"
    assert out["response"]["uid"] == "u-1"
    assert out["response"]["result"]["status"] == "Success"
    objs = out["response"]["convertedObjects"]
    assert len(objs) == 2
    assert all(o["apiVersion"] == "kubeflow.org/v1" for o in objs)


def test_conversion_review_failure_converts_nothing():
    bad = hub_notebook()
    bad["apiVersion"] = "example.com/v1"
    review = {"request": {
        "uid": "u-2",
        "desiredAPIVersion": "kubeflow.org/v1beta1",
        "objects": [hub_notebook(), bad],
    }}
    out = nbapi.convert_review(review)
    assert out["response"]["result"]["status"] == "Failed"
    assert out["response"]["convertedObjects"] == []


def test_convert_endpoint_over_http():
    import urllib.request

    from kubeflow_tpu.platform.testing import FakeKube
    from kubeflow_tpu.platform.webhook.server import WebhookServer

    kube = FakeKube()
    server = WebhookServer(kube, host="127.0.0.1", port=0)
    server.start()
    try:
        review = {"request": {
            "uid": "u-3",
            "desiredAPIVersion": "kubeflow.org/v1",
            "objects": [hub_notebook()],
        }}
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/convert",
            data=json.dumps(review).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=5) as resp:
            out = json.loads(resp.read())
        assert out["response"]["uid"] == "u-3"
        converted = out["response"]["convertedObjects"][0]
        assert converted["apiVersion"] == "kubeflow.org/v1"
    finally:
        server.stop()


def test_crd_manifest_serves_all_versions_with_webhook_conversion():
    crd = nbapi.crd_manifest()
    versions = {v["name"]: v for v in crd["spec"]["versions"]}
    assert set(versions) == set(nbapi.VERSIONS)
    assert versions["v1beta1"]["storage"]
    assert not versions["v1"]["storage"]
    assert crd["spec"]["conversion"]["strategy"] == "Webhook"
    assert crd["spec"]["conversion"]["webhook"]["clientConfig"]["service"][
        "path"] == "/convert"


def test_limit_only_tpu_request_preserved_without_annotation():
    # GKE-idiomatic v1 shape: bare chip limit, no accelerator annotation.
    # Conversion must not silently drop the limit (it stays in the template).
    v1 = {
        "apiVersion": "kubeflow.org/v1",
        "kind": "Notebook",
        "metadata": {"name": "nb", "namespace": "user1"},
        "spec": {"template": {"spec": {"containers": [{
            "name": "nb", "image": "jupyter",
            "resources": {"limits": {nbapi.TPU_RESOURCE: "8"}},
        }]}}},
    }
    hub = nbapi.convert(v1, "v1beta1")
    limits = hub["spec"]["template"]["spec"]["containers"][0]["resources"]["limits"]
    assert limits[nbapi.TPU_RESOURCE] == "8"
    assert "tpu" not in hub["spec"]


def test_convert_endpoint_non_dict_body_returns_failed_review():
    import urllib.request

    from kubeflow_tpu.platform.testing import FakeKube
    from kubeflow_tpu.platform.webhook.server import WebhookServer

    kube = FakeKube()
    server = WebhookServer(kube, host="127.0.0.1", port=0)
    server.start()
    try:
        for body in (b"[]", b"null", b'{"request": {"objects": [42]}}'):
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/convert",
                data=body, headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=5) as resp:
                out = json.loads(resp.read())
            assert out["kind"] == "ConversionReview"
            # non-Notebook object → Failed; empty review → Success no-op
            assert out["response"]["result"]["status"] in ("Success", "Failed")
    finally:
        server.stop()
