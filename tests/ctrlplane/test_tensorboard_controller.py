import pytest

from kubeflow_tpu.platform.controllers.tensorboard import TensorboardReconciler
from kubeflow_tpu.platform.k8s.types import DEPLOYMENT, SERVICE, VIRTUALSERVICE, deep_get
from kubeflow_tpu.platform.runtime import Request
from kubeflow_tpu.platform.testing import FakeKube


def make_tb(name="tb", logspath="pvc://logs-claim/run1"):
    return {
        "apiVersion": "tensorboard.kubeflow.org/v1alpha1",
        "kind": "Tensorboard",
        "metadata": {"name": name, "namespace": "user1"},
        "spec": {"logspath": logspath},
    }


@pytest.fixture
def kube():
    k = FakeKube()
    k.add_namespace("user1")
    return k


def test_pvc_logspath(kube):
    kube.create(make_tb())
    TensorboardReconciler(kube).reconcile(Request("user1", "tb"))
    dep = kube.get(DEPLOYMENT, "tb", "user1")
    spec = deep_get(dep, "spec", "template", "spec")
    assert spec["volumes"][0]["persistentVolumeClaim"]["claimName"] == "logs-claim"
    mount = spec["containers"][0]["volumeMounts"][0]
    assert mount["mountPath"] == "/logs" and mount["subPath"] == "run1"
    args = spec["containers"][0]["args"]
    assert "--logdir=/logs" in args
    assert "--path_prefix=/tensorboard/user1/tb" in args
    svc = kube.get(SERVICE, "tb", "user1")
    assert svc["spec"]["ports"][0]["targetPort"] == 6006
    kube.get(VIRTUALSERVICE, "tensorboard-user1-tb", "user1")


def test_gcs_logspath_mounts_creds_when_secret_exists(kube):
    kube.create({"apiVersion": "v1", "kind": "Secret",
                 "metadata": {"name": "user-gcp-sa", "namespace": "user1"}})
    kube.create(make_tb(logspath="gs://bucket/logs"))
    TensorboardReconciler(kube).reconcile(Request("user1", "tb"))
    spec = deep_get(kube.get(DEPLOYMENT, "tb", "user1"), "spec", "template", "spec")
    env = {e["name"]: e["value"] for e in spec["containers"][0]["env"]}
    assert env["GOOGLE_APPLICATION_CREDENTIALS"].endswith("user-gcp-sa.json")
    assert "--logdir=gs://bucket/logs" in spec["containers"][0]["args"]


def test_rwo_scheduling_affinity(kube):
    kube.create({
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "nb-0", "namespace": "user1"},
        "spec": {
            "nodeName": "node-7",
            "volumes": [{"name": "ws",
                         "persistentVolumeClaim": {"claimName": "logs-claim"}}],
        },
    })
    kube.create(make_tb())
    TensorboardReconciler(kube, rwo_pvc_scheduling=True).reconcile(
        Request("user1", "tb")
    )
    spec = deep_get(kube.get(DEPLOYMENT, "tb", "user1"), "spec", "template", "spec")
    terms = spec["affinity"]["nodeAffinity"][
        "requiredDuringSchedulingIgnoredDuringExecution"]["nodeSelectorTerms"]
    assert terms[0]["matchExpressions"][0]["values"] == ["node-7"]


def test_status_from_deployment(kube):
    kube.create(make_tb())
    r = TensorboardReconciler(kube)
    r.reconcile(Request("user1", "tb"))
    dep = kube.get(DEPLOYMENT, "tb", "user1")
    dep["status"] = {"readyReplicas": 1,
                     "conditions": [{"type": "Available", "status": "True"}]}
    kube.update_status(dep)
    r.reconcile(Request("user1", "tb"))
    tb = kube.get(
        __import__("kubeflow_tpu.platform.k8s.types", fromlist=["TENSORBOARD"]).TENSORBOARD,
        "tb", "user1",
    )
    assert tb["status"]["readyReplicas"] == 1
