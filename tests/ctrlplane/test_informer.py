"""Shared informer cache + field selectors (reference KFAM informer,
api_default.go:94-103)."""
import time

import pytest

from kubeflow_tpu.platform.k8s.types import EVENT, ROLEBINDING
from kubeflow_tpu.platform.runtime.informer import Informer
from kubeflow_tpu.platform.testing import FakeKube


def rb(name, ns, user="alice@x.org"):
    return {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "RoleBinding",
        "metadata": {"name": name, "namespace": ns,
                     "annotations": {"role": "edit", "user": user}},
        "roleRef": {"apiGroup": "rbac.authorization.k8s.io",
                    "kind": "ClusterRole", "name": "kubeflow-edit"},
    }


@pytest.fixture
def kube():
    k = FakeKube()
    k.add_namespace("ns1")
    k.add_namespace("ns2")
    return k


def _wait(predicate, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(0.01)
    return predicate()


def test_informer_seeds_then_tracks(kube):
    kube.create(rb("b1", "ns1"))
    inf = Informer(kube, ROLEBINDING).start()
    assert inf.wait_for_sync(5)
    assert len(inf) == 1 and inf.get("b1", "ns1") is not None

    kube.create(rb("b2", "ns2"))
    assert _wait(lambda: inf.get("b2", "ns2") is not None)
    kube.delete(ROLEBINDING, "b1", "ns1")
    assert _wait(lambda: inf.get("b1", "ns1") is None)
    assert [r["metadata"]["name"] for r in inf.list("ns2")] == ["b2"]
    inf.stop()


def test_informer_handlers_replay_and_stream(kube):
    kube.create(rb("b1", "ns1"))
    inf = Informer(kube, ROLEBINDING).start()
    inf.wait_for_sync(5)
    seen = []
    inf.add_handler(lambda et, obj: seen.append((et, obj["metadata"]["name"])))
    assert seen == [("ADDED", "b1")]  # replay of the existing store
    kube.create(rb("b2", "ns1"))
    assert _wait(lambda: ("ADDED", "b2") in seen)
    inf.stop()


def test_kfam_reads_through_cache(kube):
    from kubeflow_tpu.platform.kfam.bindings import BindingManager

    kube.create(rb("user-alice-clusterrole-edit", "ns1"))
    inf = Informer(kube, ROLEBINDING).start()
    inf.wait_for_sync(5)
    mgr = BindingManager(kube, cache=inf)
    out = mgr.list_bindings("ns1")
    assert len(out) == 1 and out[0]["referredNamespace"] == "ns1"

    # The cache serves reads even if the live client starts failing.
    class Broken:
        def list(self, *a, **k):
            raise RuntimeError("api server down")

    mgr_broken = BindingManager(Broken(), cache=inf)
    assert len(mgr_broken.list_bindings("ns1")) == 1
    inf.stop()


def test_field_selector_fake_and_rest_param(kube):
    kube.create({
        "apiVersion": "v1", "kind": "Event",
        "metadata": {"name": "e1", "namespace": "ns1"},
        "involvedObject": {"kind": "Pod", "name": "nb-0"},
        "reason": "FailedScheduling",
    })
    kube.create({
        "apiVersion": "v1", "kind": "Event",
        "metadata": {"name": "e2", "namespace": "ns1"},
        "involvedObject": {"kind": "StatefulSet", "name": "nb"},
        "reason": "Created",
    })
    got = kube.list(EVENT, "ns1",
                    field_selector={"involvedObject.kind": "Pod"})
    assert [e["metadata"]["name"] for e in got] == ["e1"]
    got = kube.list(EVENT, "ns1", field_selector={
        "involvedObject.kind": "StatefulSet", "involvedObject.name": "nb",
    })
    assert [e["metadata"]["name"] for e in got] == ["e2"]
    assert kube.list(EVENT, "ns1",
                     field_selector={"involvedObject.kind": "Job"}) == []


def test_informer_survives_watch_failure(kube):
    # A watch that raises must trigger a relist, not kill the informer.
    calls = {"n": 0}
    real_watch = kube.watch

    def flaky_watch(*args, **kwargs):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("watch broke")
        return real_watch(*args, **kwargs)

    kube.watch = flaky_watch
    kube.create(rb("b1", "ns1"))
    inf = Informer(kube, ROLEBINDING).start()
    assert inf.wait_for_sync(5)
    kube.create(rb("b2", "ns1"))
    assert _wait(lambda: inf.get("b2", "ns1") is not None)
    inf.stop()


def test_informer_dedups_watch_replay(kube):
    # A re-established watch without a resume RV replays the backlog as
    # ADDED; handlers must not see duplicates for unchanged objects.
    kube.create(rb("b1", "ns1"))
    inf = Informer(kube, ROLEBINDING).start()
    inf.wait_for_sync(5)
    seen = []
    inf.add_handler(lambda et, obj: seen.append((et, obj["metadata"]["name"])))
    assert seen == [("ADDED", "b1")]
    # Simulate a replayed ADDED at the same resourceVersion.
    obj = kube.get(ROLEBINDING, "b1", "ns1")
    inf._apply("ADDED", obj)
    assert seen == [("ADDED", "b1")]  # no duplicate
    # A genuinely new version still notifies.
    obj["metadata"]["annotations"]["role"] = "view"
    kube.update(obj)
    assert _wait(lambda: any(e == "MODIFIED" for e, _ in seen))
    inf.stop()


def test_informer_resumes_from_collection_rv(kube):
    # Between resyncs the watch must resume from the list's COLLECTION
    # resourceVersion (object RVs miss deletions: an object created and
    # deleted between the max object RV and the snapshot would otherwise be
    # replayed into the store as a spurious ADDED).
    kube.create(rb("b1", "ns1"))
    created = kube.create(rb("doomed", "ns1"))
    kube.delete(ROLEBINDING, "doomed", "ns1")
    inf = Informer(kube, ROLEBINDING)
    rv = inf._relist()
    assert rv is not None
    # The collection RV is at least as new as the deleted object's RV.
    assert int(rv) >= int(created["metadata"]["resourceVersion"])


def test_informer_error_event_forces_relist(kube):
    # A watch ERROR (410 Gone on a compacted RV) must trigger a relist, not
    # a tight reconnect loop with the same stale RV.
    import queue as _q

    kube.create(rb("b1", "ns1"))
    relists = []
    orig = kube.list_with_rv

    def counting_list_with_rv(*a, **k):
        relists.append(1)
        return orig(*a, **k)

    kube.list_with_rv = counting_list_with_rv
    events = _q.Queue()
    events.put(("ERROR", {"kind": "Status", "code": 410}))

    real_watch = kube.watch

    def watch_with_error(*args, stop=None, **kwargs):
        try:
            yield events.get_nowait()
        except _q.Empty:
            yield from real_watch(*args, stop=stop, **kwargs)

    kube.watch = watch_with_error
    inf = Informer(kube, ROLEBINDING).start()
    assert inf.wait_for_sync(5)
    # ERROR consumed -> second relist happens and the informer still tracks.
    assert _wait(lambda: len(relists) >= 2)
    kube.create(rb("b2", "ns1"))
    assert _wait(lambda: inf.get("b2", "ns1") is not None)
    inf.stop()


# -- indexers (round 5: cache-backed reconcile reads, client-go ByIndex) ----


def _user_index(obj):
    u = obj["metadata"].get("annotations", {}).get("user")
    ns = obj["metadata"].get("namespace", "")
    return [f"{ns}/{u}"] if u else []


def test_index_list_tracks_adds_moves_and_deletes(kube):
    inf = Informer(kube, ROLEBINDING,
                   indexers={"user": _user_index}).start()
    assert inf.wait_for_sync()
    kube.create(rb("b1", "ns1", user="alice@x.org"))
    kube.create(rb("b2", "ns1", user="alice@x.org"))
    kube.create(rb("b3", "ns1", user="bob@x.org"))
    assert _wait(lambda: len(inf.index_list("user", "ns1/alice@x.org")) == 2)
    assert len(inf.index_list("user", "ns1/bob@x.org")) == 1
    assert inf.index_list("user", "ns1/nobody@x.org") == []

    # An update that MOVES the object between index values refiles it.
    b1 = kube.get(ROLEBINDING, "b1", "ns1")
    b1["metadata"]["annotations"]["user"] = "bob@x.org"
    kube.update(b1)
    assert _wait(lambda: len(inf.index_list("user", "ns1/bob@x.org")) == 2)
    assert len(inf.index_list("user", "ns1/alice@x.org")) == 1

    kube.delete(ROLEBINDING, "b2", "ns1")
    assert _wait(lambda: inf.index_list("user", "ns1/alice@x.org") == [])
    inf.stop()


def test_index_survives_relist(kube):
    kube.create(rb("b1", "ns1"))
    inf = Informer(kube, ROLEBINDING,
                   indexers={"user": _user_index}).start()
    assert inf.wait_for_sync()
    assert len(inf.index_list("user", "ns1/alice@x.org")) == 1
    # Force a full relist (the resync/410 path rebuilds indexes from
    # scratch); the index must reflect post-relist reality.
    kube.create(rb("b2", "ns1"))
    inf._relist()
    assert len(inf.index_list("user", "ns1/alice@x.org")) == 2
    inf.stop()


def test_index_list_results_are_read_only(kube):
    # Zero-copy contract: index_list hands out frozen views; a caller
    # that tries to mutate one gets TypeError and the cache stays intact
    # (the deeper matrix lives in test_frozen_views.py).
    import pytest as _pytest

    from kubeflow_tpu.platform.k8s.types import thaw

    kube.create(rb("b1", "ns1"))
    inf = Informer(kube, ROLEBINDING,
                   indexers={"user": _user_index}).start()
    assert inf.wait_for_sync()
    got = inf.index_list("user", "ns1/alice@x.org")[0]
    with _pytest.raises(TypeError, match="read-only"):
        got["metadata"]["annotations"]["user"] = "evil@x.org"
    assert inf.index_list("user", "ns1/alice@x.org"), \
        "cache corrupted by caller mutation"
    # thaw() is the sanctioned write path: private copy, cache untouched.
    mine = thaw(got)
    mine["metadata"]["annotations"]["user"] = "evil@x.org"
    assert inf.index_list("user", "ns1/alice@x.org")[0][
        "metadata"]["annotations"]["user"] == "alice@x.org"
    inf.stop()
