"""RestKubeClient against the HTTP FakeKube shim (VERDICT r1 item 5).

Every wire behavior the in-memory suites could not exercise: JSON Status
errors mapping to typed exceptions, resourceVersion 409s, patch dispatch by
Content-Type, selector serialization, chunked watch streams with RV resume,
SARs, the pod-log subresource, and the token bucket in front of it all."""
from __future__ import annotations

import threading

import pytest

from kubeflow_tpu.platform.k8s import errors
from kubeflow_tpu.platform.k8s.client import RestKubeClient
from kubeflow_tpu.platform.k8s.types import (
    EVENT,
    NAMESPACE,
    NOTEBOOK,
    POD,
    PROFILE,
)
from kubeflow_tpu.platform.testing import FakeKube
from kubeflow_tpu.platform.testing.httpkube import HttpKubeServer


@pytest.fixture(scope="module")
def stack():
    kube = FakeKube()
    kube.add_namespace("user1")
    server = HttpKubeServer(kube).start()
    client = RestKubeClient(server.base_url, qps=0)
    yield kube, client
    server.stop()


def nb(name, ns="user1"):
    return {
        "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
        "metadata": {"name": name, "namespace": ns},
        "spec": {"template": {"spec": {"containers": [
            {"name": name, "image": "img"}]}}},
    }


def test_crud_round_trip(stack):
    kube, client = stack
    created = client.create(nb("rt"))
    assert created["metadata"]["resourceVersion"]
    got = client.get(NOTEBOOK, "rt", "user1")
    assert got["spec"]["template"]["spec"]["containers"][0]["image"] == "img"
    got["spec"]["template"]["spec"]["containers"][0]["image"] = "img:2"
    updated = client.update(got)
    assert updated["metadata"]["generation"] == 2
    names = [n["metadata"]["name"] for n in client.list(NOTEBOOK, "user1")]
    assert "rt" in names
    client.delete(NOTEBOOK, "rt", "user1")
    with pytest.raises(errors.NotFound):
        client.get(NOTEBOOK, "rt", "user1")


def test_resource_version_conflict_is_409(stack):
    kube, client = stack
    client.create(nb("conf"))
    a = client.get(NOTEBOOK, "conf", "user1")
    b = client.get(NOTEBOOK, "conf", "user1")
    client.update(a)  # bumps RV
    with pytest.raises(errors.Conflict):
        client.update(b)  # stale RV over the wire -> JSON Status 409
    client.delete(NOTEBOOK, "conf", "user1")


def test_create_conflict_and_dry_run(stack):
    kube, client = stack
    client.create(nb("dup"))
    with pytest.raises(errors.Conflict):
        client.create(nb("dup"))
    # dry-run: accepted but never stored.
    client.create(nb("ghost"), dry_run=True)
    with pytest.raises(errors.NotFound):
        client.get(NOTEBOOK, "ghost", "user1")
    client.delete(NOTEBOOK, "dup", "user1")


def test_patch_types_over_content_type(stack):
    kube, client = stack
    client.create(nb("patchy"))
    out = client.patch(
        NOTEBOOK, "patchy", {"metadata": {"annotations": {"a": "1"}}}, "user1"
    )
    assert out["metadata"]["annotations"]["a"] == "1"
    out = client.patch(
        NOTEBOOK, "patchy",
        [{"op": "add", "path": "/metadata/annotations/b", "value": "2"}],
        "user1", patch_type="json",
    )
    assert out["metadata"]["annotations"]["b"] == "2"
    client.delete(NOTEBOOK, "patchy", "user1")


def test_update_status_subresource(stack):
    kube, client = stack
    client.create(nb("status-nb"))
    got = client.get(NOTEBOOK, "status-nb", "user1")
    got["status"] = {"readyReplicas": 1}
    client.update_status(got)
    assert client.get(NOTEBOOK, "status-nb", "user1")["status"] == {
        "readyReplicas": 1}
    client.delete(NOTEBOOK, "status-nb", "user1")


def test_selectors_serialize_over_the_wire(stack):
    kube, client = stack
    kube.create({
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "sel-1", "namespace": "user1",
                     "labels": {"notebook-name": "sel-nb"}},
        "spec": {"containers": [{"name": "c", "image": "i"}]},
    })
    kube.create({
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "sel-2", "namespace": "user1",
                     "labels": {"notebook-name": "other"}},
        "spec": {"containers": [{"name": "c", "image": "i"}]},
    })
    pods = client.list(POD, "user1",
                       label_selector={"notebook-name": "sel-nb"})
    assert [p["metadata"]["name"] for p in pods] == ["sel-1"]
    kube.create({
        "apiVersion": "v1", "kind": "Event",
        "metadata": {"name": "sel-ev", "namespace": "user1"},
        "involvedObject": {"kind": "Pod", "name": "sel-1"},
        "reason": "x", "message": "y", "type": "Normal",
    })
    evs = client.list(EVENT, "user1",
                      field_selector={"involvedObject.name": "sel-1"})
    assert [e["metadata"]["name"] for e in evs] == ["sel-ev"]


def test_cluster_scoped_paths(stack):
    kube, client = stack
    client.create({
        "apiVersion": "kubeflow.org/v1", "kind": "Profile",
        "metadata": {"name": "wire-prof"},
        "spec": {"owner": {"kind": "User", "name": "a@x.io"}},
    })
    assert client.get(PROFILE, "wire-prof")["spec"]["owner"]["name"] == "a@x.io"
    namespaces = [n["metadata"]["name"] for n in client.list(NAMESPACE)]
    assert "user1" in namespaces
    client.delete(PROFILE, "wire-prof")


def test_watch_streams_chunked_lines(stack):
    kube, client = stack
    stop = threading.Event()
    seen = []

    def consume():
        for etype, obj in client.watch(NOTEBOOK, "user1", stop=stop):
            seen.append((etype, obj["metadata"]["name"]))
            if len(seen) >= 2:
                return

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    import time

    time.sleep(0.3)  # let the watch register
    kube.create(nb("w-1"))
    kube.create(nb("w-2"))
    t.join(timeout=10)
    stop.set()
    assert not t.is_alive()
    assert ("ADDED", "w-1") in seen and ("ADDED", "w-2") in seen
    kube.delete(NOTEBOOK, "w-1", "user1")
    kube.delete(NOTEBOOK, "w-2", "user1")


def test_watch_resume_from_rv_skips_backlog(stack):
    kube, client = stack
    client.create(nb("rv-1"))
    _, rv = client.list_with_rv(NOTEBOOK, "user1")
    stop = threading.Event()
    seen = []

    def consume():
        for etype, obj in client.watch(
            NOTEBOOK, "user1", resource_version=rv, stop=stop
        ):
            seen.append((etype, obj["metadata"]["name"]))
            return

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    import time

    time.sleep(0.3)
    kube.create(nb("rv-2"))
    t.join(timeout=10)
    stop.set()
    # The resumed watch never replays rv-1's ADDED backlog.
    assert seen == [("ADDED", "rv-2")]
    kube.delete(NOTEBOOK, "rv-1", "user1")
    kube.delete(NOTEBOOK, "rv-2", "user1")


def test_subject_access_review_round_trip(stack):
    kube, client = stack
    kube.authz_policy = lambda user, verb, gvk, **kw: user == "allowed@x.io"
    try:
        assert client.can_i("allowed@x.io", "list", NOTEBOOK, "user1")
        assert not client.can_i("denied@x.io", "list", NOTEBOOK, "user1")
    finally:
        kube.authz_policy = None


def test_pod_log_subresource(stack):
    kube, client = stack
    kube.create({
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "loggy", "namespace": "user1"},
        "spec": {"containers": [{"name": "main", "image": "i"}]},
    })
    kube.set_pod_logs("user1", "loggy", "hello wire", container="main")
    assert client.pod_logs("loggy", "user1", container="main") == "hello wire"


def test_unknown_resource_is_404_status(stack):
    kube, client = stack
    from kubeflow_tpu.platform.k8s.types import GVK

    bogus = GVK("nope.example.com", "v1", "Widget", "widgets")
    with pytest.raises(errors.NotFound):
        client.list(bogus, "user1")


def test_already_exists_reason_survives_the_wire(stack):
    """409 + reason AlreadyExists must raise AlreadyExists (not bare
    Conflict) so typed handlers behave identically in both transports."""
    kube, client = stack
    client.create(nb("typed-dup"))
    with pytest.raises(errors.AlreadyExists):
        client.create(nb("typed-dup"))
    client.delete(NOTEBOOK, "typed-dup", "user1")


def test_sar_resolves_real_gvk(stack):
    """The SAR endpoint reconstructs the registered GVK (kind/version), not
    a plural-as-kind fabrication, so policies keyed on gvk.kind agree."""
    kube, client = stack
    kube.authz_policy = lambda user, verb, gvk, **kw: gvk.kind == "Notebook"
    try:
        assert client.can_i("u@x.io", "list", NOTEBOOK, "user1")
        assert not client.can_i("u@x.io", "list", POD, "user1")
    finally:
        kube.authz_policy = None
