import time

from kubeflow_tpu.platform.runtime.controller import Request, _WorkQueue


def test_dedup_of_pending_items():
    q = _WorkQueue()
    r = Request("ns", "a")
    q.add(r)
    q.add(r)
    assert q.get(timeout=0.5) == r
    assert q.get(timeout=0.05) is None


def test_immediate_add_preempts_backoff():
    q = _WorkQueue(base_delay=5.0)  # backoff would be ~5s
    r = Request("ns", "a")
    q.add_rate_limited(r)
    # A watch event arrives: must be served now, not after the backoff.
    q.add(r)
    t0 = time.monotonic()
    assert q.get(timeout=1.0) == r
    assert time.monotonic() - t0 < 1.0
    # The superseded delayed entry must not deliver a duplicate.
    assert q.get(timeout=0.1) is None


def test_backoff_grows_and_forget_resets():
    q = _WorkQueue(base_delay=0.05, max_delay=0.2)
    r = Request("ns", "a")
    q.add_rate_limited(r)  # 0.05
    assert q.get(timeout=1.0) == r
    q.done(r)
    q.add_rate_limited(r)  # 0.1
    t0 = time.monotonic()
    assert q.get(timeout=1.0) == r
    assert time.monotonic() - t0 >= 0.08
    q.done(r)
    q.forget(r)
    q.add_rate_limited(r)  # back to 0.05
    t0 = time.monotonic()
    assert q.get(timeout=1.0) == r
    assert time.monotonic() - t0 < 0.09
    q.done(r)


def test_processing_key_parks_readds_until_done():
    """Per-key mutual exclusion: while a key is out (get..done), re-adds
    park and must NOT be delivered to another worker."""
    q = _WorkQueue()
    r = Request("ns", "a")
    q.add(r)
    assert q.get(timeout=0.5) == r
    q.add(r)  # watch event during reconcile
    assert q.get(timeout=0.1) is None  # parked: no concurrent delivery
    q.done(r)
    assert q.get(timeout=0.5) == r  # fires after done
    q.done(r)
    assert q.get(timeout=0.05) is None


def test_dirty_readd_keeps_earliest_delay():
    """A backoff re-add parked during processing keeps its delay; a watch
    event re-add during processing fires immediately after done."""
    q = _WorkQueue()
    r = Request("ns", "a")
    q.add(r)
    assert q.get(timeout=0.5) == r
    q.add(r, delay=0.3)   # backoff requeue from the worker
    q.done(r)
    assert q.get(timeout=0.05) is None  # still honoring the delay
    assert q.get(timeout=1.0) == r
    q.done(r)
