"""Causal-propagation unit matrix (ISSUE 14 tentpole): context
mint/stamp/extract/link round trips, first-admission minting at every
client create path, child inheritance through apply.*, wire carry over
HttpKube, the controller's watch→queue→reconcile span chain, and the
critical-path analyzer's decomposition contract."""
from __future__ import annotations

import threading
import time

import pytest

from kubeflow_tpu.platform.k8s.types import NOTEBOOK, SERVICE, STATEFULSET
from kubeflow_tpu.platform.runtime import apply
from kubeflow_tpu.platform.testing.fake import FakeKube
from kubeflow_tpu.telemetry import causal, critical_path


@pytest.fixture(autouse=True)
def _clean_store():
    causal.STORE.clear()
    causal.set_current(None)
    yield
    causal.STORE.clear()
    causal.set_current(None)


# -- context / ids ------------------------------------------------------------


def test_traceparent_round_trip_and_rejects():
    ctx = causal.mint()
    assert len(ctx.trace_id) == 32 and len(ctx.span_id) == 16
    parsed = causal.parse_traceparent(ctx.to_traceparent())
    assert parsed == causal.TraceContext(ctx.trace_id, ctx.span_id)
    for junk in (None, "", "junk", "00-short-span-01",
                 "00-" + "g" * 32 + "-" + "0" * 16 + "-01"):
        assert causal.parse_traceparent(junk) is None


def test_minting_never_touches_urandom(monkeypatch):
    """The counter-in-random-block property: after import, ids cost no
    syscall — the PR-2 no-urandom-per-reconcile contract."""
    import os as _os

    def boom(_n):
        raise AssertionError("urandom called per mint")

    monkeypatch.setattr(_os, "urandom", boom)
    ids = {causal.new_trace_id() for _ in range(64)}
    ids |= {causal.new_span_id() for _ in range(64)}
    assert len(ids) == 128


def test_mint_entropy_lives_above_the_counter():
    """The cross-PROCESS collision property an in-process fleet cannot
    exercise: each process's ids are its own 128-bit random block plus a
    counter, so two processes' id sets are disjoint unless their blocks
    land within counter range of each other (~N/2^128).  Simulate the
    second process by swapping the block: no overlap, and the ids differ
    in the HIGH bits (the entropy region), not just the counter tail —
    a shared-prefix scheme (the PR-1 bug) fails here."""
    ids_a = [causal.new_trace_id() for _ in range(64)]
    saved = causal._trace_base
    try:
        causal._trace_base = saved ^ (1 << 100)  # another process's block
        ids_b = [causal.new_trace_id() for _ in range(64)]
    finally:
        causal._trace_base = saved
    assert not set(ids_a) & set(ids_b)
    assert ids_a[0][:8] != ids_b[0][:8]  # high bits differ, not the tail


def test_trace_ids_unique_across_threads():
    out, lock = set(), threading.Lock()

    def mint_many():
        local = [causal.new_trace_id() for _ in range(200)]
        with lock:
            out.update(local)

    threads = [threading.Thread(target=mint_many) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(out) == 8 * 200


def test_use_and_lazy_context():
    assert causal.current() is None
    ctx = causal.mint()
    with causal.use(ctx):
        assert causal.current() is ctx
        with causal.use(None):  # no-op wrapper keeps the outer context
            assert causal.current() is ctx
    assert causal.current() is None
    # Lazy: the factory resolves on first current(), once.
    calls = []

    def factory():
        calls.append(1)
        return causal.mint()

    causal.set_lazy(factory)
    assert causal.current_resolved() is None and not calls
    first = causal.current()
    assert first is not None and causal.current() is first
    assert calls == [1]
    causal.set_current(None)


# -- first-admission minting --------------------------------------------------


def test_fake_kube_mints_platform_crs_only():
    kube = FakeKube()
    kube.add_namespace("ns")
    nb = kube.create({"apiVersion": "kubeflow.org/v1beta1",
                      "kind": "Notebook",
                      "metadata": {"name": "nb", "namespace": "ns"},
                      "spec": {}})
    ctx = causal.from_object(nb)
    assert ctx is not None and ctx.stamped_ts is not None
    # Core kinds are NOT minted server-side (their stamps come from
    # apply.* with a real parent).
    pod = kube.create({"apiVersion": "v1", "kind": "Pod",
                       "metadata": {"name": "p", "namespace": "ns"},
                       "spec": {"containers": [{"name": "c"}]}})
    assert causal.from_object(pod) is None


def test_create_inherits_current_context():
    kube = FakeKube()
    kube.add_namespace("ns")
    parent = causal.mint()
    with causal.use(parent):
        nb = kube.create({"apiVersion": "kubeflow.org/v1beta1",
                          "kind": "Notebook",
                          "metadata": {"name": "nb", "namespace": "ns"},
                          "spec": {}})
    ctx = causal.from_object(nb)
    assert ctx.trace_id == parent.trace_id
    assert ctx.span_id != parent.span_id


def test_existing_stamp_is_preserved():
    kube = FakeKube()
    kube.add_namespace("ns")
    pre = causal.mint()
    obj = {"apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
           "metadata": {"name": "nb", "namespace": "ns"}, "spec": {}}
    causal.stamp(obj, pre)
    stored = kube.create(obj)
    assert causal.from_object(stored).trace_id == pre.trace_id


# -- child stamping through apply.* ------------------------------------------


def _sts(name="child", ns="ns"):
    return {"apiVersion": "apps/v1", "kind": "StatefulSet",
            "metadata": {"name": name, "namespace": ns},
            "spec": {"replicas": 1}}


def test_apply_create_stamps_child_and_records_write():
    kube = FakeKube()
    kube.add_namespace("ns")
    parent = causal.mint()
    with causal.use(parent):
        apply.create(kube, _sts())
    child = causal.from_object(kube.get(STATEFULSET, "child", "ns"))
    assert child.trace_id == parent.trace_id
    assert child.span_id != parent.span_id
    writes = [s for s in causal.journey(parent.trace_id)
              if s["segment"] == "write_rtt"]
    assert len(writes) == 1 and writes[0]["kind"] == "StatefulSet"
    assert writes[0]["parent_span_id"] == parent.span_id


def test_create_or_update_restamps_on_content_change():
    kube = FakeKube()
    kube.add_namespace("ns")
    gen0 = causal.mint()
    desired = _sts()
    with causal.use(gen0):
        apply.create_or_update(kube, STATEFULSET, desired)
    first = causal.from_object(kube.get(STATEFULSET, "child", "ns"))
    assert first.trace_id == gen0.trace_id
    # Steady state: hash unchanged -> no write, stamp untouched.
    with causal.use(causal.mint()):
        apply.create_or_update(kube, STATEFULSET, _sts())
    assert causal.from_object(
        kube.get(STATEFULSET, "child", "ns")).span_id == first.span_id
    # A content change restamps from the causing reconcile's context.
    gen1 = causal.mint()
    changed = _sts()
    changed["spec"]["replicas"] = 3
    with causal.use(gen1):
        apply.create_or_update(kube, STATEFULSET, changed)
    second = causal.from_object(kube.get(STATEFULSET, "child", "ns"))
    assert second.trace_id == gen1.trace_id
    assert second.span_id != first.span_id


def test_patch_status_diff_records_write_rtt():
    kube = FakeKube()
    kube.add_namespace("ns")
    nb = kube.create({"apiVersion": "kubeflow.org/v1beta1",
                      "kind": "Notebook",
                      "metadata": {"name": "nb", "namespace": "ns"},
                      "spec": {}})
    ctx = causal.from_object(nb)
    with causal.use(causal.child(ctx)):
        apply.patch_status_diff(kube, NOTEBOOK, nb, {"phase": "Ready"})
    spans = causal.journey(ctx.trace_id)
    assert any(s["name"] == "k8s.patch_status" for s in spans)


def test_stamp_child_tolerates_frozen_views():
    from kubeflow_tpu.platform.k8s.types import freeze

    frozen = freeze(_sts())
    with causal.use(causal.mint()):
        assert causal.stamp_child(frozen) is None  # no raise, no stamp


# -- wire carry (RestKubeClient <-> HttpKube) ---------------------------------


def test_traceparent_rides_the_wire():
    from kubeflow_tpu.platform.testing.httpkube import make_transport

    kube = FakeKube()
    kube.add_namespace("ns")
    client, server = make_transport(kube, "http")
    try:
        parent = causal.mint()
        with causal.use(parent):
            # The client stamps platform CRs BEFORE serializing; strip
            # that to prove the HEADER path alone carries the context to
            # the server-side mint.
            obj = {"apiVersion": "kubeflow.org/v1beta1",
                   "kind": "Notebook",
                   "metadata": {"name": "nb", "namespace": "ns"},
                   "spec": {}}
            created = client.create(obj)
        ctx = causal.from_object(created)
        assert ctx is not None and ctx.trace_id == parent.trace_id
    finally:
        server.stop()


def test_header_only_carry_server_side_mint():
    """A context-free body + traceparent header: the server-side mint
    inherits the header's trace (the CRUD-backend / webhook shape)."""
    import json
    import urllib.request

    from kubeflow_tpu.platform.testing.httpkube import HttpKubeServer

    kube = FakeKube()
    kube.add_namespace("ns")
    server = HttpKubeServer(kube).start()
    try:
        parent = causal.mint()
        body = json.dumps({
            "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
            "metadata": {"name": "nb", "namespace": "ns"}, "spec": {},
        }).encode()
        req = urllib.request.Request(
            server.base_url + "/apis/kubeflow.org/v1beta1/namespaces/ns/"
            "notebooks",
            data=body, method="POST",
            headers={"Content-Type": "application/json",
                     "traceparent": parent.to_traceparent()})
        urllib.request.urlopen(req, timeout=10)
        stored = kube.get(NOTEBOOK, "nb", "ns")
        assert causal.from_object(stored).trace_id == parent.trace_id
    finally:
        server.stop()


# -- controller end to end ----------------------------------------------------


def test_controller_journey_watch_queue_reconcile_write():
    """The full chain over a real Controller: API write → watch_lag →
    queue_wait → reconcile → child write_rtt, one trace_id, and the
    reconcile trace in /debug/traces carries the causal link."""
    from kubeflow_tpu.platform.runtime import trace as rtrace
    from kubeflow_tpu.platform.runtime.controller import (
        Controller,
        Reconciler,
        Request,
    )
    from kubeflow_tpu.platform.runtime.informer import Informer

    kube = FakeKube()
    kube.add_namespace("ns")

    class ChildWriter(Reconciler):
        def __init__(self, client):
            self.client = client

        def reconcile(self, req):
            from kubeflow_tpu.platform.k8s import errors

            try:
                self.client.get(SERVICE, f"{req.name}-svc", req.namespace)
            except errors.NotFound:
                apply.create(self.client, {
                    "apiVersion": "v1", "kind": "Service",
                    "metadata": {"name": f"{req.name}-svc",
                                 "namespace": req.namespace},
                    "spec": {"selector": {"app": req.name}},
                })
            return None

    ctrl = Controller(
        "causal-probe", ChildWriter(kube), primary=NOTEBOOK,
        informers={NOTEBOOK: Informer(kube, NOTEBOOK)}, workers=2)
    ctrl.start(kube)
    try:
        nb = kube.create({"apiVersion": "kubeflow.org/v1beta1",
                          "kind": "Notebook",
                          "metadata": {"name": "nb", "namespace": "ns"},
                          "spec": {}})
        ctx = causal.from_object(nb)
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            segs = {s["segment"] for s in causal.journey(ctx.trace_id)
                    if s.get("segment")}
            if {"watch_lag", "queue_wait", "reconcile",
                    "write_rtt"} <= segs:
                break
            time.sleep(0.02)
        spans = causal.journey(ctx.trace_id)
        segs = {s.get("segment") for s in spans}
        assert {"watch_lag", "queue_wait", "reconcile",
                "write_rtt"} <= segs, spans
        assert {s["trace_id"] for s in spans} == {ctx.trace_id}
        # The child inherited the trace.
        svc = kube.get(SERVICE, "nb-svc", "ns")
        assert causal.from_object(svc).trace_id == ctx.trace_id
        # The reconcile trace links the journey.
        linked = [t for t in rtrace.recent()
                  if t.get("causal_trace_id") == ctx.trace_id]
        assert linked and linked[0]["controller"] == "causal-probe"
        # Decomposition ties out against the journey's own window.
        d = critical_path.decompose(spans)
        assert d["total_s"] > 0
        assert abs(sum(d["segments"].values()) - d["total_s"]) < 1e-6
    finally:
        ctrl.stop()


def test_noop_resync_reconcile_records_nothing():
    """The lazy-context contract: a reconcile that neither came from an
    event nor wrote anything leaves zero spans (the resync allocation
    band leans on this)."""
    from kubeflow_tpu.platform.runtime.controller import (
        Controller,
        Reconciler,
        Request,
    )
    from kubeflow_tpu.platform.runtime.informer import Informer

    kube = FakeKube()
    kube.add_namespace("ns")
    nb = kube.create({"apiVersion": "kubeflow.org/v1beta1",
                      "kind": "Notebook",
                      "metadata": {"name": "nb", "namespace": "ns"},
                      "spec": {}})
    ctx = causal.from_object(nb)

    class Noop(Reconciler):
        def reconcile(self, req):
            return None

    informer = Informer(kube, NOTEBOOK).start()
    assert informer.wait_for_sync(10.0)
    ctrl = Controller("noop-probe", Noop(), primary=NOTEBOOK,
                      informers={NOTEBOOK: informer}, workers=1)
    ctrl._client = kube
    causal.STORE.clear()
    ctrl._reconcile_one(Request("ns", "nb"))  # resync-style: no event
    assert causal.journey(ctx.trace_id) == []


def test_flight_only_writes_still_mark_the_reconcile():
    """A reconcile whose only writes ran inside FlightPool slots must
    still read as ACTING on the submitting thread (the mark lands on
    pool threads; the carry propagates it back), or lazy-context repair
    reconciles would drop their reconcile span and orphan the write
    spans."""
    from kubeflow_tpu.platform.runtime.flight import FlightPool

    pool = FlightPool(4)
    causal.consume_mark()
    ctx = causal.mint()

    def write_in_slot():
        cur = causal.current()
        causal.record("k8s.create", trace_id=cur.trace_id,
                      parent_span_id=cur.span_id, segment="write_rtt",
                      start_ts=time.time(), end_ts=time.time(),
                      kind="Service", object="x")

    with causal.use(ctx):
        pool.run([write_in_slot, write_in_slot])
    assert causal.consume_mark() is True


def test_serve_telemetry_links_causal_context():
    """Header passthrough seam (models/serve.py installs the parsed
    traceparent as current; the trace layer links it): a serve request
    trace carries causal_trace_id so /debug/traces?trace_id=<journey>
    finds it."""
    from prometheus_client import CollectorRegistry

    from kubeflow_tpu.telemetry.serve import ServeTelemetry

    tel = ServeTelemetry(CollectorRegistry(), component="probe-model")
    ctx = causal.mint()
    with causal.use(ctx):
        assert tel.begin_request() is not None
        d = tel.finish_request("ok")
    assert d["causal_trace_id"] == ctx.trace_id
    # Without a context the link keys are absent (no empty-string noise).
    tel.begin_request()
    d2 = tel.finish_request("ok")
    assert "causal_trace_id" not in d2


# -- critical path ------------------------------------------------------------


def _span(name, seg, t0, t1, trace="t" * 32, **attrs):
    return {"name": name, "trace_id": trace,
            "span_id": causal.new_span_id(), "segment": seg,
            "start_ts": t0, "end_ts": t1,
            "duration_ms": (t1 - t0) * 1e3, **attrs}


def test_critical_path_walks_latest_predecessors():
    spans = [
        _span("watch_lag", "watch_lag", 0.0, 1.0),
        _span("queue_wait", "queue_wait", 1.0, 2.0),
        _span("reconcile", "reconcile", 2.0, 5.0),
        _span("stale", "watch_lag", 0.0, 0.5),  # early dead-end branch
    ]
    path = critical_path.critical_path(spans)
    assert [s["name"] for s in path] == [
        "watch_lag", "queue_wait", "reconcile"]


def test_critical_path_terminates_on_mutual_eps_predecessors():
    """Two spans within EPS of each other read as MUTUAL predecessors
    under the tolerant ordering; the visited guard must terminate the
    walk instead of alternating between them forever (adjacent
    sub-100µs FakeKube writes hit this in practice)."""
    spans = [
        _span("a", "write_rtt", 10.00000, 10.00001, kind="Service"),
        _span("b", "write_rtt", 10.00002, 10.00003, kind="Service"),
    ]
    path = critical_path.critical_path(spans)
    assert 1 <= len(path) <= 2
    d = critical_path.decompose(spans)
    assert d["total_s"] >= 0


def test_decompose_carves_nested_segments_and_sums_to_total():
    spans = [
        _span("queue_wait", "queue_wait", 0.0, 1.0),
        _span("reconcile", "reconcile", 1.0, 5.0),
        _span("admission_queue", "admission_queue", 2.0, 2.0),  # zero-len
        _span("k8s.create", "write_rtt", 3.0, 4.0, kind="StatefulSet"),
    ]
    d = critical_path.decompose(spans)
    segs = d["segments"]
    assert segs["write_rtt"] == pytest.approx(1.0)
    assert segs["reconcile"] == pytest.approx(3.0)
    assert "admission_queue" in segs  # present even at zero length
    assert sum(segs.values()) == pytest.approx(d["total_s"])
    admissions = [e for e in d["path"]
                  if e.get("segment") == "admission_queue"]
    assert len(admissions) == 1


def test_gap_after_pod_owner_write_is_pod_start():
    spans = [
        _span("reconcile", "reconcile", 0.0, 1.0),
        _span("k8s.create", "write_rtt", 0.2, 0.9, kind="StatefulSet"),
        _span("watch_lag", "watch_lag", 3.0, 3.2),
        _span("reconcile", "reconcile", 3.2, 3.5),
    ]
    d = critical_path.decompose(spans)
    assert d["segments"].get("pod_start") == pytest.approx(2.0)
    assert sum(d["segments"].values()) == pytest.approx(d["total_s"])


def test_gap_covered_by_admission_wait_is_admission_queue():
    spans = [
        _span("reconcile", "reconcile", 0.0, 0.5),   # parks Queued
        _span("reconcile", "reconcile", 4.0, 4.5),   # the admit poll
        _span("admission_queue", "admission_queue", 0.4, 4.2),
    ]
    d = critical_path.decompose(spans)
    assert d["segments"].get("admission_queue", 0) > 3.0
    assert sum(d["segments"].values()) == pytest.approx(d["total_s"])


def test_queued_admission_counts_once_on_the_path():
    """A GENUINELY queued job produces both an attributed gap and the
    admission span's tail carved into the granting reconcile — the same
    wait, merged into ONE path entry so the 'exactly one admission_queue
    segment' conformance contract holds under real queue contention, not
    just the immediate-admit case."""
    spans = [
        _span("reconcile", "reconcile", 10.0, 10.1),          # parks
        _span("admission_queue", "admission_queue", 10.05, 15.1),
        _span("reconcile", "reconcile", 15.0, 15.3),          # grants
    ]
    d = critical_path.decompose(spans)
    admissions = [e for e in d["path"]
                  if e.get("segment") == "admission_queue"]
    assert len(admissions) == 1, d["path"]
    assert d["segments"]["admission_queue"] == pytest.approx(5.0, abs=0.2)
    assert sum(d["segments"].values()) == pytest.approx(d["total_s"])


def test_merge_journeys_dedupes_by_span_id():
    a = _span("reconcile", "reconcile", 0.0, 1.0)
    b = _span("reconcile", "reconcile", 2.0, 3.0)
    merged = causal.merge_journeys([a, b], [dict(a)], [b, a])
    assert len(merged) == 2
    assert merged[0]["start_ts"] <= merged[1]["start_ts"]


def test_empty_journey_decomposes_empty():
    d = critical_path.decompose([])
    assert d == {"total_s": 0.0, "segments": {}, "path": []}
