"""Zero-copy informer reads: the enforced read-ownership contract.

Informer reads (get/list/index_list, handler deliveries) hand out frozen
views over the shared cache — client-go's "informer objects must not be
mutated" rule, enforced instead of conventional.  This suite is the
mutation-safety matrix: no mutation attempt, at any nesting depth, on
either the frozen or the thawed path, may corrupt the shared store or its
indexes; the resync loop must enqueue key-only with ZERO copy_resource
calls; and frozen views must serialize/compare/write back like plain
dicts.  Plus the gvk_for pluralization rules that ride along this PR.
"""
from __future__ import annotations

import copy
import json

import pytest

from kubeflow_tpu.platform.k8s import types as k8s_types
from kubeflow_tpu.platform.k8s.types import (
    NOTEBOOK,
    POD,
    FrozenList,
    FrozenResource,
    freeze,
    gvk_for,
    json_default,
    pluralize,
    thaw,
)
from kubeflow_tpu.platform.runtime.informer import Informer
from kubeflow_tpu.platform.testing import FakeKube


def nb(name, ns="ns1", image="img"):
    return {
        "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
        "metadata": {"name": name, "namespace": ns,
                     "labels": {"team": "ml"}},
        "spec": {"template": {"spec": {"containers": [
            {"name": name, "image": image}]}}},
        "status": {"conditions": [{"type": "Ready", "status": "True"}]},
    }


@pytest.fixture
def kube():
    k = FakeKube()
    k.add_namespace("ns1")
    k.add_namespace("ns2")
    return k


@pytest.fixture
def informer(kube):
    kube.create(nb("a"))
    kube.create(nb("b", ns="ns2"))
    inf = Informer(
        kube, NOTEBOOK,
        indexers={"team": lambda o: [
            (o["metadata"].get("labels") or {}).get("team", "")]},
    ).start()
    assert inf.wait_for_sync(5)
    yield inf
    inf.stop()


# -- frozen path: every mutation surface refuses ------------------------------


def test_every_mutation_surface_raises(informer):
    obj = informer.get("a", "ns1")
    assert isinstance(obj, FrozenResource)
    for attempt in (
        lambda: obj.__setitem__("x", 1),
        lambda: obj.__delitem__("spec"),
        lambda: obj.setdefault("x", 1),
        lambda: obj.update({"x": 1}),
        lambda: obj.pop("spec"),
        lambda: obj.popitem(),
        lambda: obj.clear(),
        # nested dicts (metadata.labels) are frozen too
        lambda: obj["metadata"].__setitem__("name", "evil"),
        lambda: obj["metadata"]["labels"].__setitem__("team", "evil"),
        lambda: obj["metadata"]["labels"].pop("team"),
        # nested lists (spec containers, status conditions)
        lambda: obj["spec"]["template"]["spec"]["containers"].append({}),
        lambda: obj["spec"]["template"]["spec"]["containers"].__setitem__(
            0, {}),
        lambda: obj["status"]["conditions"][0].__setitem__("status", "False"),
        lambda: obj["status"]["conditions"].clear(),
    ):
        with pytest.raises(TypeError, match="read-only; call thaw"):
            attempt()
    # The shared store never noticed any of it.
    assert informer.get("a", "ns1")["metadata"]["labels"]["team"] == "ml"


def test_list_and_index_list_and_handlers_hand_out_frozen_views(informer):
    for obj in informer.list():
        assert isinstance(obj, FrozenResource)
    for obj in informer.list("ns1"):
        assert isinstance(obj, FrozenResource)
    for obj in informer.index_list("team", "ml"):
        assert isinstance(obj, FrozenResource)
    seen = []
    informer.add_handler(lambda et, o: seen.append(o))
    assert seen and all(isinstance(o, FrozenResource) for o in seen)


def test_namespace_list_uses_per_ns_index(informer):
    assert [o["metadata"]["name"] for o in informer.list("ns2")] == ["b"]
    assert informer.keys("ns2") == [("ns2", "b")]
    assert sorted(informer.keys()) == [("ns1", "a"), ("ns2", "b")]
    assert informer.list("nope") == [] and informer.keys("nope") == []


# -- thawed path: private copies, store and indexes survive -------------------


def test_thaw_is_private_and_indexes_survive(kube, informer):
    view = informer.get("a", "ns1")
    mine = thaw(view)
    assert isinstance(mine, dict)
    mine["metadata"]["labels"]["team"] = "evil"
    mine["spec"]["template"]["spec"]["containers"][0]["image"] = "evil"
    mine["status"]["conditions"].append({"type": "Evil"})
    # Store object untouched...
    again = informer.get("a", "ns1")
    assert again["metadata"]["labels"]["team"] == "ml"
    assert again["spec"]["template"]["spec"]["containers"][0]["image"] == "img"
    assert len(again["status"]["conditions"]) == 1
    # ...and the index still files "a" under its original value.
    assert [o["metadata"]["name"] for o in informer.index_list("team", "ml")
            if o["metadata"]["namespace"] == "ns1"] == ["a"]
    assert informer.index_list("team", "evil") == []
    # deepcopy of a frozen view behaves like thaw (mutable private copy).
    other = copy.deepcopy(view)
    other["metadata"]["name"] = "elsewhere"
    assert informer.get("a", "ns1")["metadata"]["name"] == "a"


def test_thaw_on_plain_objects_is_a_deep_copy(kube):
    plain = nb("p")
    mine = thaw(plain)
    mine["metadata"]["labels"]["team"] = "evil"
    assert plain["metadata"]["labels"]["team"] == "ml"


# -- interop: equality, serialization, write-back -----------------------------


def test_frozen_equality_matches_plain(kube, informer):
    view = informer.get("a", "ns1")
    plain = kube.get(NOTEBOOK, "a", "ns1")
    assert view == plain and plain == view
    assert view["status"]["conditions"] == plain["status"]["conditions"]
    assert plain["status"]["conditions"] == view["status"]["conditions"]
    assert isinstance(view["status"]["conditions"], FrozenList)
    plain["metadata"]["labels"]["team"] = "other"
    assert view != plain


def test_frozen_views_serialize_via_json_default(informer):
    view = informer.get("a", "ns1")
    round_tripped = json.loads(json.dumps(view, default=json_default))
    assert round_tripped == thaw(view)
    # Embedded frozen subtrees serialize too (the read-modify-write shape:
    # a plain object carrying frozen nested values).
    status = {"conditions": view["status"]["conditions"]}
    assert json.loads(json.dumps(status, default=json_default)) == {
        "conditions": [{"type": "Ready", "status": "True"}]}


def test_fakekube_writes_accept_frozen_views(kube, informer):
    # update() built from a thawed view with frozen subtrees grafted in.
    base = kube.get(NOTEBOOK, "a", "ns1")
    view = informer.get("a", "ns1")
    base["status"] = {"conditions": view["status"]["conditions"]}
    out = kube.update_status(base)
    assert out["status"]["conditions"][0]["type"] == "Ready"
    assert isinstance(out["status"]["conditions"], list)
    # patch() carrying frozen values.
    patched = kube.patch(
        NOTEBOOK, "a",
        {"metadata": {"annotations": {"from-frozen": "yes"},
                      "labels": view["metadata"]["labels"]}},
        "ns1")
    assert patched["metadata"]["annotations"]["from-frozen"] == "yes"
    # create() of an object embedding a frozen template.
    fresh = nb("c")
    fresh["spec"]["template"] = view["spec"]["template"]
    created = kube.create(fresh)
    assert created["spec"]["template"]["spec"]["containers"][0]["image"] == "img"


def test_deep_get_and_meta_work_on_frozen(informer):
    from kubeflow_tpu.platform.k8s.types import deep_get, meta

    view = informer.get("a", "ns1")
    assert deep_get(view, "spec", "template", "spec",
                    "containers")[0]["name"] == "a"
    assert deep_get(view, "no", "such", "path", default=7) == 7
    assert meta(view)["name"] == "a"
    # No metadata on a frozen view: an EMPTY FROZEN mapping — reads see
    # nothing, writes fail loudly (a detached plain {} would swallow them).
    empty = meta(freeze({}))
    assert empty == {}
    with pytest.raises(TypeError, match="read-only"):
        empty["annotations"] = {}


# -- resync: key-only, zero copies --------------------------------------------


def test_resync_loop_performs_zero_copy_resource_calls(kube, monkeypatch):
    from kubeflow_tpu.platform.runtime import Controller, Reconciler

    for i in range(25):
        kube.create(nb(f"r-{i:02d}"))
    inf = Informer(kube, NOTEBOOK).start()
    assert inf.wait_for_sync(5)

    class Noop(Reconciler):
        def reconcile(self, req):
            return None

    ctrl = Controller("frozen-resync-test", Noop(), primary=NOTEBOOK,
                      informers={NOTEBOOK: inf})
    calls = []
    real = k8s_types.copy_resource

    def counting(x):
        calls.append(x)
        return real(x)

    monkeypatch.setattr(k8s_types, "copy_resource", counting)
    try:
        ctrl._resync_once(kube)
    finally:
        monkeypatch.undo()
    assert calls == [], (
        f"resync pass copied {len(calls)} objects; it must enqueue "
        "key-only (Informer.keys)")
    assert ctrl.queue.pending() == 25
    ctrl.queue.shut_down()
    inf.stop()


def test_resync_falls_back_to_client_list_when_cache_unsynced(kube):
    from kubeflow_tpu.platform.runtime import Controller, Reconciler

    kube.create(nb("solo"))

    class Noop(Reconciler):
        def reconcile(self, req):
            return None

    ctrl = Controller("fallback-resync-test", Noop(), primary=NOTEBOOK)
    ctrl._resync_once(kube)
    assert ctrl.queue.pending() == 1
    ctrl.queue.shut_down()


# -- raw watch resume (non-informer sources) ----------------------------------


def test_raw_watch_loop_resumes_by_rv_and_recovers_from_410():
    """Controller._watch_loop must re-establish raw watches from the last
    seen resourceVersion (no full-kind backlog replay per bounded window)
    — and when the apiserver rejects that RV AT ESTABLISHMENT (a real
    server answers a compacted RV with HTTP 410 before any event can
    stream), it must fall back to one full replay instead of livelocking
    on the same expired RV forever."""
    import threading
    import time as _time

    from kubeflow_tpu.platform.k8s import errors as k8s_errors
    from kubeflow_tpu.platform.runtime import Controller, Reconciler

    class Noop(Reconciler):
        def reconcile(self, req):
            return None

    class FakeWatchClient:
        def __init__(self):
            self.calls = []
            self.done = threading.Event()

        def watch(self, gvk, namespace=None, *, resource_version=None,
                  stop=None, **kw):
            self.calls.append(resource_version)
            n = len(self.calls)
            if n == 1:
                # initial watch: one event, then the bounded window closes
                def first():
                    obj = nb("w1")
                    obj["metadata"]["resourceVersion"] = "7"
                    yield ("ADDED", obj)
                return first()
            if n == 2:
                # resume attempt: the RV was compacted — reject at
                # establishment, like a real apiserver
                raise k8s_errors.ApiError(
                    "too old resource version", status=410)

            def rest():
                self.done.set()
                while stop is None or not stop.is_set():
                    _time.sleep(0.01)
                return
                yield  # pragma: no cover

            return rest()

    client = FakeWatchClient()
    ctrl = Controller("watch-resume-test", Noop(), primary=NOTEBOOK)
    t = threading.Thread(
        target=ctrl._watch_loop,
        args=(client, NOTEBOOK, ctrl._primary_mapper), daemon=True)
    t.start()
    assert client.done.wait(10.0), f"watch never recovered: {client.calls}"
    ctrl._stop.set()
    ctrl.queue.shut_down()
    t.join(timeout=5.0)
    # call 1: fresh (None); call 2: resumed from the event's RV; call 3:
    # reset to None after the establishment-410.
    assert client.calls == [None, "7", None], client.calls


def test_raw_watch_event_rv_is_carried_to_next_establishment():
    import threading
    import time as _time

    from kubeflow_tpu.platform.runtime import Controller, Reconciler

    class Noop(Reconciler):
        def reconcile(self, req):
            return None

    class FakeWatchClient:
        def __init__(self):
            self.calls = []
            self.done = threading.Event()

        def watch(self, gvk, namespace=None, *, resource_version=None,
                  stop=None, **kw):
            self.calls.append(resource_version)
            if len(self.calls) == 1:
                def first():
                    obj = nb("w1")
                    obj["metadata"]["resourceVersion"] = "42"
                    yield ("ADDED", obj)
                return first()

            def rest():
                self.done.set()
                while stop is None or not stop.is_set():
                    _time.sleep(0.01)
                return
                yield  # pragma: no cover

            return rest()

    client = FakeWatchClient()
    ctrl = Controller("watch-rv-test", Noop(), primary=NOTEBOOK)
    t = threading.Thread(
        target=ctrl._watch_loop,
        args=(client, NOTEBOOK, ctrl._primary_mapper), daemon=True)
    t.start()
    assert client.done.wait(10.0)
    ctrl._stop.set()
    ctrl.queue.shut_down()
    t.join(timeout=5.0)
    assert client.calls[:2] == [None, "42"], client.calls


# -- gvk_for fallback pluralization -------------------------------------------


@pytest.mark.parametrize("kind,plural", [
    ("NetworkPolicy", "networkpolicies"),       # consonant + y -> ies
    ("PriorityClass", "priorityclasses"),       # sibilant ss -> es
    ("Ingress", "ingresses"),
    ("Status", "statuses"),                     # us -> es
    ("Analysis", "analysises"),                 # is -> es (deterministic
                                                # guess; flect's irregular
                                                # table would say analyses)
    ("Endpoints", "endpoints"),                 # already plural: unchanged
    ("Gateway", "gateways"),                    # vowel + y -> ys
    ("Box", "boxes"),
    ("Branch", "branches"),
    ("Dish", "dishes"),
    ("Topaz", "topazes"),
    ("Widget", "widgets"),                      # default -> s
])
def test_gvk_for_fallback_pluralization(kind, plural):
    assert pluralize(kind) == plural
    gvk = gvk_for("example.com/v1", kind)
    assert gvk.plural == plural and gvk.kind == kind


def test_gvk_for_well_known_kinds_keep_registered_plurals():
    assert gvk_for("v1", "Pod").plural == "pods"
    assert gvk_for("v1", "Pod") is POD
    assert gvk_for("kubeflow.org/v1beta1", "Notebook").plural == "notebooks"
