"""kftlint engine + rule tests (ISSUE 13 tentpole).

Each rule is pinned by its corpus twins: the bad twin must fire the rule
(and ONLY that rule — precision is the product), the good twin must stay
silent.  Suppressions, the baseline round-trip, fingerprint stability
under unrelated edits, and the real repo's cleanliness are pinned here
too — the last one IS the acceptance criterion the `lint` CI lane gates
on.
"""
import json
import os
import shutil
import subprocess
import sys

import pytest

from kubeflow_tpu.analysis import engine
from kubeflow_tpu.analysis import rules as _rules  # noqa: F401

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
CORPUS_DIR = os.path.join(os.path.dirname(__file__), "lintcorpus")

# rule id -> (corpus stem, pretend path routing the file into the rule's
# scope; the good twin may use a second pretend path where the rule keys
# off the module identity itself).
CORPUS = {
    "R001": ("r001", "kubeflow_tpu/platform/controllers/corpus.py", None),
    "R002": ("r002", "kubeflow_tpu/platform/controllers/corpus.py", None),
    "R003": ("r003", "kubeflow_tpu/platform/controllers/corpus.py", None),
    "R004": ("r004", "kubeflow_tpu/platform/controllers/corpus.py", None),
    "R005": ("r005", "kubeflow_tpu/models/corpus.py", None),
    "R006": ("r006", "kubeflow_tpu/platform/runtime/corpus.py", None),
    "R007": ("r007", "kubeflow_tpu/platform/controllers/corpus.py",
             "kubeflow_tpu/platform/runtime/metrics.py"),
    "R008": ("r008", "kubeflow_tpu/platform/controllers/corpus.py", None),
    "R009": ("r009", "kubeflow_tpu/platform/controllers/corpus.py", None),
    "R010": ("r010", "kubeflow_tpu/platform/runtime/corpus.py", None),
}


def _corpus(stem: str, kind: str) -> str:
    with open(os.path.join(CORPUS_DIR, f"{stem}_{kind}.py")) as fh:
        return fh.read()


def test_registry_has_the_ten_rules():
    ids = sorted(r.id for r in engine.all_rules())
    assert ids == [f"R00{i}" for i in range(1, 10)] + ["R010"]
    assert set(CORPUS) == set(ids)


@pytest.mark.parametrize("rule_id", sorted(CORPUS))
def test_bad_twin_fires_exactly_its_rule(rule_id):
    stem, bad_path, _ = CORPUS[rule_id]
    findings = engine.lint_source(_corpus(stem, "bad"), bad_path)
    fired = {f.rule for f in findings}
    assert rule_id in fired, f"{rule_id} missed its bad twin"
    assert fired == {rule_id}, (
        f"bad twin for {rule_id} leaked other findings: {findings}")


@pytest.mark.parametrize("rule_id", sorted(CORPUS))
def test_good_twin_is_silent(rule_id):
    stem, bad_path, good_path = CORPUS[rule_id]
    findings = engine.lint_source(_corpus(stem, "good"),
                                  good_path or bad_path)
    assert findings == [], f"good twin for {rule_id} fired: {findings}"


# -- suppressions -------------------------------------------------------------

_BAD_ENV = "import os\nTIMEOUT = os.environ.get('X', '1')\n"


def test_same_line_suppression():
    src = _BAD_ENV.replace(
        "'1')\n", "'1')  # kft: disable=R005 migration pending\n")
    assert engine.lint_source(src, "kubeflow_tpu/models/x.py") == []


def test_line_above_suppression():
    src = ("import os\n"
           "# kft: disable=R005 migration pending\n"
           "TIMEOUT = os.environ.get('X', '1')\n")
    assert engine.lint_source(src, "kubeflow_tpu/models/x.py") == []


def test_file_level_suppression():
    src = "# kft: disable-file=R005 generated shim\n" + _BAD_ENV
    assert engine.lint_source(src, "kubeflow_tpu/models/x.py") == []


def test_suppressing_one_rule_keeps_others():
    src = ("import os\n"
           "def f():\n"
           "    try:\n"
           "        return os.environ['X']  # kft: disable=R005 demo\n"
           "    except Exception:\n"
           "        pass\n")
    findings = engine.lint_source(
        src, "kubeflow_tpu/platform/runtime/x.py")
    assert {f.rule for f in findings} == {"R006"}


# -- baseline round-trip ------------------------------------------------------


@pytest.fixture
def tmp_repo(tmp_path):
    tree = tmp_path / "repo"
    ctrl = tree / "kubeflow_tpu" / "platform" / "controllers"
    ctrl.mkdir(parents=True)
    shutil.copy(os.path.join(CORPUS_DIR, "r001_bad.py"), ctrl / "bad.py")
    return tree


def test_baseline_round_trip(tmp_repo, tmp_path):
    findings = engine.lint_paths(root=str(tmp_repo))
    assert findings and all(f.rule == "R001" for f in findings)
    baseline_path = tmp_path / "baseline.json"
    engine.write_baseline(findings, str(baseline_path))
    baseline = engine.load_baseline(str(baseline_path))
    again = engine.lint_paths(root=str(tmp_repo))
    new = [f for f in again
           if (f.rule, f.path, f.fingerprint) not in baseline]
    assert new == []


def test_baselined_finding_survives_unrelated_edits(tmp_repo, tmp_path):
    findings = engine.lint_paths(root=str(tmp_repo))
    baseline_path = tmp_path / "baseline.json"
    engine.write_baseline(findings, str(baseline_path))
    baseline = engine.load_baseline(str(baseline_path))
    bad = tmp_repo / "kubeflow_tpu" / "platform" / "controllers" / "bad.py"
    # Unrelated edit above the findings: line numbers shift, fingerprints
    # (line-content keyed) must not.
    bad.write_text("# a new leading comment\n\n" + bad.read_text())
    shifted = engine.lint_paths(root=str(tmp_repo))
    new = [f for f in shifted
           if (f.rule, f.path, f.fingerprint) not in baseline]
    assert new == [], "unrelated edit resurfaced baselined findings"
    # Touching the offending line itself DOES resurface it.
    bad.write_text(bad.read_text().replace(
        "self.client.inner.update(obj)",
        "self.client.inner.update(obj)  # tweaked"))
    touched = engine.lint_paths(root=str(tmp_repo))
    new = [f for f in touched
           if (f.rule, f.path, f.fingerprint) not in baseline]
    assert len(new) == 1


# -- the repo itself ----------------------------------------------------------


def test_repo_is_clean_under_shipped_baseline():
    """THE acceptance pin: zero unsuppressed, un-baselined findings over
    the real tree."""
    findings = engine.lint_paths(root=REPO)
    baseline = engine.load_baseline(
        os.path.join(REPO, "ci", "kftlint_baseline.json"))
    new = [f for f in findings
           if (f.rule, f.path, f.fingerprint) not in baseline]
    assert new == [], "\n".join(f.render() for f in new)


def test_shipped_baseline_is_empty_for_top_contracts():
    """Baseline hygiene (ISSUE 13): R001/R003/R004 are enforced from day
    one — no baselined debt, every real site fixed or inline-suppressed
    with a reason."""
    with open(os.path.join(REPO, "ci", "kftlint_baseline.json")) as fh:
        data = json.load(fh)
    debt = {e["rule"] for e in data.get("findings", [])}
    assert not (debt & {"R001", "R003", "R004"}), debt


# -- CLI ----------------------------------------------------------------------


def test_cli_list_rules_and_exit_codes(tmp_repo, tmp_path):
    env = dict(os.environ, PYTHONPATH=REPO)
    out = subprocess.run(
        [sys.executable, "-m", "kubeflow_tpu.analysis", "--list-rules"],
        capture_output=True, text=True, env=env, cwd=REPO)
    assert out.returncode == 0
    assert all(f"R00{i}" in out.stdout for i in range(1, 10))
    assert "R010" in out.stdout

    dirty = subprocess.run(
        [sys.executable, "-m", "kubeflow_tpu.analysis",
         "--root", str(tmp_repo), "--format", "json"],
        capture_output=True, text=True, env=env, cwd=REPO)
    assert dirty.returncode == 1
    payload = json.loads(dirty.stdout)
    assert payload["findings"] and payload["baselined"] == 0

    baseline = tmp_path / "b.json"
    wrote = subprocess.run(
        [sys.executable, "-m", "kubeflow_tpu.analysis",
         "--root", str(tmp_repo), "--baseline", str(baseline),
         "--write-baseline"],
        capture_output=True, text=True, env=env, cwd=REPO)
    assert wrote.returncode == 0
    clean = subprocess.run(
        [sys.executable, "-m", "kubeflow_tpu.analysis",
         "--root", str(tmp_repo), "--baseline", str(baseline)],
        capture_output=True, text=True, env=env, cwd=REPO)
    assert clean.returncode == 0, clean.stdout
