"""Lease-based leader election (reference main.go:90-92) + client QPS."""
import datetime
import time

from kubeflow_tpu.platform.runtime.leader import LeaderElector
from kubeflow_tpu.platform.testing import FakeKube

T0 = datetime.datetime(2026, 7, 30, 12, 0, 0, tzinfo=datetime.timezone.utc)


class Clock:
    def __init__(self):
        self.now = T0

    def __call__(self):
        return self.now

    def advance(self, seconds):
        self.now += datetime.timedelta(seconds=seconds)


def elector(kube, ident, clock, **kw):
    return LeaderElector(
        kube, name="test-lease", namespace="kubeflow", identity=ident,
        lease_seconds=15, now=clock, **kw,
    )


def test_exactly_one_acquires():
    kube = FakeKube()
    kube.add_namespace("kubeflow")
    clock = Clock()
    a, b = elector(kube, "a", clock), elector(kube, "b", clock)
    assert a.try_acquire_or_renew() == "leading"
    assert b.try_acquire_or_renew() == "lost"
    # a renews fine; b still locked out.
    clock.advance(5)
    assert a.try_acquire_or_renew() == "leading"
    assert b.try_acquire_or_renew() == "lost"


def test_expiry_failover():
    kube = FakeKube()
    kube.add_namespace("kubeflow")
    clock = Clock()
    a, b = elector(kube, "a", clock), elector(kube, "b", clock)
    assert a.try_acquire_or_renew() == "leading"
    clock.advance(16)  # past leaseDurationSeconds without renewal
    assert b.try_acquire_or_renew() == "leading"
    # a's next renew must fail: the lease moved.
    assert a.try_acquire_or_renew() == "lost"


def test_release_hands_over_immediately():
    kube = FakeKube()
    kube.add_namespace("kubeflow")
    clock = Clock()
    a, b = elector(kube, "a", clock), elector(kube, "b", clock)
    assert a.try_acquire_or_renew() == "leading"
    a.release()
    assert b.try_acquire_or_renew() == "leading"


def test_manager_leader_election_single_writer():
    from kubeflow_tpu.platform.runtime import Manager

    kube = FakeKube()
    kube.add_namespace("kubeflow")
    m1 = Manager(kube, leader_election=True, identity="m1")
    m2 = Manager(kube, leader_election=True, identity="m2")
    # Speed the loops up for the test.
    for m in (m1, m2):
        m.elector.lease_seconds = 1.0
        m.elector.renew_seconds = 0.05
        m.elector.retry_seconds = 0.05
    m1.start()
    deadline = time.time() + 5
    while time.time() < deadline and not m1.is_leader:
        time.sleep(0.01)
    assert m1.is_leader
    m2.start()
    time.sleep(0.2)
    assert not m2.is_leader
    assert m2.healthy()  # standby is healthy, just not leading
    # m1 shuts down -> lease released -> m2 takes over.
    m1.stop()
    deadline = time.time() + 5
    while time.time() < deadline and not m2.is_leader:
        time.sleep(0.01)
    assert m2.is_leader
    m2.stop()


def test_token_bucket_limits_rate():
    from kubeflow_tpu.platform.k8s.client import TokenBucket

    tb = TokenBucket(qps=200, burst=3)
    t0 = time.monotonic()
    for _ in range(3):
        tb.acquire()  # burst: immediate
    burst_t = time.monotonic() - t0
    assert burst_t < 0.05
    for _ in range(4):
        tb.acquire()  # must wait ~5ms each at 200 qps
    assert time.monotonic() - t0 >= 4 / 200


def test_transient_api_error_does_not_drop_leadership():
    # client-go semantics: a failed renew only demotes once the lease
    # duration has elapsed without a successful renewal.
    kube = FakeKube()
    kube.add_namespace("kubeflow")

    class Flaky:
        """Proxy that can be told to fail the next N API calls."""

        def __init__(self, inner):
            self._inner = inner
            self.fail = 0

        def __getattr__(self, name):
            fn = getattr(self._inner, name)

            def wrapped(*a, **k):
                if self.fail > 0 and name in ("get", "update", "create"):
                    self.fail -= 1
                    raise RuntimeError("apiserver blip")
                return fn(*a, **k)

            return wrapped

    flaky = Flaky(kube)
    el = LeaderElector(
        kube, name="t", namespace="kubeflow", identity="a",
        lease_seconds=2.0, renew_seconds=0.05, retry_seconds=0.05,
    )
    el.client = flaky
    became, lost = [], []
    el.on_started_leading = lambda: became.append(1)
    el.on_stopped_leading = lambda: lost.append(1)
    el.start()
    deadline = time.time() + 5
    while time.time() < deadline and not el.is_leader:
        time.sleep(0.01)
    assert el.is_leader
    flaky.fail = 3  # a few blips, well inside the 2s lease window
    time.sleep(0.5)
    assert el.is_leader and not lost
    el.stop()


def test_lost_leadership_is_terminal_for_manager():
    from kubeflow_tpu.platform.runtime import Manager

    kube = FakeKube()
    kube.add_namespace("kubeflow")
    m = Manager(kube, leader_election=True, identity="m")
    m.elector.lease_seconds = 0.4
    m.elector.renew_seconds = 0.05
    m.elector.retry_seconds = 0.05
    m.start()
    deadline = time.time() + 5
    while time.time() < deadline and not m.is_leader:
        time.sleep(0.01)
    assert m.is_leader
    # Steal the lease: m sees a live foreign holder -> definitive loss.
    from kubeflow_tpu.platform.k8s.types import LEASE

    lease = kube.get(LEASE, "kubeflow-tpu-controller-leader", "kubeflow")
    lease["spec"]["holderIdentity"] = "other"
    lease["spec"]["renewTime"] = "2199-01-01T00:00:00.000000Z"
    kube.update(lease)
    deadline = time.time() + 5
    while time.time() < deadline and m.healthy():
        time.sleep(0.01)
    assert not m.healthy()          # terminal: liveness probe restarts us
    time.sleep(0.2)
    assert not m.is_leader          # and we never re-contend
    m.stop()
