"""Write-coalesced hot path, pinned from two sides:

1. ONE patch-semantics matrix every KubeClient implementation must pass —
   FakeKube in memory, ChaosKube wrapping it (quiet schedule), and the
   real RestKubeClient against the fake served over HTTP — so
   ``patch``/``patch_status`` behave identically whichever client a
   controller is handed (ISSUE 5 acceptance).
2. A seeded-chaos A/B of the reconciler's write path: the same scenario
   driven once through the patched writes and once through a shim that
   restores the pre-patch shape (full RV-carrying updates, an Event
   create per recorder call) must show strictly fewer 409 conflicts and
   fewer Event creates on the patched side, asserted from ChaosKube call
   logs.
"""
from __future__ import annotations

import copy

import pytest

from kubeflow_tpu.platform.k8s import errors
from kubeflow_tpu.platform.k8s.types import EVENT, NOTEBOOK, STATEFULSET, thaw
from kubeflow_tpu.platform.runtime.apply import merge_patch_for, patch_status_diff
from kubeflow_tpu.platform.testing import FakeKube
from kubeflow_tpu.platform.testing.chaos import ChaosKube, Fault
from kubeflow_tpu.platform.testing.httpkube import HttpKubeServer


# -- the shared patch-semantics matrix ----------------------------------------


@pytest.fixture(params=["fake", "chaos", "rest"])
def client(request):
    """Each KubeClient implementation over one seeded store."""
    kube = FakeKube()
    kube.add_namespace("ns")
    kube.create({
        "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
        "metadata": {"name": "nb", "namespace": "ns",
                     "labels": {"keep": "me"},
                     "annotations": {"a": "1", "b": "2"}},
        "spec": {"template": {"spec": {"containers": [
            {"name": "nb", "image": "img:1"}]}}},
    })
    kube.patch_status(NOTEBOOK, "nb", {
        "status": {"readyReplicas": 0, "replicas": 2,
                   "conditions": [{"type": "Ready", "status": "False"}]},
    }, "ns")
    if request.param == "fake":
        yield kube
        return
    if request.param == "chaos":
        yield ChaosKube(kube, faults=[])
        return
    from kubeflow_tpu.platform.k8s.client import RestKubeClient

    server = HttpKubeServer(kube).start()
    try:
        yield RestKubeClient(server.base_url, qps=0)
    finally:
        server.stop()


def test_merge_patch_add_replace_remove(client):
    out = client.patch(NOTEBOOK, "nb", {
        "metadata": {"annotations": {"a": "9", "b": None, "c": "3"}},
    }, "ns")
    ann = {k: v for k, v in out["metadata"]["annotations"].items()
           if not k.startswith("kubeflow.org/trace")}  # causal stamp rides every CR
    assert ann == {"a": "9", "c": "3"}
    assert out["metadata"]["labels"] == {"keep": "me"}  # untouched siblings


def test_patch_does_not_need_a_resource_version(client):
    # Concurrent writer bumps the RV between our read and our patch: a
    # merge patch carries no RV precondition, so no 409 — THE property the
    # write-coalesced hot path buys under churn.
    before = client.get(NOTEBOOK, "nb", "ns")
    bump = thaw(before)
    bump["metadata"].setdefault("annotations", {})["touch"] = "x"
    client.update(bump)
    out = client.patch(NOTEBOOK, "nb", {
        "metadata": {"annotations": {"post-conflict": "yes"}}}, "ns")
    assert out["metadata"]["annotations"]["post-conflict"] == "yes"
    assert out["metadata"]["annotations"]["touch"] == "x"  # both survive


def test_patch_keeps_status_subresource(client):
    out = client.patch(NOTEBOOK, "nb", {
        "spec": {"template": {"spec": {"containers": [
            {"name": "nb", "image": "img:2"}]}}}}, "ns")
    assert out["status"]["replicas"] == 2  # spec patch left status alone


def test_patch_status_touches_only_the_changed_subtree(client):
    out = client.patch_status(NOTEBOOK, "nb", {
        "status": {"readyReplicas": 2}}, "ns")
    assert out["status"]["readyReplicas"] == 2
    assert out["status"]["replicas"] == 2          # unpatched key kept
    assert out["status"]["conditions"]             # unpatched key kept
    live = client.get(NOTEBOOK, "nb", "ns")
    assert live["status"]["readyReplicas"] == 2


def test_patch_status_discards_smuggled_spec_edits(client):
    client.patch_status(NOTEBOOK, "nb", {
        "status": {"readyReplicas": 1},
        "spec": {"template": None},
        "metadata": {"labels": {"keep": "overwritten"}},
    }, "ns")
    live = client.get(NOTEBOOK, "nb", "ns")
    assert live["status"]["readyReplicas"] == 1
    assert live["spec"]["template"] is not None          # spec isolated
    assert live["metadata"]["labels"] == {"keep": "me"}  # metadata isolated


def test_patch_status_null_removes_status_keys(client):
    out = client.patch_status(NOTEBOOK, "nb", {
        "status": {"conditions": None}}, "ns")
    assert "conditions" not in out["status"]
    assert out["status"]["replicas"] == 2


def test_patch_missing_object_is_typed_not_found(client):
    with pytest.raises(errors.NotFound):
        client.patch(NOTEBOOK, "ghost", {"metadata": {}}, "ns")
    with pytest.raises(errors.NotFound):
        client.patch_status(NOTEBOOK, "ghost", {"status": {}}, "ns")


def test_patch_bumps_resource_version_and_emits_watch_delta(client):
    before = client.get(NOTEBOOK, "nb", "ns")
    after = client.patch_status(NOTEBOOK, "nb",
                                {"status": {"readyReplicas": 2}}, "ns")
    assert (after["metadata"]["resourceVersion"]
            != before["metadata"]["resourceVersion"])


# -- merge_patch_for / patch_status_diff helpers ------------------------------


def test_merge_patch_for_minimal_diff():
    cur = {"a": 1, "b": {"x": 1, "y": 2}, "c": [1, 2], "gone": True}
    want = {"a": 1, "b": {"x": 1, "y": 3}, "c": [1, 2, 3]}
    assert merge_patch_for(cur, want) == {
        "b": {"y": 3}, "c": [1, 2, 3], "gone": None}
    assert merge_patch_for(cur, copy.deepcopy(cur)) is None
    assert merge_patch_for({}, {"new": 1}) == {"new": 1}
    assert merge_patch_for(None, {"new": 1}) == {"new": 1}


def test_merge_patch_for_accepts_frozen_current():
    from kubeflow_tpu.platform.k8s.types import freeze

    cur = freeze({"spec": {"replicas": 1, "svc": "s"}})
    patch = merge_patch_for(cur, {"spec": {"replicas": 2, "svc": "s"}})
    assert patch == {"spec": {"replicas": 2}}
    # The produced patch must be plain data (serializable, mutable).
    assert type(patch["spec"]) is dict


def test_patch_status_diff_writes_only_on_change():
    kube = FakeKube()
    kube.add_namespace("ns")
    chaos = ChaosKube(kube, faults=[])
    chaos.create({
        "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
        "metadata": {"name": "nb", "namespace": "ns"},
        "spec": {"template": {"spec": {"containers": [{"name": "c"}]}}},
    })
    obj = chaos.get(NOTEBOOK, "nb", "ns")
    assert patch_status_diff(chaos, NOTEBOOK, obj, {"replicas": 2})
    obj = chaos.get(NOTEBOOK, "nb", "ns")
    assert not patch_status_diff(chaos, NOTEBOOK, obj, {"replicas": 2})
    assert chaos.calls.get("patch_status") == 1
    assert chaos.calls.get("update_status") is None


# -- the chaos A/B: patched writes vs the pre-patch shape ---------------------


class _AlwaysCreateCorrelator:
    """The pre-correlator recorder: every call is a fresh Event create."""

    def observe(self, key):
        return "create", None

    def created(self, key, name):
        pass


class _LegacyWriteShim:
    """Restores the pre-patch write shape for the A/B arm: ``patch``
    becomes GET + full-object PUT (resourceVersion attached, so injected
    and real 409s apply), and ``patch_status`` is ABSENT so status writers
    fall back to full ``update_status`` — exactly the seed-era path."""

    def __init__(self, inner):
        self.inner = inner

    def patch(self, gvk, name, patch, namespace=None, *, patch_type="merge"):
        from kubeflow_tpu.platform.testing.fake import _merge_patch

        cur = thaw(self.inner.get(gvk, name, namespace))
        _merge_patch(cur, copy.deepcopy(patch))
        return self.inner.update(cur)

    @property
    def patch_status(self):
        raise AttributeError("pre-patch clients have no patch_status")

    def __getattr__(self, name):
        return getattr(self.inner, name)


def _run_write_scenario(legacy: bool, *, passes: int = 6):
    """Drive the notebook reconciler synchronously over a seeded 409 storm
    (conflicts injected on the RV-carrying verbs only — merge patches
    carry no RV, which is how a real apiserver behaves) and return the
    ChaosKube for call-log assertions."""
    from kubeflow_tpu.platform.controllers.notebook import NotebookReconciler
    from kubeflow_tpu.platform.runtime import EventRecorder, Request

    kube = FakeKube()
    kube.add_namespace("ns")
    kube.add_tpu_node("tpu-node", topology="2x4")
    chaos = ChaosKube(kube, faults=[
        Fault("409", 0.4, verbs=frozenset({"update", "update_status"})),
    ], seed=7)
    client = _LegacyWriteShim(chaos) if legacy else chaos
    rec = NotebookReconciler(client, use_istio=False,
                             mirror_min_interval=3600.0)
    if legacy:
        rec.recorder = EventRecorder(
            client, "notebook-controller",
            correlator=_AlwaysCreateCorrelator())
    names = [f"nb-{i}" for i in range(3)]
    for name in names:
        kube.create({
            "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
            "metadata": {"name": name, "namespace": "ns"},
            "spec": {
                "tpu": {"accelerator": "v5e", "topology": "2x4"},
                "template": {"spec": {"containers": [
                    {"name": name, "image": "img"}]}},
            },
        })
    # A sibling squatting on nb-conflict's slice-1 name: every reconcile
    # of nb-conflict emits a SliceNameConflict Warning — the recorder
    # flood the correlator exists to coalesce.
    kube.create({
        "apiVersion": "apps/v1", "kind": "StatefulSet",
        "metadata": {"name": "nb-conflict-s1", "namespace": "ns",
                     "labels": {"notebook-name": "other"}},
        "spec": {"replicas": 1, "selector": {"matchLabels": {"x": "y"}},
                 "template": {"metadata": {"labels": {"x": "y"}},
                              "spec": {"containers": [{"name": "c"}]}}},
    })
    kube.create({
        "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
        "metadata": {"name": "nb-conflict", "namespace": "ns"},
        "spec": {
            "tpu": {"accelerator": "v5e", "topology": "2x4", "slices": 2},
            "template": {"spec": {"containers": [
                {"name": "nb-conflict", "image": "img"}]}},
        },
    })
    for p in range(passes):
        # Touch each notebook's spec between passes (stop/start toggles
        # replicas) so every pass has real STS + status writes to make.
        for name in names:
            nb = kube.get(NOTEBOOK, name, "ns")
            ann = nb["metadata"].setdefault("annotations", {})
            if p % 2:
                ann["kubeflow-resource-stopped"] = "2026-08-04T00:00:00Z"
            else:
                ann.pop("kubeflow-resource-stopped", None)
            kube.update(nb)
        for name in names + ["nb-conflict"]:
            try:
                rec.reconcile(Request("ns", name))
            except errors.ApiError:
                pass  # injected conflicts; the queue would requeue
    return chaos, kube


def test_patched_write_path_beats_legacy_under_seeded_conflicts():
    legacy_chaos, _ = _run_write_scenario(legacy=True)
    patched_chaos, patched_kube = _run_write_scenario(legacy=False)

    # Strictly fewer 409s: the patched hot path writes through RV-free
    # merge patches, so the conflict schedule has almost nothing to hit.
    legacy_409 = legacy_chaos.injected("409")
    patched_409 = patched_chaos.injected("409")
    assert legacy_409 > 0, "scenario must actually conflict the legacy arm"
    assert patched_409 < legacy_409, (patched_409, legacy_409)

    # Fewer Event creates: the correlator turns the per-pass
    # SliceNameConflict flood into count-increment patches.
    legacy_event_creates = legacy_chaos.calls_by_kind.get(
        ("create", "Event"), 0)
    patched_event_creates = patched_chaos.calls_by_kind.get(
        ("create", "Event"), 0)
    assert patched_event_creates < legacy_event_creates, (
        patched_event_creates, legacy_event_creates)

    # And the coalesced Event really carries the flood as a count.
    conflict_events = [
        e for e in patched_kube.list(EVENT, "ns")
        if e.get("reason") == "SliceNameConflict"]
    assert len(conflict_events) == 1
    assert conflict_events[0]["count"] > 1

    # The patched arm's steady-state secondary writes go through patch
    # verbs, not full updates.
    assert patched_chaos.calls.get("patch_status", 0) > 0
    assert patched_chaos.calls_by_kind.get(("update", "StatefulSet"), 0) == 0


def test_sts_update_path_is_a_patch_and_converges():
    """Spec change -> the reconciler PATCHes only the owned fields, and a
    second reconcile is a no-op (hash annotation converged)."""
    from kubeflow_tpu.platform.controllers.notebook import NotebookReconciler
    from kubeflow_tpu.platform.runtime import Request

    kube = FakeKube()
    kube.add_namespace("ns")
    chaos = ChaosKube(kube, faults=[])
    rec = NotebookReconciler(chaos, use_istio=False,
                             mirror_min_interval=3600.0)
    kube.create({
        "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
        "metadata": {"name": "nb", "namespace": "ns"},
        "spec": {"template": {"spec": {"containers": [
            {"name": "nb", "image": "img:1"}]}}},
    })
    rec.reconcile(Request("ns", "nb"))
    nb = kube.get(NOTEBOOK, "nb", "ns")
    nb["spec"]["template"]["spec"]["containers"][0]["image"] = "img:2"
    kube.update(nb)
    before = dict(chaos.calls)
    rec.reconcile(Request("ns", "nb"))
    sts = kube.get(STATEFULSET, "nb", "ns")
    assert sts["spec"]["template"]["spec"]["containers"][0]["image"] == "img:2"
    assert chaos.calls.get("patch", 0) > before.get("patch", 0)
    assert chaos.calls.get("update", 0) == before.get("update", 0)
    # Converged: the next reconcile writes nothing.
    quiet = dict(chaos.calls)
    rec.reconcile(Request("ns", "nb"))
    for verb in ("create", "update", "update_status", "patch",
                 "patch_status"):
        assert chaos.calls.get(verb, 0) == quiet.get(verb, 0), verb
