import pytest

from kubeflow_tpu.platform.controllers.profile import (
    AUTH_POLICY_NAME,
    QUOTA_NAME,
    ProfileReconciler,
    WorkloadIdentityPlugin,
)
from kubeflow_tpu.platform.k8s import errors
from kubeflow_tpu.platform.k8s.types import (
    AUTHORIZATIONPOLICY,
    NAMESPACE,
    PROFILE,
    RESOURCEQUOTA,
    ROLEBINDING,
    SERVICEACCOUNT,
    deep_get,
)
from kubeflow_tpu.platform.runtime import Request
from kubeflow_tpu.platform.testing import FakeKube


def make_profile(name="alice", owner="alice@example.com", quota=None, plugins=None):
    p = {
        "apiVersion": "kubeflow.org/v1",
        "kind": "Profile",
        "metadata": {"name": name},
        "spec": {"owner": {"kind": "User", "name": owner}},
    }
    if quota:
        p["spec"]["resourceQuotaSpec"] = quota
    if plugins:
        p["spec"]["plugins"] = plugins
    return p


@pytest.fixture
def kube():
    k = FakeKube()
    k.add_namespace("default")
    return k


def reconcile(kube, **kwargs):
    r = ProfileReconciler(kube, **kwargs)
    r.reconcile(Request("", "alice"))
    return r


def test_profile_creates_workspace(kube):
    kube.create(make_profile(quota={"hard": {"google.com/tpu": "32", "cpu": "64"}}))
    reconcile(kube)
    ns = kube.get(NAMESPACE, "alice")
    assert ns["metadata"]["annotations"]["owner"] == "alice@example.com"
    assert ns["metadata"]["labels"]["istio-injection"] == "enabled"
    for sa in ("default-editor", "default-viewer"):
        kube.get(SERVICEACCOUNT, sa, "alice")
    rb = kube.get(ROLEBINDING, "namespaceAdmin", "alice")
    assert rb["roleRef"]["name"] == "kubeflow-admin"
    assert rb["subjects"][0]["name"] == "alice@example.com"
    quota = kube.get(RESOURCEQUOTA, QUOTA_NAME, "alice")
    assert quota["spec"]["hard"]["google.com/tpu"] == "32"
    policy = kube.get(AUTHORIZATIONPOLICY, AUTH_POLICY_NAME, "alice")
    rules = policy["spec"]["rules"]
    assert rules[0]["when"][0]["values"] == ["alice@example.com"]
    assert rules[2]["to"][0]["operation"]["paths"] == ["*/api/kernels"]
    assert kube.get(PROFILE, "alice")["status"]["status"] == "Succeeded"


def test_foreign_namespace_not_taken_over(kube):
    kube.add_namespace("alice")  # pre-existing, no owner annotation
    kube.create(make_profile())
    reconcile(kube)
    profile = kube.get(PROFILE, "alice")
    assert profile["status"]["status"] == "Failed"
    with pytest.raises(errors.NotFound):
        kube.get(SERVICEACCOUNT, "default-editor", "alice")


def test_quota_removed_when_spec_cleared(kube):
    kube.create(make_profile(quota={"hard": {"cpu": "4"}}))
    reconcile(kube)
    kube.get(RESOURCEQUOTA, QUOTA_NAME, "alice")
    p = kube.get(PROFILE, "alice")
    del p["spec"]["resourceQuotaSpec"]
    kube.update(p)
    reconcile(kube)
    with pytest.raises(errors.NotFound):
        kube.get(RESOURCEQUOTA, QUOTA_NAME, "alice")


def test_workload_identity_plugin_and_finalizer(kube):
    iam_calls = []
    plugin = WorkloadIdentityPlugin(
        bind_iam=lambda sa, member, add: iam_calls.append((sa, member, add)),
        identity_pool="proj.svc.id.goog",
    )
    kube.create(make_profile(plugins=[{
        "kind": "WorkloadIdentity",
        "spec": {"gcpServiceAccount": "ml@proj.iam.gserviceaccount.com"},
    }]))
    r = ProfileReconciler(kube, plugins=[plugin])
    r.reconcile(Request("", "alice"))
    sa = kube.get(SERVICEACCOUNT, "default-editor", "alice")
    assert sa["metadata"]["annotations"]["iam.gke.io/gcp-service-account"] == (
        "ml@proj.iam.gserviceaccount.com"
    )
    assert iam_calls[-1] == (
        "ml@proj.iam.gserviceaccount.com",
        "serviceAccount:proj.svc.id.goog[alice/default-editor]",
        True,
    )
    # Delete → finalizer drives revocation, then the profile disappears.
    kube.delete(PROFILE, "alice")
    r.reconcile(Request("", "alice"))
    assert iam_calls[-1][2] is False
    with pytest.raises(errors.NotFound):
        kube.get(PROFILE, "alice")


def test_idempotent(kube):
    kube.create(make_profile(quota={"hard": {"cpu": "4"}}))
    reconcile(kube)
    rv1 = kube.get(RESOURCEQUOTA, QUOTA_NAME, "alice")["metadata"]["resourceVersion"]
    reconcile(kube)
    rv2 = kube.get(RESOURCEQUOTA, QUOTA_NAME, "alice")["metadata"]["resourceVersion"]
    assert rv1 == rv2


def test_namespace_labels_hot_reload(kube, tmp_path):
    # Labels come from the mounted file, re-read each reconcile (reference
    # profile_controller.go:368-399 fsnotify + :762-777 file loader).
    labels = tmp_path / "namespace-labels.yaml"
    labels.write_text("istio-injection: enabled\n")
    kube.create(make_profile())
    r = ProfileReconciler(kube, default_namespace_labels_path=str(labels))
    r.reconcile(Request("", "alice"))
    ns = kube.get(NAMESPACE, "alice")
    assert ns["metadata"]["labels"]["istio-injection"] == "enabled"
    assert "team" not in ns["metadata"]["labels"]

    labels.write_text("istio-injection: enabled\nteam: ml\n")
    r.reconcile(Request("", "alice"))
    ns = kube.get(NAMESPACE, "alice")
    assert ns["metadata"]["labels"]["team"] == "ml"


def test_labels_file_watcher_triggers_reconcile_all(kube, tmp_path):
    import time

    from kubeflow_tpu.platform.controllers.profile import make_controller

    labels = tmp_path / "namespace-labels.yaml"
    labels.write_text("a: b\n")
    kube.create(make_profile())
    c = make_controller(
        kube, default_namespace_labels_path=str(labels)
    )
    # Shrink the poll for the test.
    c.runnables = [
        __import__("kubeflow_tpu.platform.controllers.profile", fromlist=["x"])
        .labels_file_watcher(str(labels), poll_seconds=0.05)
    ]
    c.start(kube)
    try:
        deadline = time.time() + 5
        while time.time() < deadline:
            try:
                ns = kube.get(NAMESPACE, "alice")
                break
            except errors.NotFound:
                time.sleep(0.02)
        ns = kube.get(NAMESPACE, "alice")
        assert ns["metadata"]["labels"].get("a") == "b"
        # Change the file; the watcher must re-reconcile and apply new labels.
        time.sleep(0.1)
        labels.write_text("a: b\nc: d\n")
        deadline = time.time() + 5
        while time.time() < deadline:
            ns = kube.get(NAMESPACE, "alice")
            if ns["metadata"]["labels"].get("c") == "d":
                break
            time.sleep(0.02)
        assert ns["metadata"]["labels"].get("c") == "d"
    finally:
        c.stop()


def test_request_counters_incremented(kube):
    from kubeflow_tpu.platform.runtime import metrics

    before = metrics.request_kf.labels(
        component="profile", kind="resourcequota"
    )._value.get()
    kube.create(make_profile(quota={"hard": {"google.com/tpu": "16"}}))
    reconcile(kube)
    after = metrics.request_kf.labels(
        component="profile", kind="resourcequota"
    )._value.get()
    assert after == before + 1
