"""Differential corpus for the JS engine (VERDICT r2 item 4).

Each fixture under ``jscorpus/`` is a standalone script whose companion
``.expected`` file holds the output real ECMAScript semantics produce — the
expectations were written to the spec, NOT captured from this engine, so a
mismatch means the ENGINE is wrong, never the fixture.  The corpus already
caught (and drove fixes for): ECMAScript Number::toString thresholds
(1e21/0.000001/1e-7 formatting), array-destructuring defaults, object rest
+ nested patterns, assignment destructuring, the Error instanceof
hierarchy, JSON.parse raising a JS SyntaxError, JSON.stringify separators
and undefined handling, String.substring argument swap, Array.includes
SameValueZero, URLSearchParams append/has/set-in-place, and URL.origin.
"""
from __future__ import annotations

import glob
import os

import pytest

from kubeflow_tpu.platform.testing.jsdom import run_sandbox_script

CORPUS = os.path.join(os.path.dirname(__file__), "jscorpus")
FIXTURES = sorted(glob.glob(os.path.join(CORPUS, "*.js")))


def _ids():
    return [os.path.basename(f)[:-3] for f in FIXTURES]


def test_corpus_is_present():
    # A glob that silently matches nothing would green-wash the suite.
    assert len(FIXTURES) >= 8


def test_corpus_covers_bundle_language_surface():
    """The coverage contract (VERDICT r3 item 4): every language-level
    construct the five shipped SPA bundles use — syntax node types,
    builtin method calls, builtin-global calls — must be exercised by at
    least one corpus fixture.  A SPA adopting an uncovered construct
    (a new Date format, a URLSearchParams edge, a RegExp method) FAILS
    this test until a spec-written fixture covers it, so the corpus can
    never silently lag the apps it certifies."""
    from kubeflow_tpu.platform.testing.jsinventory import coverage_gaps

    gaps = coverage_gaps(CORPUS)
    assert all(not v for v in gaps.values()), {
        k: sorted(v) for k, v in gaps.items() if v
    }


def test_inventory_extraction_mechanics():
    """inventory() attributes calls correctly: builtin statics, instance
    method names, global constructors, and app-defined names (which must
    NOT create corpus obligations)."""
    from kubeflow_tpu.platform.testing.jsinventory import (
        BUILTIN_GLOBALS,
        inventory,
    )

    inv = inventory("""
      function mine(x) { return x; }
      const helper = (v) => v;
      const obj = { render: mine, fmt(v) { return v; } };
      mine(1); helper(2); obj.render(3); obj.fmt(4);
      JSON.stringify({}); Math.max(1, 2); Date.now();
      const d = new Date(0);
      "abc".includes("a"); [1].map(helper);
      const { a, b: renamed = 1, ...rest } = {};
      for (const [k, v] of Object.entries({})) { if (k) continue; }
    """)
    assert {"JSON.stringify", "Math.max", "Date.now",
            "Object.entries"} <= inv["static_calls"]
    assert {"includes", "map"} <= inv["method_calls"]
    assert "Date" in inv["global_calls"]
    assert {"mine", "helper", "render", "fmt", "obj",
            "a", "renamed", "rest", "d"} <= inv["defined"]
    assert {"ForOf", "If", "Continue", "ObjectPat"} <= inv["node_types"]
    assert "Date" in BUILTIN_GLOBALS


def test_coverage_contract_detects_new_construct(tmp_path):
    """A bundle construct with no fixture must surface as a gap: run the
    gap computation against a corpus copy MISSING the regexp fixture and
    assert the contract would fail."""
    import shutil

    from kubeflow_tpu.platform.testing.jsinventory import coverage_gaps

    reduced = tmp_path / "jscorpus"
    reduced.mkdir()
    for f in FIXTURES:
        if "regexp" not in f:
            shutil.copy(f, reduced / os.path.basename(f))
    gaps = coverage_gaps(str(reduced))
    assert "test" in gaps["method_calls"] or "RegExp" in gaps["global_calls"]


@pytest.mark.parametrize("fixture", FIXTURES, ids=_ids())
def test_corpus_fixture_matches_ecmascript(fixture):
    with open(fixture) as f:
        src = f.read()
    with open(fixture[:-3] + ".expected") as f:
        expected = f.read().splitlines()
    got = run_sandbox_script(src, filename=os.path.basename(fixture))
    assert got == expected, "\n".join(
        f"line {i + 1}: engine={g!r} ecmascript={e!r}"
        for i, (g, e) in enumerate(zip(got, expected))
        if g != e
    ) or f"line count: engine={len(got)} expected={len(expected)}"
