"""Differential corpus for the JS engine (VERDICT r2 item 4).

Each fixture under ``jscorpus/`` is a standalone script whose companion
``.expected`` file holds the output real ECMAScript semantics produce — the
expectations were written to the spec, NOT captured from this engine, so a
mismatch means the ENGINE is wrong, never the fixture.  The corpus already
caught (and drove fixes for): ECMAScript Number::toString thresholds
(1e21/0.000001/1e-7 formatting), array-destructuring defaults, object rest
+ nested patterns, assignment destructuring, the Error instanceof
hierarchy, JSON.parse raising a JS SyntaxError, JSON.stringify separators
and undefined handling, String.substring argument swap, Array.includes
SameValueZero, URLSearchParams append/has/set-in-place, and URL.origin.
"""
from __future__ import annotations

import glob
import os

import pytest

from kubeflow_tpu.platform.testing.jsdom import run_sandbox_script

CORPUS = os.path.join(os.path.dirname(__file__), "jscorpus")
FIXTURES = sorted(glob.glob(os.path.join(CORPUS, "*.js")))


def _ids():
    return [os.path.basename(f)[:-3] for f in FIXTURES]


def test_corpus_is_present():
    # A glob that silently matches nothing would green-wash the suite.
    assert len(FIXTURES) >= 8


@pytest.mark.parametrize("fixture", FIXTURES, ids=_ids())
def test_corpus_fixture_matches_ecmascript(fixture):
    with open(fixture) as f:
        src = f.read()
    with open(fixture[:-3] + ".expected") as f:
        expected = f.read().splitlines()
    got = run_sandbox_script(src, filename=os.path.basename(fixture))
    assert got == expected, "\n".join(
        f"line {i + 1}: engine={g!r} ecmascript={e!r}"
        for i, (g, e) in enumerate(zip(got, expected))
        if g != e
    ) or f"line count: engine={len(got)} expected={len(expected)}"
