"""CI workflow dispatch + conformance suite registration tests
(reference prow_config.yaml:8-40 dispatch, conformance/1.5)."""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

from ci.workflows import WORKFLOWS, select  # noqa: E402


def test_dispatch_table_selects_by_changed_path():
    # The lint lane triggers on ANY kubeflow_tpu change (ISSUE 13), so
    # every component selection now carries it alongside its own lane.
    assert select(["kubeflow_tpu/platform/webhook/mutate.py"]) == [
        "lint", "admission-webhook"
    ]
    got = select(["kubeflow_tpu/platform/controllers/notebook.py"])
    assert "notebook-controller" in got
    assert select(["docs/irrelevant.md"]) == []
    # releasing/* triggers everything presubmit (the reference's
    # releasing/version/* entries).
    everything = select(["releasing/VERSION"])
    assert set(everything) == {
        n for n, wf in WORKFLOWS.items() if "presubmit" in wf.job_types
    }


def test_presubmit_lane_list_is_pinned():
    """The full presubmit lane list, pinned (ISSUE 13): a lane silently
    dropped from the dispatch table is a coverage regression this test
    turns into a loud diff."""
    presubmit = sorted(n for n, wf in WORKFLOWS.items()
                       if "presubmit" in wf.job_types)
    assert presubmit == sorted([
        "notebook-controller", "resilience", "ha-shard", "bench-smoke",
        "tpujob", "inferenceservice", "lint", "journey", "slo",
        "profile", "admission-webhook", "web-apps", "compute", "native",
        "native-wire", "notebook-images", "serve", "activator",
    ])


def test_activator_lane_registered_and_shaped():
    """The activator lane (ISSUE 19): front-door unit matrix (hold/
    replay, QoS admission, the wake staleness race) gates the
    replica-side QoS gates and the noisy-neighbor conformance smoke —
    triggered by activator, serve-plane, and controller changes."""
    assert "activator" in select(["kubeflow_tpu/platform/activator.py"])
    assert "activator" in select(["kubeflow_tpu/models/serve.py"])
    assert "activator" in select(
        ["kubeflow_tpu/platform/controllers/inferenceservice.py"])
    wf = WORKFLOWS["activator"]
    assert [s.name for s in wf.steps] == [
        "unit", "qos-gates", "noisy-neighbor-smoke"]
    unit = " ".join(wf.steps[0].command)
    assert "test_activator.py" in unit and "test_autoscale.py" in unit
    gates = " ".join(wf.steps[1].command)
    assert "test_serve.py" in gates and "test_scheduler.py" in gates
    assert wf.steps[1].depends == "unit"
    smoke = wf.steps[2].command
    assert smoke[-2:] == ["--only", "inferenceservice-noisy-neighbor"]
    assert smoke[1].endswith("conformance/run.py")
    assert wf.steps[2].depends == "unit"


def test_lint_lane_registered_and_shaped():
    """The lint lane (ISSUE 13): triggered by any kubeflow_tpu change,
    kftlint gates on the shipped baseline, and the locktrace tier-1 suite
    rides the same lane."""
    assert "lint" in select(["kubeflow_tpu/platform/runtime/controller.py"])
    assert "lint" in select(["kubeflow_tpu/models/llama.py"])
    wf = WORKFLOWS["lint"]
    assert [s.name for s in wf.steps] == ["kftlint", "lint-unit", "locktrace"]
    kftlint = wf.steps[0].command
    assert kftlint[1:4] == ["-m", "kubeflow_tpu.analysis", "--baseline"]
    baseline_path = os.path.join(REPO, kftlint[4])
    assert os.path.exists(baseline_path)
    # Baseline hygiene: the highest-value contracts carry no debt.
    data = json.load(open(baseline_path))
    assert not {e["rule"] for e in data["findings"]} & {
        "R001", "R003", "R004"}
    assert "test_locktrace.py" in " ".join(wf.steps[2].command)


def test_journey_lane_registered_and_shaped():
    """The journey lane (ISSUE 14): causal-propagation units gate the
    TPUJob merged-journey conformance smoke, triggered by telemetry and
    control-plane changes."""
    assert "journey" in select(["kubeflow_tpu/telemetry/causal.py"])
    assert "journey" in select(
        ["kubeflow_tpu/platform/runtime/controller.py"])
    wf = WORKFLOWS["journey"]
    assert [s.name for s in wf.steps] == ["unit", "journey-smoke"]
    assert "test_causal.py" in " ".join(wf.steps[0].command)
    smoke = wf.steps[1].command
    assert smoke[-2:] == ["--only", "tpujob-train-converge"]
    assert wf.steps[1].depends == "unit"


def test_slo_lane_registered_and_shaped():
    """The slo lane (ISSUE 15): pipeline unit matrices gate the
    autoscaler A/B migration pin, triggered by telemetry and
    control-plane changes."""
    assert "slo" in select(["kubeflow_tpu/telemetry/tsdb.py"])
    assert "slo" in select(
        ["kubeflow_tpu/platform/controllers/inferenceservice.py"])
    wf = WORKFLOWS["slo"]
    assert [s.name for s in wf.steps] == ["unit", "autoscale-ab"]
    unit = " ".join(wf.steps[0].command)
    for piece in ("test_tsdb.py", "test_fleetscrape.py", "test_slo.py",
                  "test_goodput.py"):
        assert piece in unit
    assert "test_autoscale.py" in " ".join(wf.steps[1].command)
    assert wf.steps[1].depends == "unit"


def test_profile_lane_registered_and_shaped():
    """The profile lane (ISSUE 16): profiler + incident unit matrices
    gate the debug-index coverage pin, triggered by telemetry and
    control-plane runtime changes."""
    assert "profile" in select(["kubeflow_tpu/telemetry/profiler.py"])
    assert "profile" in select(
        ["kubeflow_tpu/platform/runtime/flight.py"])
    wf = WORKFLOWS["profile"]
    assert [s.name for s in wf.steps] == ["unit", "observability"]
    unit = " ".join(wf.steps[0].command)
    for piece in ("test_profiler.py", "test_incidents.py"):
        assert piece in unit
    assert "test_observability.py" in " ".join(wf.steps[1].command)
    assert wf.steps[1].depends == "unit"


def test_native_wire_lane_registered_and_shaped():
    """The native-wire lane (ISSUE 18): the library build gates the 3-way
    codec matrix, the filtered sharding suite, and the KF_NATIVE=0
    pure-Python fallback leg — triggered by native, codec, and runtime
    changes."""
    assert "native-wire" in select(["native/wirecodec.cc"])
    assert "native-wire" in select(["kubeflow_tpu/platform/k8s/codec.py"])
    assert "native-wire" in select(
        ["kubeflow_tpu/platform/runtime/informer.py"])
    wf = WORKFLOWS["native-wire"]
    assert [s.name for s in wf.steps] == [
        "build", "matrix", "filtered-sharding", "python-fallback"]
    assert wf.steps[0].command == ["make", "-C", "native"]
    matrix = " ".join(wf.steps[1].command)
    assert "test_wirecodec.py" in matrix and "test_native.py" in matrix
    assert "test_sharding.py" in " ".join(wf.steps[2].command)
    fallback = wf.steps[3].command
    assert fallback[:2] == ["env", "KF_NATIVE=0"]
    assert "test_wirecodec.py" in " ".join(fallback)
    assert all(s.depends == "build" for s in wf.steps[1:])


def test_conformance_is_postsubmit_only():
    assert "conformance" not in select(["kubeflow_tpu/models/llama.py"])
    assert "conformance" in select(
        ["kubeflow_tpu/models/llama.py"], job_type="postsubmit"
    )


def test_argo_manifest_shape():
    wf = WORKFLOWS["notebook-controller"]
    manifest = wf.to_argo()
    assert manifest["kind"] == "Workflow"
    dag = manifest["spec"]["templates"][0]["dag"]["tasks"]
    assert [t["name"] for t in dag] == ["unit", "e2e"]
    assert dag[1]["depends"] == "unit"
    # Every task's command is JSON so the runner template can exec it.
    for t in dag:
        cmd = json.loads(t["arguments"]["parameters"][0]["value"])
        assert isinstance(cmd, list) and cmd


def test_conformance_report_contract(tmp_path):
    # Runs only the two fastest checks to pin the CLI + report contract;
    # ci/run.sh runs the full suite as its own step (not duplicated here).
    report = tmp_path / "report.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "conformance", "run.py"),
         "--report", str(report),
         "--only", "webhook-merge-semantics,crd-version-conversion"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    data = json.loads(report.read_text())
    assert data["passed"] is True
    assert [c["check"] for c in data["checks"]] == [
        "webhook-merge-semantics", "crd-version-conversion"
    ]
    # The full check list is registered even when filtered.
    from conformance.run import CHECKS

    names = {n for n, _ in CHECKS}
    assert {"notebook-spawn-lifecycle", "multi-host-slice",
            "webhook-merge-semantics", "api-authn-authz"} <= names


def test_image_chain_consistency():
    """Every image directory is reachable from the Makefile graph and every
    Dockerfile's default BASE_IMAGE points at an image the Makefile builds
    (a renamed/added image that isn't wired in fails here)."""
    import os
    import re

    images = os.path.join(os.path.dirname(__file__), "..", "..", "images")
    makefile = open(os.path.join(images, "Makefile")).read()
    dirs = sorted(
        d for d in os.listdir(images)
        if os.path.isdir(os.path.join(images, d))
        and os.path.exists(os.path.join(images, d, "Dockerfile"))
        and d not in ("platform", "ci")  # built by their own harness
    )
    for d in dirs:
        assert re.search(rf"^{re.escape(d)}:", makefile, re.M), (
            f"images/{d} has no Makefile target"
        )
        dockerfile = open(os.path.join(images, d, "Dockerfile")).read()
        m = re.search(r"ARG BASE_IMAGE=ghcr.io/kubeflow-tpu/([\w-]+):", dockerfile)
        if m:
            parent = m.group(1)
            assert re.search(rf"^{re.escape(parent)}:", makefile, re.M) or \
                parent == "base", f"images/{d} builds FROM unbuilt {parent}"
    # The TF chain exists as BASELINE config 2 names it.
    assert "jupyter-tensorflow-tpu-full" in dirs


def test_hardware_baselines_lane_emits_and_reports(tmp_path):
    """The hardware lane (BASELINE configs 2-3): workflow emits an Argo
    manifest, is hardware-job-typed (never presubmit), and the script's
    skip path is loud — per-config JSON with a reason, exit 3."""
    import json as _json
    import subprocess
    import sys as _sys

    from ci.workflows import WORKFLOWS

    wf = WORKFLOWS["hardware-baselines"]
    assert wf.job_types == ["hardware"]
    manifest = wf.to_argo()
    assert manifest["kind"] == "Workflow"

    # Force all three runtimes absent so the test is hermetic and fast even
    # on images that DO ship tensorflow/jax.
    repo = os.path.join(os.path.dirname(__file__), "..", "..")
    shim = tmp_path / "shim"
    shim.mkdir()
    for mod in ("tensorflow", "torch_xla", "jax"):
        (shim / mod).mkdir()
        (shim / mod / "__init__.py").write_text(
            "raise ImportError('hermetically absent')\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = f"{shim}:{repo}"
    proc = subprocess.run(
        [_sys.executable, os.path.join(repo, "ci", "hardware_baselines.py")],
        capture_output=True, text=True, env=env, cwd=str(tmp_path),
    )
    assert proc.returncode == 3, proc.stderr
    lines = [_json.loads(l) for l in proc.stdout.splitlines() if l.strip()]
    assert {r["config"] for r in lines} == {2, 3, 4}
    assert all("skipped" in r for r in lines)


def test_hardware_baselines_record_replaces_not_appends(tmp_path):
    """VERDICT r3 item 6: re-running the lane must yield ONE measurement
    block — same-config rows replace prior ones (old blocks, including the
    r3 duplicated pair, are collapsed), other configs' rows carry over."""
    from ci.hardware_baselines import record_in_baseline

    md = tmp_path / "BASELINE.md"
    md.write_text(
        "# Baselines\n\nprose stays\n\n"
        "Hardware lane measurements (2026-07-31, ci/hardware_baselines.py):\n\n"
        '- config 2: tf_resnet50_cifar_images_per_sec = 77.7 ({"device": "cpu/gpu"})\n'
        "\n"
        "Hardware lane measurements (2026-07-31, ci/hardware_baselines.py):\n\n"
        '- config 2: tf_resnet50_cifar_images_per_sec = 77.5 ({"device": "cpu/gpu"})\n'
    )
    record_in_baseline(
        [{"config": 4, "metric": "jax_vit_b16_images_per_sec",
          "value": 900.0, "device": "tpu"}],
        path=str(md),
    )
    text = md.read_text()
    assert text.count("Hardware lane measurements") == 1
    assert "prose stays" in text
    # later duplicate block won; config-2 row carried over exactly once
    assert text.count("config 2:") == 1 and "77.5" in text and "77.7" not in text
    assert "config 4: jax_vit_b16_images_per_sec = 900.0" in text

    # Re-measuring config 4 replaces its row, still one block.
    record_in_baseline(
        [{"config": 4, "metric": "jax_vit_b16_images_per_sec",
          "value": 950.0, "device": "tpu"}],
        path=str(md),
    )
    text = md.read_text()
    assert text.count("Hardware lane measurements") == 1
    assert "950.0" in text and "900.0" not in text
    assert text.count("config 2:") == 1

    # Skip-only runs leave the file untouched.
    before = md.read_text()
    record_in_baseline([{"config": 3, "skipped": "absent"}], path=str(md))
    assert md.read_text() == before


def test_hardware_baselines_vit_smoke_executes(tmp_path):
    """The config-4 JAX lane actually executes (KFT_HWLANE_SMOKE shrinks to
    vit_debug on CPU) and lands a roofline-annotated row in the target
    BASELINE.md."""
    import json as _json
    import subprocess
    import sys as _sys

    repo = os.path.join(os.path.dirname(__file__), "..", "..")
    md = tmp_path / "BASELINE.md"
    md.write_text("# Baselines\n")
    shim = tmp_path / "shim"
    shim.mkdir()
    for mod in ("tensorflow", "torch_xla"):
        (shim / mod).mkdir()
        (shim / mod / "__init__.py").write_text(
            "raise ImportError('hermetically absent')\n")
    env = dict(os.environ)
    env.update(PYTHONPATH=f"{shim}:{repo}", KFT_HWLANE_SMOKE="1",
               KFT_BASELINE_MD=str(md), JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [_sys.executable, os.path.join(repo, "ci", "hardware_baselines.py")],
        capture_output=True, text=True, env=env, cwd=str(tmp_path),
    )
    assert proc.returncode == 3, proc.stderr  # tf/torch_xla still skipped
    rows = [_json.loads(l) for l in proc.stdout.splitlines() if l.strip()]
    vit = next(r for r in rows if r["config"] == 4)
    assert vit["value"] > 0
    assert {"model_tflops_per_sec", "mfu_vs_197tf",
            "model_gflops_per_image"} <= set(vit)
    assert "config 4: jax_vit_b16_images_per_sec" in md.read_text()
