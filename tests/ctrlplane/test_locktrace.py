"""locktrace tier-1 suite (ISSUE 13): planted-violation detection plus
the real control-plane harnesses running clean under the tracer.

The three "suite" tests below are the acceptance pin: the sharding,
jobqueue and chaos harnesses — the same machinery their own suites
hammer — run under ``locktrace.trace()`` with the coordinator's shared
state guarded, and must produce **zero lock-order cycles and zero
unguarded writes**.  The planted fixtures prove the detector is not
vacuous: an ABBA pair and an unguarded write must both fail loudly.
"""
from __future__ import annotations

import logging
import threading
import time

import pytest

from kubeflow_tpu.platform.testing import locktrace
from kubeflow_tpu.platform.testing.locktrace import (
    GuardViolation,
    LockOrderViolation,
)

TTL = 0.5
RENEW = 0.05


# -- planted fixtures ---------------------------------------------------------


def test_planted_abba_single_thread_detected():
    """Lock-order is a class property: one thread taking A->B then B->A
    proves a deadlocking two-thread interleaving exists."""
    with locktrace.trace() as t:
        a, b = threading.Lock(), threading.Lock()
        t.name_lock(a, "A")
        t.name_lock(b, "B")
        with a:
            with b:
                pass
        with b:
            with a:
                pass
    cycles = t.lock_order_cycles()
    assert cycles == [["A", "B"]]
    with pytest.raises(LockOrderViolation) as e:
        t.assert_clean()
    assert "A" in str(e.value) and "B" in str(e.value)


def test_planted_abba_two_threads_detected():
    """The classic: two threads, opposite orders, sequenced so both edges
    are recorded; timeout acquires keep the test itself deadlock-free
    (edges are recorded at acquire *attempt*, exactly so a timed-out
    victim still leaves its evidence)."""
    with locktrace.trace() as t:
        a, b = threading.Lock(), threading.Lock()
        t.name_lock(a, "A")
        t.name_lock(b, "B")
        holding_a, holding_b = threading.Event(), threading.Event()

        def one():
            with a:
                holding_a.set()
                holding_b.wait(2.0)
                if b.acquire(timeout=0.2):  # attempt records A->B
                    b.release()

        def two():
            holding_a.wait(2.0)
            with b:
                holding_b.set()
                if a.acquire(timeout=0.2):  # attempt records B->A
                    a.release()

        t1 = threading.Thread(target=one)
        t2 = threading.Thread(target=two)
        t1.start(), t2.start()
        t1.join(5), t2.join(5)
    assert t.lock_order_cycles() == [["A", "B"]]


def test_same_class_distinct_instance_nesting_is_a_cycle():
    """Two locks born on ONE source line are one class; nesting one
    inside the other (coordA._lock inside coordB._lock) is lockdep's
    same-class rule: only an external order makes it safe, so it reports
    as a self-loop cycle."""
    with locktrace.trace() as t:
        pair = [threading.Lock() for _ in range(2)]  # same creation site
        with pair[0]:
            with pair[1]:
                pass
    cycles = t.lock_order_cycles()
    assert len(cycles) == 1 and len(cycles[0]) == 1, cycles
    with pytest.raises(LockOrderViolation):
        t.assert_clean()


def test_consistent_order_is_clean():
    with locktrace.trace() as t:
        # Distinct creation sites: one line each (same-line locks share a
        # class and same-class nesting is deliberately a violation).
        a = threading.Lock()
        b = threading.Lock()
        c = threading.Lock()
        for _ in range(3):
            with a, b, c:
                pass
    assert t.lock_order_cycles() == []
    t.assert_clean()


def test_planted_unguarded_write_detected():
    with locktrace.trace() as t:
        lock = threading.Lock()
        shared = t.guard({}, lock, "shared-map")
        with lock:
            shared["guarded"] = 1          # fine
        shared["unguarded"] = 2            # violation
        shared.pop("guarded")              # violation
    assert len(t.guard_violations) == 2
    with pytest.raises(GuardViolation) as e:
        t.assert_clean()
    assert "shared-map" in str(e.value)


def test_guarded_writes_from_worker_thread_clean():
    with locktrace.trace() as t:
        lock = threading.Lock()
        shared = t.guard(set(), lock, "shared-set")

        def worker(i):
            with lock:
                shared.add(i)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert len(shared) == 8
    t.assert_clean()


def test_handoff_lock_leaves_no_stale_state():
    """A Lock acquired in thread A and released in thread B (hand-off
    usage) must not leave A a phantom held entry (fabricated edges) nor a
    stale ownership (unguarded writes passing the guard)."""
    with locktrace.trace() as t:
        handoff = threading.Lock()
        t.name_lock(handoff, "H")
        other = threading.Lock()
        t.name_lock(other, "X")
        shared = t.guard({}, handoff, "handoff-guarded")
        released = threading.Event()

        def releaser():
            handoff.release()
            released.set()

        handoff.acquire()
        threading.Thread(target=releaser).start()
        assert released.wait(2.0)
        # Ownership is gone: this write must be a violation.
        shared["after-handoff"] = 1
        # And no phantom H->X edge from the stale TLS entry.
        with other:
            pass
    assert ("H", "X") not in t.edges, t.edges
    assert len(t.guard_violations) == 1


def test_guarded_set_inplace_ops_stay_guarded():
    """`s -= {...}` / `s |= {...}` must mutate through the proxy (and be
    checked), not rebind to a plain unguarded set."""
    with locktrace.trace() as t:
        lock = threading.Lock()
        s = t.guard({1, 2, 3}, lock, "inplace-set")
        with lock:
            s |= {4}
            s -= {1}
        assert set(s) == {2, 3, 4}
        assert isinstance(s, type(t.guard(set(), lock, "probe")))
        s |= {5}  # outside the lock: violation, still guarded
    assert len(t.guard_violations) == 1


def test_condition_event_and_reentrant_rlock_machinery():
    """Condition.wait over a traced RLock must release every recursion
    level (the _release_save trio) and re-acquire cleanly; Event/Queue
    built inside the window ride the patched factories."""
    with locktrace.trace() as t:
        cond = threading.Condition()
        hits = []

        def waiter():
            with cond:
                with cond:  # reentrant: wait() must shed BOTH levels
                    if cond.wait(timeout=2.0):
                        hits.append(1)

        th = threading.Thread(target=waiter)
        th.start()
        time.sleep(0.05)
        with cond:
            cond.notify_all()
        th.join(5)
        assert hits == [1]
        ev = threading.Event()
        ev.set()
        assert ev.wait(0.1)
    t.assert_clean()


# -- the real harnesses, traced ----------------------------------------------


def _guard_coordinator(t, coord):
    """Register the shard coordinator's lock-guarded state — the sets the
    fencing story rests on — so any mutation outside its _lock fails the
    run."""
    coord._owned = t.guard(coord._owned, coord._lock, "coordinator._owned")
    coord._renewed_at = t.guard(coord._renewed_at, coord._lock,
                                "coordinator._renewed_at")
    coord._tokens = t.guard(coord._tokens, coord._lock,
                            "coordinator._tokens")


def _fleet(t, **kw):
    from kubeflow_tpu.platform.testing.shardfleet import ShardedFleet

    fleet = ShardedFleet(lease_seconds=TTL, renew_seconds=RENEW, **kw)
    for r in fleet.replicas:
        _guard_coordinator(t, r.coordinator)
    return fleet


@pytest.fixture(autouse=True)
def _quiet():
    logging.disable(logging.ERROR)
    yield
    logging.disable(logging.NOTSET)


def test_sharding_suite_clean_under_locktrace():
    """The ShardedFleet replica-kill scenario (the sharding suite's
    core): zero lock-order cycles, zero unguarded coordinator writes."""
    with locktrace.trace() as t:
        fleet = _fleet(t, replicas=2, num_shards=4)
        try:
            fleet.wait_stable_shard_map()
            fleet.create_wave(30)
            fleet.kill(1)
            fleet.wait_converged(timeout=90)
            fleet.wait_stable_shard_map()
        finally:
            fleet.close()
    assert len(t.edges) > 0, "tracer saw no lock nesting — vacuous run"
    t.assert_clean()


def test_chaos_suite_clean_under_locktrace():
    """The seeded-storm scenario (the chaos suite's smoke shape) over a
    sharded fleet: fault-path locking (retries, circuit breaker, watch
    re-establishment) must stay cycle-free too."""
    from kubeflow_tpu.platform.testing.chaos import storm

    with locktrace.trace() as t:
        # Sized like the tier-1 chaos smoke (12 objects, bounded
        # injections so the tail is calm); the tracer adds per-acquire
        # bookkeeping and the full suite adds CPU contention, so the
        # convergence deadline carries slack — the pin here is the lock
        # graph, not convergence latency.
        fleet = _fleet(t, replicas=2, num_shards=4,
                       chaos_faults=storm(rate=0.05, max_injections=20),
                       chaos_seed=20260804)
        try:
            fleet.wait_stable_shard_map()
            fleet.create_wave(12)
            fleet.wait_converged(timeout=180)
        finally:
            fleet.close()
    assert len(t.edges) > 0
    t.assert_clean()


def test_jobqueue_suite_clean_under_locktrace():
    """The TPUJob gang/queue path (the jobqueue suite's machinery) over a
    sharded fleet with a replica kill mid-lifecycle: ledger + gang
    teardown/recreate locking stays cycle-free, coordinator state stays
    guarded."""
    from kubeflow_tpu.platform.apis import tpujob as jobapi
    from kubeflow_tpu.platform.controllers import tpujob as jobctrl
    from kubeflow_tpu.platform.k8s.types import TPUJOB

    n = 6
    with locktrace.trace() as t:
        fleet = _fleet(t, replicas=2, num_shards=4, workers=2,
                       controller_factory=jobctrl.make_controller,
                       tpu_nodes=2 * n)

        def all_at(phase, restarts):
            js = fleet.kube.list(TPUJOB, fleet.namespace)
            return len(js) == n and all(
                jobapi.phase_of(j) == phase
                and jobapi.restarts_of(j) == restarts for j in js)

        def wait(pred, what, timeout=90.0):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                if pred():
                    return
                time.sleep(0.05)
            raise TimeoutError(what)

        try:
            fleet.wait_stable_shard_map()
            for i in range(n):
                fleet.kube.create({
                    "apiVersion": "kubeflow.org/v1alpha1", "kind": "TPUJob",
                    "metadata": {"name": f"tj-{i:03d}",
                                 "namespace": fleet.namespace},
                    "spec": {
                        "tpu": {"accelerator": "v5e", "topology": "2x4",
                                "slices": 2},
                        "template": {"spec": {"containers": [
                            {"name": "worker", "image": "trainer"}]}},
                    },
                })
            wait(lambda: all_at("Running", 0), "initial gang converge")
            fleet.kill(0)
            # Preempt one worker of every gang so the survivor runs the
            # heavy teardown/recreate burst — the lock-richest controller
            # path — under the tracer.
            for i in range(n):
                fleet.kube.set_pod_phase(fleet.namespace,
                                         f"tj-{i:03d}-s1-0", "Failed")
            wait(lambda: all_at("Running", 1),
                 "every gang restarted by the survivor", timeout=120.0)
        finally:
            fleet.close()
    assert len(t.edges) > 0
    t.assert_clean()
