import pytest

from kubeflow_tpu.platform.apis import notebook as nbapi
from kubeflow_tpu.platform.controllers.notebook import (
    NotebookReconciler,
    pods_to_notebook_requests,
)
from kubeflow_tpu.platform.k8s import errors
from kubeflow_tpu.platform.k8s.types import (
    NOTEBOOK,
    SERVICE,
    STATEFULSET,
    VIRTUALSERVICE,
    deep_get,
    new,
)
from kubeflow_tpu.platform.runtime import Request
from kubeflow_tpu.platform.testing import FakeKube


def make_notebook(name="nb", ns="user1", tpu=None, annotations=None):
    nb = {
        "apiVersion": "kubeflow.org/v1beta1",
        "kind": "Notebook",
        "metadata": {"name": name, "namespace": ns},
        "spec": {
            "template": {
                "spec": {
                    "containers": [{
                        "name": name,
                        "image": "jupyter-jax-tpu:latest",
                    }]
                }
            }
        },
    }
    if tpu:
        nb["spec"]["tpu"] = tpu
    if annotations:
        nb["metadata"]["annotations"] = annotations
    return nb


@pytest.fixture
def kube():
    k = FakeKube()
    k.add_namespace("user1")
    return k


@pytest.fixture
def reconciler(kube):
    # mirror_min_interval=0: tests reconcile back-to-back and assert on
    # mirrors immediately; the storm throttle is covered separately.
    return NotebookReconciler(kube, use_istio=True, add_fsgroup=True,
                              mirror_min_interval=0)


def reconcile(reconciler, name="nb", ns="user1"):
    reconciler.reconcile(Request(ns, name))


def test_single_host_notebook(kube, reconciler):
    kube.create(make_notebook())
    reconcile(reconciler)
    sts = kube.get(STATEFULSET, "nb", "user1")
    assert deep_get(sts, "spec", "replicas") == 1
    assert deep_get(sts, "spec", "podManagementPolicy") == "Parallel"
    container = deep_get(sts, "spec", "template", "spec", "containers")[0]
    env = {e["name"]: e.get("value") for e in container["env"]}
    assert env["NB_PREFIX"] == "/notebook/user1/nb"
    assert "TPU_TOPOLOGY" not in env
    assert deep_get(sts, "spec", "template", "spec", "securityContext", "fsGroup") == 100
    svc = kube.get(SERVICE, "nb", "user1")
    assert svc["spec"]["selector"] == {"statefulset": "nb"}
    assert svc["spec"]["ports"][0]["port"] == 80
    assert svc["spec"]["ports"][0]["targetPort"] == 8888
    assert svc["spec"]["ports"][0]["name"].startswith("http-")


def test_multi_host_tpu_notebook(kube, reconciler):
    kube.create(make_notebook(tpu={"accelerator": "v5e", "topology": "4x4"}))
    reconcile(reconciler)
    sts = kube.get(STATEFULSET, "nb", "user1")
    # 16 chips / 8 per host = 2 hosts.
    assert deep_get(sts, "spec", "replicas") == 2
    pod = deep_get(sts, "spec", "template", "spec")
    assert pod["nodeSelector"] == {
        "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
        "cloud.google.com/gke-tpu-topology": "4x4",
    }
    container = pod["containers"][0]
    assert container["resources"]["limits"]["google.com/tpu"] == "8"
    env = {e["name"]: e for e in container["env"]}
    assert env["TPU_TOPOLOGY"]["value"] == "4x4"
    assert env["TPU_ACCELERATOR_TYPE"]["value"] == "v5e-16"
    hostnames = env["TPU_WORKER_HOSTNAMES"]["value"].split(",")
    assert hostnames == [
        "nb-0.nb-workers.user1.svc.cluster.local",
        "nb-1.nb-workers.user1.svc.cluster.local",
    ]
    assert env["TPU_WORKER_ID"]["valueFrom"]["fieldRef"]["fieldPath"] == (
        "metadata.labels['apps.kubernetes.io/pod-index']"
    )
    # Worker-0 routing for the UI service.
    svc = kube.get(SERVICE, "nb", "user1")
    assert svc["spec"]["selector"] == {"statefulset.kubernetes.io/pod-name": "nb-0"}
    # Headless service with not-ready publishing for jax.distributed.
    headless = kube.get(SERVICE, "nb-workers", "user1")
    assert headless["spec"]["clusterIP"] == "None"
    assert headless["spec"]["publishNotReadyAddresses"] is True


def test_stop_annotation_scales_to_zero(kube, reconciler):
    kube.create(make_notebook(tpu={"accelerator": "v5e", "topology": "4x4"}))
    reconcile(reconciler)
    nb = kube.get(NOTEBOOK, "nb", "user1")
    nb["metadata"].setdefault("annotations", {})[nbapi.STOP_ANNOTATION] = "2026-07-29"
    kube.update(nb)
    reconcile(reconciler)
    assert deep_get(kube.get(STATEFULSET, "nb", "user1"), "spec", "replicas") == 0
    # Restart: annotation removed → full slice back.
    nb = kube.get(NOTEBOOK, "nb", "user1")
    del nb["metadata"]["annotations"][nbapi.STOP_ANNOTATION]
    kube.update(nb)
    reconcile(reconciler)
    assert deep_get(kube.get(STATEFULSET, "nb", "user1"), "spec", "replicas") == 2


def test_virtual_service(kube, reconciler):
    kube.create(make_notebook())
    reconcile(reconciler)
    vs = kube.get(VIRTUALSERVICE, "notebook-user1-nb", "user1")
    http = vs["spec"]["http"][0]
    assert http["match"][0]["uri"]["prefix"] == "/notebook/user1/nb/"
    assert http["route"][0]["destination"]["host"] == "nb.user1.svc.cluster.local"


def test_status_mirrors_worker0(kube, reconciler):
    kube.create(make_notebook())
    reconcile(reconciler)
    # Simulate the kubelet: create the pod the STS would produce.
    pod = new(
        __import__("kubeflow_tpu.platform.k8s.types", fromlist=["POD"]).POD,
        "nb-0", "user1",
        labels={"statefulset": "nb", "notebook-name": "nb"},
    )
    kube.create(pod)
    kube.set_pod_phase("user1", "nb-0", "Running", ready=True)
    reconcile(reconciler)
    nb = kube.get(NOTEBOOK, "nb", "user1")
    assert nb["status"]["readyReplicas"] == 1
    assert nb["status"]["conditions"][0]["type"] == "Ready"


def test_user_env_and_selectors_preserved(kube, reconciler):
    nb = make_notebook(tpu={"accelerator": "v5e", "topology": "2x4"})
    spec = nb["spec"]["template"]["spec"]
    spec["nodeSelector"] = {"disk": "ssd"}
    spec["containers"][0]["env"] = [{"name": "MY_VAR", "value": "7"}]
    kube.create(nb)
    reconcile(reconciler)
    sts = kube.get(STATEFULSET, "nb", "user1")
    pod = deep_get(sts, "spec", "template", "spec")
    assert pod["nodeSelector"]["disk"] == "ssd"
    assert pod["nodeSelector"]["cloud.google.com/gke-tpu-topology"] == "2x4"
    env = {e["name"] for e in pod["containers"][0]["env"]}
    assert {"MY_VAR", "NB_PREFIX", "TPU_WORKER_HOSTNAMES"} <= env
    # Single-host 2x4: service routes to the statefulset selector.
    svc = kube.get(SERVICE, "nb", "user1")
    assert svc["spec"]["selector"] == {"statefulset": "nb"}
    assert deep_get(sts, "spec", "replicas") == 1


def test_idempotent_reconcile(kube, reconciler):
    kube.create(make_notebook(tpu={"accelerator": "v5e"}))
    reconcile(reconciler)
    sts1 = kube.get(STATEFULSET, "nb", "user1")
    reconcile(reconciler)
    sts2 = kube.get(STATEFULSET, "nb", "user1")
    assert sts1["metadata"]["resourceVersion"] == sts2["metadata"]["resourceVersion"]


def test_deleted_notebook_is_noop(kube, reconciler):
    reconcile(reconciler)  # no error
    with pytest.raises(errors.NotFound):
        kube.get(STATEFULSET, "nb", "user1")


def test_pod_event_mapper():
    pod = {"metadata": {"namespace": "user1", "name": "nb-0",
                        "labels": {"notebook-name": "nb"}}}
    assert pods_to_notebook_requests(pod) == [Request("user1", "nb")]
    assert pods_to_notebook_requests({"metadata": {"labels": {}}}) == []


def test_validation():
    with pytest.raises(nbapi.ValidationError):
        nbapi.validate({"metadata": {"name": "x"}, "spec": {}})
    with pytest.raises(nbapi.ValidationError):
        nbapi.validate(make_notebook(tpu={"accelerator": "v99"}))
    nbapi.validate(make_notebook(tpu={"accelerator": "v5e", "topology": "2x4"}))


def test_namespace_chip_gauge_aggregates(kube, reconciler):
    """Fleet gauges are scrape-time collectors (one list per Prometheus
    scrape, reference metrics.go:22-64 computes notebook_running the same
    way) — values follow the live store with no reconcile needed."""
    from kubeflow_tpu.platform.runtime import metrics

    metrics.register_fleet_collector(kube)
    # The collector is process-global: unhook this test's store afterwards
    # or every later scrape in the session reads a dead fixture.
    try:
        _gauge_test_body(kube)
    finally:
        metrics.register_fleet_collector(None)


def _gauge_test_body(kube):
    from kubeflow_tpu.platform.runtime import metrics

    kube.create(make_notebook("nb-a", tpu={"accelerator": "v5e", "topology": "4x4"}))
    kube.create(make_notebook("nb-b", tpu={"accelerator": "v5e", "topology": "2x4"}))

    def chips():
        return metrics.registry.get_sample_value(
            "tpu_chips_requested", {"namespace": "user1"})

    assert chips() == 24  # 16 + 8
    assert metrics.registry.get_sample_value(
        "notebook_running", {"namespace": "user1"}) == 2
    kube.delete(
        __import__("kubeflow_tpu.platform.k8s.types", fromlist=["NOTEBOOK"]).NOTEBOOK,
        "nb-a", "user1",
    )
    assert chips() == 8


def test_invalid_topology_rejected_at_slice_math():
    import pytest as _pytest

    from kubeflow_tpu.platform.tpu import slice_spec

    with _pytest.raises(ValueError, match="does not pack"):
        slice_spec("v5e", "3x3")


# -- event mirroring (reference notebook_controller.go:94-118, :608-644) ------


def _pod_event(kube, pod_name, ns="user1", reason="FailedScheduling",
               message="0/3 nodes available: insufficient google.com/tpu",
               etype="Warning", count=1):
    return kube.create({
        "apiVersion": "v1",
        "kind": "Event",
        "metadata": {"generateName": f"{pod_name}.", "namespace": ns},
        "involvedObject": {"kind": "Pod", "name": pod_name, "namespace": ns},
        "reason": reason, "message": message, "type": etype, "count": count,
        "firstTimestamp": "2099-01-01T00:00:00Z",
        "lastTimestamp": "2099-01-01T00:00:00Z",
    })


def test_pod_events_mirrored_onto_notebook(kube, reconciler):
    from kubeflow_tpu.platform.k8s.types import EVENT

    kube.create(make_notebook("nb", tpu={"accelerator": "v5e", "topology": "4x4"}))
    reconcile(reconciler)
    _pod_event(kube, "nb-1")
    _pod_event(kube, "other-app-1")  # not this notebook
    reconcile(reconciler)

    mirrored = [
        e for e in kube.list(EVENT, "user1")
        if e["involvedObject"].get("kind") == "Notebook"
        and e.get("reason") == "FailedScheduling"
    ]
    assert len(mirrored) == 1
    ev = mirrored[0]
    assert ev["involvedObject"]["name"] == "nb"
    assert "google.com/tpu" in ev["message"]
    assert ev["type"] == "Warning"
    # Idempotent on re-reconcile: deterministic mirror names dedup.
    reconcile(reconciler)
    again = [
        e for e in kube.list(EVENT, "user1")
        if e["involvedObject"].get("kind") == "Notebook"
        and e.get("reason") == "FailedScheduling"
    ]
    assert len(again) == 1


def test_stale_events_from_before_creation_not_mirrored(kube, reconciler):
    from kubeflow_tpu.platform.k8s.types import EVENT

    ev = _pod_event(kube, "nb-0")
    ev["firstTimestamp"] = ev["lastTimestamp"] = "2000-01-01T00:00:00Z"
    kube.update(ev)
    kube.create(make_notebook("nb"))
    reconcile(reconciler)
    assert not [
        e for e in kube.list(EVENT, "user1")
        if e["involvedObject"].get("kind") == "Notebook"
        and e.get("reason") == "FailedScheduling"
    ]


def test_events_to_notebook_requests_mapper():
    from kubeflow_tpu.platform.controllers.notebook import (
        events_to_notebook_requests,
    )

    def ev(kind, name):
        return {
            "metadata": {"namespace": "user1"},
            "involvedObject": {"kind": kind, "name": name},
        }

    assert events_to_notebook_requests(ev("Pod", "nb-12"))[0].name == "nb"
    assert events_to_notebook_requests(ev("StatefulSet", "nb"))[0].name == "nb"
    assert events_to_notebook_requests(ev("Notebook", "nb")) == []
    assert events_to_notebook_requests(ev("Pod", "no-ordinal-x")) == []


def test_event_mirroring_throttled_between_reconciles(kube):
    # During an event storm every Event triggers a reconcile; the mirror
    # pass must not re-list the namespace each time (O(events^2)).
    r = NotebookReconciler(kube, use_istio=True, mirror_min_interval=3600)
    kube.create(make_notebook("nb"))
    reconcile(r)
    _pod_event(kube, "nb-0")
    reconcile(r)  # within the window: no mirroring pass
    from kubeflow_tpu.platform.k8s.types import EVENT
    mirrored = [e for e in kube.list(EVENT, "user1")
                if e["involvedObject"].get("kind") == "Notebook"
                and e.get("reason") == "FailedScheduling"]
    assert mirrored == []
    r.mirror_min_interval = 0
    reconcile(r)
    mirrored = [e for e in kube.list(EVENT, "user1")
                if e["involvedObject"].get("kind") == "Notebook"
                and e.get("reason") == "FailedScheduling"]
    assert len(mirrored) == 1


def test_recurring_event_count_updates_mirror_in_place(kube, reconciler):
    # A FailedScheduling retry bumps count on the source event; the mirror
    # updates in place instead of minting a new Event per bump.
    from kubeflow_tpu.platform.k8s.types import EVENT

    kube.create(make_notebook("nb"))
    reconcile(reconciler)
    src = _pod_event(kube, "nb-0")
    reconcile(reconciler)
    src["count"] = 7
    src["lastTimestamp"] = "2099-01-01T00:05:00Z"
    kube.update(src)
    reconcile(reconciler)
    mirrored = [e for e in kube.list(EVENT, "user1")
                if e["involvedObject"].get("kind") == "Notebook"
                and e.get("reason") == "FailedScheduling"]
    assert len(mirrored) == 1
    assert mirrored[0]["count"] == 7
    assert mirrored[0]["lastTimestamp"] == "2099-01-01T00:05:00Z"


def test_topology_only_conversion_roundtrip():
    # Partial spec.tpu survives hub->spoke->hub (lossless both ways).
    from kubeflow_tpu.platform.apis import notebook as nbapi

    hub = make_notebook("nb", tpu={"topology": "2x4"})
    spoke = nbapi.convert(hub, "v1")
    assert spoke["metadata"]["annotations"][
        "notebooks.kubeflow.org/tpu-topology"] == "2x4"
    back = nbapi.convert(spoke, "v1beta1")
    assert back["spec"]["tpu"] == {"topology": "2x4"}


def test_multislice_notebook(kube, reconciler):
    """spec.tpu.slices > 1: one StatefulSet per slice (GKE multislice's
    one-job-per-slice layout), per-slice libtpu env, MEGASCALE identity."""
    kube.create(make_notebook(
        tpu={"accelerator": "v5e", "topology": "4x4", "slices": 2}
    ))
    reconcile(reconciler)
    for idx, sts_name in enumerate(["nb", "nb-s1"]):
        sts = kube.get(STATEFULSET, sts_name, "user1")
        # 2 hosts per 4x4 slice, ordinals restarting per slice.
        assert deep_get(sts, "spec", "replicas") == 2
        assert deep_get(sts, "spec", "serviceName") == "nb-workers"
        assert deep_get(sts, "spec", "selector", "matchLabels") == {
            "statefulset": sts_name
        }
        container = deep_get(sts, "spec", "template", "spec", "containers")[0]
        # Chip limit stays per-host.
        assert container["resources"]["limits"]["google.com/tpu"] == "8"
        env = {e["name"]: e for e in container["env"]}
        # libtpu's ICI bootstrap env is per-slice: only this slice's hosts.
        hostnames = env["TPU_WORKER_HOSTNAMES"]["value"].split(",")
        assert hostnames == [
            f"{sts_name}-{i}.nb-workers.user1.svc.cluster.local"
            for i in range(2)
        ]
        assert env["TPU_HOSTS_PER_SLICE"]["value"] == "2"
        assert env["MEGASCALE_SLICE_ID"]["value"] == str(idx)
        assert env["MEGASCALE_NUM_SLICES"]["value"] == "2"
        assert env["MEGASCALE_COORDINATOR_ADDRESS"]["value"] == (
            "nb-0.nb-workers.user1.svc.cluster.local"
        )
    # The PDB protects the whole multislice job across both STSes.
    from kubeflow_tpu.platform.k8s.types import PODDISRUPTIONBUDGET

    pdb = kube.get(PODDISRUPTIONBUDGET, "nb-slice", "user1")
    assert pdb["spec"]["minAvailable"] == 4
    assert pdb["spec"]["selector"]["matchLabels"] == {"notebook-name": "nb"}
    # The headless service spans every slice's pods.
    headless = kube.get(SERVICE, "nb-workers", "user1")
    assert headless["spec"]["selector"] == {"notebook-name": "nb"}


def test_multislice_name_conflict_parks_notebook(kube, reconciler):
    """A sibling notebook legally named <name>-s1 owns that STS name: the
    multislice notebook parks Degraded instead of fighting over it."""
    kube.create(make_notebook("train-s1"))
    reconcile(reconciler, "train-s1")
    kube.create(make_notebook(
        "train", tpu={"accelerator": "v5e", "topology": "4x4", "slices": 2}
    ))
    reconcile(reconciler, "train")
    # The sibling's StatefulSet is untouched (its notebook-name label stands).
    sts = kube.get(STATEFULSET, "train-s1", "user1")
    assert deep_get(sts, "metadata", "labels", "notebook-name") == "train-s1"
    env = deep_get(sts, "spec", "template", "spec", "containers")[0].get("env", [])
    assert not any(e["name"].startswith("MEGASCALE") for e in env)
    # The multislice notebook is parked with a clear condition...
    nb = kube.get(NOTEBOOK, "train", "user1")
    conds = deep_get(nb, "status", "conditions", default=[])
    assert any(c.get("reason") == "SliceNameConflict" for c in conds)
    # ...and NO partial deployment happened: slice 0's STS was never created
    # (it would hold TPU hosts forever at the jax.distributed barrier).
    with pytest.raises(errors.NotFound):
        kube.get(STATEFULSET, "train", "user1")


def test_sibling_slice_named_notebook_events_not_cross_mirrored(kube, reconciler):
    """Events for notebook 'nb-s1' must not be mirrored onto notebook 'nb'."""
    kube.create(make_notebook("nb"))
    kube.create(make_notebook("nb-s1"))
    kube.create({
        "apiVersion": "v1", "kind": "Event",
        "metadata": {"name": "sib-ev", "namespace": "user1"},
        "involvedObject": {"kind": "Pod", "name": "nb-s1-0"},
        "reason": "FailedScheduling", "message": "no TPU", "type": "Warning",
        "lastTimestamp": "2099-01-01T00:00:00Z",
    })
    reconcile(reconciler, "nb")
    from kubeflow_tpu.platform.k8s.types import EVENT

    def mirrors_onto(name):
        return [
            e for e in kube.list(EVENT, "user1")
            if (e.get("involvedObject") or {}).get("kind") == "Notebook"
            and (e.get("involvedObject") or {}).get("name") == name
            and NotebookReconciler.MIRROR_ANNOTATION
            in (deep_get(e, "metadata", "annotations", default={}) or {})
        ]

    assert mirrors_onto("nb") == []
    # But the owner does get it.
    reconcile(reconciler, "nb-s1")
    assert len(mirrors_onto("nb-s1")) == 1


def test_multislice_scale_down_deletes_stale_sts(kube, reconciler):
    kube.create(make_notebook(
        tpu={"accelerator": "v5e", "topology": "4x4", "slices": 3}
    ))
    reconcile(reconciler)
    assert kube.get(STATEFULSET, "nb-s2", "user1")
    nb = kube.get(NOTEBOOK, "nb", "user1")
    nb["spec"]["tpu"]["slices"] = 2
    kube.update(nb)
    reconcile(reconciler)
    assert kube.get(STATEFULSET, "nb-s1", "user1")
    import pytest as _pytest
    from kubeflow_tpu.platform.k8s import errors as _errors

    with _pytest.raises(_errors.NotFound):
        kube.get(STATEFULSET, "nb-s2", "user1")


def test_single_slice_has_no_megascale_env(kube, reconciler):
    kube.create(make_notebook(tpu={"accelerator": "v5e", "topology": "4x4"}))
    reconcile(reconciler)
    sts = kube.get(STATEFULSET, "nb", "user1")
    container = deep_get(sts, "spec", "template", "spec", "containers")[0]
    names = {e["name"] for e in container["env"]}
    assert "MEGASCALE_NUM_SLICES" not in names
    assert "TPU_HOSTS_PER_SLICE" in names


def test_multi_host_slice_gets_pdb(kube, reconciler):
    from kubeflow_tpu.platform.k8s.types import PODDISRUPTIONBUDGET

    kube.create(make_notebook("nb", tpu={"accelerator": "v5e", "topology": "4x4"}))
    reconcile(reconciler)
    pdb = kube.get(PODDISRUPTIONBUDGET, "nb-slice", "user1")
    assert pdb["spec"]["minAvailable"] == 2  # v5e 4x4 = 2 hosts
    assert pdb["spec"]["selector"]["matchLabels"] == {"notebook-name": "nb"}
    # Stopping removes the PDB so drains aren't blocked by an idle slice.
    nb = kube.get(NOTEBOOK, "nb", "user1")
    nb["metadata"].setdefault("annotations", {})[nbapi.STOP_ANNOTATION] = "now"
    kube.update(nb)
    reconcile(reconciler)
    with pytest.raises(errors.NotFound):
        kube.get(PODDISRUPTIONBUDGET, "nb-slice", "user1")


def test_single_host_gets_no_pdb(kube, reconciler):
    from kubeflow_tpu.platform.k8s.types import PODDISRUPTIONBUDGET

    kube.create(make_notebook("nb", tpu={"accelerator": "v5e", "topology": "2x4"}))
    reconcile(reconciler)
    with pytest.raises(errors.NotFound):
        kube.get(PODDISRUPTIONBUDGET, "nb-slice", "user1")


def test_mirror_throttle_survives_controller_restart(kube):
    """Leader failover mid-storm must not re-list namespace events
    unthrottled: a fresh reconciler seeds its window from the durable
    .mirror-pass marker Event (VERDICT r1 item 10)."""
    from kubeflow_tpu.platform.k8s.types import EVENT

    r1 = NotebookReconciler(kube, use_istio=True, mirror_min_interval=3600)
    kube.create(make_notebook("nb"))
    reconcile(r1)  # first pass mirrors and stamps the marker
    marker = kube.get(EVENT, "nb.mirror-pass", "user1")
    # Bookkeeping stays out of user event feeds (they filter by
    # involvedObject, which here is the controller, not the notebook).
    assert marker["involvedObject"]["kind"] == "Controller"

    class CountingClient:
        def __init__(self, inner):
            self._inner = inner
            self.event_lists = 0

        def list(self, gvk, ns=None, **kw):
            if gvk == EVENT:
                self.event_lists += 1
            return self._inner.list(gvk, ns, **kw)

        def __getattr__(self, k):
            return getattr(self._inner, k)

    # "Failover": a fresh reconciler with empty memory is hammered by the
    # storm; the marker (stamped moments ago) must keep it from listing.
    r2 = NotebookReconciler(kube, use_istio=True, mirror_min_interval=3600)
    r2.client = CountingClient(kube)
    _pod_event(kube, "nb-0")
    for _ in range(20):
        reconcile(r2)
    assert r2.client.event_lists == 0


def test_mirror_marker_deleted_with_notebook(kube):
    from kubeflow_tpu.platform.k8s.types import EVENT, NOTEBOOK

    r = NotebookReconciler(kube, use_istio=True, mirror_min_interval=0)
    kube.create(make_notebook("nb"))
    reconcile(r)
    assert kube.get(EVENT, "nb.mirror-pass", "user1")
    kube.delete(NOTEBOOK, "nb", "user1")
    reconcile(r)  # NotFound path cleans the marker
    with pytest.raises(errors.NotFound):
        kube.get(EVENT, "nb.mirror-pass", "user1")


def test_deleted_high_ordinal_pod_event_still_mirrored(kube, reconciler):
    """A scaled-down worker's Warning must keep mirroring even though its
    pod is gone and its ordinal exceeds the current host count (review r5:
    the per-ordinal event fetch dropped these; the STS-prefix lookup must
    not)."""
    from kubeflow_tpu.platform.k8s.types import EVENT

    # 2x4 = single host: only ordinal 0 is expected to exist.
    kube.create(make_notebook("nb", tpu={"accelerator": "v5e", "topology": "2x4"}))
    reconcile(reconciler)
    _pod_event(kube, "nb-5", reason="OOMKilled",
               message="worker 5 OOMKilled during scale-down")
    reconcile(reconciler)
    mirrored = [
        e for e in kube.list(EVENT, "user1")
        if e["involvedObject"].get("kind") == "Notebook"
        and e.get("reason") == "OOMKilled"
    ]
    assert len(mirrored) == 1, "deleted high-ordinal worker event lost"


def test_mirror_via_informer_matches_client_fallback(kube):
    """The informer-backed mirror path (prefix index) mirrors the same
    events as the bare-client fallback path."""
    from kubeflow_tpu.platform.k8s.types import EVENT, POD, STATEFULSET
    from kubeflow_tpu.platform.runtime.informer import Informer
    from kubeflow_tpu.platform.controllers.notebook import (
        _event_involved_index,
        _pod_notebook_index,
    )

    kube.create(make_notebook("nb", tpu={"accelerator": "v5e", "topology": "2x4"}))
    _pod_event(kube, "nb-0")
    _pod_event(kube, "nb-7", reason="OOMKilled", message="gone worker")
    _pod_event(kube, "unrelated-1")
    informers = {
        POD: Informer(kube, POD,
                      indexers={"notebook": _pod_notebook_index}),
        STATEFULSET: Informer(kube, STATEFULSET,
                              indexers={"notebook": _pod_notebook_index}),
        EVENT: Informer(kube, EVENT,
                        indexers={"involved": _event_involved_index}),
    }
    for inf in informers.values():
        inf.start()
        assert inf.wait_for_sync()
    r = NotebookReconciler(kube, use_istio=True, mirror_min_interval=0,
                           informers=informers)
    reconcile(r)
    for inf in informers.values():
        inf.stop()
    mirrored = {
        e.get("reason")
        for e in kube.list(EVENT, "user1")
        if e["involvedObject"].get("kind") == "Notebook"
    }
    assert "FailedScheduling" in mirrored and "OOMKilled" in mirrored


def test_istio_less_controller_has_no_virtualservice_machinery(kube):
    """USE_ISTIO=false must not wire a VirtualService informer or watch:
    the informer's failed cache sync is FATAL at start (unlike the old
    tolerant raw watch), so on a cluster without the Istio CRD an
    istio-less controller would refuse to boot (review r5)."""
    from kubeflow_tpu.platform.controllers.notebook import make_controller
    from kubeflow_tpu.platform.k8s.types import VIRTUALSERVICE

    ctrl = make_controller(kube, use_istio=False)
    assert VIRTUALSERVICE not in ctrl.informers
    assert VIRTUALSERVICE not in ctrl.owns

    ctrl_istio = make_controller(kube, use_istio=True)
    assert VIRTUALSERVICE in ctrl_istio.informers
    assert VIRTUALSERVICE in ctrl_istio.owns
    # Neither controller was started, so the scrape-time collector was
    # never hooked (that happens in Controller.start); nothing to unhook.
