"""Chaos soak: seeded fault storms through ChaosKube, end to end.

The fixed-seed storms here are the repo's repeatable failure injection
(ISSUE 4 tentpole): the notebook + culling controllers and the jupyter
backend all run against a ChaosKube-wrapped apiserver and must CONVERGE
with zero invariant violations —

* no duplicate children (exactly one StatefulSet/Service set per notebook,
  each owned by its notebook),
* no lost status updates (every notebook reports its true replica state),
* no unfrozen cache mutations (informer views value-equal server state),
* no unbounded retry loops (queues drain; dead-letter never fires for
  transient faults, and DOES fire for permanent ones).

Tier 1 runs the small smoke storm; the 60 s soak (http transport: a real
RestKubeClient through HttpKube over a chaotic FakeKube, so retries,
Retry-After, circuit and watch-resume all cross an actual wire) is
``slow``-marked for the nightly lane.
"""
import threading
import time

import pytest

from kubeflow_tpu.platform.k8s import errors
from kubeflow_tpu.platform.k8s.types import (
    NOTEBOOK,
    SERVICE,
    STATEFULSET,
    deep_get,
)
from kubeflow_tpu.platform.testing import ChaosKube, FakeKube, Fault
from kubeflow_tpu.platform.testing.chaos import storm

SEED = 20260804  # fixed, in-repo: every run storms identically


# -- ChaosKube unit behavior --------------------------------------------------


def make_nb(name, ns="fleet"):
    return {
        "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
        "metadata": {"name": name, "namespace": ns},
        "spec": {
            "tpu": {"accelerator": "v5e", "topology": "2x4"},
            "template": {"spec": {"containers": [
                {"name": "notebook", "image": "jupyter-jax"}]}},
        },
    }


def test_chaos_schedule_is_deterministic():
    """Same seed + same call sequence → identical fault log."""
    def run():
        kube = FakeKube()
        kube.add_namespace("ns")
        chaos = ChaosKube(kube, storm(rate=0.3), seed=SEED)
        for i in range(40):
            try:
                chaos.create(make_nb(f"nb-{i}", "ns"))
            except errors.ApiError:
                pass
            try:
                chaos.list(NOTEBOOK, "ns")
            except errors.ApiError:
                pass
        return list(chaos.fault_log)

    log1, log2 = run(), run()
    assert log1 == log2
    assert log1, "a 30% storm over 80 calls must inject something"


def test_chaos_respects_verb_and_kind_selectors():
    kube = FakeKube()
    kube.add_namespace("ns")
    chaos = ChaosKube(kube, [
        Fault("503", 1.0, verbs=frozenset({"create"}),
              kinds=frozenset({"Notebook"})),
    ], seed=1)
    # Wrong kind: never faulted.
    chaos.create({"apiVersion": "v1", "kind": "Namespace",
                  "metadata": {"name": "other"}})
    # Wrong verb: never faulted.
    assert chaos.list(NOTEBOOK, "ns") == []
    with pytest.raises(errors.ServiceUnavailable):
        chaos.create(make_nb("nb", "ns"))
    assert chaos.injected() == 1
    assert chaos.fault_log == [("create", "503", "Notebook")]


def test_chaos_max_injections_bounds_a_fault():
    kube = FakeKube()
    kube.add_namespace("ns")
    chaos = ChaosKube(kube, [Fault("500", 1.0, max_injections=2)], seed=1)
    for i in range(2):
        with pytest.raises(errors.InternalError):
            chaos.list(NOTEBOOK, "ns")
    assert chaos.list(NOTEBOOK, "ns") == []  # storm exhausted
    assert chaos.injected("500") == 2


def test_chaos_pause_stops_injection():
    kube = FakeKube()
    kube.add_namespace("ns")
    chaos = ChaosKube(kube, [Fault("500", 1.0)], seed=1)
    with pytest.raises(errors.InternalError):
        chaos.list(NOTEBOOK, "ns")
    chaos.pause()
    assert chaos.list(NOTEBOOK, "ns") == []


# -- the soak harness ---------------------------------------------------------


class ChaosHarness:
    """Notebook + culling controllers (shared informer) against a chaotic
    apiserver, with a kubelet sim bringing worker pods up on the REAL
    (non-chaotic) store — the cluster itself is healthy, only the
    apiserver path flakes."""

    def __init__(self, chaos_client, kube, *, idle_minutes=1e9):
        from kubeflow_tpu.platform.controllers import culling
        from kubeflow_tpu.platform.controllers.notebook import make_controller
        from kubeflow_tpu.platform.k8s.types import NOTEBOOK as NB

        self.kube = kube
        self.client = chaos_client
        self.ctrl = make_controller(chaos_client, use_istio=False)
        self.ctrl.workers = 4
        self.cull = culling.make_controller(
            chaos_client,
            notebook_informer=self.ctrl.informers.get(NB),
            prober=lambda url: [{"execution_state": "busy"}],
            idle_minutes=idle_minutes,
            check_period_minutes=0.02,
        )
        self._stop = threading.Event()
        self._kubelet = threading.Thread(target=self._kubelet_loop,
                                         daemon=True)
        self._kubelet.start()
        self.ctrl.start(chaos_client)
        self.cull.start(chaos_client)

    def close(self):
        self._stop.set()
        self.cull.stop()
        self.ctrl.stop()
        self._kubelet.join(timeout=5)

    def _kubelet_loop(self):
        from kubeflow_tpu.platform.k8s.types import deep_get as dg

        acked = {}
        for _etype, sts in self.kube.watch(STATEFULSET, "fleet",
                                           stop=self._stop):
            name = sts["metadata"]["name"]
            replicas = dg(sts, "spec", "replicas", default=0)
            if acked.get(name) == replicas or not replicas:
                continue
            tmpl = dg(sts, "spec", "template")
            for i in range(replicas):
                pod = {
                    "apiVersion": "v1", "kind": "Pod",
                    "metadata": {
                        "name": f"{name}-{i}", "namespace": "fleet",
                        "labels": dict(dg(tmpl, "metadata", "labels",
                                          default={}) or {}),
                    },
                    "spec": dg(tmpl, "spec"),
                }
                try:
                    self.kube.create(pod)
                except errors.AlreadyExists:
                    pass
                try:
                    self.kube.set_pod_phase("fleet", f"{name}-{i}",
                                            "Running", ready=True)
                except errors.ApiError:
                    pass
            acked[name] = replicas

    def wait_converged(self, n, timeout):
        """Every notebook fully ready, queues drained."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            nbs = self.kube.list(NOTEBOOK, "fleet")
            ready = [
                nb for nb in nbs
                if deep_get(nb, "status", "replicas", default=0)
                and deep_get(nb, "status", "readyReplicas", default=-1)
                == deep_get(nb, "status", "replicas", default=0)
            ]
            if (len(ready) >= n and self.ctrl.queue.pending() == 0):
                return True
            time.sleep(0.05)
        return False

    # -- invariants ----------------------------------------------------------

    def assert_invariants(self, n):
        nbs = self.kube.list(NOTEBOOK, "fleet")
        assert len(nbs) == n
        stses = self.kube.list(STATEFULSET, "fleet")
        svcs = self.kube.list(SERVICE, "fleet")

        # No duplicate children: exactly one STS per notebook, owned by it.
        by_owner = {}
        for sts in stses:
            refs = [r for r in sts["metadata"].get("ownerReferences", [])
                    if r.get("kind") == "Notebook"]
            assert len(refs) == 1, f"{sts['metadata']['name']}: owners {refs}"
            by_owner.setdefault(refs[0]["name"], []).append(
                sts["metadata"]["name"])
        for nb in nbs:
            name = nb["metadata"]["name"]
            assert by_owner.get(name) == [name], (
                f"{name}: duplicate/missing slice STS {by_owner.get(name)}")
        # Two services per notebook (user-facing + headless), no extras.
        svc_names = sorted(s["metadata"]["name"] for s in svcs)
        want = sorted(sum((([nb["metadata"]["name"],
                             nb["metadata"]["name"] + "-workers"])
                           for nb in nbs), []))
        assert svc_names == want

        # No lost status updates: status matches pod reality.
        for nb in nbs:
            assert deep_get(nb, "status", "readyReplicas") == \
                deep_get(nb, "status", "replicas"), nb["metadata"]["name"]

        # No unfrozen cache mutations: the informer's view of every
        # notebook is value-equal to the server's object.
        informer = self.ctrl.informers.get(NOTEBOOK)
        if informer is not None and informer.has_synced:
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                server = {nb["metadata"]["name"]: nb for nb in
                          self.kube.list(NOTEBOOK, "fleet")}
                cached = {name: informer.get(name, "fleet")
                          for name in server}
                if all(cached[k] is not None and dict(cached[k]) == server[k]
                       for k in server):
                    break
                time.sleep(0.05)  # watch deltas still propagating
            for name, obj in server.items():
                view = informer.get(name, "fleet")
                assert view is not None, f"{name} missing from cache"
                assert dict(view) == obj, f"{name}: cache != server"

        # No unbounded retry loops: nothing parked for transient faults.
        assert not self.ctrl.dead_letters
        assert not self.cull.dead_letters


@pytest.fixture
def fleet_kube():
    kube = FakeKube()
    kube.add_namespace("fleet")
    # 16 single-host 2x4 nodes = 16 slice slots: the 8-gang x 2-slice
    # tpujob storm fits whole (gang admission is capacity-gated now).
    for i in range(16):
        kube.add_tpu_node(f"tpu-node-{i + 1}", topology="2x4")
    return kube


def test_smoke_storm_converges_with_invariants(fleet_kube):
    """Tier-1 chaos smoke: a small seeded storm (bounded injections so
    the tail is calm) over the in-memory transport; the fleet must
    converge with every invariant intact and the storm must have
    actually stormed."""
    chaos = ChaosKube(fleet_kube,
                      storm(rate=0.08, max_injections=40), seed=SEED)
    h = ChaosHarness(chaos, fleet_kube)
    n = 12
    try:
        for i in range(n):
            fleet_kube.create(make_nb(f"nb-{i:03d}"))
        assert h.wait_converged(n, timeout=90.0), (
            f"fleet unconverged under storm; queue depth "
            f"{h.ctrl.queue.pending()}, faults {dict(h.client.calls)}")
        chaos.pause()
        h.assert_invariants(n)
    finally:
        h.close()
    assert chaos.injected() > 0, "the storm never stormed"


def make_tpujob(name, ns="fleet"):
    return {
        "apiVersion": "kubeflow.org/v1alpha1", "kind": "TPUJob",
        "metadata": {"name": name, "namespace": ns},
        "spec": {
            "tpu": {"accelerator": "v5e", "topology": "2x4", "slices": 2},
            "template": {"spec": {"containers": [
                {"name": "worker", "image": "trainer"}]}},
        },
    }


def test_tpujob_storm_converges_with_invariants(fleet_kube):
    """The TPUJob controller under the same seeded storm contract as the
    notebook fleet (ISSUE 10): every gang converges to Running with

    * no duplicate gangs — exactly one StatefulSet per slice per job, all
      of ONE generation, each owned by its job,
    * no lost status — per-slice ready counts match pod reality,
    * no spurious restarts — transient apiserver faults are retried, they
      must NEVER be read as worker failures and condemn a healthy gang,
    * no dead-letters on transient faults.
    """
    from kubeflow_tpu.platform.apis import tpujob as jobapi
    from kubeflow_tpu.platform.controllers import tpujob as jobctrl
    from kubeflow_tpu.platform.k8s.types import TPUJOB
    from kubeflow_tpu.platform.testing.jobsim import TpuJobGangSim

    chaos = ChaosKube(fleet_kube,
                      storm(rate=0.08, max_injections=40), seed=SEED)
    sim = TpuJobGangSim(fleet_kube, "fleet")  # kubelet only: pods run
    ctrl = jobctrl.make_controller(chaos)
    ctrl.workers = 4
    # A gang reconcile touches more faultable calls than a notebook's, so
    # one key can absorb enough consecutive injections to ride the 30 s
    # backoff ceiling past the convergence window.  Swap in a queue built
    # with a bounded ceiling (the native engine bakes max_delay in at
    # construction — poking a _max attribute is a silent no-op): retries
    # and their invariants still happen, the wait between them just can't
    # wait out the deadline.
    from kubeflow_tpu.platform.runtime.controller import make_workqueue

    ctrl.queue = make_workqueue(base_delay=0.05, max_delay=2.0)
    ctrl.start(chaos)
    n = 8
    try:
        for i in range(n):
            fleet_kube.create(make_tpujob(f"tj-{i:03d}"))
        # Slack over the ~35 s typical converge: under full-suite CPU
        # contention the storm's retry backoffs stretch, and a dead-letter
        # revival may need a resync tick — the pin is the invariant set
        # below, not convergence latency.
        deadline = time.monotonic() + 150.0
        while time.monotonic() < deadline:
            jobs = fleet_kube.list(TPUJOB, "fleet")
            if (len(jobs) == n
                    and all(jobapi.phase_of(j) == "Running" for j in jobs)
                    and ctrl.queue.pending() == 0):
                break
            time.sleep(0.05)
        chaos.pause()
        jobs = fleet_kube.list(TPUJOB, "fleet")
        assert all(jobapi.phase_of(j) == "Running" for j in jobs), (
            f"gangs unconverged under storm: "
            f"{[(j['metadata']['name'], jobapi.phase_of(j)) for j in jobs]}"
            f"; queue depth {ctrl.queue.pending()}")
        stses = fleet_kube.list(STATEFULSET, "fleet")
        by_job = {}
        for sts in stses:
            labels = sts["metadata"].get("labels", {})
            refs = [r for r in sts["metadata"].get("ownerReferences", [])
                    if r.get("kind") == "TPUJob"]
            assert len(refs) == 1, sts["metadata"]["name"]
            by_job.setdefault(refs[0]["name"], []).append(
                (sts["metadata"]["name"], labels.get("tpujob-generation")))
        for job in jobs:
            name = job["metadata"]["name"]
            # No duplicate gangs: exactly the two slice STSes, one
            # generation across them, and it is generation 0 — the storm
            # never condemned a healthy gang.
            assert sorted(by_job.get(name, [])) == [
                (name, "0"), (f"{name}-s1", "0")], by_job.get(name)
            assert jobapi.restarts_of(job) == 0, job.get("status")
            assert deep_get(job, "status", "slices") == [
                {"slice": 0, "ready": 1, "total": 1},
                {"slice": 1, "ready": 1, "total": 1},
            ], job.get("status")
        assert not ctrl.dead_letters
    finally:
        ctrl.stop()
        sim.close()
    assert chaos.injected() > 0, "the storm never stormed"


def test_inferenceservice_storm_scale_converges(fleet_kube):
    """The InferenceService controller under the seeded storm contract
    (ISSUE 12, the `-k inferenceservice` presubmit lane): six services
    ride a traffic wave — scale 1→4 on deep scraped queues, then drain
    back to 1 when the queues empty — with

    * exactly ONE revision Deployment per service, owned by it (a storm
      retry must never leave a duplicate revision standing),
    * status matching pod reality at the end,
    * zero dead-letters on transient faults.
    """
    from kubeflow_tpu.platform.controllers import (
        inferenceservice as svcctrl,
    )
    from kubeflow_tpu.platform.k8s.types import DEPLOYMENT, INFERENCESERVICE
    from kubeflow_tpu.platform.runtime.controller import make_workqueue
    from kubeflow_tpu.platform.testing.servesim import InferenceFleetSim

    traffic = {"queue_depth": 16.0}

    def scraper(url):
        # sim://<svc>/<ordinal>/(metrics|readyz) — one synthetic page per
        # replica, driven by the shared traffic knob.
        if url.endswith("/readyz"):
            return '{"ready": true}'
        return (f"serve_queue_depth {traffic['queue_depth']}\n"
                'generate_requests_total{outcome="ok"} 100\n')

    chaos = ChaosKube(fleet_kube,
                      storm(rate=0.08, max_injections=40), seed=SEED)
    sim = InferenceFleetSim(
        fleet_kube, "fleet",
        endpoint_for=lambda svc, rev, i: f"sim://{svc}/{rev}/{i}")
    ctrl = svcctrl.make_controller(chaos, scraper=scraper,
                                   sync_period=0.1)
    ctrl.workers = 4
    ctrl.queue = make_workqueue(base_delay=0.05, max_delay=2.0)
    ctrl.start(chaos)
    n = 6

    def statuses():
        return [s.get("status") or {}
                for s in fleet_kube.list(INFERENCESERVICE, "fleet")]

    def wait(fn, what, timeout=90.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if fn():
                return
            time.sleep(0.05)
        summary = [(s.get("phase"), s.get("replicas"),
                    s.get("readyReplicas")) for s in statuses()]
        raise AssertionError(
            f"inferenceservice storm: timed out on {what}: {summary}")

    try:
        for i in range(n):
            fleet_kube.create({
                "apiVersion": "kubeflow.org/v1alpha1",
                "kind": "InferenceService",
                "metadata": {"name": f"svc-{i:02d}", "namespace": "fleet"},
                "spec": {
                    "model": "llama_125m",
                    "tpu": {"accelerator": "v5e", "topology": "2x4"},
                    "replicas": {"min": 1, "max": 4, "initial": 1},
                    "scale": {"queueDepthTarget": 4.0,
                              "cooldownSeconds": 0.05},
                },
            })
        # The wave: deep queues scale every service to its ceiling.
        wait(lambda: len(statuses()) == n and all(
            s.get("replicas") == 4 and s.get("readyReplicas") == 4
            and s.get("phase") == "Ready" for s in statuses()),
            "scale-up to 4/4 Ready")
        # Traffic drains: every service steps back down to its floor.
        traffic["queue_depth"] = 0.0
        wait(lambda: all(s.get("replicas") == 1 and
                         s.get("readyReplicas") == 1
                         for s in statuses()),
             "scale-down to 1")
        chaos.pause()
        # Exactly one revision Deployment per service, owned by it.
        deps = [d for d in fleet_kube.list(DEPLOYMENT, "fleet")
                if deep_get(d, "metadata", "labels",
                            "inferenceservice-name")]
        assert len(deps) == n, [d["metadata"]["name"] for d in deps]
        for d in deps:
            refs = [r for r in d["metadata"].get("ownerReferences", [])
                    if r.get("kind") == "InferenceService"]
            assert len(refs) == 1, d["metadata"]["name"]
            assert d["metadata"]["name"] == f"{refs[0]['name']}-v1"
            assert deep_get(d, "spec", "replicas") == 1
        assert not ctrl.dead_letters
        assert not sim.errors, sim.errors
    finally:
        ctrl.stop()
        sim.close()
    assert chaos.injected() > 0, "the storm never stormed"


def test_permanent_fault_dead_letters_instead_of_hot_looping(fleet_kube):
    """Acceptance: dead-letter fires for PERMANENT faults — with STS
    creation 100% broken, the notebook key parks with a terminal
    ReconcileFailed condition instead of retrying forever."""
    chaos = ChaosKube(fleet_kube, [
        Fault("500", 1.0, verbs=frozenset({"create"}),
              kinds=frozenset({"StatefulSet"})),
    ], seed=SEED)
    from kubeflow_tpu.platform.controllers.notebook import make_controller

    ctrl = make_controller(chaos, use_istio=False)
    ctrl.max_retries = 3
    try:
        ctrl.queue._base = 0.001
    except AttributeError:
        pass
    ctrl.start(chaos)
    try:
        fleet_kube.create(make_nb("doomed"))
        deadline = time.monotonic() + 30.0
        while not ctrl.dead_letters and time.monotonic() < deadline:
            time.sleep(0.02)
        assert ctrl.dead_letters, "permanent fault never dead-lettered"
        nb = fleet_kube.get(NOTEBOOK, "doomed", "fleet")
        conds = {c["type"] for c in
                 deep_get(nb, "status", "conditions", default=[])}
        assert "ReconcileFailed" in conds
        # Bounded: the broken create was attempted ~(1 + max_retries)
        # times plus event-driven revivals, not a hot loop.
        assert chaos.injected("500") <= 10
    finally:
        ctrl.stop()


def test_jupyter_backend_degrades_under_storm(fleet_kube):
    """The jupyter backend under the same storm: every response is a
    clean envelope — 2xx (possibly ``degraded``), a mapped apiserver
    error, or 503/429 with Retry-After.  Never a raw 500 'internal
    error'."""
    from kubeflow_tpu.platform.apps.jupyter.app import create_app
    from kubeflow_tpu.platform.web.crud_backend import AuthContext

    chaos = ChaosKube(fleet_kube, storm(rate=0.25), seed=SEED)
    app = create_app(chaos, auth=AuthContext(disable_auth=True),
                     secure_cookies=False)
    from werkzeug.test import Client
    import json as _json

    c = Client(app)
    saw_failure = False
    for _ in range(60):
        resp = c.get("/api/namespaces/fleet/notebooks")
        payload = _json.loads(resp.get_data(as_text=True))
        if resp.status_code == 200:
            assert payload["success"] is True
        else:
            saw_failure = True
            assert payload["success"] is False
            assert payload["log"] != "internal error", (
                "an injected transient fault leaked as a raw 500")
            if resp.status_code in (429, 503):
                assert resp.headers.get("Retry-After")
    assert chaos.injected() > 0
    assert saw_failure, "a 25% storm over 60 requests should fail some"


@pytest.mark.slow
def test_soak_60s_http_transport_storm(fleet_kube):
    """The full-stack soak: RestKubeClient (retries, Retry-After,
    circuit, finite timeouts) over real HTTP against HttpKube serving a
    chaotic store, 60 s of churn + storm, then quiesce and assert every
    invariant.  Watch drops sever real chunked streams mid-flight."""
    from kubeflow_tpu.platform.k8s.client import RestKubeClient
    from kubeflow_tpu.platform.testing.httpkube import HttpKubeServer

    chaos = ChaosKube(fleet_kube, storm(rate=0.05), seed=SEED)
    server = HttpKubeServer(chaos).start()
    client = RestKubeClient(server.base_url, qps=0, retries=3,
                            retry_base=0.02, retry_cap=0.5,
                            breaker_threshold=8, breaker_cooldown=0.2)
    client.WATCH_TIMEOUT_SECONDS = 5  # many resume windows per soak
    h = ChaosHarness(client, fleet_kube)
    n = 20
    try:
        for i in range(n):
            fleet_kube.create(make_nb(f"nb-{i:03d}"))
        # 60 s of churn under storm: annotation touches + stop/start
        # toggles through the CHAOTIC client (writes see 409/503/429).
        t_end = time.monotonic() + 60.0
        import random as _random

        rng = _random.Random(SEED)
        touches = 0
        while time.monotonic() < t_end:
            name = f"nb-{rng.randrange(n):03d}"
            try:
                nb = fleet_kube.get(NOTEBOOK, name, "fleet")
                nb["metadata"].setdefault("annotations", {})[
                    "touch"] = str(touches)
                fleet_kube.update(nb)
                touches += 1
            except errors.ApiError:
                pass
            time.sleep(0.05)
        chaos.pause()  # quiesce: let the fleet converge cleanly
        assert h.wait_converged(n, timeout=120.0), (
            f"fleet unconverged after soak; queue depth "
            f"{h.ctrl.queue.pending()}")
        h.assert_invariants(n)
        assert chaos.injected() > 50, dict(h.client.calls)
    finally:
        h.close()
        server.stop()
