"""Race-detection stress tier (SURVEY.md §5: the reference runs `go test`
without -race and leans on controller-runtime's single-reconciler-per-key
model; VERDICT r1 called out that this repo had no -race-equivalent).

These tests hammer the concurrency-bearing pieces from many threads and
assert the invariants the platform's safety story rests on:

* workqueue (both engines): per-key mutual exclusion between get() and
  done(), no lost keys, clean shutdown under fire;
* Controller with workers > 1: one reconcile per key at a time, ever;
* FakeKube: concurrent mutators never corrupt the store (RVs advance,
  typed errors only, watchers see a coherent stream).
"""
from __future__ import annotations

import random
import threading
import time
from collections import defaultdict

import pytest

from kubeflow_tpu.platform import native
from kubeflow_tpu.platform.k8s import errors
from kubeflow_tpu.platform.k8s.types import NOTEBOOK
from kubeflow_tpu.platform.runtime import Request
from kubeflow_tpu.platform.runtime.controller import (
    Controller,
    Reconciler,
    _WorkQueue,
)
from kubeflow_tpu.platform.testing import FakeKube

pytestmark = pytest.mark.slow

N_KEYS = 24
N_PRODUCERS = 6
N_CONSUMERS = 4
DURATION_S = 1.5


def _queues():
    qs = [lambda: _WorkQueue(base_delay=0.001, max_delay=0.01)]
    if native.available():
        qs.append(lambda: native.NativeWorkQueue(
            base_delay=0.001, max_delay=0.01))
    return qs


@pytest.mark.parametrize("make_q", _queues(),
                         ids=lambda f: "native" if "Native" in repr(f()) or
                         type(f()).__name__ == "NativeWorkQueue" else "python")
def test_workqueue_per_key_exclusion_under_fire(make_q):
    q = make_q()
    keys = [Request("ns", f"nb-{i}") for i in range(N_KEYS)]
    in_flight = defaultdict(int)
    max_in_flight = defaultdict(int)
    processed = defaultdict(int)
    lock = threading.Lock()
    stop = threading.Event()
    violations = []

    def producer(seed):
        rng = random.Random(seed)
        while not stop.is_set():
            r = rng.choice(keys)
            if rng.random() < 0.2:
                q.add_rate_limited(r)
            else:
                q.add(r, delay=rng.choice([0.0, 0.0, 0.002]))
            if rng.random() < 0.1:
                q.forget(r)

    def consumer():
        while True:
            r = q.get(timeout=0.05)
            if r is None:
                if stop.is_set():
                    return
                continue
            with lock:
                in_flight[r] += 1
                max_in_flight[r] = max(max_in_flight[r], in_flight[r])
                if in_flight[r] > 1:
                    violations.append(r)
            time.sleep(random.random() * 0.002)  # hold the key briefly
            with lock:
                in_flight[r] -= 1
                processed[r] += 1
            q.done(r)

    producers = [threading.Thread(target=producer, args=(i,), daemon=True)
                 for i in range(N_PRODUCERS)]
    consumers = [threading.Thread(target=consumer, daemon=True)
                 for _ in range(N_CONSUMERS)]
    for t in producers + consumers:
        t.start()
    time.sleep(DURATION_S)
    stop.set()
    for t in producers:
        t.join(timeout=5)
    for t in consumers:
        t.join(timeout=5)
    q.shut_down()
    assert not violations, f"concurrent reconcile of keys {set(violations)}"
    # Every key was hammered; every key must have been processed.
    assert len(processed) == N_KEYS
    assert all(v == 1 for v in max_in_flight.values())


@pytest.mark.parametrize("workers", [4, 8])
def test_controller_workers_gt_one_single_reconciler_per_key(workers):
    """A multi-worker controller under an event storm: the queue's
    exclusion must make concurrent same-key reconciles impossible at ANY
    worker count — multi-worker is now the DEFAULT dispatch mode
    (CONTROLLER_WORKERS), so this invariant is load-bearing, not
    theoretical."""
    kube = FakeKube()
    kube.add_namespace("ns")

    lock = threading.Lock()
    in_flight = defaultdict(int)
    violations = []
    counts = defaultdict(int)

    class Probe(Reconciler):
        def reconcile(self, req):
            with lock:
                in_flight[req] += 1
                if in_flight[req] > 1:
                    violations.append(req)
            time.sleep(random.random() * 0.003)
            with lock:
                in_flight[req] -= 1
                counts[req] += 1
            return None

    ctrl = Controller("stress", Probe(), primary=NOTEBOOK, workers=workers)
    assert ctrl.workers == workers
    ctrl.start(kube)
    try:
        # Storm: create/update/delete a handful of notebooks repeatedly.
        names = [f"nb-{i}" for i in range(8)]
        for name in names:
            kube.create({
                "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
                "metadata": {"name": name, "namespace": "ns"},
                "spec": {"template": {"spec": {"containers": [
                    {"name": name, "image": "i"}]}}},
            })
        deadline = time.monotonic() + DURATION_S
        rng = random.Random(0)
        while time.monotonic() < deadline:
            name = rng.choice(names)
            try:
                nb = kube.get(NOTEBOOK, name, "ns")
                nb["metadata"]["annotations"] = {"touch": str(rng.random())}
                kube.update(nb)
            except errors.ApiError:
                pass
        time.sleep(0.3)  # drain
    finally:
        ctrl.stop()
    assert not violations, f"concurrent reconcile of {set(violations)}"
    assert len(counts) == 8 and all(c >= 1 for c in counts.values())


def test_fakekube_concurrent_mutators_keep_store_coherent():
    kube = FakeKube()
    kube.add_namespace("ns")
    stop = threading.Event()
    failures = []
    created = defaultdict(int)
    deleted = defaultdict(int)

    def mutator(tid):
        rng = random.Random(tid)
        while not stop.is_set():
            name = f"nb-{tid}-{rng.randrange(4)}"
            obj = {
                "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
                "metadata": {"name": name, "namespace": "ns"},
                "spec": {"template": {"spec": {"containers": [
                    {"name": name, "image": "i"}]}}},
            }
            op = rng.random()
            try:
                if op < 0.4:
                    kube.create(obj)
                    created[name] += 1
                elif op < 0.7:
                    cur = kube.get(NOTEBOOK, name, "ns")
                    cur["metadata"]["annotations"] = {"t": str(rng.random())}
                    kube.update(cur)
                elif op < 0.9:
                    kube.delete(NOTEBOOK, name, "ns")
                    deleted[name] += 1
                else:
                    kube.list(NOTEBOOK, "ns")
            except (errors.NotFound, errors.Conflict):
                pass  # expected races
            except Exception as e:  # noqa: BLE001 — the assertion target
                failures.append(repr(e))
                return

    watcher_events = []
    watch_stop = threading.Event()

    def watcher():
        for etype, obj in kube.watch(NOTEBOOK, "ns", stop=watch_stop):
            watcher_events.append((etype, obj["metadata"]["name"]))

    wt = threading.Thread(target=watcher, daemon=True)
    wt.start()
    threads = [threading.Thread(target=mutator, args=(i,), daemon=True)
               for i in range(8)]
    for t in threads:
        t.start()
    time.sleep(DURATION_S)
    stop.set()
    for t in threads:
        t.join(timeout=5)
    watch_stop.set()
    wt.join(timeout=5)

    assert failures == [], failures
    # Store is coherent: every remaining object gets/lists cleanly and the
    # per-key create/delete balance matches what survived.
    remaining = {o["metadata"]["name"] for o in kube.list(NOTEBOOK, "ns")}
    for name in set(created) | set(deleted):
        alive = created[name] - deleted[name]
        assert alive in (0, 1), (name, created[name], deleted[name])
        assert (name in remaining) == (alive == 1), name
    # The watch stream only ever carried well-formed events.
    assert all(etype in ("ADDED", "MODIFIED", "DELETED")
               for etype, _ in watcher_events)


@pytest.mark.skipif(not native.available(), reason="native lib unavailable")
def test_native_wrapper_prune_never_orphans_processing_keys():
    """Regression (review r2): done()'s id-map prune raced a concurrent
    kfq_get holding the just-popped id — the key leaked into the C++
    processing set forever and its parked re-add was lost."""
    q = native.NativeWorkQueue(base_delay=0.001, max_delay=0.01)
    r = Request("ns", "k")
    stop = threading.Event()

    def worker():
        while not stop.is_set():
            x = q.get(timeout=0.01)
            if x is not None:
                q.done(x)

    threads = [threading.Thread(target=worker, daemon=True) for _ in range(3)]
    for t in threads:
        t.start()
    for _ in range(10_000):
        q.add(r)
    time.sleep(0.3)
    stop.set()
    for t in threads:
        t.join(timeout=5)
    # After quiesce the key must still be deliverable (not stuck
    # processing) and the id maps bounded.
    q.add(r)
    assert q.get(timeout=1.0) == r
    q.done(r)
    assert len(q._to_id) <= 1
    q.shut_down()
