"""Acceptance tests for the control-plane observability layer (ISSUE 1):

* a running manager's /metrics exposes the workqueue, reconcile-time, and
  rest-client series — over the REAL wire (RestKubeClient against the
  httpkube shim), so the client metrics/spans come from the production
  code path, not the in-memory fake;
* a forced-slow reconcile dumps a structured one-line JSON trace with the
  full span tree (dequeue → reconcile → client call), and the same trace
  is queryable via /debug/traces next to /metrics.
"""
import json
import logging
import re
import time
import urllib.request
import uuid

from kubeflow_tpu.platform import main as main_mod
from kubeflow_tpu.platform.controllers.notebook import make_controller
from kubeflow_tpu.platform.k8s.types import NOTEBOOK, STATEFULSET
from kubeflow_tpu.platform.runtime import Manager, Reconciler
from kubeflow_tpu.platform.runtime import trace
from kubeflow_tpu.platform.runtime.controller import Controller
from kubeflow_tpu.platform.testing import FakeKube
from kubeflow_tpu.platform.testing.httpkube import make_transport

from .test_notebook_controller import make_notebook
from .test_runtime_e2e import wait_for


def _get(url: str) -> bytes:
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.read()


def test_manager_metrics_expose_runtime_series_end_to_end():
    """Spawn the notebook controller through a Manager over HTTP, run one
    notebook through a reconcile, and scrape the health server: every new
    series family must be present with the right labels."""
    from kubeflow_tpu.platform.runtime import metrics

    kube = FakeKube()
    kube.add_namespace("user1")
    # Pre-create so the informer's initial relist (a LIST over the wire)
    # replays the ADDED — no dependency on long-poll watch delivery.
    kube.create(make_notebook(tpu={"accelerator": "v5e", "topology": "4x4"}))
    client, api_server = make_transport(kube, "http")
    mgr = Manager(client)
    mgr.add(make_controller(client, use_istio=False))
    health = None
    try:
        mgr.start()
        health = main_mod._serve_health(mgr, 0, host="127.0.0.1")
        base = f"http://127.0.0.1:{health.server_port}"
        wait_for(lambda: kube.get(STATEFULSET, "nb", "user1"))
        # The STS write proves a reconcile ran; wait for its histogram
        # observation, then scrape.
        wait_for(lambda: metrics.registry.get_sample_value(
            "controller_runtime_reconcile_time_seconds_count",
            {"controller": "notebook-controller", "result": "success"}))
        text = _get(base + "/metrics").decode()

        # Workqueue series, labeled by controller (client-go names).
        assert 'workqueue_depth{name="notebook-controller"}' in text
        assert 'workqueue_adds_total{name="notebook-controller"}' in text
        assert 'workqueue_unfinished_work_seconds{name="notebook-controller"}' in text
        assert re.search(
            r'workqueue_queue_duration_seconds_bucket{le="[^"]+",'
            r'name="notebook-controller"}', text)
        assert re.search(
            r'workqueue_work_duration_seconds_bucket{le="[^"]+",'
            r'name="notebook-controller"}', text)
        assert 'workqueue_retries_total{name="notebook-controller"}' in text

        # Reconcile histogram with controller+result labels.
        assert re.search(
            r'controller_runtime_reconcile_time_seconds_bucket'
            r'{controller="notebook-controller",le="[^"]+",result="success"}',
            text)

        # Rest-client series from the real REST client over the wire.
        assert re.search(
            r'rest_client_requests_total{code="200",kind="Notebook",'
            r'verb="(get|list|update_status)"}', text)
        assert re.search(
            r'rest_client_request_duration_seconds_bucket'
            r'{kind="[^"]+",le="[^"]+",verb="[a-z_]+"}', text)

        # Informer series (the controller's informer-backed caches).
        assert "informer_last_sync_age_seconds" in text
        assert "informer_relist_duration_seconds" in text

        # /debug/traces serves the reconcile span trees next to /metrics.
        body = json.loads(_get(base + "/debug/traces"))
        traces = [t for t in body["traces"]
                  if t["controller"] == "notebook-controller"]
        assert traces, body
        assert all("trace_id" in t and "spans" in t for t in traces)
        # ?n= bounds the response.
        body_1 = json.loads(_get(base + "/debug/traces?n=1"))
        assert len(body_1["traces"]) == 1
        # ?controller= / ?trace_id= filter BEFORE the ?n= cap (the
        # contract documented in docs/observability.md "Reconcile traces
        # and journeys").
        filtered = json.loads(_get(
            base + "/debug/traces?controller=notebook-controller"))
        assert filtered["traces"] and all(
            t["controller"] == "notebook-controller"
            for t in filtered["traces"])
        assert json.loads(_get(
            base + "/debug/traces?controller=no-such"))["traces"] == []
        one = traces[-1]
        by_id = json.loads(_get(
            base + f"/debug/traces?trace_id={one['trace_id']}"))
        assert [t["trace_id"] for t in by_id["traces"]] == [
            one["trace_id"]]
        # The reconcile trace links its causal journey, ?trace_id=
        # matches the JOURNEY id too, and /debug/journey/<trace_id>
        # serves the causal spans themselves.
        from kubeflow_tpu.telemetry import causal

        nb_live = kube.get(NOTEBOOK, "nb", "user1")
        jctx = causal.from_object(nb_live)
        assert jctx is not None
        by_journey = json.loads(_get(
            base + f"/debug/traces?trace_id={jctx.trace_id}"))
        assert by_journey["traces"] and all(
            t.get("causal_trace_id") == jctx.trace_id
            for t in by_journey["traces"])
        journey = json.loads(_get(
            base + f"/debug/journey/{jctx.trace_id}"))
        assert journey["trace_id"] == jctx.trace_id
        segs = {s.get("segment") for s in journey["spans"]}
        assert {"watch_lag", "queue_wait", "reconcile",
                "write_rtt"} <= segs, segs
    finally:
        if health is not None:
            health.shutdown()
        mgr.stop()
        api_server.stop()


def test_trace_lift_keeps_platform_surface_and_one_implementation():
    """ISSUE 6 shared-core contract: the PR-1 module API survives the
    lift into kubeflow_tpu.telemetry (no behavior drift — same names,
    same knobs, same controller/request wire keys), and there is exactly
    ONE span/trace implementation under both halves."""
    from kubeflow_tpu import telemetry
    from kubeflow_tpu.telemetry import compute as ctel

    # Full PR-1 surface present with the env-knob module attributes.
    for name in ("begin", "current", "active", "span", "finish", "recent",
                 "clear", "Span", "Trace"):
        assert hasattr(trace, name), name
    assert isinstance(trace.SLOW_RECONCILE_SECONDS, float)
    assert isinstance(trace.ENABLED, bool)
    assert isinstance(trace.TRACE_BUFFER_SIZE, int)

    # One implementation: the platform Span IS the telemetry Span, and
    # the compute plane's tracer is the same engine.
    assert trace.Span is telemetry.Span
    assert issubclass(trace.Trace, telemetry.Trace)
    assert isinstance(ctel.train_tracer, telemetry.Tracer)
    assert trace.log.name == "kubeflow_tpu.runtime.trace"

    # A begin/span/finish round-trip keeps the control-plane wire format.
    trace.begin("probe-controller", "ns/name")
    assert trace.active()
    with trace.span("work", kind="Probe"):
        pass
    d = trace.finish(result="success")
    assert d["controller"] == "probe-controller"
    assert d["request"] == "ns/name"
    assert d["result"] == "success"
    assert d["spans"][0]["name"] == "work"
    assert d["spans"][0]["kind"] == "Probe"
    assert trace.recent()[-1]["trace_id"] == d["trace_id"]
    # Disabled begin clears the active slot (the stale-trace guard).
    prev = trace.ENABLED
    try:
        trace.ENABLED = False
        assert trace.begin("x", "y") is None
        assert not trace.active()
    finally:
        trace.ENABLED = prev


def test_slow_reconcile_dumps_structured_trace(monkeypatch, caplog):
    """A reconcile crossing the slow threshold emits ONE JSON log line
    whose span tree covers dequeue → reconcile → client call, and the
    trace is retrievable from the ring buffer."""
    monkeypatch.setattr(trace, "SLOW_RECONCILE_SECONDS", 0.01)
    kube = FakeKube()
    kube.add_namespace("user1")
    client, api_server = make_transport(kube, "http")
    name = f"trace-probe-{uuid.uuid4().hex[:6]}"

    class SlowReconciler(Reconciler):
        def reconcile(self, req):
            client.get(NOTEBOOK, req.name, req.namespace)  # traced client call
            time.sleep(0.05)

    # Pre-create + informer-sourced primary: the initial relist replays
    # the ADDED, driving the reconcile without live watch delivery.
    kube.create(make_notebook())
    from kubeflow_tpu.platform.runtime.informer import Informer

    ctrl = Controller(name, SlowReconciler(), primary=NOTEBOOK,
                      informers={NOTEBOOK: Informer(client, NOTEBOOK)})
    try:
        with caplog.at_level(logging.WARNING, logger="kubeflow_tpu.runtime.trace"):
            ctrl.start(client)
            dumped = wait_for(lambda: [
                r for r in caplog.records
                if r.name == "kubeflow_tpu.runtime.trace"
                and name in r.getMessage()
            ])
    finally:
        ctrl.stop()
        api_server.stop()

    msg = dumped[0].getMessage()
    payload = json.loads(msg[msg.index("{"):])
    assert payload["controller"] == name
    assert payload["request"] == "user1/nb"
    assert payload["duration_ms"] >= 10.0
    span_names = [s["name"] for s in payload["spans"]]
    assert len(span_names) >= 3, span_names
    assert "dequeue" in span_names
    assert "reconcile" in span_names
    client_spans = [s for s in payload["spans"] if s["name"].startswith("k8s.")]
    assert client_spans and client_spans[0]["kind"] == "Notebook"
    assert client_spans[0]["code"] == "200"

    # Same trace in the ring buffer (the /debug/traces source).
    ring = [t for t in trace.recent() if t["controller"] == name]
    assert ring and ring[-1]["trace_id"] == payload["trace_id"]


def test_debug_knobs_endpoint_dumps_registry_with_redaction():
    """/debug/knobs (ISSUE 13, kftlint R005): every knob resolved through
    platform/config.py shows up with value/default/source; names sniffed
    as secrets are redacted when set from the environment."""
    import os

    from kubeflow_tpu.platform import config

    config.knob("KFT_TEST_KNOB_PLAIN", 7, int, doc="observability test knob")
    config.knob("KFT_TEST_KNOB_TOKEN", "")
    os.environ["KFT_TEST_KNOB_PLAIN"] = "11"
    os.environ["KFT_TEST_KNOB_TOKEN"] = "hunter2"

    class _Mgr:
        def healthy(self):
            return True

    server = main_mod._serve_health(_Mgr(), 0, host="127.0.0.1")
    try:
        body = json.loads(
            _get(f"http://127.0.0.1:{server.server_port}/debug/knobs"))
        knobs = body["knobs"]
        plain = knobs["KFT_TEST_KNOB_PLAIN"]
        assert plain["value"] == 11 and plain["default"] == 7
        assert plain["source"] == "env"
        assert plain["doc"] == "observability test knob"
        token = knobs["KFT_TEST_KNOB_TOKEN"]
        assert token["value"] == "<redacted>"
        assert "hunter2" not in json.dumps(body)
    finally:
        server.shutdown()
        del os.environ["KFT_TEST_KNOB_PLAIN"]
        del os.environ["KFT_TEST_KNOB_TOKEN"]


def test_debug_knobs_reports_unparseable_env_source():
    """An env var that is SET but fails its parser must not masquerade as
    a clean env-sourced value — the typo is what the reader is hunting."""
    import os

    from kubeflow_tpu.platform import config

    config.knob("KFT_TEST_KNOB_BADINT", 3, int)
    os.environ["KFT_TEST_KNOB_BADINT"] = "five"
    try:
        assert config.knob("KFT_TEST_KNOB_BADINT", 3, int) == 3  # runtime falls back
        entry = config.effective()["KFT_TEST_KNOB_BADINT"]
        assert entry["value"] == 3
        assert entry["source"] == "env-unparseable"
    finally:
        del os.environ["KFT_TEST_KNOB_BADINT"]


def test_debug_knobs_reports_invalid_validated_env_source():
    """Validated knobs (ISSUE 17, config.knob validate=): an env value
    that parses but fails validation raises at the read site, and
    /debug/knobs reports the rejection as env-invalid with the default
    in effect — it must not pretend the bad value took hold."""
    import os

    import pytest

    from kubeflow_tpu.platform import config

    def in_range(v):
        return None if 1 <= v <= 100 else "must be in [1, 100]"

    config.knob("KFT_TEST_KNOB_RANGED", 10, int, validate=in_range)
    os.environ["KFT_TEST_KNOB_RANGED"] = "4096"
    try:
        with pytest.raises(ValueError, match=r"must be in \[1, 100\]"):
            config.knob("KFT_TEST_KNOB_RANGED", 10, int, validate=in_range)
        entry = config.effective()["KFT_TEST_KNOB_RANGED"]
        assert entry["value"] == 10
        assert entry["source"] == "env-invalid"
        # A validated knob is also strict about parse failures: it names
        # the parser and raises instead of silently taking the default.
        os.environ["KFT_TEST_KNOB_RANGED"] = "banana"
        with pytest.raises(ValueError, match="not a valid int"):
            config.knob("KFT_TEST_KNOB_RANGED", 10, int, validate=in_range)
        assert config.effective()["KFT_TEST_KNOB_RANGED"][
            "source"] == "env-unparseable"
    finally:
        del os.environ["KFT_TEST_KNOB_RANGED"]


def test_debug_index_lists_live_surfaces():
    """/debug/ (ISSUE 15 satellite, hardened by ISSUE 16): the health
    port indexes every live debug surface with a one-line description,
    so the family is discoverable without the docs open — and every
    route actually wired in _serve_health must appear in the index, so
    a future surface can't ship unlisted."""
    import inspect

    class _Mgr:
        def healthy(self):
            return True

    server = main_mod._serve_health(_Mgr(), 0, host="127.0.0.1")
    try:
        base = f"http://127.0.0.1:{server.server_port}"
        body = json.loads(_get(base + "/debug/"))
        index = body["debug"]
        assert set(index) == {
            "/debug/knobs", "/debug/queue", "/debug/shards",
            "/debug/traces", "/debug/journey/<trace_id>",
            "/debug/alerts", "/debug/goodput", "/debug/profile",
            "/debug/incidents", "/debug/activator"}
        assert all(isinstance(v, str) and v for v in index.values())
        # The bare path serves it too.
        assert json.loads(_get(base + "/debug"))["debug"] == index

        # Source-derived coverage pin: every routed /debug path in
        # _serve_health — exact matches and startswith prefixes — must
        # be represented in the index.  Adding a route without an index
        # entry fails HERE, not the first time an operator goes looking.
        src = inspect.getsource(main_mod._serve_health)
        exact = set(re.findall(r'path == "(/debug/[^"]+)"', src))
        prefixes = set(re.findall(
            r'path\.startswith\("(/debug/[^"]+/)"\)', src))
        assert exact and prefixes  # the regexes still match the source
        for path in exact:
            assert path in index, f"routed {path} missing from /debug/ index"
        for prefix in prefixes:
            assert any(k == prefix.rstrip("/") or k.startswith(prefix)
                       for k in index), (
                f"routed prefix {prefix} missing from /debug/ index")
    finally:
        server.shutdown()


def test_debug_alerts_and_goodput_endpoints_serve_registered_state():
    """/debug/alerts + /debug/goodput (ISSUE 15): 404 until the pipeline
    registers its engine/accountant (the single-slot registry pattern,
    like /debug/queue), then live JSON."""
    import urllib.error

    from kubeflow_tpu.telemetry import fleetscrape as fs
    from kubeflow_tpu.telemetry import goodput as goodput_mod
    from kubeflow_tpu.telemetry import slo as slo_mod
    from kubeflow_tpu.telemetry.tsdb import TSDB

    class _Mgr:
        def healthy(self):
            return True

    server = main_mod._serve_health(_Mgr(), 0, host="127.0.0.1")
    base = f"http://127.0.0.1:{server.server_port}"
    try:
        for path in ("/debug/alerts", "/debug/goodput"):
            try:
                _get(base + path)
            except urllib.error.HTTPError as e:
                assert e.code == 404
            else:  # pragma: no cover
                raise AssertionError(f"{path} served before registration")

        db = TSDB()
        pipe = fs.MetricsPipeline(tsdb=db, now=lambda: 100.0,
                                  interval=999.0)
        slo_mod.register_debug_alerts(pipe.engine)
        goodput_mod.register_debug_goodput(pipe.goodput)
        try:
            pipe.step(at=100.0)
            alerts = json.loads(_get(base + "/debug/alerts"))
            assert {a["alert"] for a in alerts["alerts"]} == {
                "serve-ttft-p99", "reconcile-p99", "watch-lag",
                "queue-wait"}
            assert all(a["state"] == "inactive" for a in alerts["alerts"])
            goodput = json.loads(_get(base + "/debug/goodput"))
            assert goodput == {"profiles": {}, "lastTickAt": 100.0}
        finally:
            slo_mod.register_debug_alerts(None)
            goodput_mod.register_debug_goodput(None)
    finally:
        server.shutdown()


def test_queue_oldest_wait_gauge_rides_scrape(monkeypatch):
    """tpujob_queue_oldest_wait_seconds (ISSUE 15 satellite): the
    starvation gauge reads the registered ledger at scrape time — ages
    grow with wall time without a queue state change."""
    from kubeflow_tpu.platform.runtime import jobqueue as jq
    from kubeflow_tpu.platform.runtime import metrics

    clock = [1000.0]
    q = jq.JobQueue(now=lambda: clock[0])
    q.observe({
        "apiVersion": "kubeflow.org/v1alpha1", "kind": "TPUJob",
        "metadata": {"name": "starving", "namespace": "team-a",
                     "creationTimestamp": "2026-01-01T00:00:00Z"},
        "spec": {"tpu": {"accelerator": "v5e", "topology": "2x4",
                         "slices": 1},
                 "template": {"spec": {"containers": [{"name": "w"}]}}},
        "status": {"phase": "Queued", "queuedAt": 900.0},
    })
    jq.register_debug_queue(q)
    try:
        def sample():
            return metrics.registry.get_sample_value(
                "tpujob_queue_oldest_wait_seconds",
                {"profile": "team-a"})

        assert sample() == 100.0
        clock[0] = 1250.0  # no state change: the age still grows
        assert sample() == 350.0
    finally:
        jq.register_debug_queue(None)
    assert sample() is None
