// try/catch/finally ordering and typed errors.
const log = [];
function t1() {
  try {
    log.push("try");
    throw new Error("boom");
  } catch (e) {
    log.push("catch:" + e.message);
    return "from-catch";
  } finally {
    log.push("finally");
  }
}
print(t1(), log.join(","));
try {
  null.foo;
} catch (e) {
  print(e instanceof TypeError, typeof e.message === "string");
}
try {
  undefinedFunction();
} catch (e) {
  print(e instanceof Error);
}
function t2() {
  try {
    return "a";
  } finally {
    log.push("fin2");
  }
}
print(t2(), log.includes("fin2"));
let caught = "";
try {
  try {
    throw new TypeError("inner");
  } finally {
    caught += "F";
  }
} catch (e) {
  caught += "C:" + e.name;
}
print(caught);
try { JSON.parse("{bad"); } catch (e) { print("parse-error", e instanceof Error); }
// CoverInitializedName outside destructuring is a SyntaxError.
try {
  const bad = { x = 5 };
  print("no-error");
} catch (e) {
  print("cover-init", e.name);
}
