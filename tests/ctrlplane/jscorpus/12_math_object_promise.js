// Math statics, Object.assign/entries/values ordering, forEach,
// decodeURIComponent round-trips, and synchronous-settling promise
// chains (then/catch, Promise.all).
print(Math.ceil(1.1), Math.ceil(-1.1), Math.max(1, 5, 3), Math.min(2, -2));
const merged = Object.assign({}, { a: 1 }, { b: 2 }, { a: 3 });
print(JSON.stringify(merged));
print(Object.entries({ x: 1, y: 2 }).map(([k, v]) => k + "=" + v).join("&"));
print(Object.values({ x: 1, y: 2 }).join(","));
const out = [];
[3, 1].forEach((v, i) => out.push(i + ":" + v));
print(out.join(" "));
print(decodeURIComponent("a%20b%2Fc"));
print(decodeURIComponent(encodeURIComponent("ns/notebook name")));
Promise.all([Promise.resolve(1), Promise.resolve(2)])
  .then((vals) => print("all", vals.join(",")));
Promise.resolve(7).then((v) => v + 1).then((v) => print("chain", v));
Promise.reject(new Error("boom")).catch((e) => print("caught", e.message));
Promise.resolve("v").catch(() => print("skipped")).then((v) => print("kept", v));
