// Date: epoch-ms construction, ISO-string construction, getTime,
// toISOString formatting, and a monotonic Date.now sanity bound.
const epoch = new Date(0);
print(epoch.toISOString());
print(epoch.getTime());
print(new Date(1722470400000).toISOString());
print(new Date("2026-07-31T12:30:00Z").getTime());
print(new Date(86400000).toISOString());
print(new Date(1500).getTime());
const t0 = Date.now();
print(t0 > new Date("2026-01-01T00:00:00Z").getTime());
print(Date.now() >= t0);
print(new Date("2026-07-31T12:30:00.250Z").toISOString());
