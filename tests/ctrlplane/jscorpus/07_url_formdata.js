// URL / URLSearchParams / FormData / encodeURIComponent semantics.
const u = new URL("http://host:8080/path/to?a=1&b=two#frag");
print(u.origin);
print(u.pathname);
print(u.search);
print(u.searchParams.get("a"), u.searchParams.get("b"), u.searchParams.get("zz"));
const rel = new URL("/other?x=9", "http://base.example");
print(rel.href);
const sp = new URLSearchParams("a=1&b=2");
sp.set("a", "10");
sp.append("c", "three");
print(sp.toString());
print(sp.get("a"), sp.has("b"), sp.has("z"));
const sp2 = new URLSearchParams();
sp2.set("q", "hello world");
sp2.set("amp", "a&b");
print(sp2.toString());
print(encodeURIComponent("a b&c=d"));
print(encodeURIComponent("safe-._~"));
const url2 = new URL("http://h/p");
url2.searchParams.set("ns", "user1");
print(url2.searchParams.get("ns"));
// WHATWG origin normalization: lowercase host, default port elided.
print(new URL("http://Host.Example:80/p").origin);
print(new URL("https://h.example:443/p").origin);
print(new URL("https://h.example:8443/p").origin);
print(new URL("http://h.example/p#x").hash);
// FormData standalone construction + entry semantics.
const fd = new FormData();
fd.append("a", "1");
fd.append("a", "2");
fd.append("b", "x");
print(fd.get("a"));
print(fd.getAll("a").join("|"));
print(fd.get("missing"));
print(fd.has("b"), fd.has("zz"));
fd.set("a", "9");
print(fd.getAll("a").join("|"), fd.get("b"));
fd.delete("b");
print(fd.has("b"));
print(new FormData(undefined).has("x"));
try { new FormData("not-a-form"); } catch (e) { print("fd-ctor", e.name); }
