// RegExp via string construction (the engine's supported form — regex
// LITERALS are deliberately outside the SPA subset): test/match/exec,
// flags (g, i), null on no match, $n group substitution in replace.
const re = new RegExp("a+", "g");
print(re.test("baaa"));
print(re.test("zzz"));
print("baaa banana".match(new RegExp("a+", "g")).join(","));
print("no".match(new RegExp("x")));
print("a-b-c".replace(new RegExp("-", "g"), "+"));
print(new RegExp("^\\d{2}$").test("42"));
print(new RegExp("^\\d{2}$").test("426"));
print("CaSe sensitivity".match(new RegExp("case", "i"))[0]);
print("v5e-16".match(new RegExp("^(\\w+)-(\\d+)$")).join("|"));
print("2026-07-31".replace(new RegExp("(\\d+)-(\\d+)-(\\d+)"), "$3/$2/$1"));
print(new RegExp("(?:^|; )tok=([^;]*)").exec("a=1; tok=xyz")[1]);
print(new RegExp("(\\w)x", "g").exec("axbx").join(","));

