// Defaults apply ONLY for undefined, never for null/0/"".
const { a = 1, b = 2, c = 3 } = { a: 0, b: undefined };
print(a, b, c);
const [x = 5, y = 6, z = 7] = [undefined, null];
print(x, y, z);
const { p: { q = 9 } = {} } = {};
print(q);
const { m: renamed = "dflt" } = { m: "val" };
print(renamed);
const [first, ...rest] = [1, 2, 3, 4];
print(first, rest.length, rest[0]);
const { u, ...others } = { u: 1, v: 2, w: 3 };
print(u, Object.keys(others).join("|"));
let s1 = "a", s2 = "b";
[s1, s2] = [s2, s1];
print(s1, s2);
function f({ k = "kd" } = {}) { return k; }
print(f(), f({}), f({ k: "x" }), f({ k: undefined }));
const [, second] = ["skip", "take"];
print(second);
// Assignment (non-declaration) forms: member targets, object rest,
// shorthand defaults.
const obj = {};
[obj.a, obj.b] = [1, 2];
print(obj.a, obj.b);
let r1, r2;
({ r1, ...r2 } = { r1: "x", k1: 1, k2: 2 });
print(r1, Object.keys(r2).join("|"));
let d1 = null;
({ d1 = "dflt" } = {});
print(d1);
({ d1 = "dflt" } = { d1: "set" });
print(d1);
