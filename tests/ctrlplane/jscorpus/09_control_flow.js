// Control flow: ternary (right-assoc), if/else-if chains, for-of with
// continue, and ++/-- value semantics — expectations written to the spec.
let total = 0;
for (const n of [1, 2, 3, 4, 5]) {
  if (n % 2 === 0) continue;
  total += n;
}
print(total);
let i = 0;
print(i++);
print(i);
print(++i);
let j = 2;
print(j--, --j);
print(1 > 2 ? "a" : "b");
print(true ? false ? "x" : "y" : "z");
let s = "";
for (const [k, v] of Object.entries({ a: 1, b: 2 })) { s += k + v; }
print(s);
if (0) { print("no"); } else if ("") { print("no2"); } else { print("yes"); }
let count = 0;
for (const ch of "hello") count++;
print(count);
let cls = "";
for (const n of [1, 2, 3]) {
  cls = n === 2 ? cls : cls + n;
}
print(cls);
