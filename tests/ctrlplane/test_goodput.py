"""Per-tenant goodput accounting (ISSUE 15): the tiling invariant
(goodput + queued + restarting + idle == allocated, by construction),
the workload decompositions, staleness (a dead replica's series stops
counting), and the seeded-storm acceptance — a TPUJob + InferenceService
fleet under chaos produces a nonzero, monotone-consistent
tpu_goodput_ratio at /debug/goodput."""
from __future__ import annotations

import json
import time
import urllib.request

import pytest

from kubeflow_tpu.telemetry import goodput as gp
from kubeflow_tpu.telemetry.tsdb import TSDB


def job(*, ns="team-a", phase="Running", alloc=2, ready=None, total=None,
        slices=2):
    status = {"phase": phase, "allocatedSlices": alloc, "generation": 0,
              "restarts": 0}
    if ready is not None:
        status["slices"] = [{"slice": 0, "ready": ready, "total": total}]
    return {
        "apiVersion": "kubeflow.org/v1alpha1", "kind": "TPUJob",
        "metadata": {"name": "j", "namespace": ns},
        "spec": {"tpu": {"accelerator": "v5e", "topology": "2x4",
                         "slices": slices},
                 "template": {"spec": {"containers": [{"name": "w"}]}}},
        "status": status,
    }


def service(*, ns="team-a", replicas=2, ready=2, name="svc"):
    return {
        "apiVersion": "kubeflow.org/v1alpha1", "kind": "InferenceService",
        "metadata": {"name": name, "namespace": ns},
        "spec": {"model": "llama_125m",
                 "tpu": {"accelerator": "v5e", "topology": "2x4"},
                 "replicas": {"min": 1, "max": 4}},
        "status": {"phase": "Ready", "replicas": replicas,
                   "readyReplicas": ready, "revision": 1,
                   "targetRevision": 1},
    }


def test_job_use_decomposition_by_phase():
    # 2x4 v5e slice = 8 chips; 2 slices allocated = 16 chips.
    u = gp.job_use(job(phase="Running", ready=2, total=2))
    assert (u.chips, u.productive, u.idle) == (16.0, 16.0, 0.0)
    u = gp.job_use(job(phase="Running", ready=1, total=2))
    assert u.productive == 8.0 and u.idle == 8.0
    u = gp.job_use(job(phase="Pending"))
    assert u.queued == u.chips == 16.0
    u = gp.job_use(job(phase="Restarting"))
    assert u.restarting == 16.0
    u = gp.job_use(job(phase="Preempting"))
    assert u.restarting == 16.0
    assert gp.job_use(job(phase="Succeeded")) is None
    # Queued jobs hold nothing.
    q = job(phase="Queued")
    q["status"].pop("allocatedSlices")
    assert gp.job_use(q) is None


def test_service_use_occupancy_and_cold_replicas():
    db = TSDB()
    # svc: 2 replicas x 8 chips, 1 ready.  Ready replica half-occupied.
    db.append("serve_decode_slots", {"service": "team-a/svc",
                                     "replica": "p0"}, 8.0, ts=100.0)
    db.append("serve_decode_slots_active", {"service": "team-a/svc",
                                            "replica": "p0"}, 4.0, ts=100.0)
    u = gp.service_use(service(replicas=2, ready=1), tsdb=db, at=100.0,
                       staleness=60.0)
    assert u.chips == 16.0
    assert u.queued == 8.0            # the cold replica
    assert u.productive == 4.0        # 8 ready chips * 0.5 occupancy
    assert u.idle == 4.0
    # No scraped series -> ready chips read idle, never productive.
    u2 = gp.service_use(service(replicas=1, ready=1), tsdb=TSDB(),
                        at=100.0, staleness=60.0)
    assert u2.productive == 0.0 and u2.idle == 8.0


def test_dead_replica_series_stops_counting():
    """A killed serving pod leaves its frozen slot gauges in the ring;
    past the staleness bound they must not count — otherwise its
    replacement double-counts the same chips."""
    db = TSDB()
    for replica, ts in (("dead", 10.0), ("live", 100.0)):
        db.append("serve_decode_slots", {"service": "t/s",
                                         "replica": replica}, 8.0, ts=ts)
        db.append("serve_decode_slots_active", {"service": "t/s",
                                                "replica": replica},
                  8.0, ts=ts)
    u = gp.service_use(service(ns="t", name="s", replicas=1, ready=1),
                       tsdb=db, at=100.0, staleness=30.0)
    # Only the live replica's occupancy (8/8) over one replica's chips.
    assert u.productive == 8.0 and u.idle == 0.0


def test_tiling_invariant_holds_under_adversarial_inputs():
    """The accountant clamps each bucket into the remaining allocation,
    so the four states tile allocated chip-seconds EXACTLY whatever the
    inputs claim (overlapping states, over-claims, negatives)."""
    acct = gp.GoodputAccountant(now=lambda: 0.0)
    acct.tick([], at=0.0)
    uses = [
        gp.WorkloadUse("p", 16.0, productive=20.0, queued=10.0,
                       restarting=10.0),               # over-claimed
        gp.WorkloadUse("p", 8.0, productive=-3.0),     # negative claim
        gp.WorkloadUse("q", 4.0, productive=1.0, queued=1.0),
    ]
    acct.tick(uses, at=10.0)
    snap = acct.snapshot()
    for profile, row in snap["profiles"].items():
        assert row["tiles"], row
        total = sum(row[f"{s}ChipSeconds"] for s in gp.STATES)
        assert total == pytest.approx(row["allocatedChipSeconds"])
    assert snap["profiles"]["p"]["allocatedChipSeconds"] == \
        pytest.approx(240.0)
    assert snap["profiles"]["q"]["goodputRatio"] == pytest.approx(0.25)


def test_observe_integrates_jobs_and_services_per_profile():
    db = TSDB()
    acct = gp.GoodputAccountant(now=lambda: 0.0, staleness=60.0)
    jobs = [job(ns="team-a", phase="Running", ready=2, total=2)]
    services = [service(ns="team-b", replicas=1, ready=0)]
    acct.observe(jobs, services, tsdb=db, at=0.0)     # baseline tick
    acct.observe(jobs, services, tsdb=db, at=5.0)
    snap = acct.snapshot()["profiles"]
    assert snap["team-a"]["goodputChipSeconds"] == pytest.approx(80.0)
    assert snap["team-a"]["goodputRatio"] == 1.0
    assert snap["team-b"]["queuedChipSeconds"] == pytest.approx(40.0)
    assert snap["team-b"]["goodputRatio"] == 0.0


def test_monotone_counters_and_ratio_consistency():
    acct = gp.GoodputAccountant(now=lambda: 0.0)
    acct.tick([], at=0.0)
    last = {}
    for t in range(1, 6):
        frac = 0.5 + 0.1 * t
        acct.tick([gp.WorkloadUse("p", 10.0, productive=10.0 * frac)],
                  at=float(t))
        snap = acct.snapshot()["profiles"]["p"]
        for s in gp.STATES:
            key = f"{s}ChipSeconds"
            assert snap[key] >= last.get(key, 0.0)  # never decreases
            last[key] = snap[key]
        assert 0.0 < snap["goodputRatio"] <= 1.0


@pytest.mark.slow
def test_storm_fleet_produces_nonzero_goodput_at_debug_endpoint():
    """THE acceptance pin: TPUJob gangs + InferenceService replicas on
    one seeded ChaosKube storm, both REAL controllers reconciling, the
    accountant ticking from watch state + the shared TSDB — the
    /debug/goodput page serves a nonzero goodput ratio whose cumulative
    counters are monotone and tile exactly."""
    from kubeflow_tpu.platform import main as main_mod
    from kubeflow_tpu.platform.controllers import (
        inferenceservice as svcctrl,
    )
    from kubeflow_tpu.platform.controllers import tpujob as jobctrl
    from kubeflow_tpu.platform.k8s.types import INFERENCESERVICE, TPUJOB
    from kubeflow_tpu.platform.testing import FakeKube
    from kubeflow_tpu.platform.testing.chaos import ChaosKube, storm
    from kubeflow_tpu.platform.testing.jobsim import TpuJobGangSim
    from kubeflow_tpu.platform.testing.servesim import InferenceFleetSim

    ns = "goodput"
    inner = FakeKube()
    inner.add_namespace(ns)
    for i in range(8):
        inner.add_tpu_node(f"tpu-{i}", topology="2x4")
    kube = ChaosKube(inner, storm(rate=0.05), seed=7)
    db = TSDB()

    def pages(url):
        if url.endswith("/readyz"):
            return '{"ready": true}'
        return ("serve_decode_slots 8\nserve_decode_slots_active 6\n"
                "serve_queue_depth 1.0\n"
                'generate_requests_total{outcome="ok"} 50\n')

    jc = jobctrl.make_controller(kube)
    sc = svcctrl.make_controller(kube, scraper=pages, sync_period=0.05,
                                 tsdb=db)
    jobsim = TpuJobGangSim(inner, ns)
    servesim = InferenceFleetSim(
        inner, ns, endpoint_for=lambda s, r, i: f"sim://{s}/{r}/{i}")
    acct = gp.GoodputAccountant(staleness=60.0)
    from kubeflow_tpu.telemetry import goodput as gpmod

    gpmod.register_debug_goodput(acct)

    class _Mgr:
        def healthy(self):
            return True

    server = main_mod._serve_health(_Mgr(), 0, host="127.0.0.1")
    try:
        jc.start(kube)
        sc.start(kube)
        for i in range(2):
            inner.create({
                "apiVersion": "kubeflow.org/v1alpha1", "kind": "TPUJob",
                "metadata": {"name": f"gang-{i}", "namespace": ns},
                "spec": {"tpu": {"accelerator": "v5e", "topology": "2x4",
                                 "slices": 1},
                         "template": {"spec": {"containers": [
                             {"name": "w", "image": "x"}]}}},
            })
            inner.create({
                "apiVersion": "kubeflow.org/v1alpha1",
                "kind": "InferenceService",
                "metadata": {"name": f"svc-{i}", "namespace": ns},
                "spec": {"model": "llama_125m",
                         "tpu": {"accelerator": "v5e", "topology": "2x4"},
                         "replicas": {"min": 1, "max": 2, "initial": 1}},
            })

        url = f"http://127.0.0.1:{server.server_port}/debug/goodput"
        deadline = time.monotonic() + 30.0
        prev_alloc = 0.0
        good_seen = False
        while time.monotonic() < deadline:
            acct.observe(inner.list(TPUJOB, None),
                         inner.list(INFERENCESERVICE, None),
                         tsdb=db)
            body = json.load(urllib.request.urlopen(url))
            row = body["profiles"].get(ns)
            if row:
                assert row["tiles"], row
                assert row["allocatedChipSeconds"] >= prev_alloc
                prev_alloc = row["allocatedChipSeconds"]
                ratio = row["goodputRatio"]
                if ratio and ratio > 0:
                    good_seen = True
                    assert 0.0 < ratio <= 1.0
                    if row["allocatedChipSeconds"] > 20.0:
                        break
            time.sleep(0.1)
        assert good_seen, "no nonzero goodput ratio under the storm"
    finally:
        server.shutdown()
        gpmod.register_debug_goodput(None)
        sc.stop()
        jc.stop()
        servesim.close()
        jobsim.close()


def test_backwards_clock_never_reintegrates_an_interval():
    """An out-of-order tick (NTP step, duplicate timestamp) must not
    move the integration anchor backward — rewinding it would count an
    already-integrated interval twice."""
    acct = gp.GoodputAccountant(now=lambda: 0.0)
    use = [gp.WorkloadUse("p", 8.0, productive=8.0)]
    acct.tick(use, at=0.0)
    acct.tick(use, at=100.0)   # integrates [0, 100] = 800 chip-seconds
    acct.tick(use, at=95.0)    # clock stepped back: ignored entirely
    acct.tick(use, at=100.0)   # duplicate: ignored
    snap = acct.snapshot()["profiles"]["p"]
    assert snap["allocatedChipSeconds"] == pytest.approx(800.0)
    assert snap["goodputChipSeconds"] == pytest.approx(800.0)
