"""TpuJobQueue (ISSUE 11): quota-aware gang queueing, priority preemption,
and elastic capacity for TPUJob.

Three layers, bottom up:

* ledger units — rank order, elastic k_max math, head-of-line blocking,
  minimal victim selection, incremental-vs-rebuilt equivalence;
* controller flows over FakeKube — park-with-reason, priority-then-FIFO
  drain, quota park, the two-phase checkpoint-then-evict, elastic admit
  at minSlices + grow-back, crashloop-cannot-starve (backoffLimit binds);
* the chaos/HA pins (slow, the queue-chaos postsubmit lane) — a priority
  storm under seeded faults and a ShardedFleet replica kill, both of
  which must preserve the drain-order and no-half-gang invariants.
"""
import threading
import time

import pytest

from kubeflow_tpu.platform.apis import tpujob as jobapi
from kubeflow_tpu.platform.controllers.tpujob import TPUJobReconciler
from kubeflow_tpu.platform.k8s import errors
from kubeflow_tpu.platform.k8s.types import (
    POD,
    STATEFULSET,
    TPUJOB,
    deep_get,
    name_of,
)
from kubeflow_tpu.platform.runtime import Request
from kubeflow_tpu.platform.runtime import jobqueue as jq
from kubeflow_tpu.platform.testing import FakeKube


def make_job(name, ns="fleet", *, slices=1, min_slices=None, priority=None,
             topology="2x4", backoff_limit=None, created=None,
             checkpoint_dir=None):
    spec = {
        "tpu": {"accelerator": "v5e", "topology": topology,
                "slices": slices},
        "template": {"spec": {"containers": [{
            "name": "worker", "image": "trainer",
            "command": ["python", "-m", "kubeflow_tpu.train.run"],
        }]}},
    }
    if min_slices is not None:
        spec["tpu"]["minSlices"] = min_slices
    if priority is not None:
        spec["priority"] = priority
    if backoff_limit is not None:
        spec["backoffLimit"] = backoff_limit
    if checkpoint_dir is not None:
        spec["checkpointDir"] = checkpoint_dir
    job = {
        "apiVersion": "kubeflow.org/v1alpha1", "kind": "TPUJob",
        "metadata": {"name": name, "namespace": ns},
        "spec": spec,
    }
    if created is not None:
        job["metadata"]["creationTimestamp"] = created
    return job


def make_quota(ns, chips):
    return {
        "apiVersion": "v1", "kind": "ResourceQuota",
        "metadata": {"name": "kf-resource-quota", "namespace": ns},
        "spec": {"hard": {"google.com/tpu": str(chips)}},
    }


def kube_with_slots(slots, *, ns="fleet"):
    """FakeKube with ``slots`` single-host v5e 2x4 nodes = that many free
    slice slots in the (v5e, 2x4) pool (8 chips per slice)."""
    k = FakeKube()
    k.add_namespace(ns)
    for i in range(slots):
        k.add_tpu_node(f"tpu-{i + 1}", topology="2x4")
    return k


def reconcile(kube, name, ns="fleet", **kwargs):
    kwargs.setdefault("preemption_grace", 0.05)
    kwargs.setdefault("queue_poll", 0.05)
    return TPUJobReconciler(kube, **kwargs).reconcile(Request(ns, name))


def drive(kube, names, ns="fleet", rounds=6, **kwargs):
    """A few level-triggered passes over every key (the controller's
    event+poll loop, compressed and deterministic)."""
    for _ in range(rounds):
        for name in names:
            reconcile(kube, name, ns, **kwargs)


def phase(kube, name, ns="fleet"):
    return jobapi.phase_of(kube.get(TPUJOB, name, ns))


def alloc(kube, name, ns="fleet"):
    return jobapi.allocated_slices(kube.get(TPUJOB, name, ns))


def finish_gang(kube, name, ns="fleet"):
    """Kubelet-sim: every worker pod of the job exits 0."""
    for pod in kube.list(POD, ns,
                         label_selector={jobapi.LABEL_TPUJOB_NAME: name}):
        kube.set_pod_phase(ns, name_of(pod), "Succeeded", ready=False)


def run_gang(kube, name, ns="fleet"):
    """Kubelet-sim: bring every expected worker pod Running/ready."""
    job = kube.get(TPUJOB, name, ns)
    gen = jobapi.generation_of(job)
    k = jobapi.allocated_slices(job)
    assert k, f"{name} not admitted: {job.get('status')}"
    for s in range(k):
        sts_name = TPUJobReconciler.slice_sts_name(name, s)
        sts = kube.get(STATEFULSET, sts_name, ns)
        tmpl = deep_get(sts, "spec", "template")
        for i in range(deep_get(sts, "spec", "replicas", default=0)):
            pod_name = f"{sts_name}-{i}"
            try:
                kube.create({
                    "apiVersion": "v1", "kind": "Pod",
                    "metadata": {"name": pod_name, "namespace": ns,
                                 "labels": dict(deep_get(
                                     tmpl, "metadata", "labels",
                                     default={}) or {})},
                    "spec": deep_get(tmpl, "spec"),
                })
            except errors.AlreadyExists:
                pass
            kube.set_pod_phase(ns, pod_name, "Running", ready=True)
    return gen


# -- ledger units -------------------------------------------------------------


def entry(q, kube, job):
    kube.create(job)
    q.observe(kube.get(TPUJOB, job["metadata"]["name"],
                       job["metadata"]["namespace"]))


def test_rank_is_priority_then_fifo_then_name():
    q = jq.JobQueue()
    jobs = [
        make_job("b", priority=100, created="2026-01-01T00:00:01Z"),
        make_job("a", priority=100, created="2026-01-01T00:00:02Z"),
        make_job("z", priority=500, created="2026-01-01T00:00:09Z"),
        make_job("c", priority=100, created="2026-01-01T00:00:01Z"),
    ]
    for j in jobs:
        q.observe(j)
    order = [key for _r, key in q._waiting]
    # priority DESC, then creationTimestamp ASC, then name ASC.
    assert order == ["fleet/z", "fleet/b", "fleet/c", "fleet/a"]


def test_k_max_elastic_against_pool_and_quota():
    q = jq.JobQueue()
    q.set_nodes([{"metadata": {"labels": {
        "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
        "cloud.google.com/gke-tpu-topology": "2x4"}},
        "status": {"capacity": {"google.com/tpu": "8"}}}] * 3)
    q.set_quotas([make_quota("fleet", 16)])  # 2 slices' worth of chips
    q.observe(make_job("big", slices=4, min_slices=1))
    d = q._entries["fleet/big"].demand
    # pool allows 3, quota allows 2 -> elastic grant is 2.
    assert q._k_max(d) == 2
    assert q.decide("fleet", "big").action == "admit"
    assert q.decide("fleet", "big").slices == 2


def test_quota_accounting_counts_other_consumers_stored_usage():
    """The ledger must charge max(declared gang chips, the quota's
    stored status.used): a notebook holding 24 of 32 chips (visible only
    in status.used) leaves room for ONE 8-chip slice, not four — over-
    admitting here would create a gang whose pods the apiserver plugin
    partially 403s, the half-scheduled deadlock this queue prevents."""
    q = jq.JobQueue()
    quota = make_quota("fleet", 32)
    quota["status"] = {"used": {"google.com/tpu": "24"}}
    q.set_quotas([quota])
    q.observe(make_job("wants-four", slices=4, min_slices=1))
    d = q.decide("fleet", "wants-four")
    assert d.action == "admit" and d.slices == 1  # 32-24 = one 8-chip slice
    quota["status"]["used"]["google.com/tpu"] = "32"
    q.set_quotas([quota])
    d = q.decide("fleet", "wants-four")
    assert d.action == "wait" and d.reason == jq.REASON_QUOTA


def make_inference_service(name, ns="fleet", *, replicas, topology="2x4"):
    """An InferenceService holding ``replicas`` one-slice v5e replicas
    (8 chips each at 2x4), as the serving controller would have committed
    it (status.replicas is the ledger charge)."""
    return {
        "apiVersion": "kubeflow.org/v1alpha1", "kind": "InferenceService",
        "metadata": {"name": name, "namespace": ns},
        "spec": {"model": "llama_125m",
                 "tpu": {"accelerator": "v5e", "topology": topology}},
        "status": {"phase": "Ready", "replicas": replicas},
    }


def test_inference_service_scale_up_parks_tpujob_insufficient_quota():
    """THE serving-side quota weld (ISSUE 12 satellite): a model server's
    replica chips are declared charges in the SAME per-namespace ledger
    gangs admit against — a scale-up parks a pending TPUJob Queued with
    InsufficientQuota, and the scale-down lifts it."""
    q = jq.JobQueue()
    q.set_quotas([make_quota("fleet", 32)])
    # 2 serving replicas x 8 chips = 16 committed; a 2-slice gang (16
    # chips) still fits in the 32-chip profile.
    q.observe_service(make_inference_service("llm", replicas=2))
    q.observe(make_job("train", slices=2))
    assert q.decide("fleet", "train").action == "admit"
    # The autoscaler scales serving to 3 replicas (24 chips): the gang's
    # 16 no longer fit — it must park with the quota reason, never be
    # promised chips the model server holds.
    q.observe_service(make_inference_service("llm", replicas=3))
    d = q.decide("fleet", "train")
    assert d.action == "wait" and d.reason == jq.REASON_QUOTA
    assert "committed" in d.message
    # Scale back down (or to zero): the gang admits into the freed chips.
    q.observe_service(make_inference_service("llm", replicas=1))
    assert q.decide("fleet", "train").action == "admit"
    q.forget_service("fleet", "llm")
    assert q.decide("fleet", "train").slices == 2


def test_inference_service_headroom_clamps_serving_scale():
    """The reverse direction of the weld: serving scale-ups are clamped
    to the profile's free chips, with the service's own charge counted
    as free to itself."""
    q = jq.JobQueue()
    q.set_quotas([make_quota("fleet", 32)])
    q.observe(make_job("train", slices=2))        # waiting: holds nothing
    assert q.service_headroom("fleet") == 32.0
    # An admitted 2-slice gang commits 16 chips.
    admitted = make_job("train", slices=2)
    admitted["status"] = {"phase": "Running", "allocatedSlices": 2,
                          "generation": 0, "restarts": 0}
    q.observe(admitted)
    assert q.service_headroom("fleet") == 16.0
    # The service's own 8-chip replica is free capacity to itself.
    q.observe_service(make_inference_service("llm", replicas=1))
    assert q.service_headroom("fleet") == 8.0
    assert q.service_headroom("fleet", own_chips=8.0) == 16.0
    # No quota feed = unlimited (same contract as gang admission).
    assert q.service_headroom("other-ns") == float("inf")


def test_inference_service_rollout_charges_both_revisions():
    """While a rollout is in flight both revision Deployments run side
    by side — the ledger must charge the overlap, or a gang admits into
    chips the warming revision's pods hold."""
    from kubeflow_tpu.platform.apis import inferenceservice as svcapi

    svc = make_inference_service("llm", replicas=2)
    assert svcapi.chips_of(svc) == 16.0
    svc["status"]["targetRevision"] = 2
    svc["status"]["revision"] = 1
    assert svcapi.chips_of(svc) == 32.0  # serving 2 + warming 2
    q = jq.JobQueue()
    q.set_quotas([make_quota("fleet", 32)])
    q.observe_service(svc)
    q.observe(make_job("train", slices=1))
    d = q.decide("fleet", "train")
    assert (d.action, d.reason) == ("wait", jq.REASON_QUOTA)


def test_inference_service_charge_survives_rebuild():
    """Ledger rebuilds (restart, confirm()) recompute the serving charge
    from watch state — incremental observe and full refresh agree."""
    quotas = [make_quota("fleet", 32)]
    svc = make_inference_service("llm", replicas=3)
    job = make_job("train", slices=2)
    q1 = jq.JobQueue()
    q1.set_quotas(quotas)
    q1.observe_service(svc)
    q1.observe(job)
    q2 = jq.JobQueue()
    q2.refresh([job], quotas, [], [svc])
    for q in (q1, q2):
        d = q.decide("fleet", "train")
        assert (d.action, d.reason) == ("wait", jq.REASON_QUOTA)
    snap = q2.snapshot()
    assert snap["inferenceServiceChips"] == {"fleet/llm": 24.0}
    assert snap["namespaceCommittedChips"] == {"fleet": 24.0}


def test_unknown_pool_is_unlimited_but_empty_pool_blocks():
    q = jq.JobQueue()
    q.observe(make_job("nofeed", slices=3))
    # No node inventory at all: admission must not deadlock.
    assert q.decide("fleet", "nofeed").action == "admit"
    # A known pool with too few hosts DOES block.
    q2 = jq.JobQueue()
    q2.set_nodes([{"metadata": {"labels": {
        "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
        "cloud.google.com/gke-tpu-topology": "2x4"}},
        "status": {"capacity": {"google.com/tpu": "8"}}}])
    q2.observe(make_job("toobig", slices=3, min_slices=2))
    d2 = q2.decide("fleet", "toobig")
    assert d2.action == "wait" and d2.reason == jq.REASON_CAPACITY


def test_head_of_line_blocks_smaller_lower_rank_job():
    kube = kube_with_slots(2)
    q = jq.JobQueue()
    q.set_nodes(kube.list(
        __import__("kubeflow_tpu.platform.k8s.types",
                   fromlist=["NODE"]).NODE, None))
    q.observe(make_job("head", slices=4, min_slices=4, priority=200,
                       created="2026-01-01T00:00:01Z"))
    q.observe(make_job("small", slices=1, priority=100,
                       created="2026-01-01T00:00:02Z"))
    # head does not fit (needs 4 of 2) and cannot preempt (nothing
    # admitted): it waits on capacity...
    d = q.decide("fleet", "head")
    assert d.action == "wait" and d.reason == jq.REASON_CAPACITY
    # ...and small, though it fits, must NOT jump an inadmissible head?
    # No — head-of-line blocks only behind an ADMISSIBLE better-ranked
    # waiter; a head that cannot fit at all does not dam the queue.
    assert q.decide("fleet", "small").action == "admit"
    # Once the head CAN fit, the small job yields the right of way.
    for i in range(2):
        kube.add_tpu_node(f"extra-{i}", topology="2x4")
    q.set_nodes(kube.list(
        __import__("kubeflow_tpu.platform.k8s.types",
                   fromlist=["NODE"]).NODE, None))
    assert q.decide("fleet", "head").action == "admit"
    d = q.decide("fleet", "small")
    assert d.action == "wait" and d.reason == jq.REASON_QUEUED_BEHIND


def test_preemption_picks_lowest_priority_youngest_minimal_set():
    q = jq.JobQueue()
    q.set_nodes([{"metadata": {"labels": {
        "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
        "cloud.google.com/gke-tpu-topology": "2x4"}},
        "status": {"capacity": {"google.com/tpu": "8"}}}] * 4)

    def admitted(name, *, priority, alloc, created):
        j = make_job(name, priority=priority, slices=alloc,
                     created=created)
        j["status"] = {"phase": "Running", "allocatedSlices": alloc,
                       "generation": 0, "restarts": 0}
        q.observe(j)

    admitted("old-low", priority=50, alloc=1,
             created="2026-01-01T00:00:01Z")
    admitted("young-low", priority=50, alloc=1,
             created="2026-01-01T00:00:05Z")
    admitted("mid", priority=100, alloc=2,
             created="2026-01-01T00:00:02Z")
    q.observe(make_job("high", priority=500, slices=1, min_slices=1,
                       created="2026-01-01T00:00:09Z"))
    # One slice needed: exactly ONE victim — the YOUNGEST lowest-priority
    # gang — never mid (higher priority), never both lows.
    assert q.should_yield("fleet", "young-low") == ("fleet/high",
                                                   "priority")
    assert q.should_yield("fleet", "old-low") is None
    assert q.should_yield("fleet", "mid") is None
    d = q.decide("fleet", "high")
    assert d.action == "wait" and d.reason == jq.REASON_AWAITING_PREEMPTION


def test_preemption_skips_victims_that_relax_no_binding_constraint():
    """A pool-blocked head must never evict a gang from ANOTHER pool just
    because it shares the head's namespace (freeing chips the head
    doesn't need) — minimality means every victim moves k_max."""
    q = jq.JobQueue()
    nodes_2x4 = [{"metadata": {"labels": {
        "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
        "cloud.google.com/gke-tpu-topology": "2x4"}},
        "status": {"capacity": {"google.com/tpu": "8"}}}]
    nodes_4x4 = [{"metadata": {"labels": {
        "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
        "cloud.google.com/gke-tpu-topology": "4x4"}},
        "status": {"capacity": {"google.com/tpu": "8"}}}] * 2
    q.set_nodes(nodes_2x4 + nodes_4x4)
    # Same namespace, OTHER pool (4x4), lowest priority — useless to a
    # 2x4-blocked head, must not be chosen.
    other_pool = make_job("other-pool", priority=10, slices=1,
                          topology="4x4", created="2026-01-01T00:00:05Z")
    other_pool["status"] = {"phase": "Running", "allocatedSlices": 1,
                            "generation": 0, "restarts": 0}
    q.observe(other_pool)
    same_pool = make_job("same-pool", priority=50, slices=1,
                         created="2026-01-01T00:00:01Z")
    same_pool["status"] = {"phase": "Running", "allocatedSlices": 1,
                           "generation": 0, "restarts": 0}
    q.observe(same_pool)
    q.observe(make_job("head", priority=500, slices=1))
    assert q.should_yield("fleet", "same-pool") == ("fleet/head",
                                                    "priority")
    assert q.should_yield("fleet", "other-pool") is None


def test_equal_priority_never_preempts():
    q = jq.JobQueue()
    q.set_nodes([{"metadata": {"labels": {
        "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
        "cloud.google.com/gke-tpu-topology": "2x4"}},
        "status": {"capacity": {"google.com/tpu": "8"}}}])
    j = make_job("incumbent", priority=100, slices=1)
    j["status"] = {"phase": "Running", "allocatedSlices": 1,
                   "generation": 0, "restarts": 0}
    q.observe(j)
    q.observe(make_job("rival", priority=100, slices=1))
    assert q.should_yield("fleet", "incumbent") is None
    d = q.decide("fleet", "rival")
    assert d.action == "wait" and d.reason == jq.REASON_CAPACITY


def test_capacity_shrink_yields_lowest_ranked_gang():
    nodes = [{"metadata": {"labels": {
        "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
        "cloud.google.com/gke-tpu-topology": "2x4"}},
        "status": {"capacity": {"google.com/tpu": "8"}}}] * 2
    q = jq.JobQueue()
    q.set_nodes(nodes)
    for name, prio in (("keep", 200), ("shed", 50)):
        j = make_job(name, priority=prio, slices=1, min_slices=1)
        j["status"] = {"phase": "Running", "allocatedSlices": 1,
                       "generation": 0, "restarts": 0}
        q.observe(j)
    assert q.should_yield("fleet", "shed") is None
    q.set_nodes(nodes[:1])  # the fleet shrank under the gangs
    assert q.should_yield("fleet", "shed") == ("", "capacity")
    assert q.should_yield("fleet", "keep") is None


def test_incremental_observe_matches_full_rebuild():
    kube = kube_with_slots(3)
    kube.create(make_quota("fleet", 24))
    jobs = [make_job(f"j{i}", slices=1 + i % 2, min_slices=1,
                     priority=(i % 3 + 1) * 100) for i in range(8)]
    inc = jq.JobQueue()
    from kubeflow_tpu.platform.k8s.types import NODE, RESOURCEQUOTA
    inc.set_nodes(kube.list(NODE, None))
    inc.set_quotas(kube.list(RESOURCEQUOTA, None))
    for j in jobs:
        kube.create(j)
        inc.observe(kube.get(TPUJOB, j["metadata"]["name"], "fleet"))
    full = jq.JobQueue(kube)
    full.ensure_fresh()
    assert [k for _r, k in inc._waiting] == [k for _r, k in full._waiting]
    for name in ("j0", "j3", "j7"):
        assert inc.decide("fleet", name).action == \
            full.decide("fleet", name).action
    assert inc.depth_by_namespace() == full.depth_by_namespace()
    assert inc.allocated_total() == full.allocated_total()


def test_snapshot_shape_for_debug_endpoint():
    q = jq.JobQueue()
    q.observe(make_job("waiting-job", slices=2, min_slices=1,
                       priority=300))
    j = make_job("running-job", slices=2)
    j["status"] = {"phase": "Running", "allocatedSlices": 2,
                   "generation": 0, "restarts": 0}
    q.observe(j)
    snap = q.snapshot()
    assert snap["waiting"][0]["key"] == "fleet/waiting-job"
    assert snap["waiting"][0]["priority"] == 300
    assert snap["admitted"][0]["key"] == "fleet/running-job"
    assert snap["admitted"][0]["allocatedSlices"] == 2
    jq.register_debug_queue(q)
    try:
        assert jq.debug_snapshot() == snap
    finally:
        jq.register_debug_queue(None)
    assert jq.debug_snapshot() is None


# -- controller flows ---------------------------------------------------------


def test_job_parks_queued_with_structured_reason_then_admits():
    kube = kube_with_slots(1)
    kube.create(make_job("holder", slices=1))
    drive(kube, ["holder"])
    assert phase(kube, "holder") == "Pending" and alloc(kube, "holder") == 1
    kube.create(make_job("parked", slices=1))
    result = reconcile(kube, "parked")
    job = kube.get(TPUJOB, "parked", "fleet")
    assert jobapi.phase_of(job) == "Queued"
    assert deep_get(job, "status", "reason") == jq.REASON_CAPACITY
    conds = {c["type"]: c for c in deep_get(
        job, "status", "conditions", default=[])}
    assert conds["Unschedulable"]["status"] == "True"
    assert "free slice slot" in conds["Unschedulable"]["message"]
    assert jobapi.queued_at(job) is not None
    assert result is not None and result.requeue_after  # polls the ledger
    # Nothing was created: no half-gang ever.
    with pytest.raises(errors.NotFound):
        kube.get(STATEFULSET, "parked", "fleet")
    # Capacity frees (holder completes) -> parked admits, reason cleared.
    run_gang(kube, "holder")
    drive(kube, ["holder"])
    finish_gang(kube, "holder")
    drive(kube, ["holder", "parked"])
    job = kube.get(TPUJOB, "parked", "fleet")
    assert jobapi.allocated_slices(job) == 1
    assert deep_get(job, "status", "reason") is None
    assert not deep_get(job, "status", "conditions", default=[])
    kube.get(STATEFULSET, "parked", "fleet")


def test_queue_drains_in_priority_then_fifo_order():
    kube = kube_with_slots(1)
    kube.create(make_job("holder", slices=1))
    drive(kube, ["holder"])
    run_gang(kube, "holder")
    # Same-priority FIFO pair + one later high-priority jumper.
    kube.create(make_job("fifo-1", slices=1, priority=100,
                         created="2026-01-01T00:00:01Z"))
    kube.create(make_job("fifo-2", slices=1, priority=100,
                         created="2026-01-01T00:00:02Z"))
    kube.create(make_job("vip", slices=1, priority=400,
                         created="2026-01-01T00:00:09Z"))
    names = ["holder", "fifo-1", "fifo-2", "vip"]
    drive(kube, names)
    assert [phase(kube, n) for n in ("fifo-1", "fifo-2", "vip")] == \
        ["Queued"] * 3

    admitted_order = []

    def drain_one(finishing):
        finish_gang(kube, finishing)
        drive(kube, names)
        for n in ("vip", "fifo-1", "fifo-2"):
            if n not in admitted_order and alloc(kube, n) is not None:
                admitted_order.append(n)
                run_gang(kube, n)
                drive(kube, names)

    drain_one("holder")
    drain_one(admitted_order[0])
    drain_one(admitted_order[1])
    assert admitted_order == ["vip", "fifo-1", "fifo-2"]


def test_quota_park_reports_insufficient_quota_and_lifts():
    kube = kube_with_slots(4)
    kube.create(make_quota("fleet", 8))  # one 8-chip slice
    kube.create(make_job("first", slices=1))
    drive(kube, ["first"])
    assert alloc(kube, "first") == 1
    kube.create(make_job("second", slices=1))
    drive(kube, ["second"])
    job = kube.get(TPUJOB, "second", "fleet")
    assert jobapi.phase_of(job) == "Queued"
    assert deep_get(job, "status", "reason") == jq.REASON_QUOTA
    # Admin raises the quota -> the job admits on its next poll.
    quota = kube.get(
        __import__("kubeflow_tpu.platform.k8s.types",
                   fromlist=["RESOURCEQUOTA"]).RESOURCEQUOTA,
        "kf-resource-quota", "fleet")
    quota["spec"]["hard"]["google.com/tpu"] = "16"
    kube.update(quota)
    drive(kube, ["second"])
    assert alloc(kube, "second") == 1


def test_two_phase_preemption_checkpoints_then_frees_never_half_admits():
    kube = kube_with_slots(2)
    kube.create(make_job("victim", slices=2, min_slices=1, priority=50,
                         checkpoint_dir="/ckpt/victim"))
    drive(kube, ["victim"])
    run_gang(kube, "victim")
    drive(kube, ["victim"])
    assert phase(kube, "victim") == "Running"

    kube.create(make_job("vip", slices=2, min_slices=2, priority=500))
    names = ["victim", "vip"]
    # First pass: vip waits on preemption; victim marks Preempting and
    # tears its StatefulSets (the SIGTERM path) but KEEPS its chips.
    reconcile(kube, "vip")
    job = kube.get(TPUJOB, "vip", "fleet")
    assert jobapi.phase_of(job) == "Queued"
    assert deep_get(job, "status", "reason") == \
        jq.REASON_AWAITING_PREEMPTION
    reconcile(kube, "victim")
    job = kube.get(TPUJOB, "victim", "fleet")
    assert jobapi.phase_of(job) == "Preempting"
    assert jobapi.allocated_slices(job) == 2  # still charged: phase 1
    assert deep_get(job, "status", "preemption", "by") == "fleet/vip"
    for sts_name in ("victim", "victim-s1"):
        with pytest.raises(errors.NotFound):
            kube.get(STATEFULSET, sts_name, "fleet")
    # The preemptor is NEVER half-admitted into still-held capacity.
    reconcile(kube, "vip")
    assert alloc(kube, "vip") is None
    with pytest.raises(errors.NotFound):
        kube.get(STATEFULSET, "vip", "fleet")
    # Phase 2 after the checkpoint grace: chips released, victim
    # re-queued, vip admitted WHOLE.
    time.sleep(0.08)
    drive(kube, names)
    victim = kube.get(TPUJOB, "victim", "fleet")
    assert jobapi.phase_of(victim) == "Queued"
    assert jobapi.allocated_slices(victim) is None
    # The reason is LIVE: "Preempted" right after eviction, then the
    # current blocking cause once the poll re-evaluates the ledger.
    assert deep_get(victim, "status", "reason") in (
        jq.REASON_PREEMPTED, jq.REASON_CAPACITY)
    assert alloc(kube, "vip") == 2
    kube.get(STATEFULSET, "vip", "fleet")
    kube.get(STATEFULSET, "vip-s1", "fleet")
    # Restarts untouched: a preemption is not a failure.
    assert jobapi.restarts_of(victim) == 0
    assert jobapi.generation_of(victim) == 0


def test_preempted_job_resumes_elastically_then_grows_back():
    kube = kube_with_slots(3)
    kube.create(make_job("low", slices=3, min_slices=1, priority=50,
                         checkpoint_dir="/ckpt/low"))
    drive(kube, ["low"])
    run_gang(kube, "low")
    drive(kube, ["low"])
    # vip (2 slices) preempts low (holds all 3).
    kube.create(make_job("vip", slices=2, priority=500))
    names = ["low", "vip"]
    drive(kube, names)
    time.sleep(0.08)
    drive(kube, names)
    run_gang(kube, "vip")
    drive(kube, names)
    # low resumed ELASTICALLY at 1 of 3 slices (generation bumped — a new
    # gang resumes the same checkpoint), vip holds 2.
    low = kube.get(TPUJOB, "low", "fleet")
    assert jobapi.allocated_slices(low) == 1
    assert jobapi.generation_of(low) == 1
    assert jobapi.restarts_of(low) == 0
    sts = kube.get(STATEFULSET, "low", "fleet")
    env = {e["name"]: e.get("value") for e in deep_get(
        sts, "spec", "template", "spec", "containers")[0]["env"]}
    assert env["MEGASCALE_NUM_SLICES"] == "1"
    assert env["KFT_SPEC_SLICES"] == "3"
    with pytest.raises(errors.NotFound):
        kube.get(STATEFULSET, "low-s1", "fleet")
    run_gang(kube, "low")
    drive(kube, names)
    assert phase(kube, "low") == "Running"
    # vip finishes -> low grows back to its full 3 slices via a graceful
    # checkpoint-restart (generation bumps again, restarts still 0).
    finish_gang(kube, "vip")
    drive(kube, names)
    time.sleep(0.08)
    drive(kube, names)
    low = kube.get(TPUJOB, "low", "fleet")
    assert jobapi.allocated_slices(low) == 3, low.get("status")
    assert jobapi.generation_of(low) == 2
    assert jobapi.restarts_of(low) == 0
    for s in ("low", "low-s1", "low-s2"):
        sts = kube.get(STATEFULSET, s, "fleet")
        env = {e["name"]: e.get("value") for e in deep_get(
            sts, "spec", "template", "spec", "containers")[0]["env"]}
        assert env["MEGASCALE_NUM_SLICES"] == "3"


def test_crashlooping_high_priority_job_cannot_starve_queue():
    kube = kube_with_slots(1)
    kube.create(make_job("looper", slices=1, priority=900,
                         backoff_limit=1))
    kube.create(make_job("patient", slices=1, priority=100))
    drive(kube, ["looper", "patient"])
    assert alloc(kube, "looper") == 1
    assert phase(kube, "patient") == "Queued"
    # The looper's worker crashes, restarts (backoffLimit 1), crashes
    # again -> terminally Failed -> the queue drains to patient.
    for _ in range(2):
        run_gang(kube, "looper")
        for pod in kube.list(
                POD, "fleet",
                label_selector={jobapi.LABEL_TPUJOB_NAME: "looper"}):
            kube.set_pod_phase("fleet", name_of(pod), "Failed")
        drive(kube, ["looper", "patient"])
    looper = kube.get(TPUJOB, "looper", "fleet")
    assert jobapi.phase_of(looper) == "Failed"
    assert jobapi.restarts_of(looper) == 1
    assert alloc(kube, "patient") == 1
    assert phase(kube, "patient") == "Pending"


def test_queue_state_survives_controller_restart():
    """Every decision input lives in statuses/quotas/nodes: a brand-new
    reconciler (fresh ledger) must reach the same schedule — the rebuilt-
    from-informer-caches contract that makes the queue HA-safe."""
    kube = kube_with_slots(1)
    kube.create(make_job("holder", slices=1, priority=100,
                         created="2026-01-01T00:00:00Z"))
    kube.create(make_job("waiter-b", slices=1, priority=100,
                         created="2026-01-01T00:00:02Z"))
    kube.create(make_job("waiter-a", slices=1, priority=100,
                         created="2026-01-01T00:00:01Z"))
    drive(kube, ["holder", "waiter-a", "waiter-b"])
    assert alloc(kube, "holder") == 1
    # "Restart": all further reconciles use fresh reconcilers anyway (the
    # drive() helper constructs one per call) — free capacity and check
    # FIFO held across the rebuild.
    run_gang(kube, "holder")
    finish_gang(kube, "holder")
    drive(kube, ["holder", "waiter-b", "waiter-a"])
    assert alloc(kube, "waiter-a") == 1
    assert phase(kube, "waiter-b") == "Queued"


def test_queue_metrics_depth_wait_preemptions_allocated():
    from kubeflow_tpu.platform.runtime import metrics

    kube = kube_with_slots(1)
    kube.create(make_job("m-holder", slices=1, priority=50))
    drive(kube, ["m-holder"])
    run_gang(kube, "m-holder")
    before = metrics.tpujob_preemptions_total.labels(
        reason="priority")._value.get()
    wait_before = metrics.tpujob_queue_wait_seconds._sum.get()
    kube.create(make_job("m-waiter", slices=1, priority=100))
    drive(kube, ["m-holder", "m-waiter"])
    assert metrics.tpujob_queue_depth.labels(
        profile="fleet")._value.get() == 1
    assert metrics.tpujob_slices_allocated._value.get() == 1
    # The waiter preempts (higher priority), the victim drains, the
    # waiter admits -> wait histogram observed, preemption counted.
    drive(kube, ["m-holder", "m-waiter"])
    time.sleep(0.08)
    drive(kube, ["m-holder", "m-waiter"])
    assert metrics.tpujob_preemptions_total.labels(
        reason="priority")._value.get() == before + 1
    assert alloc(kube, "m-waiter") == 1
    assert metrics.tpujob_queue_wait_seconds._sum.get() >= wait_before
    assert metrics.tpujob_queue_depth.labels(
        profile="fleet")._value.get() == 1  # the evicted holder re-queued


# -- chaos + HA pins (the queue-chaos postsubmit lane) ------------------------


def _watch_admission_order(kube, ns, stop, order, lock):
    for _etype, job in kube.watch(TPUJOB, ns, stop=stop):
        if jobapi.allocated_slices(job) is not None:
            name = job["metadata"]["name"]
            with lock:
                if name not in order:
                    order.append(name)


@pytest.mark.slow
def test_priority_storm_drains_in_order_with_invariants():
    """The queue under fire (queue-chaos lane): 9 jobs of three
    priorities into a 2-slot budget with a seeded ChaosKube storm on the
    controller's whole apiserver path.  The queue must drain in
    priority-then-FIFO order, with zero dead-letters, zero half-gangs,
    and no job lost."""
    from kubeflow_tpu.platform.controllers import tpujob as jobctrl
    from kubeflow_tpu.platform.runtime.controller import make_workqueue
    from kubeflow_tpu.platform.testing.chaos import ChaosKube, storm
    from kubeflow_tpu.platform.testing.jobsim import TpuJobGangSim

    kube = kube_with_slots(2)
    chaos = ChaosKube(kube, storm(rate=0.05, max_injections=60),
                      seed=20260804)
    sim = TpuJobGangSim(kube, "fleet")  # kubelet: pods come up Running
    ctrl = jobctrl.make_controller(
        chaos, preemption_grace=0.1, queue_poll=0.1)
    ctrl.workers = 4
    ctrl.queue = make_workqueue(base_delay=0.05, max_delay=2.0)
    stop = threading.Event()
    order, lock = [], threading.Lock()
    watcher = threading.Thread(
        target=_watch_admission_order,
        args=(kube, "fleet", stop, order, lock), daemon=True)
    watcher.start()
    ctrl.start(chaos)
    names = []
    try:
        # Fill the budget FIRST with unpreemptable holders so a real
        # queue forms (admission order is only defined among jobs that
        # actually wait together — the queue is not clairvoyant about
        # jobs submitted later).
        for h in range(2):
            kube.create(make_job(f"holder-{h}", slices=1, priority=900))
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if all(jobapi.allocated_slices(
                    kube.get(TPUJOB, f"holder-{h}", "fleet")) is not None
                   for h in range(2)):
                break
            time.sleep(0.05)
        for i, prio in enumerate([100, 100, 100, 300, 300, 300,
                                  500, 500, 500]):
            name = f"sj-{prio}-{i}"
            names.append(name)
            kube.create(make_job(name, slices=1, priority=prio,
                                 created=f"2026-01-01T00:00:{i:02d}Z"))

        def drained():
            with lock:
                return len([n for n in order
                            if n.startswith("sj-")]) >= len(names)

        def all_succeeded():
            return all(jobapi.phase_of(j) == "Succeeded"
                       for j in kube.list(TPUJOB, "fleet"))

        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline and not (
                drained() and all_succeeded()):
            # Complete whatever is currently admitted so the queue moves.
            for job in kube.list(TPUJOB, "fleet"):
                if (jobapi.phase_of(job) == "Running"):
                    finish_gang(kube, job["metadata"]["name"])
            time.sleep(0.1)
        chaos.pause()
        assert drained(), (order, [
            (j["metadata"]["name"], j.get("status"))
            for j in kube.list(TPUJOB, "fleet")])
        with lock:
            got = [n for n in order if n.startswith("sj-")]
        # Priority bands drain high-to-low; FIFO inside each band.
        expected = sorted(
            names, key=lambda n: (-int(n.split("-")[1]),
                                  int(n.split("-")[2])))
        assert got == expected, (got, expected)
        assert not ctrl.dead_letters
        # No half-gangs anywhere: every admitted generation materialized
        # exactly its granted StatefulSet count before completing.
        for job in kube.list(TPUJOB, "fleet"):
            assert jobapi.phase_of(job) == "Succeeded", job.get("status")
    finally:
        stop.set()
        ctrl.stop()
        sim.close()
    assert chaos.injected() > 0, "the storm never stormed"


@pytest.mark.slow
def test_sharded_replica_kill_preserves_drain_order():
    """ISSUE 11 acceptance (HA half): the queue is a pure function of
    watch state, so a replica kill mid-drain must not reorder it — the
    survivor absorbs the dead replica's keys and keeps admitting in
    priority-then-FIFO order, with every write fenced and zero
    dead-letters / lost jobs / duplicate gangs."""
    from kubeflow_tpu.platform.controllers import tpujob as jobctrl
    from kubeflow_tpu.platform.testing.shardfleet import ShardedFleet

    fleet = ShardedFleet(
        replicas=2, num_shards=4, workers=2,
        lease_seconds=0.5, renew_seconds=0.05,
        controller_factory=lambda client, **kw: jobctrl.make_controller(
            client, preemption_grace=0.1, queue_poll=0.1, **kw),
        tpu_nodes=2)  # 2-slot budget: a real queue forms
    ns = fleet.namespace
    stop = threading.Event()
    order, lock = [], threading.Lock()
    watcher = threading.Thread(
        target=_watch_admission_order,
        args=(fleet.kube, ns, stop, order, lock), daemon=True)
    watcher.start()
    names = []
    try:
        fleet.wait_stable_shard_map()
        # Fill the 2-slot budget with unpreemptable holders so the six
        # test jobs all queue TOGETHER (admission order is only defined
        # among jobs waiting at the same time — the queue is not
        # clairvoyant about later submissions).
        for h in range(2):
            fleet.kube.create(make_job(f"holder-{h}", ns=ns, slices=1,
                                       priority=900))
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if all(jobapi.allocated_slices(fleet.kube.get(
                    TPUJOB, f"holder-{h}", ns)) is not None
                   for h in range(2)):
                break
            time.sleep(0.05)
        for i, prio in enumerate([100, 100, 400, 400, 200, 200]):
            name = f"kj-{prio}-{i}"
            names.append(name)
            fleet.kube.create(make_job(
                name, ns=ns, slices=1, priority=prio,
                created=f"2026-01-01T00:00:{i:02d}Z"))

        def admitted_count():
            with lock:
                return len([n for n in order if n.startswith("kj-")])

        # THE kill: one replica dies while the whole queue is parked —
        # the survivor absorbs its shards and must drain everything in
        # rank order.
        fleet.kill(0)
        deadline = time.monotonic() + 180.0
        while time.monotonic() < deadline and admitted_count() < len(names):
            for job in fleet.kube.list(TPUJOB, ns):
                if jobapi.phase_of(job) == "Running":
                    finish_gang(fleet.kube, job["metadata"]["name"], ns)
            time.sleep(0.1)
        assert admitted_count() == len(names), (order, [
            (j["metadata"]["name"], j.get("status"))
            for j in fleet.kube.list(TPUJOB, ns)])
        with lock:
            got = [n for n in order if n.startswith("kj-")]
        expected = sorted(
            names, key=lambda n: (-int(n.split("-")[1]),
                                  int(n.split("-")[2])))
        assert got == expected, (got, expected)
        checked = fleet.assert_fencing_invariant(
            kinds={"StatefulSet", "Service", "TPUJob"})
        assert checked > 0
        for r in fleet.replicas:
            if r.alive:
                assert not r.controller.dead_letters
    finally:
        stop.set()
        fleet.close()
