import threading

import pytest

from kubeflow_tpu.platform.k8s import errors
from kubeflow_tpu.platform.k8s.types import CONFIGMAP, NAMESPACE, NOTEBOOK, POD, new, set_owner
from kubeflow_tpu.platform.testing import FakeKube


@pytest.fixture
def kube():
    k = FakeKube()
    k.add_namespace("user1")
    return k


def test_create_get_roundtrip(kube):
    obj = new(CONFIGMAP, "cm", "user1")
    obj["data"] = {"k": "v"}
    created = kube.create(obj)
    assert created["metadata"]["uid"]
    assert created["metadata"]["resourceVersion"]
    got = kube.get(CONFIGMAP, "cm", "user1")
    assert got["data"] == {"k": "v"}


def test_create_requires_namespace(kube):
    with pytest.raises(errors.NotFound):
        kube.create(new(CONFIGMAP, "cm", "nope"))
    with pytest.raises(errors.Invalid):
        kube.create({"apiVersion": "v1", "kind": "ConfigMap", "metadata": {}})


def test_duplicate_create_conflicts(kube):
    kube.create(new(CONFIGMAP, "cm", "user1"))
    with pytest.raises(errors.Conflict):
        kube.create(new(CONFIGMAP, "cm", "user1"))


def test_update_bumps_rv_and_detects_conflict(kube):
    created = kube.create(new(CONFIGMAP, "cm", "user1"))
    stale_rv = created["metadata"]["resourceVersion"]
    created["data"] = {"a": "1"}
    updated = kube.update(created)
    assert updated["metadata"]["resourceVersion"] != stale_rv
    created["metadata"]["resourceVersion"] = stale_rv
    with pytest.raises(errors.Conflict):
        kube.update(created)


def test_status_is_a_subresource(kube):
    nb = new(NOTEBOOK, "nb", "user1")
    nb["spec"] = {"template": {"spec": {"containers": [{"image": "x"}]}}}
    created = kube.create(nb)
    created["status"] = {"readyReplicas": 1}
    kube.update_status(created)
    # A spec update must not clobber status…
    got = kube.get(NOTEBOOK, "nb", "user1")
    got["spec"]["tpu"] = {"accelerator": "v5e"}
    kube.update(got)
    assert kube.get(NOTEBOOK, "nb", "user1")["status"] == {"readyReplicas": 1}
    # …and a status update must not clobber spec.
    s = kube.get(NOTEBOOK, "nb", "user1")
    s["spec"] = {"junk": True}
    s["status"] = {"readyReplicas": 2}
    kube.update_status(s)
    final = kube.get(NOTEBOOK, "nb", "user1")
    assert final["spec"]["tpu"] == {"accelerator": "v5e"}
    assert final["status"] == {"readyReplicas": 2}


def test_label_selector_list(kube):
    for i, team in enumerate(["a", "a", "b"]):
        obj = new(CONFIGMAP, f"cm{i}", "user1", labels={"team": team})
        kube.create(obj)
    assert len(kube.list(CONFIGMAP, "user1", label_selector={"team": "a"})) == 2


def test_owner_cascade_delete(kube):
    nb = kube.create(
        {"apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
         "metadata": {"name": "nb", "namespace": "user1"}}
    )
    child = new(POD, "nb-0", "user1")
    set_owner(child, nb)
    kube.create(child)
    grandchild = new(CONFIGMAP, "cm", "user1")
    set_owner(grandchild, kube.get(POD, "nb-0", "user1"))
    kube.create(grandchild)
    kube.delete(NOTEBOOK, "nb", "user1")
    with pytest.raises(errors.NotFound):
        kube.get(POD, "nb-0", "user1")
    with pytest.raises(errors.NotFound):
        kube.get(CONFIGMAP, "cm", "user1")


def test_merge_patch(kube):
    obj = new(CONFIGMAP, "cm", "user1")
    obj["data"] = {"keep": "1", "drop": "2"}
    kube.create(obj)
    out = kube.patch(CONFIGMAP, "cm", {"data": {"drop": None, "new": "3"}}, "user1")
    assert out["data"] == {"keep": "1", "new": "3"}


def test_watch_sees_initial_state_and_updates(kube):
    kube.create(new(CONFIGMAP, "cm0", "user1"))
    stop = threading.Event()
    events = []

    def consume():
        for evt in kube.watch(CONFIGMAP, "user1", stop=stop):
            events.append(evt)
            if len(events) >= 2:
                stop.set()

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    import time

    time.sleep(0.2)
    kube.create(new(CONFIGMAP, "cm1", "user1"))
    t.join(timeout=5)
    assert [e[0] for e in events] == ["ADDED", "ADDED"]
    assert {e[1]["metadata"]["name"] for e in events} == {"cm0", "cm1"}


def test_generate_name(kube):
    obj = {"apiVersion": "v1", "kind": "ConfigMap",
           "metadata": {"generateName": "ev-", "namespace": "user1"}}
    a, b = kube.create(dict(obj)), kube.create(dict(obj))
    assert a["metadata"]["name"] != b["metadata"]["name"]
    assert a["metadata"]["name"].startswith("ev-")


def test_error_for_status_reasonless_409_is_generic_conflict():
    """A 409 whose Status body lacks a reason is an optimistic-concurrency
    conflict (resourceVersion mismatch), not a create collision; it must
    not classify as AlreadyExists (which also carries status 409)."""
    err = errors.error_for_status(409, "rv mismatch", body={})
    assert type(err) is errors.Conflict
    # With the explicit reason, the subclass is still selected.
    err = errors.error_for_status(
        409, "exists", body={"reason": "AlreadyExists"})
    assert type(err) is errors.AlreadyExists


def test_watch_resume_replays_history_window(kube):
    """A watch resuming from a resourceVersion replays the events that
    happened after it (the etcd window a real apiserver serves) — without
    this, every event between an informer's LIST and its WATCH was lost
    until resync (round-5 fix)."""
    import threading

    nb = {
        "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
        "metadata": {"name": "nb", "namespace": "user1"},
        "spec": {"template": {"spec": {"containers": [{"name": "c"}]}}},
    }
    created = kube.create(nb)
    rv = created["metadata"]["resourceVersion"]
    # Mutations AFTER the captured rv, BEFORE the watch starts:
    cur = kube.get(NOTEBOOK, "nb", "user1")
    cur["metadata"]["annotations"] = {"a": "1"}
    kube.update(cur)
    kube.delete(NOTEBOOK, "nb", "user1")

    stop = threading.Event()
    seen = []
    for etype, obj in kube.watch(NOTEBOOK, "user1", resource_version=rv,
                                 stop=stop):
        seen.append((etype, obj["metadata"]["name"]))
        if etype == "DELETED":
            break
    assert ("MODIFIED", "nb") in seen and ("DELETED", "nb") in seen
    # and nothing from before/at the resume point:
    assert ("ADDED", "nb") not in seen


def test_watch_resume_too_old_gets_410_error(kube):
    """A resume older than the retained history answers a single 410-style
    ERROR event (compacted etcd semantics); informers relist on it."""
    kube.WATCH_HISTORY = 4  # shrink the window for the test
    for i in range(8):
        kube.create({
            "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
            "metadata": {"name": f"nb-{i}", "namespace": "user1"},
            "spec": {"template": {"spec": {"containers": [{"name": "c"}]}}},
        })
    events = list(kube.watch(NOTEBOOK, "user1", resource_version="1"))
    assert len(events) == 1
    etype, obj = events[0]
    assert etype == "ERROR" and obj.get("code") == 410


def test_watch_registration_is_atomic_with_backlog(kube):
    """No event can fall between the backlog snapshot and live delivery:
    registration happens at watch() CALL time under the store lock."""
    import threading

    stream = kube.watch(NOTEBOOK, "user1", stop=threading.Event())
    # An event fired AFTER watch() returned but BEFORE iteration begins
    # must be delivered (the lazy-generator bug lost it).
    kube.create({
        "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
        "metadata": {"name": "late", "namespace": "user1"},
        "spec": {"template": {"spec": {"containers": [{"name": "c"}]}}},
    })
    etype, obj = next(iter(stream))
    assert (etype, obj["metadata"]["name"]) == ("ADDED", "late")
