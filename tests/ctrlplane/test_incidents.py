"""The incident flight recorder (ISSUE 16): a firing burn-rate
transition captures ONE bounded deterministic evidence bundle — the TSDB
burn window, worst-object journeys, the covering profile window, live
debug snapshots and knob state — debounced per alert, ring-bounded,
announced fleet-wide by exactly one Event (the PR-15 dedup discipline),
and served at /debug/incidents."""
from __future__ import annotations

import json
import urllib.error
import urllib.request

from kubeflow_tpu.platform.k8s.types import EVENT, deep_get
from kubeflow_tpu.platform import main as main_mod
from kubeflow_tpu.platform.testing import FakeKube
from kubeflow_tpu.telemetry import causal, incidents, profiler, slo
from kubeflow_tpu.telemetry.tsdb import TSDB

from .test_slo import TTFT_BUCKET, feed, rule


def _get(url: str) -> bytes:
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.read()


def _recorder(db, **kw):
    kw.setdefault("client", None)
    kw.setdefault("now", lambda: 10.0)
    return incidents.IncidentRecorder(db, **kw)


def _plant_journey(trace_id: str, *, at: float, duration_s: float):
    causal.STORE.record(
        "reconcile", trace_id=trace_id, segment="reconcile",
        start_ts=at - duration_s, end_ts=at)


def test_firing_transition_captures_one_bundle_with_evidence():
    """The tentpole wiring: RuleEngine._transition(firing) →
    recorder.capture() → one bundle whose sections carry the burn-window
    TSDB export, the ranked worst journeys, the covering profile window,
    the recorded series, live snapshots and knobs — plus the counter
    bump and the announce Event."""
    from kubeflow_tpu.platform.runtime import metrics

    kube = FakeKube()
    kube.add_namespace("kubeflow")
    db = TSDB()
    feed(db, at=5.0, good=0, total=0)
    feed(db, at=10.0, good=0, total=50)
    causal.STORE.clear()  # earlier tests' fake-clock spans must not rank
    # Two journeys inside the burn window, one outside: the bundle keeps
    # the in-window ones ranked worst-first.
    _plant_journey("a" * 32, at=9.0, duration_s=2.0)
    _plant_journey("b" * 32, at=9.5, duration_s=5.0)
    _plant_journey("c" * 32, at=-4000.0, duration_s=9.0)  # aged out
    prof = profiler.Profiler(now=lambda: 10.0)
    prof.sample_once()
    profiler.register_debug_profiler(prof)
    rec = _recorder(db, client=kube, max_journeys=2)
    eng = slo.RuleEngine(
        db, [rule()],
        recording=[slo.RecordingRule(record="ttft:p99", metric=TTFT_BUCKET,
                                     q=0.5, window_s=100.0)],
        client=kube, incidents=rec, now=lambda: 10.0)
    try:
        before = metrics.registry.get_sample_value(
            "kft_incidents_captured_total", {"alert": "ttft"}) or 0.0
        assert [t["state"] for t in eng.evaluate()] == ["firing"]
        snap = rec.snapshot()
        assert len(snap["incidents"]) == 1
        manifest = snap["incidents"][0]
        assert manifest["id"] == "ttft-10"
        assert manifest["alert"] == "ttft"
        assert manifest["state"] == "firing"
        assert manifest["capturedAt"] == 10
        assert manifest["profileWindow"] is not None
        assert manifest["series"] >= 1 and manifest["journeys"] == 2

        bundle = rec.get("ttft-10")
        alert = bundle["alert"]
        assert alert["metric"] == TTFT_BUCKET
        assert alert["state"] == "firing" and alert["fastBurn"] is not None
        # The TSDB export replays the rule's slow window: every sample
        # timestamp lands inside [at - slow_window, at].
        tsdb_sec = bundle["tsdb"]
        assert tsdb_sec["metric"] == TTFT_BUCKET
        assert tsdb_sec["start"] == 10.0 - 3600.0 and tsdb_sec["end"] == 10.0
        assert tsdb_sec["series"]
        for series in tsdb_sec["series"]:
            assert series["samples"]
            for ts, _v in series["samples"]:
                assert tsdb_sec["start"] <= ts <= tsdb_sec["end"]
        # Worst journeys, ranked by longest span; the aged-out trace is
        # excluded even though its span was the longest ever recorded.
        journeys = bundle["journeys"]
        assert [j["trace_id"] for j in journeys] == ["b" * 32, "a" * 32]
        assert journeys[0]["worst_span_ms"] == 5000.0
        assert all(j["spans"] for j in journeys)
        # Profile, recorded series, knobs, engine alert snapshot.
        assert bundle["profile"]["window"] == prof.current_window_id()
        assert isinstance(bundle["profile"]["folded"], str)
        assert bundle["recorded"][0]["metric"] == "ttft:p99"
        assert bundle["recorded"][0]["series"]
        assert "KFT_INCIDENT_RING" in bundle["knobs"]
        assert bundle["alerts"]["alerts"][0]["alert"] == "ttft"
        assert sorted(manifest["sections"]) == manifest["sections"]
        assert {"alert", "alerts", "journeys", "knobs", "profile",
                "recorded", "tsdb"} <= set(manifest["sections"])

        assert metrics.registry.get_sample_value(
            "kft_incidents_captured_total", {"alert": "ttft"}) == before + 1
        ev = kube.get(EVENT, "kft-incident-ttft", "kubeflow")
        assert ev["reason"] == "IncidentCaptured"
        assert ev["type"] == "Warning"
        assert deep_get(ev, "involvedObject", "kind") == "FleetSLO"
    finally:
        profiler.register_debug_profiler(None)
        causal.STORE.clear()


def test_two_replicas_capture_one_event_and_equivalent_manifests():
    """Determinism across the fleet (the PR-15 Event discipline): two
    engines over the same scraped data, each with its OWN recorder, both
    capture — but the stamped announce Event dedupes to ONE object, and
    the two bundles' manifests are EQUAL (every field a deterministic
    function of rule + transition time + shared state)."""
    kube = FakeKube()
    kube.add_namespace("kubeflow")
    db = TSDB()
    feed(db, at=5.0, good=0, total=0)
    feed(db, at=10.0, good=0, total=50)
    causal.STORE.clear()
    _plant_journey("d" * 32, at=9.0, duration_s=1.0)
    recorders = [_recorder(db, client=kube) for _ in range(2)]
    engines = [slo.RuleEngine(db, [rule()], client=kube, incidents=r,
                              now=lambda: 10.0)
               for r in recorders]
    try:
        for eng in engines:
            eng.evaluate()
        events = [e for e in kube.list(EVENT, "kubeflow")
                  if e.get("reason") == "IncidentCaptured"]
        assert len(events) == 1, events
        assert events[0]["metadata"]["name"] == "kft-incident-ttft"
        manifests = [r.snapshot()["incidents"] for r in recorders]
        assert len(manifests[0]) == len(manifests[1]) == 1
        assert manifests[0][0] == manifests[1][0]
    finally:
        causal.STORE.clear()


def test_debounce_and_ring_bound():
    """A flapping alert must not churn the ring: captures of the same
    alert inside the debounce window return None; the ring keeps only
    the newest KFT_INCIDENT_RING bundles."""
    db = TSDB()
    feed(db, at=5.0, good=0, total=0)
    feed(db, at=10.0, good=0, total=50)
    rec = _recorder(db, debounce_s=100.0, ring=2)
    r = rule()
    st = slo.AlertState(state="firing", fast_burn=5.0, slow_burn=5.0)
    assert rec.capture(r, st, at=10.0) is not None
    assert rec.capture(r, st, at=50.0) is None  # debounced
    # A DIFFERENT alert is not debounced by the first one's capture.
    other = rule(name="ttft-other")
    assert rec.capture(other, st, at=50.0) is not None
    assert rec.capture(r, st, at=200.0) is not None
    assert rec.capture(r, st, at=400.0) is not None
    snap = rec.snapshot()
    assert snap["ring"] == 2 and snap["debounceSeconds"] == 100.0
    # Newest first; the ring evicted everything before the last two.
    assert [m["id"] for m in snap["incidents"]] == ["ttft-400", "ttft-200"]
    assert rec.get("ttft-10") is None
    assert rec.get("ttft-400")["id"] == "ttft-400"


def test_capture_failure_never_breaks_the_transition():
    """The flight recorder is evidence, not control flow: a recorder
    that raises must not stop the alert transition (or the Event)."""

    class Boom:
        def capture(self, *a, **kw):
            raise RuntimeError("recorder exploded")

    kube = FakeKube()
    kube.add_namespace("kubeflow")
    db = TSDB()
    feed(db, at=5.0, good=0, total=0)
    feed(db, at=10.0, good=0, total=50)
    eng = slo.RuleEngine(db, [rule()], client=kube, incidents=Boom(),
                         now=lambda: 10.0)
    assert [t["state"] for t in eng.evaluate()] == ["firing"]
    assert eng.states["ttft"].state == "firing"
    assert kube.get(EVENT, "kft-alert-ttft", "kubeflow") is not None


def test_extra_sections_ride_the_bundle():
    """Entrypoint-wired sections (main.py adds "shards") land in the
    bundle and its manifest; a section that raises degrades to None
    instead of killing the capture."""
    db = TSDB()
    feed(db, at=5.0, good=0, total=0)
    feed(db, at=10.0, good=0, total=50)
    rec = _recorder(db)
    rec.add_section("shards", lambda: {"identity": "r0", "owned": [0, 1]})
    rec.add_section("broken", lambda: 1 / 0)
    st = slo.AlertState(state="firing")
    bundle = rec.capture(rule(), st, at=10.0)
    assert bundle["shards"] == {"identity": "r0", "owned": [0, 1]}
    assert bundle["broken"] is None
    assert "shards" in bundle["manifest"]["sections"]
    assert "broken" not in bundle["manifest"]["sections"]


def test_debug_incidents_endpoints():
    """/debug/incidents + /debug/incidents/<id>: 404 until a recorder
    registers, then the manifest list and full bundles."""

    class _Mgr:
        def healthy(self):
            return True

    server = main_mod._serve_health(_Mgr(), 0, host="127.0.0.1")
    base = f"http://127.0.0.1:{server.server_port}"
    try:
        for path in ("/debug/incidents", "/debug/incidents/ttft-10"):
            try:
                _get(base + path)
            except urllib.error.HTTPError as e:
                assert e.code == 404
            else:  # pragma: no cover
                raise AssertionError(f"{path} served before registration")

        db = TSDB()
        feed(db, at=5.0, good=0, total=0)
        feed(db, at=10.0, good=0, total=50)
        rec = _recorder(db)
        rec.capture(rule(), slo.AlertState(state="firing"), at=10.0)
        incidents.register_debug_incidents(rec)
        try:
            listing = json.loads(_get(base + "/debug/incidents"))
            assert [m["id"] for m in listing["incidents"]] == ["ttft-10"]
            bundle = json.loads(_get(base + "/debug/incidents/ttft-10"))
            assert bundle["id"] == "ttft-10"
            assert bundle["tsdb"]["series"]
            try:
                _get(base + "/debug/incidents/no-such-id")
            except urllib.error.HTTPError as e:
                assert e.code == 404
            else:  # pragma: no cover
                raise AssertionError("unknown incident id served")
        finally:
            incidents.register_debug_incidents(None)
    finally:
        server.shutdown()


def test_pipeline_wires_a_recorder_by_default():
    """MetricsPipeline attaches an IncidentRecorder to its engine unless
    the caller opts out with incidents=False — the entrypoint gets
    flight recording without extra plumbing."""
    from kubeflow_tpu.telemetry import fleetscrape as fs

    pipe = fs.MetricsPipeline(tsdb=TSDB(), now=lambda: 100.0,
                              interval=999.0)
    assert isinstance(pipe.incidents, incidents.IncidentRecorder)
    assert pipe.engine.incidents is pipe.incidents
    off = fs.MetricsPipeline(tsdb=TSDB(), now=lambda: 100.0,
                             interval=999.0, incidents=False)
    assert off.incidents is None and off.engine.incidents is None
