"""Metrics-layer unit tests: fleet collector failure paths, heartbeat
lifecycle, workqueue-shim parity across queue engines, registry hygiene,
and the histogram-quantile helpers bench_scale.py reports from."""
import threading
import uuid

import pytest

from kubeflow_tpu.platform import native
from kubeflow_tpu.platform.runtime import metrics
from kubeflow_tpu.platform.runtime.controller import Request, _WorkQueue


# -- NotebookFleetCollector ---------------------------------------------------


def _families(collector):
    return {f.name: f for f in collector.collect()}


def test_fleet_collector_list_failure_yields_empty_families():
    """A raising client must not take the /metrics page down: both gauge
    families are still yielded, just with no samples (the except path)."""

    class Boom:
        def list(self, gvk, namespace=None, **kw):
            raise RuntimeError("apiserver down")

    metrics.register_fleet_collector(Boom())
    try:
        fams = _families(metrics._fleet_collector)
        assert fams["tpu_chips_requested"].samples == []
        assert fams["notebook_running"].samples == []
        # The full registry render survives the scrape too.
        assert b"notebook_running" in metrics.render()
    finally:
        metrics.register_fleet_collector(None)


def test_register_fleet_collector_none_unhooks_dead_client():
    """register_fleet_collector(None) in teardown must really unhook: a
    later scrape may not touch the dead fixture client at all."""

    class DeadFixture:
        def __init__(self):
            self.calls = 0

        def list(self, gvk, namespace=None, **kw):
            self.calls += 1
            raise AssertionError("scraped a dead fixture client")

    dead = DeadFixture()
    metrics.register_fleet_collector(dead)
    metrics.register_fleet_collector(None)
    fams = _families(metrics._fleet_collector)
    assert dead.calls == 0
    assert fams["tpu_chips_requested"].samples == []
    assert fams["notebook_running"].samples == []


# -- heartbeat lifecycle ------------------------------------------------------


def test_heartbeat_stop_allows_restart():
    stop1 = metrics.start_heartbeat("hb-test", interval=60.0)
    assert metrics.start_heartbeat("hb-test", interval=60.0) is stop1
    metrics.stop_heartbeat("hb-test")
    assert stop1.is_set()
    assert "hb-test" not in metrics._heartbeats  # entry dropped, no leak
    stop2 = metrics.start_heartbeat("hb-test", interval=60.0)
    assert stop2 is not stop1 and not stop2.is_set()
    metrics.stop_heartbeat("hb-test")


def test_heartbeat_replaces_externally_stopped_entry():
    """Setting the returned Event directly (the pre-stop_heartbeat idiom)
    used to wedge the component forever; start now replaces it."""
    stop1 = metrics.start_heartbeat("hb-test-2", interval=60.0)
    stop1.set()  # stopped without going through stop_heartbeat
    stop2 = metrics.start_heartbeat("hb-test-2", interval=60.0)
    assert stop2 is not stop1 and not stop2.is_set()
    metrics.stop_heartbeat("hb-test-2")


def test_stop_heartbeat_unknown_component_is_noop():
    metrics.stop_heartbeat("never-started")


# -- workqueue metrics shim ---------------------------------------------------


def _engines():
    yield "python", lambda shim: _WorkQueue(
        base_delay=0.01, max_delay=0.1, metrics=shim)
    if native.available():
        yield "native", lambda shim: native.NativeWorkQueue(
            base_delay=0.01, max_delay=0.1, metrics=shim)


def _sample(name, labels):
    return metrics.registry.get_sample_value(name, labels) or 0.0


@pytest.mark.parametrize(
    "engine,make", list(_engines()), ids=lambda v: v if isinstance(v, str) else "",
)
def test_workqueue_series_parity(engine, make):
    """Both queue engines drive the shared shim through an identical
    add/retry/get/done sequence and must export identical counters —
    the parity contract behind make_workqueue(name=...)."""
    name = f"parity-{engine}-{uuid.uuid4().hex[:6]}"
    shim = metrics.WorkQueueMetrics(name)
    q = make(shim)
    shim.attach(q)
    labels = {"name": name}
    r = Request("ns", "a")

    q.add(r)
    assert _sample("workqueue_depth", labels) == 1
    assert q.get(1.0) == r
    q.add_rate_limited(r)  # parked retry while processing
    q.done(r)
    assert q.get(2.0) == r
    q.done(r)
    q.forget(r)

    assert _sample("workqueue_adds_total", labels) == 2
    assert _sample("workqueue_retries_total", labels) == 1
    assert _sample("workqueue_queue_duration_seconds_count", labels) == 2
    assert _sample("workqueue_work_duration_seconds_count", labels) == 2
    assert _sample("workqueue_depth", labels) == 0
    assert _sample("workqueue_unfinished_work_seconds", labels) == 0
    q.shut_down()


@pytest.mark.parametrize(
    "engine,make", list(_engines()), ids=lambda v: v if isinstance(v, str) else "",
)
def test_workqueue_unfinished_work_tracks_inflight(engine, make):
    name = f"inflight-{engine}-{uuid.uuid4().hex[:6]}"
    shim = metrics.WorkQueueMetrics(name)
    q = make(shim)
    shim.attach(q)
    r = Request("ns", "slow")
    q.add(r)
    assert q.get(1.0) == r
    # In-flight: unfinished work is accruing and wait_of is queryable
    # (the controller's dequeue trace span reads it).
    assert shim.unfinished_seconds() >= 0.0
    assert shim.wait_of(r) >= 0.0
    q.done(r)
    assert shim.unfinished_seconds() == 0.0
    q.shut_down()


def test_controller_queue_is_instrumented_by_name():
    from kubeflow_tpu.platform.runtime.controller import make_workqueue

    name = f"ctl-{uuid.uuid4().hex[:6]}"
    q = make_workqueue(name=name)
    try:
        assert q.metrics is not None and q.metrics.name == name
        q.add(Request("ns", "x"))
        assert _sample("workqueue_adds_total", {"name": name}) == 1
    finally:
        q.shut_down()


# -- registry hygiene ---------------------------------------------------------


def test_no_kubeflow_metrics_in_global_registry():
    """Every kubeflow_tpu series must live in a module-local registry —
    a collector in prometheus_client.REGISTRY (the process-global default)
    would stack duplicates when tests reimport modules.  Covers BOTH
    planes: the control-plane registry (runtime/metrics.py) and the
    compute-plane registry (telemetry/compute.py) obey the same
    contract."""
    import prometheus_client

    # Import every module that defines or registers metrics.
    import kubeflow_tpu.ops.attention  # noqa: F401
    import kubeflow_tpu.platform.k8s.client  # noqa: F401
    import kubeflow_tpu.platform.runtime.controller  # noqa: F401
    import kubeflow_tpu.platform.runtime.informer  # noqa: F401
    import kubeflow_tpu.platform.web.crud_backend  # noqa: F401
    import kubeflow_tpu.train.loop  # noqa: F401
    from kubeflow_tpu.telemetry import compute as ctel

    ours = {
        name
        for names in metrics.registry._collector_to_names.values()
        for name in names
    }
    assert ours, "module-local registry unexpectedly empty"
    compute_names = {
        name
        for names in ctel.registry._collector_to_names.values()
        for name in names
    }
    assert compute_names, "compute-plane registry unexpectedly empty"
    assert "train_step_seconds" in compute_names
    # The two planes' registries must not shadow each other's series
    # either — one scrape target per family.
    shared = ours & compute_names
    assert not shared, f"series defined in both plane registries: {shared}"
    global_names = {
        name
        for names in prometheus_client.REGISTRY._collector_to_names.values()
        for name in names
    }
    leaked = (ours | compute_names) & global_names
    assert not leaked, (
        f"kubeflow_tpu metrics registered into the process-global "
        f"prometheus registry: {sorted(leaked)}"
    )


# -- quantile helpers ---------------------------------------------------------


def test_quantile_from_buckets_interpolates():
    inf = float("inf")
    # 10 observations <= 0.1, 10 more <= 1.0.
    buckets = {0.1: 10.0, 1.0: 20.0, inf: 20.0}
    assert metrics.quantile_from_buckets(buckets, 0.5) == pytest.approx(0.1)
    assert metrics.quantile_from_buckets(buckets, 0.75) == pytest.approx(0.55)
    # Everything beyond the last finite bound clamps to it.
    assert metrics.quantile_from_buckets({inf: 5.0}, 0.5) is None
    assert metrics.quantile_from_buckets({}, 0.5) is None


def test_reconcile_quantiles_with_snapshot_diff():
    hist = metrics.controller_runtime_reconcile_time_seconds
    ctrl = f"quant-{uuid.uuid4().hex[:6]}"
    child = hist.labels(controller=ctrl, result="success")
    for _ in range(10):
        child.observe(0.05)
    snap = metrics.histogram_snapshot(hist, {"controller": ctrl})
    for _ in range(10):
        child.observe(100.0)  # beyond the last finite bucket
    q = metrics.reconcile_quantiles(ctrl, (0.5, 0.99), since=snap)
    # Only the post-snapshot observations count: all in the +Inf bucket.
    assert q[0.5] == pytest.approx(60.0)  # clamped to last finite bound
    assert q[0.99] == pytest.approx(60.0)
    # Without the snapshot the earlier fast half pulls p50 down.
    q_all = metrics.reconcile_quantiles(ctrl, (0.5,))
    assert q_all[0.5] <= 0.1


def test_informer_gauge_tracks_worst_same_kind_instance():
    """Two live same-kind informers must BOTH feed the stall gauge (max
    age wins — a wedged one can't hide behind a healthy sibling), and a
    stopped informer deregisters so its frozen age doesn't false-alarm."""
    import time

    from kubeflow_tpu.platform.k8s.types import NOTEBOOK
    from kubeflow_tpu.platform.runtime.informer import Informer
    from kubeflow_tpu.platform.testing import FakeKube

    kube = FakeKube()
    kube.add_namespace("u")
    a = Informer(kube, NOTEBOOK)
    a.start()
    assert a.wait_for_sync(5.0)

    class Wedged:
        def list(self, gvk, namespace=None, **kw):
            time.sleep(30)

        def watch(self, *args, **kw):
            return iter(())

    stuck = Informer(Wedged(), NOTEBOOK)
    stuck.start()  # never syncs: age counts from start()
    try:
        assert id(a) in metrics._informers and id(stuck) in metrics._informers
        time.sleep(0.3)

        def notebook_age():
            for fam in metrics._RuntimeStateCollector().collect():
                if fam.name == "informer_last_sync_age_seconds":
                    return {s.labels["kind"]: s.value for s in fam.samples}

        ages = notebook_age()
        # One sample per kind, dominated by the never-synced informer.
        assert ages["Notebook"] >= stuck_age_floor(stuck)
    finally:
        stuck.stop()
        a.stop()
    assert id(stuck) not in metrics._informers
    assert id(a) not in metrics._informers


def stuck_age_floor(informer) -> float:
    import time

    return time.monotonic() - informer.started_monotonic - 0.05


@pytest.mark.parametrize(
    "engine,make", list(_engines()), ids=lambda v: v if isinstance(v, str) else "",
)
def test_workqueue_shutdown_drops_adds_without_counting(engine, make):
    """Both engines silently drop adds after shut_down(); the shim must
    not count them (or record queued-at state that never resolves)."""
    name = f"shutdown-{engine}-{uuid.uuid4().hex[:6]}"
    shim = metrics.WorkQueueMetrics(name)
    q = make(shim)
    shim.attach(q)
    q.shut_down()
    q.add(Request("ns", "late"))
    q.add_rate_limited(Request("ns", "late2"))
    assert _sample("workqueue_adds_total", {"name": name}) == 0
    assert _sample("workqueue_retries_total", {"name": name}) == 0
    with shim._lock:
        assert not shim._queued_at  # no orphaned timing state


def test_worker_count_env_resolution(monkeypatch):
    """Parallel dispatch wiring: CONTROLLER_WORKERS sets the fleet
    default, CONTROLLER_WORKERS_<NAME> pins one controller, and an
    explicit workers= argument beats both."""
    from kubeflow_tpu.platform.runtime.controller import (
        Controller,
        DEFAULT_WORKERS,
        Reconciler,
        worker_count,
    )
    from kubeflow_tpu.platform.k8s.types import NOTEBOOK

    monkeypatch.delenv("CONTROLLER_WORKERS", raising=False)
    assert worker_count("notebook-controller") == DEFAULT_WORKERS
    monkeypatch.setenv("CONTROLLER_WORKERS", "6")
    assert worker_count("notebook-controller") == 6
    monkeypatch.setenv("CONTROLLER_WORKERS_NOTEBOOK_CONTROLLER", "2")
    assert worker_count("notebook-controller") == 2
    assert worker_count("profile-controller") == 6

    c = Controller("notebook-controller", Reconciler(), primary=NOTEBOOK)
    assert c.workers == 2
    c = Controller("notebook-controller", Reconciler(), primary=NOTEBOOK,
                   workers=1)
    assert c.workers == 1


def test_worker_utilization_gauges_exported():
    """controller_workers / controller_workers_busy come from the
    scrape-time collector while the controller runs, and vanish after
    stop() deregisters it."""
    import time as _time

    from kubeflow_tpu.platform.k8s.types import NOTEBOOK
    from kubeflow_tpu.platform.runtime.controller import Controller, Reconciler
    from kubeflow_tpu.platform.testing import FakeKube

    gate = threading.Event()
    entered = threading.Event()

    class Block(Reconciler):
        def reconcile(self, req):
            entered.set()
            gate.wait(5.0)
            return None

    kube = FakeKube()
    kube.add_namespace("ns")
    ctrl = Controller("util-probe", Block(), primary=NOTEBOOK, workers=3)
    ctrl.start(kube)
    try:
        assert _sample("controller_workers", {"controller": "util-probe"}) == 3
        kube.create({
            "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
            "metadata": {"name": "nb", "namespace": "ns"},
            "spec": {"template": {"spec": {"containers": [{"name": "c"}]}}},
        })
        assert entered.wait(5.0)
        assert _sample("controller_workers_busy",
                       {"controller": "util-probe"}) >= 1
    finally:
        gate.set()
        ctrl.stop()
    deadline = _time.monotonic() + 2.0
    while _time.monotonic() < deadline:
        if _sample("controller_workers", {"controller": "util-probe"}) == 0:
            break
        _time.sleep(0.01)
    assert _sample("controller_workers", {"controller": "util-probe"}) == 0
