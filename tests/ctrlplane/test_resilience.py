"""Client-side resilience, unit-level: the RestKubeClient retry policy
(idempotent-only, jitter bounds, Retry-After honored), the circuit
breaker, watch-drop-mid-stream resume for Controller._watch_loop and
Informer, the dead-letter path, the stuck-reconcile watchdog, graceful
CRUD degradation, probe fail-safety, and atomic cert rotation.  The
seeded end-to-end fault storms live in test_chaos.py."""
import threading
import time

import pytest

from kubeflow_tpu.platform.k8s import errors
from kubeflow_tpu.platform.k8s.client import RestKubeClient
from kubeflow_tpu.platform.k8s.types import NOTEBOOK, PVC, deep_get
from kubeflow_tpu.platform.runtime import Reconciler, Request
from kubeflow_tpu.platform.runtime.controller import Controller
from kubeflow_tpu.platform.runtime.informer import Informer
from kubeflow_tpu.platform.testing import ChaosKube, FakeKube, Fault


# -- RestKubeClient retry policy ----------------------------------------------


class FakeResponse:
    def __init__(self, status_code, body=None, headers=None):
        self.status_code = status_code
        self._body = body if body is not None else {}
        self.headers = headers or {}
        self.text = str(self._body)

    def json(self):
        if isinstance(self._body, Exception):
            raise self._body
        return self._body

    def iter_lines(self, chunk_size=512):
        return iter(())

    def close(self):
        pass


class ScriptedSession:
    """Stands in for requests.Session: answers each request from a script
    of FakeResponses / exceptions, and records every call's kwargs."""

    def __init__(self, script):
        self.script = list(script)
        self.calls = []
        self.headers = {}

    def request(self, method, url, **kwargs):
        self.calls.append((method, url, kwargs))
        item = self.script.pop(0) if self.script else FakeResponse(200)
        if isinstance(item, Exception):
            raise item
        return item


def make_client(script, **kwargs):
    kwargs.setdefault("retries", 3)
    kwargs.setdefault("retry_base", 0.001)
    kwargs.setdefault("retry_cap", 0.002)
    kwargs.setdefault("breaker_threshold", 0)  # breaker off unless asked
    client = RestKubeClient("http://api.invalid", qps=0, **kwargs)
    client._session = ScriptedSession(script)
    return client


def transport_exc():
    import requests

    return requests.ConnectionError("refused")


def test_get_retried_on_503_then_succeeds():
    client = make_client([
        FakeResponse(503, {"message": "shedding"}),
        FakeResponse(200, {"metadata": {"name": "nb"}}),
    ])
    out = client.get(NOTEBOOK, "nb", "user1")
    assert out["metadata"]["name"] == "nb"
    assert len(client._session.calls) == 2


def test_get_retried_on_transport_error():
    client = make_client([transport_exc(), FakeResponse(200, {"items": []})])
    assert client.list(NOTEBOOK, "user1") == []
    assert len(client._session.calls) == 2


def test_get_retries_are_bounded():
    script = [FakeResponse(503, {"message": "down"}) for _ in range(10)]
    client = make_client(script, retries=2)
    with pytest.raises(errors.ServiceUnavailable):
        client.get(NOTEBOOK, "nb", "user1")
    # 1 initial + 2 retries, never the whole script.
    assert len(client._session.calls) == 3


def test_create_not_retried_on_500():
    """Non-idempotent verbs are NEVER blind-retried on 5xx/transport: the
    server may have applied the write before dying."""
    client = make_client([
        FakeResponse(500, {"message": "boom"}),
        FakeResponse(201, {}),
    ])
    obj = {"apiVersion": "v1", "kind": "Namespace",
           "metadata": {"name": "x"}}
    with pytest.raises(errors.InternalError):
        client.create(obj)
    assert len(client._session.calls) == 1


def test_update_not_retried_on_transport_error():
    client = make_client([transport_exc()])
    obj = {"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": "x"}}
    with pytest.raises(errors.TransportError):
        client.update(obj)
    assert len(client._session.calls) == 1


def test_create_retried_on_429_honoring_retry_after(monkeypatch):
    """429 is retried for EVERY verb (the server rejected before
    processing) and a numeric Retry-After is slept verbatim."""
    sleeps = []
    monkeypatch.setattr(time, "sleep", lambda s: sleeps.append(s))
    client = make_client([
        FakeResponse(429, {"message": "slow down",
                           "reason": "TooManyRequests"},
                     headers={"Retry-After": "0.25"}),
        FakeResponse(201, {"metadata": {"name": "x"}}),
    ])
    obj = {"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": "x"}}
    out = client.create(obj)
    assert out["metadata"]["name"] == "x"
    assert sleeps == [0.25]


def test_delete_is_idempotent_and_retried():
    client = make_client([
        FakeResponse(503, {"message": "down"}),
        FakeResponse(200, {"status": "Success"}),
    ])
    client.delete(NOTEBOOK, "nb", "user1")
    assert len(client._session.calls) == 2


def test_jitter_bounds(monkeypatch):
    """Full jitter: each backoff is uniform in [0, base*2^attempt],
    capped — assert the bounds passed to the RNG."""
    import random as _random

    draws = []

    def fake_uniform(lo, hi):
        draws.append((lo, hi))
        return 0.0

    monkeypatch.setattr(_random, "uniform", fake_uniform)
    script = [FakeResponse(503, {"message": "down"}) for _ in range(4)]
    client = make_client(script, retries=3, retry_base=0.1, retry_cap=0.3)
    with pytest.raises(errors.ServiceUnavailable):
        client.get(NOTEBOOK, "nb", "user1")
    assert draws == [(0.0, 0.1), (0.0, 0.2), (0.0, 0.3)]  # capped at 0.3


def test_every_verb_carries_finite_timeout():
    """The acceptance bar: every verb's wire call has a finite (connect,
    read) timeout — no request can hang a controller forever."""
    client = make_client([FakeResponse(200, {"items": [], "metadata": {}})
                          for _ in range(8)], retries=0)
    obj = {"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": "x"}}
    client.get(NOTEBOOK, "nb", "user1")
    client.list(NOTEBOOK, "user1")
    client.create(obj)
    client.update(obj)
    client.update_status(obj)
    client.patch(NOTEBOOK, "nb", {"metadata": {}}, "user1")
    client.delete(NOTEBOOK, "nb", "user1")
    client.pod_logs("p", "user1")
    assert client._session.calls
    for _method, _url, kwargs in client._session.calls:
        timeout = kwargs.get("timeout")
        assert timeout is not None
        connect, read = timeout
        assert connect and connect > 0 and read and read > 0

    # Watch establishment too (stream): finite read = window + slack.
    client._session.script = [FakeResponse(200)]
    wi = client.watch(NOTEBOOK, "user1")
    # generator: establishment happens at first next(); 200 with no body
    # ends the stream immediately.
    list(wi)
    _m, _u, kwargs = client._session.calls[-1]
    connect, read = kwargs["timeout"]
    assert connect > 0 and read == client.WATCH_TIMEOUT_SECONDS + 30


def test_circuit_breaker_trips_and_half_opens():
    client = make_client(
        [transport_exc(), transport_exc()],
        retries=0, breaker_threshold=2, breaker_cooldown=0.05,
    )
    for _ in range(2):
        with pytest.raises(errors.TransportError):
            client.get(NOTEBOOK, "nb", "user1")
    assert client.breaker.state == "open"
    assert client.health()["circuit"] == "open"
    assert client.health()["consecutive_failures"] == 2

    # Open: fails FAST, without touching the wire.
    wire_calls = len(client._session.calls)
    with pytest.raises(errors.TransportError) as ei:
        client.get(NOTEBOOK, "nb", "user1")
    assert "circuit breaker open" in str(ei.value)
    assert len(client._session.calls) == wire_calls

    # After the cooldown: ONE half-open probe goes through; success closes.
    time.sleep(0.06)
    client._session.script = [FakeResponse(200, {"metadata": {}})]
    client.get(NOTEBOOK, "nb", "user1")
    assert client.breaker.state == "closed"
    assert client.health()["consecutive_failures"] == 0


def test_circuit_breaker_reopens_on_failed_probe():
    client = make_client(
        [transport_exc(), transport_exc()],
        retries=0, breaker_threshold=1, breaker_cooldown=0.03,
    )
    with pytest.raises(errors.TransportError):
        client.get(NOTEBOOK, "nb", "user1")
    assert client.breaker.state == "open"
    time.sleep(0.04)
    with pytest.raises(errors.TransportError):  # the probe, failing
        client.get(NOTEBOOK, "nb", "user1")
    assert client.breaker.state == "open"


def test_open_circuit_fails_fast_without_retry_sleeps(monkeypatch):
    """A known-open circuit must not be retried: retries even WITH a
    retry budget would just burn jittered sleeps against fail-fast
    errors."""
    sleeps = []
    monkeypatch.setattr(time, "sleep", lambda s: sleeps.append(s))
    client = make_client([transport_exc()], retries=3,
                         breaker_threshold=1, breaker_cooldown=60)
    with pytest.raises(errors.TransportError):
        client.get(NOTEBOOK, "nb", "user1")
    assert client.breaker.state == "open"
    wire_calls = len(client._session.calls)
    sleeps.clear()  # the tripping call itself was allowed one retry
    with pytest.raises(errors.TransportError) as ei:
        client.get(NOTEBOOK, "nb", "user1")
    assert "circuit breaker open" in str(ei.value)
    assert len(client._session.calls) == wire_calls  # zero wire attempts
    assert sleeps == []  # and zero retry sleeps


def test_4xx_does_not_trip_breaker():
    client = make_client(
        [FakeResponse(404, {"message": "nope", "reason": "NotFound"})] * 3,
        retries=0, breaker_threshold=1, breaker_cooldown=10,
    )
    with pytest.raises(errors.NotFound):
        client.get(NOTEBOOK, "nb", "user1")
    assert client.breaker.state == "closed"


# -- watch-drop-mid-stream resume ---------------------------------------------


def make_nb(name, ns="ns"):
    return {
        "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
        "metadata": {"name": name, "namespace": ns},
        "spec": {"template": {"spec": {"containers": [{"name": "c"}]}}},
    }


def test_informer_resumes_by_rv_after_stream_drop():
    """ChaosKube cuts the watch stream after every event; the informer
    must resume the watch from the last RV — ONE initial list, zero
    relists, no missed deltas."""
    kube = FakeKube()
    kube.add_namespace("ns")
    chaos = ChaosKube(kube, [Fault("drop", 1.0, verbs=frozenset({"watch"}))])
    informer = Informer(chaos, NOTEBOOK)
    informer.start()
    try:
        assert informer.wait_for_sync(10.0)
        for i in range(5):
            kube.create(make_nb(f"nb-{i}"))
            deadline = time.monotonic() + 10.0
            while informer.get(f"nb-{i}", "ns") is None:
                assert time.monotonic() < deadline, f"nb-{i} never cached"
                time.sleep(0.01)
    finally:
        informer.stop()
    assert chaos.calls["list"] == 1, "stream drops must not force relists"
    assert chaos.calls["watch"] >= 2  # it really did re-establish
    # Every re-establishment resumed from a concrete RV.
    for est in chaos.watch_establishments[1:]:
        assert est["resource_version"] is not None


def test_watch_loop_resumes_by_rv_after_stream_drop():
    """Controller._watch_loop (the raw, non-informer source) keeps its
    resume RV across mid-stream drops: re-establishments carry the last
    seen resourceVersion instead of replaying the whole kind."""
    kube = FakeKube()
    kube.add_namespace("ns")
    kube.create(make_nb("nb-pre"))
    chaos = ChaosKube(kube, [Fault("drop", 1.0, verbs=frozenset({"watch"}))])

    seen = []

    class Probe(Reconciler):
        def reconcile(self, req):
            seen.append(req.name)

    ctrl = Controller("drop-probe", Probe(), primary=NOTEBOOK,
                      namespace="ns", stuck_deadline=0)
    ctrl.start(chaos)
    try:
        deadline = time.monotonic() + 10.0
        while "nb-pre" not in seen and time.monotonic() < deadline:
            time.sleep(0.01)
        for i in range(3):
            kube.create(make_nb(f"nb-{i}"))
            deadline = time.monotonic() + 10.0
            while f"nb-{i}" not in seen and time.monotonic() < deadline:
                time.sleep(0.01)
            assert f"nb-{i}" in seen
    finally:
        ctrl.stop()
    assert chaos.calls["watch"] >= 2
    resumed = [est["resource_version"]
               for est in chaos.watch_establishments[1:]]
    assert resumed and all(rv is not None for rv in resumed), (
        "watch loop re-established without an RV (full-kind replay)")
    assert chaos.calls.get("list", 0) == 0


# -- dead-letter + watchdog ---------------------------------------------------


class FlakyReconciler(Reconciler):
    def __init__(self, fail=True):
        self.fail = fail
        self.attempts = 0

    def reconcile(self, req):
        self.attempts += 1
        if self.fail:
            raise errors.InternalError("chaos: permanently broken")


def test_dead_letter_parks_key_and_writes_condition():
    kube = FakeKube()
    kube.add_namespace("ns")
    kube.create(make_nb("doomed"))
    rec = FlakyReconciler()
    ctrl = Controller("dl-test", rec, primary=NOTEBOOK, namespace="ns",
                      max_retries=3, stuck_deadline=0)
    try:  # tight backoff so the retries burn down fast (python queue only)
        ctrl.queue._base = 0.001
    except AttributeError:
        pass
    ctrl.start(kube)
    try:
        deadline = time.monotonic() + 10.0
        while not ctrl.dead_letters and time.monotonic() < deadline:
            time.sleep(0.01)
        assert Request("ns", "doomed") in ctrl.dead_letters
        # Parked means NOT hot-looping: attempts stop growing.  (The
        # condition write itself triggers one last watch-event reconcile,
        # which fails and re-parks without writing again.)
        deadline = time.monotonic() + 5.0
        stable_since = rec.attempts
        quiet = time.monotonic()
        while time.monotonic() < deadline:
            time.sleep(0.05)
            if rec.attempts != stable_since:
                stable_since = rec.attempts
                quiet = time.monotonic()
            elif time.monotonic() - quiet > 0.4:
                break
        # 1 initial + max_retries, plus at most one event-driven revival
        # from our own condition write — bounded, never a hot loop.
        assert 4 <= rec.attempts <= 5
        nb = kube.get(NOTEBOOK, "doomed", "ns")
        conds = {c["type"]: c for c in nb["status"]["conditions"]}
        assert conds["ReconcileFailed"]["status"] == "True"
        assert conds["ReconcileFailed"]["reason"] == "MaxRetriesExceeded"
        from kubeflow_tpu.platform.k8s.types import EVENT

        events = kube.list(EVENT, "ns")
        assert any(e.get("reason") == "ReconcileFailed" for e in events)

        # Recovery: fix the reconciler, touch the object — the key revives
        # via the watch event, succeeds, and the condition clears.
        rec.fail = False
        nb = kube.get(NOTEBOOK, "doomed", "ns")
        nb["metadata"].setdefault("annotations", {})["kick"] = "1"
        kube.update(nb)
        deadline = time.monotonic() + 10.0
        while ctrl.dead_letters and time.monotonic() < deadline:
            time.sleep(0.01)
        assert not ctrl.dead_letters
        nb = kube.get(NOTEBOOK, "doomed", "ns")
        assert all(c["type"] != "ReconcileFailed"
                   for c in (nb.get("status") or {}).get("conditions", []))
    finally:
        ctrl.stop()


def test_conflicts_do_not_count_toward_dead_letter():
    kube = FakeKube()
    kube.add_namespace("ns")
    kube.create(make_nb("contended"))

    class Conflicted(Reconciler):
        def __init__(self):
            self.attempts = 0

        def reconcile(self, req):
            self.attempts += 1
            if self.attempts < 6:
                raise errors.Conflict("object was modified")

    rec = Conflicted()
    ctrl = Controller("conflict-test", rec, primary=NOTEBOOK, namespace="ns",
                      max_retries=2, stuck_deadline=0)
    try:
        ctrl.queue._base = 0.001
    except AttributeError:
        pass
    ctrl.start(kube)
    try:
        deadline = time.monotonic() + 10.0
        while rec.attempts < 6 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert rec.attempts >= 6  # retried PAST max_retries
        assert not ctrl.dead_letters
    finally:
        ctrl.stop()


def test_already_exists_counts_toward_dead_letter():
    """AlreadyExists subclasses Conflict (both 409) but is a CREATE
    COLLISION requeueing cannot heal — it must dead-letter, not retry
    forever like an optimistic-concurrency conflict."""
    kube = FakeKube()
    kube.add_namespace("ns")
    kube.create(make_nb("squatted"))

    class Colliding(Reconciler):
        def reconcile(self, req):
            raise errors.AlreadyExists('statefulsets "squatted" already exists')

    ctrl = Controller("collision-test", Colliding(), primary=NOTEBOOK,
                      namespace="ns", max_retries=2, stuck_deadline=0)
    try:
        ctrl.queue._base = 0.001
    except AttributeError:
        pass
    ctrl.start(kube)
    try:
        deadline = time.monotonic() + 10.0
        while not ctrl.dead_letters and time.monotonic() < deadline:
            time.sleep(0.01)
        assert Request("ns", "squatted") in ctrl.dead_letters
    finally:
        ctrl.stop()


def test_stuck_reconcile_watchdog_fires(caplog):
    from kubeflow_tpu.platform.runtime import metrics

    kube = FakeKube()
    kube.add_namespace("ns")
    kube.create(make_nb("slowpoke"))
    release = threading.Event()

    class Stuck(Reconciler):
        def reconcile(self, req):
            release.wait(5.0)

    before = metrics.reconcile_stuck_total.labels(
        controller="stuck-test")._value.get()
    ctrl = Controller("stuck-test", Stuck(), primary=NOTEBOOK, namespace="ns",
                      stuck_deadline=0.1)
    import logging

    with caplog.at_level(logging.ERROR, logger="kubeflow_tpu.runtime"):
        ctrl.start(kube)
        try:
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                now = metrics.reconcile_stuck_total.labels(
                    controller="stuck-test")._value.get()
                if now > before:
                    break
                time.sleep(0.02)
            assert metrics.reconcile_stuck_total.labels(
                controller="stuck-test")._value.get() > before
        finally:
            release.set()
            ctrl.stop()
    assert any("stuck" in r.message for r in caplog.records)


# -- graceful CRUD degradation ------------------------------------------------


def synced_informer_with(kube, gvk, objs):
    """An informer that synced against a healthy client (seeding the
    cache), for wrapping behind a now-broken live path."""
    inf = Informer(kube, gvk)
    inf.start()
    assert inf.wait_for_sync(10.0)
    inf.stop()  # cache + has_synced survive stop; live path is the client
    return inf


def make_volumes_app(client, caches=None):
    from kubeflow_tpu.platform.apps.volumes.app import create_app
    from kubeflow_tpu.platform.web.crud_backend import AuthContext

    return create_app(client, auth=AuthContext(disable_auth=True),
                      secure_cookies=False, caches=caches)


def _call(app, method, path, body=None):
    import json as _json

    from werkzeug.test import Client

    c = Client(app)
    kwargs = {}
    if body is not None:
        kwargs = {"data": _json.dumps(body),
                  "content_type": "application/json"}
    resp = getattr(c, method)(path, **kwargs)
    try:
        payload = _json.loads(resp.get_data(as_text=True))
    except ValueError:
        payload = None
    return resp, payload


def make_pvc(name, ns="user1"):
    return {"apiVersion": "v1", "kind": "PersistentVolumeClaim",
            "metadata": {"name": name, "namespace": ns},
            "spec": {"accessModes": ["ReadWriteOnce"],
                     "resources": {"requests": {"storage": "1Gi"}}}}


def test_degraded_list_serves_cache_when_live_fails():
    """Transport errors on the live path serve the informer cache with
    ``degraded: true`` instead of 500ing the page."""
    kube = FakeKube()
    kube.add_namespace("user1")
    kube.create(make_pvc("vol-a"))
    informer = synced_informer_with(kube, PVC, None)
    chaos = ChaosKube(kube, [Fault("timeout", 1.0,
                                   verbs=frozenset({"list", "get"}))])
    # Cache wired but NOT synced from the app's view?  No: synced cache
    # with a broken live path — reads of the cached kind come from the
    # cache (normal), reads of UNCACHED kinds (pods here) fail transiently
    # and degrade to an absent cache → the PVC list endpoint must still
    # answer from what it has.
    app = make_volumes_app(chaos, caches={PVC: informer})
    resp, payload = _call(app, "get", "/api/namespaces/user1/pvcs")
    # POD has no cache: the pods read raises 503 → the endpoint fails with
    # Retry-After rather than a raw 500.
    assert resp.status_code == 503
    assert resp.headers.get("Retry-After")

    # The single-kind storageclasses endpoint: cache absent → 503 either;
    # now wire an unsynced cache and watch it degrade instead.
    unsynced = Informer(chaos, PVC)  # never started → has_synced False,
    unsynced._store = dict(informer._store)  # but it holds yesterday's data
    app2 = make_volumes_app(chaos, caches={PVC: unsynced})
    resp, payload = _call(app2, "get", "/api/namespaces/user1/pvcs/vol-a")
    assert resp.status_code == 200
    assert payload["degraded"] is True
    assert payload["pvc"]["metadata"]["name"] == "vol-a"


def test_degraded_list_refuses_empty_never_synced_cache():
    """A never-synced EMPTY cache must not degrade a failing list into a
    200-with-zero-items ('you have no PVCs') — the error propagates."""
    kube = FakeKube()
    kube.add_namespace("user1")
    chaos = ChaosKube(kube, [Fault("timeout", 1.0,
                                   verbs=frozenset({"list"}))])
    empty_unsynced = Informer(chaos, PVC)  # never started, nothing cached
    app = make_volumes_app(chaos, caches={PVC: empty_unsynced})
    resp, payload = _call(app, "get", "/api/namespaces/user1/pvcs")
    assert resp.status_code == 503
    assert payload["success"] is False


def test_degraded_flag_not_set_on_healthy_path():
    kube = FakeKube()
    kube.add_namespace("user1")
    kube.create(make_pvc("vol-a"))
    app = make_volumes_app(kube)
    resp, payload = _call(app, "get", "/api/namespaces/user1/pvcs/vol-a")
    assert resp.status_code == 200
    assert "degraded" not in payload


def test_writes_return_503_with_retry_after_on_transport_error():
    kube = FakeKube()
    kube.add_namespace("user1")
    chaos = ChaosKube(kube, [Fault("timeout", 1.0,
                                   verbs=frozenset({"create"}))])
    app = make_volumes_app(chaos)
    resp, payload = _call(app, "post", "/api/namespaces/user1/pvcs",
                          body={"name": "new-vol"})
    assert resp.status_code == 503
    assert resp.headers.get("Retry-After")
    assert payload["success"] is False


def test_writes_return_429_with_retry_after():
    kube = FakeKube()
    kube.add_namespace("user1")
    chaos = ChaosKube(kube, [Fault("429", 1.0, verbs=frozenset({"create"}),
                                   retry_after=7)])
    app = make_volumes_app(chaos)
    resp, _payload = _call(app, "post", "/api/namespaces/user1/pvcs",
                           body={"name": "new-vol"})
    assert resp.status_code == 429
    assert resp.headers.get("Retry-After") == "7"


# -- culling probe fail-safety ------------------------------------------------


def test_raising_prober_counts_as_busy():
    """A prober that RAISES (not just returns None) must not cull and
    must not crash the reconcile into backoff — fail safe, requeue."""
    from kubeflow_tpu.platform.apis import notebook as nbapi
    from kubeflow_tpu.platform.controllers.culling import CullingReconciler

    kube = FakeKube()
    kube.add_namespace("user1")
    kube.create(make_nb("nb", ns="user1"))

    def broken(url):
        raise RuntimeError("probe exploded")

    r = CullingReconciler(kube, prober=broken, idle_minutes=0)
    result = r.reconcile(Request("user1", "nb"))
    assert result is not None and result.requeue_after
    assert not nbapi.is_stopped(kube.get(NOTEBOOK, "nb", "user1"))


def test_probe_timeout_is_configurable(monkeypatch):
    from kubeflow_tpu.platform.controllers import culling

    seen = {}

    class FakeRequests:
        RequestException = RuntimeError

        @staticmethod
        def get(url, timeout=None):
            seen["timeout"] = timeout
            raise culling.json.JSONDecodeError("x", "y", 0)

    import sys

    monkeypatch.setitem(sys.modules, "requests", FakeRequests)
    monkeypatch.setenv("CULL_PROBE_TIMEOUT_SECONDS", "2.5")
    assert culling.default_prober("http://x/api/kernels") is None
    assert seen["timeout"] == 2.5
    assert culling.default_prober("http://x", timeout=0.5) is None
    assert seen["timeout"] == 0.5


def test_probe_budget_caps_per_cycle_probe_time():
    """Once the per-cycle budget is spent, later probes this cycle are
    skipped (notebooks count busy) — next period retries."""
    from kubeflow_tpu.platform.apis import notebook as nbapi
    from kubeflow_tpu.platform.controllers.culling import CullingReconciler

    kube = FakeKube()
    kube.add_namespace("user1")
    kube.create(make_nb("nb-a", ns="user1"))
    kube.create(make_nb("nb-b", ns="user1"))

    calls = []

    def slow_idle_prober(url):
        calls.append(url)
        time.sleep(0.05)
        return [{"execution_state": "idle",
                 "last_activity": "2020-01-01T00:00:00Z"}]

    r = CullingReconciler(kube, prober=slow_idle_prober, idle_minutes=0,
                          probe_budget_s=0.01)
    r.reconcile(Request("user1", "nb-a"))  # consumes the whole budget
    r.reconcile(Request("user1", "nb-b"))  # budget gone: skipped, busy
    assert len(calls) == 1
    assert nbapi.is_stopped(kube.get(NOTEBOOK, "nb-a", "user1"))
    assert not nbapi.is_stopped(kube.get(NOTEBOOK, "nb-b", "user1"))


# -- atomic cert rotation -----------------------------------------------------


def test_write_pair_survives_kill_mid_write(tmp_path, monkeypatch):
    """A writer killed mid-write must leave the live pair untouched: the
    targets are only ever replaced by whole-file rename."""
    from kubeflow_tpu.platform.webhook import certs

    certs.write_pair(str(tmp_path), b"CERT-A", b"KEY-A")

    # Kill during the temp-file WRITE (before any rename).
    real_open = open
    import builtins

    def dying_open(path, *args, **kwargs):
        f = real_open(path, *args, **kwargs)
        if str(path).endswith(".tmp"):
            class Dying:
                def __init__(self, inner):
                    self._inner = inner

                def write(self, blob):
                    self._inner.write(blob[: len(blob) // 2])
                    raise OSError("killed mid-write")

                def __getattr__(self, name):
                    return getattr(self._inner, name)

                def __enter__(self):
                    return self

                def __exit__(self, *exc):
                    return self._inner.__exit__(*exc)

            return Dying(f)
        return f

    monkeypatch.setattr(builtins, "open", dying_open)
    with pytest.raises(OSError):
        certs.write_pair(str(tmp_path), b"CERT-B", b"KEY-B")
    monkeypatch.undo()
    assert (tmp_path / "tls.crt").read_bytes() == b"CERT-A"
    assert (tmp_path / "tls.key").read_bytes() == b"KEY-A"


def test_write_pair_survives_kill_between_write_and_rename(tmp_path, monkeypatch):
    from kubeflow_tpu.platform.webhook import certs

    certs.write_pair(str(tmp_path), b"CERT-A", b"KEY-A")

    import os as _os

    def dying_replace(src, dst):
        raise OSError("killed before rename")

    monkeypatch.setattr(_os, "replace", dying_replace)
    with pytest.raises(OSError):
        certs.write_pair(str(tmp_path), b"CERT-B", b"KEY-B")
    monkeypatch.undo()
    assert (tmp_path / "tls.crt").read_bytes() == b"CERT-A"
    assert (tmp_path / "tls.key").read_bytes() == b"KEY-A"


def test_reload_counts_partial_write_and_recovers(tmp_path):
    """A truncated pair on disk is counted + retried, never loaded; the
    next complete rotation goes live.  Needs real keygen."""
    pytest.importorskip("cryptography")
    from kubeflow_tpu.platform.webhook.certs import (
        generate_self_signed,
        write_pair,
    )
    from kubeflow_tpu.platform.webhook.server import WebhookServer

    kube = FakeKube()
    cert, key = write_pair(str(tmp_path), *generate_self_signed())
    srv = WebhookServer(kube, host="127.0.0.1", port=0,
                        cert_file=cert, key_file=key)
    try:
        # Simulate a non-atomic writer dying mid-write: truncated cert
        # straight at the target path (bypassing write_pair).
        good = (tmp_path / "tls.crt").read_bytes()
        time.sleep(0.02)  # ensure a fresh mtime
        (tmp_path / "tls.crt").write_bytes(good[: len(good) // 2])
        assert srv.reload_certs() is False
        assert srv.reload_failures == 1
        # Writer finishes properly: reload goes through.
        write_pair(str(tmp_path), *generate_self_signed())
        assert srv.reload_certs() is True
    finally:
        srv.stop()
