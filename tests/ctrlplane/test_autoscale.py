"""The InferenceService autoscaler decision function in isolation
(runtime/autoscale.py): target-tracking math, scale-to-zero idle window,
cooldown/hysteresis on a noisy series, max-replica clamp.  Pure unit —
no cluster, no controller, no clock."""
from __future__ import annotations

import dataclasses

from kubeflow_tpu.platform.runtime.autoscale import (
    ScaleState,
    ScaleTargets,
    ServeSample,
    decide_scale,
    state_from_status,
    state_to_status,
    targets_from_spec,
)


def _targets(**kw):
    base = dict(min_replicas=1, max_replicas=8, queue_depth=4.0,
                ttft_p99_s=None, slot_occupancy=0.8,
                idle_seconds=60.0, cooldown_seconds=30.0)
    base.update(kw)
    return ScaleTargets(**base)


def _sample(**kw):
    base = dict(replicas_scraped=2, queue_depth=0.0, ttft_p99_s=None,
                slot_occupancy=None, requests_total=0.0)
    base.update(kw)
    return ServeSample(**base)


def test_target_tracking_scales_up_proportionally():
    # 2 replicas at mean queue depth 12 against a target of 4 → ceil(2*3)=6.
    d = decide_scale(2, _sample(queue_depth=12.0), _targets(),
                     ScaleState(), now=100.0)
    assert d.replicas == 6 and d.reason == "ScaleUp"


def test_most_pressured_signal_wins():
    # Queue depth alone says hold; occupancy says double.
    d = decide_scale(2, _sample(queue_depth=4.0, slot_occupancy=1.6),
                     _targets(), ScaleState(), now=100.0)
    assert d.replicas == 4 and d.reason == "ScaleUp"


def test_ttft_ceiling_drives_scale_up():
    d = decide_scale(2, _sample(ttft_p99_s=6.0),
                     _targets(ttft_p99_s=2.0), ScaleState(), now=100.0)
    assert d.replicas == 6 and d.reason == "ScaleUp"


def test_max_replica_clamp():
    d = decide_scale(4, _sample(queue_depth=400.0), _targets(max_replicas=8),
                     ScaleState(), now=100.0)
    assert d.replicas == 8 and d.reason == "ScaleUp"
    # Already at the ceiling: pressure changes nothing (no reason churn).
    d2 = decide_scale(8, _sample(queue_depth=400.0),
                      _targets(max_replicas=8), d.state, now=110.0)
    assert d2.replicas == 8 and d2.reason == ""


def test_min_replica_floor():
    d = decide_scale(4, _sample(queue_depth=0.0), _targets(min_replicas=2),
                     ScaleState(last_scale_down_at=0.0), now=1000.0)
    assert d.replicas >= 2 and d.reason == "ScaleDown"


def test_scale_down_respects_cooldown():
    state = ScaleState(last_scale_down_at=90.0)
    d = decide_scale(6, _sample(queue_depth=0.5),
                     _targets(cooldown_seconds=30.0), state, now=100.0)
    assert d.replicas == 6 and d.reason == "Cooldown"
    # Cooldown elapsed → one bounded step.
    d2 = decide_scale(6, _sample(queue_depth=0.5),
                      _targets(cooldown_seconds=30.0), state, now=121.0)
    assert d2.reason == "ScaleDown" and d2.replicas < 6


def test_scale_down_never_more_than_halves():
    # Desired from a near-zero sample would be 1; one step only halves.
    d = decide_scale(8, _sample(queue_depth=0.01), _targets(),
                     ScaleState(), now=1000.0)
    assert d.replicas == 4 and d.reason == "ScaleDown"


def test_noisy_series_does_not_flap():
    """Alternating deep/empty samples: width may step up with pressure but
    must never see-saw — at most ONE scale-down inside a cooldown window,
    and never below half the peak in that window."""
    targets = _targets(cooldown_seconds=1000.0)
    state = ScaleState()
    width = 4
    widths = [width]
    for i, depth in enumerate([16.0, 0.0, 16.0, 0.0, 16.0, 0.0]):
        d = decide_scale(width, _sample(queue_depth=depth), targets,
                         state, now=100.0 + i)
        width, state = d.replicas, d.state
        widths.append(width)
    downs = sum(1 for a, b in zip(widths, widths[1:]) if b < a)
    assert downs <= 1, widths
    assert min(widths) >= max(widths) // 2, widths


def test_spec_bounds_are_authoritative_and_immediate():
    """An operator edit to replicas.min/max takes effect this pass —
    no cooldown, no sample needed (the hold-on-silence rule must not
    freeze an out-of-bounds width)."""
    d = decide_scale(6, _sample(replicas_scraped=0),
                     _targets(max_replicas=2),
                     ScaleState(last_scale_down_at=99.0), now=100.0)
    assert d.replicas == 2 and d.reason == "ScaleDown"
    d2 = decide_scale(1, _sample(replicas_scraped=0),
                      _targets(min_replicas=3), ScaleState(), now=100.0)
    assert d2.replicas == 3 and d2.reason == "ScaleUp"
    # Parked at zero when min is raised above zero: comes back up.
    d3 = decide_scale(0, _sample(replicas_scraped=0),
                      _targets(min_replicas=2),
                      ScaleState(idle_since_zero=True), now=100.0)
    assert d3.replicas == 2 and d3.reason == "ScaleUp"
    assert not d3.state.idle_since_zero


def test_scale_to_zero_after_idle_window():
    targets = _targets(min_replicas=0, idle_seconds=60.0)
    # Traffic at t=100; window not elapsed at t=150 (queue at target →
    # the load signals are neutral; idleness is what's under test).
    state = ScaleState(last_traffic_at=100.0, last_requests_total=10.0,
                       scraped=True)
    d = decide_scale(2, _sample(queue_depth=4.0, requests_total=10.0),
                     targets, state, now=150.0)
    assert d.replicas == 2
    # Window elapsed at t=161 → straight to zero (no staged drain).
    d2 = decide_scale(2, _sample(queue_depth=4.0, requests_total=10.0),
                      targets, d.state, now=161.0)
    assert d2.replicas == 0 and d2.reason == "ScaleToZero"
    assert d2.state.idle_since_zero


def test_traffic_resets_idle_window():
    targets = _targets(min_replicas=0, idle_seconds=60.0)
    state = ScaleState(last_traffic_at=100.0, last_requests_total=10.0,
                       scraped=True)
    # The counter moved at t=155: the window restarts from there.
    d = decide_scale(2, _sample(queue_depth=4.0, requests_total=11.0),
                     targets, state, now=155.0)
    assert d.replicas == 2
    d2 = decide_scale(2, _sample(queue_depth=4.0, requests_total=11.0),
                      targets, d.state, now=214.0)
    assert d2.replicas == 2  # only 59 s since the last traffic
    d3 = decide_scale(2, _sample(queue_depth=4.0, requests_total=11.0),
                      targets, d2.state, now=216.0)
    assert d3.replicas == 0


def test_fresh_service_gets_full_idle_window():
    """A just-created service (zero state) must not scale to zero on its
    first decision — the window counts from the first decision, not the
    epoch."""
    targets = _targets(min_replicas=0, idle_seconds=60.0)
    d = decide_scale(1, _sample(), targets, ScaleState(), now=1e9)
    assert d.replicas == 1


def test_counter_regression_rebaselines_instead_of_reading_idle():
    """A scale-down (or pod restart) shrinks the fleet-wide summed
    request counter; a frozen high-water baseline would then read steady
    traffic as idleness and scale an ACTIVE service to zero.  The
    baseline must follow the sum down (without counting as traffic) so
    the very next real request reads as movement."""
    targets = _targets(min_replicas=0, idle_seconds=60.0,
                       cooldown_seconds=0.0)
    # Established at 4 replicas, sum 1000.
    state = ScaleState(last_traffic_at=100.0, last_requests_total=1000.0,
                       scraped=True)
    # Halved to 2 replicas: the sum drops to ~500.  Not traffic — but
    # the baseline re-anchors.
    d = decide_scale(2, _sample(queue_depth=4.0, requests_total=500.0),
                     targets, state, now=110.0)
    assert d.state.last_requests_total == 500.0
    assert d.state.last_traffic_at == 100.0  # regression is not traffic
    # Real traffic on the survivors now reads as movement immediately,
    # keeping the service alive past the idle window.
    d2 = decide_scale(2, _sample(queue_depth=4.0, requests_total=510.0),
                      targets, d.state, now=165.0)
    assert d2.replicas == 2
    assert d2.state.last_traffic_at == 165.0


def test_wake_from_zero_on_annotation():
    targets = _targets(min_replicas=0)
    state = ScaleState(last_scale_down_at=100.0, idle_since_zero=True,
                       last_traffic_at=40.0)
    # Stale wake stamp (predates the idle transition): stays at zero.
    d = decide_scale(0, _sample(replicas_scraped=0), targets, state,
                     now=200.0, wake_requested_at=90.0)
    assert d.replicas == 0
    # Fresh stamp: wakes to max(min, 1) immediately — no cooldown.
    d2 = decide_scale(0, _sample(replicas_scraped=0), targets, state,
                      now=200.0, wake_requested_at=150.0)
    assert d2.replicas == 1 and d2.reason == "Wake"
    assert not d2.state.idle_since_zero


def test_wake_from_zero_on_traffic_counter():
    """Traffic observed while at zero (a lingering scrape target, or the
    activator's own probe) also wakes — the annotation is the contract,
    the counter is the backstop."""
    targets = _targets(min_replicas=0)
    state = ScaleState(last_requests_total=5.0, idle_since_zero=True,
                       last_scale_down_at=100.0, last_traffic_at=40.0)
    d = decide_scale(0, _sample(requests_total=6.0), targets, state,
                     now=200.0)
    assert d.replicas == 1 and d.reason == "Wake"


def test_empty_scrape_holds_width():
    """No replica answered (warming, or the scrape path is down): silence
    must not scale anything in either direction."""
    d = decide_scale(3, _sample(replicas_scraped=0, queue_depth=0.0),
                     _targets(), ScaleState(last_traffic_at=50.0),
                     now=100.0)
    assert d.replicas == 3 and d.reason == ""


def test_warm_up_never_idles_to_zero():
    """A cold pool whose replicas are still warming must not idle out to
    zero, however long the warm-up takes — and the idle window restarts
    at FIRST scrape contact, so a warm-up slower than idle_seconds never
    reads as an idle service (the conformance cold-start regression
    pin)."""
    targets = _targets(min_replicas=0, idle_seconds=60.0)
    state = ScaleState(last_traffic_at=10.0)
    # Warming: nothing scraped for ages → hold.
    d = decide_scale(2, _sample(replicas_scraped=0), targets, state,
                     now=10_000.0)
    assert d.replicas == 2 and d.reason == ""
    # First contact: the window restarts NOW, not at creation.
    d2 = decide_scale(2, _sample(queue_depth=4.0), targets, d.state,
                      now=10_100.0)
    assert d2.replicas == 2 and d2.state.scraped
    # Only a full idle window past first contact scales to zero.
    d3 = decide_scale(2, _sample(queue_depth=4.0), targets, d2.state,
                      now=10_159.0)
    assert d3.replicas == 2
    d4 = decide_scale(2, _sample(queue_depth=4.0), targets, d3.state,
                      now=10_161.0)
    assert d4.replicas == 0 and d4.reason == "ScaleToZero"
    assert not d4.state.scraped  # next episode gets its own window


def test_state_status_roundtrip():
    state = ScaleState(last_traffic_at=12.5, last_requests_total=99.0,
                       last_scale_down_at=10.0, idle_since_zero=True,
                       scraped=True)
    assert state_from_status(state_to_status(state)) == state
    assert state_from_status({}) == ScaleState()
    assert state_from_status(None) == ScaleState()


def test_targets_from_spec_defaults_and_overrides():
    svc = {"spec": {"model": "llama_125m",
                    "tpu": {"accelerator": "v5e"},
                    "replicas": {"min": 0, "max": 6},
                    "scale": {"queueDepthTarget": 2.0,
                              "ttftP99TargetSeconds": 1.5,
                              "idleSeconds": 10}}}
    t = targets_from_spec(svc)
    assert (t.min_replicas, t.max_replicas) == (0, 6)
    assert t.queue_depth == 2.0 and t.ttft_p99_s == 1.5
    assert t.idle_seconds == 10.0
    assert t.slot_occupancy == 0.8      # default
    assert t.cooldown_seconds == 30.0   # default
    # Decisions are pure: same inputs, same outputs, inputs untouched.
    s = _sample(queue_depth=8.0)
    a = decide_scale(2, s, t, ScaleState(), now=50.0)
    b = decide_scale(2, s, t, ScaleState(), now=50.0)
    assert a == b and s == _sample(queue_depth=8.0)
    assert dataclasses.asdict(a.state)  # state is a plain value


# -- the TSDB-migration A/B pin (ISSUE 15) ------------------------------------
#
# The autoscaler moved off its private scrape (parse pages per reconcile,
# diff TTFT buckets in ``_ttft_prev``) onto the fleet pipeline (scrape ->
# TSDB -> ``fleetscrape.serve_sample``).  The matrix below REPLAYS the
# retired private-path semantics as a reference implementation and pins
# the stored-series path sample-identical on the same scraped traffic —
# and ``decide_scale`` is pure, so identical samples mean identical
# decisions by construction (asserted anyway).


def _legacy_samples(passes):
    """The pre-migration private-scrape path, frozen as a reference:
    parse_serve_pages + the _ttft_prev merged-bucket delta."""
    from kubeflow_tpu.platform.controllers.inferenceservice import (
        parse_serve_pages,
    )
    from kubeflow_tpu.telemetry.metrics import quantile_from_buckets

    prev = None
    out = []
    for texts in passes:
        sample, buckets = parse_serve_pages([t for t in texts
                                             if t is not None])
        if sample.replicas_scraped:
            last, prev = prev, buckets
            if last is None:
                sample = dataclasses.replace(sample, ttft_p99_s=None)
            else:
                delta = {le: max(0.0, c - last.get(le, 0.0))
                         for le, c in buckets.items()}
                sample = dataclasses.replace(
                    sample,
                    ttft_p99_s=quantile_from_buckets(delta, 0.99))
        else:
            prev = None
        out.append(sample)
    return out


def _tsdb_samples(passes):
    """The same traffic through the fleet substrate."""
    from kubeflow_tpu.telemetry import fleetscrape as fs
    from kubeflow_tpu.telemetry.tsdb import TSDB

    db = TSDB()
    box = {}
    sc = fs.FleetScraper(db, scraper=lambda url: box.get(url))
    out = []
    for i, texts in enumerate(passes):
        box = {f"u{j}": t for j, t in enumerate(texts)}
        targets = [fs.Target(url=f"u{j}",
                             labels={"service": "ns/svc",
                                     "replica": f"r{j}"})
                   for j in range(len(texts))]
        sc.scrape_service("ns/svc", targets, ts=100.0 + 10.0 * i)
        out.append(fs.serve_sample(db, "ns/svc"))
    return out


def _page(*, queue=0.0, requests=0.0, slots=None, active=None, ttft=None):
    lines = [f"serve_queue_depth {queue}",
             f'generate_requests_total{{outcome="ok"}} {requests}']
    if slots is not None:
        lines += [f"serve_decode_slots {slots}",
                  f"serve_decode_slots_active {active or 0}"]
    for le, v in (ttft or {}).items():
        lines.append(
            f'serve_time_to_first_token_seconds_bucket{{le="{le}"}} {v}')
    return "\n".join(lines) + "\n"


AB_TRAFFIC = {
    "steady": [
        [_page(queue=2.0, requests=10.0, ttft={"0.2": 5, "1.0": 9,
                                               "+Inf": 10})],
        [_page(queue=4.0, requests=30.0, ttft={"0.2": 6, "1.0": 12,
                                               "+Inf": 30})],
        [_page(queue=1.0, requests=35.0, ttft={"0.2": 10, "1.0": 16,
                                               "+Inf": 35})],
    ],
    "two-replicas-merge": [
        [_page(queue=8.0, requests=10.0, slots=8, active=4,
               ttft={"1.0": 4, "+Inf": 5}),
         _page(queue=2.0, requests=6.0, slots=8, active=8,
               ttft={"1.0": 1, "+Inf": 6})],
        [_page(queue=6.0, requests=20.0, slots=8, active=2,
               ttft={"1.0": 8, "+Inf": 9}),
         _page(queue=0.0, requests=9.0, slots=8, active=1,
               ttft={"1.0": 2, "+Inf": 11})],
    ],
    "replica-restart-resets-buckets": [
        [_page(requests=50.0, ttft={"1.0": 40, "+Inf": 50})],
        [_page(requests=2.0, ttft={"1.0": 1, "+Inf": 2})],   # reset
        [_page(requests=8.0, ttft={"1.0": 5, "+Inf": 8})],
    ],
    "outage-pass-rebaselines": [
        [_page(requests=10.0, ttft={"1.0": 10, "+Inf": 10})],
        [None],                                              # all fail
        [_page(requests=90.0, ttft={"1.0": 20, "+Inf": 90})],
        [_page(requests=95.0, ttft={"1.0": 21, "+Inf": 95})],
    ],
    "replica-appears-mid-window": [
        [_page(requests=10.0, ttft={"1.0": 9, "+Inf": 10})],
        [_page(requests=12.0, ttft={"1.0": 10, "+Inf": 12}),
         _page(requests=100.0, ttft={"1.0": 60, "+Inf": 100})],
    ],
    "replica-drops-mid-window": [
        [_page(requests=10.0, ttft={"1.0": 9, "+Inf": 10}),
         _page(requests=20.0, ttft={"1.0": 15, "+Inf": 20})],
        [_page(requests=14.0, ttft={"1.0": 11, "+Inf": 14})],
    ],
}


def test_tsdb_path_matches_private_scrape_path_sample_for_sample():
    for name, passes in AB_TRAFFIC.items():
        legacy = _legacy_samples(passes)
        stored = _tsdb_samples(passes)
        assert stored == legacy, (name, stored, legacy)


def test_tsdb_path_yields_identical_decision_sequences():
    """Belt AND suspenders over purity: chain decide_scale over both
    sample sequences (state threaded through) and pin the decisions."""
    targets = _targets(min_replicas=0, max_replicas=8,
                       ttft_p99_s=1.0, idle_seconds=120.0,
                       cooldown_seconds=0.0)
    for name, passes in AB_TRAFFIC.items():
        seqs = []
        for samples in (_legacy_samples(passes), _tsdb_samples(passes)):
            state, width, decisions = ScaleState(), 2, []
            for i, sample in enumerate(samples):
                d = decide_scale(width, sample, targets, state,
                                 now=1000.0 + 10.0 * i)
                decisions.append((d.replicas, d.reason))
                state, width = d.state, d.replicas
            seqs.append(decisions)
        assert seqs[0] == seqs[1], (name, seqs)


def test_wake_restamp_defeats_stale_stamp_and_fires_once():
    """The activator staleness race (ISSUE 19): the service idled to
    zero at t=100 while an OLD wake stamp (t=90, from the previous
    episode) is still on the object.  A controller replica reading that
    stale stamp must hold zero — and the activator's re-stamp cadence
    (platform/activator.py ``_stamp_wake``) is what converges it: the
    fresh stamp postdates the scale-down, the wake fires EXACTLY once,
    and further re-stamps mid-warm neither re-wake nor let the warming
    pool flap back to zero."""
    targets = _targets(min_replicas=0, idle_seconds=60.0)
    state = ScaleState(last_scale_down_at=100.0, idle_since_zero=True,
                       last_traffic_at=40.0)

    # Pass 1: the stale stamp (90 < last_scale_down_at=100) holds zero.
    d1 = decide_scale(0, _sample(replicas_scraped=0), targets, state,
                      now=105.0, wake_requested_at=90.0)
    assert d1.replicas == 0 and d1.reason == ""
    assert d1.state.idle_since_zero

    # Pass 2: the activator re-stamped at t=106 while requests stay
    # held — the fresh stamp postdates the scale-down: wake.
    d2 = decide_scale(0, _sample(replicas_scraped=0), targets, d1.state,
                      now=107.0, wake_requested_at=106.0)
    assert d2.replicas == 1 and d2.reason == "Wake"
    assert not d2.state.idle_since_zero

    # Pass 3: the annotation is still on the object (the controller
    # clears it asynchronously) and the activator may re-stamp again
    # mid-warm — with the pool already awake (current=1, nothing
    # scraped yet) the stamp is inert: width holds, no second "Wake".
    d3 = decide_scale(1, _sample(replicas_scraped=0), targets, d2.state,
                      now=110.0, wake_requested_at=109.0)
    assert d3.replicas == 1 and d3.reason == ""

    # Pass 4: warm-up outlasts the idle window (scrape still silent at
    # t=300, idle_seconds=60) — the warming pool must NOT flap back to
    # zero; silence is not idleness.
    d4 = decide_scale(1, _sample(replicas_scraped=0), targets, d3.state,
                      now=300.0, wake_requested_at=109.0)
    assert d4.replicas == 1 and d4.reason == ""

    # Pass 5: first scrape contact restarts the idle window — the
    # replayed traffic keeps the service up well past the old window.
    d5 = decide_scale(1, _sample(replicas_scraped=1, requests_total=3.0),
                      targets, d4.state, now=301.0)
    assert d5.replicas == 1
    assert d5.state.last_traffic_at == 301.0
