import os

# Web-app tests run over plain HTTP on localhost; secure-cookie CSRF mode is
# exercised explicitly in test_csrf_double_submit.
os.environ.setdefault("APP_SECURE_COOKIES", "false")
