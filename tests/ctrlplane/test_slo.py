"""Burn-rate SLO alerting over the fleet TSDB (ISSUE 15): the
multi-window math, the alert state machine, fleet-wide exactly-one
Event emission, and the non-vacuity acceptance — a planted TTFT
degradation through the REAL serving scrape path fires the fast-window
alert within one evaluation cadence across a 2-replica ShardedFleet,
emits one Event, and clears on recovery."""
from __future__ import annotations

import time

import pytest

from kubeflow_tpu.platform.k8s.types import EVENT, deep_get
from kubeflow_tpu.platform.runtime import metrics
from kubeflow_tpu.platform.testing import FakeKube
from kubeflow_tpu.telemetry import slo
from kubeflow_tpu.telemetry.tsdb import TSDB

TTFT_BUCKET = "serve_time_to_first_token_seconds_bucket"


def rule(**kw):
    base = dict(name="ttft", metric=TTFT_BUCKET, threshold=1.0,
                objective=0.9, fast_window_s=300.0, slow_window_s=3600.0,
                fast_burn=2.0, slow_burn=1.0)
    base.update(kw)
    return slo.BurnRateRule(**base)


def feed(db, *, at, good, total, labels=None):
    """One cumulative bucket observation set (good = under-threshold)."""
    db.append(TTFT_BUCKET, {**(labels or {}), "le": "1.0"}, good, ts=at)
    db.append(TTFT_BUCKET, {**(labels or {}), "le": "+Inf"}, total, ts=at)


def test_first_scrape_of_a_long_lived_counter_is_not_an_increase():
    """A fresh TSDB's first scrape of a replica that has served for a
    day stores huge cumulative buckets; strict increase semantics (a
    series' first sample is history, not events) keep that from firing
    a spurious page on a healthy fleet right after a controller
    restart."""
    db = TSDB()
    feed(db, at=100.0, good=80_000, total=100_000)  # first-ever sample
    eng = slo.RuleEngine(db, [rule()], now=lambda: 100.0)
    assert eng.evaluate() == []
    assert eng.states["ttft"].state == "inactive"


def test_burn_rate_math_and_two_window_gate():
    db = TSDB()
    r = rule()
    # 50% of events over the threshold against a 10% budget: burn = 5.
    feed(db, at=90.0, good=0, total=0)
    feed(db, at=100.0, good=5, total=10)
    fast, slow, events = r.burn_rates(db, at=100.0)
    assert fast == pytest.approx(5.0) and slow == pytest.approx(5.0)
    assert events == 10
    eng = slo.RuleEngine(db, [r], now=lambda: 100.0)
    out = eng.evaluate()
    assert [t["state"] for t in out] == ["firing"]
    assert eng.states["ttft"].state == "firing"
    assert metrics.registry.get_sample_value(
        "kft_alerts_firing", {"alert": "ttft"}) == 1.0


def test_fast_window_alone_does_not_fire():
    """A cliff inside the fast window with a CLEAN slow window history
    pages; a clean fast window never does — and an hour of old errors
    with a clean fast window doesn't page either (the slow window
    confirms, the fast window gates recency)."""
    db = TSDB()
    r = rule(fast_window_s=10.0, slow_window_s=1000.0,
             fast_burn=2.0, slow_burn=2.0)
    # Old errors far outside the fast window.
    feed(db, at=100.0, good=0, total=100)
    # Fast window: all good (delta 100 good / 100 total).
    feed(db, at=995.0, good=100, total=200)
    feed(db, at=1000.0, good=200, total=300)
    eng = slo.RuleEngine(db, [r], now=lambda: 1000.0)
    assert eng.evaluate() == []
    assert eng.states["ttft"].state == "inactive"


def test_no_events_is_no_signal_not_recovery():
    db = TSDB()
    r = rule()
    feed(db, at=99.0, good=0, total=0)
    feed(db, at=100.0, good=0, total=10)
    eng = slo.RuleEngine(db, [r], now=lambda: 100.0)
    assert [t["state"] for t in eng.evaluate()] == ["firing"]
    # Every series ages out of both windows: silence must hold the page
    # (a scrape outage mid-incident is not recovery).
    out = eng.evaluate(at=100.0 + 3600.0 * 3)
    assert out == [] and eng.states["ttft"].state == "firing"
    # Fresh clean traffic inside both windows resolves it.
    feed(db, at=100.0 + 3600.0 * 3, good=110, total=120)
    out = eng.evaluate(at=100.0 + 3600.0 * 3 + 1)
    assert [t["state"] for t in out] == ["resolved"]
    assert eng.states["ttft"].state == "inactive"


def test_recording_rule_materializes_quantile_series():
    db = TSDB()
    feed(db, at=10.0, good=0, total=0)
    feed(db, at=50.0, good=9, total=10)
    rec = slo.RecordingRule(record="ttft:p99", metric=TTFT_BUCKET,
                            q=0.5, window_s=100.0)
    eng = slo.RuleEngine(db, [], recording=[rec], now=lambda: 50.0)
    eng.evaluate()
    rows = db.instant("ttft:p99")
    assert rows and 0.0 < rows[0][2] <= 1.0


def test_default_rules_cover_the_four_slos():
    names = {r.name for r in slo.default_rules()}
    assert names == {"serve-ttft-p99", "reconcile-p99", "watch-lag",
                     "queue-wait"}
    for r in slo.default_rules():
        assert r.fast_window_s < r.slow_window_s
        assert r.fast_burn > r.slow_burn


def test_transition_emits_exactly_one_event_across_replicas():
    """Two replicas evaluating the same rules over the same scraped data
    both announce the transition; the stamping apply helper's
    content-hash (deterministic Event name + owned content) makes the
    second apply a no-op — exactly one Event object fleet-wide, flipped
    in place on resolve."""
    kube = FakeKube()
    kube.add_namespace("kubeflow")
    db = TSDB()
    feed(db, at=5.0, good=0, total=0)
    feed(db, at=10.0, good=0, total=50)
    engines = [slo.RuleEngine(db, [rule()], client=kube,
                              now=lambda: 10.0) for _ in range(2)]
    for eng in engines:
        eng.evaluate()
    events = kube.list(EVENT, "kubeflow")
    firing = [e for e in events if e.get("reason") == "AlertFiring"]
    assert len(firing) == 1, events
    assert firing[0]["metadata"]["name"] == "kft-alert-ttft"
    # Stamped through the apply helpers: the content hash rides it.
    assert deep_get(firing[0], "metadata", "annotations",
                    "kubeflow.org/generated-hash")
    # Recovery flips the same object (still one Event, reason flipped).
    feed(db, at=11.0, good=550, total=600)
    for eng in engines:
        eng.evaluate(at=11.0)
    events = kube.list(EVENT, "kubeflow")
    assert len([e for e in events
                if e["metadata"]["name"] == "kft-alert-ttft"]) == 1
    assert kube.get(EVENT, "kft-alert-ttft",
                    "kubeflow")["reason"] == "AlertResolved"


@pytest.mark.slow
def test_planted_ttft_degradation_fires_once_across_sharded_fleet():
    """THE acceptance pin (ISSUE 15): a 2-replica ShardedFleet runs the
    REAL InferenceService controllers (servesim playing the kubelet,
    replica /metrics pages planted); both replicas scrape the same
    service into the shared process TSDB; per-replica rule engines
    evaluate the serve-TTFT burn rule.  A planted TTFT degradation
    fires the fast-window alert within ONE evaluation cadence, emits
    exactly one Event fleet-wide, survives a replica kill, and clears
    on recovery.

    Extended by ISSUE 16: the page also auto-captures evidence — each
    replica's flight recorder snapshots exactly ONE incident bundle at
    its firing transition (debounce holds it at one), the bundle's
    manifest carries a live profile window, the TTFT burn-window TSDB
    export, and at least one merged journey, and the capture announce
    dedupes to ONE fleet-wide Event."""
    from kubeflow_tpu.platform.controllers import inferenceservice as svcctrl
    from kubeflow_tpu.platform.testing.servesim import InferenceFleetSim
    from kubeflow_tpu.platform.testing.shardfleet import ShardedFleet
    from kubeflow_tpu.telemetry import incidents as incidents_mod
    from kubeflow_tpu.telemetry import profiler as profiler_mod

    state = {"degraded": False, "requests": 0.0}

    def pages(url):
        if url.endswith("/readyz"):
            return '{"ready": true}'
        state["requests"] += 1.0
        # Cumulative TTFT buckets: healthy traffic lands under 0.2s;
        # degraded traffic all lands past 5s (the rule threshold).
        good = 0.0 if state["degraded"] else state["requests"]
        return (
            "serve_queue_depth 0.0\n"
            f'generate_requests_total{{outcome="ok"}} {state["requests"]}\n'
            f'serve_time_to_first_token_seconds_bucket{{le="0.2"}} {good}\n'
            f'serve_time_to_first_token_seconds_bucket{{le="5.0"}} {good}\n'
            "serve_time_to_first_token_seconds_bucket{le=\"+Inf\"} "
            f"{state['requests']}\n")

    db = TSDB()
    fleet = ShardedFleet(
        replicas=2, num_shards=4, workers=2,
        controller_factory=lambda client, **kw: svcctrl.make_controller(
            client, scraper=pages, sync_period=0.05, tsdb=db, **kw))
    sim = InferenceFleetSim(
        fleet.kube, fleet.namespace,
        endpoint_for=lambda svc, rev, i: f"sim://{svc}/{rev}/{i}")
    ttft_rule = rule(name="serve-ttft-p99", threshold=0.2,
                     objective=0.9, fast_window_s=5.0,
                     slow_window_s=60.0, fast_burn=2.0, slow_burn=1.0)
    engines = [slo.RuleEngine(db, [ttft_rule], client=r.chaos,
                              namespace="kubeflow")
               for r in fleet.replicas]
    # The always-on profiler samples the REAL storm; each replica's
    # engine carries its own flight recorder, as in production.
    prof = profiler_mod.Profiler()
    prof.start()
    profiler_mod.register_debug_profiler(prof)
    recorders = []
    for eng in engines:
        eng.incidents = incidents_mod.IncidentRecorder(
            db, client=eng.client, namespace="kubeflow")
        recorders.append(eng.incidents)
    try:
        fleet.kube.create({
            "apiVersion": "kubeflow.org/v1alpha1",
            "kind": "InferenceService",
            "metadata": {"name": "llm", "namespace": fleet.namespace},
            "spec": {"model": "llama_125m",
                     "tpu": {"accelerator": "v5e", "topology": "2x4"},
                     "replicas": {"min": 1, "max": 2, "initial": 1}},
        })

        def wait(cond, timeout=20.0, what=""):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                if cond():
                    return
                time.sleep(0.05)
            raise TimeoutError(what or "condition")

        # Healthy traffic flows through the real scrape path.
        wait(lambda: fleet.kube.list(EVENT, fleet.namespace) is not None
             and db.latest_n("fleetscrape_pass",
                             {"service": f"{fleet.namespace}/llm"}, 1),
             what="first scrape pass")
        wait(lambda: db.increase(TTFT_BUCKET,
                                 {"service": f"{fleet.namespace}/llm",
                                  "le": "+Inf"}, window=60.0,
                                 at=time.time()) > 0,
             what="ttft series flowing")
        for eng in engines:
            eng.evaluate()
        assert all(e.states["serve-ttft-p99"].state == "inactive"
                   for e in engines)

        # Plant the degradation; the controllers' own scrape cadence
        # carries it into the store; ONE evaluation pass fires.
        state["degraded"] = True
        base = state["requests"]
        wait(lambda: state["requests"] > base + 4, what="degraded scrapes")
        fired = []
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and not fired:
            for eng in engines:
                fired.extend(eng.evaluate())
            time.sleep(0.05)
        assert any(t["state"] == "firing" for t in fired), fired
        events = [e for e in fleet.kube.list(EVENT, "kubeflow")
                  if e["metadata"]["name"] == "kft-alert-serve-ttft-p99"]
        assert len(events) == 1 and events[0]["reason"] == "AlertFiring"

        # ISSUE 16 acceptance: the page carried its evidence.  Drive
        # both engines to their firing transition (each captures on its
        # OWN transition), then: exactly one bundle per recorder, each
        # manifest holding a profile window + the TTFT burn-window TSDB
        # export + at least one journey — and ONE announce Event
        # fleet-wide despite two captures.
        deadline = time.monotonic() + 10.0
        while (time.monotonic() < deadline
               and not all(e.states["serve-ttft-p99"].state == "firing"
                           for e in engines)):
            for eng in engines:
                eng.evaluate()
            time.sleep(0.05)
        assert all(e.states["serve-ttft-p99"].state == "firing"
                   for e in engines)
        incident_events = [e for e in fleet.kube.list(EVENT, "kubeflow")
                           if e.get("reason") == "IncidentCaptured"]
        assert len(incident_events) == 1, incident_events
        assert incident_events[0]["metadata"]["name"] == \
            "kft-incident-serve-ttft-p99"
        for rec in recorders:
            bundles = rec.snapshot()["incidents"]
            assert len(bundles) == 1, bundles
            manifest = bundles[0]
            assert manifest["alert"] == "serve-ttft-p99"
            assert manifest["profileWindow"] is not None
            assert manifest["series"] >= 1
            assert manifest["journeys"] >= 1
            bundle = rec.get(manifest["id"])
            assert bundle["tsdb"]["metric"] == TTFT_BUCKET
            assert bundle["profile"]["folded"]
            assert bundle["journeys"][0]["spans"]

        # Replica 0 dies mid-incident: the survivor keeps evaluating and
        # the Event set stays at exactly one.
        fleet.kill(0)
        engines[1].evaluate()
        events = [e for e in fleet.kube.list(EVENT, "kubeflow")
                  if e["metadata"]["name"] == "kft-alert-serve-ttft-p99"]
        assert len(events) == 1

        # Recovery: healthy traffic again; the survivor's engine clears
        # the alert and flips the same Event to AlertResolved.
        state["degraded"] = False
        base = state["requests"]
        wait(lambda: state["requests"] > base + 8, what="recovery scrapes")
        deadline = time.monotonic() + 10.0
        while (time.monotonic() < deadline
               and engines[1].states["serve-ttft-p99"].state == "firing"):
            engines[1].evaluate()
            time.sleep(0.05)
        assert engines[1].states["serve-ttft-p99"].state == "inactive"
        ev = fleet.kube.get(EVENT, "kft-alert-serve-ttft-p99", "kubeflow")
        assert ev["reason"] == "AlertResolved"
    finally:
        profiler_mod.register_debug_profiler(None)
        prof.stop()
        sim.close()
        fleet.close()
