"""The always-on sampling profiler (ISSUE 16): window rotation and the
folded-stack export under a fake clock, the attribution resolution order
(active Tracer role → static pool role → stripped thread name →
unattributed), the FlightPool/fleetscrape attribution pins (satellite 3
— a slot sample lands under the SUBMITTING controller's role, idle pool
workers under the pool name), the /debug/profile surface, the scrape
metrics, and the slow-dump profile-window reference."""
from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

from kubeflow_tpu.platform import main as main_mod
from kubeflow_tpu.telemetry import trace as trace_mod
from kubeflow_tpu.telemetry.profiler import (
    ProfileWindow,
    Profiler,
    UNATTRIBUTED,
    debug_profiler,
    register_debug_profiler,
    register_thread_role,
    resolve_role,
    set_active_role,
)


def _get(url: str) -> bytes:
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.read()


def _spin(stop: threading.Event):
    while not stop.wait(0.001):
        sum(range(50))


def _spawn_spinner(name=None):
    stop = threading.Event()
    t = threading.Thread(target=_spin, args=(stop,), name=name, daemon=True)
    t.start()
    return t, stop


def _wait_for(cond, timeout=10.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        got = cond()
        if got:
            return got
        time.sleep(0.01)
    raise TimeoutError(what)


def test_attribution_resolution_order():
    """active (Tracer) → static (registered) → stripped thread name →
    unattributed; interpreter default names mean nobody claimed the
    thread."""
    active = {7: "notebook-controller"}
    static = {7: "controller", 8: "fleetscrape"}
    assert resolve_role(7, "Thread-3", active, static) == "notebook-controller"
    assert resolve_role(8, "Thread-4", active, static) == "fleetscrape"
    # Name fallback strips trailing -N/_N counters so pool siblings fold.
    assert resolve_role(9, "notebook-worker-3", active, static) == \
        "notebook-worker"
    assert resolve_role(9, "scrape_pool-0-1", active, static) == "scrape_pool"
    assert resolve_role(9, "fleet-metrics-pipeline", active, static) == \
        "fleet-metrics-pipeline"
    # Interpreter defaults and empty names fold to unattributed.
    assert resolve_role(9, "Thread-12", active, static) == UNATTRIBUTED
    assert resolve_role(9, "Dummy-5", active, static) == UNATTRIBUTED
    assert resolve_role(9, "", active, static) == UNATTRIBUTED


def test_dead_threads_role_never_claims_a_recycled_ident():
    """The OS recycles thread idents: a role registered by a thread that
    has since died (a closed pool's worker, a Tracer user killed
    mid-trace) must stop resolving the moment the thread does, or an
    unrelated new thread inheriting the ident would silently sample
    under the dead thread's role (real order-dependent failure: a prior
    test's controller workers re-attributed this file's spinners)."""
    done = threading.Event()

    def _register_and_exit():
        register_thread_role("doomed-pool")
        set_active_role("doomed-controller")
        done.set()

    t = threading.Thread(target=_register_and_exit, daemon=True)
    t.start()
    assert done.wait(10.0)
    t.join(timeout=10.0)
    ident = t.ident
    # Both seams registered by the now-dead thread fall through to the
    # NAME seam for whoever owns that ident next.
    assert resolve_role(ident, "fresh-worker-2") == "fresh-worker"
    assert resolve_role(ident, "Thread-99") == UNATTRIBUTED


def test_window_rotation_and_folded_format():
    """Samples fold into (role, stack) counts; windows rotate on the
    clock; the export is standard folded-stack text (root-first frames,
    trailing count) fed straight to flamegraph tooling."""
    clock = [0.0]
    p = Profiler(hz=50.0, window_seconds=10.0, windows=3,
                 now=lambda: clock[0])
    t, stop = _spawn_spinner(name="probe-spinner")
    try:
        assert p.sample_once() >= 1
        w1 = p.current_window_id()
        folded = p.folded()
        lines = [ln for ln in folded.splitlines()
                 if ln.startswith("probe-spinner;")]
        assert lines, folded
        # role;root;...;leaf count — every line ends with an int count.
        role, rest = lines[0].split(";", 1)
        assert role == "probe-spinner"
        assert int(rest.rsplit(" ", 1)[1]) >= 1
        # Same window until the clock crosses the rotation period.
        clock[0] = 9.0
        p.sample_once()
        assert p.current_window_id() == w1
        clock[0] = 10.0
        p.sample_once()
        w2 = p.current_window_id()
        assert w2 == w1 + 1
        index = p.windows()
        assert [w["window"] for w in index] == [w1, w2]
        assert index[0]["end"] == 10.0 and index[1]["end"] is None
        assert index[0]["samples"] >= 2
        # Closed windows stay addressable until the ring evicts them.
        assert "probe-spinner;" in p.folded(w1)
        assert p.folded(w1 + 99) is None
    finally:
        stop.set()
        t.join()


def test_stack_depth_truncation():
    """Deep stacks keep the leaf-most frames and mark the cut at the
    root, so one pathological recursion can't bloat every aggregate."""
    p = Profiler(hz=50.0, window_seconds=60.0, stack_depth=1,
                 now=lambda: 0.0)
    t, stop = _spawn_spinner(name="deep-spinner")
    try:
        p.sample_once()
        lines = [ln for ln in p.folded().splitlines()
                 if ln.startswith("deep-spinner;")]
        assert lines and all(
            ln.split(";", 1)[1].startswith("<truncated>;") for ln in lines)
    finally:
        stop.set()
        t.join()


def test_diff_is_signed_deltas_largest_regressions_first():
    p = Profiler(now=lambda: 0.0)
    w1 = ProfileWindow(1, 0.0)
    w1.end = 10.0
    w1.stacks = {("a", "x"): 5, ("a", "y"): 2, ("a", "same"): 3}
    w2 = ProfileWindow(2, 10.0)
    w2.end = 20.0
    w2.stacks = {("a", "x"): 9, ("a", "z"): 3, ("a", "same"): 3}
    p._ring.append(w1)
    p._ring.append(w2)
    # Signed w2-w1, sorted by largest growth; zero deltas dropped.
    assert p.diff(1, 2).splitlines() == ["a;x +4", "a;z +3", "a;y -2"]
    assert p.diff(2, 1).splitlines() == ["a;y +2", "a;z -3", "a;x -4"]
    assert p.diff(1, 99) is None


def test_tracer_seam_sets_and_clears_active_role():
    """The shared Tracer is THE attribution seam: begin() points the
    thread's samples at the traced component, finish() restores the
    fallback — a default-named thread goes back to unattributed."""
    p = Profiler(now=lambda: 0.0)
    tracer = trace_mod.Tracer("probe-domain")
    in_trace = threading.Event()
    release = threading.Event()
    cleared = threading.Event()
    done = threading.Event()

    def run():
        tracer.begin("probe-component", "req")
        in_trace.set()
        release.wait(10)
        tracer.finish()
        cleared.set()
        done.wait(10)

    t = threading.Thread(target=run, daemon=True)  # default Thread-N name
    t.start()
    try:
        assert in_trace.wait(10)
        p.sample_once()
        assert any(ln.startswith("probe-component;")
                   for ln in p.folded().splitlines()), p.folded()
        release.set()
        assert cleared.wait(10)
        p.rotate()
        p.sample_once()
        fresh = p.folded()
        assert not any(ln.startswith("probe-component;")
                       for ln in fresh.splitlines()), fresh
    finally:
        release.set()
        done.set()
        t.join()


def test_flight_slot_sample_lands_under_submitting_controller():
    """Satellite 3: a FlightPool worker claims the pool name at birth
    (never Thread-N), and a slot carrying a submitted reconcile's trace
    attributes to the SUBMITTING controller's role for the duration of
    the carry."""
    from kubeflow_tpu.platform.runtime import trace as rt_trace
    from kubeflow_tpu.platform.runtime.flight import FlightPool

    p = Profiler(now=lambda: 0.0)
    pool = FlightPool(2, name="probe-pool")
    inside = threading.Event()
    release = threading.Event()

    def blocking_slot():
        inside.set()
        release.wait(10)

    def submit():
        rt_trace.begin("probe-flight-ctrl", "user1/nb")
        try:
            # Two calls: a single call short-circuits to inline execution.
            pool.run([blocking_slot, lambda: None])
        finally:
            rt_trace.finish()

    submitter = threading.Thread(target=submit, daemon=True)
    submitter.start()
    try:
        assert inside.wait(10)
        p.sample_once()
        slot_lines = [ln for ln in p.folded().splitlines()
                      if ln.startswith("probe-flight-ctrl;")]
        assert slot_lines, p.folded()
        assert any("blocking_slot" in ln for ln in slot_lines), slot_lines
    finally:
        release.set()
        submitter.join()
    # After the carry ends the idle worker samples under its static pool
    # role — the attribution hole this PR closes (Thread-N no more).
    p.rotate()
    p.sample_once()
    folded = p.folded()
    assert any(ln.startswith("probe-pool;") for ln in folded.splitlines()), \
        folded
    assert not any(ln.startswith("probe-flight-ctrl;")
                   for ln in folded.splitlines()), folded


def test_fleetscrape_pool_has_stable_role():
    """Satellite 3, second half: the fleetscrape fan-out runs on its own
    named pool, so scrape I/O shows up in profiles as ``fleetscrape``."""
    from kubeflow_tpu.telemetry import fleetscrape

    pool = fleetscrape.scrape_pool()
    assert pool.name == "fleetscrape"
    assert fleetscrape.scrape_pool() is pool  # stable singleton
    p = Profiler(now=lambda: 0.0)
    pool.run([lambda: None, lambda: None])  # spawn + idle the workers
    p.sample_once()
    folded = p.folded()
    assert any(ln.startswith("fleetscrape;")
               for ln in folded.splitlines()), folded


def test_sampler_thread_runs_and_stops():
    """The real sampler thread fills the open window at hz without any
    caller driving it, and stop() joins it."""
    p = Profiler(hz=200.0, window_seconds=60.0)
    t, stop = _spawn_spinner(name="live-spinner")
    p.start()
    try:
        _wait_for(lambda: p.windows() and p.windows()[-1]["samples"] > 0,
                  what="sampler samples")
        assert p.errors == 0
    finally:
        stop.set()
        t.join()
        p.stop()
    assert p._thread is None


def test_profile_counters_and_self_time_gauge_ride_scrape():
    """kft_profile_samples_total{role} counts every folded sample;
    kft_profile_self_seconds reads the REGISTERED profiler's open window
    at scrape time and disappears on deregistration."""
    from kubeflow_tpu.platform.runtime import metrics

    p = Profiler(hz=50.0, now=lambda: 0.0)
    t, stop = _spawn_spinner(name="gauge-spinner")
    try:
        before = metrics.registry.get_sample_value(
            "kft_profile_samples_total", {"role": "gauge-spinner"}) or 0.0
        p.sample_once()
        after = metrics.registry.get_sample_value(
            "kft_profile_samples_total", {"role": "gauge-spinner"})
        assert after == before + 1.0
        register_debug_profiler(p)
        try:
            val = metrics.registry.get_sample_value(
                "kft_profile_self_seconds", {"role": "gauge-spinner"})
            # samples / hz over the open window.
            assert val == 1.0 / 50.0
        finally:
            register_debug_profiler(None)
        assert metrics.registry.get_sample_value(
            "kft_profile_self_seconds", {"role": "gauge-spinner"}) is None
    finally:
        stop.set()
        t.join()


def test_slow_trace_dump_references_covering_profile_window():
    """A slow trace's JSON dump carries the covering profile window id —
    the flamegraph for "why was this slow" was already being collected
    while it ran."""
    p = Profiler(now=lambda: 0.0)
    tracer = trace_mod.Tracer("probe-slow")
    register_debug_profiler(p)
    try:
        tracer.begin("probe-slow-comp", "req")
        d = tracer.finish(result="ok", slow_seconds=0.0)
        assert d["profile_window"] == p.current_window_id()
    finally:
        register_debug_profiler(None)
    tracer.begin("probe-slow-comp", "req")
    d = tracer.finish(result="ok", slow_seconds=0.0)
    assert "profile_window" not in d  # no profiler, no dangling reference


def test_debug_profile_endpoint():
    """/debug/profile: 404 until a profiler registers, folded text by
    default, ?list=1 window index, ?window= one window, ?diff= signed
    deltas, ?seconds= synchronous capture; DEBUG_TRACES=false turns the
    whole surface off (stacks reveal more than /metrics)."""

    class _Mgr:
        def healthy(self):
            return True

    server = main_mod._serve_health(_Mgr(), 0, host="127.0.0.1")
    base = f"http://127.0.0.1:{server.server_port}"
    t, stop = _spawn_spinner(name="http-spinner")
    try:
        try:
            _get(base + "/debug/profile")
        except urllib.error.HTTPError as e:
            assert e.code == 404
        else:  # pragma: no cover
            raise AssertionError("served before registration")

        p = Profiler(hz=50.0, now=time.time)
        p.sample_once()
        wid = p.current_window_id()
        register_debug_profiler(p)
        try:
            assert debug_profiler() is p
            text = _get(base + "/debug/profile").decode()
            assert any(ln.startswith("http-spinner;")
                       for ln in text.splitlines()), text
            index = json.loads(_get(base + "/debug/profile?list=1"))
            assert index["hz"] == 50.0 and index["errors"] == 0
            assert [w["window"] for w in index["windows"]] == [wid]
            assert _get(base + f"/debug/profile?window={wid}").decode() \
                == text
            # Same window against itself: every delta is zero → empty.
            assert _get(base + f"/debug/profile?diff={wid},{wid}") == b""
            # On-demand capture samples immediately at seconds=0.
            cap = _get(base + "/debug/profile?seconds=0").decode()
            assert any(ln.startswith("http-spinner;")
                       for ln in cap.splitlines()), cap
            for bad in ("?window=999", "?diff=1,999", "?window=nope"):
                try:
                    _get(base + "/debug/profile" + bad)
                except urllib.error.HTTPError as e:
                    assert e.code == 404, bad
                else:  # pragma: no cover
                    raise AssertionError(f"{bad} served a missing window")
        finally:
            register_debug_profiler(None)
    finally:
        stop.set()
        t.join()
        server.shutdown()

    # The gate: stacks are off with the traces endpoint.
    gated = main_mod._serve_health(_Mgr(), 0, host="127.0.0.1",
                                   debug_traces=False)
    p = Profiler(now=lambda: 0.0)
    register_debug_profiler(p)
    try:
        try:
            _get(f"http://127.0.0.1:{gated.server_port}/debug/profile")
        except urllib.error.HTTPError as e:
            assert e.code == 404
        else:  # pragma: no cover
            raise AssertionError("gate off but profile served")
    finally:
        register_debug_profiler(None)
        gated.shutdown()
