"""bench.py emission paths: outside of these smokes the benchmarks only
execute on the TPU chip at round end, so a typo would surface exactly when
the headline number is being recorded (review r2).  KFT_BENCH_SMOKE=1
shrinks the llama arm to flash-supported tiny shapes (interpret-mode pallas
on CPU)."""
import json

import pytest


@pytest.mark.slow
def test_bench_llama_smoke_emits_metric(capsys, monkeypatch):
    monkeypatch.setenv("KFT_BENCH_SMOKE", "1")
    import bench

    bench.llama_8k_bench()
    line = capsys.readouterr().out.strip().splitlines()[-1]
    out = json.loads(line)
    assert out["metric"] == "llama8k_train_tokens_per_sec"
    assert set(out) >= {"metric", "value", "unit", "vs_baseline"}
    assert out["value"] > 0 and out["xla_tokens_per_sec"] > 0


@pytest.mark.slow
def test_bench_resnet_emits_metric(capsys, monkeypatch):
    import bench

    for name, val in (("BATCH", 4), ("IMAGE", 32), ("WARMUP", 1),
                      ("STEPS", 1), ("WINDOWS", 2)):
        monkeypatch.setattr(bench, name, val)
    bench.resnet50_bench()
    line = capsys.readouterr().out.strip().splitlines()[-1]
    out = json.loads(line)
    assert out["metric"] == "resnet50_images_per_sec_per_chip"
    assert {"value", "vs_baseline", "value_mean_window",
            "vs_baseline_mean"} <= set(out)
    assert out["value"] >= out["value_mean_window"] > 0
