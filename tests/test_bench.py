"""bench.py emission paths: outside of these smokes the benchmarks only
execute on the TPU chip at round end, so a typo would surface exactly when
the headline number is being recorded (review r2).  KFT_BENCH_SMOKE=1
shrinks the llama arm to flash-supported tiny shapes (interpret-mode pallas
on CPU)."""
import json

import pytest


def _metric_lines(capsys):
    """{metric: parsed line}.  Section functions emit the metric line
    FIRST and may follow it with companion lines (the
    attention_mask_bytes_estimate line rides after the llama metric), so
    smoke tests select by name instead of position."""
    out = {}
    for ln in capsys.readouterr().out.strip().splitlines():
        rec = json.loads(ln)
        out[rec["metric"]] = rec
    return out


@pytest.mark.slow
def test_bench_llama_smoke_emits_metric(capsys, monkeypatch):
    monkeypatch.setenv("KFT_BENCH_SMOKE", "1")
    import bench

    bench.llama_8k_bench()
    lines = _metric_lines(capsys)
    out = lines["llama8k_train_tokens_per_sec"]
    assert set(out) >= {"metric", "value", "unit", "vs_baseline"}
    assert out["value"] > 0 and out["xla_tokens_per_sec"] > 0
    # Kernel-selection proof (ISSUE 7): flash arm traced pallas, XLA arm
    # never did.
    assert out["flash_arm_pallas_calls"] > 0
    assert out["xla_arm_pallas_calls"] == 0
    # The XLA arm's pre-flight estimate rides as its own line, mask-free
    # (logits + probs only).
    est = lines["attention_mask_bytes_estimate"]
    assert est["value"] > 0


@pytest.mark.slow
def test_bench_llama_1b4_smoke_emits_metric(capsys, monkeypatch):
    monkeypatch.setenv("KFT_BENCH_SMOKE", "1")
    import bench

    bench.llama_1b4_bench()
    out = _metric_lines(capsys)["llama1b4_8k_train_tokens_per_sec"]
    assert out["value"] > 0 and out["xla_tokens_per_sec"] > 0
    assert {"mfu", "model_tflops_per_sec", "mfu_mean",
            "model_gflops_per_token"} <= set(out)


@pytest.mark.slow
def test_bench_main_emits_primary_first_and_last(capsys, monkeypatch):
    """The driver parses the LAST line: main() must print the primary
    metric first (so a truncated run still computed it) AND re-print it
    last (so the final line is always the primary)."""
    monkeypatch.setenv("KFT_BENCH_SMOKE", "1")
    import bench

    for name, val in (("BATCH", 4), ("IMAGE", 32), ("WARMUP", 1),
                      ("STEPS", 1), ("WINDOWS", 1)):
        monkeypatch.setattr(bench, name, val)
    bench.main([])
    lines = [json.loads(l)
             for l in capsys.readouterr().out.strip().splitlines()]
    metrics = [l["metric"] for l in lines]
    assert metrics[0] == "llama8k_train_tokens_per_sec"
    assert metrics[-1] == "llama8k_train_tokens_per_sec"
    assert lines[0] == lines[-1]
    assert "llama1b4_8k_train_tokens_per_sec" in metrics
    assert "resnet50_images_per_sec_per_chip" in metrics
    assert "vit_b16_images_per_sec" in metrics
    # Band discipline (VERDICT r4 item 2): the non-smoke value bands are
    # suppressed under KFT_BENCH_SMOKE (a debug model vs a hardware
    # baseline is meaningless), but resnet's protocol band is always on.
    resnet = next(l for l in lines
                  if l["metric"] == "resnet50_images_per_sec_per_chip")
    assert resnet["band"] in ("pass", "REGRESSION")
    assert resnet["band_floor"] == bench.RESNET_REGRESSION_BAND


def test_lm_train_flops_per_token_accounting():
    """The MFU accounting matches a by-hand computation of the bench's own
    8k config (the number written down in BASELINE.md)."""
    import bench
    from kubeflow_tpu.models.llama import LlamaConfig

    cfg = LlamaConfig(vocab_size=8192, dim=1024, n_layers=4, n_heads=8,
                      n_kv_heads=8, ffn_dim=4096, max_seq_len=8192)
    # fwd, per token: proj 8d^2 + causal attn 2sd + swiglu 6*d*ffn per
    # layer, plus the vocab head; train = 3x fwd.
    d, s = 1024, 8192
    per_layer = 8 * d * d + 2 * s * d + 6 * d * 4096
    fwd = 4 * per_layer + 2 * d * 8192
    assert bench.lm_train_flops_per_token(cfg, s) == 3.0 * fwd
    # ~0.654 GFLOPs/token — the VERDICT r3 back-of-envelope.
    assert 0.6e9 < bench.lm_train_flops_per_token(cfg, s) < 0.7e9
    # GQA: fewer kv heads shrink only the k/v projections.
    gqa = LlamaConfig(vocab_size=8192, dim=1024, n_layers=4, n_heads=8,
                      n_kv_heads=2, ffn_dim=4096, max_seq_len=8192)
    assert bench.lm_train_flops_per_token(gqa, s) < \
        bench.lm_train_flops_per_token(cfg, s)


def test_value_band_tripwire():
    """Every banded line's pass/REGRESSION boundary (VERDICT r4 item 2)."""
    import bench

    base = 100.0
    floor = bench.VALUE_BAND_FLOOR
    assert bench.value_band(base, base) == "pass"
    assert bench.value_band(base * floor, base) == "pass"
    assert bench.value_band(base * floor - 1e-9, base) == "REGRESSION"
    assert bench.value_band(0.0, base) == "REGRESSION"
    # The baseline constants the bands compare against are the documented
    # established readings (BASELINE.md) — pin their magnitudes so a
    # fat-fingered constant cannot silently re-tune a tripwire.
    assert 150_000 < bench.BASELINE_LLAMA8K_TPS < 160_000
    assert 10_000 < bench.BASELINE_LLAMA1B4_TPS < 12_000
    assert 900 < bench.BASELINE_VIT_IPS < 1_050
    assert 2_400 < bench.BASELINE_IMAGES_PER_SEC < 2_550


def test_bench_resnet_band_tripwire():
    """The regression tripwire flags a mean ratio below the band floor
    (pure check — the field's presence on the emitted line is asserted by
    the slow resnet smoke)."""
    import bench

    assert bench.resnet_band(1.0) == "pass"
    assert bench.resnet_band(bench.RESNET_REGRESSION_BAND) == "pass"
    assert bench.resnet_band(bench.RESNET_REGRESSION_BAND - 1e-9) == \
        "REGRESSION"
    assert bench.resnet_band(0.3) == "REGRESSION"


@pytest.mark.slow
def test_bench_resnet_emits_metric(capsys, monkeypatch):
    import bench

    for name, val in (("BATCH", 4), ("IMAGE", 32), ("WARMUP", 1),
                      ("STEPS", 1), ("WINDOWS", 2)):
        monkeypatch.setattr(bench, name, val)
    bench.resnet50_bench()
    line = capsys.readouterr().out.strip().splitlines()[-1]
    out = json.loads(line)
    assert out["metric"] == "resnet50_images_per_sec_per_chip"
    assert {"value", "vs_baseline", "value_mean_window",
            "vs_baseline_mean", "band"} <= set(out)
    assert out["value"] >= out["value_mean_window"] > 0


def test_trace_summary_parses_device_ops(tmp_path):
    """trace_summary aggregates XLA-op events by hlo_category and ignores
    host-side rows (the bench --profile contract)."""
    import gzip

    from kubeflow_tpu.train.profiling import trace_summary

    d = tmp_path / "plugins" / "profile" / "2026_01_01_00_00_00"
    d.mkdir(parents=True)
    events = [
        {"ph": "M", "pid": 3, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 3, "tid": 3, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        {"ph": "M", "pid": 7, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        {"ph": "M", "pid": 7, "tid": 1, "name": "thread_name",
         "args": {"name": "python3"}},
        {"ph": "X", "pid": 3, "tid": 3, "name": "conv.1", "dur": 2000.0,
         "args": {"hlo_category": "convolution fusion",
                  "bytes_accessed": "2000000", "model_flops": "4000000"}},
        {"ph": "X", "pid": 3, "tid": 3, "name": "fus.1", "dur": 1000.0,
         "args": {"hlo_category": "loop fusion",
                  "bytes_accessed": "1000000", "model_flops": "0"}},
        # host event must be excluded
        {"ph": "X", "pid": 7, "tid": 1, "name": "py", "dur": 9999.0,
         "args": {"hlo_category": "host", "bytes_accessed": "1"}},
    ]
    with gzip.open(d / "vm.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)
    s = trace_summary(str(tmp_path))
    assert round(s["total_ms"], 3) == 3.0
    cats = s["categories"]
    assert set(cats) == {"convolution fusion", "loop fusion"}
    assert round(cats["convolution fusion"]["gb_per_s"], 1) == 1.0
    assert round(cats["convolution fusion"]["tf_per_s"], 3) == 0.002
    # sorted by time, conv first
    assert list(cats) == ["convolution fusion", "loop fusion"]


def test_trace_summary_excludes_start_events_and_rejects_empty(tmp_path):
    import gzip

    from kubeflow_tpu.train.profiling import trace_summary

    d = tmp_path / "plugins" / "profile" / "x"
    d.mkdir(parents=True)
    events = [
        {"ph": "M", "pid": 3, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 3, "tid": 3, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        {"ph": "X", "pid": 3, "tid": 3, "name": "cs", "dur": 0.1,
         "args": {"hlo_category": "copy-start",
                  "bytes_accessed": "5000000"}},
        {"ph": "X", "pid": 3, "tid": 3, "name": "cd", "dur": 100.0,
         "args": {"hlo_category": "copy-done",
                  "bytes_accessed": "5000000"}},
    ]
    with gzip.open(d / "vm.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)
    s = trace_summary(str(tmp_path))
    assert set(s["categories"]) == {"copy-done"}
    assert round(s["total_gb"], 4) == 0.005  # not double-booked

    e = tmp_path / "empty"
    (e / "plugins" / "profile" / "y").mkdir(parents=True)
    with gzip.open(e / "plugins" / "profile" / "y" / "vm.trace.json.gz",
                   "wt") as f:
        json.dump({"traceEvents": [
            {"ph": "M", "pid": 7, "name": "process_name",
             "args": {"name": "/host:CPU"}},
        ]}, f)
    with pytest.raises(ValueError, match="no device-side"):
        trace_summary(str(e))
