"""bench.py emission paths: outside of these smokes the benchmarks only
execute on the TPU chip at round end, so a typo would surface exactly when
the headline number is being recorded (review r2).  KFT_BENCH_SMOKE=1
shrinks the llama arm to flash-supported tiny shapes (interpret-mode pallas
on CPU)."""
import json

import pytest


@pytest.mark.slow
def test_bench_llama_smoke_emits_metric(capsys, monkeypatch):
    monkeypatch.setenv("KFT_BENCH_SMOKE", "1")
    import bench

    bench.llama_8k_bench()
    line = capsys.readouterr().out.strip().splitlines()[-1]
    out = json.loads(line)
    assert out["metric"] == "llama8k_train_tokens_per_sec"
    assert set(out) >= {"metric", "value", "unit", "vs_baseline"}
    assert out["value"] > 0 and out["xla_tokens_per_sec"] > 0


@pytest.mark.slow
def test_bench_resnet_emits_metric(capsys, monkeypatch):
    import bench

    for name, val in (("BATCH", 4), ("IMAGE", 32), ("WARMUP", 1),
                      ("STEPS", 1), ("WINDOWS", 2)):
        monkeypatch.setattr(bench, name, val)
    bench.resnet50_bench()
    line = capsys.readouterr().out.strip().splitlines()[-1]
    out = json.loads(line)
    assert out["metric"] == "resnet50_images_per_sec_per_chip"
    assert {"value", "vs_baseline", "value_mean_window",
            "vs_baseline_mean"} <= set(out)
    assert out["value"] >= out["value_mean_window"] > 0


def test_trace_summary_parses_device_ops(tmp_path):
    """trace_summary aggregates XLA-op events by hlo_category and ignores
    host-side rows (the bench --profile contract)."""
    import gzip

    from kubeflow_tpu.train.profiling import trace_summary

    d = tmp_path / "plugins" / "profile" / "2026_01_01_00_00_00"
    d.mkdir(parents=True)
    events = [
        {"ph": "M", "pid": 3, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 3, "tid": 3, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        {"ph": "M", "pid": 7, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        {"ph": "M", "pid": 7, "tid": 1, "name": "thread_name",
         "args": {"name": "python3"}},
        {"ph": "X", "pid": 3, "tid": 3, "name": "conv.1", "dur": 2000.0,
         "args": {"hlo_category": "convolution fusion",
                  "bytes_accessed": "2000000", "model_flops": "4000000"}},
        {"ph": "X", "pid": 3, "tid": 3, "name": "fus.1", "dur": 1000.0,
         "args": {"hlo_category": "loop fusion",
                  "bytes_accessed": "1000000", "model_flops": "0"}},
        # host event must be excluded
        {"ph": "X", "pid": 7, "tid": 1, "name": "py", "dur": 9999.0,
         "args": {"hlo_category": "host", "bytes_accessed": "1"}},
    ]
    with gzip.open(d / "vm.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)
    s = trace_summary(str(tmp_path))
    assert round(s["total_ms"], 3) == 3.0
    cats = s["categories"]
    assert set(cats) == {"convolution fusion", "loop fusion"}
    assert round(cats["convolution fusion"]["gb_per_s"], 1) == 1.0
    assert round(cats["convolution fusion"]["tf_per_s"], 3) == 0.002
    # sorted by time, conv first
    assert list(cats) == ["convolution fusion", "loop fusion"]


def test_trace_summary_excludes_start_events_and_rejects_empty(tmp_path):
    import gzip

    from kubeflow_tpu.train.profiling import trace_summary

    d = tmp_path / "plugins" / "profile" / "x"
    d.mkdir(parents=True)
    events = [
        {"ph": "M", "pid": 3, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 3, "tid": 3, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        {"ph": "X", "pid": 3, "tid": 3, "name": "cs", "dur": 0.1,
         "args": {"hlo_category": "copy-start",
                  "bytes_accessed": "5000000"}},
        {"ph": "X", "pid": 3, "tid": 3, "name": "cd", "dur": 100.0,
         "args": {"hlo_category": "copy-done",
                  "bytes_accessed": "5000000"}},
    ]
    with gzip.open(d / "vm.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)
    s = trace_summary(str(tmp_path))
    assert set(s["categories"]) == {"copy-done"}
    assert round(s["total_gb"], 4) == 0.005  # not double-booked

    e = tmp_path / "empty"
    (e / "plugins" / "profile" / "y").mkdir(parents=True)
    with gzip.open(e / "plugins" / "profile" / "y" / "vm.trace.json.gz",
                   "wt") as f:
        json.dump({"traceEvents": [
            {"ph": "M", "pid": 7, "name": "process_name",
             "args": {"name": "/host:CPU"}},
        ]}, f)
    with pytest.raises(ValueError, match="no device-side"):
        trace_summary(str(e))
